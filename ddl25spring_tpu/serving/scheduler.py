"""Continuous-batching scheduler: admit/retire at token boundaries.

Orca-style iteration-level scheduling over the slot engine (engine.py):
instead of freezing a batch for a whole generation (`generate()`'s scan),
the scheduler revisits the batch at EVERY token boundary — admitting queued
requests into freed slots, advancing one prefill chunk, decoding one token
for everyone in flight, and retiring finished sequences (their blocks
return to the pool immediately).

Admission policy — reservation-based, FCFS by default (the documented
seam, now a config knob):
- ``admit`` reserves a request's worst-case block count up front
  (``Engine.required_blocks``), all-or-nothing. An admitted request can
  therefore ALWAYS run to completion: pool exhaustion can only delay
  admissions, never strand in-flight work, so there is no deadlock and no
  need for mid-flight preemption — the liveness bar the serving smoke
  pins (`experiments/serving_bench.py` completes every request with the
  pool sized below peak naive demand). The cost is utilization: blocks a
  short-stopping request never writes sit reserved until retirement.
  vLLM's alternative — allocate lazily per block, preempt-and-recompute a
  victim on exhaustion — buys that utilization back at the price of
  recompute; swap `_admit` (and add victim selection) to explore it.
- ``admission="fcfs"`` (default): strict arrival order — the queue head
  blocks the line even when a smaller request behind it would fit.
  Keeping arrival order makes queue-wait percentiles meaningful under
  the Poisson load harness. This mode is byte-for-byte the pre-knob
  behavior (pinned in tests/test_fleet_serving.py).
- ``admission="sjf"``: size-aware — when the pool is tight (the head's
  reservation doesn't fit but a slot is free), admit the SHORTEST
  reservation among the same-priority queued requests that does fit,
  ties broken by arrival. Strictly more admissions per boundary under
  mixed lengths, at the price of possible head-of-line latency for the
  large request (its turn still comes: the pool drains toward its
  reservation, and ``submit`` already rejected anything that could
  never fit).
- Priorities (``Request.priority``, higher first): admission considers
  the highest-priority queued class first, FCFS (or SJF) within it.
  With every priority equal (the default 0) both modes reduce to their
  single-class behavior, so single-tenant streams are untouched.

Admission order is a LATENCY decision only: per-slot state (position, RNG
key, temperature) is carried per sequence and every engine op is
row-independent, so WHICH slot a request lands in — or who shares a step
with it — never changes its tokens (the bitwise bar in
tests/test_serving.py::test_admission_order_does_not_change_tokens).

Telemetry: every lifecycle edge emits a ``request_*`` event (schema v2,
telemetry/events.py) through the shared JSONL stream — queue wait, TTFT,
per-token progress, blocks held — rendered as p50/p95/p99 by
`experiments/obs_report.py`.

Tracing (schema v4, telemetry/trace.py): each request is ONE trace
(trace_id = the request id) with a ``request`` root span and
``queue`` → ``prefill`` (with per-tick ``prefill_chunk`` children) →
``decode`` → ``retire`` child spans, all on the scheduler's clock — so
queue-wait/TTFT percentiles and the span timeline agree by construction.
Contexts are held host-side per request and passed explicitly; nothing
crosses into the compiled engine programs, so the engine's two-programs
contract and the zero-in-jit-overhead invariant are untouched. A
``prefill_chunk`` span covers the whole engine step that advanced the
chunk (one compiled call serves every slot — the per-slot share is not
observable from the host), flagged with the chunk index; reassemble with
``telemetry.trace.trace_trees`` or export via
``experiments/trace_export.py``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..telemetry.events import EventLog
from ..telemetry.trace import Span, Tracer
from .engine import Engine


@dataclass(frozen=True)
class Request:
    """One generation request. ``seed`` feeds ``jax.random.PRNGKey`` when
    ``temperature > 0`` (equal seed ⇒ the stream ``generate()`` would emit
    alone). ``arrival`` is an offset in seconds from workload start — the
    load harness's Poisson schedule, ignored by direct submitters.
    ``eos_id``: emitting this token retires the request at that token
    boundary, returning ALL its worst-case-reserved blocks immediately
    (the stream up to and including the EOS is still bitwise
    ``generate()``'s, which has no early stop — see ``Scheduler.tick``).
    ``tenant`` names the traffic class (frontend.TrafficClass) for
    per-class SLO accounting; ``priority`` orders admission (higher
    first) — both are latency knobs only, never token knobs."""
    rid: str
    prompt: Tuple[int, ...]
    max_new: int
    temperature: float = 0.0
    seed: int = 0
    arrival: float = 0.0
    eos_id: Optional[int] = None
    tenant: str = "default"
    priority: int = 0


@dataclass
class RequestRecord:
    """Per-request lifecycle + emitted tokens (the scheduler's ground truth
    for the zero-dropped/zero-duplicated assertion)."""
    rid: str
    prompt_len: int
    max_new: int
    blocks: int = 0
    tenant: str = "default"
    engine: Optional[int] = None   # fleet: which engine served it
    enqueue_t: Optional[float] = None
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    done_t: Optional[float] = None
    tokens: List[int] = field(default_factory=list)

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.admit_t is None or self.enqueue_t is None:
            return None
        return self.admit_t - self.enqueue_t

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None or self.enqueue_t is None:
            return None
        return self.first_token_t - self.enqueue_t

    @property
    def tokens_per_sec(self) -> Optional[float]:
        if self.done_t is None or self.admit_t is None:
            return None
        dt = self.done_t - self.admit_t
        return len(self.tokens) / dt if dt > 0 else None


class Scheduler:
    """FCFS continuous batching over one Engine.

    >>> sched = Scheduler(engine, events=telemetry.events)
    >>> sched.submit(req, now=0.0)
    >>> while sched.outstanding:
    ...     sched.tick()
    >>> sched.records[req.rid].tokens
    """

    def __init__(self, engine: Engine, *, events: Optional[EventLog] = None,
                 token_events: bool = True,
                 clock: Callable[[], float] = time.monotonic,
                 engine_id: Optional[int] = None,
                 admission: str = "fcfs",
                 memory_every: int = 0):
        if admission not in ("fcfs", "sjf"):
            raise ValueError(f"admission must be 'fcfs' or 'sjf' "
                             f"(got {admission!r})")
        self.engine = engine
        self.events = events
        self.token_events = token_events
        self.clock = clock
        # Admission-policy seam (module docstring): "fcfs" is byte-for-byte
        # the historical behavior; "sjf" is size-aware within a priority.
        self.policy = admission
        # Fleet seam: tag every request_* event (and span) with the engine
        # this scheduler fronts, so an N-engine stream's percentiles can
        # be grouped per engine (obs_report) instead of pooled.
        self.engine_id = (engine_id if engine_id is not None
                          else getattr(engine, "engine_id", None))
        self._tag = ({"engine": self.engine_id}
                     if self.engine_id is not None else {})
        # Completions since the router last harvested (serving/fleet.py's
        # predicted-TTFT window feed): (done_t, ttft_s) appended at
        # retirement, drained by Router.harvest — bounded by whoever
        # consumes it, same O(requests) order as ``records`` without one.
        self.recent_done: List[Tuple[float, Optional[float]]] = []
        # Per-verify-dispatch speculation accounting (engine.last_spec
        # snapshots) — the host-side twin of the schema-v7 ``speculate``
        # events, kept even with no event stream so ServingReport can
        # compute acceptance/tokens-per-dispatch either way.
        self.spec_rounds: List[dict] = []
        if events is not None:
            # Late-bind the stream to the engine's compile watches: the
            # engine is built before any telemetry exists, but its
            # compilations (two programs plain, five with speculation —
            # and any retrace, a budget violation) should land in THIS
            # scheduler's event stream.
            from ..telemetry.introspect import bind_events
            for w in engine.watches():
                bind_events(w, events)
        # Per-request trace trees ride the scheduler's OWN clock (the load
        # harness fast-forwards it through idle gaps), so span timestamps
        # and the queue_wait_s/ttft_s latency fields share one timebase.
        self.tracer = (Tracer(events,
                              clock_ns=lambda: int(self.clock() * 1e9))
                       if events is not None else None)
        self._spans: Dict[str, Dict[str, Span]] = {}   # rid -> open spans
        self._chunks: Dict[str, int] = {}              # rid -> chunks done
        # Live memory census (telemetry/memory.py, schema v9): every
        # ``memory_every``-th busy tick emits one ``memory`` event with
        # the pool occupancy + fragmentation census and this engine's
        # static params bytes. Default OFF (0): the serving hot loop pays
        # nothing — not even the counter compare — unless a harness arms
        # it; with it armed the census is host-list arithmetic only, so
        # served streams stay bitwise identical (the smoke pins this).
        self.memory_every = int(memory_every)
        self.memory_meter = None
        self._bytes_per_block = None
        self._ticks = 0
        if self.memory_every > 0:
            from ..telemetry.memory import MemoryMeter, tree_state_bytes
            self.memory_meter = MemoryMeter(events, source="serve")
            self.memory_meter.note(
                params_bytes=tree_state_bytes(engine.params))
            try:
                from .kvcache import kv_bytes_per_token
                self._bytes_per_block = (
                    engine.paged.block_len
                    * kv_bytes_per_token(engine.cfg,
                                         engine.paged.kv_dtype))
            except Exception:
                self._bytes_per_block = None
        self.queue: List[Request] = []
        self.records: Dict[str, RequestRecord] = {}
        self._by_slot: Dict[int, Request] = {}
        self.completed = 0
        # High-water mark of in-flight requests, recorded AT admission —
        # the instant concurrency peaks. An end-of-tick sample would
        # undercount whenever a fully-loaded step also retires someone.
        self.peak_in_flight = 0

    # -------------------------------------------------------------- lifecycle
    def submit(self, req: Request, now: Optional[float] = None) -> None:
        """Enqueue; raises for a request NO pool state could ever serve
        (so the queue can never hold an unadmittable head — the liveness
        precondition)."""
        need = self.engine.required_blocks(len(req.prompt), req.max_new)
        positions = len(req.prompt) + req.max_new - 1
        if (need > self.engine.allocator.capacity
                or positions > self.engine.paged.max_seq_len):
            raise ValueError(
                f"{req.rid}: needs {positions} cache positions / {need} "
                f"blocks but the engine serves at most "
                f"{self.engine.paged.max_seq_len} positions / "
                f"{self.engine.allocator.capacity} blocks — oversized for "
                "this engine at any load")
        now = self.clock() if now is None else now
        self.queue.append(req)
        self.records[req.rid] = RequestRecord(
            rid=req.rid, prompt_len=len(req.prompt), max_new=req.max_new,
            blocks=need, tenant=req.tenant, engine=self.engine_id,
            enqueue_t=now)
        if self.events:
            self.events.request_enqueue(
                req=req.rid, prompt_len=len(req.prompt), max_new=req.max_new,
                temperature=req.temperature, queued=len(self.queue),
                tenant=req.tenant, priority=req.priority, **self._tag)
        if self.tracer:
            root = self.tracer.start("request", trace=req.rid,
                                     prompt_len=len(req.prompt),
                                     max_new=req.max_new, **self._tag)
            self._spans[req.rid] = {
                "root": root,
                "queue": self.tracer.start("queue", parent=root.ctx)}

    @property
    def outstanding(self) -> int:
        """Requests not yet retired (queued + in flight)."""
        return len(self.queue) + len(self._by_slot)

    def tick(self) -> List[Tuple[str, int]]:
        """One token boundary: admit, advance the engine, retire. Returns
        the (rid, token) pairs emitted this boundary."""
        self._admit()
        if not self.engine.busy:
            return []
        emitted: List[Tuple[str, int]] = []
        chunk_spans: List[Tuple[str, Span]] = []
        if self.tracer:
            # Slots without a first token advance exactly one prefill
            # chunk in this step (engine contract); open their chunk spans
            # BEFORE the step so the span covers the compiled call.
            for slot, req in self._by_slot.items():
                if self.records[req.rid].first_token_t is None:
                    i = self._chunks.get(req.rid, 0)
                    self._chunks[req.rid] = i + 1
                    chunk_spans.append((req.rid, self.tracer.start(
                        "prefill_chunk",
                        parent=self._spans[req.rid]["prefill"].ctx,
                        chunk=i)))
        events = self.engine.step()
        now = self.clock()   # post-step: token timestamps include the step
        for _, s in chunk_spans:
            s.end()
        eos_retired: set = set()
        eos_dropped = 0
        for ev in events:
            if ev.slot in eos_retired:
                # The slot EOS-retired earlier THIS tick (engine.step can
                # emit a final prefill token and a same-boundary decode
                # token for one slot): anything after the EOS is post-end
                # and never existed semantically — drop it. Scoped to
                # this tick's EOS retirements only, so an event for a
                # slot the scheduler genuinely doesn't own still raises
                # (a dropped-token bug must stay loud).
                eos_dropped += 1
                continue
            req = self._by_slot[ev.slot]
            rec = self.records[req.rid]
            rec.tokens.append(ev.token)
            if ev.first:
                rec.first_token_t = now
                if self.tracer:
                    spans = self._spans[req.rid]
                    spans["prefill"].end(
                        chunks=self._chunks.get(req.rid, 0))
                    spans["decode"] = self.tracer.start(
                        "decode", parent=spans["root"].ctx, slot=ev.slot)
            if self.events and self.token_events:
                self.events.request_token(req=req.rid,
                                          i=len(rec.tokens) - 1,
                                          tok=ev.token, slot=ev.slot,
                                          **self._tag)
            done = ev.done
            early_eos = False
            if not done and req.eos_id is not None and ev.token == req.eos_id:
                # EOS early retirement: the request is semantically
                # finished at THIS token boundary, so its blocks — the
                # whole worst-case reservation, including the tail it will
                # now never write — go back to the pool immediately
                # instead of idling until the max_new horizon. Purely a
                # capacity decision: the emitted stream is generate()'s
                # stream truncated at the first EOS (the engine never fed
                # the EOS back, so nothing downstream of it ever existed).
                # Under speculation one verify window can BOTH emit the
                # EOS mid-window and reach max_new at its last row — the
                # engine then already self-retired the slot while
                # emitting the tail this loop is about to drop, so the
                # explicit retire is conditional on the slot still being
                # live (blocks are back in the pool either way).
                if self.engine.slots[ev.slot] is not None:
                    self.engine.retire(ev.slot)
                eos_retired.add(ev.slot)
                done = early_eos = True
            if done:
                rec.done_t = now
                del self._by_slot[ev.slot]
                self.completed += 1
                self.recent_done.append((now, rec.ttft_s))
                if self.tracer:
                    spans = self._spans.pop(req.rid)
                    self._chunks.pop(req.rid, None)
                    # Always opened at the first token (a one-token request
                    # gets a zero-duration decode: first == done in one
                    # engine event).
                    spans["decode"].end(tokens=len(rec.tokens))
                    # The retire point: blocks (the whole worst-case
                    # reservation) return to the pool here — an instant on
                    # the timeline rather than an interval, since the free
                    # is a host list append.
                    self.tracer.start("retire", parent=spans["root"].ctx,
                                      blocks_freed=rec.blocks).end()
                    spans["root"].end(tokens=len(rec.tokens),
                                      **({"eos": True} if early_eos else {}))
                if self.events:
                    self.events.request_done(
                        req=req.rid, tokens=len(rec.tokens),
                        queue_wait_s=rec.queue_wait_s, ttft_s=rec.ttft_s,
                        tokens_per_sec=rec.tokens_per_sec,
                        blocks_freed=rec.blocks,
                        blocks_in_use=self.engine.blocks_in_use(),
                        tenant=req.tenant, **self._tag,
                        **({"eos": True} if early_eos else {}))
            emitted.append((req.rid, ev.token))
        if self.engine.last_spec is not None:
            # One ``speculate`` event per verify dispatch (schema v7):
            # the round's proposed/accepted/rejected counts — the
            # acceptance-rate and tokens-per-dispatch feed for obs_report
            # and slo_monitor's acceptance floor. Emitted AFTER the event
            # loop so ``emitted`` counts tokens actually DELIVERED: a
            # mid-window EOS drops the window tail above, and those
            # tokens must not inflate tokens-per-dispatch (the CI 2× bar
            # measures delivered throughput). proposed/accepted/rejected
            # stay verify-outcome accounting — EOS truncation is not a
            # draft failure, so the acceptance floor never sees it.
            spec = self.engine.last_spec
            if eos_dropped:
                spec = {**spec, "emitted": spec["emitted"] - eos_dropped}
            self.spec_rounds.append(spec)
            if self.events:
                self.events.speculate(**spec, **self._tag)
        if eos_dropped:
            # Keep the report's token count (ServingReport.decode_tokens
            # → tokens_per_dispatch) on the same delivered basis.
            self.engine.decode_tokens -= eos_dropped
        if self.memory_meter is not None:
            self._ticks += 1
            if self._ticks % self.memory_every == 0:
                from ..telemetry.memory import allocator_census
                self.memory_meter.sample(
                    tick=self._ticks, in_flight=len(self._by_slot),
                    queued=len(self.queue),
                    **allocator_census(
                        self.engine.allocator,
                        bytes_per_block=self._bytes_per_block),
                    **self._tag)
        return emitted

    # ---------------------------------------------------------- weight swap
    def swap_weights(self, params, version, *, fused=None) -> None:
        """Hot-swap the engine's weights at the CURRENT token boundary
        (between ``tick()`` calls — the only place this scheduler ever
        is, host-driven), without touching queued or in-flight requests:
        their next tokens sample under the new weights, nothing emitted
        changes, nothing recompiles (``Engine.swap_params`` enforces the
        equal-tree contract). Emits a ``deploy`` event + span (schema
        v6) carrying the publication ``version`` and how many streams
        crossed the swap live.

        With speculation on, a tick is one whole draft-propose + verify
        round, so a swap between ticks necessarily lands at a VERIFY
        boundary: a round's proposals and its verification always run
        under one generation of target weights — draft and target never
        mix generations mid-window. (The draft keeps its own weights; a
        stale draft can only lower acceptance, never correctness.)"""
        span = (self.tracer.start("deploy", trace=f"deploy-{version}",
                                  version=version,
                                  in_flight=len(self._by_slot),
                                  queued=len(self.queue), **self._tag)
                if self.tracer else None)
        self.engine.swap_params(params, fused=fused)
        if span is not None:
            span.end()
        if self.events:
            self.events.deploy(version=version,
                               in_flight=len(self._by_slot),
                               queued=len(self.queue), **self._tag)

    # -------------------------------------------------------------- admission
    def _pick_admittable(self) -> Optional[int]:
        """Queue index of the next request to admit under the policy seam
        (module docstring), or None when nothing admits this boundary.
        Highest priority class first; within it, FCFS — or, under "sjf"
        when the class head's reservation doesn't fit, the shortest
        fitting reservation (ties by arrival)."""
        top = max(r.priority for r in self.queue)
        group = [i for i, r in enumerate(self.queue) if r.priority == top]
        head = self.queue[group[0]]
        if self.engine.can_admit(len(head.prompt), head.max_new,
                                 prompt=head.prompt):
            return group[0]
        if self.policy == "sjf" and self.engine.free_slot() is not None:
            fitting = [i for i in group
                       if self.engine.can_admit(len(self.queue[i].prompt),
                                                self.queue[i].max_new,
                                                prompt=self.queue[i].prompt)]
            if fitting:
                return min(fitting,
                           key=lambda i: (self.records[self.queue[i].rid]
                                          .blocks, i))
        return None

    def _admit(self) -> None:
        """Admit while the policy yields a fitting request; stop when the
        (priority-ordered) head blocks the line — under "fcfs" that is
        strict arrival order, byte-for-byte the historical behavior."""
        while self.queue:
            pick = self._pick_admittable()
            if pick is None:
                return
            head = self.queue.pop(pick)
            key = (jax.random.PRNGKey(head.seed)
                   if head.temperature > 0 else None)
            slot = self.engine.admit(np.asarray(head.prompt, np.int32),
                                     head.max_new,
                                     temperature=head.temperature, key=key)
            self._by_slot[slot] = head
            self.peak_in_flight = max(self.peak_in_flight,
                                      len(self._by_slot))
            rec = self.records[head.rid]
            rec.admit_t = self.clock()
            if self.tracer:
                spans = self._spans[head.rid]
                spans["queue"].end()
                spans["prefill"] = self.tracer.start(
                    "prefill", parent=spans["root"].ctx, slot=slot,
                    blocks=rec.blocks)
            if self.events:
                self.events.request_prefill(
                    req=head.rid, slot=slot, blocks=rec.blocks,
                    queue_wait_s=rec.queue_wait_s,
                    blocks_in_use=self.engine.blocks_in_use(),
                    **self._tag)
