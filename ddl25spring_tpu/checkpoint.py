"""Checkpoint / resume via orbax — distributed-aware, sharding-preserving.

The reference has essentially NO persistence: its only checkpointing is a
best-weights `state_dict()` snapshot held in memory and restored at the end
of one training run (reference: lab/tutorial_2a/centralized.py:51,67-70);
there is no torch.save, no distributed checkpointing, no resume (SURVEY.md
§5.4). This module exceeds that cheaply with the TPU-native standard:
orbax writes each shard from the device that owns it (multi-host safe) and
restores arrays directly into the target mesh layout.

Works for every TrainState in the framework — DP-replicated, PP
stage-sharded, TP/EP weight-sharded — because restore takes a template state
whose shapes/shardings define the layout to materialize into.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Any, Dict, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp
from jax.sharding import NamedSharding, PartitionSpec as P

from .metrics import ResilienceStats
from .resilience.retry import retry_call

MANIFEST_VERSION = 1


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class Checkpointer:
    """Thin wrapper over an orbax CheckpointManager.

    Usage::

        ckpt = Checkpointer(dir, max_to_keep=3)
        ckpt.save(int(state.step), state)          # async-capable save
        state = ckpt.restore(template_state)       # into template's sharding
        step = ckpt.latest_step()                  # None if nothing saved

    Robustness contract (resilience layer): ``save`` retries transient IO
    failures with exponential backoff; ``restore`` falls back past a
    corrupt/unreadable step to the newest step that restores cleanly —
    counted in ``stats.ckpt_fallbacks`` — so a checkpoint truncated by a
    mid-write kill costs ``checkpoint_every`` steps of progress, never the
    run. ``max_to_keep >= 2`` is what makes the fallback non-vacuous.

    Integrity manifests: each save records a per-step JSON manifest
    (``<dir>/digests/<step>.json``) of shard-file SHA-256 digests — written
    once the async save lands (``wait``/``restore``/``close`` flush it) —
    plus the saved leaf shapes/dtypes. ``restore`` verifies digests BEFORE
    handing the step to orbax, so a silent on-disk bit-flip (injectable via
    ``resilience/faults.py``) is detected and skipped as a
    ``ckpt_fallbacks`` fallback instead of restoring poisoned weights
    bit-exactly. Steps saved without a manifest (pre-manifest checkpoints)
    restore unverified, as before.

    Cross-topology restore (elastic re-mesh, resilience/elastic.py): when
    the manifest's saved leaf shapes differ from ``template``'s — a ZeRO-1
    state saved at world size N restored onto M survivors — the step is
    restored at its SAVED shapes (replicated) and resharded into the
    template via ``parallel.dp.reshard_state`` (pad-swap with a hard error
    on non-zero truncated tails, never orbax's silent shape adaptation).
    Counted in ``stats.ckpt_reshards``. When the template lives on a
    ``(data, stage)`` mesh this includes a stage RE-PARTITION: a state
    saved at (D, S) restores onto (D′, S′) via
    ``parallel.pp.repartition_stage_state``'s global-coordinate-id remap
    of the stage-sharded moments / EF residuals, same entry point.
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 retry_attempts: int = 3, retry_base_delay: float = 0.1,
                 stats: Optional[ResilienceStats] = None):
        self._retry_attempts = max(1, retry_attempts)
        self._retry_base = retry_base_delay
        self.stats = stats if stats is not None else ResilienceStats()
        self.restored_step: Optional[int] = None  # set by restore()
        self._dir = os.path.abspath(directory)
        self._digest_dir = os.path.join(self._dir, "digests")
        # step -> saved leaf metadata, held until the async save lands and
        # the digest manifest can be computed from the on-disk files.
        self._pending_manifests: Dict[int, List[Optional[dict]]] = {}
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    # ------------------------------------------------- integrity manifests

    def _manifest_path(self, step: int) -> str:
        return os.path.join(self._digest_dir, f"{step}.json")

    def _read_manifest(self, step: int) -> Optional[dict]:
        try:
            with open(self._manifest_path(step)) as f:
                m = json.load(f)
            return m if isinstance(m, dict) else None
        except (OSError, ValueError):
            return None

    def _step_files(self, step: int) -> Dict[str, str]:
        """relpath -> abspath for every file under the committed step dir."""
        root = os.path.join(self._dir, str(step))
        out = {}
        for base, _, files in os.walk(root):
            for fname in files:
                p = os.path.join(base, fname)
                out[os.path.relpath(p, root)] = p
        return out

    def _flush_manifests(self) -> None:
        """Write digest manifests for landed saves; prune manifests of
        steps the manager has since deleted (max_to_keep). Call only after
        ``wait_until_finished`` — digests of in-flight files would be
        digests of half-written bytes."""
        live = set(self.all_steps())
        for step in list(self._pending_manifests):
            leaves = self._pending_manifests.pop(step)
            if step not in live:
                continue             # evicted before landing; nothing to do
            try:
                files = {rel: _sha256_file(p)
                         for rel, p in self._step_files(step).items()}
                os.makedirs(self._digest_dir, exist_ok=True)
                fd, tmp = tempfile.mkstemp(dir=self._digest_dir,
                                           suffix=".json.tmp")
                with os.fdopen(fd, "w") as f:
                    json.dump({"version": MANIFEST_VERSION, "step": step,
                               "files": files, "leaves": leaves}, f)
                os.replace(tmp, self._manifest_path(step))
            except OSError:
                pass                 # integrity extras must not sink a save
        try:
            for name in os.listdir(self._digest_dir):
                stem = name.partition(".")[0]
                if stem.isdigit() and int(stem) not in live:
                    os.unlink(os.path.join(self._digest_dir, name))
        except OSError:
            pass

    def _verify_digests(self, step: int) -> Optional[str]:
        """None if the step's files match its manifest (or no manifest
        exists — legacy steps restore unverified); else a description of
        the first mismatch.

        Deliberately re-hashes even steps this process digested moments
        ago in ``_flush_manifests``: the threat model is on-disk mutation
        AFTER the bytes landed (bit rot, another process, an injected
        fault between save and restore), and a skip-if-recently-hashed
        fast path would be blind to exactly that window. The cost is one
        extra read+hash per restored step in the save-then-restore-same-
        process case (StepGuard rollback, elastic recovery)."""
        manifest = self._read_manifest(step)
        if manifest is None or not isinstance(manifest.get("files"), dict):
            return None
        on_disk = self._step_files(step)
        for rel, want in manifest["files"].items():
            p = on_disk.get(rel)
            if p is None:
                return f"missing shard file {rel!r}"
            try:
                got = _sha256_file(p)
            except OSError as e:
                return f"unreadable shard file {rel!r}: {e}"
            if got != want:
                return f"digest mismatch in {rel!r}"
        return None

    def _count_retry(self, attempt: int, exc: BaseException) -> None:
        self.stats.retries += 1

    def save(self, step: int, state: Any, *, force: bool = False,
             overwrite: bool = False) -> bool:
        """Persist a pytree (e.g. a TrainState) at ``step``. Returns as soon
        as the arrays are snapshotted; serialization/IO continues in the
        background (orbax async) — call ``wait()`` to block, or rely on the
        lazy waits in restore()/close(). Transient failures (disk pressure,
        a previous async save erroring out at the enqueue barrier) are
        retried with backoff before surfacing.

        ``overwrite=True`` deletes any existing step ``step`` first. Only
        for callers re-treading step indices after a corrupt-latest fallback
        resume: the on-disk entry is then a stale (possibly the corrupt)
        remnant of the pre-fallback lineage, and a blind save would be an
        orbax StepAlreadyExistsError. Default False so double-save bugs
        still fail loudly."""
        if step in self.all_steps():
            if not overwrite:
                # Fail fast and outside the retry loop: a double-save is a
                # deterministic caller bug, and retrying it would both delay
                # the failure and count phantom IO retries into the stats.
                raise ValueError(
                    f"checkpoint step {step} already exists "
                    f"(pass overwrite=True to replace a stale entry)")
            self._mgr.delete(step)
            self._pending_manifests.pop(step, None)
            try:
                os.unlink(self._manifest_path(step))
            except OSError:
                pass
        ok = retry_call(
            self._mgr.save, step, args=ocp.args.StandardSave(state),
            force=force, attempts=self._retry_attempts,
            base=self._retry_base, seed=step, on_retry=self._count_retry)
        # Leaf metadata for the integrity/reshard manifest, captured NOW
        # (shapes/dtypes only — no device sync); digests wait for the
        # async write to land (_flush_manifests).
        self._pending_manifests[step] = [
            {"shape": list(x.shape), "dtype": str(x.dtype)}
            if isinstance(x, jax.Array) else None
            for x in jax.tree.leaves(state)]
        return ok

    def wait(self) -> None:
        self._mgr.wait_until_finished()
        self._flush_manifests()

    def restore(self, template: Any, *, step: Optional[int] = None) -> Any:
        """Restore into ``template``'s structure, dtypes, and shardings.

        ``template`` is a live pytree with the desired layout (typically a
        freshly built TrainState on the current mesh — its values are only
        read for shape/sharding). Defaults to the latest step; if that step
        is corrupt/unreadable (truncated by a kill, garbled on disk, or
        failing its digest manifest), falls back to the next-newest step
        that restores cleanly — each skipped step counts into
        ``stats.ckpt_fallbacks``. An explicitly requested ``step`` does NOT
        fall back: the caller named it, so failing loudly is correct.

        A step whose manifest records leaf shapes DIFFERENT from the
        template's (saved at another data-parallel world size) is restored
        at its saved shapes and resharded into the template — see the class
        docstring's cross-topology contract.
        """
        self._mgr.wait_until_finished()   # flush any in-flight async save
        self._flush_manifests()

        def abstract(x):
            if isinstance(x, jax.Array):
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            return x

        target = jax.tree.map(abstract, template)

        def place(restored):
            # Belt-and-braces: orbax can return scalar/replicated leaves on
            # a single device; re-place every leaf into the template's
            # sharding so the result is directly usable by the mesh-compiled
            # train step.
            return jax.tree.map(
                lambda r, t: (jax.device_put(r, t.sharding)
                              if isinstance(t, jax.Array) else r),
                restored, template)

        def restore_one(s: int):
            bad = self._verify_digests(s)
            if bad is not None:
                raise ValueError(
                    f"checkpoint step {s} failed integrity check: {bad}")
            saved_target = self._saved_shape_target(s, template)
            if saved_target is None:      # shapes match: the common case
                return place(self._mgr.restore(
                    s, args=ocp.args.StandardRestore(target)))
            # Cross-topology: restore at SAVED shapes (replicated), then
            # pad-swap + rescatter into the template's mesh — never let
            # orbax silently truncate into a smaller target.
            from .parallel.dp import reshard_state
            restored = self._mgr.restore(
                s, args=ocp.args.StandardRestore(saved_target))
            out = reshard_state(restored, template)
            self.stats.ckpt_reshards += 1
            return out

        if step is not None:
            restored = restore_one(step)
            self.restored_step = step  # only after the restore succeeded
            return restored

        candidates = sorted(self.all_steps(), reverse=True)
        if not candidates:
            raise FileNotFoundError("no checkpoint found")
        last_exc: Optional[BaseException] = None
        for s in candidates:
            try:
                restored = restore_one(s)
            except Exception as e:  # corrupt/garbled/digest-failed step
                last_exc = e
                self.stats.ckpt_fallbacks += 1
                continue
            self.restored_step = s  # which step actually won (≤ latest_step)
            return restored
        raise FileNotFoundError(
            f"all {len(candidates)} checkpoint steps failed to restore "
            f"(newest error: {last_exc!r})") from last_exc

    def _saved_shape_target(self, step: int, template):
        """An abstract restore target at the manifest's SAVED leaf shapes
        (template structure, replicated sharding on the template's mesh) —
        or None when shapes already match the template / no manifest
        records them (legacy steps restore as before)."""
        manifest = self._read_manifest(step)
        leaves_meta = (manifest or {}).get("leaves")
        t_leaves, treedef = jax.tree.flatten(template)
        if (not isinstance(leaves_meta, list)
                or len(leaves_meta) != len(t_leaves)):
            return None
        changed = False
        out = []
        for t, meta in zip(t_leaves, leaves_meta):
            if not isinstance(t, jax.Array) or meta is None:
                out.append(jax.ShapeDtypeStruct(t.shape, t.dtype,
                                                sharding=t.sharding)
                           if isinstance(t, jax.Array) else t)
                continue
            shape = tuple(meta["shape"])
            if shape == t.shape:
                out.append(jax.ShapeDtypeStruct(t.shape, t.dtype,
                                                sharding=t.sharding))
                continue
            changed = True
            mesh = getattr(t.sharding, "mesh", None)
            repl = NamedSharding(mesh, P()) if mesh is not None else None
            out.append(jax.ShapeDtypeStruct(shape, np.dtype(meta["dtype"]),
                                            sharding=repl))
        return jax.tree.unflatten(treedef, out) if changed else None

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def close(self) -> None:
        try:
            self._mgr.wait_until_finished()
            self._flush_manifests()
        except Exception:
            pass              # closing must succeed even on a broken disk
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_best(path: str, params: Any) -> None:
    """The reference's best-weights idiom (centralized.py:51) as a one-shot
    file save: host-gather params and write an .npz.

    Atomic: the archive is written to a temp file in the target directory
    and ``os.replace``d into place, so a mid-write kill leaves either the
    previous best intact or the new one — never a truncated .npz (np.savez
    writes incrementally, so a plain in-place save can be killed half-way)."""
    import tempfile

    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    arrays = {jax.tree_util.keystr(p): np.asarray(v) for p, v in flat}
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(os.path.abspath(path)), suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_best(path: str, template: Any) -> Any:
    """Inverse of save_best: load the .npz back into template's structure."""
    data = np.load(path)
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = [jax.device_put(data[jax.tree_util.keystr(p)],
                             v.sharding if isinstance(v, jax.Array) else None)
              for p, v in flat]
    return jax.tree_util.tree_unflatten(treedef, leaves)
