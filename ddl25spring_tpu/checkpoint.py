"""Checkpoint / resume via orbax — distributed-aware, sharding-preserving.

The reference has essentially NO persistence: its only checkpointing is a
best-weights `state_dict()` snapshot held in memory and restored at the end
of one training run (reference: lab/tutorial_2a/centralized.py:51,67-70);
there is no torch.save, no distributed checkpointing, no resume (SURVEY.md
§5.4). This module exceeds that cheaply with the TPU-native standard:
orbax writes each shard from the device that owns it (multi-host safe) and
restores arrays directly into the target mesh layout.

Works for every TrainState in the framework — DP-replicated, PP
stage-sharded, TP/EP weight-sharded — because restore takes a template state
whose shapes/shardings define the layout to materialize into.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from .metrics import ResilienceStats
from .resilience.retry import retry_call


class Checkpointer:
    """Thin wrapper over an orbax CheckpointManager.

    Usage::

        ckpt = Checkpointer(dir, max_to_keep=3)
        ckpt.save(int(state.step), state)          # async-capable save
        state = ckpt.restore(template_state)       # into template's sharding
        step = ckpt.latest_step()                  # None if nothing saved

    Robustness contract (resilience layer): ``save`` retries transient IO
    failures with exponential backoff; ``restore`` falls back past a
    corrupt/unreadable step to the newest step that restores cleanly —
    counted in ``stats.ckpt_fallbacks`` — so a checkpoint truncated by a
    mid-write kill costs ``checkpoint_every`` steps of progress, never the
    run. ``max_to_keep >= 2`` is what makes the fallback non-vacuous.
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 retry_attempts: int = 3, retry_base_delay: float = 0.1,
                 stats: Optional[ResilienceStats] = None):
        self._retry_attempts = max(1, retry_attempts)
        self._retry_base = retry_base_delay
        self.stats = stats if stats is not None else ResilienceStats()
        self.restored_step: Optional[int] = None  # set by restore()
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def _count_retry(self, attempt: int, exc: BaseException) -> None:
        self.stats.retries += 1

    def save(self, step: int, state: Any, *, force: bool = False,
             overwrite: bool = False) -> bool:
        """Persist a pytree (e.g. a TrainState) at ``step``. Returns as soon
        as the arrays are snapshotted; serialization/IO continues in the
        background (orbax async) — call ``wait()`` to block, or rely on the
        lazy waits in restore()/close(). Transient failures (disk pressure,
        a previous async save erroring out at the enqueue barrier) are
        retried with backoff before surfacing.

        ``overwrite=True`` deletes any existing step ``step`` first. Only
        for callers re-treading step indices after a corrupt-latest fallback
        resume: the on-disk entry is then a stale (possibly the corrupt)
        remnant of the pre-fallback lineage, and a blind save would be an
        orbax StepAlreadyExistsError. Default False so double-save bugs
        still fail loudly."""
        if step in self.all_steps():
            if not overwrite:
                # Fail fast and outside the retry loop: a double-save is a
                # deterministic caller bug, and retrying it would both delay
                # the failure and count phantom IO retries into the stats.
                raise ValueError(
                    f"checkpoint step {step} already exists "
                    f"(pass overwrite=True to replace a stale entry)")
            self._mgr.delete(step)
        return retry_call(
            self._mgr.save, step, args=ocp.args.StandardSave(state),
            force=force, attempts=self._retry_attempts,
            base=self._retry_base, seed=step, on_retry=self._count_retry)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def restore(self, template: Any, *, step: Optional[int] = None) -> Any:
        """Restore into ``template``'s structure, dtypes, and shardings.

        ``template`` is a live pytree with the desired layout (typically a
        freshly built TrainState on the current mesh — its values are only
        read for shape/sharding). Defaults to the latest step; if that step
        is corrupt/unreadable (truncated by a kill, garbled on disk), falls
        back to the next-newest step that restores cleanly — each skipped
        step counts into ``stats.ckpt_fallbacks``. An explicitly requested
        ``step`` does NOT fall back: the caller named it, so failing loudly
        is correct.
        """
        self._mgr.wait_until_finished()   # flush any in-flight async save

        def abstract(x):
            if isinstance(x, jax.Array):
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            return x

        target = jax.tree.map(abstract, template)

        def place(restored):
            # Belt-and-braces: orbax can return scalar/replicated leaves on
            # a single device; re-place every leaf into the template's
            # sharding so the result is directly usable by the mesh-compiled
            # train step.
            return jax.tree.map(
                lambda r, t: (jax.device_put(r, t.sharding)
                              if isinstance(t, jax.Array) else r),
                restored, template)

        if step is not None:
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(target))
            self.restored_step = step  # only after the restore succeeded
            return place(restored)

        candidates = sorted(self.all_steps(), reverse=True)
        if not candidates:
            raise FileNotFoundError("no checkpoint found")
        last_exc: Optional[BaseException] = None
        for s in candidates:
            try:
                restored = self._mgr.restore(
                    s, args=ocp.args.StandardRestore(target))
            except Exception as e:  # corrupt/truncated/garbled step
                last_exc = e
                self.stats.ckpt_fallbacks += 1
                continue
            self.restored_step = s  # which step actually won (≤ latest_step)
            return place(restored)
        raise FileNotFoundError(
            f"all {len(candidates)} checkpoint steps failed to restore "
            f"(newest error: {last_exc!r})") from last_exc

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_best(path: str, params: Any) -> None:
    """The reference's best-weights idiom (centralized.py:51) as a one-shot
    file save: host-gather params and write an .npz.

    Atomic: the archive is written to a temp file in the target directory
    and ``os.replace``d into place, so a mid-write kill leaves either the
    previous best intact or the new one — never a truncated .npz (np.savez
    writes incrementally, so a plain in-place save can be killed half-way)."""
    import tempfile

    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    arrays = {jax.tree_util.keystr(p): np.asarray(v) for p, v in flat}
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(os.path.abspath(path)), suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_best(path: str, template: Any) -> Any:
    """Inverse of save_best: load the .npz back into template's structure."""
    data = np.load(path)
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = [jax.device_put(data[jax.tree_util.keystr(p)],
                             v.sharding if isinstance(v, jax.Array) else None)
              for p, v in flat]
    return jax.tree_util.tree_unflatten(treedef, leaves)
