"""Checkpoint / resume via orbax — distributed-aware, sharding-preserving.

The reference has essentially NO persistence: its only checkpointing is a
best-weights `state_dict()` snapshot held in memory and restored at the end
of one training run (reference: lab/tutorial_2a/centralized.py:51,67-70);
there is no torch.save, no distributed checkpointing, no resume (SURVEY.md
§5.4). This module exceeds that cheaply with the TPU-native standard:
orbax writes each shard from the device that owns it (multi-host safe) and
restores arrays directly into the target mesh layout.

Works for every TrainState in the framework — DP-replicated, PP
stage-sharded, TP/EP weight-sharded — because restore takes a template state
whose shapes/shardings define the layout to materialize into.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp


class Checkpointer:
    """Thin wrapper over an orbax CheckpointManager.

    Usage::

        ckpt = Checkpointer(dir, max_to_keep=3)
        ckpt.save(int(state.step), state)          # async-capable save
        state = ckpt.restore(template_state)       # into template's sharding
        step = ckpt.latest_step()                  # None if nothing saved
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3):
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Persist a pytree (e.g. a TrainState) at ``step``. Returns as soon
        as the arrays are snapshotted; serialization/IO continues in the
        background (orbax async) — call ``wait()`` to block, or rely on the
        lazy waits in restore()/close()."""
        return self._mgr.save(step, args=ocp.args.StandardSave(state),
                              force=force)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def restore(self, template: Any, *, step: Optional[int] = None) -> Any:
        """Restore into ``template``'s structure, dtypes, and shardings.

        ``template`` is a live pytree with the desired layout (typically a
        freshly built TrainState on the current mesh — its values are only
        read for shape/sharding). Defaults to the latest step.
        """
        self._mgr.wait_until_finished()   # flush any in-flight async save
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint found")

        def abstract(x):
            if isinstance(x, jax.Array):
                return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
            return x

        target = jax.tree.map(abstract, template)
        restored = self._mgr.restore(step, args=ocp.args.StandardRestore(target))
        # Belt-and-braces: orbax can return scalar/replicated leaves on a
        # single device; re-place every leaf into the template's sharding so
        # the result is directly usable by the mesh-compiled train step.
        return jax.tree.map(
            lambda r, t: (jax.device_put(r, t.sharding)
                          if isinstance(t, jax.Array) else r),
            restored, template)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_best(path: str, params: Any) -> None:
    """The reference's best-weights idiom (centralized.py:51) as a one-shot
    file save: host-gather params and write an .npz."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    arrays = {jax.tree_util.keystr(p): np.asarray(v) for p, v in flat}
    np.savez(path, **arrays)


def load_best(path: str, template: Any) -> Any:
    """Inverse of save_best: load the .npz back into template's structure."""
    data = np.load(path)
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = [jax.device_put(data[jax.tree_util.keystr(p)],
                             v.sharding if isinstance(v, jax.Array) else None)
              for p, v in flat]
    return jax.tree_util.tree_unflatten(treedef, leaves)
