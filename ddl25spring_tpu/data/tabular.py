"""Heart-disease tabular pipeline: loading, preprocessing, vertical splits.

Capability target: the reference's heart.csv preprocessing — one-hot
expansion of the categorical columns + MinMax scaling (lab/tutorial_2b/
vfl.py:105-157, lab/tutorial_2a/centralized.py) — and the hw2 feature→client
partition policies: seeded permutations, even split, and min-2-features with
duplication (lab/hw02/Tea_Pula_HW2.ipynb cells 5, 13, 20).

Offline-capable: reads heart.csv from an explicit path, $DDL_HEART_CSV,
./data/heart.csv, or the reference checkout; otherwise synthesizes a
statistically similar dataset from a ground-truth generalized linear model so
training accuracy targets (~85%) remain meaningful.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

COLUMNS = ["age", "sex", "cp", "trestbps", "chol", "fbs", "restecg",
           "thalach", "exang", "oldpeak", "slope", "ca", "thal"]
CATEGORICAL = ["cp", "restecg", "slope", "ca", "thal"]
TARGET = "target"

_SEARCH = ("data/heart.csv", "/root/reference/lab/tutorial_2a/heart.csv")


def synthetic_heart(n: int = 1025, seed: int = 7) -> Tuple[np.ndarray, np.ndarray]:
    """Rows mimicking heart.csv's columns/ranges, labels from a noisy linear
    model over a few risk features — learnable to roughly the reference's
    ~85% accuracy regime."""
    rng = np.random.default_rng(seed)
    age = rng.integers(29, 78, n)
    sex = rng.integers(0, 2, n)
    cp = rng.integers(0, 4, n)
    trestbps = rng.integers(94, 201, n)
    chol = rng.integers(126, 565, n)
    fbs = rng.integers(0, 2, n)
    restecg = rng.integers(0, 3, n)
    thalach = rng.integers(71, 203, n)
    exang = rng.integers(0, 2, n)
    oldpeak = np.round(rng.uniform(0, 6.2, n), 1)
    slope = rng.integers(0, 3, n)
    ca = rng.integers(0, 5, n)
    thal = rng.integers(0, 4, n)
    logit = (
        -0.04 * (age - 54) + 0.9 * (cp > 0) - 0.02 * (trestbps - 130)
        + 0.025 * (thalach - 150) - 1.1 * exang - 0.7 * oldpeak
        + 0.5 * (slope == 2) - 0.8 * (ca > 0) - 0.9 * (thal == 3) + 0.6
        + rng.normal(0, 0.8, n)
    )
    target = (logit > 0).astype(np.int64)
    X = np.stack([age, sex, cp, trestbps, chol, fbs, restecg, thalach,
                  exang, oldpeak, slope, ca, thal], axis=1).astype(np.float64)
    return X, target


def load_heart(path: Optional[str] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (X [N, 13] float64 raw columns, y [N] int64)."""
    candidates = [path, os.environ.get("DDL_HEART_CSV"), *_SEARCH]
    for c in candidates:
        if c and os.path.exists(c):
            raw = np.genfromtxt(c, delimiter=",", names=True)
            X = np.stack([raw[name] for name in COLUMNS], axis=1)
            y = raw[TARGET].astype(np.int64)
            return X, y
    return synthetic_heart()


def preprocess(X: np.ndarray, *, onehot: bool = True
               ) -> Tuple[np.ndarray, List[str]]:
    """One-hot expand categoricals, MinMax-scale everything to [0, 1].

    Returns (features [N, D], feature_names) where one-hot columns are named
    ``<col>_<value>`` — the naming the feature partitioners group by.
    """
    cols: List[np.ndarray] = []
    names: List[str] = []
    for j, name in enumerate(COLUMNS):
        v = X[:, j]
        if onehot and name in CATEGORICAL:
            values = np.unique(v)
            for val in values:
                cols.append((v == val).astype(np.float32))
                names.append(f"{name}_{int(val)}")
        else:
            lo, hi = v.min(), v.max()
            cols.append(((v - lo) / (hi - lo if hi > lo else 1.0)).astype(np.float32))
            names.append(name)
    return np.stack(cols, axis=1), names


def train_test_split(X: np.ndarray, y: np.ndarray, *, test_fraction: float = 0.2,
                     seed: int = 0, dedup: bool = False):
    """Seeded random split. With ``dedup``, duplicate rows are grouped so no
    test row has an identical twin in train.

    The Kaggle heart.csv the reference uses (1025 rows) is the 303-row UCI
    set expanded with duplicates; a plain random split leaks most test rows
    into train, so a well-trained model scores ≈100% (the reference's
    ≈85% band survives only because of its optimizer quirks). ``dedup=True``
    is the honest-generalization protocol; the default matches the
    reference's leaky protocol for comparability.
    """
    rng = np.random.default_rng(seed)
    if dedup:
        rows = np.concatenate([X, y[:, None].astype(X.dtype)], axis=1)
        _, group = np.unique(rows, axis=0, return_inverse=True)
        n_groups = group.max() + 1
        gperm = rng.permutation(n_groups)
        n_test_groups = int(n_groups * test_fraction)
        test_groups = set(gperm[:n_test_groups].tolist())
        is_test = np.asarray([g in test_groups for g in group])
        te, tr = np.where(is_test)[0], np.where(~is_test)[0]
    else:
        perm = rng.permutation(len(y))
        n_test = int(len(y) * test_fraction)
        te, tr = perm[:n_test], perm[n_test:]
    return X[tr], y[tr], X[te], y[te]


# ------------------------------------------------- vertical feature partitioners

def base_feature_groups(names: Sequence[str]) -> List[List[int]]:
    """Group one-hot columns of the same base feature together so a vertical
    partition never splits a single original column across parties."""
    groups: Dict[str, List[int]] = {}
    for i, n in enumerate(names):
        base = n.rsplit("_", 1)[0] if "_" in n and n.rsplit("_", 1)[0] in CATEGORICAL else n
        groups.setdefault(base, []).append(i)
    return [groups[k] for k in sorted(groups, key=lambda k: groups[k][0])]


def split_features_evenly(names: Sequence[str], nr_clients: int, *, seed: Optional[int] = None
                          ) -> List[List[int]]:
    """Deal base features round-robin (optionally after a seeded permutation)
    — hw2's even partitioner (Tea_Pula_HW2.ipynb cell 13)."""
    groups = base_feature_groups(names)
    if seed is not None:
        rng = np.random.default_rng(seed)
        groups = [groups[i] for i in rng.permutation(len(groups))]
    parts: List[List[int]] = [[] for _ in range(nr_clients)]
    for i, g in enumerate(groups):
        parts[i % nr_clients].extend(g)
    return parts


def split_features_with_minimum(names: Sequence[str], nr_clients: int, *,
                                min_features: int = 2, seed: int = 0) -> List[List[int]]:
    """Every client gets at least ``min_features`` base features, duplicating
    features when there aren't enough to go around — hw2's min-2 policy
    (Tea_Pula_HW2.ipynb cell 20)."""
    groups = base_feature_groups(names)
    min_features = min(min_features, len(groups))  # can't hold more than exist
    rng = np.random.default_rng(seed)
    parts: List[List[int]] = [[] for _ in range(nr_clients)]
    order = list(rng.permutation(len(groups)))
    for i, g in enumerate(order):
        parts[i % nr_clients].extend(groups[g])
    for p in parts:
        held = {tuple(g) for g in groups if set(g) <= set(p)}
        while len(held) < min_features:
            extra = groups[rng.integers(len(groups))]
            if tuple(extra) not in held:
                p.extend(extra)
                held.add(tuple(extra))
    return parts
