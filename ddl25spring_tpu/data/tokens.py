"""Token-stream data pipeline for LLM training.

Capability target: simplellm's ``TinyStories(tokenizer, batch_size, seq_l,
skip=...)`` iterable yielding ``[batch_size, seq_l]`` int batches, where
``skip`` offsets the stream so DP ranks see disjoint data (reference:
lab/tutorial_1b/DP/gradient_aggr/intro_DP_GA.py:29).

Offline-capable: reads a text corpus (one document per line) when one is
available ($DDL_TINYSTORIES or ./data/tinystories.txt), else generates a
deterministic synthetic story corpus from a template grammar — structured
enough that a tiny causal LM shows the reference's loss-curve character
(≈10.5 → ≈6 over a few thousand steps, BASELINE.md) without network access.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional

import numpy as np

# ------------------------------------------------------------ synthetic corpus

_NAMES = ["Lily", "Tom", "Mia", "Ben", "Sara", "Max", "Anna", "Leo", "Ella", "Sam",
          "Lucy", "Tim", "Amy", "Jack", "Rosa", "Finn"]
_ANIMALS = ["cat", "dog", "bird", "bunny", "frog", "duck", "fox", "bear", "mouse", "owl"]
_OBJECTS = ["ball", "kite", "book", "toy", "hat", "cake", "flower", "boat", "drum", "star"]
_PLACES = ["park", "garden", "forest", "house", "beach", "hill", "farm", "pond", "yard", "school"]
_ADJS = ["happy", "little", "big", "red", "shiny", "soft", "brave", "silly", "kind", "tiny"]
_VERBS = ["played", "jumped", "ran", "laughed", "sang", "danced", "walked", "smiled", "looked", "hopped"]

_TEMPLATES = [
    "Once upon a time there was a {adj} {animal} named {name}. {name} loved to play with a {obj} in the {place}. One day {name} {verb} all day long. The {animal} was very {adj2}. At the end of the day {name} went home and slept.",
    "{name} and {name2} went to the {place}. They found a {adj} {obj}. {name} said, I want to share this {obj} with you. {name2} {verb} with joy. They were {adj2} friends forever.",
    "One day a {adj} {animal} found a {obj} near the {place}. The {animal} {verb} and {verb2}. A {adj2} {animal2} came to help. Together they played until the sun went down.",
    "Little {name} had a {adj} {obj}. Every morning {name} took the {obj} to the {place}. One day the {obj} was lost. {name} {verb} everywhere. A {adj2} {animal} found it and {name} was happy again.",
]


def synthetic_story(rng: np.random.Generator) -> str:
    t = _TEMPLATES[rng.integers(len(_TEMPLATES))]
    return t.format(
        name=_NAMES[rng.integers(len(_NAMES))],
        name2=_NAMES[rng.integers(len(_NAMES))],
        animal=_ANIMALS[rng.integers(len(_ANIMALS))],
        animal2=_ANIMALS[rng.integers(len(_ANIMALS))],
        obj=_OBJECTS[rng.integers(len(_OBJECTS))],
        place=_PLACES[rng.integers(len(_PLACES))],
        adj=_ADJS[rng.integers(len(_ADJS))],
        adj2=_ADJS[rng.integers(len(_ADJS))],
        verb=_VERBS[rng.integers(len(_VERBS))],
        verb2=_VERBS[rng.integers(len(_VERBS))],
    )


def synthetic_documents(seed: int = 0) -> Iterator[str]:
    rng = np.random.default_rng(seed)
    while True:
        yield synthetic_story(rng)


_DEFAULT_CORPUS = ("data/tinystories.txt",)


def _document_source(path: Optional[str], seed: int) -> Iterator[str]:
    candidates = [path, os.environ.get("DDL_TINYSTORIES"), *_DEFAULT_CORPUS]
    for c in candidates:
        if c and os.path.exists(c):
            def file_docs(p=c):
                while True:  # cycle the corpus like a streaming dataset
                    yielded = False
                    with open(p, "r", encoding="utf-8") as f:
                        for line in f:
                            line = line.strip()
                            if line:
                                yielded = True
                                yield line
                    if not yielded:
                        raise ValueError(f"corpus file {p} contains no non-empty lines")
            return file_docs()
    return synthetic_documents(seed)


class TokenStream:
    """Iterable of ``[batch_size, seq_len]`` int32 batches.

    ``skip`` counts *sequences* to drop from the head of the stream — the
    reference passes ``skip=rank*5000`` so each DP rank reads a disjoint
    window (intro_DP_GA.py:29). For an SPMD program, pass the per-shard skip
    and stack shard batches, or use `sharded_batches`.
    """

    def __init__(self, tokenizer, batch_size: int, seq_len: int, *,
                 skip: int = 0, path: Optional[str] = None, seed: int = 0):
        self.tokenizer = tokenizer
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.skip = skip
        self._docs = _document_source(path, seed)
        self._buf: List[int] = []
        self._skipped = False

    def _next_seq(self) -> np.ndarray:
        need = self.seq_len
        eos = getattr(self.tokenizer, "eos_id", -1)
        while len(self._buf) < need:
            ids = self.tokenizer.encode(next(self._docs), add_bos=True)
            if eos >= 0:
                ids.append(eos)
            self._buf.extend(ids)
        seq = self._buf[:need]
        del self._buf[:need]
        return np.asarray(seq, dtype=np.int32)

    def __iter__(self):
        if not self._skipped:
            for _ in range(self.skip):
                self._next_seq()
            self._skipped = True
        while True:
            yield np.stack([self._next_seq() for _ in range(self.batch_size)])


def sharded_batches(tokenizer, per_shard_batch: int, seq_len: int, n_shards: int, *,
                    shard_skip: int = 5000, path: Optional[str] = None, seed: int = 0):
    """Yield ``[n_shards, per_shard_batch, seq_len]`` global batches where
    shard ``i`` reads the window the reference's rank ``i`` would have read
    (skip = i·shard_skip). Feed directly to a shard_map'd step with the
    leading axis sharded over the ``data`` mesh axis."""
    streams = [
        iter(TokenStream(tokenizer, per_shard_batch, seq_len,
                         skip=i * shard_skip, path=path, seed=seed))
        for i in range(n_shards)
    ]
    while True:
        yield np.stack([next(s) for s in streams])
