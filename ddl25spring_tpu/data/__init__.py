from . import mnist, tabular, tokens  # noqa: F401
