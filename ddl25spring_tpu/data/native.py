"""ctypes bindings for the native (C++) token data pipeline.

The native engine (native/tokenstream.cpp) supplies the framework's
equivalent of the reference's native data path (sentencepiece C++ +
dataloader machinery inside its deps — SURVEY.md §2.12): SP-compatible
encoding, sequence packing with skip offsets, and a producer thread with a
bounded prefetch ring so tokenization overlaps device compute.

`NativeTokenStream` is a drop-in for data.tokens.TokenStream (same batch
shapes, same skip semantics, same corpus-file behavior). If the shared
library is missing it is built on first use with `make` (g++ is in the
image); if that fails, callers should fall back to the pure-Python stream —
`native_available()` reports which world you're in.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Iterator, List, Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libtokenstream.so"))
_lib = None


def _build() -> bool:
    """One build attempt, retried with backoff: `make` can fail transiently
    (a concurrent build holding an output half-written despite the flock —
    e.g. a watchdog-killed builder's stale artifacts — or memory pressure on
    the oversubscribed host), and the retry turns those into a pause instead
    of a session-long silent fallback to the Python stream. A build that
    *hangs* to its 300 s timeout is not retried — it already proved it won't
    finish, and two more 300 s waits would blow the tier-1 suite's wall-time
    budget (.github/workflows/tier1.yml)."""
    from ..resilience.retry import retry_call

    def attempt() -> None:
        subprocess.run(["make", "-C", os.path.abspath(_NATIVE_DIR)],
                       check=True, capture_output=True, timeout=300)
        if not os.path.exists(_LIB_PATH):
            raise OSError(f"make succeeded but {_LIB_PATH} missing")

    try:
        retry_call(attempt, attempts=3, base=0.5, max_delay=5.0,
                   retry_on=(subprocess.CalledProcessError, OSError))
        return True
    except Exception:
        return False


def _stale() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    try:
        src = os.path.join(os.path.abspath(_NATIVE_DIR), "tokenstream.cpp")
        return os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
    except OSError:
        return True


def _load():
    global _lib
    if _lib is not None:
        return _lib
    # Rebuild when the source is newer than the .so (the library is never
    # committed, only built here). flock serializes concurrent first-loads —
    # multi-process launches must not dlopen a half-written library.
    if _stale():
        import fcntl
        with open(os.path.join(os.path.abspath(_NATIVE_DIR), ".build.lock"),
                  "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if _stale():
                _build()
    if not os.path.exists(_LIB_PATH):
        raise OSError("native tokenstream library unavailable "
                      f"(build failed; see {_NATIVE_DIR})")
    lib = ctypes.CDLL(_LIB_PATH)
    lib.ts_create.restype = ctypes.c_void_p
    lib.ts_create.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32, ctypes.c_int32, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int64, ctypes.c_int32,
    ]
    lib.ts_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)]
    lib.ts_encode.restype = ctypes.c_int64
    lib.ts_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
    ]
    lib.ts_batches_produced.restype = ctypes.c_int64
    lib.ts_batches_produced.argtypes = [ctypes.c_void_p]
    lib.ts_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def native_available() -> bool:
    try:
        _load()
        return True
    except OSError:
        return False


def _vocab_arrays(tokenizer) -> Tuple[bytes, np.ndarray, np.ndarray, np.ndarray, bool]:
    """Flatten a tokenizers.spm.SentencePieceTokenizer's piece table into the
    (pieces_blob, offsets, scores, types) arrays the C ABI takes."""
    pieces: List[Tuple[str, float, int]] = tokenizer.pieces
    blobs = [p.encode("utf-8") for p, _, _ in pieces]
    offsets = np.zeros(len(blobs) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in blobs], out=offsets[1:])
    return (b"".join(blobs), offsets,
            np.asarray([s for _, s, _ in pieces], dtype=np.float32),
            np.asarray([t for _, _, t in pieces], dtype=np.int32),
            bool(tokenizer.is_bpe))


class NativeTokenStream:
    """Drop-in for data.tokens.TokenStream backed by the C++ engine.

    Requires a SentencePieceTokenizer (it ships the piece table across the
    ABI); for ByteTokenizer or other tokenizers use the Python stream.
    """

    def __init__(self, tokenizer, batch_size: int, seq_len: int, *,
                 skip: int = 0, path: Optional[str] = None, seed: int = 0,
                 prefetch: int = 4):
        if not hasattr(tokenizer, "pieces"):
            raise TypeError("NativeTokenStream needs a SentencePieceTokenizer "
                            "(piece table); use data.tokens.TokenStream")
        lib = _load()
        self.batch_size = batch_size
        self.seq_len = seq_len
        blob, offsets, scores, types, is_bpe = _vocab_arrays(tokenizer)
        # Resolve the corpus the same way the Python stream does.
        from .tokens import _DEFAULT_CORPUS
        corpus = b""
        for c in (path, os.environ.get("DDL_TINYSTORIES"), *_DEFAULT_CORPUS):
            if c and os.path.exists(c):
                corpus = os.path.abspath(c).encode()
                break
        self._lib = lib
        self._handle = lib.ts_create(
            blob, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            scores.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            types.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(types), int(is_bpe), corpus, seed,
            batch_size, seq_len, skip, prefetch)
        # keep the arrays alive until ts_create returns (it copies them)
        del blob, offsets, scores, types

    def encode(self, text: str, *, add_bos: bool = False) -> List[int]:
        """Direct native encode (parity-testable against spm.py)."""
        data = text.encode("utf-8")
        cap = max(4 * len(data) + 8, 64)
        out = np.empty(cap, dtype=np.int32)
        n = self._lib.ts_encode(
            self._handle, data, len(data), int(add_bos),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), cap)
        if n > cap:  # shouldn't happen with the generous cap; re-ask
            out = np.empty(n, dtype=np.int32)
            n = self._lib.ts_encode(
                self._handle, data, len(data), int(add_bos),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n)
        return out[:n].tolist()

    def next_batch(self) -> np.ndarray:
        out = np.empty((self.batch_size, self.seq_len), dtype=np.int32)
        self._lib.ts_next(
            self._handle, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return out

    def batches_produced(self) -> int:
        return int(self._lib.ts_batches_produced(self._handle))

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next_batch()

    def close(self) -> None:
        if self._handle:
            self._lib.ts_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
