"""MNIST pipeline: loading, normalization, and federated splits.

Capability target: the reference's torchvision MNIST load with normalization
constants (0.1307, 0.3081) (lab/tutorial_1a/hfl_complete.py:23-31) and its
`split()` partitioner — IID: seeded permutation split into N equal subsets;
non-IID: sort by label into 2N shards and deal 2 shards per client
(hfl_complete.py:91-104).

Offline-capable: reads standard IDX files (optionally .gz) from
$DDL_MNIST_DIR or ./data/mnist; otherwise generates a deterministic
procedural digit dataset (bitmap-font glyphs + jitter + noise) with the same
shapes/statistics so every FL experiment and test runs with no network.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

MEAN, STD = 0.1307, 0.3081  # the reference's normalization constants

# 7x5 bitmap font for the ten digits — the synthetic fallback's glyph source.
_FONT = {
    0: ["01110", "10001", "10011", "10101", "11001", "10001", "01110"],
    1: ["00100", "01100", "00100", "00100", "00100", "00100", "01110"],
    2: ["01110", "10001", "00001", "00010", "00100", "01000", "11111"],
    3: ["11110", "00001", "00001", "01110", "00001", "00001", "11110"],
    4: ["00010", "00110", "01010", "10010", "11111", "00010", "00010"],
    5: ["11111", "10000", "11110", "00001", "00001", "10001", "01110"],
    6: ["00110", "01000", "10000", "11110", "10001", "10001", "01110"],
    7: ["11111", "00001", "00010", "00100", "01000", "01000", "01000"],
    8: ["01110", "10001", "10001", "01110", "10001", "10001", "01110"],
    9: ["01110", "10001", "10001", "01111", "00001", "00010", "01100"],
}


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    magic, = struct.unpack(">i", data[:4])
    ndim = magic & 0xFF
    dims = struct.unpack(">" + "i" * ndim, data[4:4 + 4 * ndim])
    return np.frombuffer(data, dtype=np.uint8, offset=4 + 4 * ndim).reshape(dims)


def _find_idx(data_dir: str, stem: str) -> Optional[str]:
    for suffix in ("", ".gz"):
        p = os.path.join(data_dir, stem + suffix)
        if os.path.exists(p):
            return p
    return None


def _glyph(digit: int) -> np.ndarray:
    g = np.array([[int(c) for c in row] for row in _FONT[digit]], dtype=np.float32)
    # upscale 7x5 -> 21x15, centered on a 28x28 canvas
    up = np.kron(g, np.ones((3, 3), dtype=np.float32))
    canvas = np.zeros((28, 28), dtype=np.float32)
    canvas[3:24, 6:21] = up
    return canvas


def synthetic_mnist(n_train: int = 60000, n_test: int = 10000, seed: int = 0
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Deterministic digit dataset with MNIST shapes: images uint8 [N,28,28],
    labels uint8 [N]. Glyphs are jittered (±3 px), scaled in intensity, and
    noised — linearly separable enough to train, hard enough to need learning."""
    rng = np.random.default_rng(seed)
    glyphs = np.stack([_glyph(d) for d in range(10)])

    def make(n, rng):
        labels = rng.integers(0, 10, size=n).astype(np.uint8)
        images = np.zeros((n, 28, 28), dtype=np.float32)
        dx = rng.integers(-3, 4, size=n)
        dy = rng.integers(-3, 4, size=n)
        intensity = rng.uniform(0.6, 1.0, size=n).astype(np.float32)
        noise = rng.normal(0.0, 0.08, size=(n, 28, 28)).astype(np.float32)
        for i in range(n):
            images[i] = np.roll(np.roll(glyphs[labels[i]], dy[i], axis=0), dx[i], axis=1)
        images = np.clip(images * intensity[:, None, None] + noise, 0.0, 1.0)
        return (images * 255).astype(np.uint8), labels

    x_train, y_train = make(n_train, rng)
    x_test, y_test = make(n_test, rng)
    return x_train, y_train, x_test, y_test


def load_mnist(data_dir: Optional[str] = None, *, n_train: int = 60000,
               n_test: int = 10000, seed: int = 0):
    """(x_train, y_train, x_test, y_test) as raw uint8 arrays.

    Search order: explicit dir, $DDL_MNIST_DIR, ./data/mnist (IDX files,
    gzipped or not); falls back to the synthetic procedural dataset.
    """
    for d in (data_dir, os.environ.get("DDL_MNIST_DIR"), "data/mnist"):
        if not d or not os.path.isdir(d):
            continue
        paths = [_find_idx(d, s) for s in (
            "train-images-idx3-ubyte", "train-labels-idx1-ubyte",
            "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")]
        if all(paths):
            return (_read_idx(paths[0]), _read_idx(paths[1]),
                    _read_idx(paths[2]), _read_idx(paths[3]))
    return synthetic_mnist(n_train, n_test, seed)


def normalize(images: np.ndarray) -> np.ndarray:
    """uint8 [N,28,28] -> normalized float32 NCHW [N,1,28,28] with the
    reference's constants (hfl_complete.py:23)."""
    x = images.astype(np.float32) / 255.0
    return ((x - MEAN) / STD)[:, None, :, :]


def split(labels: np.ndarray, nr_clients: int, iid: bool, seed: int) -> List[np.ndarray]:
    """Partition example indices across clients.

    IID: seeded permutation dealt evenly. Non-IID: sort by label, cut into
    2·N contiguous shards, deal 2 random shards to each client — the
    reference's pathological label-skew scheme (hfl_complete.py:91-104).
    """
    rng = np.random.default_rng(seed)
    n = len(labels)
    if iid:
        perm = rng.permutation(n)
        return [np.sort(s) for s in np.array_split(perm, nr_clients)]
    order = np.argsort(labels, kind="stable")
    shards = np.array_split(order, 2 * nr_clients)
    shard_perm = rng.permutation(2 * nr_clients)
    return [
        np.sort(np.concatenate([shards[shard_perm[2 * i]], shards[shard_perm[2 * i + 1]]]))
        for i in range(nr_clients)
    ]
