"""Workload configuration dataclasses.

The reference has no flag system: hyperparameters live as module constants and
homework-text defaults (reference: lab/tutorial_1b/primer/intro.py:7-23 for the
tiny-Llama constants; lab/homework-1.ipynb cell 5 for the FL defaults N=100,
lr=0.01, C=0.1, E=1, B=100, rounds=10, iid=True, seed=10). Here each workload
gets one frozen dataclass whose *defaults are the reference's parity configs*,
so `FLConfig()` with no arguments reproduces the homework setting.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple


@dataclass(frozen=True)
class FLConfig:
    """Horizontal federated learning (FedSGD / FedAvg) configuration.

    Defaults mirror the homework-1 defaults (reference: lab/homework-1.ipynb
    cell 5 and lab/tutorial_1a/hfl_complete.py:256-386).
    """

    nr_clients: int = 100          # N
    client_fraction: float = 0.1   # C — fraction of clients sampled per round
    batch_size: int = 100          # B — -1 means full local dataset (∞)
    epochs: int = 1                # E — local epochs per round (FedAvg)
    lr: float = 0.01               # η
    rounds: int = 10
    iid: bool = True
    seed: int = 10

    @property
    def clients_per_round(self) -> int:
        # max(1, C·N) like the reference's client sampling.
        return max(1, int(self.client_fraction * self.nr_clients))


@dataclass(frozen=True)
class LlamaConfig:
    """tiny-Llama model configuration.

    Defaults are the canonical config used by every reference LLM experiment
    (reference: lab/tutorial_1b/primer/intro.py:7-10 — dmodel=288, 6 heads,
    6 layers, seq 256; Adam lr 8e-4 at intro.py:22).
    """

    vocab_size: int = 32000
    dmodel: int = 288
    num_heads: int = 6
    n_layers: int = 6
    ctx_size: int = 256
    ffn_hidden: Optional[int] = None   # None -> 4 * dmodel (SwiGLU-gated)
    padding_idx: Optional[int] = None
    norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    dtype: str = "float32"             # computation dtype ("bfloat16" on TPU)
    param_dtype: str = "float32"
    # Attention backend: "xla" (fused-softmax dot_generals), "pallas" (the
    # flash kernel), or "auto" (pallas iff running on TPU and the sequence is
    # at least ``flash_min_seq``). The crossover is measured, not guessed:
    # with the dh-major wide-block kernel the flash path wins at every swept
    # length on v5e — fwd+bwd 4.65 vs 4.77 ms at T=256 (and 25x at T=8192),
    # +7% end-to-end on the train step (experiments/results/attn_bench.csv,
    # BENCH_r04) — so "auto" takes it from the canonical T=256 up. Below 256
    # it is unmeasured and auto stays on XLA.
    attention_impl: str = "auto"
    flash_min_seq: int = 256
    # Stream flash-kernel operands in the dense [BH, Dh, T] layout instead of
    # [BH, T, Dh]. At head dims below 128 lanes (this model's 48) the
    # row-major layout pads every q/k/v/o and gradient transfer to 128 lanes
    # — 2.67x the useful HBM bytes at Dh=48 — while dh-major is exactly
    # dense. Same math and MXU shapes (ops/flash_attention.py); on by
    # default since the on-chip measurement (attn_bench.csv) says it wins
    # at every swept length when combined with ``flash_block`` wide blocks.
    flash_dh_major: bool = True
    # Pallas block size cap (block_q = block_k = min(T, flash_block)). The
    # kernel default 128 keeps VMEM small for long sequences; at T ≤ 512 a
    # whole-sequence block ("wide": one grid step per (b, h), no
    # online-softmax recurrence) is measured fastest on v5e at every swept
    # length (experiments/results/attn_bench.csv) — 512 is therefore the
    # default cap.
    flash_block: int = 512
    # Dtype of the materialized [B·H, T, T] attention score tensor. The
    # default fp32 is what the PP/SP equivalence tests are calibrated to;
    # "bfloat16" halves the attention leg's dominant HBM tensor (softmax
    # max/denominator stay fp32) at ~1e-2 logit drift — an opt-in throughput
    # knob, measured ~9% on standalone attention fwd+bwd (ROOFLINE.md).
    # Applies to the XLA attention path only: the pallas flash kernel never
    # materializes the score tensor in the first place (fp32 accumulators,
    # tile-local scores), and SP's ring attention owns its own fp32
    # online-softmax accumulation — on those paths this knob is a no-op.
    softmax_dtype: str = "float32"
    # Rematerialize block activations in backward (jax.checkpoint) — trades
    # FLOPs for HBM, the TPU-native answer to activation memory pressure.
    remat: bool = False

    @property
    def head_dim(self) -> int:
        assert self.dmodel % self.num_heads == 0
        return self.dmodel // self.num_heads

    @property
    def ffn_dim(self) -> int:
        return self.ffn_hidden if self.ffn_hidden is not None else 4 * self.dmodel

    def replace(self, **kw) -> "LlamaConfig":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class MoEConfig:
    """Mixture-of-Experts tiny-Llama configuration (parity-plus: the
    reference has no MoE/expert parallelism — SURVEY.md §2.10 marks EP
    "Absent"). Every block's SwiGLU MLP becomes a top-k routed expert bank;
    attention/embedding stay the LlamaConfig canonical shapes."""

    base: LlamaConfig = field(default_factory=LlamaConfig)
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25  # expert capacity = ceil(N·k/E · factor)
    aux_loss_coef: float = 0.01    # load-balance loss weight (Switch-style)

    def replace(self, **kw) -> "MoEConfig":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class TrainConfig:
    """LLM training loop configuration (reference: primer/intro.py:22-23 —
    Adam lr 8e-4, 5000 iterations, batch 3 per rank, seq 256)."""

    batch_size: int = 3            # per-data-shard batch (reference: per-rank)
    seq_len: int = 256
    lr: float = 8e-4
    iters: int = 5000
    seed: int = 0
    # Mesh layout: named axis sizes. 1 disables that axis.
    data: int = 1
    # Hierarchical data parallelism: dcn > 1 splits the DP world into
    # ``dcn`` ICI islands of ``data`` replicas each (total world =
    # dcn·data; parallel/distributed.py:hier_data_mesh). Gradient sync
    # must then run the two-level ring driver (overlap_microbatches >= 1)
    # with per-axis wire formats: ``wire`` is the ICI tier's format
    # (fp32/bf16), ``wire_dcn`` the scarce DCN tier's (fp32/bf16/int8_ef)
    # — compression spent exactly where bandwidth is scarce.
    dcn: int = 1
    stage: int = 1                 # pipeline stages
    model: int = 1                 # tensor parallel degree
    seq: int = 1                   # sequence/context parallel degree
    microbatches: int = 1          # GPipe microbatches per step (PP)
    remat: bool = False            # jax.checkpoint on transformer blocks
    # Optimizer: "adam" (optax, the reference's), "fused" (ops/adam.py
    # single-pass), "pallas" (ops/pallas_adam.py fused apply), "master"
    # (ops/mixed_precision.py — pair with LlamaConfig param_dtype bf16).
    optimizer: str = "adam"
    # Gradient-allreduce wire format: "fp32" (plain pmean), "bf16" or
    # "int8_ef" (parallel/compress.py). On a hierarchical mesh (dcn > 1)
    # this is the ICI tier's format and ``wire_dcn`` selects the DCN
    # tier's. On the PP trainer a non-fp32 wire requires
    # overlap_microbatches >= 1 — it rides the DP×PP data-axis ring
    # (parallel/pp.py make_pipeline_overlap_*).
    wire: str = "fp32"
    # DCN-tier wire format of the two-level hierarchical collectives
    # (requires dcn > 1 and overlap_microbatches >= 1): "" defaults to
    # "fp32"; "int8_ef" is the headline mode — full-precision
    # reduce-scatter within each ICI island, int8+error-feedback across
    # the DCN hop only, intra-island gather after (the EQuARX/DynamiQ
    # shape; parallel/compress.py hier_reduce_scatter).
    wire_dcn: str = ""
    accum_steps: int = 1           # DP gradient accumulation (dp.py)
    # Fused multi-step dispatch (DP and PP trainers): K > 1 lax.scans K
    # training steps over a [K, B, T] device-resident batch window in ONE
    # compiled, donated dispatch (dp.make_multi_step /
    # make_zero1_multi_step; pp.make_pipeline_multi_step for any pipeline
    # schedule) — the per-step Python dispatch overhead is paid once per
    # window. Loss trajectory is bit-identical to K=1; host-side work
    # (loss sink, telemetry step events, checkpoint saves, StepGuard
    # verdicts, preempt checks) quantizes to chunk edges — see
    # train/llm.py:_run_loop.
    steps_per_dispatch: int = 1
    # Overlapped+compressed gradient sync (parallel/compress.py; on the
    # PP trainer the DP×PP data-axis version, parallel/pp.py
    # make_pipeline_overlap_*): M >= 1 routes gradient sync through the
    # ACCO-style microbatch ring driver — each step's local batch splits
    # into M
    # microbatches and microbatch k+1's grad compute overlaps microbatch
    # k's ppermute-pipelined ring reduce-scatter, with the in-flight
    # chunks in the ``wire`` format (fp32 / bf16 / int8+error-feedback,
    # EF residuals carried in the scan carry and the checkpointed state).
    # Composes with aggregation in {"gradient", "zero1"} and
    # steps_per_dispatch (bitwise-identical losses at any K for fixed M).
    # M = 1 is the no-split ring (compressed wire at zero1 composition,
    # no overlap); 0 disables — the legacy per-step paths run unchanged.
    # Wire bytes scale with M on the ring leg (each microbatch syncs), so
    # M > 1 trades wire for overlap — see docs/COMPONENTS.md's
    # composition matrix.
    overlap_microbatches: int = 0
    # Bucketed backward for the overlap drivers (compress.py BucketMap;
    # all three columns — DP, DP×PP, DP×TP — and the hierarchical
    # wire={"ici","dcn"} tier): B > 1 splits each microbatch's flat
    # gradient into B ordered buckets aligned to the stacked ``blocks``
    # layer groups, top-of-network first (VJP emission order), and each
    # bucket rings independently (labels ``*ring_grad_b{b}``) with no
    # data dependence on later buckets' grad compute — the within-
    # backward ACCO overlap (first ring hop starts before the full
    # gradient materializes; evidence via compress.ring_overlap_evidence,
    # gated in experiments/comm_wire_smoke.py). ZeRO-1 moments and EF
    # residuals become per-bucket tuples in the checkpointed state (the
    # reshard_state bucket contract). Total ring/gather payload bytes are
    # exactly invariant in B (the int8 ring adds one 4-byte scale per
    # extra bucket per hop); fp32 stays bitwise vs B=1 on
    # exact-arithmetic inputs. Requires overlap_microbatches >= 1;
    # 1 is the legacy single-vector ring.
    comm_buckets: int = 1
    # In-jit numerics summaries (telemetry/introspect.py; DP trainer
    # gradient/zero1, PP trainer via pp.make_pp_numerics with block
    # groups stage-qualified): N > 0 instruments the compiled step with
    # per-layer-group grad/param/update norms + per-leaf NaN attribution
    # and emits a ``numerics`` event every N steps (the emission syncs the
    # tiny summary arrays; the in-jit compute itself is free and
    # bitwise-invisible — losses/params identical on vs off, pinned in
    # tests/test_introspect.py and tests/test_pp.py). 0 disables
    # instrumentation entirely.
    numerics_every: int = 0
    # Partially-synchronized activations (TP trainer; parallel/tp.py,
    # after arXiv 2506.19645): how the per-sub-layer TP activation
    # all-reduces on the forward critical path are performed. "" — the
    # legacy Megatron path (raw in-model psum; the bitwise reference).
    # "full" — the SAME sync positions routed through the telemetry comm
    # wrappers: value-identical to "", but the model-axis activation wire
    # becomes visible to telemetry/comm.py (the smoke's same-run
    # baseline). "defer:L" — one boundary sync per L layers instead of
    # two per layer (requires n_layers % L == 0); activations between
    # boundaries evolve from per-shard partial sums, cutting model-axis
    # activation wire to 1/(2L) of full sync at a pinned
    # convergence-tolerance cost. "int8_ef" — every sub-layer sync is an
    # int8 all-gather with a per-(model-shard, sub-layer) error-feedback
    # residual tree carried in the train state (compress.py's EF shape),
    # ~tp/8 of full-sync wire; gradients flow as if the sync were an
    # exact psum. Relaxed modes hold the convergence bars pinned in
    # tests/test_tp.py; wire budgets are gated in
    # experiments/tp_fusion_smoke.py.
    psa: str = ""


@dataclass(frozen=True)
class ResilienceConfig:
    """Self-healing knobs for the training loops (resilience/).

    Passed to the LLM trainers (``resilience=``) and honored by bench /
    experiment drivers. ``faults`` is a FaultPlan spec string (see
    resilience/faults.py) so injection runs are configurable from a CLI
    flag; empty means inject nothing. Defaults are the production posture:
    guard on, detector warmed up past optimizer-startup transients.
    """

    guard: bool = True             # wrap the train step in a StepGuard
    # In-jit non-finite skip fused INTO the compiled step (gradient/zero1
    # and the overlap/ring drivers, parallel/{dp,compress}.py
    # ``guard_nonfinite``): a bad step select-backs the whole state —
    # EF residuals included — without leaving jit, the step counter does
    # not advance, and the loop counts the non-advances into
    # ``ResilienceStats.skipped_steps`` at the end-of-run sync. Mutually
    # exclusive with ``guard`` (the host-side StepGuard would double-count
    # the same skip; pick the sync-free fused skip OR the host guard's
    # EMA/rollback machinery).
    injit_guard: bool = False
    max_consecutive_bad: int = 3   # K consecutive bad steps → rollback
    ema_decay: float = 0.98        # update-norm EMA smoothing
    anomaly_factor: float = 10.0   # spike threshold (×EMA); <=0 disables
    ema_warmup: int = 20           # good steps before the detector arms
    retry_attempts: int = 3        # checkpoint-IO retry budget
    retry_base_delay: float = 0.1  # seconds; doubles per attempt, jittered
    faults: str = ""               # FaultPlan spec for injection runs
    fault_seed: int = 0            # drives every random fault choice
    # Elastic parallelism (resilience/elastic.py; DP, DP×PP, and DP×TP
    # fused-dispatch trainers): survive device loss mid-run by draining
    # at the chunk edge, re-meshing onto the survivors and resharding
    # the state. On a DP×PP mesh the controller prefers dropping a data
    # row; when the victim's stage column has no surviving replica it
    # RE-PARTITIONS layers onto fewer stages (S→S′, S′ | n_layers) and
    # re-slices the stage-sharded state by global coordinate id. On
    # DP×TP only the data axis re-meshes (PSA activation EF residuals
    # resize per data row); a model-axis loss is unrecoverable. With
    # zero faults the elastic loop's loss trajectory is bitwise the
    # non-elastic one (tests/test_elastic.py).
    elastic: bool = False
    # Host-RAM last-good state mirror cadence, in chunk edges: 1 mirrors
    # every edge (recovery replays nothing), k mirrors every k-th (cheaper
    # steady state, up to k·steps_per_dispatch steps replayed on
    # recovery), 0 disables the fast path (recovery goes through the
    # checkpoint).
    mirror_every: int = 1

    def fault_plan(self):
        """The configured FaultPlan (empty spec → empty plan)."""
        from .resilience.faults import FaultPlan
        return FaultPlan.from_spec(self.faults, seed=self.fault_seed)


@dataclass(frozen=True)
class VFLConfig:
    """Vertical FL / split learning configuration (reference:
    lab/tutorial_2b/vfl.py:159-168 — 4 clients, 300 epochs, batch 64)."""

    nr_clients: int = 4
    epochs: int = 300
    batch_size: int = 64
    lr: float = 1e-3
    # Per-client bottom output width multiplier: party i sends
    # bottom_out_mult · d_i activations up the cut — the reference's
    # outs_per_client sizing (vfl.py:139-141).
    bottom_out_mult: int = 2
    seed: int = 0


@dataclass(frozen=True)
class VAEConfig:
    """Tabular VAE configuration (reference: lab/tutorial_2a/
    generative-modeling.py:13-116 — input 13, latent dim 3, BN-MLP stack)."""

    input_dim: int = 13
    hidden_dims: Tuple[int, ...] = (50, 12)
    latent_dim: int = 3
    lr: float = 1e-3
    epochs: int = 200
    batch_size: int = 64
    seed: int = 0


@dataclass(frozen=True)
class AttackConfig:
    """Byzantine adversary injection (reference: lab/tutorial_3/
    attacks_and_defenses.ipynb cell 9 — 20% malicious, and the hw03 sweep
    setting lr=0.02, B=200, C=0.2, E=2, seed 42)."""

    malicious_fraction: float = 0.2
    attack: str = "gradient_reversion"
    scale: float = 5.0             # the -5x / 5x / 2x update scaling knobs
    backdoor_label: int = 0
    seed: int = 42
