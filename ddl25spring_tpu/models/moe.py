"""Mixture-of-Experts tiny-Llama with capacity-based top-k routing.

Parity-plus capability: the reference has no MoE (SURVEY.md §2.10 marks
expert parallelism "Absent"). This is the TPU-native formulation: routing is
expressed as dense one-hot dispatch/combine einsums over a fixed expert
capacity — static shapes, no gather/scatter of ragged token lists — so XLA
tiles every expert matmul onto the MXU and `parallel.ep` can shard the
expert bank over an ``expert`` mesh axis with one psum to combine.

Shapes (N = B·T flattened tokens, E experts, C capacity, D model, F ffn):
- router logits  [N, E]  → top-k probs, renormalized over the chosen k.
- dispatch       [N, E, C] one-hot: token n occupies slot c of expert e.
  Tokens beyond an expert's capacity are DROPPED (their combine weight is 0
  and the residual stream passes them through unchanged — Switch semantics).
- expert_in = einsum('nec,nd->ecd') ; expert MLP maps [E, C, D] → [E, C, D];
  combine = einsum('nec,ecd->nd') with probabilities folded into dispatch.

The auxiliary load-balance loss is the Switch/GShard form:
``E · Σ_e fraction_tokens(e) · mean_router_prob(e)``, minimized at uniform
routing; forward returns it alongside the logits.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import MoEConfig
from .. import nn
from . import llama


# ------------------------------------------------------------------ init

def init_moe_block(key, cfg: MoEConfig) -> dict:
    """One MoE transformer block: llama attention + routed expert MLPs."""
    base = cfg.base
    dt = jnp.dtype(base.param_dtype)
    d, f, e = base.dmodel, base.ffn_dim, cfg.n_experts
    ks = jax.random.split(key, 9)
    std = 0.02
    out_std = 0.02 / (2 * base.n_layers) ** 0.5
    normal = lambda k, shape, s: jax.random.normal(k, shape, dt) * jnp.asarray(s, dt)
    return {
        "attn_norm": nn.rmsnorm_init(d, dt),
        "wq": normal(ks[0], (d, d), std),
        "wk": normal(ks[1], (d, d), std),
        "wv": normal(ks[2], (d, d), std),
        "wo": normal(ks[3], (d, d), out_std),
        "mlp_norm": nn.rmsnorm_init(d, dt),
        "router": normal(ks[4], (d, e), std),
        "w_gate": normal(ks[5], (e, d, f), std),
        "w_up": normal(ks[6], (e, d, f), std),
        "w_down": normal(ks[7], (e, f, d), out_std),
    }


def init_moe_llama(key, cfg: MoEConfig) -> dict:
    """Full MoE model; same embed/final_norm/lm_head structure as llama so
    checkpointing and stage-splitting tooling applies unchanged."""
    base = cfg.base
    dt = jnp.dtype(base.param_dtype)
    k_embed, k_blocks, k_head = jax.random.split(key, 3)
    block_keys = jax.random.split(k_blocks, base.n_layers)
    blocks = jax.vmap(lambda k: init_moe_block(k, cfg))(block_keys)
    normal = lambda k, shape: jax.random.normal(k, shape, dt) * jnp.asarray(0.02, dt)
    return {
        "embed": normal(k_embed, (base.vocab_size, base.dmodel)),
        "blocks": blocks,
        "final_norm": nn.rmsnorm_init(base.dmodel, dt),
        "lm_head": normal(k_head, (base.dmodel, base.vocab_size)),
    }


def capacity(n_tokens: int, cfg: MoEConfig) -> int:
    c = math.ceil(n_tokens * cfg.top_k / cfg.n_experts * cfg.capacity_factor)
    return max(c, cfg.top_k)


# ------------------------------------------------------------------ routing

def route(router_logits: jnp.ndarray, cfg: MoEConfig, cap: int
          ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Top-k dispatch. router_logits [N, E] →
    (dispatch [N, E, C] binary, combine [N, E, C] prob-weighted, aux loss).

    dispatch[n, e, c] = 1 iff token n occupies slot c of expert e — experts
    see the UNSCALED token x (Switch semantics); combine = dispatch · prob is
    applied only on the way out. Slot assignment is first-come-first-served
    by token order via a per-expert cumulative count; overflowing tokens
    contribute nothing (their residual stream passes through unchanged).
    """
    n, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    top_p, top_idx = lax.top_k(probs, cfg.top_k)               # [N, k]
    top_p = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)

    # Aux load-balance loss uses the pre-normalization router probabilities
    # and the realized assignment fractions (Switch eq. 4).
    assign1 = jax.nn.one_hot(top_idx[:, 0], e)                 # primary expert
    frac_tokens = assign1.mean(0)
    frac_probs = probs.mean(0)
    aux = e * jnp.sum(frac_tokens * frac_probs)

    # Slot positions: for the flattened (k·N) assignment sequence, each
    # token's slot within its expert = #prior assignments to that expert.
    # Order: all tokens' 1st choices, then 2nd choices (priority to 1st).
    flat_idx = top_idx.T.reshape(-1)                           # [k·N]
    onehot = jax.nn.one_hot(flat_idx, e, dtype=jnp.int32)      # [k·N, E]
    pos_in_expert = jnp.cumsum(onehot, axis=0) - onehot        # exclusive
    slot = (pos_in_expert * onehot).sum(-1)                    # [k·N]
    keep = slot < cap
    slot_oh = jax.nn.one_hot(slot, cap) * keep[:, None]        # [k·N, C]
    # disp[k·N, E, C] → fold k back onto tokens; a (token, expert, slot)
    # triple is unique, so summing over k keeps dispatch binary.
    disp = onehot[:, :, None] * slot_oh[:, None, :]            # [k·N, E, C]
    disp = disp.reshape(cfg.top_k, n, e, cap)
    weights = top_p.T.reshape(cfg.top_k, n, 1, 1)
    dispatch = disp.sum(0)                                     # [N, E, C]
    combine = (disp * weights).sum(0)                          # [N, E, C]
    return dispatch, combine, aux


def moe_mlp(block: dict, x: jnp.ndarray, cfg: MoEConfig,
            expert_axis: Optional[str] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Routed expert MLP. x [B, T, D] → ([B, T, D], aux loss).

    Under ``expert_axis`` (shard_map EP): the expert bank's leading axis is
    the local slice; routing runs replicated against ALL experts (the router
    is tiny), each shard processes its local experts' slots, and the combine
    is a psum over the axis.
    """
    b, t, d = x.shape
    xf = x.reshape(b * t, d)
    logits = xf @ block["router"].astype(x.dtype)              # [N, E_global]
    e_local = block["w_gate"].shape[0]
    cap = capacity(b * t, cfg)
    dispatch, combine, aux = route(logits, cfg, cap)           # [N, E, C] ×2
    if expert_axis is not None:
        shard = lax.axis_index(expert_axis)
        dispatch = lax.dynamic_slice_in_dim(
            dispatch, shard * e_local, e_local, axis=1)        # local experts
        combine = lax.dynamic_slice_in_dim(
            combine, shard * e_local, e_local, axis=1)
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, xf)        # [E_l, C, D]
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in,
                                  block["w_gate"].astype(x.dtype)))
    up = jnp.einsum("ecd,edf->ecf", expert_in, block["w_up"].astype(x.dtype))
    expert_out = jnp.einsum("ecf,efd->ecd", gate * up,
                            block["w_down"].astype(x.dtype))
    y = jnp.einsum("nec,ecd->nd", combine, expert_out)
    if expert_axis is not None:
        y = lax.psum(y, expert_axis)
    return y.reshape(b, t, d), aux


# ------------------------------------------------------------------ forward

def moe_block_apply(block: dict, x: jnp.ndarray, cfg: MoEConfig,
                    cos: jnp.ndarray, sin: jnp.ndarray,
                    expert_axis: Optional[str] = None
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    base = cfg.base
    x = x + llama.attention(
        block, nn.rmsnorm(block["attn_norm"], x, eps=base.norm_eps),
        base, cos, sin)
    y, aux = moe_mlp(block, nn.rmsnorm(block["mlp_norm"], x, eps=base.norm_eps),
                     cfg, expert_axis)
    return x + y, aux


def forward(params: dict, tokens: jnp.ndarray, cfg: MoEConfig,
            expert_axis: Optional[str] = None
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """tokens [B, T] → (logits [B, T, V], total aux loss over blocks)."""
    base = cfg.base
    h = llama.embed(params, tokens, base)
    positions = jnp.arange(tokens.shape[1])
    cos, sin = llama.rope_angles(positions, base.head_dim, base.rope_theta)

    def apply_one(block, h, cos, sin):
        return moe_block_apply(block, h, cfg, cos, sin, expert_axis)

    fn = jax.checkpoint(apply_one) if base.remat else apply_one

    def body(carry, block):
        h, aux_sum = carry
        h, aux = fn(block, h, cos, sin)
        return (h, aux_sum + aux), None

    (h, aux), _ = lax.scan(body, (h, jnp.zeros((), jnp.float32)),
                           params["blocks"])
    return llama.head(params, h, base), aux


def param_count(params) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(params))
