"""MNIST CNN — the HFL workhorse model.

Capability target: the reference's `MnistCnn` (lab/tutorial_1a/
hfl_complete.py:39-64), the model every FedSGD/FedAvg/attack/defense
experiment trains. Standard two-conv CNN; inputs are NCHW [B, 1, 28, 28]
normalized with the MNIST constants (0.1307, 0.3081) preserved by the data
layer (hfl_complete.py:23).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import nn

NUM_CLASSES = 10


def init(key) -> dict:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    return {
        "conv1": nn.conv2d_init(k1, 1, 32, 3),
        "conv2": nn.conv2d_init(k2, 32, 64, 3),
        # 28 -> conv3 26 -> pool 13 -> conv3 11 -> pool 5; 64·5·5 = 1600
        "fc1": nn.dense_init(k3, 64 * 5 * 5, 128),
        "fc2": nn.dense_init(k4, 128, NUM_CLASSES),
    }


def apply(params: dict, x: jnp.ndarray) -> jnp.ndarray:
    """x: [B, 1, 28, 28] -> logits [B, 10]."""
    h = nn.relu(nn.conv2d(params["conv1"], x))
    h = nn.max_pool2d(h)
    h = nn.relu(nn.conv2d(params["conv2"], h))
    h = nn.max_pool2d(h)
    h = h.reshape(h.shape[0], -1)
    h = nn.relu(nn.dense(params["fc1"], h))
    return nn.dense(params["fc2"], h)
