"""MNIST CNN — the HFL workhorse model.

Capability target: the reference's `MnistCnn` (lab/tutorial_1a/
hfl_complete.py:39-64), the model every FedSGD/FedAvg/attack/defense
experiment trains, reproduced architecture-for-architecture:
conv1(1→32,3) → relu → conv2(32→64,3) → relu → maxpool(2) → dropout(0.25)
→ flatten (64·12·12 = 9216) → fc1(9216→128) → relu → dropout(0.5)
→ fc2(128→10). Inputs are NCHW [B, 1, 28, 28] normalized with the MNIST
constants (0.1307, 0.3081) preserved by the data layer (hfl_complete.py:23).

The reference returns log-probabilities and trains with NLL loss; we return
logits and train with cross-entropy — the same function. Dropout is active
iff a PRNG ``key`` is passed (the functional analog of ``model.train()`` /
``model.eval()``, hfl_complete.py:72,172): FL local-training kernels thread
per-(client, round) keys; evaluation passes none.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import nn

NUM_CLASSES = 10


def init(key) -> dict:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    return {
        "conv1": nn.conv2d_init(k1, 1, 32, 3),
        "conv2": nn.conv2d_init(k2, 32, 64, 3),
        # 28 -> conv3 26 -> conv3 24 -> pool 12; 64·12·12 = 9216
        "fc1": nn.dense_init(k3, 64 * 12 * 12, 128),
        "fc2": nn.dense_init(k4, 128, NUM_CLASSES),
    }


def apply(params: dict, x: jnp.ndarray, *, key=None) -> jnp.ndarray:
    """x: [B, 1, 28, 28] -> logits [B, 10]. Dropout active iff key given."""
    h = nn.relu(nn.conv2d(params["conv1"], x))
    h = nn.relu(nn.conv2d(params["conv2"], h))
    h = nn.max_pool2d(h)
    if key is not None:
        k1, k2 = jax.random.split(key)
        h = nn.dropout(k1, h, 0.25, train=True)
    h = h.reshape(h.shape[0], -1)
    h = nn.relu(nn.dense(params["fc1"], h))
    if key is not None:
        h = nn.dropout(k2, h, 0.5, train=True)
    return nn.dense(params["fc2"], h)
