"""Tabular classifier for the heart-disease task.

Capability target: the reference's `HeartDiseaseNN`
(lab/tutorial_2a/centralized.py:13-28), reproduced
architecture-for-architecture: in(30 one-hot features)→64→128→256→2 with
LeakyReLU activations and dropout(0.1) before the output layer, trained with
best-state_dict-by-test-accuracy tracking (centralized.py:51,67-70).

Dropout is active iff a PRNG ``key`` is passed. Documented deviation: the
reference never calls ``model.eval()`` in centralized.py, so its test-time
forward keeps dropout on; we evaluate deterministically (pass no key), which
only reduces evaluation noise.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from .. import nn

NUM_CLASSES = 2
DROPOUT = 0.1


def init(key, in_dim: int = 30, hidden: Sequence[int] = (64, 128, 256)) -> list:
    """Layer stack [in, *hidden, 2]; defaults are the reference architecture."""
    return nn.mlp_init(key, [in_dim, *hidden, NUM_CLASSES])


def apply(params: list, x: jnp.ndarray, *, key=None) -> jnp.ndarray:
    """x: [B, in_dim] -> logits [B, 2]. LeakyReLU between layers, dropout
    before the final layer when a key is given (centralized.py:22-27)."""
    for layer in params[:-1]:
        x = nn.leaky_relu(nn.dense(layer, x))
    if key is not None:
        x = nn.dropout(key, x, DROPOUT, train=True)
    return nn.dense(params[-1], x)
