"""Tabular classifier for the heart-disease task.

Capability target: the reference's `HeartDiseaseNN` 4-layer MLP
(lab/tutorial_2a/centralized.py:13-28) trained on heart.csv with
best-state_dict-by-test-accuracy tracking (centralized.py:51,67-70).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from .. import nn

NUM_CLASSES = 2


def init(key, in_dim: int = 13, hidden: Sequence[int] = (64, 32, 16)) -> list:
    return nn.mlp_init(key, [in_dim, *hidden, NUM_CLASSES])


def apply(params: list, x: jnp.ndarray) -> jnp.ndarray:
    """x: [B, in_dim] -> logits [B, 2]."""
    return nn.mlp(params, x)
