"""tiny-Llama: a functional causal transformer, designed for TPU parallelism.

Capability target (NOT a port): the ``simplellm`` Llama family the reference
trains everywhere — full model `LLama(...)`, plus the pipeline-stage variants
`LLamaFirstStage` (with a separate ``.embed``), `LLamaStage` (hidden→hidden),
and `LLamaLastStage` (hidden→logits); canonical config dmodel=288, 6 heads,
6 layers, ctx 256 (reference: lab/tutorial_1b/primer/intro.py:7-18,
lab/tutorial_1b/PP/1F1B/intro_PP_1F1B.py:29-39).

TPU-first design decisions:
- Transformer blocks are *stacked*: every block parameter has a leading
  ``[n_layers, ...]`` axis and the forward pass is a single ``lax.scan`` —
  one compiled block body regardless of depth, which keeps compile time flat
  and makes pipeline-stage splitting a pure array slice on the leading axis
  (`split_stages` / `stage_apply`).
- Pre-norm RMSNorm + RoPE + SwiGLU MLP (Llama conventions).
- dtype-parameterized: params in fp32, activations typically bf16 so matmuls
  land on the MXU at full rate.
- No data-dependent Python control flow: jit/scan end-to-end.
- Attention is pluggable: "xla" einsum-softmax (XLA fuses it well) or the
  Pallas flash kernel (ops.flash_attention) once seq lengths warrant it.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import LlamaConfig
from .. import nn


# ------------------------------------------------------------------ init

def _normal(key, shape, std, dtype):
    return jax.random.normal(key, shape, dtype) * jnp.asarray(std, dtype)


def init_block(key, cfg: LlamaConfig) -> dict:
    """One transformer block's parameters (un-stacked)."""
    dt = jnp.dtype(cfg.param_dtype)
    d, f = cfg.dmodel, cfg.ffn_dim
    ks = jax.random.split(key, 7)
    std = 0.02
    # Residual-out projections scaled down by sqrt(2·L) (GPT-2/Llama init).
    out_std = 0.02 / math.sqrt(2 * cfg.n_layers)
    return {
        "attn_norm": nn.rmsnorm_init(d, dt),
        "wq": _normal(ks[0], (d, d), std, dt),
        "wk": _normal(ks[1], (d, d), std, dt),
        "wv": _normal(ks[2], (d, d), std, dt),
        "wo": _normal(ks[3], (d, d), out_std, dt),
        "mlp_norm": nn.rmsnorm_init(d, dt),
        "w_gate": _normal(ks[4], (d, f), std, dt),
        "w_up": _normal(ks[5], (d, f), std, dt),
        "w_down": _normal(ks[6], (f, d), out_std, dt),
    }


def init_llama(key, cfg: LlamaConfig) -> dict:
    """Full model parameters.

    Structure: {"embed": [V, D], "blocks": pytree with leading [L] axis,
    "final_norm": ..., "lm_head": [D, V]} — the leading block axis is what
    `split_stages` slices for pipeline parallelism.
    """
    dt = jnp.dtype(cfg.param_dtype)
    k_embed, k_blocks, k_head = jax.random.split(key, 3)
    block_keys = jax.random.split(k_blocks, cfg.n_layers)
    blocks = jax.vmap(lambda k: init_block(k, cfg))(block_keys)
    embed = _normal(k_embed, (cfg.vocab_size, cfg.dmodel), 0.02, dt)
    if cfg.padding_idx is not None:
        embed = embed.at[cfg.padding_idx].set(0.0)
    return {
        "embed": embed,
        "blocks": blocks,
        "final_norm": nn.rmsnorm_init(cfg.dmodel, dt),
        "lm_head": _normal(k_head, (cfg.dmodel, cfg.vocab_size), 0.02, dt),
    }


def param_count(params) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(params))


# ------------------------------------------------------------------ RoPE

def rope_angles(positions: jnp.ndarray, head_dim: int, theta: float) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for rotary embeddings. positions: [T] (absolute), so
    sequence-parallel shards pass their global offsets and stay correct."""
    half = head_dim // 2
    inv_freq = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    ang = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]   # [T, half]
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """x: [B, T, H, Dh]; rotate pairs (x1, x2) = (x[..., :half], x[..., half:])."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[None, :, None, :].astype(x.dtype)
    s = sin[None, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)


# ------------------------------------------------------------------ attention

def qkv_proj(block: dict, x: jnp.ndarray, head_dim: int
             ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Fused QKV projection: x [B, T, D] → q, k, v [B, T, H_local, Dh].

    One [D, 3·D_local] matmul instead of three: at dmodel 288 each separate
    projection's 288-wide output pads to a 384-wide MXU tile (25% waste);
    fused, 3·288=864 pads to 896 (~4%). The concat copies ~1 MB of weights
    per step — noise next to the matmul. Param tree unchanged, so TP sharding
    (column-sharded wq/wk/wv concat along the sharded axis), checkpoints and
    stage splitting are unaffected. The decode path (models.generate)
    performs the same split on weights pre-fused once per generate() call —
    its per-position agreement with this path is asserted in
    tests/test_generate.py.
    """
    b, t, _ = x.shape
    dl = block["wq"].shape[1]                        # = dmodel / tp_size
    h_local = dl // head_dim                         # = num_heads / tp_size
    w_qkv = jnp.concatenate(
        [block["wq"], block["wk"], block["wv"]], axis=1).astype(x.dtype)
    qkv = x @ w_qkv
    q = qkv[..., :dl].reshape(b, t, h_local, head_dim)
    k = qkv[..., dl:2 * dl].reshape(b, t, h_local, head_dim)
    v = qkv[..., 2 * dl:].reshape(b, t, h_local, head_dim)
    return q, k, v


def _xla_attention(q, k, v, *, causal: bool, q_offset: int = 0,
                   softmax_dtype: str = "float32") -> jnp.ndarray:
    """[B, T, H, Dh] attention. q_offset shifts the causal mask for
    sequence-parallel query shards.

    Heads are folded into the batch dimension and the two O(T²) contractions
    are explicit batched dot_generals in [B·H, T, Dh] layout — identical math
    to the einsum formulation but measurably faster on TPU at small head_dim
    (the einsum path's backward introduces extra layout transposes; at the
    bench config this halves attention fwd+bwd time, experiments/attn_bench).

    ``softmax_dtype="bfloat16"`` (opt-in via LlamaConfig) materializes the
    [B·H, T, T] score tensor in bf16 — halving the dominant HBM tensor of
    the attention leg (measured ~9% on standalone attention fwd+bwd at the
    bench config) — while the softmax max/sum still accumulate in fp32.
    Off by default: the ~1e-2 drift is outside the PP/SP equivalence-test
    tolerances.
    """
    b, tq, h, dh = q.shape
    tk = k.shape[1]
    scale = 1.0 / math.sqrt(dh)
    st = jnp.dtype(softmax_dtype)
    qm = q.transpose(0, 2, 1, 3).reshape(b * h, tq, dh)
    km = k.transpose(0, 2, 1, 3).reshape(b * h, tk, dh)
    vm = v.transpose(0, 2, 1, 3).reshape(b * h, tk, dh)
    scores = lax.dot_general(qm, km, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=st) * jnp.asarray(scale, st)
    if causal:
        qpos = jnp.arange(tq)[:, None] + q_offset
        kpos = jnp.arange(tk)[None, :]
        scores = jnp.where(qpos >= kpos, scores, -jnp.inf)
    if st == jnp.float32:
        probs = jax.nn.softmax(scores, axis=-1)
    else:
        # bf16 scores; subtract the fp32 row max, then divide in fp32 (the
        # upcast/divide/downcast fuses into one elementwise kernel, so no
        # fp32 [T, T] tensor ever hits HBM) — only the stored [T, T]-sized
        # tensors stay bf16.
        m = jnp.max(scores.astype(jnp.float32), axis=-1, keepdims=True)
        e = jnp.exp(scores - m.astype(st)).astype(jnp.float32)
        probs = e / jnp.sum(e, axis=-1, keepdims=True)
    probs = probs.astype(q.dtype)
    out = lax.dot_general(probs, vm, (((2,), (1,)), ((0,), (0,))))
    return out.reshape(b, h, tq, dh).transpose(0, 2, 1, 3)


def attention(block: dict, x: jnp.ndarray, cfg: LlamaConfig,
              cos: jnp.ndarray, sin: jnp.ndarray,
              attn_fn: Optional[Callable] = None,
              tp_axis: Optional[str] = None) -> jnp.ndarray:
    """``attn_fn(q, k, v) -> out`` (all [B, T, H, Dh]) overrides the attention
    inner — the hook sequence parallelism uses to swap in ring attention.

    ``tp_axis`` enables Megatron-style tensor parallelism under shard_map:
    wq/wk/wv are column-sharded (local heads), wo row-sharded, and the output
    projection's partial sum is psum-ed over the axis. Head count is inferred
    from the local weight shapes, so the same code runs sharded or full.
    """
    b, t, d = x.shape
    dh = cfg.head_dim
    q, k, v = qkv_proj(block, x, dh)
    h_local = q.shape[2]                             # = num_heads / tp_size
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    use_pallas = cfg.attention_impl == "pallas" or (
        cfg.attention_impl == "auto"
        and t >= cfg.flash_min_seq
        and jax.default_backend() == "tpu")
    if attn_fn is not None:
        out = attn_fn(q, k, v)
    elif use_pallas:
        from ..ops.flash_attention import flash_attention
        blk = min(t, cfg.flash_block)
        out = flash_attention(q, k, v, causal=True,
                              dh_major=cfg.flash_dh_major,
                              block_q=blk, block_k=blk)
    else:
        out = _xla_attention(q, k, v, causal=True,
                             softmax_dtype=cfg.softmax_dtype)
    y = out.reshape(b, t, h_local * dh) @ block["wo"].astype(x.dtype)
    if tp_axis is not None:
        y = lax.psum(y, tp_axis)                     # combine head groups
    return y


def mlp(block: dict, x: jnp.ndarray,
        tp_axis: Optional[str] = None) -> jnp.ndarray:
    """SwiGLU MLP. With ``tp_axis``: w_gate/w_up column-sharded (local ffn
    slice), w_down row-sharded, partial output psum-ed over the axis."""
    f = block["w_gate"].shape[1]                     # = ffn_dim / tp_size
    w_gu = jnp.concatenate(
        [block["w_gate"], block["w_up"]], axis=1).astype(x.dtype)
    gu = x @ w_gu                                    # fused gate+up matmul
    y = (jax.nn.silu(gu[..., :f]) * gu[..., f:]) @ block["w_down"].astype(x.dtype)
    if tp_axis is not None:
        y = lax.psum(y, tp_axis)
    return y


def block_apply(block: dict, x: jnp.ndarray, cfg: LlamaConfig,
                cos: jnp.ndarray, sin: jnp.ndarray,
                attn_fn: Optional[Callable] = None,
                tp_axis: Optional[str] = None) -> jnp.ndarray:
    x = x + attention(block, nn.rmsnorm(block["attn_norm"], x, eps=cfg.norm_eps),
                      cfg, cos, sin, attn_fn, tp_axis)
    x = x + mlp(block, nn.rmsnorm(block["mlp_norm"], x, eps=cfg.norm_eps), tp_axis)
    return x


# ------------------------------------------------------------------ stages
# These four functions are the framework's equivalent of simplellm's
# LLamaFirstStage.embed / LLamaStage / LLamaLastStage surface
# (reference: intro_PP_1F1B.py:29-39,53).

def embed(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig) -> jnp.ndarray:
    """tokens [B, T] -> activations [B, T, D] in the compute dtype.

    With ``padding_idx`` set, pad positions produce zero vectors AND the pad
    row receives no gradient (torch Embedding(padding_idx) semantics — the
    masked output cuts the backward path to that row).
    """
    h = params["embed"][tokens]
    if cfg.padding_idx is not None:
        h = jnp.where((tokens == cfg.padding_idx)[..., None], 0.0, h)
    return h.astype(jnp.dtype(cfg.dtype))


def blocks_apply(blocks: dict, h: jnp.ndarray, cfg: LlamaConfig,
                 positions: Optional[jnp.ndarray] = None,
                 attn_fn: Optional[Callable] = None,
                 tp_axis: Optional[str] = None) -> jnp.ndarray:
    """Apply a stack of blocks (leading [L] axis) via one lax.scan."""
    t = h.shape[1]
    if positions is None:
        positions = jnp.arange(t)
    cos, sin = rope_angles(positions, cfg.head_dim, cfg.rope_theta)

    def apply_one(block, carry, cos, sin):
        # cfg/attn_fn captured by closure: cfg is static config, attn_fn may
        # close over collective primitives that must trace fresh per call.
        return block_apply(block, carry, cfg, cos, sin, attn_fn, tp_axis)

    fn = jax.checkpoint(apply_one) if cfg.remat else apply_one

    def body(carry, block):
        return fn(block, carry, cos, sin), None

    out, _ = lax.scan(body, h, blocks)
    return out


def head(params: dict, h: jnp.ndarray, cfg: LlamaConfig) -> jnp.ndarray:
    """activations [B, T, D] -> logits [B, T, V] (fp32 for a stable loss)."""
    h = nn.rmsnorm(params["final_norm"], h, eps=cfg.norm_eps)
    return (h @ params["lm_head"].astype(h.dtype)).astype(jnp.float32)


def forward(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
            positions: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Full causal LM: tokens [B, T] -> logits [B, T, V]."""
    h = embed(params, tokens, cfg)
    h = blocks_apply(params["blocks"], h, cfg, positions)
    return head(params, h, cfg)


def head_loss(params: dict, h: jnp.ndarray, tokens: jnp.ndarray,
              cfg: LlamaConfig, chunk_size: int = 512) -> jnp.ndarray:
    """Fused final-norm + lm_head + next-token cross-entropy.

    Mathematically ``causal_lm_loss(head(params, h, cfg), tokens)`` but the
    [B, T, V] logits are never materialized in HBM — see
    ops.losses.fused_linear_cross_entropy. At the canonical config the
    unfused fp32 logits are the single largest HBM tensor of the train step.
    """
    from ..ops.losses import fused_linear_cross_entropy
    h = nn.rmsnorm(params["final_norm"], h, eps=cfg.norm_eps)
    shift_h = h[:, :-1, :].reshape(-1, h.shape[-1])
    labels = tokens[:, 1:].reshape(-1)
    return fused_linear_cross_entropy(shift_h, params["lm_head"], labels,
                                      chunk_size=chunk_size)


def forward_loss(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
                 positions: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Full causal-LM training loss with the fused head (no [B,T,V] logits)."""
    h = embed(params, tokens, cfg)
    h = blocks_apply(params["blocks"], h, cfg, positions)
    return head_loss(params, h, tokens, cfg)


# ------------------------------------------------------------------ pipeline splitting

def split_stages(params: dict, n_stages: int) -> list:
    """Slice the stacked block axis into ``n_stages`` contiguous stage params.

    Stage 0 carries the embedding, the last stage carries final_norm+lm_head —
    mirroring the First/Stage/Last decomposition of the reference's pipeline
    (intro_PP_1F1B.py:29-39) as pure array slicing.
    """
    n_layers = jax.tree.leaves(params["blocks"])[0].shape[0]
    assert n_layers % n_stages == 0, (n_layers, n_stages)
    per = n_layers // n_stages
    stages = []
    for s in range(n_stages):
        stage = {"blocks": jax.tree.map(lambda x: x[s * per:(s + 1) * per], params["blocks"])}
        if s == 0:
            stage["embed"] = params["embed"]
        if s == n_stages - 1:
            stage["final_norm"] = params["final_norm"]
            stage["lm_head"] = params["lm_head"]
        stages.append(stage)
    return stages


def merge_stages(stages: list) -> dict:
    """Inverse of split_stages."""
    blocks = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0),
                          *[s["blocks"] for s in stages])
    return {
        "embed": stages[0]["embed"],
        "blocks": blocks,
        "final_norm": stages[-1]["final_norm"],
        "lm_head": stages[-1]["lm_head"],
    }


def stage_apply(stage: dict, x: jnp.ndarray, cfg: LlamaConfig, *,
                is_first: bool, is_last: bool,
                positions: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Run one pipeline stage: embeds if first, heads if last.

    x is tokens [B, T] for the first stage, activations [B, T, D] otherwise.
    """
    h = embed(stage, x, cfg) if is_first else x
    h = blocks_apply(stage["blocks"], h, cfg, positions)
    return head(stage, h, cfg) if is_last else h
