from . import generate, llama, mnist_cnn, tabular, vae, vfl_nets  # noqa: F401
