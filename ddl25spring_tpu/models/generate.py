"""Autoregressive decoding for the tiny-Llama: KV cache + sampling.

Parity-plus: the reference's training stack (simplellm surface, SURVEY.md
§2.9) never decodes — but a framework a reference user can *switch to* needs
inference. TPU-native shape of the problem:

- The KV cache is a pair of static-shape ``[L, B, max_len, H, Dh]`` arrays
  (stacked-layer layout, matching the model's scanned ``[L, ...]`` blocks).
  Static shapes mean one compile for prefill and one for the decode step —
  no per-length recompilation; position is a traced scalar.
- The whole generation loop is a single ``lax.scan`` over decode steps —
  one compiled program per (batch, prompt_len, max_new) shape, sampling
  included; nothing returns to Python between tokens.
- Cache updates are ``lax.dynamic_update_slice`` writes; with the step jitted
  and the cache donated, XLA performs them in place.
- Decode attention masks by absolute position (``kpos <= pos``), so the
  cache's unwritten tail is unread garbage, not a correctness hazard.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..config import LlamaConfig
from .. import nn
from . import llama


def init_cache(cfg: LlamaConfig, batch: int, max_len: int,
               kv_dtype: Optional[str] = None) -> dict:
    """Zeroed KV cache: {"k","v"} each [L, B, max_len, H, Dh]. ``max_len``
    bounds prompt + generated tokens. ``kv_dtype`` overrides the storage
    dtype (default: the compute dtype): serving decode re-reads the whole
    cache every step, so bf16 storage halves the per-step KV traffic — the
    dominant HBM stream once the batch amortizes the weights (see
    experiments/ROOFLINE.md, decode section). K is stored post-RoPE and
    attention runs fp32 softmax either way; the only precision change is
    the rounding of cached K/V."""
    dt = jnp.dtype(kv_dtype or cfg.dtype)
    shape = (cfg.n_layers, batch, max_len, cfg.num_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def _attend_cached(q: jnp.ndarray, ck: jnp.ndarray, cv: jnp.ndarray,
                   q_positions: jnp.ndarray) -> jnp.ndarray:
    """Attention of q [B, Tq, H, Dh] over the full cache [B, Tmax, H, Dh],
    masked to ``kpos <= q_position`` per query row. fp32 softmax, heads
    folded into batch (the same layout as llama._xla_attention)."""
    b, tq, h, dh = q.shape
    tmax = ck.shape[1]
    scale = 1.0 / math.sqrt(dh)
    qm = q.transpose(0, 2, 1, 3).reshape(b * h, tq, dh)
    # Casts after the transpose/reshape fuse into the dots: the HBM read is
    # of the cache's storage dtype (bf16 when kv_dtype narrows it).
    km = ck.transpose(0, 2, 1, 3).reshape(b * h, tmax, dh).astype(q.dtype)
    vm = cv.transpose(0, 2, 1, 3).reshape(b * h, tmax, dh).astype(q.dtype)
    scores = lax.dot_general(qm, km, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32) * scale
    mask = q_positions[:, None] >= jnp.arange(tmax)[None, :]   # [Tq, Tmax]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = lax.dot_general(probs, vm, (((2,), (1,)), ((0,), (0,))))
    return out.reshape(b, h, tq, dh).transpose(0, 2, 1, 3)


def _fuse_blocks(blocks: dict) -> dict:
    """Pre-concatenate each layer's QKV and gate/up weights (leading [L] axis
    preserved). Training fuses these per call — fine there, the concat is
    noise next to a [B·T, D] matmul — but the decode loop runs matVECs, which
    are weight-bandwidth-bound: a per-token concat would read and re-write
    every weight byte it is about to stream, doubling traffic. Fusing once
    per generate() call keeps the hot loop at one read per weight byte."""
    return {
        "attn_norm": blocks["attn_norm"],
        "mlp_norm": blocks["mlp_norm"],
        "w_qkv": jnp.concatenate([blocks["wq"], blocks["wk"], blocks["wv"]],
                                 axis=-1),
        "wo": blocks["wo"],
        "w_gu": jnp.concatenate([blocks["w_gate"], blocks["w_up"]], axis=-1),
        "w_down": blocks["w_down"],
    }


def _block_with_cache(block: dict, ck: jnp.ndarray, cv: jnp.ndarray,
                      x: jnp.ndarray, positions: jnp.ndarray, start: jnp.ndarray,
                      cfg: LlamaConfig) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One pre-fused block over x [B, T, D] at absolute ``positions`` [T],
    writing this call's K/V into the cache at offset ``start`` and attending
    over the whole cache. Serves both prefill (T = prompt length, start = 0)
    and decode (T = 1, start = pos). Same math as llama.block_apply —
    asserted against llama.forward position-by-position in
    tests/test_generate.py."""
    b, t, d = x.shape
    dh = cfg.head_dim
    xn = nn.rmsnorm(block["attn_norm"], x, eps=cfg.norm_eps)
    qkv = xn @ block["w_qkv"].astype(x.dtype)
    dl = qkv.shape[-1] // 3
    h_local = dl // dh
    q = qkv[..., :dl].reshape(b, t, h_local, dh)
    k = qkv[..., dl:2 * dl].reshape(b, t, h_local, dh)
    v = qkv[..., 2 * dl:].reshape(b, t, h_local, dh)
    cos, sin = llama.rope_angles(positions, dh, cfg.rope_theta)
    q = llama.apply_rope(q, cos, sin)
    k = llama.apply_rope(k, cos, sin)          # cached K is stored post-RoPE
    ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, start, 0, 0))
    cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, start, 0, 0))
    out = _attend_cached(q, ck, cv, positions)
    x = x + out.reshape(b, t, h_local * dh) @ block["wo"].astype(x.dtype)
    xn = nn.rmsnorm(block["mlp_norm"], x, eps=cfg.norm_eps)
    gu = xn @ block["w_gu"].astype(x.dtype)
    f = gu.shape[-1] // 2
    x = x + (jax.nn.silu(gu[..., :f]) * gu[..., f:]) @ block["w_down"].astype(x.dtype)
    return x, ck, cv


def _forward_fused(params: dict, fused_blocks: dict, tokens: jnp.ndarray,
                   cache: dict, start, cfg: LlamaConfig
                   ) -> Tuple[jnp.ndarray, dict]:
    """Body of forward_cached, taking blocks already through _fuse_blocks."""
    t = tokens.shape[1]
    start = jnp.asarray(start, jnp.int32)
    positions = start + jnp.arange(t)
    h = llama.embed(params, tokens, cfg)

    def body(carry, layer):
        block, ck, cv = layer
        out, ck, cv = _block_with_cache(block, ck, cv, carry, positions,
                                        start, cfg)
        return out, (ck, cv)

    h, (ck, cv) = lax.scan(body, h, (fused_blocks, cache["k"], cache["v"]))
    logits = llama.head(params, h[:, -1:, :], cfg)[:, 0, :]
    return logits, {"k": ck, "v": cv}


def forward_cached(params: dict, tokens: jnp.ndarray, cache: dict,
                   start, cfg: LlamaConfig
                   ) -> Tuple[jnp.ndarray, dict]:
    """tokens [B, T] at absolute positions start..start+T → (logits of the
    LAST position [B, V] fp32, updated cache). One lax.scan over the stacked
    blocks, threading each layer's cache slice through the scanned axis."""
    return _forward_fused(params, _fuse_blocks(params["blocks"]), tokens,
                          cache, start, cfg)


def filter_logits(logits: jnp.ndarray, top_k: Optional[int],
                  top_p: Optional[float]) -> jnp.ndarray:
    """Apply the top_k / top_p (nucleus) filters to temperature-scaled
    logits [B, V]. The filters compose: k-truncation first, then the
    smallest prefix of the remaining distribution whose mass reaches p.

    The ONE implementation of the filter contract: the serving engine's
    per-slot sampler (serving/engine.py) calls this too, and its
    bitwise-parity bar means the two paths must stay the same ops — keep
    any change here."""
    if top_k is not None:
        kth = lax.top_k(logits, top_k)[0][..., -1:]    # [B, 1]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None:
        # Static-shape nucleus filter: one descending sort + cumsum, then a
        # per-row logit threshold — no gather/scatter back through sort
        # indices. A token is kept iff the mass of strictly-better tokens is
        # < p (so the top token always survives, and the boundary token that
        # crosses p is included, matching the usual nucleus definition).
        sorted_logits = -jnp.sort(-logits, axis=-1)            # descending
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        mass_before = jnp.cumsum(probs, axis=-1) - probs       # exclusive
        kept = mass_before < top_p                             # [B, V]
        thresh = jnp.min(jnp.where(kept, sorted_logits, jnp.inf),
                         axis=-1, keepdims=True)               # [B, 1]
        logits = jnp.where(logits < thresh, -jnp.inf, logits)
    return logits


def _sample(key, logits: jnp.ndarray, temperature: float,
            top_k: Optional[int], top_p: Optional[float]) -> jnp.ndarray:
    """logits [B, V] → token ids [B]. temperature 0 = greedy (argmax)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = filter_logits(logits / temperature, top_k, top_p)
    return jax.random.categorical(key, logits, axis=-1)


@partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "temperature",
                                   "top_k", "top_p", "max_len", "kv_dtype"))
def generate(params: dict, prompt: jnp.ndarray, cfg: LlamaConfig,
             max_new_tokens: int, *, key: Optional[jax.Array] = None,
             temperature: float = 0.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             max_len: Optional[int] = None,
             kv_dtype: Optional[str] = None) -> jnp.ndarray:
    """prompt [B, Tp] → generated ids [B, max_new_tokens].

    One compiled program: prefill over the prompt, then a lax.scan of
    single-token decode steps with in-place cache writes. Greedy by default;
    ``temperature``/``top_k``/``top_p`` enable sampling (``key`` required
    then). ``kv_dtype`` narrows the cache storage dtype (init_cache).
    """
    b, tp = prompt.shape
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
    assert top_p is None or 0.0 < top_p <= 1.0, \
        f"top_p must be in (0, 1], got {top_p}"  # p<=0 would mask every token
    if max_len is None:
        max_len = tp + max_new_tokens
    if max_len < tp + max_new_tokens:
        # Hard error, not an assert: an oversized request would silently
        # write K/V past the masked range (dynamic_update_slice clamps the
        # start index, so late positions OVERWRITE earlier cache entries)
        # and the tail tokens would be garbage — and `python -O` would
        # strip an assert entirely. Raised at trace time, so it fires on
        # the first call of each shape, jit or not.
        raise ValueError(
            f"prompt_len + max_new_tokens = {tp} + {max_new_tokens} = "
            f"{tp + max_new_tokens} exceeds max_len={max_len}: the KV cache "
            f"only holds max_len positions, so the request cannot fit — "
            f"raise max_len or shorten the request")
    if key is None:
        assert temperature == 0.0, "sampling (temperature>0) requires a key"
        key = jax.random.PRNGKey(0)   # unused by greedy argmax
    cache = init_cache(cfg, b, max_len, kv_dtype)
    fused = _fuse_blocks(params["blocks"])   # once, hoisted out of the scan
    logits, cache = _forward_fused(params, fused, prompt, cache, 0, cfg)
    key, sub = jax.random.split(key)
    first = _sample(sub, logits, temperature, top_k, top_p)

    def step(carry, _):
        cache, tok, pos, key = carry
        logits, cache = _forward_fused(params, fused, tok[:, None], cache,
                                       pos, cfg)
        key, sub = jax.random.split(key)
        nxt = _sample(sub, logits, temperature, top_k, top_p)
        return (cache, nxt, pos + 1, key), nxt

    carry = (cache, first, jnp.asarray(tp, jnp.int32), key)
    _, rest = lax.scan(step, carry, None, length=max_new_tokens - 1)
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def speculative_stream(params: dict, draft_params: dict,
                       prompt, cfg: LlamaConfig, max_new_tokens: int, *,
                       k: int, draft_cfg: Optional[LlamaConfig] = None):
    """REFERENCE greedy speculative decoding — the parity twin of the
    serving engine's draft-propose / verify round (serving/speculate.py),
    written as the obviously-correct O(T²) re-forward loop (the same
    style as tests/test_generate.py's greedy reference): the draft
    proposes ``k`` tokens by argmax over its own full forward, the target
    scores the whole window in one forward, and the accepted prefix plus
    one correction/bonus token extends the stream.

    Greedy speculative decoding emits EXACTLY the greedy stream — every
    accepted token is re-derived as the target's own argmax and so is the
    token beyond the accepted prefix — so the returned tokens equal
    ``generate(params, prompt, cfg, max_new_tokens)``'s bitwise at any
    ``k`` and any draft (pinned in tests/test_generate.py). Returns
    ``(tokens, stats)`` with ``stats`` counting proposed/accepted draft
    tokens and target rounds — the acceptance-rate accounting the
    engine's schema-v7 ``speculate`` events report per dispatch.

    Deliberately NOT a production path (each round re-runs full forwards;
    one compile per sequence length): it exists so the engine's
    one-dispatch verify program has an independent, hand-checkable
    reference for both the emitted stream and the acceptance counts."""
    dcfg = draft_cfg or cfg
    if k < 1 or max_new_tokens < 1:
        raise ValueError(f"k={k}, max_new_tokens={max_new_tokens}")
    seq = jnp.asarray(prompt, jnp.int32).reshape(1, -1)
    out = []
    stats = {"proposed": 0, "accepted": 0, "rounds": 0, "k": k}
    while len(out) < max_new_tokens:
        d_seq = seq
        drafts = []
        for _ in range(k):
            d_log = llama.forward(draft_params, d_seq, dcfg)[:, -1, :]
            d_tok = jnp.argmax(d_log, axis=-1)
            drafts.append(int(d_tok[0]))
            d_seq = jnp.concatenate([d_seq, d_tok[:, None]], axis=1)
        window = jnp.concatenate(
            [seq, jnp.asarray(drafts, jnp.int32)[None, :]], axis=1)
        t_log = llama.forward(params, window, cfg)[0]          # [T, V]
        base = seq.shape[1] - 1
        targets = [int(jnp.argmax(t_log[base + i])) for i in range(k + 1)]
        a = 0
        while a < k and targets[a] == drafts[a]:
            a += 1
        remaining = max_new_tokens - len(out)
        emit = targets[:a + 1][:remaining]
        # Horizon truncation never reads as rejection: proposals past
        # max_new could never be emitted, so — the engine's schema-v7
        # rule — only min(k, remaining) count as proposed (a same-weights
        # draft stays at acceptance exactly 1 at any max_new).
        stats["proposed"] += min(k, remaining)
        stats["accepted"] += min(a, len(emit))
        stats["rounds"] += 1
        out.extend(emit)
        seq = jnp.concatenate(
            [seq, jnp.asarray(emit, jnp.int32)[None, :]], axis=1)
    return out, stats
