"""Tabular VAE for synthetic-data generation.

Capability target: the reference's BatchNorm-MLP `Autoencoder` with
encode/reparameterize/decode, the MSE+KLD `customLoss`, and `sample()` from
N(0, I) (lab/tutorial_2a/generative-modeling.py:13-128), plus the
synthetic-data evaluation protocol (train an evaluator on real vs synthetic,
compare test accuracy — generative-modeling.py:165-209).

Functional design: params + explicit BatchNorm running-state pytrees; the
reparameterization trick takes a jax PRNG key. All pure — jit/vmap friendly.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .. import nn
from ..config import VAEConfig


def init(key, cfg: VAEConfig) -> Tuple[dict, dict]:
    """Returns (params, state) — state holds BatchNorm running stats."""
    dims = [cfg.input_dim, *cfg.hidden_dims]
    keys = jax.random.split(key, 2 * len(cfg.hidden_dims) + 4)
    ki = iter(keys)
    params, state = {"enc": [], "dec": []}, {"enc": [], "dec": []}
    for i in range(len(dims) - 1):
        bn_p, bn_s = nn.batchnorm_init(dims[i + 1])
        params["enc"].append({"lin": nn.dense_init(next(ki), dims[i], dims[i + 1]), "bn": bn_p})
        state["enc"].append(bn_s)
    params["mu"] = nn.dense_init(next(ki), dims[-1], cfg.latent_dim)
    params["logvar"] = nn.dense_init(next(ki), dims[-1], cfg.latent_dim)
    rdims = [cfg.latent_dim, *reversed(cfg.hidden_dims)]
    for i in range(len(rdims) - 1):
        bn_p, bn_s = nn.batchnorm_init(rdims[i + 1])
        params["dec"].append({"lin": nn.dense_init(next(ki), rdims[i], rdims[i + 1]), "bn": bn_p})
        state["dec"].append(bn_s)
    params["out"] = nn.dense_init(next(ki), rdims[-1], cfg.input_dim)
    return params, state


def _stack(layers, states, x, *, train):
    new_states = []
    for layer, st in zip(layers, states):
        x = nn.dense(layer["lin"], x)
        x, st2 = nn.batchnorm(layer["bn"], st, x, train=train)
        x = nn.relu(x)
        new_states.append(st2)
    return x, new_states


def encode(params, state, x, *, train: bool):
    h, enc_state = _stack(params["enc"], state["enc"], x, train=train)
    mu = nn.dense(params["mu"], h)
    logvar = nn.dense(params["logvar"], h)
    return mu, logvar, {**state, "enc": enc_state}


def reparameterize(key, mu, logvar):
    std = jnp.exp(0.5 * logvar)
    return mu + std * jax.random.normal(key, mu.shape, mu.dtype)


def kl_divergence(mu, logvar) -> jnp.ndarray:
    """Summed KL(q(z|x) || N(0, I)) — shared by the VAE and VFL-VAE losses."""
    return -0.5 * jnp.sum(1 + logvar - jnp.square(mu) - jnp.exp(logvar))


def decode(params, state, z, *, train: bool):
    h, dec_state = _stack(params["dec"], state["dec"], z, train=train)
    return nn.dense(params["out"], h), {**state, "dec": dec_state}


def apply(params, state, x, key, *, train: bool):
    """Full VAE pass: returns (recon, mu, logvar, new_state)."""
    mu, logvar, state = encode(params, state, x, train=train)
    z = reparameterize(key, mu, logvar) if train else mu
    recon, state = decode(params, state, z, train=train)
    return recon, mu, logvar, state


def loss_fn(recon, x, mu, logvar) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """MSE(sum) + KLD, the reference's `customLoss`
    (generative-modeling.py:119-128). Returns (total, mse, kld)."""
    mse = jnp.sum(jnp.square(recon - x))
    kld = kl_divergence(mu, logvar)
    return mse + kld, mse, kld


def sample(key, params, state, n: int, latent_dim: int):
    """Draw n synthetic rows by decoding z ~ N(0, I) in eval mode
    (generative-modeling.py sample())."""
    z = jax.random.normal(key, (n, latent_dim))
    out, _ = decode(params, state, z, train=False)
    return out
