"""Vertical-FL model stack: per-party bottom models + server top model.

Capability target: the reference's `BottomModel` (per-client MLP over that
client's feature slice), `TopModel` (classifier over concatenated bottom
outputs), and `VFLNetwork` (lab/tutorial_2b/vfl.py:11-102), plus the VFL-VAE
hybrid of hw2 ex3: client encoders -> concat(mu) -> server VAE -> split
synthetic latents -> client decoders, loss = Σ per-client MSE + KL/batch
(lab/hw02/Tea_Pula_HW2.ipynb cells 32-40).

The cut layer is explicit: `bottoms_forward` returns the per-party
activations (what would cross the wire up), and the server side consumes only
the concatenation — so per-party isolation is enforceable and testable.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import nn
from .vae import kl_divergence, reparameterize

NUM_CLASSES = 2


# ------------------------------------------------------- discriminative VFL

def init_bottom(key, in_dim: int, out_dim: int = 2, hidden: int = 16) -> list:
    return nn.mlp_init(key, [in_dim, hidden, out_dim])


def init_top(key, in_dim: int, hidden: int = 16, num_classes: int = NUM_CLASSES) -> list:
    return nn.mlp_init(key, [in_dim, hidden, num_classes])


def init_vfl(key, feature_dims: Sequence[int], *, bottom_out: int = 2) -> dict:
    """One bottom model per party (sized to its feature slice) + the top."""
    keys = jax.random.split(key, len(feature_dims) + 1)
    bottoms = [init_bottom(keys[i], d, bottom_out) for i, d in enumerate(feature_dims)]
    top = init_top(keys[-1], bottom_out * len(feature_dims))
    return {"bottoms": bottoms, "top": top}


def bottoms_forward(params: dict, xs: Sequence[jnp.ndarray]) -> List[jnp.ndarray]:
    """Per-party forward — the activations that cross the cut layer."""
    return [nn.mlp(b, x, final_activation=nn.relu) for b, x in zip(params["bottoms"], xs)]


def vfl_forward(params: dict, xs: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Full split-NN forward: concat bottom outputs at the server, classify
    (reference: vfl.py:87-89)."""
    cut = jnp.concatenate(bottoms_forward(params, xs), axis=1)
    return nn.mlp(params["top"], cut)


# ------------------------------------------------------- VFL-VAE hybrid

def init_vfl_vae(key, feature_dims: Sequence[int], *, client_latent: int = 4,
                 server_latent: int = 8, enc_hidden: int = 16) -> dict:
    """hw2 ex3 stack: per-client encoder/decoder + server VAE over the
    concatenated client mus."""
    n = len(feature_dims)
    keys = jax.random.split(key, 2 * n + 2)
    encoders = [nn.mlp_init(keys[i], [feature_dims[i], enc_hidden, client_latent]) for i in range(n)]
    decoders = [nn.mlp_init(keys[n + i], [client_latent, enc_hidden, feature_dims[i]]) for i in range(n)]
    concat = client_latent * n
    k_mu, k_logvar = jax.random.split(keys[2 * n])
    server = {
        "mu": nn.dense_init(k_mu, concat, server_latent),
        "logvar": nn.dense_init(k_logvar, concat, server_latent),
        "dec": nn.mlp_init(keys[2 * n + 1], [server_latent, concat]),
    }
    return {"encoders": encoders, "decoders": decoders, "server": server,
            "client_latent": client_latent}


def vfl_vae_forward(params: dict, xs: Sequence[jnp.ndarray], key) -> Tuple[List[jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """Returns (per-client reconstructions, mu, logvar)."""
    client_lat = [nn.mlp(e, x, final_activation=nn.relu) for e, x in zip(params["encoders"], xs)]
    concat = jnp.concatenate(client_lat, axis=1)                      # the upward wire
    mu = nn.dense(params["server"]["mu"], concat)
    logvar = nn.dense(params["server"]["logvar"], concat)
    z = reparameterize(key, mu, logvar)
    synth = nn.mlp(params["server"]["dec"], z)                        # the downward wire
    lat = params["client_latent"]
    parts = [synth[:, i * lat:(i + 1) * lat] for i in range(len(xs))]  # split back per client
    recons = [nn.mlp(d, p) for d, p in zip(params["decoders"], parts)]
    return recons, mu, logvar


def vfl_vae_loss(recons: Sequence[jnp.ndarray], xs: Sequence[jnp.ndarray],
                 mu: jnp.ndarray, logvar: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Σ per-client mean-MSE + KL/batch (reference: Tea_Pula_HW2.ipynb cell 38
    compute_loss). Returns (total, recon_term, kl_term)."""
    recon = sum(jnp.mean(jnp.square(r - x)) for r, x in zip(recons, xs))
    kl = kl_divergence(mu, logvar) / mu.shape[0]
    return recon + kl, recon, kl
