"""Vertical-FL model stack: per-party bottom models + server top model.

Capability target: the reference's `BottomModel` (per-client MLP over that
client's feature slice), `TopModel` (classifier over concatenated bottom
outputs), and `VFLNetwork` (lab/tutorial_2b/vfl.py:11-102), plus the VFL-VAE
hybrid of hw2 ex3: client encoders -> concat(mu) -> server VAE -> split
synthetic latents -> client decoders, loss = Σ per-client MSE + KL/batch
(lab/hw02/Tea_Pula_HW2.ipynb cells 32-40).

The cut layer is explicit: `bottoms_forward` returns the per-party
activations (what would cross the wire up), and the server side consumes only
the concatenation — so per-party isolation is enforceable and testable.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp

from .. import nn
from .vae import kl_divergence, reparameterize

NUM_CLASSES = 2


# ------------------------------------------------------- discriminative VFL

DROPOUT = 0.1


def init_bottom(key, in_dim: int, out_dim: int) -> list:
    """Reference BottomModel (vfl.py:11-22): fc1 in→out, fc2 out→out, ReLU
    after each, dropout(0.1) on the output."""
    return nn.mlp_init(key, [in_dim, out_dim, out_dim])


def init_top(key, in_dim: int, num_classes: int = NUM_CLASSES) -> list:
    """Reference TopModel (vfl.py:25-40): concat→128→256→num_classes."""
    return nn.mlp_init(key, [in_dim, 128, 256, num_classes])


def init_vfl(key, feature_dims: Sequence[int], *, bottom_out_mult: int = 2) -> dict:
    """One bottom model per party (sized to its feature slice) + the top.

    Each party's bottom output width is ``bottom_out_mult · d_i`` — the
    reference's ``outs_per_client * len(in_feats)`` sizing (vfl.py:139-141),
    so parties with more features send wider activations up the cut.
    """
    keys = jax.random.split(key, len(feature_dims) + 1)
    bottoms = [init_bottom(keys[i], d, bottom_out_mult * d)
               for i, d in enumerate(feature_dims)]
    top = init_top(keys[-1], sum(bottom_out_mult * d for d in feature_dims))
    return {"bottoms": bottoms, "top": top}


def bottoms_forward(params: dict, xs: Sequence[jnp.ndarray], *,
                    key=None) -> List[jnp.ndarray]:
    """Per-party forward — the activations that cross the cut layer.
    Dropout(0.1) on each party's output iff a key is given (vfl.py:21-22)."""
    outs = []
    keys = (jax.random.split(key, len(xs)) if key is not None
            else [None] * len(xs))
    for b, x, k in zip(params["bottoms"], xs, keys):
        h = nn.mlp(b, x, activation=nn.relu, final_activation=nn.relu)
        if k is not None:
            h = nn.dropout(k, h, DROPOUT, train=True)
        outs.append(h)
    return outs


def top_forward(params: dict, cut: jnp.ndarray, *, key=None) -> jnp.ndarray:
    """Server-side classifier over the concatenated cut-layer activations.

    Faithful to the reference quirk (vfl.py:36-40): LeakyReLU is applied
    after EVERY layer including the output — the 'logits' the CE loss sees
    are LeakyReLU-activated — and train-mode dropout(0.1) lands on the
    output too. Reproduced because the published accuracy bands
    (84.8-85.3% @ 4 clients) were trained through it.
    """
    h = nn.mlp(params["top"], cut, activation=nn.leaky_relu,
               final_activation=nn.leaky_relu)
    if key is not None:
        h = nn.dropout(key, h, DROPOUT, train=True)
    return h


def vfl_forward(params: dict, xs: Sequence[jnp.ndarray], *,
                key=None) -> jnp.ndarray:
    """Full split-NN forward: concat bottom outputs at the server, classify
    (reference: vfl.py:87-89)."""
    if key is not None:
        kb, kt = jax.random.split(key)
    else:
        kb = kt = None
    cut = jnp.concatenate(bottoms_forward(params, xs, key=kb), axis=1)
    return top_forward(params, cut, key=kt)


# ------------------------------------------------------- VFL-VAE hybrid

def init_vfl_vae(key, feature_dims: Sequence[int], *, client_latent: int = 4,
                 server_latent: int = 8, enc_hidden: int = 16) -> dict:
    """hw2 ex3 stack: per-client encoder/decoder + server VAE over the
    concatenated client mus."""
    n = len(feature_dims)
    keys = jax.random.split(key, 2 * n + 2)
    encoders = [nn.mlp_init(keys[i], [feature_dims[i], enc_hidden, client_latent]) for i in range(n)]
    decoders = [nn.mlp_init(keys[n + i], [client_latent, enc_hidden, feature_dims[i]]) for i in range(n)]
    concat = client_latent * n
    k_mu, k_logvar = jax.random.split(keys[2 * n])
    server = {
        "mu": nn.dense_init(k_mu, concat, server_latent),
        "logvar": nn.dense_init(k_logvar, concat, server_latent),
        "dec": nn.mlp_init(keys[2 * n + 1], [server_latent, concat]),
    }
    return {"encoders": encoders, "decoders": decoders, "server": server,
            "client_latent": client_latent}


def vfl_vae_forward(params: dict, xs: Sequence[jnp.ndarray], key) -> Tuple[List[jnp.ndarray], jnp.ndarray, jnp.ndarray]:
    """Returns (per-client reconstructions, mu, logvar)."""
    client_lat = [nn.mlp(e, x, final_activation=nn.relu) for e, x in zip(params["encoders"], xs)]
    concat = jnp.concatenate(client_lat, axis=1)                      # the upward wire
    mu = nn.dense(params["server"]["mu"], concat)
    logvar = nn.dense(params["server"]["logvar"], concat)
    z = reparameterize(key, mu, logvar)
    synth = nn.mlp(params["server"]["dec"], z)                        # the downward wire
    lat = params["client_latent"]
    parts = [synth[:, i * lat:(i + 1) * lat] for i in range(len(xs))]  # split back per client
    recons = [nn.mlp(d, p) for d, p in zip(params["decoders"], parts)]
    return recons, mu, logvar


def vfl_vae_loss(recons: Sequence[jnp.ndarray], xs: Sequence[jnp.ndarray],
                 mu: jnp.ndarray, logvar: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Σ per-client mean-MSE + KL/batch (reference: Tea_Pula_HW2.ipynb cell 38
    compute_loss). Returns (total, recon_term, kl_term)."""
    recon = sum(jnp.mean(jnp.square(r - x)) for r, x in zip(recons, xs))
    kl = kl_divergence(mu, logvar) / mu.shape[0]
    return recon + kl, recon, kl
