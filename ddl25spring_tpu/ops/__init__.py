from .adam import fused_adam  # noqa: F401
from .losses import causal_lm_loss, cross_entropy_loss  # noqa: F401

# NOTE: the flash-attention kernel is deliberately NOT re-exported here —
# import it from ddl25spring_tpu.ops.flash_attention. A package-level
# re-export would either pull jax.experimental.pallas into every ops import
# or (with a lazy __getattr__) collide with the submodule of the same name.
