from .losses import causal_lm_loss, cross_entropy_loss  # noqa: F401
