from .losses import causal_lm_loss, cross_entropy_loss  # noqa: F401
from .flash_attention import flash_attention  # noqa: F401
