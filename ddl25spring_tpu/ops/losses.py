"""Loss functions.

`causal_lm_loss` is the framework's equivalent of simplellm's
``causalLLMLoss(logits, target_tokens, vocab_size)`` (reference:
lab/tutorial_1b/primer/intro.py:29): the shift is done *inside* the loss —
callers pass the same token batch they fed the model.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray,
                       mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Mean softmax cross-entropy. logits [..., C], integer labels [...]."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if mask is not None:
        mask = mask.astype(nll.dtype)
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return nll.mean()


def causal_lm_loss(logits: jnp.ndarray, tokens: jnp.ndarray, *,
                   ignore_index: Optional[int] = None) -> jnp.ndarray:
    """Next-token cross-entropy: logits [B, T, V] vs tokens [B, T], predicting
    tokens[:, 1:] from logits[:, :-1]."""
    shift_logits = logits[:, :-1]
    shift_labels = tokens[:, 1:]
    mask = None
    if ignore_index is not None:
        mask = (shift_labels != ignore_index)
    return cross_entropy_loss(shift_logits, shift_labels, mask)
