"""Loss functions.

`causal_lm_loss` is the framework's equivalent of simplellm's
``causalLLMLoss(logits, target_tokens, vocab_size)`` (reference:
lab/tutorial_1b/primer/intro.py:29): the shift is done *inside* the loss —
callers pass the same token batch they fed the model.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray,
                       mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Mean softmax cross-entropy. logits [..., C], integer labels [...]."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if mask is not None:
        mask = mask.astype(nll.dtype)
        return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return nll.mean()


def causal_lm_loss(logits: jnp.ndarray, tokens: jnp.ndarray, *,
                   ignore_index: Optional[int] = None) -> jnp.ndarray:
    """Next-token cross-entropy: logits [B, T, V] vs tokens [B, T], predicting
    tokens[:, 1:] from logits[:, :-1]."""
    shift_logits = logits[:, :-1]
    shift_labels = tokens[:, 1:]
    mask = None
    if ignore_index is not None:
        mask = (shift_labels != ignore_index)
    return cross_entropy_loss(shift_logits, shift_labels, mask)


def fused_linear_cross_entropy(h: jnp.ndarray, w: jnp.ndarray,
                               labels: jnp.ndarray,
                               mask: Optional[jnp.ndarray] = None,
                               chunk_size: int = 512) -> jnp.ndarray:
    """Mean cross-entropy of ``softmax(h @ w)`` vs ``labels`` WITHOUT ever
    materializing the full [N, V] logits.

    The unfused path (llama.head → causal_lm_loss) writes the fp32 logits to
    HBM — at the canonical bench config that is [8192, 32000]·4B ≈ 1 GB
    round-tripped per step, the dominant HBM cost of the whole model (the
    reference's causalLLMLoss has the same shape on CUDA,
    lab/tutorial_1b/primer/intro.py:29). Here a ``lax.scan`` over row chunks
    computes each [chunk, V] logit tile in fp32 *on-chip* (one MXU matmul +
    logsumexp), keeps only per-chunk scalar sums, and ``jax.checkpoint``
    makes the backward rematerialize the tile instead of saving it — peak
    logit memory drops from O(N·V) to O(chunk·V), and the only HBM traffic
    left is re-reading ``w`` per chunk.

    h: [N, D] activations (compute dtype, e.g. bf16 — the matmul accumulates
    fp32 via preferred_element_type); w: [D, V]; labels: int [N];
    mask: optional [N] validity weights. Returns mean NLL over valid rows.
    """
    n, d = h.shape
    if mask is None:
        mask = jnp.ones((n,), jnp.float32)
    mask = mask.astype(jnp.float32)
    n_chunks = max(1, -(-n // chunk_size))
    pad = n_chunks * chunk_size - n
    if pad:
        h = jnp.pad(h, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad))
        mask = jnp.pad(mask, (0, pad))
    h_c = h.reshape(n_chunks, -1, d)
    lab_c = labels.reshape(n_chunks, -1)
    mask_c = mask.reshape(n_chunks, -1)
    w_cast = w.astype(h.dtype)

    @jax.checkpoint
    def chunk_nll(hc, lc, mc):
        logits = jnp.dot(hc, w_cast, preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        lab_logit = jnp.take_along_axis(logits, lc[:, None], axis=-1)[:, 0]
        return ((lse - lab_logit) * mc).sum()

    def body(acc, xs):
        return acc + chunk_nll(*xs), None

    total, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32),
                            (h_c, lab_c, mask_c))
    return total / jnp.maximum(mask.sum(), 1.0)
