"""Single-pass fused Adam — an optax-compatible GradientTransformation.

Why: ``optax.adam`` composes scale_by_adam → scale(-lr), each stage a
separate tree_map producing materialized intermediates (updated moments,
bias-corrected copies, scaled updates). On a memory-bound optimizer step
that is several extra HBM round trips over the full parameter footprint.
Here the whole update rule is one jnp expression per leaf —

    m ← β1·m + (1−β1)·g
    v ← β2·v + (1−β2)·g²
    u = −lr · (m/(1−β1^t)) / (√(v/(1−β2^t)) + ε)

— so XLA fuses it into a single read of (g, m, v) and a single write of
(u, m, v) per leaf. Semantics match ``optax.adam(lr, b1, b2, eps)`` bitwise
up to float re-association (asserted ≤1e-6 in tests/test_core.py).

Drop-in: ``fused_adam(8e-4)`` anywhere an ``optax.GradientTransformation``
is accepted (dp/pp/ep steps, train.llm, bench.py).

ZeRO-1 note (parallel/dp.py): Adam is elementwise — the update at
coordinate i depends only on (g, m, v) at i — so applying it to a 1/N
slice of the flattened parameter vector commutes with slicing. That is
the property the sharded weight update relies on for exact equivalence
with the replicated update, and it holds for every transformation in this
module.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


def resize_zero_padded(vec, new_len: int):
    """Resize a ZeRO-1 padded flat vector (params / Adam mu / Adam nu slice
    stack) from its N-way padded length to an M-way padded length — the
    elementwise core of cross-topology optimizer-state resharding
    (resilience/elastic.py, checkpoint reshard-on-load).

    Valid because the pad region of every ZeRO-1 flat vector is EXACTLY
    zero, forever: the padded gradient tail is zero by construction
    (``jnp.pad`` in ``parallel/dp.py``), so mu/nu at pad coordinates stay
    ``b·0 + (1−b)·0 = 0`` and the padded param tail steps by
    ``−lr·(0/c1)/(√(0/c2)+ε) = 0`` under every elementwise rule in this
    module. Truncating the tail therefore loses nothing and extending it
    appends the zeros a larger pad would have carried — the resized vector
    is bit-identical to the one an M-way ``_zero1_setup`` would have built
    from the same unpadded content. A non-zero truncated tail means the
    vector is NOT a zero-padded slice stack (layout bug or corrupted
    state), and silently dropping real data would poison the run — hard
    error instead."""
    vec = np.asarray(vec)
    if vec.ndim != 1:
        raise ValueError(f"resize_zero_padded wants a flat vector, got "
                         f"shape {vec.shape}")
    if new_len == vec.shape[0]:
        return vec
    if new_len < vec.shape[0]:
        tail = vec[new_len:]
        if tail.any():
            raise ValueError(
                f"cannot truncate {vec.shape[0]} -> {new_len}: tail is not "
                f"all-zero (max |tail| = {np.abs(tail).max()}) — not a "
                "zero-padded ZeRO-1 vector")
        return vec[:new_len]
    return np.concatenate([vec, np.zeros(new_len - vec.shape[0], vec.dtype)])


def apply_optimizer(optimizer, grads, opt_state, params):
    """One optimizer application: the duck-typed ``apply_gradients`` fast
    path when the optimizer provides it (ops.pallas_adam.FusedApplyAdam —
    one fused kernel pass over {p, m, v, g} instead of update + apply),
    else the plain optax update. Shared by every step factory that
    consumes averaged gradients (parallel/dp.py — including the ZeRO-1
    slice update, where the fast path runs on each replica's 1/N shard —
    and parallel/compress.py)."""
    if hasattr(optimizer, "apply_gradients"):
        return optimizer.apply_gradients(params, grads, opt_state)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state


class FusedAdamState(NamedTuple):
    count: jnp.ndarray   # [] int32
    mu: optax.Params
    nu: optax.Params


def adam_leaf_math(g, m, v, c1, c2, *, lr: float, b1: float, b2: float,
                   eps: float):
    """The per-leaf Adam recurrence, shared by every implementation here
    and by ops.pallas_adam's jnp fallback (the Pallas kernel mirrors this
    expression on Refs — keep the two in sync). Returns (update, m, v);
    the update is the signed step BEFORE it is added to the params."""
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * jnp.square(g)
    u = (-lr) * (m / c1) / (jnp.sqrt(v / c2) + eps)
    return u, m, v


def fused_adam(learning_rate: float, b1: float = 0.9, b2: float = 0.999,
               eps: float = 1e-8) -> optax.GradientTransformation:
    def init_fn(params):
        zeros = lambda p: jnp.zeros_like(p)
        return FusedAdamState(jnp.zeros((), jnp.int32),
                              jax.tree.map(zeros, params),
                              jax.tree.map(zeros, params))

    def update_fn(grads, state, params=None):
        del params
        count = state.count + 1
        c1 = 1.0 - b1 ** count.astype(jnp.float32)
        c2 = 1.0 - b2 ** count.astype(jnp.float32)

        def leaf(g, m, v):
            u, m, v = adam_leaf_math(g, m, v, c1, c2, lr=learning_rate,
                                     b1=b1, b2=b2, eps=eps)
            return u.astype(g.dtype), m, v

        # Flatten-then-unflatten rather than a tree.map returning tuples:
        # grads trees may themselves contain tuple nodes, which an
        # is_leaf=isinstance(x, tuple) unzip would mistake for leaf triples.
        g_flat, treedef = jax.tree.flatten(grads)
        triples = [leaf(g, m, v) for g, m, v in
                   zip(g_flat, jax.tree.leaves(state.mu),
                       jax.tree.leaves(state.nu))]
        updates = jax.tree.unflatten(treedef, [t[0] for t in triples])
        mu = jax.tree.unflatten(treedef, [t[1] for t in triples])
        nu = jax.tree.unflatten(treedef, [t[2] for t in triples])
        return updates, FusedAdamState(count, mu, nu)

    return optax.GradientTransformation(init_fn, update_fn)
