"""Fully-fused Adam step as a Pallas TPU kernel: params + moments in one pass.

Why another Adam: ``ops.adam.fused_adam`` collapses optax's multi-stage
update into one jnp expression per leaf, which XLA fuses into a single
elementwise kernel — but the *apply* (``p + u``) still lives outside the
optimizer contract, and XLA's fusion decisions over a 13-leaf tree are its
own. The optimizer leg is pure HBM bandwidth (24 M params × fp32 × {p, m, v,
g} read + {p, m, v} write ≈ 0.8 ms at v5e's 819 GB/s); the measured XLA leg
runs ~3.5× that floor (experiments/ROOFLINE.md). This module commits the
whole update rule

    m ← β1·m + (1−β1)·g
    v ← β2·v + (1−β2)·g²
    p ← p − lr · (m/(1−β1^t)) / (√(v/(1−β2^t)) + ε)

to one Pallas kernel per large leaf — seven HBM streams, nothing else — with
``input_output_aliases`` so p/m/v update in place.

Integration: ``FusedApplyAdam`` keeps the optax surface (``init`` /
``update`` — the latter the plain jnp rule, used by ZeRO-1 and anything else
that wants updates without params) and adds ``apply_gradients(params, grads,
state)``, the fused fast path. ``parallel.dp.make_grad_aggregation_step``
duck-types on ``apply_gradients`` and routes through it when present.

Leaf routing: fp32 leaves whose element count is a multiple of 512 and at
least 64 K go through the kernel reshaped to [N/512, 512] lanes-dense tiles;
everything else (norm vectors, odd shapes, non-fp32) falls back to the jnp
rule. At the canonical 288/6/6 config the kernel covers >99.9 % of the 24 M
parameters. Semantics match ``optax.adam`` within float re-association
(asserted in tests/test_pallas_adam.py, interpret mode on CPU).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .adam import FusedAdamState, adam_leaf_math, fused_adam

_LANES = 512          # flattened-leaf row width: 4 × the 128-lane vector
_ROW_BLOCK = 512      # rows per grid step → 1 MB fp32 per operand block
_MIN_PALLAS = 1 << 16  # leaves smaller than this stay on the jnp path


def _adam_kernel(c_ref, p_ref, m_ref, v_ref, g_ref, po_ref, mo_ref, vo_ref,
                 *, lr: float, b1: float, b2: float, eps: float):
    # Mirrors ops.adam.adam_leaf_math on Refs (the shared jnp rule can't be
    # called on Ref reads without materializing extra temporaries) — keep in
    # sync with it.
    # c_ref (SMEM, via scalar prefetch): [c1, c2] bias corrections for the
    # current step — traced values, so they ride in as data, not constants.
    c1 = c_ref[0]
    c2 = c_ref[1]
    g = g_ref[...]
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * (g * g)
    mo_ref[...] = m
    vo_ref[...] = v
    po_ref[...] = p_ref[...] - lr * (m / c1) / (jnp.sqrt(v / c2) + eps)


@functools.partial(jax.jit, static_argnames=("lr", "b1", "b2", "eps",
                                             "interpret"))
def _adam_leaf_pallas(p, m, v, g, corrections, *, lr, b1, b2, eps,
                      interpret=False):
    """One leaf's fused update. p/m/v/g flat-reshaped to [rows, 512]."""
    shape = p.shape
    rows = p.size // _LANES
    p2, m2, v2, g2 = (x.reshape(rows, _LANES) for x in (p, m, v, g))
    block = min(rows, _ROW_BLOCK)
    kernel = functools.partial(_adam_kernel, lr=lr, b1=b1, b2=b2, eps=eps)
    # index_map under scalar prefetch receives (grid_idx, scalar_ref).
    spec = pl.BlockSpec((block, _LANES), lambda i, c: (i, 0))
    po, mo, vo = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(pl.cdiv(rows, block),),
            in_specs=[spec] * 4,
            out_specs=[spec] * 3,
        ),
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), jnp.float32)] * 3,
        # p/m/v update in place: input i (after the scalar arg) → output.
        input_output_aliases={1: 0, 2: 1, 3: 2},
        interpret=interpret,
    )(corrections, p2, m2, v2, g2)
    return po.reshape(shape), mo.reshape(shape), vo.reshape(shape)


def _leaf_jnp(p, m, v, g, c1, c2, *, lr, b1, b2, eps):
    """Fallback: the shared rule (ops.adam.adam_leaf_math) + in-expression
    apply, fused by XLA into one elementwise kernel."""
    u, m, v = adam_leaf_math(g, m, v, c1, c2, lr=lr, b1=b1, b2=b2, eps=eps)
    return p + u, m, v


def smoke_check(atol: float = 1e-5) -> None:
    """One-step Mosaic-lowering smoke: run the compiled kernel (interpret
    only if off-TPU) on one eligible leaf and assert it matches the jnp
    rule. The bench gates the '+padam' variant on this so a kernel whose
    actual TPU lowering is wrong can never produce a trusted number —
    interpret-mode CPU tests exercise the math, not the lowering.
    Raises on mismatch; returns None when the kernel is trustworthy."""
    key = jax.random.key(0)
    kp, km, kv, kg = jax.random.split(key, 4)
    # 972 rows of 512 lanes: >_ROW_BLOCK rows forces a multi-step grid with
    # a ragged last block — the configuration the real 24 M-param leaves
    # hit (e.g. the 6×288×288 stack is rows=972) — so the gate exercises
    # index_map stepping, cross-step scalar prefetch, and multi-block
    # aliasing, not just a single-block lowering.
    shape = (972 * _LANES,)
    p = jax.random.normal(kp, shape, jnp.float32)
    m = 0.1 * jax.random.normal(km, shape, jnp.float32)
    v = jnp.abs(0.1 * jax.random.normal(kv, shape, jnp.float32))
    g = jax.random.normal(kg, shape, jnp.float32)
    hyper = dict(lr=1e-3, b1=0.9, b2=0.999, eps=1e-8)
    c1, c2 = 1.0 - 0.9 ** 3, 1.0 - 0.999 ** 3
    corrections = jnp.asarray([c1, c2], jnp.float32)
    interpret = jax.default_backend() != "tpu"
    got = _adam_leaf_pallas(p, m, v, g, corrections, interpret=interpret,
                            **hyper)
    want = _leaf_jnp(p, m, v, g, c1, c2, **hyper)
    for name, a, b in zip(("p", "m", "v"), got, want):
        err = float(jnp.max(jnp.abs(a - b)))
        if not err <= atol:      # NaN-safe: NaN fails the comparison
            raise AssertionError(
                f"pallas Adam smoke: {name} max|Δ|={err:.3e} > {atol} on "
                f"backend {jax.default_backend()!r} — kernel lowering is "
                "not trustworthy")


def _pallas_eligible(p, g) -> bool:
    return (p.dtype == jnp.float32 and g.dtype == jnp.float32
            and p.size >= _MIN_PALLAS and p.size % _LANES == 0)


class FusedApplyAdam:
    """Adam with a Pallas fused param+moment apply (see module docstring).

    optax-compatible: ``.init(params)`` / ``.update(grads, state, params)``
    behave exactly like ``ops.adam.fused_adam`` (one jnp expression per
    leaf). The fast path is ``.apply_gradients(params, grads, state)`` —
    used automatically by ``parallel.dp.make_grad_aggregation_step``.
    """

    def __init__(self, learning_rate: float, b1: float = 0.9,
                 b2: float = 0.999, eps: float = 1e-8,
                 interpret: Optional[bool] = None):
        self.lr, self.b1, self.b2, self.eps = learning_rate, b1, b2, eps
        # interpret=None: resolved at trace time — pallas interpret mode off
        # TPU keeps the same code path testable on the virtual CPU mesh.
        self.interpret = interpret
        self._fallback = fused_adam(learning_rate, b1, b2, eps)

    # ---- optax surface -------------------------------------------------
    def init(self, params) -> FusedAdamState:
        return self._fallback.init(params)

    def update(self, grads, state, params=None):
        return self._fallback.update(grads, state, params)

    # ---- fused fast path -----------------------------------------------
    def apply_gradients(self, params, grads, state: FusedAdamState):
        interpret = (jax.default_backend() != "tpu"
                     if self.interpret is None else self.interpret)
        count = state.count + 1
        cf = count.astype(jnp.float32)
        c1 = 1.0 - self.b1 ** cf
        c2 = 1.0 - self.b2 ** cf
        corrections = jnp.stack([c1, c2])

        hyper = dict(lr=self.lr, b1=self.b1, b2=self.b2, eps=self.eps)
        p_flat, treedef = jax.tree.flatten(params)
        g_flat = jax.tree.leaves(grads)
        m_flat = jax.tree.leaves(state.mu)
        v_flat = jax.tree.leaves(state.nu)
        new_p, new_m, new_v = [], [], []
        for p, m, v, g in zip(p_flat, m_flat, v_flat, g_flat):
            if _pallas_eligible(p, g):
                p2, m2, v2 = _adam_leaf_pallas(
                    p, m, v, g, corrections, interpret=interpret, **hyper)
            else:
                p2, m2, v2 = _leaf_jnp(p, m, v, g.astype(p.dtype), c1, c2,
                                       **hyper)
            new_p.append(p2)
            new_m.append(m2)
            new_v.append(v2)
        unflat = functools.partial(jax.tree.unflatten, treedef)
        return unflat(new_p), FusedAdamState(count, unflat(new_m),
                                             unflat(new_v))
