"""Mixed-precision training: bf16 parameters with fp32 master weights.

Capability target (parity-plus; absent in the reference, which trains fp32
torch modules end to end — lab/tutorial_1b/primer/intro.py): the standard
large-model recipe on TPU. The model's parameters live in bf16 — halving
their HBM footprint and the weight-read traffic of every matmul (the
canonical tiny-Llama re-casts fp32 weights to bf16 on every use;
models/llama.py's ``.astype(x.dtype)`` becomes a no-op when params are
already bf16) — while the optimizer accumulates in fp32 so tiny updates
are not rounded away (bf16 has ~8 bits of mantissa; an Adam step of
relative size < 2^-9 would vanish if applied in bf16).

``master_weight_adam`` is a plain ``optax.GradientTransformation``, so it
drops into every step factory here (dp/pp/zero1/compressed):

- state: (count, mu, nu, master) — master is the fp32 copy of the params,
  initialized by upcasting.
- update(grads, state, params): runs the shared Adam rule
  (ops.adam.adam_leaf_math) in fp32 against the master, then returns
  ``updates = master_new.astype(bf16) - params`` — so
  ``optax.apply_updates(params, updates)`` lands the params on the downcast
  master (exact under Sterbenz's lemma whenever consecutive values are
  within 2×, i.e. for Adam-sized steps; tests/test_mixed_precision.py).

The decode path composes: train in bf16+master, serve the bf16 params
directly (bench.py's decode sidebar measures the same layout).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from .adam import adam_leaf_math


class MasterAdamState(NamedTuple):
    count: jnp.ndarray     # [] int32
    mu: optax.Params       # fp32
    nu: optax.Params       # fp32
    master: optax.Params   # fp32 master weights


def master_weight_adam(learning_rate: float, b1: float = 0.9,
                       b2: float = 0.999, eps: float = 1e-8
                       ) -> optax.GradientTransformation:
    def init_fn(params):
        f32 = lambda p: p.astype(jnp.float32)
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return MasterAdamState(jnp.zeros((), jnp.int32),
                               jax.tree.map(zeros, params),
                               jax.tree.map(zeros, params),
                               jax.tree.map(f32, params))

    def update_fn(grads, state, params):
        assert params is not None, (
            "master_weight_adam needs params (optax passes them in every "
            "step factory in this package)")
        count = state.count + 1
        cf = count.astype(jnp.float32)
        c1 = 1.0 - b1 ** cf
        c2 = 1.0 - b2 ** cf

        def leaf(g, m, v, master, p):
            u, m, v = adam_leaf_math(g.astype(jnp.float32), m, v, c1, c2,
                                     lr=learning_rate, b1=b1, b2=b2, eps=eps)
            master = master + u
            # The update is defined so apply_updates lands the params
            # EXACTLY on the downcast master (no drift between the two).
            return (master.astype(p.dtype) - p), m, v, master

        g_flat, treedef = jax.tree.flatten(grads)
        quads = [leaf(g, m, v, w, p) for g, m, v, w, p in
                 zip(g_flat, jax.tree.leaves(state.mu),
                     jax.tree.leaves(state.nu),
                     jax.tree.leaves(state.master),
                     jax.tree.leaves(params))]
        unflat = lambda i: jax.tree.unflatten(treedef,
                                              [q[i] for q in quads])
        return unflat(0), MasterAdamState(count, unflat(1), unflat(2),
                                          unflat(3))

    return optax.GradientTransformation(init_fn, update_fn)
