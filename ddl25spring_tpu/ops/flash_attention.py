"""Fused causal attention (FlashAttention) as Pallas TPU kernels, fwd + bwd.

Capability/perf target: the reference computes attention inside simplellm's
torch modules (materializing the full [T, T] score matrix per head). On TPU
the memory-bound step is HBM traffic for those scores; these kernels stream
K/V blocks through VMEM with the online-softmax recurrence so scores never
leave the chip, and the matmuls hit the MXU.

The op is differentiable via ``jax.custom_vjp``: the forward kernel saves the
per-row logsumexp (LSE) alongside the output, and the backward pass recomputes
attention probabilities block-wise from (q, k, lse) — the standard
FlashAttention backward — in two kernels:

- dQ kernel: for each query block, sweep key blocks (sequential last grid
  axis), accumulating ``dq += ds @ k`` in VMEM scratch;
- dK/dV kernel: for each key block, sweep query blocks, accumulating
  ``dk += ds^T @ q`` and ``dv += p^T @ do``.

Design notes (see /opt/skills/guides/pallas_guide.md):
- grid = (batch*heads, outer_blocks, inner_blocks); the LAST grid axis runs
  sequentially on TPU, so running statistics / accumulators live in VMEM
  scratch that persists across the inner sweep.
- m/l/lse/delta are kept lane-replicated at (block, 128) to respect the fp32
  (8, 128) min tile; column values are identical across lanes.
- Causal blocks strictly above the diagonal are skipped via `pl.when`
  (predicated out — no FLOPs), and their block index maps are clamped so the
  pipeline elides the HBM fetch entirely.
- On non-TPU backends `interpret=True` keeps tests runnable on the virtual
  CPU mesh; production CPU paths should use the XLA einsum attention
  (models/llama._xla_attention) instead.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_NEG_INF = -1e30


# ------------------------------------------------------------------ forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref, *,
                block_q: int, block_k: int, n_k_blocks: int, scale: float,
                causal: bool, seq_len: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: block contributes iff its first key position can be visible to
    # the last query position of this q block.
    run = (ik * block_k <= iq * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)                     # [bq, dh]
        k = k_ref[0].astype(jnp.float32)                     # [bk, dh]
        v = v_ref[0].astype(jnp.float32)                     # [bk, dh]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) \
            + ik * block_k
        if causal:
            qpos = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) \
                + iq * block_q
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        if not causal:
            # Zero-padded tail keys must not receive softmax mass. (With
            # causal=True the causal mask already hides them from every real
            # query, and padded query rows are trimmed by the wrapper.)
            s = jnp.where(kpos < seq_len, s, _NEG_INF)

        m_prev = m_ref[:, :1]                                # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                               # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)                      # [bq, 1]
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == n_k_blocks - 1)
    def _finalize():
        # Fully-masked rows (can't happen for causal q>=0) would have l=0;
        # guard anyway so padding rows emit zeros, not NaNs.
        l = l_ref[:, :1]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(jnp.broadcast_to(safe, lse_ref.shape[1:]))


def _causal_kv_index(block_q: int, block_k: int):
    # Above-diagonal grid steps are predicated out in the kernel; clamp
    # their K/V block index to the diagonal so consecutive steps reference
    # the same block and the pipeline elides the HBM fetch entirely.
    def kv_index(bh, iq, ik):
        return (bh, jnp.minimum(ik, (iq * block_q + block_q - 1) // block_k), 0)
    return kv_index


def _fwd(qb, kb, vb, causal: bool, block_q: int, block_k: int,
         interpret: bool, seq_len: int, out_dtype):
    """Runs the forward kernel on [BH, T_pad, Dh] inputs.

    Returns (out [BH, T_pad, Dh], lse [BH, T_pad, LANES] lane-replicated).
    """
    bh, t_pad, dh = qb.shape
    n_q = t_pad // block_q
    n_k = t_pad // block_k
    scale = 1.0 / math.sqrt(dh)

    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, n_k_blocks=n_k,
        scale=scale, causal=causal, seq_len=seq_len)
    kv_index = (_causal_kv_index(block_q, block_k) if causal
                else (lambda bh_, iq, ik: (bh_, ik, 0)))

    return pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda bh_, iq, ik: (bh_, iq, 0)),
            pl.BlockSpec((1, block_k, dh), kv_index),
            pl.BlockSpec((1, block_k, dh), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dh), lambda bh_, iq, ik: (bh_, iq, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda bh_, iq, ik: (bh_, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_pad, dh), out_dtype),
            jax.ShapeDtypeStruct((bh, t_pad, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),       # m
            pltpu.VMEM((block_q, _LANES), jnp.float32),       # l
            pltpu.VMEM((block_q, dh), jnp.float32),           # acc
        ],
        interpret=interpret,
    )(qb, kb, vb)


# ----------------------------------------------------------------- backward

def _bwd_mask(iq, ik, block_q: int, block_k: int, causal: bool, seq_len: int):
    """[bq, bk] validity mask. Unlike the forward (where padded query rows
    are merely trimmed), the backward MUST zero padded query rows: their
    lse is -inf, so exp(s - lse) would overflow and 0*inf-poison dK/dV."""
    qpos = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) \
        + iq * block_q
    kpos = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) \
        + ik * block_k
    mask = qpos < seq_len
    if causal:
        mask &= qpos >= kpos
    else:
        mask &= kpos < seq_len
    return mask


def _bwd_p_ds(q, k, v, do, lse, delta, iq, ik, *, block_q, block_k, scale,
              causal, seq_len):
    """Shared recompute: attention probs p and score-gradient ds for a block.

    p  = exp(q k^T scale - lse)         (exact softmax probabilities)
    ds = p * (do v^T - delta) * scale   (delta = rowsum(do * o))
    """
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    mask = _bwd_mask(iq, ik, block_q, block_k, causal, seq_len)
    p = jnp.where(mask, jnp.exp(s - lse), 0.0)               # [bq, bk]
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * scale                            # [bq, bk]
    return p, ds


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, block_q: int, block_k: int, n_k_blocks: int,
               scale: float, causal: bool, seq_len: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (ik * block_k <= iq * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        _, ds = _bwd_p_ds(q, k, v, do, lse_ref[0][:, :1], delta_ref[0][:, :1],
                          iq, ik, block_q=block_q, block_k=block_k,
                          scale=scale, causal=causal, seq_len=seq_len)
        dq_acc[:] += jax.lax.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ik == n_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, dk_acc, dv_acc, *, block_q: int, block_k: int,
                n_q_blocks: int, scale: float, causal: bool, seq_len: int):
    # Grid is (bh, ik, iq): the sequential inner sweep is over QUERY blocks.
    ik = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (iq * block_q + block_q - 1 >= ik * block_k) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        p, ds = _bwd_p_ds(q, k, v, do, lse_ref[0][:, :1], delta_ref[0][:, :1],
                          iq, ik, block_q=block_q, block_k=block_k,
                          scale=scale, causal=causal, seq_len=seq_len)
        dv_acc[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dk_acc[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(iq == n_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_kernels(qb, kb, vb, dob, lse, delta, causal: bool, block_q: int,
                 block_k: int, interpret: bool, seq_len: int):
    """Runs dQ and dK/dV kernels on [BH, T_pad, Dh] inputs."""
    bh, t_pad, dh = qb.shape
    n_q = t_pad // block_q
    n_k = t_pad // block_k
    scale = 1.0 / math.sqrt(dh)
    common = dict(block_q=block_q, block_k=block_k, scale=scale,
                  causal=causal, seq_len=seq_len)

    q_spec = pl.BlockSpec((1, block_q, dh), lambda bh_, iq, ik: (bh_, iq, 0))
    row_spec = pl.BlockSpec((1, block_q, _LANES),
                            lambda bh_, iq, ik: (bh_, iq, 0))
    kv_index = (_causal_kv_index(block_q, block_k) if causal
                else (lambda bh_, iq, ik: (bh_, ik, 0)))
    kv_spec = pl.BlockSpec((1, block_k, dh), kv_index)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, n_k_blocks=n_k, **common),
        grid=(bh, n_q, n_k),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, t_pad, dh), qb.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, dh), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)

    # Grid reordered to (bh, ik, iq). Below-diagonal skipped steps clamp the
    # q-side index to the first contributing q block of this key block.
    if causal:
        def q_index(bh_, ik, iq):
            return (bh_, jnp.maximum(iq, (ik * block_k) // block_q), 0)
    else:
        def q_index(bh_, ik, iq):
            return (bh_, iq, 0)
    q_spec_t = pl.BlockSpec((1, block_q, dh), q_index)
    row_spec_t = pl.BlockSpec((1, block_q, _LANES), q_index)
    kv_spec_t = pl.BlockSpec((1, block_k, dh),
                             lambda bh_, ik, iq: (bh_, ik, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, n_q_blocks=n_q, **common),
        grid=(bh, n_k, n_q),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=[kv_spec_t, kv_spec_t],
        out_shape=[jax.ShapeDtypeStruct((bh, t_pad, dh), kb.dtype),
                   jax.ShapeDtypeStruct((bh, t_pad, dh), vb.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, dh), jnp.float32),
                        pltpu.VMEM((block_k, dh), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)
    return dq, dk, dv


# ------------------------------------------------ dh-major ("packed") layout
#
# The kernels above stream [BH, T, Dh] blocks. At this model's Dh=48 the
# minor dim is lane-padded to 128 in the TPU tiled layout, so every q/k/v/o
# (and backward dq/dk/dv) HBM transfer moves 128/48 ≈ 2.67x the useful
# bytes. Transposing the operands to [BH, Dh, T] makes them exactly dense —
# Dh=48 is a whole number of f32/bf16 sublane tiles and T a lane multiple —
# which converts the streamed traffic to 100% useful bytes. The MXU dots
# keep the same shapes (K=Dh for QK is intrinsic to attention; no dense
# packing can beat XLA's K-padding — a block-diagonal 2-head pack spends
# exactly its saved padding on zero blocks), so this is a pure
# memory-bandwidth play; scores are computed key-major ([bk, bq]) so the
# softmax statistics live along lanes and never need a relayout.

def _fwd_kernel_t(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                  *, block_q: int, block_k: int, n_k_blocks: int, scale: float,
                  causal: bool, seq_len: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = (ik * block_k <= iq * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _body():
        qt = q_ref[0].astype(jnp.float32)                    # [dh, bq]
        kt = k_ref[0].astype(jnp.float32)                    # [dh, bk]
        vt = v_ref[0].astype(jnp.float32)                    # [dh, bk]
        # Key-major scores: keys on sublanes, queries on lanes.
        s = jax.lax.dot_general(kt, qt, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 0) \
            + ik * block_k
        if causal:
            qpos = jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 1) \
                + iq * block_q
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        else:
            s = jnp.where(kpos < seq_len, s, _NEG_INF)

        m_prev = m_ref[:1, :]                                # [1, bq]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=0, keepdims=True))
        p = jnp.exp(s - m_new)                               # [bk, bq]
        alpha = jnp.exp(m_prev - m_new)                      # [1, bq]
        l_new = alpha * l_ref[:1, :] + jnp.sum(p, axis=0, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot(
            vt, p, preferred_element_type=jnp.float32)       # [dh, bq]
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == n_k_blocks - 1)
    def _finalize():
        l = l_ref[:1, :]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(
            jnp.broadcast_to(safe, lse_ref.shape[1:]))


_SUBLANES = 8


def _fwd_t(qb, kb, vb, causal: bool, block_q: int, block_k: int,
           interpret: bool, seq_len: int, out_dtype):
    """Forward on dh-major [BH, Dh, T_pad] inputs.

    Returns (out [BH, Dh, T_pad], lse [BH, SUBLANES, T_pad] row-replicated).
    """
    bh, dh, t_pad = qb.shape
    n_q = t_pad // block_q
    n_k = t_pad // block_k
    scale = 1.0 / math.sqrt(dh)

    kernel = functools.partial(
        _fwd_kernel_t, block_q=block_q, block_k=block_k, n_k_blocks=n_k,
        scale=scale, causal=causal, seq_len=seq_len)
    if causal:
        def kv_index(bh_, iq, ik):
            return (bh_, 0,
                    jnp.minimum(ik, (iq * block_q + block_q - 1) // block_k))
    else:
        def kv_index(bh_, iq, ik):
            return (bh_, 0, ik)

    return pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, dh, block_q), lambda bh_, iq, ik: (bh_, 0, iq)),
            pl.BlockSpec((1, dh, block_k), kv_index),
            pl.BlockSpec((1, dh, block_k), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, dh, block_q), lambda bh_, iq, ik: (bh_, 0, iq)),
            pl.BlockSpec((1, _SUBLANES, block_q),
                         lambda bh_, iq, ik: (bh_, 0, iq)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, dh, t_pad), out_dtype),
            jax.ShapeDtypeStruct((bh, _SUBLANES, t_pad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_SUBLANES, block_q), jnp.float32),    # m
            pltpu.VMEM((_SUBLANES, block_q), jnp.float32),    # l
            pltpu.VMEM((dh, block_q), jnp.float32),           # acc
        ],
        interpret=interpret,
    )(qb, kb, vb)


def _bwd_mask_t(iq, ik, block_q: int, block_k: int, causal: bool,
                seq_len: int):
    """[bk, bq] validity mask (key-major twin of _bwd_mask)."""
    kpos = jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 0) \
        + ik * block_k
    qpos = jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 1) \
        + iq * block_q
    mask = qpos < seq_len
    if causal:
        mask &= qpos >= kpos
    else:
        mask &= kpos < seq_len
    return mask


def _bwd_p_ds_t(qt, kt, vt, dot_, lse_row, delta_row, iq, ik, *, block_q,
                block_k, scale, causal, seq_len):
    """Key-major recompute: pT [bk, bq] and dsT [bk, bq]."""
    s = jax.lax.dot_general(kt, qt, (((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    mask = _bwd_mask_t(iq, ik, block_q, block_k, causal, seq_len)
    p = jnp.where(mask, jnp.exp(s - lse_row), 0.0)           # [bk, bq]
    dp = jax.lax.dot_general(vt, dot_, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_row) * scale                        # [bk, bq]
    return p, ds


def _dq_kernel_t(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                 dq_acc, *, block_q: int, block_k: int, n_k_blocks: int,
                 scale: float, causal: bool, seq_len: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = (ik * block_k <= iq * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _body():
        qt = q_ref[0].astype(jnp.float32)
        kt = k_ref[0].astype(jnp.float32)
        vt = v_ref[0].astype(jnp.float32)
        dot_ = do_ref[0].astype(jnp.float32)
        _, ds = _bwd_p_ds_t(qt, kt, vt, dot_, lse_ref[0][:1, :],
                            delta_ref[0][:1, :], iq, ik, block_q=block_q,
                            block_k=block_k, scale=scale, causal=causal,
                            seq_len=seq_len)
        dq_acc[:] += jax.lax.dot(kt, ds,
                                 preferred_element_type=jnp.float32)

    @pl.when(ik == n_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel_t(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                  dv_ref, dk_acc, dv_acc, *, block_q: int, block_k: int,
                  n_q_blocks: int, scale: float, causal: bool, seq_len: int):
    ik = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (iq * block_q + block_q - 1 >= ik * block_k) if causal else True

    @pl.when(run)
    def _body():
        qt = q_ref[0].astype(jnp.float32)
        kt = k_ref[0].astype(jnp.float32)
        vt = v_ref[0].astype(jnp.float32)
        dot_ = do_ref[0].astype(jnp.float32)
        p, ds = _bwd_p_ds_t(qt, kt, vt, dot_, lse_ref[0][:1, :],
                            delta_ref[0][:1, :], iq, ik, block_q=block_q,
                            block_k=block_k, scale=scale, causal=causal,
                            seq_len=seq_len)
        dv_acc[:] += jax.lax.dot_general(
            dot_, p, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [dh, bk]
        dk_acc[:] += jax.lax.dot_general(
            qt, ds, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [dh, bk]

    @pl.when(iq == n_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_kernels_t(qb, kb, vb, dob, lse, delta, causal: bool, block_q: int,
                   block_k: int, interpret: bool, seq_len: int):
    """dQ and dK/dV kernels on dh-major [BH, Dh, T_pad] inputs."""
    bh, dh, t_pad = qb.shape
    n_q = t_pad // block_q
    n_k = t_pad // block_k
    scale = 1.0 / math.sqrt(dh)
    common = dict(block_q=block_q, block_k=block_k, scale=scale,
                  causal=causal, seq_len=seq_len)

    q_spec = pl.BlockSpec((1, dh, block_q), lambda bh_, iq, ik: (bh_, 0, iq))
    row_spec = pl.BlockSpec((1, _SUBLANES, block_q),
                            lambda bh_, iq, ik: (bh_, 0, iq))
    if causal:
        def kv_index(bh_, iq, ik):
            return (bh_, 0,
                    jnp.minimum(ik, (iq * block_q + block_q - 1) // block_k))
    else:
        def kv_index(bh_, iq, ik):
            return (bh_, 0, ik)
    kv_spec = pl.BlockSpec((1, dh, block_k), kv_index)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel_t, n_k_blocks=n_k, **common),
        grid=(bh, n_q, n_k),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, dh, t_pad), qb.dtype),
        scratch_shapes=[pltpu.VMEM((dh, block_q), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)

    if causal:
        def q_index(bh_, ik, iq):
            return (bh_, 0, jnp.maximum(iq, (ik * block_k) // block_q))
    else:
        def q_index(bh_, ik, iq):
            return (bh_, 0, iq)
    q_spec_t = pl.BlockSpec((1, dh, block_q), q_index)
    row_spec_t = pl.BlockSpec((1, _SUBLANES, block_q), q_index)
    kv_spec_t = pl.BlockSpec((1, dh, block_k),
                             lambda bh_, ik, iq: (bh_, 0, ik))

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel_t, n_q_blocks=n_q, **common),
        grid=(bh, n_k, n_q),
        in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=[kv_spec_t, kv_spec_t],
        out_shape=[jax.ShapeDtypeStruct((bh, dh, t_pad), kb.dtype),
                   jax.ShapeDtypeStruct((bh, dh, t_pad), vb.dtype)],
        scratch_shapes=[pltpu.VMEM((dh, block_k), jnp.float32),
                        pltpu.VMEM((dh, block_k), jnp.float32)],
        interpret=interpret,
    )(qb, kb, vb, dob, lse, delta)
    return dq, dk, dv


def _layout_t(x, t_pad: int):
    """[B, T, H, Dh] -> [B*H, Dh, T_pad] (dense dh-major kernel layout)."""
    b, t, h, dh = x.shape
    x = jnp.transpose(x, (0, 2, 3, 1)).reshape(b * h, dh, t)
    if t_pad != t:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, t_pad - t)))
    return x


def _unlayout_t(x, b: int, t: int):
    """[B*H, Dh, T_pad] -> [B, T, H, Dh]."""
    bh, dh, _ = x.shape
    return jnp.transpose(x[:, :, :t].reshape(b, bh // b, dh, t), (0, 3, 1, 2))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_t(q, k, v, causal: bool, block_q: int, block_k: int,
             interpret: bool):
    out, _ = _flash_t_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_t_fwd(q, k, v, causal, block_q, block_k, interpret):
    b, t, h, dh = q.shape
    t_pad = _pad_len(t, block_q, block_k)
    out, lse = _fwd_t(_layout_t(q, t_pad), _layout_t(k, t_pad),
                      _layout_t(v, t_pad), causal, block_q, block_k,
                      interpret, t, q.dtype)
    return _unlayout_t(out, b, t), (q, k, v, _unlayout_t(out, b, t),
                                    lse[:, :1, :])


def _flash_t_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    b, t, h, dh = q.shape
    t_pad = _pad_len(t, block_q, block_k)
    lse = jnp.broadcast_to(lse, (b * h, _SUBLANES, t_pad))
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.moveaxis(delta, 2, 1).reshape(b * h, t)       # [BH, T]
    delta = jnp.pad(delta, ((0, 0), (0, t_pad - t)))
    delta = jnp.broadcast_to(delta[:, None, :], (b * h, _SUBLANES, t_pad))
    dq, dk, dv = _bwd_kernels_t(
        _layout_t(q, t_pad), _layout_t(k, t_pad), _layout_t(v, t_pad),
        _layout_t(g, t_pad), lse, delta, causal, block_q, block_k, interpret,
        t)
    return (_unlayout_t(dq, b, t), _unlayout_t(dk, b, t),
            _unlayout_t(dv, b, t))


_flash_t.defvjp(_flash_t_fwd, _flash_t_bwd)


# --------------------------------------------------- custom_vjp + public API

def _layout(x, t_pad: int):
    """[B, T, H, Dh] -> [B*H, T_pad, Dh] (the kernels' layout)."""
    b, t, h, dh = x.shape
    x = jnp.moveaxis(x, 2, 1).reshape(b * h, t, dh)
    if t_pad != t:
        x = jnp.pad(x, ((0, 0), (0, t_pad - t), (0, 0)))
    return x


def _unlayout(x, b: int, t: int):
    """[B*H, T_pad, Dh] -> [B, T, H, Dh]."""
    bh, _, dh = x.shape
    return jnp.moveaxis(x[:, :t].reshape(b, bh // b, t, dh), 1, 2)


def _pad_len(t: int, block_q: int, block_k: int) -> int:
    # Common multiple of both block sizes so the q and k grids each tile
    # t_pad exactly.
    lcm = math.lcm(block_q, block_k)
    return math.ceil(t / lcm) * lcm


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal: bool, block_q: int, block_k: int,
           interpret: bool):
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    b, t, h, dh = q.shape
    t_pad = _pad_len(t, block_q, block_k)
    out, lse = _fwd(_layout(q, t_pad), _layout(k, t_pad), _layout(v, t_pad),
                    causal, block_q, block_k, interpret, t, q.dtype)
    out = _unlayout(out, b, t)
    # The kernel emits lse lane-replicated ([BH, T_pad, 128]); keep only one
    # lane as the residual (128x less memory held until the backward).
    return out, (q, k, v, out, lse[:, :, :1])


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    b, t, h, dh = q.shape
    t_pad = _pad_len(t, block_q, block_k)
    lse = jnp.broadcast_to(lse, (b * h, t_pad, _LANES))
    # delta = rowsum(dO * O), the softmax-Jacobian correction term. An XLA
    # elementwise reduce — not worth a kernel. Lane-replicated like lse.
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.moveaxis(delta, 2, 1).reshape(b * h, t)       # [BH, T]
    delta = jnp.pad(delta, ((0, 0), (0, t_pad - t)))
    delta = jnp.broadcast_to(delta[:, :, None], (b * h, t_pad, _LANES))
    dq, dk, dv = _bwd_kernels(
        _layout(q, t_pad), _layout(k, t_pad), _layout(v, t_pad),
        _layout(g, t_pad), lse, delta, causal, block_q, block_k, interpret, t)
    return (_unlayout(dq, b, t), _unlayout(dk, b, t), _unlayout(dv, b, t))


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret", "dh_major"))
def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None,
                    dh_major: bool = False) -> jnp.ndarray:
    """Fused attention, differentiable. q, k, v: [B, T, H, Dh] (same layout
    as the XLA path in models/llama.attention). Returns [B, T, H, Dh].

    Sequence length is padded up to a block multiple internally; padded keys
    get zero softmax mass and padded query rows are trimmed on return (and
    zeroed in the backward).

    ``dh_major=True`` streams operands in the [BH, Dh, T] layout, which is
    exactly dense on TPU for head dims like this model's 48 (a [_, T, 48]
    operand is lane-padded to 128, costing 2.67x HBM bytes on every q/k/v/o
    and gradient transfer). Same math, same MXU shapes — a pure
    memory-bandwidth variant; see experiments/attn_bench.py for the
    measured comparison.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if dh_major:
        return _flash_t(q, k, v, causal, block_q, block_k, interpret)
    return _flash(q, k, v, causal, block_q, block_k, interpret)
