"""Fused causal attention (FlashAttention) as a Pallas TPU kernel.

Capability/perf target: the reference computes attention inside simplellm's
torch modules (materializing the full [T, T] score matrix per head). On TPU
the memory-bound step is HBM traffic for those scores; this kernel streams
K/V blocks through VMEM with the online-softmax recurrence so scores never
leave the chip, and the matmuls hit the MXU in bf16.

Design notes (see /opt/skills/guides/pallas_guide.md):
- grid = (batch·heads, q_blocks, k_blocks); the LAST grid axis runs
  sequentially on TPU, so the (m, l, acc) running statistics live in VMEM
  scratch that persists across the k sweep for a fixed q block.
- m/l scratch is shaped (block_q, 128) — lane-width replicated — to respect
  the fp32 (8, 128) min tile; column values are identical across lanes.
- Causal blocks strictly above the diagonal are skipped via `pl.when`
  (predicated out — no FLOPs, no VMEM traffic); the diagonal block applies
  an iota mask.
- On non-TPU backends `interpret=True` keeps tests runnable on the virtual
  CPU mesh; production CPU paths should use the XLA einsum attention
  (models/llama._xla_attention) instead.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  block_q: int, block_k: int, n_k_blocks: int, scale: float,
                  causal: bool, seq_len: int):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: block contributes iff its first key position can be visible to
    # the last query position of this q block.
    run = (ik * block_k <= iq * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)                     # [bq, dh]
        k = k_ref[0].astype(jnp.float32)                     # [bk, dh]
        v = v_ref[0].astype(jnp.float32)                     # [bk, dh]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) \
            + ik * block_k
        if causal:
            qpos = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) \
                + iq * block_q
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        if not causal:
            # Zero-padded tail keys must not receive softmax mass. (With
            # causal=True the causal mask already hides them from every real
            # query, and padded query rows are trimmed by the wrapper.)
            s = jnp.where(kpos < seq_len, s, _NEG_INF)

        m_prev = m_ref[:, :1]                                # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                               # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)                      # [bq, 1]
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ik == n_k_blocks - 1)
    def _finalize():
        # Fully-masked rows (can't happen for causal q>=0) would have l=0;
        # guard anyway so padding rows emit zeros, not NaNs.
        l = l_ref[:, :1]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                    causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None
                    ) -> jnp.ndarray:
    """Fused attention. q, k, v: [B, T, H, Dh] (same layout as the XLA path
    in models/llama.attention). Returns [B, T, H, Dh].

    Sequence length is padded up to a block multiple internally; with
    ``causal=True`` the tail padding keys are masked by causality for every
    real query, so no extra length mask is needed.
    """
    b, t, h, dh = q.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # Sequence is padded to a common multiple of both block sizes so the
    # q and k grids each tile t_pad exactly; padded keys are masked in the
    # kernel and padded query rows are trimmed on return.
    lcm = math.lcm(block_q, block_k)
    t_pad = math.ceil(t / lcm) * lcm

    def to_bh(x):
        x = jnp.moveaxis(x, 2, 1).reshape(b * h, t, dh)      # [BH, T, Dh]
        if t_pad != t:
            x = jnp.pad(x, ((0, 0), (0, t_pad - t), (0, 0)))
        return x

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    n_q = t_pad // block_q
    n_k = t_pad // block_k
    scale = 1.0 / math.sqrt(dh)

    kernel = functools.partial(
        _flash_kernel, block_q=block_q, block_k=block_k, n_k_blocks=n_k,
        scale=scale, causal=causal, seq_len=t)

    if causal:
        # Above-diagonal grid steps are predicated out in the kernel; clamp
        # their K/V block index to the diagonal so consecutive steps reference
        # the same block and the pipeline elides the HBM fetch entirely.
        def kv_index(bh, iq, ik):
            return (bh, jnp.minimum(ik, (iq * block_q + block_q - 1) // block_k), 0)
    else:
        def kv_index(bh, iq, ik):
            return (bh, ik, 0)

    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda bh, iq, ik: (bh, iq, 0)),
            pl.BlockSpec((1, block_k, dh), kv_index),
            pl.BlockSpec((1, block_k, dh), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, dh), lambda bh, iq, ik: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t_pad, dh), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),       # m
            pltpu.VMEM((block_q, _LANES), jnp.float32),       # l
            pltpu.VMEM((block_q, dh), jnp.float32),           # acc
        ],
        interpret=interpret,
    )(qb, kb, vb)

    out = out[:, :t].reshape(b, h, t, dh)
    return jnp.moveaxis(out, 1, 2)                            # [B, T, H, Dh]
