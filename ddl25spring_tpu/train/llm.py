"""End-to-end LLM training drivers.

`train_llm_dp` is the framework's minimum end-to-end slice: the reference's
whole DP gradient-aggregation script (lab/tutorial_1b/DP/gradient_aggr/
intro_DP_GA.py — N processes, gloo, per-iter flatten/allreduce) collapsed
into one jitted SPMD program reproducing its loss trajectory
(10.5 → ≈6 over 5000 iters, lab/out_b1_2.txt).
"""

from __future__ import annotations

import functools
import math
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..config import LlamaConfig, ResilienceConfig, TrainConfig
from ..data.tokens import TokenStream, sharded_batches
from ..metrics import ResilienceStats
from ..models import llama
from ..parallel import dp, make_mesh, pp, tp
from ..resilience.preemption import PreemptionHandler
from ..telemetry import introspect
from ..telemetry.trace import Spans, Tracer
from ..tokenizers import load_tokenizer


@dataclass
class LLMTrainReport:
    losses: List[float] = field(default_factory=list)
    tokens_per_sec: float = 0.0
    steps: int = 0
    wall_time: float = 0.0
    # Resilience accounting: True if the loop exited early on a SIGTERM
    # force-save (re-running the same call resumes); counters cover guard
    # skips/rollbacks, checkpoint retries/fallbacks, and preemptions.
    # ``start_step`` is the stream position losses[0] corresponds to (the
    # resumed-from step; 0 for a fresh run) — ``iters - len(losses)`` is
    # WRONG for a preempted run, which ends early.
    preempted: bool = False
    start_step: int = 0
    resilience: Optional[ResilienceStats] = None
    # Elastic mode (resilience/elastic.py): one dict per replica-loss
    # recovery (RemeshRecord.as_dict — old/new world, path, seconds,
    # steps replayed), and the throughput measured on the final topology
    # (0.0 when no remesh happened or too little ran after the last one).
    remeshes: List[dict] = field(default_factory=list)
    post_remesh_tokens_per_sec: float = 0.0

    def tokens_per_sec_per_device(self, n_devices: int) -> float:
        return self.tokens_per_sec / max(n_devices, 1)


@functools.partial(jax.jit, static_argnames="cfg")
def _eval_batch_loss(params, batch, cfg: LlamaConfig):
    # Module-level + static cfg: periodic eval_llm calls from a train loop
    # hit the jit cache instead of recompiling a per-call closure.
    return llama.forward_loss(params, batch, cfg)


def eval_llm(params, model_cfg: LlamaConfig, *, n_batches: int = 16,
             batch_size: int = 8, skip: int = 0,
             tokenizer=None, seed: int = 1, stream=None) -> dict:
    """Held-out evaluation: mean next-token loss and perplexity over
    ``n_batches``. Parity-plus: the reference only ever prints train-batch
    loss (lab/tutorial_1b/primer/intro.py); an eval split is what lets a
    user see overfitting on the tiny corpus at all. Uses the fused head+CE,
    so no [B, T, V] logits materialize. Returns {"loss", "perplexity",
    "n_tokens"}.

    Held-out contract: on the synthetic fallback corpus a different
    ``seed`` IS a disjoint corpus (the generator is seed-parameterized), so
    the default seed=1 vs the trainers' seed=0 needs no skipping. For a
    file-backed corpus pass ``skip`` explicitly, PAST your training window
    (trainer shard i reads from sequence i·5000 for iters·batch_size
    sequences) — and note the stream cycles a short corpus, so disjointness
    holds only while skip + the eval span stays within one pass. For
    periodic evals with a nonzero skip, build the iterator once —
    ``it = iter(TokenStream(...))`` — and pass it via ``stream``: each call
    then continues it instead of re-tokenizing the whole skip window. (A
    raw TokenStream is also accepted but restarts — and re-pays the skip —
    on every call.)
    """
    tok = tokenizer or load_tokenizer()
    model_cfg = model_cfg.replace(vocab_size=tok.vocab_size)
    if stream is None:
        stream = TokenStream(tok, batch_size, model_cfg.ctx_size,
                             skip=skip, seed=seed)
    stream = iter(stream)  # no-op on iterators; accepts a raw TokenStream
    total = 0.0
    n_tokens = 0
    for _ in range(n_batches):
        batch = jnp.asarray(next(stream))
        total += float(_eval_batch_loss(params, batch, model_cfg))
        # The causal loss scores T-1 next-token positions per sequence.
        n_tokens += batch.shape[0] * (batch.shape[1] - 1)
    mean = total / n_batches
    return {"loss": mean, "perplexity": math.exp(min(mean, 30.0)),
            "n_tokens": n_tokens}


def _make_trainer_optimizer(train_cfg: TrainConfig):
    """TrainConfig.optimizer -> optimizer instance, shared by both trainers:
    "adam" is the reference's plain optax.adam; everything else dispatches
    through bench_utils.make_optimizer ("fused"/"pallas"/"master")."""
    if train_cfg.optimizer == "adam":
        return optax.adam(train_cfg.lr)
    from ..bench_utils import make_optimizer
    return make_optimizer(train_cfg.optimizer, train_cfg.lr)


def _setup_checkpoint(checkpoint_dir: Optional[str], state, iters: int,
                      log_fn: Callable[[str], None], *,
                      resilience: Optional[ResilienceConfig] = None,
                      stats: Optional[ResilienceStats] = None):
    """Shared resume preamble: open the orbax dir, restore the newest VALID
    step into ``state``'s layout (sharding-preserving; a corrupt latest step
    falls back to the previous one — checkpoint.py). Returns
    ``(ckpt, state, start_step, done)`` — ``done`` means the checkpoint is
    already at/past ``iters`` and there is nothing to train."""
    if checkpoint_dir is None:
        return None, state, 0, False
    from ..checkpoint import Checkpointer
    res = resilience or ResilienceConfig()
    ckpt = Checkpointer(checkpoint_dir, retry_attempts=res.retry_attempts,
                        retry_base_delay=res.retry_base_delay, stats=stats)
    start_step = 0
    if ckpt.latest_step() is not None:
        state = ckpt.restore(state)
        # The step that actually restored, NOT latest_step(): after a
        # corrupt-step fallback they differ, and resuming the loop from the
        # corrupt step's index would skip data the weights never saw.
        start_step = int(ckpt.restored_step)
        if start_step != int(ckpt.latest_step()):
            log_fn(f"latest step {int(ckpt.latest_step())} unreadable; "
                   f"fell back to step {start_step}")
        log_fn(f"resumed from step {start_step}")
    if start_step >= iters:
        log_fn(f"checkpoint already at step {start_step} >= iters {iters}; "
               "nothing to train")
        ckpt.close()
        return ckpt, state, start_step, True
    return ckpt, state, start_step, False


def _emit_manifest(telemetry, *, trainer: str, model_cfg, train_cfg,
                   mesh, start_step: int, step_fn, state, n_data: int,
                   steps_per_dispatch: int = 1, windowed: bool = False,
                   overlap_microbatches: int = 1,
                   preflight: Optional[dict] = None) -> None:
    """Open a telemetry run: one manifest event carrying the configuration
    and the step's static communication profile (telemetry/comm.py —
    measured by abstract tracing BEFORE the first real call, so the trace
    lands in the jit cache and costs nothing extra). Must run on the
    UNGUARDED step: StepGuard's host-side logic cannot be eval_shape'd.
    ``steps_per_dispatch > 1`` traces the fused K-step driver over its
    [K, B, T] window — the profile then covers one DISPATCH (K steps), with
    per-step normalization carried alongside (CommProfile.as_dict)."""
    if telemetry is None:
        return
    import dataclasses

    from ..telemetry import measure_comm
    comm_profile = None
    try:
        batch_shape = (n_data * train_cfg.batch_size, train_cfg.seq_len)
        if steps_per_dispatch > 1 or windowed:
            # ``windowed``: the elastic loop drives the [K, B, T] window
            # step even at K=1, so the trace needs the leading step axis.
            batch_shape = (steps_per_dispatch,) + batch_shape
        batch_sds = jax.ShapeDtypeStruct(batch_shape, jnp.int32)
        profile = measure_comm(step_fn, state, batch_sds)
        comm_profile = (profile.as_dict(
            steps_per_dispatch=steps_per_dispatch,
            overlap_microbatches=overlap_microbatches)
            if profile is not None else None)
    except Exception:
        pass                       # telemetry must never sink a trainer
    platform = jax.devices()[0].platform
    telemetry.events.manifest(
        trainer=trainer, jax_version=jax.__version__,
        platform=platform, n_devices=len(jax.devices()),
        mesh={k: int(v) for k, v in mesh.shape.items()},
        model_cfg=dataclasses.asdict(model_cfg),
        train_cfg=dataclasses.asdict(train_cfg),
        start_step=start_step, comm=comm_profile,
        # Roofline denominators (introspect.platform_peaks: ROOFLINE.md's
        # measured chip peaks, or a calibrated CPU baseline) — recorded
        # HERE so the jax-free readers (obs_report's attainment section,
        # slo_monitor's MFU floor) never have to re-derive them.
        peaks=introspect.platform_peaks(platform),
        # Preflight fit estimate (telemetry/memory.py, schema v9): the
        # predicted per-device byte budget, recorded next to the comm
        # profile so obs_report's memory section can table
        # preflight-vs-measured without re-deriving the model.
        **({} if preflight is None else {"preflight": preflight}))


def _fault_extra(step_fn) -> dict:
    """StepGuard trip attribution (non-finite leaf paths of the rejected
    state) as extra ``fault``-event fields — and from the stream into the
    flight-recorder bundle that dumps on it. Shared by ``_run_loop`` and
    ``_run_elastic_loop`` so the two cannot drift."""
    pop = getattr(step_fn, "pop_trip", None)
    trip = pop() if callable(pop) else None
    return {"attribution": trip} if trip else {}


def _notify_checkpoint(hook, step: int, state, log_fn) -> None:
    """Checkpoint publication hook (the train→deploy seam,
    serving/deploy.py): called after every successful periodic/final
    ``ckpt.save`` with the step index and the live state, so a serving
    fleet can pick the weights up while this run keeps training. Guarded
    like telemetry — a broken publisher loses the publication, never the
    run. Shared by ``_run_loop`` and ``_run_elastic_loop`` so the two
    cannot drift."""
    if hook is None:
        return
    try:
        hook(step, state)
    except Exception as e:
        log_fn(f"checkpoint publication hook at step {step} failed "
               f"({type(e).__name__}: {e}); continuing")


def _run_loop(step_fn, state, batches, train_cfg: TrainConfig, shard_fn, *,
              n_data: int, start_step: int, ckpt, checkpoint_every: int,
              loss_sink, sink_every: int, log_every: int, log_fn,
              warmup_steps_excluded: int,
              stats: Optional[ResilienceStats] = None,
              telemetry=None, steps_per_dispatch: int = 1,
              window_shard_fn=None, numerics=None,
              numerics_every: int = 0, compile_watch=None,
              injit_guard: bool = False,
              on_checkpoint=None, memory_meter=None) -> LLMTrainReport:
    """The training loop both trainers share: stream replay on resume,
    per-iteration loss sinking/logging, periodic + final checkpoint saves,
    and async-honest throughput accounting (the timer starts after
    ``warmup_steps_excluded`` post-resume steps, on a hard host sync).

    Self-healing (resilience/): when a checkpointer is attached, SIGTERM is
    caught at the next step boundary, a resumable checkpoint is force-saved,
    and the loop returns with ``report.preempted=True`` — re-running the
    same call resumes with data order preserved. A failed *periodic* save
    (after its internal retries) is logged and skipped rather than killing
    an otherwise healthy run; the final save still raises.

    Step indices are STREAM positions, not gradient-update counts: a
    StepGuard skip consumes its batch without learning from it, and a guard
    rollback extends that to the whole faulted window (the restored weights
    continue from the CURRENT stream position — the window's batches are
    deliberately not replayed, mirroring skip-and-count). That is what keeps
    resume deterministic: a checkpoint at step k always means "the stream
    has advanced k batches", so replay-to-k reproduces the data order no
    matter how many steps were skipped or rolled back.

    Loss buffering: device losses are held unsynced in a bounded pending
    buffer and flushed to host floats at sink boundaries (every
    ``sink_every`` steps and at the end) — the flush is where ``loss_sink``
    already forced a sync, so bounding the buffer costs no extra host round
    trips, and the old grow-O(iters) device-scalar list is gone.

    Chunked mode (``steps_per_dispatch`` = K > 1; DP trainer only): the
    step is a fused K-step driver (dp.make_multi_step /
    make_zero1_multi_step) taking a ``[K, B, T]`` window via
    ``window_shard_fn``, and every host-side decision quantizes to chunk
    edges, whose positions are absolute multiples of K so they are stable
    across resumes:

    - the per-step loss sequence comes back as the scan's stacked [K]
      output (bit-identical to per-step mode) and flushes through the same
      pending buffer, so ``loss_sink``/CSV rows land on the same step
      indices as per-step mode (delayed by at most a chunk);
    - periodic checkpoints save at the first chunk edge at/after each
      ``checkpoint_every`` boundary (exactly on it when K divides
      ``checkpoint_every``); SIGTERM force-saves at the next chunk edge;
      checkpoint step indices stay stream positions, so resume/replay is
      unchanged (a resume from a non-chunk-aligned step — e.g. a checkpoint
      written by a per-step run — realigns with one smaller first chunk);
    - StepGuard verdicts/skips and FaultPlan injection points are per
      DISPATCH: a skipped dispatch skips (consumes-not-learns) all K of its
      steps, and fault step indices count dispatches, not steps;
    - the throughput warmup exclusion quantizes up to the first chunk
      (``warmup_steps_excluded`` is treated as "at least", so compile time
      stays out of the timer either way);
    - the next chunk's host window is staged while the device runs the
      current one, so tokenization overlaps compute under async dispatch.

    Run-health introspection (``numerics`` = a
    telemetry.introspect.NumericsHandle, ``numerics_every`` > 0): the
    step's second output is ``(loss, NumericsSummary)`` — computed inside
    the same compiled dispatch, bitwise-invisible to losses/params — and
    the loop emits a ``numerics`` event every ``numerics_every`` steps
    (chunked mode samples the chunk's LAST step), plus one forced sample
    alongside every ``fault`` event so a flight-recorder bundle always
    carries the numerics state at the trip. Fault events additionally
    carry the StepGuard's ``pop_trip()`` attribution — the non-finite
    leaf PATHS of the rejected state.

    ``compile_watch`` (the step's introspect.CompileWatch, passed
    UNWRAPPED since the guard/fault layers don't delegate): a ``compute``
    span whose dispatch compiled (warmup, a tail-chunk shape) is stamped
    ``compiled=True`` so obs_report's attainment percentiles can exclude
    it — a compile-dominated interval is not an attainment sample.
    """
    report = LLMTrainReport()
    report.start_step = start_step
    report.resilience = stats if stats is not None else ResilienceStats()
    # In-jit guard accounting (``guard_nonfinite`` fused into the step —
    # ResilienceConfig.injit_guard): a skipped step's ONLY host-visible
    # trace is the non-advancing state.step counter, so snapshot it now
    # (post-restore) and diff once at the end — zero extra syncs per step.
    injit_step0 = (int(jax.device_get(state.step))
                   if injit_guard and hasattr(state, "step") else None)
    spans = Spans()  # phase accounting; absorbed into the registry at end
    # One tracing path (telemetry/trace.py): dispatch spans feed the SAME
    # phase accumulator they always did, and additionally land in the
    # event stream as a ``dispatch`` root with stage/compute/checkpoint/
    # sink children when telemetry is attached. Per-step mode samples at
    # the step-event cadence (a span per iteration would dominate the
    # stream); chunked mode traces every dispatch (already coarse).
    tracer = Tracer(telemetry.events if telemetry is not None else None,
                    phases=spans)

    def _phase(name: str, parent, span_name: str):
        if parent is not None:
            return tracer.span(span_name, parent=parent.ctx, phase=name)
        return spans(name)

    last_event_t = time.perf_counter()
    last_event_it = start_step - 1
    last_replay_beat = -math.inf  # first replayed batch always beats
    prev_counters = report.resilience.as_dict()
    last_saved = -1
    # First eligible step emits immediately; subsequent samples follow the
    # cadence. Tracked by stream position so chunked mode (which only sees
    # chunk edges) samples the first edge at/after each boundary.
    last_numerics_it = start_step - max(1, numerics_every)

    def _emit_numerics(it, aux, index=None):
        nonlocal last_numerics_it
        if aux is None or telemetry is None or numerics is None \
                or last_numerics_it == it:  # cadence + forced: one sample
            return
        try:
            telemetry.events.numerics(it=it,
                                      **numerics.event_fields(aux,
                                                              index=index))
        except Exception:
            pass                   # introspection must never sink the run
        last_numerics_it = it

    tokens_per_step = n_data * train_cfg.batch_size * train_cfg.seq_len
    t_start = None
    excluded_steps = warmup_steps_excluded
    pending = []  # (first step index, device loss scalar or [k] vector):
    #               bounded — flushed to host floats at sink boundaries; a
    #               float() per step would serialize dispatch and deflate
    #               throughput, an unbounded device list would leak buffers.

    def _flush_losses():
        for it0, ls in pending:
            for j, v in enumerate(np.atleast_1d(np.asarray(ls))):
                i, v = it0 + j, float(v)
                report.losses.append(v)
                if loss_sink is not None and (i % sink_every == 0
                                              or i == train_cfg.iters - 1):
                    loss_sink(i, v)
        pending.clear()

    # Installed with or without a checkpointer: an uncheckpointed run can't
    # force-save, but it still exits the loop cleanly on SIGTERM (counters
    # and report intact) instead of dying mid-step — a chaos run without
    # --checkpoint-dir must demo graceful preemption, not a hard kill.
    preempt = PreemptionHandler()
    last_it = start_step - 1

    def _force_save(at: int) -> None:
        # Force-save a resumable checkpoint BEFORE dying: the next
        # invocation restores step ``at`` and replays the stream.
        # A checkpoint of THIS run's lineage at ``at`` exists only
        # if this loop saved it (last_saved) or resumed from it
        # (start_step); any other on-disk step ``at`` is a stale —
        # possibly the corrupt — remnant of a pre-fallback lineage
        # that the save must replace, not trust (latest_step() alone
        # can't tell these apart after a corrupt-latest fallback).
        if ckpt is not None:
            if at not in (last_saved, start_step):
                ckpt.save(at, state, force=True, overwrite=True)
            ckpt.wait()
        report.preempted = True
        report.resilience.preemptions += 1
        log_fn(f"preempted at iter {at}: checkpoint "
               f"{'force-saved' if ckpt is not None else 'not saved'}"
               f"{'' if ckpt is not None else ' (no checkpoint dir)'}")

    if steps_per_dispatch <= 1:
        with preempt:
            for it in range(train_cfg.iters):
                droot = (tracer.start("dispatch", trace="train", it=it,
                                      phase=False)
                         if (telemetry is not None and it >= start_step
                             and it % telemetry.step_every == 0) else None)
                with _phase("data", droot, "stage"):
                    host_batch = next(batches).reshape(
                        n_data * train_cfg.batch_size, train_cfg.seq_len)
                if it < start_step:
                    # Replaying IS progress, but a beat per replayed batch
                    # would add thousands of temp-file renames to an
                    # otherwise host-only fast-forward; throttle to well
                    # under the watchdog's polling granularity.
                    if telemetry is not None:
                        now = time.perf_counter()
                        if now - last_replay_beat >= 0.5:
                            telemetry.heartbeat.beat(step=it, phase="replay")
                            last_replay_beat = now
                    continue  # resume: replay stream, preserving data order
                if preempt.requested:
                    if droot is not None:
                        droot.end(preempted=True)
                    _force_save(it)
                    break
                last_it = it
                t_iter = time.perf_counter()
                n_compiles = (len(compile_watch.compiles)
                              if compile_watch is not None else 0)
                with _phase("dispatch", droot, "compute") as csp:
                    state, out = step_fn(state, shard_fn(host_batch))
                    if (csp is not None and compile_watch is not None
                            and len(compile_watch.compiles) > n_compiles):
                        csp.attrs["compiled"] = True
                loss, naux = introspect.split_step_output(out)
                if it + 1 == start_step + warmup_steps_excluded:
                    float(loss)  # hard sync before starting the timer
                    t_start = time.perf_counter()
                    # Re-baseline the step-event window too: the time before
                    # this sync is compile + (on resume) stream replay, which
                    # would otherwise land in the first window's dt_s and
                    # dominate obs_report's step-time percentiles.
                    last_event_t, last_event_it = t_start, it
                pending.append((it, loss))
                if it % sink_every == 0 or it == train_cfg.iters - 1:
                    with _phase("sink", droot, "sink"):
                        _flush_losses()  # sink boundary: host ring update
                if log_every and it % log_every == 0:
                    log_fn(f"iter {it}: loss {float(loss):.4f}")
                if telemetry is not None:
                    # Host-side iteration wall time: dispatch + host work,
                    # NOT device completion (no sync; under async dispatch
                    # read the honest throughput from tokens_per_sec / the
                    # step events).
                    telemetry.registry.observe("host_iter_s",
                                               time.perf_counter() - t_iter)
                    telemetry.heartbeat.beat(step=it)
                    if (it % telemetry.step_every == 0
                            or it == train_cfg.iters - 1):
                        now = time.perf_counter()
                        extra = {}
                        if t_start is None:
                            # Pre-baseline window: dt_s still contains
                            # one-time compile/replay. Keep the event (its
                            # loss matters) but flag it so readers exclude
                            # it from step-time distributions (obs_report
                            # does).
                            extra["warmup"] = True
                        telemetry.events.step(
                            it=it, loss=float(loss),  # the documented sync
                            dt_s=now - last_event_t,
                            steps=it - last_event_it, **extra)
                        last_event_t, last_event_it = now, it
                        if memory_meter is not None:
                            # Memory census rides the step-event cadence:
                            # host-side byte math only (schema v9), no
                            # device sync beyond the loss read above.
                            memory_meter.sample(it=it)
                    if (naux is not None
                            and it - last_numerics_it >= numerics_every):
                        _emit_numerics(it, naux)
                    delta = report.resilience.delta(prev_counters)
                    if delta:
                        # Forced numerics sample + guard attribution ride
                        # ahead of / on the fault event, so the flight
                        # recorder's dump (triggered by it) carries both.
                        _emit_numerics(it, naux)
                        telemetry.events.fault(counters=delta, it=it,
                                               **_fault_extra(step_fn))
                        prev_counters = report.resilience.as_dict()
                if ckpt is not None and (it + 1) % checkpoint_every == 0:
                    try:
                        # overwrite: after a corrupt-latest fallback resume
                        # the loop re-treads step indices the dead lineage
                        # already wrote (start_step < it+1 <= old latest),
                        # and those stale entries must not survive as
                        # restore candidates.
                        with _phase("checkpoint", droot, "checkpoint"):
                            ckpt.save(it + 1, state, overwrite=True)
                        last_saved = it + 1
                        _notify_checkpoint(on_checkpoint, it + 1, state,
                                           log_fn)
                    except Exception as e:
                        log_fn(f"periodic checkpoint at {it + 1} failed "
                               f"after retries ({type(e).__name__}: {e}); "
                               "continuing")
                if droot is not None:
                    droot.end()
    else:
        # ------------------------------------------------- chunked mode
        # NOTE: _run_elastic_loop mirrors this block (plus the recovery
        # path) and its zero-fault contract is BITWISE equality with it —
        # a cadence/staging/checkpoint-edge change here must land there
        # too (tests/test_elastic.py pins the equality).
        K = steps_per_dispatch
        chunks = []
        edge = start_step
        while edge < train_cfg.iters:
            nxt = min(train_cfg.iters, (edge // K + 1) * K)
            chunks.append((edge, nxt))
            edge = nxt

        def _window(it0, it1, parent=None):
            with _phase("data", parent, "stage"):
                return np.stack([
                    next(batches).reshape(n_data * train_cfg.batch_size,
                                          train_cfg.seq_len)
                    for _ in range(it1 - it0)])

        staged = None
        last_flush_edge = start_step
        with preempt:
            for rep in range(start_step):   # resume: replay the stream
                next(batches)
                if telemetry is not None:
                    now = time.perf_counter()
                    if now - last_replay_beat >= 0.5:
                        telemetry.heartbeat.beat(step=rep, phase="replay")
                        last_replay_beat = now
            for ci, (it0, it1) in enumerate(chunks):
                if preempt.requested:
                    _force_save(it0)
                    break
                # One trace root per dispatch (the chunk IS the dispatch
                # granularity); children cover this chunk's host work,
                # including the NEXT window's staging — that overlap
                # landing inside the compute-bound interval is exactly
                # what the timeline should show.
                droot = (tracer.start("dispatch", trace="train", it=it0,
                                      steps=it1 - it0, phase=False)
                         if telemetry is not None else None)
                window = (staged if staged is not None
                          else _window(it0, it1, droot))
                staged = None
                t_iter = time.perf_counter()
                n_compiles = (len(compile_watch.compiles)
                              if compile_watch is not None else 0)
                with _phase("dispatch", droot, "compute") as csp:
                    state, out = step_fn(state, window_shard_fn(window))
                    if (csp is not None and compile_watch is not None
                            and len(compile_watch.compiles) > n_compiles):
                        csp.attrs["compiled"] = True
                losses, naux = introspect.split_step_output(out)
                # Stage the NEXT chunk's host window while the device runs
                # this one: under async dispatch the tokenize/stack work
                # overlaps compute instead of serializing after it.
                if ci + 1 < len(chunks):
                    staged = _window(*chunks[ci + 1], droot)
                last_it = it1 - 1
                first_chunk = t_start is None
                pending.append((it0, losses))
                if log_every:
                    for i in range(it0, it1):
                        if i % log_every == 0:
                            log_fn(f"iter {i}: "
                                   f"loss {float(losses[i - it0]):.4f}")
                if telemetry is not None:
                    telemetry.registry.observe(  # per DISPATCH (K steps)
                        "host_iter_s", time.perf_counter() - t_iter)
                    telemetry.heartbeat.beat(step=last_it)
                    if (last_it - last_event_it >= telemetry.step_every
                            or it1 == train_cfg.iters):
                        now = time.perf_counter()
                        extra = {"steps_per_dispatch": it1 - it0}
                        if first_chunk:
                            extra["warmup"] = True  # dt contains compile
                        telemetry.events.step(
                            it=last_it, loss=float(losses[-1]),
                            dt_s=now - last_event_t,
                            steps=last_it - last_event_it, **extra)
                        last_event_t, last_event_it = now, last_it
                        if memory_meter is not None:
                            # Chunk-edge memory census (host byte math
                            # only; same cadence as the step event).
                            memory_meter.sample(it=last_it)
                    if (naux is not None
                            and last_it - last_numerics_it >= numerics_every):
                        # Chunk-edge sampling: the stacked [K] summary's
                        # LAST step stands for the chunk.
                        _emit_numerics(last_it, naux, index=-1)
                    delta = report.resilience.delta(prev_counters)
                    if delta:
                        _emit_numerics(last_it, naux, index=-1)
                        telemetry.events.fault(counters=delta, it=last_it,
                                               **_fault_extra(step_fn))
                        prev_counters = report.resilience.as_dict()
                if first_chunk:
                    # Warmup exclusion quantized to the first chunk edge:
                    # compile + (on resume) replay land before this sync.
                    float(losses[-1])
                    t_start = time.perf_counter()
                    excluded_steps = it1 - it0
                    last_event_t, last_event_it = t_start, last_it
                if (it1 - last_flush_edge >= sink_every
                        or it1 == train_cfg.iters):
                    with _phase("sink", droot, "sink"):
                        _flush_losses()  # sink boundary (chunk-edge quantized)
                    last_flush_edge = it1
                if ckpt is not None and (it1 // checkpoint_every
                                         ) > (it0 // checkpoint_every):
                    try:
                        with _phase("checkpoint", droot, "checkpoint"):
                            ckpt.save(it1, state, overwrite=True)
                        last_saved = it1
                        _notify_checkpoint(on_checkpoint, it1, state, log_fn)
                    except Exception as e:
                        log_fn(f"periodic checkpoint at {it1} failed after "
                               f"retries ({type(e).__name__}: {e}); "
                               "continuing")
                if droot is not None:
                    droot.end()
    if ckpt is not None:
        if not report.preempted and train_cfg.iters != last_saved:
            ckpt.save(train_cfg.iters, state, force=True, overwrite=True)
            _notify_checkpoint(on_checkpoint, train_cfg.iters, state, log_fn)
        ckpt.close()
    _flush_losses()  # preempted/odd-tail runs: drain whatever is buffered
    report.steps = (last_it + 1 if report.preempted else train_cfg.iters) \
        - start_step
    if injit_step0 is not None:
        # Executed steps minus step-counter advances = fused-guard skips
        # (the select-back keeps state.step frozen on a bad step). One
        # scalar sync, after the loop — the skip itself never left jit.
        good = int(jax.device_get(state.step)) - injit_step0
        report.resilience.skipped_steps += max(0, report.steps - good)
    if t_start is not None and report.steps > excluded_steps:
        report.wall_time = time.perf_counter() - t_start
        timed = report.steps - excluded_steps
        report.tokens_per_sec = tokens_per_step * timed / report.wall_time
    if telemetry is not None:
        telemetry.registry.absorb_spans(spans)
        telemetry.registry.absorb_resilience(report.resilience)
        telemetry.events.run_end(
            steps=report.steps, start_step=start_step,
            preempted=report.preempted,
            tokens_per_sec=report.tokens_per_sec, wall_s=report.wall_time,
            metrics=telemetry.registry.snapshot())
        telemetry.heartbeat.beat(step=last_it + 1, phase="done")
    return report


def _run_elastic_loop(controller, step_fn, state, batches,
                      train_cfg: TrainConfig, *, n_data: int,
                      start_step: int, ckpt, checkpoint_every: int,
                      loss_sink, sink_every: int, log_every: int, log_fn,
                      warmup_steps_excluded: int,
                      stats: Optional[ResilienceStats] = None,
                      telemetry=None, steps_per_dispatch: int = 1,
                      window_shard_fn=None,
                      on_checkpoint=None, scale_hook=None,
                      memory_meter=None) -> LLMTrainReport:
    """The chunked training loop (``_run_loop`` chunked mode) with a
    replica-loss recovery path threaded through it: every dispatch runs
    under a ``ReplicaLossError``/``ReplicaReturnSignal`` catch, every
    chunk edge feeds the controller's host-RAM mirror, and a caught loss
    (or return) drains the in-flight work, hands the world to
    ``ElasticController.recover`` (``grow``) and swaps in the new
    mesh/state/step/stream before continuing. ``scale_hook(it, world)``
    is additionally polled at every chunk edge; a non-None target world
    triggers ``ElasticController.resize`` — the autoscaler's
    capacity-change path, zero steps lost (the resize snapshots the
    just-drained state at the edge itself).

    Zero-fault contract: the loss trajectory is bitwise the non-elastic
    path's — the step functions come from the same factories, the windows
    from the same stream arithmetic; the elastic extras (mirror sync at
    chunk edges, the try/except) never touch the numerics
    (tests/test_elastic.py pins it).

    Bookkeeping under recovery: step indices stay stream positions. A
    recovery that rewinds to mirror/checkpoint position ``m < failed_at``
    re-trains steps ``m..`` on the new topology with the new topology's
    stream — the loss record and CSV rows for those positions are
    REWRITTEN (``report.losses`` truncates to ``m``; sink rows follow the
    resume convention: later rows win), because the new-world trajectory
    is the run's trajectory from ``m`` on. Chunk edges stay absolute
    multiples of K, so a non-aligned recovery point realigns with one
    smaller chunk exactly like a non-aligned resume. Throughput:
    ``tokens_per_sec`` counts each topology's tokens at its own width
    (wall time includes recovery, honestly); ``post_remesh_tokens_per_sec``
    times the final topology from its first post-recovery synced chunk."""
    from ..resilience.faults import ReplicaLossError, ReplicaReturnSignal

    report = LLMTrainReport()
    report.start_step = start_step
    report.resilience = stats if stats is not None else ResilienceStats()
    spans = Spans()
    tracer = Tracer(telemetry.events if telemetry is not None else None,
                    phases=spans)

    def _phase(name: str, parent, span_name: str):
        if parent is not None:
            return tracer.span(span_name, parent=parent.ctx, phase=name)
        return spans(name)

    K = max(1, steps_per_dispatch)
    last_event_t = time.perf_counter()
    last_event_it = start_step - 1
    last_replay_beat = -math.inf
    prev_counters = report.resilience.as_dict()
    last_saved = -1
    t_start = None
    excluded_steps = warmup_steps_excluded
    timed_tokens = 0.0            # tokens after the warmup sync, per-width
    phase_t0 = None               # current-topology timer (post-remesh)
    phase_tokens = 0.0
    pending = []                  # (first step index, [k] device losses)

    def _flush_losses():
        for it0, ls in pending:
            for j, v in enumerate(np.atleast_1d(np.asarray(ls))):
                i, v = it0 + j, float(v)
                report.losses.append(v)
                if loss_sink is not None and (i % sink_every == 0
                                              or i == train_cfg.iters - 1):
                    loss_sink(i, v)
        pending.clear()

    def _window(it0, it1, parent=None):
        # Reads n_data/batches from the enclosing frame so a recovery's
        # rebinding re-points it at the survivors' stream automatically.
        with _phase("data", parent, "stage"):
            return np.stack([
                next(batches).reshape(n_data * train_cfg.batch_size,
                                      train_cfg.seq_len)
                for _ in range(it1 - it0)])

    preempt = PreemptionHandler()
    last_it = start_step - 1
    staged = None                   # (first step index, host window)
    edge = start_step

    def _swap(resume):
        # Install a Resume's world — shared by the fault paths (loss /
        # return) and the scale_hook resize. Step indices stay stream
        # positions: the record truncates to the resume point ``m`` and
        # every cursor rewinds with it (a fault path can land below the
        # current edge; a resize lands exactly ON it and truncates
        # nothing).
        nonlocal n_data, state, step_fn, window_shard_fn, batches, \
            last_it, last_flush_edge, last_event_t, last_event_it, \
            phase_t0, phase_tokens, staged, edge
        n_data = resume.n_data
        state, step_fn = resume.state, resume.step_fn
        window_shard_fn, batches = resume.window_shard_fn, resume.batches
        m = resume.step
        pending[:] = [p for p in pending if p[0] < m]
        # The loss record indexes from report.start_step; a slow-path
        # rewind can land BELOW it (digest-failed newest step → older
        # checkpoint), in which case the record now begins at m and
        # start_step must follow or every consumer (hw1b's sink rows,
        # report.steps) mislabels by the gap.
        del report.losses[max(0, m - report.start_step):]
        report.start_step = min(report.start_step, m)
        report.remeshes.append(resume.record.as_dict())
        # Rewind the progress cursor too: steps in [m, detected_at) were
        # discarded with the old topology, and a preemption landing
        # before they are re-trained must report/force-save position m,
        # not the rolled-back high-water mark.
        last_it = m - 1
        last_flush_edge = min(last_flush_edge, m)
        last_event_t = time.perf_counter()
        last_event_it = m - 1
        phase_t0, phase_tokens = None, 0.0
        staged = None               # old width, old stream
        edge = m

    def _force_save(at: int) -> None:
        if ckpt is not None:
            if at not in (last_saved, start_step):
                ckpt.save(at, state, force=True, overwrite=True)
            ckpt.wait()
        report.preempted = True
        report.resilience.preemptions += 1
        log_fn(f"preempted at iter {at}: checkpoint "
               f"{'force-saved' if ckpt is not None else 'not saved'}"
               f"{'' if ckpt is not None else ' (no checkpoint dir)'}")

    with preempt:
        for rep in range(start_step):   # resume: replay the stream
            next(batches)
            if telemetry is not None:
                now = time.perf_counter()
                if now - last_replay_beat >= 0.5:
                    telemetry.heartbeat.beat(step=rep, phase="replay")
                    last_replay_beat = now
        # Seed the mirror with the initial state: a loss on the very
        # first dispatch must be recoverable without a checkpoint.
        controller.note_edge(start_step, state)
        edge = start_step
        staged = None               # (first step index, host window)
        last_flush_edge = start_step
        dispatch_idx = 0
        while edge < train_cfg.iters:
            if preempt.requested:
                _force_save(edge)
                break
            it0, it1 = edge, min(train_cfg.iters, (edge // K + 1) * K)
            droot = (tracer.start("dispatch", trace="train", it=it0,
                                  steps=it1 - it0, phase=False)
                     if telemetry is not None else None)
            if staged is not None and staged[0] == it0:
                window = staged[1]
            else:
                window = _window(it0, it1, droot)
            staged = None
            t_iter = time.perf_counter()
            this_dispatch, dispatch_idx = dispatch_idx, dispatch_idx + 1
            try:
                with _phase("dispatch", droot, "compute"):
                    state, losses = step_fn(state,
                                            window_shard_fn(window))
            except (ReplicaLossError, ReplicaReturnSignal) as err:
                grow = isinstance(err, ReplicaReturnSignal)
                if droot is not None:
                    droot.end(**{"replica_return" if grow
                                 else "replica_loss": True})
                with spans("recover"):
                    # Drain: settle in-flight work AND keep the host
                    # copies — the device arrays belong to the old
                    # topology, and a flush after recovery must not
                    # re-read buffers a real backend failure took away.
                    pending[:] = [(i0, np.asarray(ls))
                                  for i0, ls in pending]
                    handle = controller.grow if grow else controller.recover
                    resume = handle(err, failed_at=it0,
                                    dispatch=this_dispatch)
                _swap(resume)
                continue
            tokens_per_step = (n_data * train_cfg.batch_size
                               * train_cfg.seq_len)
            last_it = it1 - 1
            first_chunk = t_start is None
            pending.append((it0, losses))
            if it1 < train_cfg.iters:
                # Stage the NEXT chunk's host window while the device runs
                # this one (same overlap as the non-elastic chunked loop);
                # a recovery discards it — wrong width, wrong stream.
                nxt = min(train_cfg.iters, (it1 // K + 1) * K)
                staged = (it1, _window(it1, nxt, droot))
            if log_every:
                for i in range(it0, it1):
                    if i % log_every == 0:
                        log_fn(f"iter {i}: "
                               f"loss {float(losses[i - it0]):.4f}")
            if telemetry is not None:
                telemetry.registry.observe(
                    "host_iter_s", time.perf_counter() - t_iter)
                telemetry.heartbeat.beat(step=last_it)
                if (last_it - last_event_it >= telemetry.step_every
                        or it1 == train_cfg.iters):
                    now = time.perf_counter()
                    extra = {"steps_per_dispatch": it1 - it0}
                    if first_chunk or (report.remeshes
                                       and phase_t0 is None):
                        extra["warmup"] = True  # compile / re-mesh compile
                    telemetry.events.step(
                        it=last_it, loss=float(losses[-1]),
                        dt_s=now - last_event_t,
                        steps=last_it - last_event_it, **extra)
                    last_event_t, last_event_it = now, last_it
                    if memory_meter is not None:
                        # Chunk-edge census; the elastic extras — mirror
                        # bytes and the current world — make grow/shrink
                        # memory deltas visible in the event stream.
                        memory_meter.sample(
                            it=last_it, world=n_data,
                            mirror_bytes=controller.mirror_bytes())
                delta = report.resilience.delta(prev_counters)
                if delta:
                    telemetry.events.fault(counters=delta, it=last_it,
                                           **_fault_extra(step_fn))
                    prev_counters = report.resilience.as_dict()
            if first_chunk:
                float(losses[-1])   # sync: compile/replay stay untimed
                t_start = time.perf_counter()
                excluded_steps = it1 - it0
                last_event_t, last_event_it = t_start, last_it
                if not report.remeshes:
                    phase_t0 = t_start
            elif phase_t0 is None:
                # First completed chunk on a new topology: its dt is
                # dominated by the re-mesh recompile; sync and start the
                # post-remesh throughput window after it.
                float(losses[-1])
                phase_t0 = time.perf_counter()
            else:
                timed_tokens += (it1 - it0) * tokens_per_step
                phase_tokens += (it1 - it0) * tokens_per_step
            controller.note_edge(it1, state)   # last-good mirror refresh
            if (it1 - last_flush_edge >= sink_every
                    or it1 == train_cfg.iters):
                with _phase("sink", droot, "sink"):
                    _flush_losses()
                last_flush_edge = it1
            if ckpt is not None and (it1 // checkpoint_every
                                     ) > (it0 // checkpoint_every):
                try:
                    with _phase("checkpoint", droot, "checkpoint"):
                        ckpt.save(it1, state, overwrite=True)
                    last_saved = it1
                    _notify_checkpoint(on_checkpoint, it1, state, log_fn)
                except Exception as e:
                    log_fn(f"periodic checkpoint at {it1} failed after "
                           f"retries ({type(e).__name__}: {e}); "
                           "continuing")
            if scale_hook is not None and it1 < train_cfg.iters:
                # Capacity-change seam (resilience/autoscale.py): the
                # hook sees the just-drained edge; a differing target
                # world re-meshes HERE — state snapshotted at this exact
                # position, so nothing is replayed and nothing is lost.
                target = scale_hook(it1, n_data)
                if target is not None and int(target) != n_data:
                    with spans("recover"):
                        pending[:] = [(i0, np.asarray(ls))
                                      for i0, ls in pending]
                        resume = controller.resize(
                            int(target), state=state, at_step=it1,
                            dispatch=dispatch_idx - 1)
                    if resume is not None:
                        if droot is not None:
                            droot.end(scaled=True)
                        _swap(resume)
                        continue
            if droot is not None:
                droot.end()
            edge = it1
    if ckpt is not None:
        if not report.preempted and train_cfg.iters != last_saved:
            ckpt.save(train_cfg.iters, state, force=True, overwrite=True)
            _notify_checkpoint(on_checkpoint, train_cfg.iters, state, log_fn)
        ckpt.close()
    _flush_losses()
    t_end = time.perf_counter()
    # report.start_step, not the local: a slow-path recovery may have
    # rewound the record's origin below the resumed-from step.
    report.steps = (last_it + 1 if report.preempted else train_cfg.iters) \
        - report.start_step
    if t_start is not None and report.steps > excluded_steps:
        report.wall_time = t_end - t_start
        report.tokens_per_sec = timed_tokens / max(report.wall_time, 1e-9)
    if report.remeshes and phase_t0 is not None and phase_tokens > 0:
        report.post_remesh_tokens_per_sec = (
            phase_tokens / max(t_end - phase_t0, 1e-9))
    if telemetry is not None:
        telemetry.registry.absorb_spans(spans)
        telemetry.registry.absorb_resilience(report.resilience)
        telemetry.events.run_end(
            steps=report.steps, start_step=report.start_step,
            preempted=report.preempted, remeshes=len(report.remeshes),
            tokens_per_sec=report.tokens_per_sec, wall_s=report.wall_time,
            post_remesh_tokens_per_sec=report.post_remesh_tokens_per_sec,
            metrics=telemetry.registry.snapshot())
        telemetry.heartbeat.beat(step=last_it + 1, phase="done")
    return report


def _apply_resilience(step_fn, resilience: Optional[ResilienceConfig],
                      fault_plan, ckpt, stats: ResilienceStats, *,
                      start: int = 0):
    """Compose the resilience layer around a trainer's step function:
    fault injection innermost (so the guard sees the faulted step — the two
    halves test each other), StepGuard outermost. ``fault_plan`` may come in
    as an object (tests) or via ``resilience.faults`` (CLI/config); fault
    step indices are post-resume call indices. ``start`` offsets the fault
    wrapper's dispatch counter — the elastic loop re-applies this to a step
    function REBUILT mid-run, and already-delivered faults must not
    re-fire (the StepGuard starts fresh either way: its EMA detector must
    re-learn the new topology's update norms)."""
    if fault_plan is None and resilience is not None and resilience.faults:
        fault_plan = resilience.fault_plan()
    if fault_plan:
        step_fn = fault_plan.wrap_step(step_fn, start=start)
    if resilience is not None and resilience.guard:
        from ..resilience.guard import StepGuard
        step_fn = StepGuard(
            step_fn, ckpt=ckpt, stats=stats,
            max_consecutive_bad=resilience.max_consecutive_bad,
            ema_decay=resilience.ema_decay,
            anomaly_factor=resilience.anomaly_factor,
            ema_warmup=resilience.ema_warmup)
    return step_fn


def train_llm_dp(model_cfg: Optional[LlamaConfig] = None,
                 train_cfg: Optional[TrainConfig] = None, *,
                 mesh=None,
                 tokenizer=None,
                 aggregation: str = "gradient",
                 log_every: int = 100,
                 log_fn: Callable[[str], None] = print,
                 warmup_steps_excluded: int = 2,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1000,
                 loss_sink: Optional[Callable[[int, float], None]] = None,
                 sink_every: int = 10,
                 resilience: Optional[ResilienceConfig] = None,
                 fault_plan=None,
                 telemetry=None,
                 on_checkpoint=None,
                 scale_hook=None) -> LLMTrainReport:
    """Run DP tiny-Llama training; returns losses and throughput.

    ``aggregation``: "gradient" (allreduce grads — intro_DP_GA), "weight"
    (allreduce weights post-step — intro_DP_WA's intended semantics), or
    "zero1" (ZeRO-1 sharded weight update, dp.make_zero1_step: gradients
    reduce-scattered, Adam applied to each replica's 1/N slice with
    optimizer state sharded from init, fresh params all-gathered — N× less
    optimizer memory and update FLOPs at allreduce-parity wire bytes).

    ``train_cfg.steps_per_dispatch`` = K > 1 turns on the fused multi-step
    driver (gradient/zero1 aggregation, fp32 wire only): K steps scanned in
    one compiled, donated dispatch over a [K, B, T] batch window, host work
    quantized to chunk edges — semantics spelled out in ``_run_loop``.

    ``train_cfg.overlap_microbatches`` = M >= 1 routes gradient sync
    through the overlapped ring driver (parallel/compress.py
    ``make_overlap_step`` / ``make_overlap_multi_step``): the batch splits
    into M microbatches whose grad computes overlap the previous
    microbatch's ppermute-pipelined ring reduce-scatter, with in-flight
    chunks in the ``wire`` format — the one path where wire compression
    composes with zero1 AND steps_per_dispatch. int8 EF residuals live in
    the state tree, so checkpoints/preemption carry them exactly. Replaces
    ``accum_steps`` (same batch axis); ``numerics_every``, the fused
    ``injit_guard`` and ``resilience.elastic`` all compose (elastic
    reshards the EF residual trees across re-meshes).

    ``train_cfg.dcn`` = D > 1 makes the DP world HIERARCHICAL: D ICI
    islands of ``data`` replicas bridged by DCN (hier_data_mesh), with
    gradient sync through the TWO-LEVEL ring driver (requires
    ``overlap_microbatches`` >= 1) — full-precision reduce-scatter within
    each island (``wire``: fp32/bf16), the exchange across the DCN axis
    in ``wire_dcn`` (int8+EF is the headline: ~1/S of the vector crosses
    DCN, at one byte/element), then the intra-island gather. The
    telemetry comm profile attributes bytes per mesh axis, so the DCN
    budget is first-class (manifest ``comm.axes``, gated in
    experiments/comm_wire_smoke.py).

    ``loss_sink(it, loss)`` fires every ``sink_every`` iterations with the
    host-synced loss — for incremental result recording that survives a
    killed run (each call forces a device sync; use only where the step
    time dwarfs it, e.g. the oversubscribed virtual-CPU mesh).

    ``checkpoint_dir`` enables orbax checkpoint/resume (the persistence layer
    the reference lacks, SURVEY.md §5.4): the newest VALID step in the
    directory is restored into the mesh layout before training (a corrupt
    latest step falls back — checkpoint.py), a checkpoint is written every
    ``checkpoint_every`` steps and at the end, and already-completed
    iterations are skipped — re-running the same call after an interruption
    continues where it stopped. SIGTERM mid-loop force-saves a resumable
    checkpoint and returns with ``report.preempted=True``.

    ``resilience`` (config.ResilienceConfig) wraps the step in a StepGuard
    (skip non-finite steps, EMA spike detection, rollback after K
    consecutive bad steps) and carries the checkpoint-IO retry budget.
    ``fault_plan`` (resilience.FaultPlan) injects deterministic faults for
    tests/chaos runs; counters come back in ``report.resilience``.

    ``resilience.elastic=True`` (gradient/zero1 only) survives replica
    loss: a ``device_loss`` fault (or any ``ReplicaLossError``) at
    dispatch k drains the loop at the chunk edge, re-meshes onto the
    surviving devices, reshards params + ZeRO-1 optimizer state to the
    new world size (host-RAM mirror fast path / checkpoint slow path —
    resilience/elastic.py), re-splits the stream and resumes; recovery
    records land in ``report.remeshes`` and the telemetry ``remesh``
    event. With zero faults the elastic loop's losses are bitwise the
    non-elastic path's. Elasticity is bidirectional: a ``device_return``
    fault (or any ``ReplicaReturnSignal``) grows the mesh back onto
    returned devices through the same machinery, with the same bitwise
    bar; with ``overlap_microbatches >= 1`` the compressed-wire ring
    driver composes too (EF residuals reshard alongside the moments).

    ``scale_hook(it, world)`` (requires ``resilience.elastic=True``) is
    the autoscaler's capacity-change seam: polled at every chunk edge
    with the just-drained stream position and current data world; a
    non-None return is the TARGET world, and the loop re-meshes to it via
    ``ElasticController.resize`` — snapshot at the edge, reshard, zero
    steps lost — before continuing (resilience/autoscale.py drives this
    from serving-side SLO pressure).

    ``telemetry`` (telemetry.Telemetry) opens the run's observability
    surface: a manifest event with the step's static comm profile, per-step
    records + heartbeat from the loop, fault deltas, and a run_end metrics
    snapshot — render with ``python -m experiments.obs_report <dir>``.

    ``on_checkpoint(step, state)`` is the checkpoint PUBLICATION hook —
    the train→deploy seam (serving/deploy.py): called after every
    successful periodic and final save (requires ``checkpoint_dir``), so
    a ``CheckpointPublisher`` can stream params-only snapshots to a
    serving fleet that hot-swaps them live. Guarded: a broken hook is
    logged and skipped, never fatal.
    """
    tok = tokenizer or load_tokenizer()
    model_cfg = (model_cfg or LlamaConfig()).replace(vocab_size=tok.vocab_size)
    train_cfg = train_cfg or TrainConfig()
    if mesh is None:
        if train_cfg.dcn > 1:
            # Hierarchical DP: dcn ICI islands of ``data`` replicas,
            # bridged by DCN (parallel/distributed.py:hier_data_mesh).
            from ..parallel.distributed import hier_data_mesh
            mesh = hier_data_mesh(train_cfg.dcn, train_cfg.data)
        else:
            mesh = make_mesh({"data": train_cfg.data})
    n_dcn = mesh.shape.get("dcn", 1)
    # The TOTAL data-parallel world — stream splits, batch shapes and
    # token accounting all run at dcn·data width on a hierarchical mesh.
    n_data = mesh.shape.get("data", 1) * n_dcn
    if train_cfg.wire_dcn and "dcn" not in mesh.shape:
        raise ValueError(
            "wire_dcn selects the DCN tier of a hierarchical mesh; set "
            "TrainConfig.dcn > 1 (or pass a hier_data_mesh)")
    if train_cfg.dcn > 1 and "dcn" not in mesh.shape:
        # Same bar as the wire_dcn check above: silently training the
        # flat ring while the config ASKS for islands would fake a
        # hierarchical measurement (no comm.axes, no DCN tier).
        raise ValueError(
            f"TrainConfig.dcn={train_cfg.dcn} but the supplied mesh has "
            "no 'dcn' axis — pass a hier_data_mesh (or drop the explicit "
            "mesh and let the trainer build one)")
    hier = n_dcn > 1 or (bool(train_cfg.wire_dcn) and "dcn" in mesh.shape)

    params = llama.init_llama(jax.random.key(train_cfg.seed), model_cfg)
    optimizer = _make_trainer_optimizer(train_cfg)

    def loss_fn(p, batch):
        # Fused head+CE: never materializes the [B, T, V] logits (the step's
        # dominant HBM tensor at real vocab sizes). Equivalent math to
        # causal_lm_loss(llama.forward(...)) — asserted in tests/test_core.py.
        return llama.forward_loss(p, batch, model_cfg)

    spd = train_cfg.steps_per_dispatch
    if spd < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1 (got {spd})")
    ovl = train_cfg.overlap_microbatches
    if ovl < 0:
        raise ValueError(f"overlap_microbatches must be >= 0 (got {ovl})")
    cb = train_cfg.comm_buckets
    if cb < 1:
        raise ValueError(f"comm_buckets must be >= 1 (got {cb})")
    if cb > 1 and ovl == 0:
        raise ValueError(
            "comm_buckets > 1 is a property of the overlap/ring driver "
            "(the bucketed backward splits each microbatch's ring) — set "
            f"overlap_microbatches >= 1 (got comm_buckets={cb} with "
            "overlap_microbatches=0)")
    elastic = bool(resilience is not None and resilience.elastic)
    if hier and ovl == 0:
        raise ValueError(
            "a hierarchical mesh (TrainConfig.dcn > 1 / wire_dcn) routes "
            "gradient sync through the two-level ring driver: set "
            "overlap_microbatches >= 1")
    numerics = None
    if train_cfg.numerics_every > 0:
        # In-jit run-health numerics (telemetry/introspect.py): supported
        # wherever a shared step body computes it — gradient/zero1 on the
        # fp32 wire, AND the overlap/ring drivers at any wire format and
        # topology (the summary rides the step outputs; the ring schedule
        # is untouched). Hard errors elsewhere, not silent no-ops: a
        # chaos run that THINKS it is instrumented but isn't would
        # produce attribution-free bundles.
        if aggregation not in ("gradient", "zero1"):
            raise ValueError("numerics_every requires gradient or zero1 "
                             f"aggregation (got {aggregation!r})")
        if ovl == 0 and train_cfg.wire != "fp32":
            raise ValueError(
                "numerics_every requires wire='fp32' on the legacy "
                "per-step compressed paths (they own their collective "
                "schedules) — overlap_microbatches >= 1 is the composing "
                "path")
        if elastic:
            raise ValueError("numerics_every does not compose with "
                             "elastic mode yet")
        if ovl:
            # Overlap/ring drivers: local gradients differ per shard in
            # BOTH aggregations, so the summarizer psum-agrees grad stats
            # over every data axis of the (possibly hierarchical) mesh.
            psum_axis = ("dcn", "data") if hier else "data"
        else:
            psum_axis = "data" if aggregation == "zero1" else None
        numerics = introspect.make_summarizer(params, psum_axis=psum_axis)
    injit_guard = bool(resilience is not None and resilience.injit_guard)
    if injit_guard:
        # The fused in-jit skip (parallel/{dp,compress}.py
        # guard_nonfinite): select-back without leaving jit, the
        # non-advancing step counter counted into
        # ResilienceStats.skipped_steps at the end-of-run sync.
        if resilience.guard:
            raise ValueError(
                "injit_guard and guard are mutually exclusive skip "
                "mechanisms (the host StepGuard would double-count the "
                "fused skip); set ResilienceConfig(guard=False) to use "
                "the in-jit guard")
        if elastic:
            raise ValueError("injit_guard does not compose with elastic "
                             "mode (the remesh path rebuilds its own "
                             "steps)")
        if aggregation not in ("gradient", "zero1"):
            raise ValueError("injit_guard requires gradient or zero1 "
                             f"aggregation (got {aggregation!r})")
        if ovl == 0 and train_cfg.wire != "fp32":
            raise ValueError(
                "injit_guard is not fused into the legacy per-step "
                "compressed paths — overlap_microbatches >= 1 is the "
                "composing path")
    if scale_hook is not None and not elastic:
        raise ValueError("scale_hook requires resilience.elastic=True — "
                         "capacity changes ride the elastic re-mesh "
                         "machinery")
    if elastic:
        # Elastic DP (resilience/elastic.py): the loop drives the [K, B, T]
        # window step (K = steps_per_dispatch, 1 included) so replica-loss
        # drain/recovery quantizes to chunk edges. Gradient/zero1 only —
        # the weight-aggregation step owns a collective schedule nobody
        # has taught to re-mesh. Compressed wire composes through the
        # overlap/ring driver: its EF residual trees reshard N→M with the
        # ZeRO-1 moments (parallel/dp.py reshard_state's ring-residual
        # pre-pass), so elastic × int8_ef is a supported pairing.
        if aggregation not in ("gradient", "zero1"):
            raise ValueError("elastic mode supports gradient and zero1 "
                             f"aggregation only (got {aggregation!r})")
        if train_cfg.wire != "fp32" and ovl == 0:
            raise ValueError(
                f"elastic=True composes with wire={train_cfg.wire!r} only "
                "through the overlap/ring driver, whose EF residual trees "
                "(OverlapEFState.ring_residual/gather_residual) the remesh "
                "path reshards N→M alongside the ZeRO-1 moments — the "
                "legacy per-step compressed paths own collective schedules "
                "nobody re-meshes. Set overlap_microbatches >= 1, or use "
                "wire='fp32'")
        if any(s > 1 for a, s in mesh.shape.items() if a != "data"):
            raise ValueError("elastic mode supports data-axis-only meshes "
                             f"(got {dict(mesh.shape)})")
        # Pin the init params to host memory (see the PP elastic path):
        # device_put can alias a compatibly-placed leaf into the first
        # build's donated state, deleting the buffer a rebuild needs.
        params = jax.tree.map(np.asarray, params)

        def _build_elastic(m):
            """(template_state, raw window step, window shard fn) on an
            arbitrary data mesh — initial build AND post-remesh rebuild go
            through here, so the two cannot drift."""
            if ovl >= 1:
                from ..parallel import compress
                st, fn = compress.make_overlap_multi_step(
                    loss_fn, optimizer, m, params, microbatches=ovl,
                    wire=train_cfg.wire, aggregation=aggregation,
                    comm_buckets=cb)
            elif aggregation == "zero1":
                st, fn = dp.make_zero1_multi_step(loss_fn, optimizer, m,
                                                  params)
            else:
                fn = dp.make_multi_step(loss_fn, optimizer, m,
                                        accum_steps=train_cfg.accum_steps)
                st = dp.replicate(m, dp.init_state(params, optimizer))
            # Each (re)build gets its own CompileWatch: the post-remesh
            # recompile is then a visible ``compile`` event in the stream,
            # world-size-tagged — no retrace budget (tail chunks + remesh
            # recompiles are legitimate).
            fn = introspect.watch(
                fn, name=f"train/dp-{aggregation}-elastic"
                         + (f"-ring{train_cfg.wire}-m{ovl}" if ovl else "")
                         + (f"-b{cb}" if cb > 1 else "")
                         + f"-w{m.shape['data']}",
                max_caches=None,
                events=(telemetry.events if telemetry is not None
                        else None),
                meta={"steps_per_dispatch": spd},
                meta_fn=lambda st, w: {"steps_per_dispatch":
                                       int(w.shape[0])})
            return st, fn, (lambda w, m=m: dp.shard_batch_window(m, w))
    state = None
    if ovl >= 1:
        # Overlapped+compressed gradient sync (parallel/compress.py ring
        # driver): the one path where wire ∈ {fp32, bf16, int8_ef}
        # composes with aggregation ∈ {gradient, zero1} AND
        # steps_per_dispatch. Microbatching replaces accum_steps (both
        # split the same batch axis); hard errors, not asserts.
        if aggregation not in ("gradient", "zero1"):
            raise ValueError("overlap_microbatches supports gradient and "
                             f"zero1 aggregation only (got {aggregation!r})")
        if train_cfg.accum_steps != 1:
            raise ValueError("overlap_microbatches replaces accum_steps "
                             "(both split the local batch axis); set "
                             "accum_steps=1")
        from ..parallel import compress
        # Per-axis wire on the hierarchical mesh: the ICI tier rides
        # ``wire``, the scarce DCN tier ``wire_dcn`` (default fp32).
        wire_arg = ({"ici": train_cfg.wire,
                     "dcn": train_cfg.wire_dcn or "fp32"}
                    if hier else train_cfg.wire)
        if elastic:
            state, step_fn, window_shard = _build_elastic(mesh)
        elif spd > 1:
            state, step_fn = compress.make_overlap_multi_step(
                loss_fn, optimizer, mesh, params, microbatches=ovl,
                wire=wire_arg, aggregation=aggregation, comm_buckets=cb,
                guard_nonfinite=injit_guard, numerics=numerics)
        else:
            state, step_fn = compress.make_overlap_step(
                loss_fn, optimizer, mesh, params, microbatches=ovl,
                wire=wire_arg, aggregation=aggregation, comm_buckets=cb,
                guard_nonfinite=injit_guard, numerics=numerics)
    elif train_cfg.wire != "fp32":
        # Compressed gradient allreduce (parallel/compress.py) — gradient
        # aggregation only, and accumulation stays at 1 (the compressed
        # steps own their collective schedule). Hard errors, not asserts:
        # a stripped assert (python -O) would silently run the wrong
        # aggregation algorithm.
        if aggregation != "gradient" or train_cfg.accum_steps != 1 \
                or spd != 1:
            raise ValueError(
                "wire compression requires gradient aggregation without "
                "accumulation or multi-step dispatch (got "
                f"aggregation={aggregation!r}, "
                f"accum_steps={train_cfg.accum_steps}, "
                f"steps_per_dispatch={spd}) — overlap_microbatches >= 1 "
                "is the composing path")
        from ..parallel import compress
        if train_cfg.wire == "bf16":
            step_fn = compress.make_bf16_grad_step(loss_fn, optimizer, mesh)
        elif train_cfg.wire == "int8_ef":
            state = compress.init_ef_state(mesh, params, optimizer)
            step_fn = compress.make_int8_ef_grad_step(loss_fn, optimizer,
                                                      mesh)
        else:
            raise ValueError(f"unknown wire format {train_cfg.wire!r}")
    elif aggregation == "zero1":
        if train_cfg.accum_steps != 1:
            raise ValueError("accum_steps composes with gradient "
                             "aggregation only (zero1 scatters the raw "
                             "local gradient)")
        if elastic:
            state, step_fn, window_shard = _build_elastic(mesh)
        elif spd > 1:
            state, step_fn = dp.make_zero1_multi_step(
                loss_fn, optimizer, mesh, params,
                guard_nonfinite=injit_guard, numerics=numerics)
        else:
            state, step_fn = dp.make_zero1_step(
                loss_fn, optimizer, mesh, params,
                guard_nonfinite=injit_guard, numerics=numerics)
    elif aggregation == "gradient":
        if elastic:
            state, step_fn, window_shard = _build_elastic(mesh)
        elif spd > 1:
            step_fn = dp.make_multi_step(
                loss_fn, optimizer, mesh, accum_steps=train_cfg.accum_steps,
                guard_nonfinite=injit_guard, numerics=numerics)
        else:
            step_fn = dp.make_grad_aggregation_step(
                loss_fn, optimizer, mesh, accum_steps=train_cfg.accum_steps,
                guard_nonfinite=injit_guard, numerics=numerics)
    elif aggregation == "weight":
        if train_cfg.accum_steps != 1:
            raise ValueError("accum_steps needs gradient aggregation")
        if spd != 1:
            raise ValueError("steps_per_dispatch > 1 supports gradient and "
                             "zero1 aggregation only")
        step_fn = dp.make_weight_aggregation_step(loss_fn, optimizer, mesh)
    else:
        raise ValueError(f"unknown aggregation {aggregation!r}: expected "
                         "'gradient', 'weight' or 'zero1'")
    if state is None:
        state = dp.replicate(mesh, dp.init_state(params, optimizer))

    if not elastic:
        # Compile/retrace observability (introspect.CompileWatch): every
        # XLA compilation of the hot-path step becomes a ``compile`` event
        # (wall seconds, HLO flops/bytes for attainment, cache-hit vs
        # retrace). Per-step mode promises ONE compiled program
        # (max_caches=1 — growth past it is a retrace bug); chunked mode
        # legitimately compiles a tail-chunk shape, so no budget there.
        # The elastic path wraps inside _build_elastic instead (each
        # re-mesh rebuild gets its own watch). Transparent to
        # measure_comm/eval_shape — attribute access delegates.
        step_fn = introspect.watch(
            step_fn,
            name=f"train/dp-{aggregation}"
                 + (f"-k{spd}" if spd > 1 else "")
                 + ((f"-hier{n_dcn}x{mesh.shape['data']}"
                     f"-{train_cfg.wire}/{train_cfg.wire_dcn or 'fp32'}"
                     f"-m{ovl}") if hier else
                    (f"-ring{train_cfg.wire}-m{ovl}" if ovl else ""))
                 + (f"-b{cb}" if cb > 1 else ""),
            max_caches=(1 if spd == 1 else None),
            events=(telemetry.events if telemetry is not None else None),
            # Chunked mode stamps each compile event with the COMPILING
            # call's actual window size — a tail chunk's smaller program
            # must not be normalized as a full-K one (slo_monitor's
            # per-step MFU arithmetic divides flops by this).
            meta={"steps_per_dispatch": spd},
            meta_fn=(None if spd == 1 else
                     (lambda st, w: {"steps_per_dispatch":
                                     int(w.shape[0])})))
    compile_watch = step_fn if not elastic else None

    stats = ResilienceStats()
    ckpt, state, start_step, done = _setup_checkpoint(
        checkpoint_dir, state, train_cfg.iters, log_fn,
        resilience=resilience, stats=stats)
    if done:
        return LLMTrainReport(resilience=stats)
    # Memory observability (telemetry/memory.py): the preflight fit
    # estimate lands in the manifest (obs_report tables it against the
    # measured compile-event footprint), and its per-device state figures
    # seed the live meter that samples at every step-event cadence point.
    # Both are guarded — a backend that can't account bytes degrades to
    # None/empty, never blocks training.
    pre = memory_meter = None
    if telemetry is not None:
        from ..telemetry import memory as memlib
        pre = memlib.preflight(model_cfg, train_cfg, mesh=mesh,
                               aggregation=aggregation)
        memory_meter = memlib.MemoryMeter(telemetry.events, source="train")
        if pre is not None:
            memory_meter.note(params_bytes=pre["params_bytes"],
                              opt_state_bytes=pre["opt_state_bytes"],
                              residual_bytes=pre["residual_bytes"] or None,
                              window_bytes=pre["window_bytes"] or None)
    _emit_manifest(telemetry, trainer="dp", model_cfg=model_cfg,
                   train_cfg=train_cfg, mesh=mesh, start_step=start_step,
                   step_fn=step_fn, state=state, n_data=n_data,
                   steps_per_dispatch=spd, windowed=elastic,
                   overlap_microbatches=max(1, ovl), preflight=pre)
    if fault_plan is None and resilience is not None and resilience.faults:
        fault_plan = resilience.fault_plan()   # resolve ONCE: the elastic
        #   rebuild must re-wrap the same schedule, not a fresh counter's

    def _make_batches(n):
        # Disjoint stream windows per data shard — the reference's
        # skip=rank*5000. Recovery re-splits at the new width through
        # this same constructor, so the post-remesh data order is exactly
        # a fresh n-replica run's.
        return sharded_batches(tok, train_cfg.batch_size, train_cfg.seq_len,
                               n, shard_skip=5000, seed=train_cfg.seed)

    if elastic:
        from ..resilience.elastic import ElasticController

        def _rewrap(fn, start=0):
            return _apply_resilience(fn, resilience, fault_plan, ckpt,
                                     stats, start=start)

        controller = ElasticController(
            mesh, build=_build_elastic, rewrap=_rewrap,
            make_batches=_make_batches, ckpt=ckpt,
            mirror_every=resilience.mirror_every, stats=stats,
            telemetry=telemetry, log_fn=log_fn)
        return _run_elastic_loop(
            controller, _rewrap(step_fn), state, _make_batches(n_data),
            train_cfg, n_data=n_data, start_step=start_step, ckpt=ckpt,
            checkpoint_every=checkpoint_every, loss_sink=loss_sink,
            sink_every=sink_every, log_every=log_every, log_fn=log_fn,
            warmup_steps_excluded=warmup_steps_excluded, stats=stats,
            telemetry=telemetry, steps_per_dispatch=spd,
            window_shard_fn=window_shard, on_checkpoint=on_checkpoint,
            scale_hook=scale_hook, memory_meter=memory_meter)
    step_fn = _apply_resilience(step_fn, resilience, fault_plan, ckpt, stats)

    batches = _make_batches(n_data)
    return _run_loop(step_fn, state, batches, train_cfg,
                     lambda b: dp.shard_batch(mesh, b), n_data=n_data,
                     start_step=start_step, ckpt=ckpt,
                     checkpoint_every=checkpoint_every, loss_sink=loss_sink,
                     sink_every=sink_every, log_every=log_every,
                     log_fn=log_fn,
                     warmup_steps_excluded=warmup_steps_excluded,
                     stats=stats, telemetry=telemetry,
                     steps_per_dispatch=spd,
                     window_shard_fn=lambda w: dp.shard_batch_window(mesh, w),
                     numerics=numerics,
                     numerics_every=train_cfg.numerics_every,
                     compile_watch=compile_watch,
                     injit_guard=injit_guard,
                     on_checkpoint=on_checkpoint,
                     memory_meter=memory_meter)


def train_llm_pp(model_cfg: Optional[LlamaConfig] = None,
                 train_cfg: Optional[TrainConfig] = None, *,
                 mesh=None,
                 tokenizer=None,
                 schedule: str = "gpipe",
                 aggregation: str = "gradient",
                 log_every: int = 100,
                 log_fn: Callable[[str], None] = print,
                 warmup_steps_excluded: int = 2,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1000,
                 loss_sink: Optional[Callable[[int, float], None]] = None,
                 sink_every: int = 10,
                 resilience: Optional[ResilienceConfig] = None,
                 fault_plan=None,
                 scale_hook=None,
                 on_checkpoint=None,
                 telemetry=None) -> LLMTrainReport:
    """Pipeline(-x-data)-parallel tiny-Llama training; returns losses and
    throughput.

    Capability target: the reference's 3-stage microbatched pipeline run
    (lab/hw01/homework 1 b/homework_1_b1.py, committed log out_b1_2.txt:
    loss 10.517 -> ~6.0 over 5000 iters) and the 2-pipeline x 3-stage DPxPP
    topology (homework_1_b2.py, out_b2_*.txt). ``train_cfg.stage``/
    ``train_cfg.data``/``train_cfg.microbatches`` pick the topology; each
    data shard reads a disjoint stream window (shard_skip=5000), matching
    the reference's per-pipeline data offset.

    The DP fast-path levers now compose here too (the PR 14 column):

    - ``train_cfg.steps_per_dispatch`` = K > 1 drives the fused K-step
      scan driver (pp.make_pipeline_multi_step — any schedule) through the
      same chunked ``_run_loop`` mode as the DP trainer: one compiled,
      donated dispatch per K steps, host work (checkpoint / StepGuard /
      sink / telemetry / numerics sampling) quantized to chunk edges,
      losses bitwise-identical to K=1 (tests/test_pp.py), misaligned
      resume realigning with one smaller first chunk.
    - ``aggregation="zero1"`` + ``train_cfg.overlap_microbatches`` = M ≥ 1
      routes the DP×PP data-axis sync of the cross-stage-reduced gradient
      through the compressed/overlapped ring
      (pp.make_pipeline_overlap_*): ZeRO-1 moments sharded over
      ``(data, stage)`` ride the scan carry, ``train_cfg.wire`` selects
      the in-flight ring format (fp32/bf16/int8_ef — EF residuals in the
      checkpointed state, preempt/resume bitwise).
    - ``train_cfg.numerics_every`` emits stage-stacked in-jit numerics
      (pp.make_pp_numerics — block groups stage-qualified, losses bitwise
      on/off).

    Elastic mode (``resilience.elastic=True``) now composes here: a
    ``device_loss`` on the DP×PP mesh drains at the chunk edge and
    re-meshes — dropping the victims' data rows whole when a complete
    row survives (pure reshard at the same stage count), else
    RE-PARTITIONING layers over the survivors at the largest stage count
    dividing ``n_layers`` (``pp.repartition_stage_state`` rewrites the
    ``(data, stage)`` ZeRO-1/EF stacks through topology-invariant
    coordinate ids). ``device_return`` grows back toward the original
    ``(D, S)`` factorization via pool-order rejoin. Named non-composing
    combinations: ``schedule="interleaved"`` (the chunk-major layer
    order breaks the blocked stage slices a re-partition re-slices) and
    ``numerics_every`` (as on the DP trainer).

    Still DP-trainer-only (hard errors): hierarchical DCN tiers
    (``dcn``/``wire_dcn`` — the PP mesh has no two-level data tier),
    the fused in-jit guard, and ``accum_steps`` (the pipeline schedule
    owns its microbatching).

    ``checkpoint_dir`` enables orbax checkpoint/resume with stream replay,
    the same contract as train_llm_dp: restore the latest step (sharding-
    preserving — stage-sharded params land back on their stages), skip
    already-completed iterations while still consuming the token stream so
    data order is preserved, save every ``checkpoint_every`` steps and at
    the end. Both trainers share one loop implementation (_run_loop), so
    timing/throughput/resume semantics cannot drift between them.
    """
    tok = tokenizer or load_tokenizer()
    model_cfg = (model_cfg or LlamaConfig()).replace(vocab_size=tok.vocab_size)
    train_cfg = train_cfg or TrainConfig()
    spd = train_cfg.steps_per_dispatch
    ovl = train_cfg.overlap_microbatches
    if spd < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1 (got {spd})")
    if ovl < 0:
        raise ValueError(f"overlap_microbatches must be >= 0 (got {ovl})")
    cb = train_cfg.comm_buckets
    if cb < 1:
        raise ValueError(f"comm_buckets must be >= 1 (got {cb})")
    if cb > 1 and ovl == 0:
        raise ValueError(
            "comm_buckets > 1 is a property of the overlap/ring driver "
            "(the bucketed backward splits each microbatch's ring) — set "
            f"overlap_microbatches >= 1 (got comm_buckets={cb} with "
            "overlap_microbatches=0)")
    if train_cfg.dcn != 1 or train_cfg.wire_dcn:
        raise ValueError("hierarchical DP (TrainConfig.dcn / wire_dcn) is "
                         "DP-trainer-only; the pipeline mesh has no "
                         "two-level data tier")
    if train_cfg.accum_steps != 1:
        raise ValueError("accum_steps (DP gradient accumulation) is "
                         "DP-trainer-only: the pipeline schedule owns its "
                         "microbatching — raise TrainConfig.microbatches "
                         "instead")
    if aggregation not in ("gradient", "zero1"):
        raise ValueError(f"unknown aggregation {aggregation!r}: the PP "
                         "trainer supports 'gradient' and 'zero1'")
    if train_cfg.wire != "fp32" and ovl == 0:
        raise ValueError(
            "wire compression on the PP trainer routes through the DP×PP "
            "ring driver: set overlap_microbatches >= 1 "
            f"(got wire={train_cfg.wire!r} with overlap_microbatches=0)")
    if aggregation == "zero1" and ovl == 0:
        raise ValueError(
            "PP zero1 routes the data-axis sync through the ring driver: "
            "set overlap_microbatches >= 1")
    elastic = bool(resilience is not None and resilience.elastic)
    if elastic and schedule == "interleaved":
        raise ValueError(
            "elastic mode does not compose with schedule='interleaved': a "
            "stage re-partition re-slices the blocked [n_layers/S] stage "
            "shards, and the interleaved chunk-major layer order breaks "
            "that contiguity — use schedule='gpipe' or '1f1b'")
    if elastic and train_cfg.numerics_every > 0:
        raise ValueError("numerics_every does not compose with elastic "
                         "mode yet")
    if scale_hook is not None and not elastic:
        raise ValueError("scale_hook requires resilience.elastic=True — "
                         "capacity changes ride the elastic re-mesh "
                         "machinery")
    if resilience is not None and resilience.injit_guard:
        raise ValueError("injit_guard is not fused into the pipeline step "
                         "bodies — use the host StepGuard "
                         "(ResilienceConfig.guard), which works at "
                         "dispatch granularity under steps_per_dispatch")
    mesh = mesh or make_mesh({"data": train_cfg.data,
                              "stage": train_cfg.stage})
    n_data = mesh.shape.get("data", 1)

    params = llama.init_llama(jax.random.key(train_cfg.seed), model_cfg)
    optimizer = _make_trainer_optimizer(train_cfg)
    if schedule == "interleaved":
        params = pp.interleave_params(params, mesh.shape["stage"],
                                      n_chunks=2)
    numerics = None
    if train_cfg.numerics_every > 0:
        # Stage-stacked in-jit numerics (pp.make_pp_numerics): block
        # groups come back per (stage, local layer); the ring/zero1 path
        # psum-agrees grad stats over ``data`` (local gradients differ
        # per data shard there — the compress.py rule).
        numerics = pp.make_pp_numerics(params, mesh, psum_data=ovl >= 1)

    window_shard = None
    if elastic:
        # Pin the init params to host memory: ``jax.device_put`` may
        # alias (not copy) an already-compatibly-placed leaf into the
        # first build's state, and the donated dispatches then delete
        # that buffer — a post-remesh rebuild reading the closure would
        # hit "Array has been deleted". Host arrays are never donated.
        params = jax.tree.map(np.asarray, params)

        def _build_elastic(m):
            """(template_state, raw window step, window shard fn) on an
            arbitrary (data, stage) mesh — initial build AND post-remesh
            rebuild (including at a re-partitioned stage count) go through
            here, so the two cannot drift."""
            if ovl >= 1:
                st, fn = pp.make_pipeline_overlap_multi_step(
                    model_cfg, optimizer, m, params,
                    n_microbatches=train_cfg.microbatches,
                    schedule=schedule, aggregation=aggregation,
                    wire=train_cfg.wire, overlap_microbatches=ovl,
                    comm_buckets=cb)
            else:
                st = pp.init_state(m, params, optimizer)
                fn = pp.make_pipeline_multi_step(
                    model_cfg, optimizer, m,
                    n_microbatches=train_cfg.microbatches,
                    schedule=schedule)
            # Per-(re)build CompileWatch, tagged with the (D, S)
            # factorization: zero retraces per topology is the elastic
            # PP compile bar (tests/test_elastic.py), and the tag is what
            # makes a re-partition's recompile attributable in the event
            # stream.
            fn = introspect.watch(
                fn, name=f"train/pp-{schedule}-elastic"
                         + (f"-{aggregation}" if aggregation != "gradient"
                            else "")
                         + (f"-ring{train_cfg.wire}-m{ovl}" if ovl else "")
                         + (f"-b{cb}" if cb > 1 else "")
                         + f"-d{m.shape['data']}s{m.shape['stage']}",
                max_caches=None,
                events=(telemetry.events if telemetry is not None
                        else None),
                meta={"steps_per_dispatch": spd},
                meta_fn=lambda st, w: {"steps_per_dispatch":
                                       int(w.shape[0])})
            return st, fn, (lambda w, m=m: pp.shard_batch_window(m, w))

        state, step_fn, window_shard = _build_elastic(mesh)
    elif ovl >= 1:
        # DP×PP data-axis composition (pp.make_pipeline_overlap_*): the
        # cross-stage-reduced gradient's data sync rides the
        # compressed/overlapped ring; zero1 moments + EF residuals live
        # in the state tree (checkpoint/preempt carry them exactly).
        maker = (pp.make_pipeline_overlap_multi_step if spd > 1
                 else pp.make_pipeline_overlap_step)
        state, step_fn = maker(
            model_cfg, optimizer, mesh, params,
            n_microbatches=train_cfg.microbatches, schedule=schedule,
            aggregation=aggregation, wire=train_cfg.wire,
            overlap_microbatches=ovl, comm_buckets=cb, numerics=numerics)
    elif spd > 1:
        state = pp.init_state(mesh, params, optimizer)
        step_fn = pp.make_pipeline_multi_step(
            model_cfg, optimizer, mesh,
            n_microbatches=train_cfg.microbatches, schedule=schedule,
            numerics=numerics)
    else:
        state = pp.init_state(mesh, params, optimizer)
        step_fn = pp.make_pipeline_step(
            model_cfg, optimizer, mesh,
            n_microbatches=train_cfg.microbatches, schedule=schedule,
            numerics=numerics)
    # Compile/retrace accounting (introspect.CompileWatch), the DP
    # trainer's contract: per-step mode promises ONE compiled program;
    # chunked mode legitimately compiles a tail-chunk shape, so no budget
    # there — but every compile event is stamped with the COMPILING
    # call's actual window size, so per-step MFU normalization
    # (slo_monitor) stays honest for ragged tails. The elastic path wraps
    # inside _build_elastic instead (each re-mesh rebuild gets its own
    # topology-tagged watch).
    if not elastic:
        step_fn = introspect.watch(
            step_fn,
            name=f"train/pp-{schedule}"
                 + (f"-{aggregation}" if aggregation != "gradient" else "")
                 + (f"-k{spd}" if spd > 1 else "")
                 + (f"-ring{train_cfg.wire}-m{ovl}" if ovl else "")
                 + (f"-b{cb}" if cb > 1 else ""),
            max_caches=(1 if spd == 1 else None),
            events=(telemetry.events if telemetry is not None else None),
            meta={"steps_per_dispatch": spd},
            meta_fn=(None if spd == 1 else
                     (lambda st, w: {"steps_per_dispatch":
                                     int(w.shape[0])})))
    compile_watch = step_fn if not elastic else None

    stats = ResilienceStats()
    ckpt, state, start_step, done = _setup_checkpoint(
        checkpoint_dir, state, train_cfg.iters, log_fn,
        resilience=resilience, stats=stats)
    if done:
        return LLMTrainReport(resilience=stats)
    _emit_manifest(telemetry, trainer="pp", model_cfg=model_cfg,
                   train_cfg=train_cfg, mesh=mesh, start_step=start_step,
                   step_fn=step_fn, state=state, n_data=n_data,
                   steps_per_dispatch=spd, windowed=elastic,
                   overlap_microbatches=max(1, ovl))
    if fault_plan is None and resilience is not None and resilience.faults:
        fault_plan = resilience.fault_plan()   # resolve ONCE: the elastic
        #   rebuild must re-wrap the same schedule, not a fresh counter's

    def _make_batches(n):
        return sharded_batches(tok, train_cfg.batch_size, train_cfg.seq_len,
                               n, shard_skip=5000, seed=train_cfg.seed)

    if elastic:
        from ..resilience.elastic import ElasticController

        def _rewrap(fn, start=0):
            return _apply_resilience(fn, resilience, fault_plan, ckpt,
                                     stats, start=start)

        controller = ElasticController(
            mesh, build=_build_elastic, rewrap=_rewrap,
            make_batches=_make_batches, ckpt=ckpt,
            mirror_every=resilience.mirror_every,
            layer_divisor=model_cfg.n_layers, stats=stats,
            telemetry=telemetry, log_fn=log_fn)
        return _run_elastic_loop(
            controller, _rewrap(step_fn), state, _make_batches(n_data),
            train_cfg, n_data=n_data, start_step=start_step, ckpt=ckpt,
            checkpoint_every=checkpoint_every, loss_sink=loss_sink,
            sink_every=sink_every, log_every=log_every, log_fn=log_fn,
            warmup_steps_excluded=warmup_steps_excluded, stats=stats,
            telemetry=telemetry, steps_per_dispatch=spd,
            window_shard_fn=window_shard, on_checkpoint=on_checkpoint,
            scale_hook=scale_hook)
    step_fn = _apply_resilience(step_fn, resilience, fault_plan, ckpt, stats)

    batches = _make_batches(n_data)
    return _run_loop(step_fn, state, batches, train_cfg,
                     lambda b: pp.shard_batch(mesh, b), n_data=n_data,
                     start_step=start_step, ckpt=ckpt,
                     checkpoint_every=checkpoint_every, loss_sink=loss_sink,
                     sink_every=sink_every, log_every=log_every,
                     log_fn=log_fn,
                     warmup_steps_excluded=warmup_steps_excluded,
                     stats=stats, telemetry=telemetry,
                     steps_per_dispatch=spd,
                     window_shard_fn=lambda w: pp.shard_batch_window(mesh, w),
                     numerics=numerics,
                     numerics_every=train_cfg.numerics_every,
                     compile_watch=compile_watch,
                     on_checkpoint=on_checkpoint)


def train_llm_tp(model_cfg: Optional[LlamaConfig] = None,
                 train_cfg: Optional[TrainConfig] = None, *,
                 mesh=None,
                 tokenizer=None,
                 aggregation: str = "gradient",
                 log_every: int = 100,
                 log_fn: Callable[[str], None] = print,
                 warmup_steps_excluded: int = 2,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1000,
                 loss_sink: Optional[Callable[[int, float], None]] = None,
                 sink_every: int = 10,
                 resilience: Optional[ResilienceConfig] = None,
                 fault_plan=None,
                 scale_hook=None,
                 on_checkpoint=None,
                 telemetry=None) -> LLMTrainReport:
    """Tensor(-x-data)-parallel tiny-Llama training; returns losses and
    throughput.

    ``train_cfg.model`` picks the TP degree (Megatron column/row layout,
    parallel/tp.py) and ``train_cfg.data`` the data axis; each data shard
    reads a disjoint stream window (shard_skip=5000), exactly as the
    DP/PP trainers do. The fused-dispatch + overlapped/compressed sync
    column (the PR 14/18 levers) composes here:

    - ``train_cfg.psa`` relaxes the per-layer activation all-reduces off
      the critical path (TrainConfig.psa doc comment: "" bitwise legacy /
      "full" telemetry-visible baseline / "defer:L" / "int8_ef" with the
      per-layer EF residual tree riding the checkpointed state).
    - ``train_cfg.steps_per_dispatch`` = K > 1 drives the fused K-step
      scan driver (tp.make_tp_multi_step) through the same chunked
      ``_run_loop`` mode as the DP/PP trainers: one compiled, donated
      dispatch per K steps, host work quantized to chunk edges, losses
      bitwise-identical to K=1 (tests/test_tp.py).
    - ``aggregation="zero1"`` + ``train_cfg.overlap_microbatches`` = M ≥ 1
      routes the DATA-axis gradient sync through the compressed/overlapped
      ring on the DP×TP mesh (tp.make_tp_overlap_*): ZeRO-1 moments and
      EF residuals sharded ``(data, model)`` ride the scan carry,
      ``train_cfg.wire`` selects the ring format (fp32/bf16/int8_ef).
    - ``train_cfg.numerics_every`` emits in-jit numerics whose summaries
      are model-axis psum-agreed (tp.make_tp_numerics — every shard
      carries the same summary; losses bitwise on/off).

    Elastic mode (``resilience.elastic=True``) composes with the fused
    dispatch paths (``overlap_microbatches == 0``), INCLUDING
    ``psa="int8_ef"`` — the ROADMAP 7a lift: a data-axis re-mesh resizes
    the ``TPActState`` activation EF residual tree by the per-data-row
    rule (``dp._resize_act_residual``; surviving rows copy bitwise, new
    rows start at zero pending error), so preempt → remesh → resume under
    PSA is bitwise. The model axis itself never re-meshes (a model-axis
    device loss is unrecoverable — the Megatron layout is not
    layer-sliced), and the DP×TP ring drivers
    (``overlap_microbatches >= 1``) remain a named unsupported
    combination (their ``(data, model)`` ring stacks have no reshard
    rule yet).

    Still DP-trainer-only (hard errors): hierarchical DCN tiers, the
    fused in-jit guard, and ``accum_steps``.
    ``checkpoint_dir`` enables orbax checkpoint/resume with stream
    replay, the shared _run_loop contract — PSA EF residuals and ring
    residuals live in the state tree, so preempt/resume is bitwise.
    """
    tok = tokenizer or load_tokenizer()
    model_cfg = (model_cfg or LlamaConfig()).replace(vocab_size=tok.vocab_size)
    train_cfg = train_cfg or TrainConfig()
    spd = train_cfg.steps_per_dispatch
    ovl = train_cfg.overlap_microbatches
    psa = train_cfg.psa
    if spd < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1 (got {spd})")
    if ovl < 0:
        raise ValueError(f"overlap_microbatches must be >= 0 (got {ovl})")
    cb = train_cfg.comm_buckets
    if cb < 1:
        raise ValueError(f"comm_buckets must be >= 1 (got {cb})")
    if cb > 1 and ovl == 0:
        raise ValueError(
            "comm_buckets > 1 is a property of the overlap/ring driver "
            "(the bucketed backward splits each microbatch's ring) — set "
            f"overlap_microbatches >= 1 (got comm_buckets={cb} with "
            "overlap_microbatches=0)")
    if train_cfg.dcn != 1 or train_cfg.wire_dcn:
        raise ValueError("hierarchical DP (TrainConfig.dcn / wire_dcn) is "
                         "DP-trainer-only; the TP mesh has no two-level "
                         "data tier")
    if train_cfg.accum_steps != 1:
        raise ValueError("accum_steps (DP gradient accumulation) is "
                         "DP-trainer-only; use overlap_microbatches on "
                         "the TP trainer's ring path")
    if aggregation not in ("gradient", "zero1"):
        raise ValueError(f"unknown aggregation {aggregation!r}: the TP "
                         "trainer supports 'gradient' and 'zero1'")
    if train_cfg.wire != "fp32" and ovl == 0:
        raise ValueError(
            "wire compression on the TP trainer routes through the DP×TP "
            "ring driver: set overlap_microbatches >= 1 "
            f"(got wire={train_cfg.wire!r} with overlap_microbatches=0)")
    if aggregation == "zero1" and ovl == 0:
        raise ValueError(
            "TP zero1 routes the data-axis sync through the ring driver: "
            "set overlap_microbatches >= 1")
    elastic = bool(resilience is not None and resilience.elastic)
    if elastic and ovl >= 1:
        raise ValueError(
            "elastic mode does not compose with the DP×TP ring driver "
            "(overlap_microbatches >= 1): its (data, model)-sharded ring "
            "stacks have no cross-topology reshard rule yet — set "
            "overlap_microbatches=0 (the fused dispatch paths, including "
            "psa='int8_ef', are elastic)")
    if elastic and train_cfg.numerics_every > 0:
        raise ValueError("numerics_every does not compose with elastic "
                         "mode yet")
    if scale_hook is not None and not elastic:
        raise ValueError("scale_hook requires resilience.elastic=True — "
                         "capacity changes ride the elastic re-mesh "
                         "machinery")
    if resilience is not None and resilience.injit_guard:
        raise ValueError("injit_guard is not fused into the TP step "
                         "bodies — use the host StepGuard "
                         "(ResilienceConfig.guard), which works at "
                         "dispatch granularity under steps_per_dispatch")
    mesh = mesh or make_mesh({"data": train_cfg.data,
                              "model": train_cfg.model})
    if mesh.shape.get("model", 1) < 2:
        raise ValueError("the TP trainer needs model >= 2 "
                         "(set TrainConfig.model); model=1 is the DP "
                         "trainer's mesh")
    n_data = mesh.shape.get("data", 1)

    params = llama.init_llama(jax.random.key(train_cfg.seed), model_cfg)
    optimizer = _make_trainer_optimizer(train_cfg)
    numerics = None
    if train_cfg.numerics_every > 0:
        # Model-axis psum-agreed in-jit numerics (tp.make_tp_numerics):
        # the ring/zero1 path additionally psum-agrees grad stats over
        # ``data`` (local gradients differ per data shard there — the
        # compress.py rule).
        numerics = tp.make_tp_numerics(params, mesh, psum_data=ovl >= 1)

    window_shard = None
    if elastic:
        # Pin the init params to host memory (see the PP elastic path):
        # device_put can alias a compatibly-placed leaf into the first
        # build's donated state, deleting the buffer a rebuild needs.
        params = jax.tree.map(np.asarray, params)

        def _build_elastic(m):
            """(template_state, raw window step, window shard fn) on an
            arbitrary (data, model) mesh — initial build AND post-remesh
            rebuild (data row-drop / grow; the model axis never re-meshes)
            go through here, so the two cannot drift."""
            st, fn = tp.make_tp_multi_step(
                model_cfg, optimizer, m, params, psa=psa,
                batch_shape=(train_cfg.batch_size, train_cfg.seq_len))
            # Per-(re)build CompileWatch, tagged with the (D, TP)
            # factorization: zero retraces per topology is the elastic
            # compile bar (tests/test_elastic.py).
            fn = introspect.watch(
                fn, name="train/tp-elastic"
                         + (f"-psa-{psa.replace(':', '')}" if psa else "")
                         + f"-d{m.shape['data']}x{m.shape['model']}",
                max_caches=None,
                events=(telemetry.events if telemetry is not None
                        else None),
                meta={"steps_per_dispatch": spd},
                meta_fn=lambda st, w: {"steps_per_dispatch":
                                       int(w.shape[0])})
            return st, fn, (lambda w, m=m: tp.shard_batch_window(m, w))

        state, step_fn, window_shard = _build_elastic(mesh)
    elif ovl >= 1:
        # DP×TP data-axis composition (tp.make_tp_overlap_*): the
        # model-psum-reduced gradient's data sync rides the compressed/
        # overlapped ring; zero1 moments + EF residuals sharded
        # (data, model) live in the state tree. psa="int8_ef" here is a
        # named unsupported combination (_tp_overlap_setup).
        maker = (tp.make_tp_overlap_multi_step if spd > 1
                 else tp.make_tp_overlap_step)
        state, step_fn = maker(
            model_cfg, optimizer, mesh, params,
            aggregation=aggregation, wire=train_cfg.wire,
            overlap_microbatches=ovl, psa=psa, comm_buckets=cb,
            numerics=numerics)
    else:
        maker = tp.make_tp_multi_step if spd > 1 else tp.make_tp_step
        state, step_fn = maker(
            model_cfg, optimizer, mesh, params, psa=psa,
            batch_shape=(train_cfg.batch_size, train_cfg.seq_len),
            numerics=numerics)
    # Compile/retrace accounting: the same contract as the DP/PP trainers
    # — per-step mode promises ONE compiled program; chunked mode stamps
    # every compile event with the COMPILING call's window size. The
    # elastic path wraps inside _build_elastic instead (each re-mesh
    # rebuild gets its own topology-tagged watch).
    if not elastic:
        step_fn = introspect.watch(
            step_fn,
            name="train/tp"
                 + (f"-psa-{psa.replace(':', '')}" if psa else "")
                 + (f"-{aggregation}" if aggregation != "gradient" else "")
                 + (f"-k{spd}" if spd > 1 else "")
                 + (f"-ring{train_cfg.wire}-m{ovl}" if ovl else "")
                 + (f"-b{cb}" if cb > 1 else ""),
            max_caches=(1 if spd == 1 else None),
            events=(telemetry.events if telemetry is not None else None),
            meta={"steps_per_dispatch": spd},
            meta_fn=(None if spd == 1 else
                     (lambda st, w: {"steps_per_dispatch":
                                     int(w.shape[0])})))
    compile_watch = step_fn if not elastic else None

    stats = ResilienceStats()
    ckpt, state, start_step, done = _setup_checkpoint(
        checkpoint_dir, state, train_cfg.iters, log_fn,
        resilience=resilience, stats=stats)
    if done:
        return LLMTrainReport(resilience=stats)
    _emit_manifest(telemetry, trainer="tp", model_cfg=model_cfg,
                   train_cfg=train_cfg, mesh=mesh, start_step=start_step,
                   step_fn=step_fn, state=state, n_data=n_data,
                   steps_per_dispatch=spd, windowed=elastic,
                   overlap_microbatches=max(1, ovl))
    if fault_plan is None and resilience is not None and resilience.faults:
        fault_plan = resilience.fault_plan()   # resolve ONCE: the elastic
        #   rebuild must re-wrap the same schedule, not a fresh counter's

    def _make_batches(n):
        return sharded_batches(tok, train_cfg.batch_size, train_cfg.seq_len,
                               n, shard_skip=5000, seed=train_cfg.seed)

    if elastic:
        from ..resilience.elastic import ElasticController

        def _rewrap(fn, start=0):
            return _apply_resilience(fn, resilience, fault_plan, ckpt,
                                     stats, start=start)

        # No layer_divisor: the TP model axis never re-partitions —
        # survivor_submesh either drops whole data rows or declares a
        # model-axis loss unrecoverable.
        controller = ElasticController(
            mesh, build=_build_elastic, rewrap=_rewrap,
            make_batches=_make_batches, ckpt=ckpt,
            mirror_every=resilience.mirror_every, stats=stats,
            telemetry=telemetry, log_fn=log_fn)
        return _run_elastic_loop(
            controller, _rewrap(step_fn), state, _make_batches(n_data),
            train_cfg, n_data=n_data, start_step=start_step, ckpt=ckpt,
            checkpoint_every=checkpoint_every, loss_sink=loss_sink,
            sink_every=sink_every, log_every=log_every, log_fn=log_fn,
            warmup_steps_excluded=warmup_steps_excluded, stats=stats,
            telemetry=telemetry, steps_per_dispatch=spd,
            window_shard_fn=window_shard, on_checkpoint=on_checkpoint,
            scale_hook=scale_hook)
    step_fn = _apply_resilience(step_fn, resilience, fault_plan, ckpt, stats)

    batches = _make_batches(n_data)
    return _run_loop(step_fn, state, batches, train_cfg,
                     lambda b: tp.shard_batch(mesh, b), n_data=n_data,
                     start_step=start_step, ckpt=ckpt,
                     checkpoint_every=checkpoint_every, loss_sink=loss_sink,
                     sink_every=sink_every, log_every=log_every,
                     log_fn=log_fn,
                     warmup_steps_excluded=warmup_steps_excluded,
                     stats=stats, telemetry=telemetry,
                     steps_per_dispatch=spd,
                     window_shard_fn=lambda w: tp.shard_batch_window(mesh, w),
                     numerics=numerics,
                     numerics_every=train_cfg.numerics_every,
                     compile_watch=compile_watch,
                     on_checkpoint=on_checkpoint)
