"""End-to-end LLM training drivers.

`train_llm_dp` is the framework's minimum end-to-end slice: the reference's
whole DP gradient-aggregation script (lab/tutorial_1b/DP/gradient_aggr/
intro_DP_GA.py — N processes, gloo, per-iter flatten/allreduce) collapsed
into one jitted SPMD program reproducing its loss trajectory
(10.5 → ≈6 over 5000 iters, lab/out_b1_2.txt).
"""

from __future__ import annotations

import functools
import math
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..config import LlamaConfig, TrainConfig
from ..data.tokens import TokenStream, sharded_batches
from ..models import llama
from ..parallel import dp, make_mesh, pp
from ..tokenizers import load_tokenizer


@dataclass
class LLMTrainReport:
    losses: List[float] = field(default_factory=list)
    tokens_per_sec: float = 0.0
    steps: int = 0
    wall_time: float = 0.0

    def tokens_per_sec_per_device(self, n_devices: int) -> float:
        return self.tokens_per_sec / max(n_devices, 1)


@functools.partial(jax.jit, static_argnames="cfg")
def _eval_batch_loss(params, batch, cfg: LlamaConfig):
    # Module-level + static cfg: periodic eval_llm calls from a train loop
    # hit the jit cache instead of recompiling a per-call closure.
    return llama.forward_loss(params, batch, cfg)


def eval_llm(params, model_cfg: LlamaConfig, *, n_batches: int = 16,
             batch_size: int = 8, skip: int = 0,
             tokenizer=None, seed: int = 1, stream=None) -> dict:
    """Held-out evaluation: mean next-token loss and perplexity over
    ``n_batches``. Parity-plus: the reference only ever prints train-batch
    loss (lab/tutorial_1b/primer/intro.py); an eval split is what lets a
    user see overfitting on the tiny corpus at all. Uses the fused head+CE,
    so no [B, T, V] logits materialize. Returns {"loss", "perplexity",
    "n_tokens"}.

    Held-out contract: on the synthetic fallback corpus a different
    ``seed`` IS a disjoint corpus (the generator is seed-parameterized), so
    the default seed=1 vs the trainers' seed=0 needs no skipping. For a
    file-backed corpus pass ``skip`` explicitly, PAST your training window
    (trainer shard i reads from sequence i·5000 for iters·batch_size
    sequences) — and note the stream cycles a short corpus, so disjointness
    holds only while skip + the eval span stays within one pass. For
    periodic evals with a nonzero skip, build the iterator once —
    ``it = iter(TokenStream(...))`` — and pass it via ``stream``: each call
    then continues it instead of re-tokenizing the whole skip window. (A
    raw TokenStream is also accepted but restarts — and re-pays the skip —
    on every call.)
    """
    tok = tokenizer or load_tokenizer()
    model_cfg = model_cfg.replace(vocab_size=tok.vocab_size)
    if stream is None:
        stream = TokenStream(tok, batch_size, model_cfg.ctx_size,
                             skip=skip, seed=seed)
    stream = iter(stream)  # no-op on iterators; accepts a raw TokenStream
    total = 0.0
    n_tokens = 0
    for _ in range(n_batches):
        batch = jnp.asarray(next(stream))
        total += float(_eval_batch_loss(params, batch, model_cfg))
        # The causal loss scores T-1 next-token positions per sequence.
        n_tokens += batch.shape[0] * (batch.shape[1] - 1)
    mean = total / n_batches
    return {"loss": mean, "perplexity": math.exp(min(mean, 30.0)),
            "n_tokens": n_tokens}


def _make_trainer_optimizer(train_cfg: TrainConfig):
    """TrainConfig.optimizer -> optimizer instance, shared by both trainers:
    "adam" is the reference's plain optax.adam; everything else dispatches
    through bench_utils.make_optimizer ("fused"/"pallas"/"master")."""
    if train_cfg.optimizer == "adam":
        return optax.adam(train_cfg.lr)
    from ..bench_utils import make_optimizer
    return make_optimizer(train_cfg.optimizer, train_cfg.lr)


def _setup_checkpoint(checkpoint_dir: Optional[str], state, iters: int,
                      log_fn: Callable[[str], None]):
    """Shared resume preamble: open the orbax dir, restore the latest step
    into ``state``'s layout (sharding-preserving). Returns
    ``(ckpt, state, start_step, done)`` — ``done`` means the checkpoint is
    already at/past ``iters`` and there is nothing to train."""
    if checkpoint_dir is None:
        return None, state, 0, False
    from ..checkpoint import Checkpointer
    ckpt = Checkpointer(checkpoint_dir)
    start_step = 0
    if ckpt.latest_step() is not None:
        state = ckpt.restore(state)
        start_step = int(ckpt.latest_step())
        log_fn(f"resumed from step {start_step}")
    if start_step >= iters:
        log_fn(f"checkpoint already at step {start_step} >= iters {iters}; "
               "nothing to train")
        ckpt.close()
        return ckpt, state, start_step, True
    return ckpt, state, start_step, False


def _run_loop(step_fn, state, batches, train_cfg: TrainConfig, shard_fn, *,
              n_data: int, start_step: int, ckpt, checkpoint_every: int,
              loss_sink, sink_every: int, log_every: int, log_fn,
              warmup_steps_excluded: int) -> LLMTrainReport:
    """The training loop both trainers share: stream replay on resume,
    per-iteration loss sinking/logging, periodic + final checkpoint saves,
    and async-honest throughput accounting (the timer starts after
    ``warmup_steps_excluded`` post-resume steps, on a hard host sync)."""
    report = LLMTrainReport()
    last_saved = -1
    tokens_per_step = n_data * train_cfg.batch_size * train_cfg.seq_len
    t_start = None
    device_losses = []  # keep losses on device; a float() per step would
    #                     serialize dispatch and deflate throughput
    for it in range(train_cfg.iters):
        host_batch = next(batches).reshape(
            n_data * train_cfg.batch_size, train_cfg.seq_len)
        if it < start_step:
            continue  # resume: replay the stream so data order is preserved
        state, loss = step_fn(state, shard_fn(host_batch))
        if it + 1 == start_step + warmup_steps_excluded:
            float(loss)  # hard sync before starting the timer
            t_start = time.perf_counter()
        device_losses.append(loss)
        if loss_sink is not None and (it % sink_every == 0
                                      or it == train_cfg.iters - 1):
            loss_sink(it, float(loss))
        if log_every and it % log_every == 0:
            log_fn(f"iter {it}: loss {float(loss):.4f}")
        if ckpt is not None and (it + 1) % checkpoint_every == 0:
            ckpt.save(it + 1, state)
            last_saved = it + 1
    if ckpt is not None:
        if train_cfg.iters != last_saved:
            ckpt.save(train_cfg.iters, state, force=True)
        ckpt.close()
    report.losses = [float(l) for l in device_losses]  # syncs the chain
    report.steps = train_cfg.iters - start_step
    if t_start is not None and report.steps > warmup_steps_excluded:
        report.wall_time = time.perf_counter() - t_start
        timed = report.steps - warmup_steps_excluded
        report.tokens_per_sec = tokens_per_step * timed / report.wall_time
    return report


def train_llm_dp(model_cfg: Optional[LlamaConfig] = None,
                 train_cfg: Optional[TrainConfig] = None, *,
                 mesh=None,
                 tokenizer=None,
                 aggregation: str = "gradient",
                 log_every: int = 100,
                 log_fn: Callable[[str], None] = print,
                 warmup_steps_excluded: int = 2,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1000,
                 loss_sink: Optional[Callable[[int, float], None]] = None,
                 sink_every: int = 10) -> LLMTrainReport:
    """Run DP tiny-Llama training; returns losses and throughput.

    ``aggregation``: "gradient" (allreduce grads — intro_DP_GA) or "weight"
    (allreduce weights post-step — intro_DP_WA's intended semantics).

    ``loss_sink(it, loss)`` fires every ``sink_every`` iterations with the
    host-synced loss — for incremental result recording that survives a
    killed run (each call forces a device sync; use only where the step
    time dwarfs it, e.g. the oversubscribed virtual-CPU mesh).

    ``checkpoint_dir`` enables orbax checkpoint/resume (the persistence layer
    the reference lacks, SURVEY.md §5.4): the latest step in the directory is
    restored into the mesh layout before training, a checkpoint is written
    every ``checkpoint_every`` steps and at the end, and already-completed
    iterations are skipped — re-running the same call after an interruption
    continues where it stopped.
    """
    tok = tokenizer or load_tokenizer()
    model_cfg = (model_cfg or LlamaConfig()).replace(vocab_size=tok.vocab_size)
    train_cfg = train_cfg or TrainConfig()
    mesh = mesh or make_mesh({"data": train_cfg.data})
    n_data = mesh.shape.get("data", 1)

    params = llama.init_llama(jax.random.key(train_cfg.seed), model_cfg)
    optimizer = _make_trainer_optimizer(train_cfg)

    def loss_fn(p, batch):
        # Fused head+CE: never materializes the [B, T, V] logits (the step's
        # dominant HBM tensor at real vocab sizes). Equivalent math to
        # causal_lm_loss(llama.forward(...)) — asserted in tests/test_core.py.
        return llama.forward_loss(p, batch, model_cfg)

    state = dp.replicate(mesh, dp.init_state(params, optimizer))
    if train_cfg.wire != "fp32":
        # Compressed gradient allreduce (parallel/compress.py) — gradient
        # aggregation only, and accumulation stays at 1 (the compressed
        # steps own their collective schedule). Hard errors, not asserts:
        # a stripped assert (python -O) would silently run the wrong
        # aggregation algorithm.
        if aggregation != "gradient" or train_cfg.accum_steps != 1:
            raise ValueError(
                "wire compression requires gradient aggregation without "
                f"accumulation (got aggregation={aggregation!r}, "
                f"accum_steps={train_cfg.accum_steps})")
        from ..parallel import compress
        if train_cfg.wire == "bf16":
            step_fn = compress.make_bf16_grad_step(loss_fn, optimizer, mesh)
        elif train_cfg.wire == "int8_ef":
            state = compress.init_ef_state(mesh, params, optimizer)
            step_fn = compress.make_int8_ef_grad_step(loss_fn, optimizer,
                                                      mesh)
        else:
            raise ValueError(f"unknown wire format {train_cfg.wire!r}")
    elif aggregation == "gradient":
        step_fn = dp.make_grad_aggregation_step(
            loss_fn, optimizer, mesh, accum_steps=train_cfg.accum_steps)
    else:
        if train_cfg.accum_steps != 1:
            raise ValueError("accum_steps needs gradient aggregation")
        step_fn = dp.make_weight_aggregation_step(loss_fn, optimizer, mesh)

    ckpt, state, start_step, done = _setup_checkpoint(
        checkpoint_dir, state, train_cfg.iters, log_fn)
    if done:
        return LLMTrainReport()

    # Disjoint stream windows per data shard — the reference's skip=rank*5000.
    batches = sharded_batches(tok, train_cfg.batch_size, train_cfg.seq_len, n_data,
                              shard_skip=5000, seed=train_cfg.seed)
    return _run_loop(step_fn, state, batches, train_cfg,
                     lambda b: dp.shard_batch(mesh, b), n_data=n_data,
                     start_step=start_step, ckpt=ckpt,
                     checkpoint_every=checkpoint_every, loss_sink=loss_sink,
                     sink_every=sink_every, log_every=log_every,
                     log_fn=log_fn,
                     warmup_steps_excluded=warmup_steps_excluded)


def train_llm_pp(model_cfg: Optional[LlamaConfig] = None,
                 train_cfg: Optional[TrainConfig] = None, *,
                 mesh=None,
                 tokenizer=None,
                 schedule: str = "gpipe",
                 log_every: int = 100,
                 log_fn: Callable[[str], None] = print,
                 warmup_steps_excluded: int = 2,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1000,
                 loss_sink: Optional[Callable[[int, float], None]] = None,
                 sink_every: int = 10) -> LLMTrainReport:
    """Pipeline(-x-data)-parallel tiny-Llama training; returns losses and
    throughput.

    Capability target: the reference's 3-stage microbatched pipeline run
    (lab/hw01/homework 1 b/homework_1_b1.py, committed log out_b1_2.txt:
    loss 10.517 -> ~6.0 over 5000 iters) and the 2-pipeline x 3-stage DPxPP
    topology (homework_1_b2.py, out_b2_*.txt). ``train_cfg.stage``/
    ``train_cfg.data``/``train_cfg.microbatches`` pick the topology; each
    data shard reads a disjoint stream window (shard_skip=5000), matching
    the reference's per-pipeline data offset.

    ``checkpoint_dir`` enables orbax checkpoint/resume with stream replay,
    the same contract as train_llm_dp: restore the latest step (sharding-
    preserving — stage-sharded params land back on their stages), skip
    already-completed iterations while still consuming the token stream so
    data order is preserved, save every ``checkpoint_every`` steps and at
    the end. Both trainers share one loop implementation (_run_loop), so
    timing/throughput/resume semantics cannot drift between them.
    """
    tok = tokenizer or load_tokenizer()
    model_cfg = (model_cfg or LlamaConfig()).replace(vocab_size=tok.vocab_size)
    train_cfg = train_cfg or TrainConfig()
    if train_cfg.wire != "fp32":
        raise ValueError("wire compression (TrainConfig.wire) is DP-trainer-"
                         "only; the pipeline step owns its own collectives")
    mesh = mesh or make_mesh({"data": train_cfg.data,
                              "stage": train_cfg.stage})
    n_data = mesh.shape.get("data", 1)

    params = llama.init_llama(jax.random.key(train_cfg.seed), model_cfg)
    optimizer = _make_trainer_optimizer(train_cfg)
    if schedule == "interleaved":
        params = pp.interleave_params(params, mesh.shape["stage"],
                                      n_chunks=2)
    state = pp.init_state(mesh, params, optimizer)
    step_fn = pp.make_pipeline_step(model_cfg, optimizer, mesh,
                                    n_microbatches=train_cfg.microbatches,
                                    schedule=schedule)

    ckpt, state, start_step, done = _setup_checkpoint(
        checkpoint_dir, state, train_cfg.iters, log_fn)
    if done:
        return LLMTrainReport()

    batches = sharded_batches(tok, train_cfg.batch_size, train_cfg.seq_len,
                              n_data, shard_skip=5000, seed=train_cfg.seed)
    return _run_loop(step_fn, state, batches, train_cfg,
                     lambda b: pp.shard_batch(mesh, b), n_data=n_data,
                     start_step=start_step, ckpt=ckpt,
                     checkpoint_every=checkpoint_every, loss_sink=loss_sink,
                     sink_every=sink_every, log_every=log_every,
                     log_fn=log_fn,
                     warmup_steps_excluded=warmup_steps_excluded)
