from .llm import train_llm_dp, LLMTrainReport  # noqa: F401
from .tabular import train_classifier, ClassifierReport  # noqa: F401
from .vfl import train_vfl, train_vfl_vae, VFLReport, VFLVAEReport  # noqa: F401
from .generative import (  # noqa: F401
    train_vae, synthetic_data_eval, VAEReport, SyntheticEvalResult)
