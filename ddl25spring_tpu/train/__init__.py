from .llm import train_llm_dp, LLMTrainReport  # noqa: F401
