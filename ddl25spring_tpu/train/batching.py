"""Shared host-side minibatching: pad to whole batches + validity mask.

The reference's DataLoaders keep the partial last batch (e.g. lab/tutorial_2b/
vfl.py:66-71); under jit we scan over a fixed [n_batches, batch_size, ...]
layout instead, so the remainder is zero-padded and masked rather than
dropped — losses/accuracies weight by the mask and match exactly.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax.numpy as jnp
import numpy as np


def pad_batches(arrays: Sequence[np.ndarray], y: np.ndarray, batch_size: int
                ) -> Tuple[tuple, jnp.ndarray, jnp.ndarray]:
    """Reshape each array (and labels) to [n_batches, batch_size, ...].

    Returns (xs, y_batched, mask) where ``xs`` is a tuple (one entry per
    input array — VFL passes one per party) and ``mask`` flags real rows.
    """
    n = y.shape[0]
    n_batches = math.ceil(n / batch_size)
    pad = n_batches * batch_size - n
    mask = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])

    def pad_reshape(a):
        a = np.concatenate([a, np.zeros((pad, *a.shape[1:]), a.dtype)])
        return jnp.asarray(a.reshape(n_batches, batch_size, *a.shape[1:]))

    xs = tuple(pad_reshape(a) for a in arrays)
    return xs, pad_reshape(y), jnp.asarray(mask.reshape(n_batches, batch_size))
