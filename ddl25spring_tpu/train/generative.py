"""Generative modeling: VAE training + the synthetic-data evaluation protocol.

Capability targets (lab/tutorial_2a/generative-modeling.py):
- `train_vae` — minibatch Adam on the BatchNorm-MLP VAE with the MSE(sum)+KLD
  `customLoss` (:119-128, training loop :131-163).
- `synthetic_data_eval` — the evaluation protocol (:165-209): draw synthetic
  rows from the trained VAE (decode z ~ N(0, I)), train one evaluator
  classifier on the REAL training set and another on the SYNTHETIC set, and
  compare their accuracies on the same held-out real test set. Synthetic data
  is "good" when the synthetic-trained evaluator approaches the real-trained
  one.

Labels for synthetic rows: the reference trains the VAE per-class (one VAE on
each label's rows) so sampled rows inherit the class of their generator —
`synthetic_data_eval` follows that per-class scheme.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..config import VAEConfig
from ..models import vae
from .tabular import train_classifier


@dataclass
class VAEReport:
    total_losses: List[float] = field(default_factory=list)   # per epoch means
    mse_losses: List[float] = field(default_factory=list)
    kld_losses: List[float] = field(default_factory=list)


def train_vae(x_train: np.ndarray, cfg: Optional[VAEConfig] = None, *,
              log_every: int = 0, log_fn: Callable[[str], None] = print
              ) -> Tuple[dict, dict, VAEReport]:
    """Train the VAE; returns (params, batchnorm_state, report)."""
    cfg = cfg or VAEConfig(input_dim=int(x_train.shape[1]))
    params, state = vae.init(jax.random.key(cfg.seed), cfg)
    optimizer = optax.adam(cfg.lr)
    opt_state = optimizer.init(params)

    n = x_train.shape[0]
    # BatchNorm needs full batches, so the tail remainder is dropped; a
    # training set smaller than batch_size becomes one full-dataset batch.
    bs = min(cfg.batch_size, n)
    n_batches = n // bs
    x_use = x_train[:n_batches * bs]
    xb = jnp.asarray(x_use.reshape(n_batches, bs, -1), jnp.float32)

    def minibatch_step(carry, batch):
        params, state, opt_state, key = carry
        x = batch
        key, sub = jax.random.split(key)

        def loss_fn(p):
            recon, mu, logvar, new_state = vae.apply(p, state, x, sub, train=True)
            total, mse, kld = vae.loss_fn(recon, x, mu, logvar)
            return total, (mse, kld, new_state)

        (total, (mse, kld, new_state)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, new_state, opt_state, key), (total, mse, kld)

    @jax.jit
    def epoch_fn(params, state, opt_state, key):
        (params, state, opt_state, _), (tot, mse, kld) = jax.lax.scan(
            minibatch_step, (params, state, opt_state, key), xb)
        return params, state, opt_state, tot.mean(), mse.mean(), kld.mean()

    report = VAEReport()
    key = jax.random.key(cfg.seed + 1)
    for epoch in range(cfg.epochs):
        key, sub = jax.random.split(key)
        params, state, opt_state, tot, mse, kld = epoch_fn(params, state, opt_state, sub)
        report.total_losses.append(float(tot))
        report.mse_losses.append(float(mse))
        report.kld_losses.append(float(kld))
        if log_every and epoch % log_every == 0:
            log_fn(f"epoch {epoch}: loss {report.total_losses[-1]:.2f} "
                   f"(mse {report.mse_losses[-1]:.2f} kld {report.kld_losses[-1]:.2f})")
    return params, state, report


@dataclass
class SyntheticEvalResult:
    real_accuracy: float
    synthetic_accuracy: float
    vae_reports: List[VAEReport] = field(default_factory=list)


def synthetic_data_eval(x_train: np.ndarray, y_train: np.ndarray,
                        x_test: np.ndarray, y_test: np.ndarray,
                        cfg: Optional[VAEConfig] = None, *,
                        evaluator_epochs: int = 200,
                        seed: int = 0) -> SyntheticEvalResult:
    """The full real-vs-synthetic protocol on a binary tabular task."""
    cfg = cfg or VAEConfig(input_dim=int(x_train.shape[1]))
    synth_x, synth_y, reports = [], [], []
    for label in np.unique(y_train):
        rows = x_train[y_train == label]
        params, state, rep = train_vae(rows, cfg)
        reports.append(rep)
        out = vae.sample(jax.random.key(seed + int(label)), params, state,
                         len(rows), cfg.latent_dim)
        synth_x.append(np.asarray(out))
        synth_y.append(np.full(len(rows), label, y_train.dtype))
    synth_x = np.concatenate(synth_x)
    synth_y = np.concatenate(synth_y)

    _, real_rep = train_classifier(x_train, y_train, x_test, y_test,
                                   epochs=evaluator_epochs, seed=seed)
    _, synth_rep = train_classifier(synth_x, synth_y, x_test, y_test,
                                    epochs=evaluator_epochs, seed=seed)
    return SyntheticEvalResult(real_rep.best_accuracy, synth_rep.best_accuracy,
                               reports)
