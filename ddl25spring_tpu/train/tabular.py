"""Centralized tabular training: the heart-disease classifier baseline.

Capability target: the reference's centralized trainer (lab/tutorial_2a/
centralized.py:30-70) — minibatch Adam on the 4-layer `HeartDiseaseNN` MLP,
evaluating on the test set every epoch and keeping the BEST parameters by
test accuracy (centralized.py:51,67-70 snapshots/reloads state_dict).

Also the evaluator used by the synthetic-data protocol (train on real vs
synthetic, compare test accuracy — generative-modeling.py:165-209), which is
this same trainer pointed at a different training set.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..models import tabular
from ..ops import cross_entropy_loss
from .batching import pad_batches


@dataclass
class ClassifierReport:
    train_losses: List[float] = field(default_factory=list)   # per epoch
    test_accuracies: List[float] = field(default_factory=list)
    best_accuracy: float = 0.0
    best_epoch: int = -1


def train_classifier(x_train: np.ndarray, y_train: np.ndarray,
                     x_test: np.ndarray, y_test: np.ndarray, *,
                     epochs: int = 200, batch_size: int = 64, lr: float = 1e-3,
                     hidden=(64, 128, 256), seed: int = 0,
                     log_every: int = 0,
                     log_fn: Callable[[str], None] = print
                     ) -> Tuple[list, ClassifierReport]:
    """Returns (best_params, report) — best by test accuracy, like the
    reference's best-state_dict tracking."""
    in_dim = int(x_train.shape[1])
    params = tabular.init(jax.random.key(seed), in_dim, hidden)
    optimizer = optax.adam(lr)
    opt_state = optimizer.init(params)

    (xb,), yb, mb = pad_batches([x_train.astype(np.float32)], y_train, batch_size)
    xt, yt = jnp.asarray(x_test, jnp.float32), jnp.asarray(y_test)

    def minibatch_step(carry, batch):
        params, opt_state = carry
        x, y, m, key = batch

        def loss_fn(p):
            return cross_entropy_loss(tabular.apply(p, x, key=key), y, m)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state), loss * m.sum()

    @jax.jit
    def epoch_fn(params, opt_state, epoch_key):
        keys = jax.random.split(epoch_key, yb.shape[0])
        (params, opt_state), losses = jax.lax.scan(
            minibatch_step, (params, opt_state), (xb, yb, mb, keys))
        # Evaluation is deterministic (no dropout key) — the reference omits
        # model.eval() here (a quirk we do not reproduce; see models.tabular).
        acc = (tabular.apply(params, xt).argmax(-1) == yt).mean()
        return params, opt_state, losses.sum() / mb.sum(), acc

    report = ClassifierReport()
    best_params = params
    dropout_key = jax.random.key(seed + 1)
    for epoch in range(epochs):
        params, opt_state, loss, acc = epoch_fn(
            params, opt_state, jax.random.fold_in(dropout_key, epoch))
        acc = float(acc)
        report.train_losses.append(float(loss))
        report.test_accuracies.append(acc)
        if acc > report.best_accuracy:
            report.best_accuracy, report.best_epoch = acc, epoch
            best_params = params
        if log_every and epoch % log_every == 0:
            log_fn(f"epoch {epoch}: loss {report.train_losses[-1]:.4f} test acc {acc:.4f}")
    return best_params, report
