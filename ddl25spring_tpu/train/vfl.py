"""Vertical-FL / split-learning training harnesses.

Capability targets:
- `train_vfl` — the reference's `VFLNetwork.train_with_settings(epochs, bs,
  ...)` joint training loop over vertically-partitioned features
  (lab/tutorial_2b/vfl.py:53-85): per-epoch minibatch Adam, train
  accuracy+loss per epoch, final test accuracy ≈85% on heart.csv with 4
  parties.
- `train_vfl_vae` — the hw2 ex3 hybrid: client encoders → concat(mu) →
  server VAE → split synthetic latents → client decoders, joint loss
  Σ per-client MSE + KL/batch (lab/hw02/Tea_Pula_HW2.ipynb cells 32-41,
  total ≈4.1 at 1000 epochs).

Two trainer modes:
- default (``faithful=False``): the intended semantics — every parameter
  trains on each minibatch's own gradient, dropout disabled at test time.
  On the reference's duplicate-leaking heart.csv split this trains to
  98-100%.
- ``faithful=True``: reproduces the four reference protocol quirks its
  published 84.8-85.3% band was measured through:
  (1) **frozen bottom models** — ``VFLNetwork`` keeps its bottoms in a
  plain Python list, not an ``nn.ModuleList`` (vfl.py:48), so
  ``optim.AdamW(self.parameters())`` (vfl.py:50) never sees their
  parameters: gradients flow to the clients' models but they are NEVER
  stepped; the entire run trains only the server's top model on frozen
  random client features. This is the dominant quirk — it alone caps the
  system near the published band (measured: torch-side parameter count
  41,346 seen by the optimizer vs 1,596 bottom params excluded, and
  bottom weights bit-identical after training);
  (2) ``optim.AdamW`` — decoupled weight decay at torch's defaults
  lr=1e-3, wd=1e-2;
  (3) ``zero_grad()`` once per EPOCH (vfl.py:62), so the step at
  minibatch k applies the running SUM of minibatch gradients 1..k;
  (4) ``test()`` uses ``torch.no_grad()`` but never ``.eval()``
  (vfl.py:91-102) — and ``.eval()`` could not reach the list-held
  bottoms anyway — so evaluation runs with dropout STILL ACTIVE,
  including the Dropout(0.1) on the output logits (vfl.py:40): the
  reported accuracy is one stochastic dropout draw.
  The per-quirk attribution is measured in experiments/hw2_vfl.py.

TPU-native shape: one jitted `lax.scan` over padded minibatches per epoch —
party feature widths differ, so per-party arrays ride the scan as a tuple;
the partial last batch is handled by masking, not dropped.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..config import VFLConfig
from ..models import vfl_nets
from ..ops import cross_entropy_loss
from .batching import pad_batches


@dataclass
class VFLReport:
    train_losses: List[float] = field(default_factory=list)   # per epoch
    train_accuracies: List[float] = field(default_factory=list)
    test_accuracy: float = 0.0        # under the trainer's own eval protocol
    test_accuracy_clean: float = 0.0  # always dropout-off (intended eval)


def train_vfl(xs_train: Sequence[np.ndarray], y_train: np.ndarray,
              xs_test: Sequence[np.ndarray], y_test: np.ndarray,
              cfg: Optional[VFLConfig] = None, *,
              faithful: bool = False,
              train_bottoms: Optional[bool] = None,
              accumulate_epoch_grads: Optional[bool] = None,
              eval_dropout: Optional[bool] = None,
              weight_decay: Optional[float] = None,
              log_every: int = 0,
              log_fn: Callable[[str], None] = print) -> Tuple[dict, VFLReport]:
    """Jointly train bottoms+top over vertically-partitioned features.

    ``xs_train[i]`` is party i's feature slice [N, d_i]. Returns the trained
    params and per-epoch train metrics + final test accuracy.

    ``faithful=True`` enables all four reference protocol quirks (module
    docstring); the keyword overrides toggle each quirk independently for
    attribution (None ⇒ follow ``faithful``):
    ``train_bottoms=False`` — the dominant quirk: bottom models receive
    gradients but are never stepped (the reference's plain-list /
    ``self.parameters()`` bug), so only the top model learns;
    ``weight_decay`` — AdamW decoupled decay (reference default 1e-2);
    ``accumulate_epoch_grads`` — zero-grad once per epoch, each step applies
    the epoch's running gradient sum; ``eval_dropout`` — evaluate with
    dropout active (one stochastic draw), the reference's missing-.eval()
    protocol. ``report.test_accuracy`` follows the eval protocol chosen;
    ``report.test_accuracy_clean`` is always the dropout-off number.
    """
    cfg = cfg or VFLConfig()
    bottoms_train = ((not faithful) if train_bottoms is None
                     else train_bottoms)
    accumulate = (faithful if accumulate_epoch_grads is None
                  else accumulate_epoch_grads)
    drop_eval = faithful if eval_dropout is None else eval_dropout
    wd = (1e-2 if faithful else 0.0) if weight_decay is None else weight_decay

    feature_dims = [int(a.shape[1]) for a in xs_train]
    params = vfl_nets.init_vfl(jax.random.key(cfg.seed), feature_dims,
                               bottom_out_mult=cfg.bottom_out_mult)
    optimizer = (optax.adamw(cfg.lr, weight_decay=wd) if wd
                 else optax.adam(cfg.lr))
    opt_state = optimizer.init(params)

    xs_b, y_b, m_b = pad_batches(xs_train, y_train, cfg.batch_size)
    zero_grads = jax.tree.map(jnp.zeros_like, params)

    def minibatch_step(carry, batch):
        params, opt_state, accum = carry
        xs, y, m, key = batch

        def loss_fn(p):
            logits = vfl_nets.vfl_forward(p, xs, key=key)
            return cross_entropy_loss(logits, y, m), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        if accumulate:
            # Reference quirk (vfl.py:62): .grad is never zeroed within an
            # epoch, so step k sees the SUM of minibatch grads 1..k.
            grads = accum = jax.tree.map(jnp.add, accum, grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if not bottoms_train:
            # Dominant reference quirk (vfl.py:48-50): the bottoms live in
            # a plain list outside self.parameters(), so the optimizer
            # never steps them — zero their UPDATES (not their grads: in
            # torch they are absent from the optimizer entirely, so no
            # AdamW decay reaches them either).
            updates = {"top": updates["top"],
                       "bottoms": jax.tree.map(jnp.zeros_like,
                                               updates["bottoms"])}
        params = optax.apply_updates(params, updates)
        correct = ((logits.argmax(-1) == y) * m).sum()
        return (params, opt_state, accum), (loss * m.sum(), correct, m.sum())

    @jax.jit
    def epoch_fn(params, opt_state, epoch_key):
        keys = jax.random.split(epoch_key, y_b.shape[0])
        (params, opt_state, _), (losses, correct, counts) = jax.lax.scan(
            minibatch_step, (params, opt_state, zero_grads),
            (xs_b, y_b, m_b, keys))
        n = counts.sum()
        return params, opt_state, losses.sum() / n, correct.sum() / n

    xs_te = tuple(jnp.asarray(a) for a in xs_test)
    y_te = jnp.asarray(y_test)

    @jax.jit
    def test_acc(params, key=None):
        logits = vfl_nets.vfl_forward(params, xs_te, key=key)
        return (logits.argmax(-1) == y_te).mean()

    report = VFLReport()
    dropout_key = jax.random.key(cfg.seed + 1)
    for epoch in range(cfg.epochs):
        params, opt_state, loss, acc = epoch_fn(
            params, opt_state, jax.random.fold_in(dropout_key, epoch))
        report.train_losses.append(float(loss))
        report.train_accuracies.append(float(acc))
        if log_every and epoch % log_every == 0:
            log_fn(f"epoch {epoch}: loss {report.train_losses[-1]:.4f} "
                   f"acc {report.train_accuracies[-1]:.4f}")
    report.test_accuracy_clean = float(test_acc(params))
    if drop_eval:
        # One stochastic dropout draw — exactly what the reference reports
        # (test() under no_grad but the module still in training mode).
        report.test_accuracy = float(
            test_acc(params, jax.random.key(cfg.seed + 2)))
    else:
        report.test_accuracy = report.test_accuracy_clean
    return params, report


# ------------------------------------------------------------- VFL-VAE hybrid

@dataclass
class VFLVAEReport:
    total_losses: List[float] = field(default_factory=list)   # per epoch
    recon_losses: List[float] = field(default_factory=list)
    kl_losses: List[float] = field(default_factory=list)


def train_vfl_vae(xs_train: Sequence[np.ndarray],
                  cfg: Optional[VFLConfig] = None, *,
                  epochs: int = 1000,
                  client_latent: int = 4,
                  log_every: int = 0,
                  log_fn: Callable[[str], None] = print) -> Tuple[dict, VFLVAEReport]:
    """Train the hw2 ex3 VFL-VAE on vertically-partitioned features.

    Full-batch per epoch with a fresh reparameterization key, matching the
    reference's training loop (Tea_Pula_HW2.ipynb cell 40; final total ≈4.10
    = recon 3.97 + KL 0.128 with 4 clients × latent 4). NOTE the reference's
    4.10 is trained with 3 of its 4 clients' encoder/decoders FROZEN — its
    cell-38 `add_module("client_encoder", enc)` loop registers every client
    module under one name, so only the last client's models reach
    `parameters()` (measured: 1,535 of 5,640 encoder params registered).
    This trainer optimizes all parties, so its totals land far lower;
    see PARITY.md for the attribution.
    """
    cfg = cfg or VFLConfig()
    feature_dims = [int(a.shape[1]) for a in xs_train]
    params = vfl_nets.init_vfl_vae(jax.random.key(cfg.seed), feature_dims,
                                   client_latent=client_latent)
    # client_latent rides the pytree as static metadata — keep it out of optax.
    static = {"client_latent": params.pop("client_latent")}
    optimizer = optax.adam(cfg.lr)
    opt_state = optimizer.init(params)
    xs = tuple(jnp.asarray(a) for a in xs_train)

    @jax.jit
    def step(params, opt_state, key):
        def loss_fn(p):
            recons, mu, logvar = vfl_nets.vfl_vae_forward({**p, **static}, xs, key)
            total, recon, kl = vfl_nets.vfl_vae_loss(recons, xs, mu, logvar)
            return total, (recon, kl)

        (total, (recon, kl)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, total, recon, kl

    report = VFLVAEReport()
    key = jax.random.key(cfg.seed + 1)
    for epoch in range(epochs):
        key, sub = jax.random.split(key)
        params, opt_state, total, recon, kl = step(params, opt_state, sub)
        report.total_losses.append(float(total))
        report.recon_losses.append(float(recon))
        report.kl_losses.append(float(kl))
        if log_every and epoch % log_every == 0:
            log_fn(f"epoch {epoch}: total {report.total_losses[-1]:.4f} "
                   f"(recon {report.recon_losses[-1]:.4f} kl {report.kl_losses[-1]:.4f})")
    return {**params, **static}, report
