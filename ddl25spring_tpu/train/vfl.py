"""Vertical-FL / split-learning training harnesses.

Capability targets:
- `train_vfl` — the reference's `VFLNetwork.train_with_settings(epochs, bs,
  ...)` joint training loop over vertically-partitioned features
  (lab/tutorial_2b/vfl.py:53-85): per-epoch minibatch Adam, train
  accuracy+loss per epoch, final test accuracy ≈85% on heart.csv with 4
  parties.
- `train_vfl_vae` — the hw2 ex3 hybrid: client encoders → concat(mu) →
  server VAE → split synthetic latents → client decoders, joint loss
  Σ per-client MSE + KL/batch (lab/hw02/Tea_Pula_HW2.ipynb cells 32-41,
  total ≈4.1 at 1000 epochs).

Documented deviation: the reference calls ``optimizer.zero_grad()`` once per
EPOCH (vfl.py:62), so each minibatch step applies the running sum of all
previous minibatch gradients of that epoch — an accumulation quirk, not a
design choice. Here each step uses its own minibatch gradient (the intended
semantics); convergence matches the reference's reported accuracy band.

TPU-native shape: one jitted `lax.scan` over padded minibatches per epoch —
party feature widths differ, so per-party arrays ride the scan as a tuple;
the partial last batch is handled by masking, not dropped.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..config import VFLConfig
from ..models import vfl_nets
from ..ops import cross_entropy_loss
from .batching import pad_batches


@dataclass
class VFLReport:
    train_losses: List[float] = field(default_factory=list)   # per epoch
    train_accuracies: List[float] = field(default_factory=list)
    test_accuracy: float = 0.0


def train_vfl(xs_train: Sequence[np.ndarray], y_train: np.ndarray,
              xs_test: Sequence[np.ndarray], y_test: np.ndarray,
              cfg: Optional[VFLConfig] = None, *,
              log_every: int = 0,
              log_fn: Callable[[str], None] = print) -> Tuple[dict, VFLReport]:
    """Jointly train bottoms+top over vertically-partitioned features.

    ``xs_train[i]`` is party i's feature slice [N, d_i]. Returns the trained
    params and per-epoch train metrics + final test accuracy.
    """
    cfg = cfg or VFLConfig()
    feature_dims = [int(a.shape[1]) for a in xs_train]
    params = vfl_nets.init_vfl(jax.random.key(cfg.seed), feature_dims,
                               bottom_out_mult=cfg.bottom_out_mult)
    optimizer = optax.adam(cfg.lr)
    opt_state = optimizer.init(params)

    xs_b, y_b, m_b = pad_batches(xs_train, y_train, cfg.batch_size)

    def minibatch_step(carry, batch):
        params, opt_state = carry
        xs, y, m, key = batch

        def loss_fn(p):
            logits = vfl_nets.vfl_forward(p, xs, key=key)
            return cross_entropy_loss(logits, y, m), logits

        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        correct = ((logits.argmax(-1) == y) * m).sum()
        return (params, opt_state), (loss * m.sum(), correct, m.sum())

    @jax.jit
    def epoch_fn(params, opt_state, epoch_key):
        keys = jax.random.split(epoch_key, y_b.shape[0])
        (params, opt_state), (losses, correct, counts) = jax.lax.scan(
            minibatch_step, (params, opt_state), (xs_b, y_b, m_b, keys))
        n = counts.sum()
        return params, opt_state, losses.sum() / n, correct.sum() / n

    @jax.jit
    def test_acc(params):
        logits = vfl_nets.vfl_forward(params, tuple(jnp.asarray(a) for a in xs_test))
        return (logits.argmax(-1) == jnp.asarray(y_test)).mean()

    report = VFLReport()
    dropout_key = jax.random.key(cfg.seed + 1)
    for epoch in range(cfg.epochs):
        params, opt_state, loss, acc = epoch_fn(
            params, opt_state, jax.random.fold_in(dropout_key, epoch))
        report.train_losses.append(float(loss))
        report.train_accuracies.append(float(acc))
        if log_every and epoch % log_every == 0:
            log_fn(f"epoch {epoch}: loss {report.train_losses[-1]:.4f} "
                   f"acc {report.train_accuracies[-1]:.4f}")
    report.test_accuracy = float(test_acc(params))
    return params, report


# ------------------------------------------------------------- VFL-VAE hybrid

@dataclass
class VFLVAEReport:
    total_losses: List[float] = field(default_factory=list)   # per epoch
    recon_losses: List[float] = field(default_factory=list)
    kl_losses: List[float] = field(default_factory=list)


def train_vfl_vae(xs_train: Sequence[np.ndarray],
                  cfg: Optional[VFLConfig] = None, *,
                  epochs: int = 1000,
                  client_latent: int = 4,
                  log_every: int = 0,
                  log_fn: Callable[[str], None] = print) -> Tuple[dict, VFLVAEReport]:
    """Train the hw2 ex3 VFL-VAE on vertically-partitioned features.

    Full-batch per epoch with a fresh reparameterization key, matching the
    reference's training loop (Tea_Pula_HW2.ipynb cell 40; final total ≈4.10
    = recon 3.97 + KL 0.128 with 4 clients × latent 4).
    """
    cfg = cfg or VFLConfig()
    feature_dims = [int(a.shape[1]) for a in xs_train]
    params = vfl_nets.init_vfl_vae(jax.random.key(cfg.seed), feature_dims,
                                   client_latent=client_latent)
    # client_latent rides the pytree as static metadata — keep it out of optax.
    static = {"client_latent": params.pop("client_latent")}
    optimizer = optax.adam(cfg.lr)
    opt_state = optimizer.init(params)
    xs = tuple(jnp.asarray(a) for a in xs_train)

    @jax.jit
    def step(params, opt_state, key):
        def loss_fn(p):
            recons, mu, logvar = vfl_nets.vfl_vae_forward({**p, **static}, xs, key)
            total, recon, kl = vfl_nets.vfl_vae_loss(recons, xs, mu, logvar)
            return total, (recon, kl)

        (total, (recon, kl)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, total, recon, kl

    report = VFLVAEReport()
    key = jax.random.key(cfg.seed + 1)
    for epoch in range(epochs):
        key, sub = jax.random.split(key)
        params, opt_state, total, recon, kl = step(params, opt_state, sub)
        report.total_losses.append(float(total))
        report.recon_losses.append(float(recon))
        report.kl_losses.append(float(kl))
        if log_every and epoch % log_every == 0:
            log_fn(f"epoch {epoch}: total {report.total_losses[-1]:.4f} "
                   f"(recon {report.recon_losses[-1]:.4f} kl {report.kl_losses[-1]:.4f})")
    return {**params, **static}, report
