"""Shared benchmark timing cores, used by bench.py and experiments/*.

One implementation of "time the DP train step / the decode loop on this
platform" so the headline bench and the experiment harnesses cannot drift
in timing methodology. All timings are async-dispatch honest: the timed
chain ends in a host transfer (``float(loss)``) because
``block_until_ready`` is unreliable on the tunneled-TPU platform this
project benches on.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp

from .config import LlamaConfig
from .models import llama
from .ops.adam import fused_adam
from .parallel import dp


def make_optimizer(opt_name: str, lr: float = 8e-4):
    """"fused" = single-pass fused Adam (ops/adam.py — same update as
    optax.adam, asserted ≤1e-6 in tests/test_core.py, fewer HBM round trips
    over the parameter-sized state); "pallas" = the fully-fused Pallas apply
    (ops/pallas_adam.py — moments + param write in one kernel pass per
    leaf); "master" = fp32-master-weight Adam for bf16 params
    (ops/mixed_precision.py — pair with ``param_dtype="bfloat16"``). The
    optimizer leg is memory-bound either way; benches measure which fusion
    wins on the chip at hand."""
    if opt_name == "pallas":
        from .ops.pallas_adam import FusedApplyAdam
        return FusedApplyAdam(lr)
    if opt_name == "master":
        from .ops.mixed_precision import master_weight_adam
        return master_weight_adam(lr)
    if opt_name != "fused":
        raise ValueError(
            f"unknown optimizer {opt_name!r}: expected one of "
            "'fused', 'pallas', 'master'")
    return fused_adam(lr)


def time_train_step(mesh, cfg: LlamaConfig, batch_size: int, *,
                    seq: Optional[int] = None, opt_name: str = "fused",
                    wire: Optional[str] = None,
                    warmup: int = 3, timed_steps: int = 20,
                    steps_per_dispatch: int = 1,
                    aggregation: str = "gradient",
                    overlap_microbatches: int = 0,
                    comm_buckets: int = 1) -> float:
    """Total tokens/sec of the DP train step at the given per-chip batch.

    ``seq`` defaults to ``cfg.ctx_size``. The caller divides by its device
    count for a per-chip figure. ``wire`` ∈ {None, "bf16", "int8_ef"}
    selects the compressed-allreduce step (parallel/compress.py) — on one
    chip the collective is local, so the measurement is the compression
    math's overhead (quantize + error-feedback), the number VERDICT r4
    asked for alongside the multi-chip design.

    ``steps_per_dispatch`` = K > 1 times the fused K-step scan driver
    (parallel/dp.py ``make_multi_step``): the same warmup/timed step budget
    is spent in ceil-divided windows of K, so the token accounting stays
    comparable with the per-step rows while the dispatch overhead is paid
    once per window. ``aggregation`` ∈ {"gradient", "zero1"} picks the
    plain pmean path or the ZeRO-1 sharded weight update; both compose
    with ``steps_per_dispatch`` (``make_zero1_multi_step``).

    ``overlap_microbatches`` = M >= 1 times the overlapped ring driver
    (parallel/compress.py ``make_overlap_*``) instead — the path where
    ``wire`` (fp32/bf16/int8_ef in-flight ring chunks) composes with
    zero1 AND steps_per_dispatch; M = 0 keeps the legacy composition
    rules, where ``wire`` needs per-step gradient aggregation. On a
    hierarchical mesh (hier_data_mesh), pass the per-axis dict
    ``wire={"ici": ..., "dcn": ...}`` (requires M >= 1) — the two-level
    topology-aware driver; ``dp.shard_batch``/``shard_batch_window``
    place the batch over both data axes automatically.

    ``comm_buckets`` = B > 1 (requires M >= 1) runs the bucketed
    backward: per-bucket ring dispatch in VJP emission order, so the
    first hop overlaps the remaining grad compute — the ISSUE 19
    sub-1/n chunking rows."""
    seq = seq or cfg.ctx_size
    n_dev = mesh.devices.size
    K = max(1, int(steps_per_dispatch))
    M = int(overlap_microbatches)
    B = max(1, int(comm_buckets))
    params = llama.init_llama(jax.random.key(0), cfg)
    opt = make_optimizer(opt_name)

    def loss_fn(p, batch):
        return llama.forward_loss(p, batch, cfg)

    if M == 0 and wire is not None and (aggregation != "gradient"
                                        or K != 1):
        raise ValueError("wire compression composes with per-step gradient "
                         "aggregation only (pass overlap_microbatches >= 1 "
                         "for the composing ring driver)")
    if M == 0 and B > 1:
        raise ValueError("comm_buckets > 1 needs the overlapped ring driver "
                         "(pass overlap_microbatches >= 1)")
    if M >= 1:
        from .parallel import compress
        maker = (compress.make_overlap_multi_step if K > 1
                 else compress.make_overlap_step)
        state, step = maker(loss_fn, opt, mesh, params, microbatches=M,
                            wire=wire or "fp32", aggregation=aggregation,
                            comm_buckets=B)
    elif wire == "bf16":
        from .parallel import compress
        state = dp.replicate(mesh, dp.init_state(params, opt))
        step = compress.make_bf16_grad_step(loss_fn, opt, mesh)
    elif wire == "int8_ef":
        from .parallel import compress
        state = compress.init_ef_state(mesh, params, opt)
        step = compress.make_int8_ef_grad_step(loss_fn, opt, mesh)
    elif wire is None and aggregation == "zero1":
        if K > 1:
            state, step = dp.make_zero1_multi_step(loss_fn, opt, mesh, params)
        else:
            state, step = dp.make_zero1_step(loss_fn, opt, mesh, params)
    elif wire is None and aggregation == "gradient":
        if K > 1:
            step = dp.make_multi_step(loss_fn, opt, mesh)
        else:
            step = dp.make_grad_aggregation_step(loss_fn, opt, mesh)
        state = dp.replicate(mesh, dp.init_state(params, opt))
    else:
        raise ValueError(f"unknown wire/aggregation {wire!r}/{aggregation!r}")
    tokens = jax.random.randint(jax.random.key(1), (n_dev * batch_size, seq),
                                0, cfg.vocab_size)
    if K > 1:
        window = dp.shard_batch_window(
            mesh, jnp.broadcast_to(tokens, (K,) + tokens.shape))
        warm_chunks = max(1, -(-warmup // K))
        timed_chunks = max(1, -(-timed_steps // K))
        for _ in range(warm_chunks):
            state, losses = step(state, window)
        float(losses[-1])  # hard sync before the timer
        t0 = time.perf_counter()
        for _ in range(timed_chunks):
            state, losses = step(state, window)
        float(losses[-1])  # forces the whole timed chain
        dt = time.perf_counter() - t0
        del state
        return n_dev * batch_size * seq * timed_chunks * K / dt

    batch = dp.shard_batch(mesh, tokens)
    for _ in range(warmup):
        state, loss = step(state, batch)
    float(loss)  # hard sync before the timer
    t0 = time.perf_counter()
    for _ in range(timed_steps):
        state, loss = step(state, batch)
    float(loss)  # forces the whole timed chain
    dt = time.perf_counter() - t0
    del state
    return n_dev * batch_size * seq * timed_steps / dt


def time_pp_train_step(mesh, cfg: LlamaConfig, batch_size: int, *,
                       seq: Optional[int] = None,
                       n_microbatches: int = 1, schedule: str = "gpipe",
                       opt_name: str = "fused",
                       wire: Optional[str] = None,
                       warmup: int = 3, timed_steps: int = 20,
                       steps_per_dispatch: int = 1,
                       aggregation: str = "gradient",
                       overlap_microbatches: int = 0) -> float:
    """Total tokens/sec of the PIPELINE train step — ``time_train_step``'s
    contract on a ``(data, stage)`` mesh (parallel/pp.py).

    ``batch_size`` is per data shard (must divide by ``n_microbatches``);
    the return is TOTAL tokens/sec — ``n_data · batch_size`` tokens per
    step, because stage devices share one batch — and the caller divides
    by its device count for the per-chip figure. The lever spellings match
    ``time_train_step`` one for one so sweep rows stay comparable:
    ``steps_per_dispatch`` = K > 1 times the fused K-step scan driver
    (``pp.make_pipeline_multi_step`` — any schedule, bitwise to K=1);
    ``overlap_microbatches`` = M >= 1 routes the DP×PP data-axis sync
    through the compressed/overlapped ring
    (``pp.make_pipeline_overlap_*``), where ``wire`` and
    ``aggregation="zero1"`` compose; M = 0 is the plain pmean data sync
    (``wire``/zero1 then unsupported, matching the trainer's rules)."""
    from .parallel import pp

    seq = seq or cfg.ctx_size
    n_data = mesh.shape.get("data", 1)
    K = max(1, int(steps_per_dispatch))
    M = int(overlap_microbatches)
    params = llama.init_llama(jax.random.key(0), cfg)
    opt = make_optimizer(opt_name)

    if M >= 1:
        maker = (pp.make_pipeline_overlap_multi_step if K > 1
                 else pp.make_pipeline_overlap_step)
        state, step = maker(cfg, opt, mesh, params,
                            n_microbatches=n_microbatches,
                            schedule=schedule, aggregation=aggregation,
                            wire=wire or "fp32", overlap_microbatches=M)
    else:
        if wire is not None or aggregation != "gradient":
            raise ValueError("PP wire compression / zero1 route through "
                             "the ring driver: pass "
                             "overlap_microbatches >= 1")
        state = pp.init_state(mesh, params, opt)
        maker = (pp.make_pipeline_multi_step if K > 1
                 else pp.make_pipeline_step)
        step = maker(cfg, opt, mesh, n_microbatches=n_microbatches,
                     schedule=schedule)
    tokens = jax.random.randint(jax.random.key(1),
                                (n_data * batch_size, seq),
                                0, cfg.vocab_size)
    if K > 1:
        window = pp.shard_batch_window(
            mesh, jnp.broadcast_to(tokens, (K,) + tokens.shape))
        warm_chunks = max(1, -(-warmup // K))
        timed_chunks = max(1, -(-timed_steps // K))
        for _ in range(warm_chunks):
            state, losses = step(state, window)
        float(losses[-1])  # hard sync before the timer
        t0 = time.perf_counter()
        for _ in range(timed_chunks):
            state, losses = step(state, window)
        float(losses[-1])  # forces the whole timed chain
        dt = time.perf_counter() - t0
        del state
        return n_data * batch_size * seq * timed_chunks * K / dt

    batch = pp.shard_batch(mesh, tokens)
    for _ in range(warmup):
        state, loss = step(state, batch)
    float(loss)  # hard sync before the timer
    t0 = time.perf_counter()
    for _ in range(timed_steps):
        state, loss = step(state, batch)
    float(loss)  # forces the whole timed chain
    dt = time.perf_counter() - t0
    del state
    return n_data * batch_size * seq * timed_steps / dt


def time_tp_train_step(mesh, cfg: LlamaConfig, batch_size: int, *,
                       seq: Optional[int] = None,
                       opt_name: str = "fused",
                       psa: str = "",
                       wire: Optional[str] = None,
                       warmup: int = 3, timed_steps: int = 20,
                       steps_per_dispatch: int = 1,
                       aggregation: str = "gradient",
                       overlap_microbatches: int = 0) -> float:
    """Total tokens/sec of the TENSOR-PARALLEL train step —
    ``time_train_step``'s contract on a ``(data, model)`` mesh
    (parallel/tp.py).

    ``batch_size`` is per data shard; the return is TOTAL tokens/sec —
    ``n_data · batch_size`` tokens per step, because model devices share
    one batch — and the caller divides by its device count for the
    per-chip figure. The lever spellings match ``time_pp_train_step`` one
    for one, plus ``psa`` for the partially-synchronized-activation modes
    (TrainConfig.psa: "" / "full" / "defer:L" / "int8_ef"):
    ``steps_per_dispatch`` = K > 1 times the fused K-step scan driver
    (``tp.make_tp_multi_step``, bitwise to K=1); ``overlap_microbatches``
    = M >= 1 routes the DP×TP data-axis sync through the
    compressed/overlapped ring (``tp.make_tp_overlap_*``), where ``wire``
    and ``aggregation="zero1"`` compose; M = 0 is the plain pmean data
    sync (``wire``/zero1 then unsupported, matching the trainer's
    rules)."""
    from .parallel import tp

    seq = seq or cfg.ctx_size
    n_data = mesh.shape.get("data", 1)
    K = max(1, int(steps_per_dispatch))
    M = int(overlap_microbatches)
    params = llama.init_llama(jax.random.key(0), cfg)
    opt = make_optimizer(opt_name)

    if M >= 1:
        maker = (tp.make_tp_overlap_multi_step if K > 1
                 else tp.make_tp_overlap_step)
        state, step = maker(cfg, opt, mesh, params,
                            aggregation=aggregation, wire=wire or "fp32",
                            overlap_microbatches=M, psa=psa)
    else:
        if wire is not None or aggregation != "gradient":
            raise ValueError("TP wire compression / zero1 route through "
                             "the ring driver: pass "
                             "overlap_microbatches >= 1")
        maker = tp.make_tp_multi_step if K > 1 else tp.make_tp_step
        state, step = maker(cfg, opt, mesh, params, psa=psa,
                            batch_shape=(batch_size, seq))
    tokens = jax.random.randint(jax.random.key(1),
                                (n_data * batch_size, seq),
                                0, cfg.vocab_size)
    if K > 1:
        window = tp.shard_batch_window(
            mesh, jnp.broadcast_to(tokens, (K,) + tokens.shape))
        warm_chunks = max(1, -(-warmup // K))
        timed_chunks = max(1, -(-timed_steps // K))
        for _ in range(warm_chunks):
            state, losses = step(state, window)
        float(losses[-1])  # hard sync before the timer
        t0 = time.perf_counter()
        for _ in range(timed_chunks):
            state, losses = step(state, window)
        float(losses[-1])  # forces the whole timed chain
        dt = time.perf_counter() - t0
        del state
        return n_data * batch_size * seq * timed_chunks * K / dt

    batch = tp.shard_batch(mesh, tokens)
    for _ in range(warmup):
        state, loss = step(state, batch)
    float(loss)  # hard sync before the timer
    t0 = time.perf_counter()
    for _ in range(timed_steps):
        state, loss = step(state, batch)
    float(loss)  # forces the whole timed chain
    dt = time.perf_counter() - t0
    del state
    return n_data * batch_size * seq * timed_steps / dt


def time_decode(cfg: LlamaConfig, batch: int, prompt_len: int = 64,
                new_tokens: int = 128, bf16_params: bool = False,
                kv_dtype: Optional[str] = None, reps: int = 3) -> float:
    """Generated tokens/sec for the KV-cache decode loop (models/generate).

    The two serving levers, matching the decode roofline's two HBM streams
    (experiments/ROOFLINE.md): ``bf16_params`` halves the weight bytes —
    dominant at batch 1 (training keeps fp32 master params; casting a copy
    for inference is the deployment shape); ``kv_dtype="bfloat16"`` halves
    the cache bytes — dominant once the batch amortizes the weights."""
    from .models import generate as gen
    params = llama.init_llama(jax.random.key(0), cfg)
    if bf16_params:
        params = jax.tree.map(
            lambda a: a.astype(jnp.bfloat16)
            if a.dtype == jnp.float32 else a, params)
    prompt = jax.random.randint(jax.random.key(1), (batch, prompt_len),
                                0, cfg.vocab_size)
    out = gen.generate(params, prompt, cfg, new_tokens, kv_dtype=kv_dtype)
    jax.block_until_ready(out)                      # compile + warm
    t0 = time.perf_counter()
    for _ in range(reps):
        out = gen.generate(params, prompt, cfg, new_tokens,
                           kv_dtype=kv_dtype)
    jax.block_until_ready(out)
    return batch * new_tokens * reps / (time.perf_counter() - t0)
