"""Distributed tracing: span contexts over the JSONL event stream.

The telemetry layer so far emits *flat* events — a ``request_done`` says a
request finished, but nothing links its enqueue→prefill→decode→retire hops
into one causal timeline a human (or Perfetto) can open. This module is the
span layer on top (ISSUE 8 tentpole):

- ``SpanContext``: (trace_id, span_id, parent_span_id) — the identity a
  span hands to its children. Propagation is EXPLICIT: contexts are passed
  as arguments, never stashed in thread-locals, so nothing can leak into
  (or be captured by) jit-compiled code — the zero-in-jit-overhead
  invariant of the comm wrappers extends to tracing by construction.
- ``Tracer``: opens spans against an ``EventLog``; each CLOSED span is one
  schema-v4 ``span`` event (monotonic-ns start + duration from the
  tracer's clock). ``events=None`` makes every span a no-op emit while
  still accumulating phase totals — so un-telemetered runs keep their
  phase accounting through the same code path.
- Adapters: ``Spans`` (named wall-clock accumulators) and ``StepTimer``
  (async-honest per-step timing) live HERE now — ``utils/tracing.py``
  re-exports them — and a ``Tracer(phases=Spans())`` feeds every completed
  span into the accumulator, so ``MetricsRegistry.absorb_spans`` works off
  the one tracing path instead of a parallel one.
- ``device_trace``: the jax.profiler wrapper, upgraded: while a device
  trace is active, every ``Tracer`` span also enters a
  ``jax.profiler.TraceAnnotation``, so HOST spans land on the XLA profiler
  timeline next to the device ops they dispatched. Outside an active
  device trace the hook is a single flag check — host-only runs pay
  nothing and the module stays importable without jax.
- ``trace_trees`` / ``tree_check``: jax-free reassembly of a recorded
  stream into per-trace span trees, with the orphan/imbalance self-checks
  obs_report and the serving smoke's completeness bar use.

Emission preserves the layer's invariants: ``EventLog.emit`` never raises,
the stream stays strict JSON, and span ids are per-tracer counters (not
random), so equal runs produce equal streams — the exporter golden test
depends on it.

>>> tracer = Tracer(telemetry.events)
>>> with tracer.span("request", trace="req-0007", prompt_len=16) as root:
...     with tracer.span("queue", parent=root.ctx):
...         wait_for_slot()
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from typing import Any, Dict, Iterator, List, Optional, Sequence

from .events import EventLog


class SpanContext:
    """The identity one span hands to its children — what crosses function
    boundaries (explicitly; never a thread-local)."""

    __slots__ = ("trace_id", "span_id", "parent_span_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_span_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id

    def as_dict(self) -> Dict[str, Optional[str]]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_span_id": self.parent_span_id}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SpanContext":
        return cls(d["trace_id"], d["span_id"], d.get("parent_span_id"))

    def __repr__(self) -> str:
        return (f"SpanContext({self.trace_id!r}, {self.span_id!r}, "
                f"parent={self.parent_span_id!r})")

    def __eq__(self, other) -> bool:
        return (isinstance(other, SpanContext)
                and self.as_dict() == other.as_dict())


class Span:
    """One open span. ``end()`` emits the event (idempotent: the second
    call is a no-op, so a manual-lifecycle caller crossing error paths
    can't double-emit). Usable manually (serving holds request spans open
    across many scheduler ticks) or via ``Tracer.span``'s context
    manager."""

    __slots__ = ("_tracer", "ctx", "name", "start_ns", "attrs", "_phase",
                 "_annotation", "_ended")

    def __init__(self, tracer: "Tracer", ctx: SpanContext, name: str,
                 start_ns: int, attrs: Dict[str, Any], phase: Optional[str],
                 annotation):
        self._tracer = tracer
        self.ctx = ctx
        self.name = name
        self.start_ns = start_ns
        self.attrs = attrs
        self._phase = phase
        self._annotation = annotation
        self._ended = False

    def end(self, **attrs: Any) -> None:
        if self._ended:
            return
        self._ended = True
        if self._annotation is not None:
            with contextlib.suppress(Exception):
                self._annotation.__exit__(None, None, None)
        self.attrs.update(attrs)
        self._tracer._finish(self)


class Tracer:
    """Span factory over an EventLog (or over nothing — ``events=None``
    keeps the phase accounting and skips emission).

    - ``clock_ns``: monotonic-nanosecond clock. Defaults to
      ``time.monotonic_ns``; the serving scheduler passes its own
      (fast-forwarded) clock so spans line up with queue-wait/TTFT
      semantics, and tests pass a fake for deterministic streams.
    - ``phases``: an optional ``Spans`` accumulator every completed span
      feeds (under ``phase`` when given, else the span name) — the
      adapter that keeps ``registry.absorb_spans`` working.
    - Span ids are ``s<tracer>.<n>`` from a per-tracer counter behind a
      process-wide tracer discriminator: deterministic streams (equal runs
      construct tracers in equal order), and unique within a (run_id,
      trace) even when SEVERAL tracers emit on one trace — the training
      loop and the elastic controller both write the "train" trace, and a
      collision would make ``trace_trees`` silently overwrite spans.
    """

    _instances = 0
    _instances_lock = threading.Lock()

    def __init__(self, events: Optional[EventLog] = None, *,
                 clock_ns=time.monotonic_ns,
                 phases: Optional["Spans"] = None):
        self.events = events
        self.clock_ns = clock_ns
        self.phases = phases
        self._lock = threading.Lock()
        self._n = 0
        with Tracer._instances_lock:
            Tracer._instances += 1
            self._id = Tracer._instances

    def _next_id(self) -> str:
        with self._lock:
            self._n += 1
            return f"s{self._id}.{self._n}"

    def start(self, name: str, *, parent: Optional[SpanContext] = None,
              trace: Optional[str] = None, phase=None,
              **attrs: Any) -> Span:
        """Open a span. A root span names its ``trace`` (e.g. the request
        id); a child inherits the parent's. ``phase`` overrides the name
        the ``phases`` accumulator files the duration under; ``False``
        skips accumulation (an umbrella span whose children already cover
        its wall time must not double-count the phase totals)."""
        if parent is not None:
            ctx = SpanContext(parent.trace_id, self._next_id(),
                              parent.span_id)
        else:
            ctx = SpanContext(trace if trace is not None else "main",
                              self._next_id())
        annotation = None
        if _profiling():
            # Host span → XLA profiler timeline (jax.profiler
            # TraceAnnotation), only while a device trace is live: outside
            # one this is a single module-flag check, and the import never
            # happens in jax-free processes.
            with contextlib.suppress(Exception):
                import jax
                annotation = jax.profiler.TraceAnnotation(name)
                annotation.__enter__()
        return Span(self, ctx, name, int(self.clock_ns()), dict(attrs),
                    phase, annotation)

    @contextlib.contextmanager
    def span(self, name: str, *, parent: Optional[SpanContext] = None,
             trace: Optional[str] = None, phase=None,
             **attrs: Any) -> Iterator[Span]:
        s = self.start(name, parent=parent, trace=trace, phase=phase,
                       **attrs)
        try:
            yield s
        except BaseException:
            s.end(error=True)
            raise
        s.end()

    def _finish(self, span: Span) -> None:
        dur_ns = max(0, int(self.clock_ns()) - span.start_ns)
        if self.phases is not None and span._phase is not False:
            self.phases.add(span._phase or span.name, dur_ns / 1e9)
        if self.events is not None:
            self.events.span(name=span.name, trace_id=span.ctx.trace_id,
                             span_id=span.ctx.span_id,
                             parent_span_id=span.ctx.parent_span_id,
                             start_ns=span.start_ns, dur_ns=dur_ns,
                             **span.attrs)


# --------------------------------------------------------- wall-clock phases

class Spans:
    """Named wall-clock accumulators — the phase-accounting half of the
    tracing path (absorbed by ``MetricsRegistry.absorb_spans``; fed by
    ``Tracer(phases=...)`` or used standalone).

    Thread-safe: a watchdog/monitoring thread and the training thread may
    accumulate into one instance concurrently (the lock covers the
    read-modify-write of the accumulators, not the timed block itself).

    >>> spans = Spans()
    >>> with spans("update"):
    ...     do_work()
    >>> spans.total("update")
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._acc: Dict[str, float] = defaultdict(float)
        self._count: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def __call__(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        with self._lock:
            self._acc[name] += seconds
            self._count[name] += 1

    def total(self, name: str) -> float:
        with self._lock:
            return self._acc[name]

    def count(self, name: str) -> int:
        with self._lock:
            return self._count[name]

    def as_dict(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._acc)

    def reset(self) -> None:
        with self._lock:
            self._acc.clear()
            self._count.clear()


class StepTimer:
    """Per-step timing that is honest under async dispatch: ``tick`` blocks
    on the step's outputs before reading the clock.

    ``tick()`` before ``start()`` raises instead of silently recording a
    0.0 step (the old behavior poisoned means with zeros — percentile
    consumers in telemetry.MetricsRegistry would inherit the lie).
    Thread-safe for the same reason as Spans."""

    def __init__(self):
        self.times: List[float] = []
        self._t0: Optional[float] = None
        self._lock = threading.Lock()

    def start(self) -> None:
        with self._lock:
            self._t0 = time.perf_counter()

    def tick(self, *outputs) -> float:
        if outputs:
            import jax
            for out in outputs:
                jax.block_until_ready(out)
        now = time.perf_counter()
        with self._lock:
            if self._t0 is None:
                raise RuntimeError(
                    "StepTimer.tick() before start(): the interval has no "
                    "beginning — call start() once before the timed loop")
            dt = now - self._t0
            self.times.append(dt)
            self._t0 = now
        return dt

    @property
    def mean(self) -> float:
        with self._lock:
            return sum(self.times) / max(len(self.times), 1)


# ------------------------------------------------------------- device traces

# Set while a jax.profiler device trace is live (device_trace below):
# Tracer.start checks it before paying any jax import or TraceAnnotation
# cost, so tracing stays free for host-only runs and jax-free processes.
_DEVICE_TRACE_DEPTH = 0


def _profiling() -> bool:
    return _DEVICE_TRACE_DEPTH > 0


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """jax.profiler device trace (XLA ops, HBM, ICI) → TensorBoard/Perfetto
    trace in ``log_dir``. While active, every ``Tracer`` span also enters a
    ``jax.profiler.TraceAnnotation``, so the host-side spans (queue waits,
    chunk staging, checkpoint writes) appear ON the device timeline — the
    correlation the ACCO-style overlap work needs to verify that "overlap"
    is real rather than inferred from aggregate step times."""
    global _DEVICE_TRACE_DEPTH
    import jax
    jax.profiler.start_trace(log_dir)
    _DEVICE_TRACE_DEPTH += 1
    try:
        yield
    finally:
        _DEVICE_TRACE_DEPTH -= 1
        jax.profiler.stop_trace()


# ------------------------------------------------------------ tree reassembly

def trace_trees(events: Sequence[Dict[str, Any]]
                ) -> Dict[str, Dict[str, Any]]:
    """Reassemble span events into per-trace trees.

    Returns ``{trace_id: {"spans": {span_id: event}, "roots": [event],
    "children": {span_id: [event]}, "orphans": [event]}}`` — an orphan is
    a span whose ``parent_span_id`` names a span the stream never closed
    (a crashed writer, or a propagation bug). Non-span events are ignored,
    so callers can feed a whole stream. Span ids are only unique within a
    (run_id, trace) — relaunches sharing one file re-use both the trace
    name ("train") and the id sequence — so trees are partitioned per
    run_id first, and when several runs used one trace name the extra
    runs' trees are keyed ``"run_id/trace_id"`` rather than silently
    overwriting the first run's spans."""
    by_run: Dict[tuple, Dict[str, Any]] = {}
    for e in events:
        if e.get("type") != "span":
            continue
        key = (e.get("run_id", "?"), e.get("trace_id", "?"))
        t = by_run.setdefault(key, {"spans": {}, "roots": [],
                                    "children": {}, "orphans": []})
        t["spans"][e.get("span_id")] = e
    out: Dict[str, Dict[str, Any]] = {}
    for (run, trace), t in by_run.items():
        out[trace if trace not in out else f"{run}/{trace}"] = t
    for t in out.values():
        for e in t["spans"].values():
            parent = e.get("parent_span_id")
            if parent is None:
                t["roots"].append(e)
            elif parent in t["spans"]:
                t["children"].setdefault(parent, []).append(e)
            else:
                t["orphans"].append(e)
        for kids in t["children"].values():
            kids.sort(key=lambda e: e.get("start_ns", 0))
        t["roots"].sort(key=lambda e: e.get("start_ns", 0))
    return out


def tree_check(tree: Dict[str, Any]) -> Dict[str, int]:
    """Self-check one ``trace_trees`` entry: ``roots`` (a complete request/
    round tree has exactly one), ``orphans`` (must be zero), ``imbalanced``
    (spans whose children's summed duration exceeds their own by >1% —
    an accounting bug: children are wall-clock subintervals of the
    parent)."""
    imbalanced = 0
    for pid, kids in tree["children"].items():
        parent = tree["spans"][pid]
        if (sum(k.get("dur_ns", 0) for k in kids)
                > parent.get("dur_ns", 0) * 1.01 + 1000):
            imbalanced += 1
    return {"roots": len(tree["roots"]), "orphans": len(tree["orphans"]),
            "imbalanced": imbalanced}
