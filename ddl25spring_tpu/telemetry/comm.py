"""Trace-time communication-volume accounting for the parallel layer.

Evaluating the comm-efficiency directions in PAPERS.md — compressed
allreduce (DynamiQ, arxiv 2602.08923) and quantized allreduce in XLA
(EQuARX, arxiv 2506.17615) — needs per-collective byte counts that the
stack previously never produced. This module provides them with ZERO
in-jit overhead: the ``pmean``/``psum``/... wrappers below delegate
straight to ``jax.lax`` (the compiled HLO is bit-identical to calling lax
directly), but while JAX is *tracing* the step they record each
collective's operand payload into the active collector. Tracing happens
once per compilation, in Python, so the accounting is static — measured at
trace time, free at run time.

Usage: ``parallel/{dp,tp,sp,ep,pp,compress}.py`` call these wrappers
instead of raw lax collectives, and

    profile = measure_comm(step_fn, state, batch)   # or ShapeDtypeStructs

abstractly traces the step (``jax.eval_shape`` — no compile, no execute)
with a collector installed. The resulting ``CommProfile`` reports payload
bytes and estimated per-device wire bytes per step, per collective label.

Accounting semantics (what the numbers MEAN):
- ``payload_bytes`` is the local operand size in its wire dtype — the
  quantity the compression levers act on (bf16 halves it, int8 quarters
  it vs fp32).
- ``wire_bytes_per_device`` applies the standard ring-algorithm factors to
  the payload: allreduce (psum/pmean/pmax) ``2·(n−1)/n``, all_gather
  ``(n−1)`` × the local shard sent, psum_scatter ``(n−1)/n``, ppermute
  ``1`` (one neighbor send). n = the mesh axis size; n = 1 makes every
  reduce's wire cost 0, as it should.
- ``scale`` multiplies a record for collectives inside ``lax.scan`` bodies,
  which trace once but execute many times — the call site passes the trip
  count (e.g. the SP ring passes its hop count, PP its tick count).

Known under-count, by design: collectives SYNTHESIZED by autodiff
transposition (e.g. the backward hops of a differentiated in-forward
ppermute, or psum transposes in TP/PP forward bodies) never appear in user
code, so trace-time accounting cannot see them. The post-AD data-parallel
collectives — the gradient allreduce family that the compressed-wire work
targets — are exact. Call sites that KNOW their op is differentiated pass
``scale=2`` (forward + cotangent) where that correction applies.
"""

from __future__ import annotations

import contextlib
import contextvars
import math
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

import jax
import numpy as np
from jax import lax

_collector: contextvars.ContextVar[Optional[list]] = \
    contextvars.ContextVar("ddl25_comm_collector", default=None)


@dataclass(frozen=True)
class CommRecord:
    """One collective call site, as seen at trace time."""
    op: str                  # pmean | psum | pmax | all_gather | ...
    label: str               # call-site semantic name ("grad_allreduce", ...)
    axis: str                # mesh axis name
    axis_size: Optional[int]  # None when not resolvable at trace time
    payload_bytes: int       # local operand bytes in the wire dtype
    scale: int               # executions per step (scan trip count, ...)

    @property
    def wire_bytes_per_device(self) -> float:
        """Ring-algorithm per-device wire estimate for ONE execution.

        An unresolvable axis size (both `_axis_size` probes failed — future
        API drift) must NOT silently zero the reduce factors the way n=1
        legitimately does: report factor 1.0 (within 2x of any real ring
        reduce) and let the record's ``axis_size: None`` flag the estimate
        as degraded."""
        n = self.axis_size
        if n is None:
            return float(self.payload_bytes)
        if self.op in ("pmean", "psum", "pmax"):
            factor = 2.0 * (n - 1) / n
        elif self.op == "all_gather":
            factor = float(n - 1)
        elif self.op == "psum_scatter":
            factor = (n - 1) / n
        elif self.op == "ppermute":
            factor = 1.0 if n > 1 else 0.0
        else:
            factor = 1.0
        return factor * self.payload_bytes

    def as_dict(self) -> dict:
        return {"op": self.op, "label": self.label, "axis": self.axis,
                "axis_size": self.axis_size,
                "payload_bytes": int(self.payload_bytes),
                "scale": int(self.scale),
                "wire_bytes_per_device": self.wire_bytes_per_device}


@dataclass
class CommProfile:
    """All collectives of one traced step, with per-step aggregates."""
    records: List[CommRecord] = field(default_factory=list)

    @property
    def payload_bytes_per_step(self) -> int:
        return sum(r.payload_bytes * r.scale for r in self.records)

    @property
    def wire_bytes_per_device_per_step(self) -> float:
        return sum(r.wire_bytes_per_device * r.scale for r in self.records)

    def by_label(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for r in self.records:
            agg = out.setdefault(r.label, {
                "op": r.op, "axis": r.axis, "axis_size": r.axis_size,
                "calls": 0, "payload_bytes": 0,
                "wire_bytes_per_device": 0.0})
            agg["calls"] += r.scale
            agg["payload_bytes"] += r.payload_bytes * r.scale
            agg["wire_bytes_per_device"] += r.wire_bytes_per_device * r.scale
        return out

    def by_axis(self) -> Dict[str, dict]:
        """Per-MESH-AXIS aggregates — the hierarchical-collective budget
        view (parallel/compress.py two-level drivers): every record
        carries the axis its collective crossed, so DCN-axis bytes (the
        scarce tier of a ``hier_data_mesh``) aggregate separately from
        ICI-axis bytes. The CI wire gate (experiments/comm_wire_smoke.py)
        reads the ``dcn`` entry; the flat ring's single ``data`` axis
        aggregates exactly as the per-step totals do."""
        out: Dict[str, dict] = {}
        for r in self.records:
            agg = out.setdefault(r.axis, {
                "axis_size": r.axis_size, "calls": 0, "payload_bytes": 0,
                "wire_bytes_per_device": 0.0})
            agg["calls"] += r.scale
            agg["payload_bytes"] += r.payload_bytes * r.scale
            agg["wire_bytes_per_device"] += r.wire_bytes_per_device * r.scale
        return out

    def as_dict(self, *, steps_per_dispatch: int = 1,
                overlap_microbatches: int = 1) -> dict:
        """JSON-able shape for the run manifest / bench telemetry block.

        The profile's aggregates cover one traced CALL. For a fused
        multi-step driver (parallel/dp.py ``make_multi_step``,
        parallel/pp.py ``make_pipeline_multi_step`` and the DP×PP overlap
        drivers — every PP collective records at ``scale=K`` through the
        bodies' ``comm_scale``) one call is one dispatch of K steps —
        pass ``steps_per_dispatch=K`` and the dict carries the
        per-TRAIN-STEP normalization alongside the per-dispatch totals,
        so "wire bytes per step" stays comparable across K (the
        no-regression check the zero1/scan work is held to).

        Normalization rule (pinned in tests/test_telemetry.py so future
        drivers can't double-count): the per-train-step figures divide the
        per-dispatch totals by ``steps_per_dispatch`` ONLY. The overlap
        driver's M microbatch rings (parallel/compress.py) are all part of
        ONE step's traffic — its unrolled ring hops each record their own
        ppermute at ``scale=K``, so dividing by K already yields the exact
        per-step bytes, and dividing by M as well would under-count a
        step's wire M×. ``overlap_microbatches`` = M > 1 instead ADDS the
        per-microbatch-ring view (per-train-step ÷ M) alongside, for
        readers sizing one ring trip.
        """
        d = {
            "payload_bytes_per_step": self.payload_bytes_per_step,
            "wire_bytes_per_device_per_step":
                self.wire_bytes_per_device_per_step,
            "collectives": self.by_label(),
            # Per-axis attribution (``by_axis``): on a hierarchical mesh
            # the ``dcn`` entry IS the scarce-tier budget; per-train-step
            # normalization follows the same ÷K-only rule as the totals.
            "axes": {
                ax: {**agg, **({"wire_bytes_per_device_per_train_step":
                                agg["wire_bytes_per_device"]
                                / steps_per_dispatch}
                               if steps_per_dispatch > 1 else {})}
                for ax, agg in self.by_axis().items()
            },
        }
        if steps_per_dispatch > 1:
            d["steps_per_dispatch"] = int(steps_per_dispatch)
            d["payload_bytes_per_train_step"] = \
                self.payload_bytes_per_step / steps_per_dispatch
            d["wire_bytes_per_device_per_train_step"] = \
                self.wire_bytes_per_device_per_step / steps_per_dispatch
        if overlap_microbatches > 1:
            d["overlap_microbatches"] = int(overlap_microbatches)
            per_step = (self.wire_bytes_per_device_per_step
                        / steps_per_dispatch)
            d["wire_bytes_per_device_per_microbatch"] = \
                per_step / overlap_microbatches
        return d


def tree_bytes(tree: Any) -> int:
    """Exact byte count of a pytree's leaves (shape × dtype itemsize) —
    the unit of every payload figure in this module. Public because the
    FL fleet engine (fl/fleet.py) accounts its tier-crossing uploads with
    the same rule the collective wrappers use, so 'payload bytes' means
    one thing across the whole telemetry stream."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", None)
        itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
        total += int(math.prod(shape)) * itemsize
    return total


_tree_bytes = tree_bytes          # internal alias (pre-v3 call sites)


def _axis_size(axis_name: str) -> Optional[int]:
    """Static axis size at trace time, across this jax's API drift
    (0.4.37: ``core.axis_frame(name)`` returns a plain int; newer builds
    have ``lax.axis_size``; see parallel/_compat.py, not imported here to
    keep telemetry dependency-free of the parallel layer)."""
    try:
        return int(lax.axis_size(axis_name))          # newer jax
    except Exception:
        pass
    try:
        frame = jax.core.axis_frame(axis_name)        # jax 0.4.37
        return int(getattr(frame, "size", frame))
    except Exception:
        return None


def _record(op: str, label: Optional[str], axis_name: str, operand: Any,
            scale: int) -> None:
    col = _collector.get()
    if col is None:
        return
    col.append(CommRecord(op=op, label=label or op, axis=axis_name,
                          axis_size=_axis_size(axis_name),
                          payload_bytes=_tree_bytes(operand),
                          scale=int(scale)))


# ------------------------------------------------------------- the wrappers
# Same signatures as jax.lax (plus label/scale); compiled output identical.

def pmean(x, axis_name: str, *, label: Optional[str] = None,
          scale: int = 1):
    _record("pmean", label, axis_name, x, scale)
    return lax.pmean(x, axis_name)


def psum(x, axis_name: str, *, label: Optional[str] = None, scale: int = 1):
    _record("psum", label, axis_name, x, scale)
    return lax.psum(x, axis_name)


def pmax(x, axis_name: str, *, label: Optional[str] = None, scale: int = 1):
    _record("pmax", label, axis_name, x, scale)
    return lax.pmax(x, axis_name)


def all_gather(x, axis_name: str, *, tiled: bool = False,
               label: Optional[str] = None, scale: int = 1):
    _record("all_gather", label, axis_name, x, scale)
    return lax.all_gather(x, axis_name, tiled=tiled)


def psum_scatter(x, axis_name: str, *, scatter_dimension: int = 0,
                 tiled: bool = False, label: Optional[str] = None,
                 scale: int = 1):
    _record("psum_scatter", label, axis_name, x, scale)
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=tiled)


def ppermute(x, axis_name: str, perm, *, label: Optional[str] = None,
             scale: int = 1):
    _record("ppermute", label, axis_name, x, scale)
    return lax.ppermute(x, axis_name, perm)


# ------------------------------------------------------------- measurement

@contextlib.contextmanager
def collecting() -> Iterator[List[CommRecord]]:
    """Install a fresh collector for the duration of the block; any tracing
    that happens inside lands its collective records in the yielded list."""
    records: List[CommRecord] = []
    token = _collector.set(records)
    try:
        yield records
    finally:
        _collector.reset(token)


def measure_comm(fn, *args, **kwargs) -> Optional[CommProfile]:
    """Static comm profile of one call of ``fn(*args)``.

    Abstractly traces ``fn`` via ``jax.eval_shape`` — no compile, no
    execution, and the trace lands in the jit cache, so measuring a
    freshly built step BEFORE its first real call costs nothing extra.
    Arguments may be real pytrees or ``jax.ShapeDtypeStruct``s.

    A function whose trace is already cached re-uses it without running the
    Python body, which would silently record nothing — in that case the
    one retry after ``jax.clear_caches()`` forces a fresh trace (and evicts
    warm compilations: prefer measuring before first execution). Returns
    None when tracing itself fails.
    """
    for attempt in (0, 1):
        with collecting() as records:
            try:
                jax.eval_shape(fn, *args, **kwargs)
            except Exception:
                return None
        if records:
            return CommProfile(records)
        if attempt == 0:
            jax.clear_caches()
    return CommProfile([])       # traced fresh; genuinely no collectives
