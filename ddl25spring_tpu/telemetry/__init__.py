"""Unified telemetry layer (ISSUE 2 tentpole).

One observability subsystem the whole stack reports through:

- ``events``: schema-versioned, append-only JSONL event stream (run
  manifest, per-step records, fault events, FL round summaries).
- ``registry``: MetricsRegistry — counters/gauges/histograms with
  p50/p95/p99, absorbing Spans, StepTimer and ResilienceStats as adapters.
- ``comm``: trace-time communication-volume accounting around the
  collectives in parallel/{dp,tp,sp,ep,pp,compress}.py — bytes per
  psum/all-gather per step, computed statically, zero in-jit overhead.
- ``costs``: compiled-HLO cost analysis via lower().compile()
  .cost_analysis(), guarded for jax API drift; cross-checks bench.py's
  analytic FLOPs.
- ``memory``: unified memory observability (schema v9) — guarded
  ``memory_analysis()`` program footprints, the jax-free ``MemoryMeter``
  live sampler (host RSS, state/mirror bytes, KV pool occupancy +
  fragmentation), and the ``preflight`` per-device fit estimator the
  headroom SLO and autoscaler guard rail read.
- ``heartbeat``: atomic liveness file consumed by experiments/watchdog.py
  as a first-class stall signal.
- ``trace``: span contexts (trace/span/parent ids, explicit propagation)
  over the event stream — per-request/per-round causal timelines,
  exported to Perfetto by experiments/trace_export.py and watched live by
  experiments/slo_monitor.py.

``Telemetry`` bundles the per-run pieces (event log + heartbeat +
registry) behind one handle the trainers/servers accept.
Render a recorded run with ``python -m experiments.obs_report <dir>``.
"""

from __future__ import annotations

import os
from typing import Optional

from .costs import flops_crosscheck, hlo_cost
from .events import (EventLog, SCHEMA_VERSION, default_run_id, read_events,
                     validate_event)
from .heartbeat import Heartbeat, read_heartbeat
from .introspect import (CompileWatch, FlightRecorder, NumericsSummary,
                         bind_events, make_summarizer, platform_peaks,
                         watch)
from .memory import (MemoryMeter, allocator_census, compiled_memory,
                     host_rss_bytes, preflight, program_memory)
from .registry import MetricsRegistry
from .trace import (Span, SpanContext, Spans, Tracer, device_trace,
                    trace_trees, tree_check)

# comm.py imports jax at module level; everything else here is stdlib-only.
# Lazy re-export (PEP 562) keeps jax OUT of processes that only read
# telemetry — the watchdog's LivenessMonitor and experiments/obs_report
# import telemetry submodules and must stay featherweight/jax-free.
_LAZY_COMM = ("CommProfile", "measure_comm")


def __getattr__(name: str):
    if name in _LAZY_COMM:
        from . import comm
        return getattr(comm, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "CommProfile", "CompileWatch", "EventLog", "FlightRecorder",
    "Heartbeat", "MemoryMeter", "MetricsRegistry", "NumericsSummary",
    "SCHEMA_VERSION",
    "Span", "SpanContext", "Spans", "Telemetry", "Tracer",
    "allocator_census", "bind_events", "compiled_memory",
    "default_run_id", "device_trace", "flops_crosscheck", "hlo_cost",
    "host_rss_bytes", "make_summarizer", "measure_comm", "platform_peaks",
    "preflight", "program_memory", "read_events",
    "read_heartbeat", "trace_trees", "tree_check", "validate_event", "watch",
]

EVENTS_NAME = "events.jsonl"
HEARTBEAT_NAME = "heartbeat.json"


class Telemetry:
    """Per-run telemetry bundle: event log + heartbeat + metrics registry.

    >>> tel = Telemetry("/tmp/run")          # events.jsonl, heartbeat.json
    >>> train_llm_dp(..., telemetry=tel)
    >>> # python -m experiments.obs_report /tmp/run

    ``step_every`` is the per-step event cadence — each step event forces a
    host sync of the loss (same cost model as the trainers' ``loss_sink``),
    so the default matches the trainers' ``sink_every``. The heartbeat is
    sync-free and beats every iteration regardless.

    ``flight=True`` (default) arms the anomaly flight recorder
    (introspect.FlightRecorder): a bounded ring over this run's events,
    dumped as a self-contained postmortem bundle under
    ``<out_dir>/postmortem/`` the moment a ``fault``/``remesh``/
    ``slo_violation`` event crosses the stream. Zero cost until a trigger
    fires; render bundles with ``python -m experiments.postmortem``.
    """

    def __init__(self, out_dir: str, *, run_id: Optional[str] = None,
                 step_every: int = 10, flight: bool = True):
        os.makedirs(out_dir, exist_ok=True)
        self.out_dir = out_dir
        self.run_id = run_id or default_run_id()
        # Floor at 1: the trainers take `it % step_every`, and a 0 from a
        # "disable step events" misread would ZeroDivisionError-sink the
        # run — the one failure mode this layer promises never to cause.
        self.step_every = max(1, int(step_every))
        self.events = EventLog(os.path.join(out_dir, EVENTS_NAME),
                               run_id=self.run_id)
        self.heartbeat = Heartbeat(os.path.join(out_dir, HEARTBEAT_NAME))
        self.registry = MetricsRegistry()
        self.flight = None
        if flight:
            self.flight = FlightRecorder(os.path.join(out_dir, "postmortem"))
            self.events.observers.append(self.flight.observe)
        # No default Tracer here: every emitter needs its own (the
        # serving scheduler binds its fast-forwarded clock, the trainers
        # their phase accumulator), and an unused one would burn a slot
        # in the process-wide tracer-id sequence, making span ids depend
        # on how many Telemetry bundles were ever constructed. Build one
        # with ``Tracer(telemetry.events)``.

    @property
    def events_path(self) -> str:
        return self.events.path

    @property
    def heartbeat_path(self) -> str:
        return self.heartbeat.path

    def close(self) -> None:
        self.events.close()

    def __enter__(self) -> "Telemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
