"""Unified memory observability: device/host byte accounting (schema v9).

The stack observes time (spans), wire bytes (comm profiles), numerics and
compiles — this module closes the last unobserved axis, memory, with four
pieces sharing one schema-v9 ``memory`` event shape:

- **Static program footprint** — ``program_memory`` /
  ``compiled_memory`` pull ``compiled.memory_analysis()`` (argument /
  output / temp / generated-code bytes) behind ONE API-drift guard,
  following ``costs.hlo_cost``'s probe-normalize-degrade idiom: the
  jaxlib 0.4.x ``CompiledMemoryStats`` attribute names are probed, a
  missing method or a backend that can't account returns None, never a
  crash. ``introspect.CompileWatch`` stamps these onto every ``compile``
  event; the two benches that used to call ``memory_analysis()`` ad hoc
  (sp_bench, pp_schedules) route through here.
- **Live accounting** — ``MemoryMeter``, a jax-free sampler emitting one
  ``memory`` event per cadence point (trainer chunk edges, scheduler
  ticks): host RSS (``host_rss_bytes``), training-state / elastic-mirror
  bytes (``tree_state_bytes`` — shape × dtype arithmetic on host-visible
  metadata, NEVER a device sync), and KV pool occupancy + fragmentation
  (``allocator_census`` over ``BlockAllocator``'s free list). The meter
  is pure host bookkeeping: losses and served streams are bitwise
  identical with it on or off, and it adds zero dispatches/retraces
  (pinned in tests/test_memory.py and the CI memory smoke).
- **Preflight fit estimation** — ``preflight`` predicts the per-device
  byte budget (params + optimizer moments + EF residuals + batch window
  + KV pool) from configs alone, BEFORE any compile, via
  ``jax.eval_shape`` — cross-checked against the measured
  ``memory_analysis`` footprint (tests pin agreement within 10%, and
  the ZeRO-1 moments at ~1/n of replicated).
- **Headroom SLO feed** — every sample carries ``device_bytes`` (the sum
  of its device-resident components) so ``experiments/slo_monitor.py``'s
  ``--slo-headroom`` can judge free fraction against a ``--device-bytes``
  budget, and ``resilience/autoscale.py`` can refuse to scale serving
  into a pool that cannot fit it.

Import contract: jax-free at module scope (same as introspect's readers
and slo_monitor) — jax/comm/model imports happen lazily inside the
functions that need them, so the stdlib-only consumers (obs_report,
postmortem, slo_monitor, fleet_smoke's host sampler) can import this
module without dragging in a backend.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, Optional

# jaxlib 0.4.36 CompiledMemoryStats attribute names (verified on this
# container), probed one by one so a partial drift degrades field-wise
# instead of all-or-nothing. ``alias`` counts donated input buffers that
# XLA reuses for outputs — subtracted from the peak total below so a
# donated-state trainer is not double-billed for its state.
_STAT_FIELDS = (
    ("argument_bytes", "argument_size_in_bytes"),
    ("output_bytes", "output_size_in_bytes"),
    ("temp_bytes", "temp_size_in_bytes"),
    ("generated_code_bytes", "generated_code_size_in_bytes"),
    ("alias_bytes", "alias_size_in_bytes"),
)

# The components of one ``memory`` event that live in DEVICE memory —
# summed into ``device_bytes`` (the headroom SLO's numerator) when the
# sampler didn't provide a total itself.
_DEVICE_COMPONENTS = ("params_bytes", "opt_state_bytes", "residual_bytes",
                      "window_bytes", "pool_used_bytes")


def compiled_memory(compiled) -> Optional[dict]:
    """Static footprint of an ALREADY-compiled program, or None when this
    jaxlib/backend can't account it. The one API-drift guard the repo's
    three ``memory_analysis()`` call sites share (CompileWatch, sp_bench,
    pp_schedules)."""
    fn = getattr(compiled, "memory_analysis", None)
    if fn is None:
        return None
    try:
        stats = fn()
    except Exception:
        return None
    return _normalize_stats(stats)


def program_memory(jitted_fn, *args, **kwargs) -> Optional[dict]:
    """Static footprint of the compiled program for ``jitted_fn(*args)``.

    Mirrors ``costs.hlo_cost``: arguments may be real pytrees or
    ``jax.ShapeDtypeStruct``s; compiles the program if it isn't already —
    call where a compile is acceptable (CompileWatch only calls it on a
    dispatch that ALREADY paid a compile), not on a hot path. None when
    any link of lower→compile→memory_analysis is unavailable."""
    lower = getattr(jitted_fn, "lower", None)
    if lower is None:
        return None                       # not a jitted callable
    try:
        compiled = lower(*args, **kwargs).compile()
    except Exception:
        return None
    return compiled_memory(compiled)


def _normalize_stats(stats: Any) -> Optional[dict]:
    """CompiledMemoryStats (attrs) or a dict (hypothetical drift) → one
    flat dict of floats; None when nothing usable was reported."""
    if isinstance(stats, (list, tuple)):
        stats = stats[0] if stats else None
    if stats is None:
        return None
    out: Dict[str, Any] = {}
    for name, attr in _STAT_FIELDS:
        if isinstance(stats, dict):
            v = stats.get(attr, stats.get(name))
        else:
            v = getattr(stats, attr, None)
        try:
            v = float(v) if v is not None else None
        except (TypeError, ValueError):
            v = None
        if v is not None and v >= 0:
            out[name] = v
    if not any(k in out for k, _ in _STAT_FIELDS[:3]):
        return None                       # no byte accounting at all
    # Peak device residency of one dispatch: inputs + outputs + transients
    # + program code, minus the donated buffers counted on both sides.
    out["device_bytes"] = max(0.0, sum(
        out.get(k, 0.0) for k in ("argument_bytes", "output_bytes",
                                  "temp_bytes", "generated_code_bytes"))
        - out.get("alias_bytes", 0.0))
    return out


def host_rss_bytes() -> Optional[int]:
    """Peak resident-set size of this process in bytes (``ru_maxrss`` —
    KiB on Linux, bytes on macOS), or None where rusage is unavailable.
    The shared host sampler fleet_smoke's RSS-bound check and the
    MemoryMeter's ``rss_bytes`` field both read."""
    try:
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    except Exception:
        return None
    return int(ru) * (1 if sys.platform == "darwin" else 1024)


def tree_state_bytes(tree: Any) -> Optional[int]:
    """Exact logical bytes of a pytree's leaves (comm.tree_bytes — shape ×
    dtype itemsize, host-side metadata only, never a device sync), or
    None when jax is unavailable. For numpy-only trees (the elastic
    mirror's host snapshots) ``np_tree_bytes`` stays jax-free."""
    try:
        from .comm import tree_bytes
        return int(tree_bytes(tree))
    except Exception:
        return None


def np_tree_bytes(tree: Any) -> int:
    """Bytes of a HOST (numpy) pytree without importing jax: walks nested
    dict/list/tuple/NamedTuple containers summing leaf ``nbytes``. The
    elastic mirror census uses this so resilience stays jax-free."""
    if tree is None:
        return 0
    nbytes = getattr(tree, "nbytes", None)
    if nbytes is not None and not isinstance(tree, (dict, list, tuple)):
        try:
            return int(nbytes)
        except (TypeError, ValueError):
            return 0
    if isinstance(tree, dict):
        return sum(np_tree_bytes(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return sum(np_tree_bytes(v) for v in tree)
    return 0


def allocator_census(allocator, *, bytes_per_block: Optional[int] = None,
                     ) -> Dict[str, Any]:
    """One ``BlockAllocator``'s occupancy + fragmentation snapshot:
    ``blocks_in_use``/``free_blocks``/``peak_blocks_in_use`` plus the
    free-list ``holes``/``largest_run`` census. With ``bytes_per_block``
    (``pool_bytes / num_blocks``) occupancy also lands in bytes — the
    ``pool_used_bytes`` the headroom SLO sums into ``device_bytes``."""
    out: Dict[str, Any] = {
        "blocks_in_use": int(allocator.in_use),
        "free_blocks": int(allocator.free_blocks),
        "blocks_capacity": int(allocator.capacity),
        "peak_blocks_in_use": int(allocator.peak_in_use),
    }
    out.update(allocator.fragmentation())
    if bytes_per_block:
        out["pool_used_bytes"] = out["blocks_in_use"] * int(bytes_per_block)
        out["pool_capacity_bytes"] = (out["blocks_capacity"]
                                      * int(bytes_per_block))
        out["peak_pool_used_bytes"] = (out["peak_blocks_in_use"]
                                       * int(bytes_per_block))
    return out


class MemoryMeter:
    """Jax-free live memory sampler: one schema-v9 ``memory`` event per
    ``sample()`` call, merging static per-run figures (``note``-d once —
    e.g. the preflight's params/moments bytes) with the cadence point's
    live fields (mirror bytes, pool census, stream position).

    Zero-overhead contract: every field is host-side bookkeeping (RSS
    from rusage, byte figures from shape metadata, pool stats from the
    host allocator) — no device syncs, no extra dispatches, so losses
    and served streams are bitwise identical with the meter on or off.
    Emission is guarded like every telemetry writer: a broken event log
    loses the sample, never the run. ``events=None`` keeps the meter as
    a pure accumulator (``peaks`` still track) — fleet_smoke uses that
    to keep its RSS-bound check independent of telemetry being on.
    """

    def __init__(self, events=None, *, source: str = "host",
                 static: Optional[Dict[str, Any]] = None):
        self.events = events
        self.source = source
        self.static: Dict[str, Any] = dict(static or {})
        self.samples = 0
        # Running maxima of every numeric byte/occupancy field seen — the
        # ``peak_*_bytes`` bench rows and the postmortem census read these.
        self.peaks: Dict[str, float] = {}

    def note(self, **fields: Any) -> None:
        """Merge static per-run figures into every subsequent sample."""
        self.static.update({k: v for k, v in fields.items()
                            if v is not None})

    def sample(self, source: Optional[str] = None,
               **fields: Any) -> Dict[str, Any]:
        """One cadence point: returns the merged record and (when an
        event log is bound) emits it as a ``memory`` event."""
        rec = dict(self.static)
        rec.update({k: v for k, v in fields.items() if v is not None})
        rss = host_rss_bytes()
        if rss is not None:
            rec.setdefault("rss_bytes", rss)
        if "device_bytes" not in rec:
            parts = [rec[k] for k in _DEVICE_COMPONENTS
                     if isinstance(rec.get(k), (int, float))]
            if parts:
                rec["device_bytes"] = float(sum(parts))
        self.samples += 1
        for k, v in rec.items():
            if (isinstance(v, (int, float)) and not isinstance(v, bool)
                    and (k.endswith("_bytes") or k in ("blocks_in_use",
                                                       "holes"))):
                prev = self.peaks.get(k)
                self.peaks[k] = float(v) if prev is None else max(prev,
                                                                  float(v))
        if self.events is not None:
            try:
                self.events.memory(source=source or self.source, **rec)
            except Exception:
                pass               # a meter must never sink its host
        return rec


def preflight(model_cfg, train_cfg=None, *, mesh=None, n_data=None,
              aggregation: str = "gradient", optimizer=None,
              paged=None, serve_cfg=None) -> Optional[dict]:
    """Per-device byte budget BEFORE any compile: what the training state
    (params + optimizer moments + EF residuals), the batch window and the
    serving KV pool will occupy on one device, from configs alone via
    ``jax.eval_shape`` (abstract — no arrays materialize, nothing
    compiles). None when jax/the model can't be imported.

    The figures this pins (cross-checked against the measured
    ``memory_analysis`` footprint in tests/test_memory.py):

    - ``params_bytes`` — replicated per device in every DP aggregation;
    - ``opt_state_bytes`` — per device. ``aggregation="zero1"`` shards
      the moments: each device holds ``optimizer.init`` of its padded
      1/n flat slice (dp._zero1_setup's geometry), so this lands at
      ~1/n of ``opt_state_replicated_bytes`` — the ZeRO-1 memory-parity
      claim (arXiv 2004.13336) as a number instead of prose;
    - ``residual_bytes`` — the int8-ring EF residual trees
      (compress.OverlapEFState) when ``wire`` carries error feedback:
      one padded flat vector for the ring slice plus a 1/n gather slice;
    - ``window_bytes`` — the ``[K, B, T]`` int32 dispatch window's
      per-device shard (K = steps_per_dispatch, B = per-replica batch);
    - ``kv_pool_bytes`` — the paged serving pool (kvcache.pool_bytes)
      when ``paged`` is given (``serve_cfg`` defaults to ``model_cfg``).

    ``device_bytes`` totals the components — the number to hold against
    an accelerator's HBM (or slo_monitor's ``--device-bytes`` budget)
    before committing to a compile.
    """
    try:
        import math as _math

        import jax
        import jax.numpy as jnp

        from ..models import llama
        from .comm import tree_bytes
    except Exception:
        return None
    try:
        abstract = jax.eval_shape(
            lambda: llama.init_llama(jax.random.key(0), model_cfg))
        params_bytes = int(tree_bytes(abstract))
        count = sum(int(_math.prod(leaf.shape))
                    for leaf in jax.tree.leaves(abstract))
    except Exception:
        return None
    if n_data is None:
        if mesh is not None:
            n_data = (mesh.shape.get("data", 1)
                      * mesh.shape.get("dcn", 1))
        elif train_cfg is not None:
            n_data = train_cfg.data * max(1, train_cfg.dcn)
        else:
            n_data = 1
    n = max(1, int(n_data))
    if optimizer is None:
        try:
            import optax
            lr = train_cfg.lr if train_cfg is not None else 1e-3
            name = getattr(train_cfg, "optimizer", "adam")
            if name == "adam":
                optimizer = optax.adam(lr)
            else:
                from ..bench_utils import make_optimizer
                optimizer = make_optimizer(name, lr)
        except Exception:
            return None
    padded = -(-count // n) * n            # dp._zero1_setup's flat pad
    local = padded // n
    try:
        opt_replicated = int(tree_bytes(jax.eval_shape(optimizer.init,
                                                       abstract)))
        if aggregation == "zero1":
            opt_local = int(tree_bytes(jax.eval_shape(
                optimizer.init,
                jax.ShapeDtypeStruct((local,), jnp.float32))))
        else:
            opt_local = opt_replicated
    except Exception:
        return None
    residual_bytes = 0
    wire = getattr(train_cfg, "wire", "fp32") if train_cfg else "fp32"
    ovl = getattr(train_cfg, "overlap_microbatches", 0) if train_cfg else 0
    if ovl >= 1 and "ef" in str(wire):
        # OverlapEFState per device: ring_residual slice [1, Ppad] fp32 +
        # gather_residual's 1/n shard [Ppad/n] fp32.
        residual_bytes = 4 * (padded + local)
    window_bytes = 0
    if train_cfg is not None:
        K = max(1, getattr(train_cfg, "steps_per_dispatch", 1))
        window_bytes = (K * train_cfg.batch_size * train_cfg.seq_len
                        * 4)               # int32 tokens, per-device shard
    kv_pool_bytes = 0
    if paged is not None:
        try:
            from ..serving.kvcache import pool_bytes
            kv_pool_bytes = int(pool_bytes(serve_cfg or model_cfg, paged))
        except Exception:
            kv_pool_bytes = 0
    state_bytes = params_bytes + opt_local + residual_bytes
    return {
        "n_data": n,
        "param_count": int(count),
        "params_bytes": params_bytes,
        "opt_state_bytes": opt_local,
        "opt_state_replicated_bytes": opt_replicated,
        "residual_bytes": residual_bytes,
        "window_bytes": window_bytes,
        "kv_pool_bytes": kv_pool_bytes,
        "state_bytes": state_bytes,
        "device_bytes": state_bytes + window_bytes + kv_pool_bytes,
    }
