"""Heartbeat file: a first-class liveness signal for the watchdog.

`experiments/watchdog.py` historically inferred liveness from progress-CSV
growth — an indirect signal that goes dark between sink intervals and for
runs that don't stream a CSV at all. The heartbeat is direct: a guarded
training loop overwrites ONE small JSON file every step with a monotonic
sequence number, and the watchdog treats "seq advanced" as proof of life
alongside file growth.

Contract (docs/COMPONENTS.md "Telemetry"):
- Atomic replace (temp file + ``os.replace`` in the same directory), so a
  reader NEVER sees a partial file — same dance as
  ``utils.tracing.atomic_write_csv``, for the same kill-prone environment.
- Fields: ``schema``, ``pid``, ``step`` (the trainer's stream position),
  ``seq`` (per-writer monotonic counter — THE liveness signal: wall clocks
  can repeat across relaunches, seq restarts tell the reader a new process
  took over), ``time`` (epoch), ``monotonic`` (writer's time.monotonic).
- ``beat()`` never raises: a full disk must not kill an otherwise healthy
  training run. Failures are counted on the writer (``write_errors``).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Optional

HEARTBEAT_SCHEMA = 1


class Heartbeat:
    """Atomic heartbeat writer. One instance per training process."""

    def __init__(self, path: str):
        self.path = path
        self._seq = 0
        self._lock = threading.Lock()
        self.write_errors = 0
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def beat(self, step: int = 0, **extra) -> bool:
        """Write one heartbeat; returns False (and counts) on IO failure."""
        with self._lock:
            self._seq += 1
            payload = {"schema": HEARTBEAT_SCHEMA, "pid": os.getpid(),
                       "step": int(step), "seq": self._seq,
                       "time": time.time(), "monotonic": time.monotonic()}
            payload.update(extra)
            try:
                fd, tmp = tempfile.mkstemp(
                    dir=os.path.dirname(self.path) or ".", suffix=".hb.tmp")
                try:
                    with os.fdopen(fd, "w") as f:
                        json.dump(payload, f)
                    os.replace(tmp, self.path)
                except BaseException:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    raise
            except Exception:
                self.write_errors += 1
                return False
            return True

    @property
    def seq(self) -> int:
        return self._seq


def read_heartbeat(path: str) -> Optional[dict]:
    """Parse a heartbeat file; None when missing/unreadable/not-yet-atomic.

    Readers poll this from a different process (the watchdog), so every
    failure mode — missing file, torn write from a non-atomic writer,
    wrong schema — degrades to 'no signal', never an exception.
    """
    try:
        with open(path) as f:
            payload = json.load(f)
    except Exception:
        return None
    if not isinstance(payload, dict) or "seq" not in payload:
        return None
    return payload
