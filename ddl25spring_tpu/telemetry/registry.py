"""MetricsRegistry: counters, gauges and histograms with one snapshot shape.

The unification point for the repo's previously fragmented metric holders
(ISSUE 2): `telemetry.trace.Spans` wall-clock accumulators (fed by the
span Tracer or standalone), `telemetry.trace.StepTimer` per-step times,
and `metrics.ResilienceStats`
fault counters all land here through adapters (``absorb_*``), so one
``snapshot()`` carries everything a run report needs — and the run_end
event in the JSONL stream is exactly that snapshot.

Thread-safe: the watchdog/monitoring thread and the training thread may
both touch a registry (same hazard the Spans/StepTimer locks guard).
Histograms keep raw observations — runs here are 1e3-1e5 steps, so exact
percentiles are cheaper than the sketch machinery production systems need
at 1e9; swap the storage behind ``observe`` if that ever changes.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Optional, Sequence

DEFAULT_PERCENTILES = (50.0, 95.0, 99.0)


def percentile(values: Sequence[float], q: float) -> float:
    """Linear-interpolated percentile (numpy 'linear' method) without
    requiring numpy on the read path."""
    if not values:
        raise ValueError("percentile of empty sequence")
    return _percentile_sorted(sorted(values), q)


def _percentile_sorted(v: Sequence[float], q: float) -> float:
    """``percentile`` on ALREADY-SORTED values — callers computing several
    quantiles of one histogram sort once instead of once per quantile."""
    if len(v) == 1:
        return float(v[0])
    pos = (q / 100.0) * (len(v) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(v) - 1)
    frac = pos - lo
    return float(v[lo] * (1.0 - frac) + v[hi] * frac)


class MetricsRegistry:
    """Counters (monotonic), gauges (last-write-wins), histograms
    (p50/p95/p99 + count/mean/max)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, List[float]] = defaultdict(list)

    # ------------------------------------------------------------ primitives
    def counter_inc(self, name: str, value: float = 1.0) -> None:
        if value < 0:
            raise ValueError(f"counter {name!r}: negative increment {value}")
        with self._lock:
            self._counters[name] += value

    def counter_set(self, name: str, value: float) -> None:
        """Set a counter to an externally tracked total (adapter use: the
        source — e.g. ResilienceStats — owns the accumulation)."""
        with self._lock:
            self._counters[name] = float(value)

    def gauge_set(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._hists[name].append(float(value))

    # ------------------------------------------------------------- accessors
    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def gauge(self, name: str) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name)

    def percentiles(self, name: str,
                    qs: Sequence[float] = DEFAULT_PERCENTILES
                    ) -> Dict[str, float]:
        with self._lock:
            values = list(self._hists.get(name, ()))
        if not values:
            return {}
        values.sort()
        return {f"p{q:g}": _percentile_sorted(values, q) for q in qs}

    def snapshot(self) -> dict:
        """One JSON-able view of everything — the run_end event's payload.

        The lock covers only the copy-out; sorting/aggregating thousands of
        observations happens outside it so the training/watchdog threads'
        ``observe`` calls don't stall behind a snapshot."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            raw = {name: list(v) for name, v in self._hists.items() if v}
        hists = {}
        for name, v in raw.items():
            v.sort()
            hists[name] = {"count": len(v), "mean": sum(v) / len(v),
                           "max": v[-1],
                           **{f"p{q:g}": _percentile_sorted(v, q)
                              for q in DEFAULT_PERCENTILES}}
        return {"counters": counters, "gauges": gauges,
                "histograms": hists}

    # -------------------------------------------------------------- adapters
    def absorb_spans(self, spans, prefix: str = "phase/") -> None:
        """telemetry.trace.Spans → ``phase/<name>_s`` gauges (total seconds)
        and ``phase/<name>_count`` counters."""
        for name, total in spans.as_dict().items():
            self.gauge_set(f"{prefix}{name}_s", total)
            self.counter_set(f"{prefix}{name}_count", spans.count(name))

    def absorb_step_timer(self, timer, name: str = "step_time_s") -> None:
        """telemetry.trace.StepTimer → one histogram of its recorded steps."""
        for t in list(timer.times):
            self.observe(name, t)

    def absorb_resilience(self, stats, prefix: str = "faults/") -> None:
        """metrics.ResilienceStats → ``faults/<counter>`` counters. Iterates
        the stats object's own fields, so a newly added counter shows up
        here without a registry change (the merge-completeness contract
        tests/test_telemetry.py pins)."""
        for k, v in stats.as_dict().items():
            self.counter_set(f"{prefix}{k}", v)
