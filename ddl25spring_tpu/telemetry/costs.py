"""Compiled-HLO cost accounting, guarded for jax API drift.

`bench.py` computes MFU from a hand-derived analytic FLOP formula
(``train_step_flops_per_token``). This module pulls the OTHER source of
truth — XLA's own cost model for the compiled step, via
``jitted.lower(...).compile().cost_analysis()`` — so the two can
cross-check each other. The API has drifted across jax versions (dict vs
list-of-dicts results, methods missing on some backends, backends that
return None), so everything here follows the repo's version-shim precedent
(parallel/_compat.py, experiments/_cpu_pin.py): probe, normalize, and
degrade to None rather than crash — a bench must never die because a
jaxlib can't count its own FLOPs.

On this container's jax 0.4.37 / jaxlib 0.4.36 CPU backend,
``cost_analysis()`` returns ``[{"flops": ..., "bytes accessed": ...}]``
(verified; tests/test_telemetry.py pins the guard behavior).
"""

from __future__ import annotations

from typing import Any, Optional


def hlo_cost(jitted_fn, *args, **kwargs) -> Optional[dict]:
    """Cost analysis of the compiled program for ``jitted_fn(*args)``.

    Returns ``{"flops": float, "bytes_accessed": float | None}`` or None
    when any link of the lower→compile→cost_analysis chain is unavailable
    on this jax/jaxlib/backend. Arguments may be real pytrees or
    ``jax.ShapeDtypeStruct``s. NOTE: compiles the program if it isn't
    already — call where a compile is acceptable (bench/report time), not
    on a hot path.
    """
    lower = getattr(jitted_fn, "lower", None)
    if lower is None:
        return None                       # not a jitted callable
    try:
        compiled = lower(*args, **kwargs).compile()
    except Exception:
        return None
    return compiled_cost(compiled)


def compiled_cost(compiled) -> Optional[dict]:
    """``hlo_cost`` for an ALREADY-compiled program — the shared half of
    the guard, split out so CompileWatch can pay ONE lower→compile and
    feed both this cost model and ``memory.compiled_memory``."""
    try:
        analysis = compiled.cost_analysis()
    except Exception:
        return None
    return _normalize(analysis)


def _normalize(analysis: Any) -> Optional[dict]:
    """list-of-dicts (one per partition; 0.4.x) or plain dict → one dict."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    flops = analysis.get("flops")
    if flops is None:
        return None
    try:
        flops = float(flops)
    except (TypeError, ValueError):
        return None
    if flops < 0:                          # some backends report -1
        return None
    bytes_accessed = analysis.get("bytes accessed",
                                  analysis.get("bytes_accessed"))
    try:
        bytes_accessed = (float(bytes_accessed)
                          if bytes_accessed is not None else None)
    except (TypeError, ValueError):
        bytes_accessed = None
    return {"flops": flops, "bytes_accessed": bytes_accessed}


def flops_crosscheck(analytic_flops: float, hlo: Optional[dict],
                     tolerance: float = 0.10) -> dict:
    """Compare the analytic FLOP count against the compiled program's.

    Returns ``{"flops_source", "hlo_flops", "rel_err"}``:
    - ``"hlo"`` when the compiled-program count is available and within
      ``tolerance`` relative error of the analytic formula — the formula is
      then cross-checked by the compiler;
    - ``"analytic"`` when cost_analysis is unavailable on this jaxlib or
      the two diverge beyond tolerance (caller should warn: either the
      formula or the lowering changed).

    Both counts must cover the SAME program (same config, batch, seq).
    """
    if hlo is None or not analytic_flops:
        return {"flops_source": "analytic", "hlo_flops": None,
                "rel_err": None}
    rel = abs(hlo["flops"] - analytic_flops) / analytic_flops
    source = "hlo" if rel <= tolerance else "analytic"
    return {"flops_source": source, "hlo_flops": hlo["flops"],
            "rel_err": rel}
