"""Schema-versioned, append-only JSONL event stream.

The one place every layer of the stack reports through (ISSUE 2 tentpole):
training loops emit per-step records and fault events, FL servers emit round
summaries, and every run opens with a manifest carrying its configuration
and static communication profile. `experiments/obs_report.py` renders the
stream back into a human report; `tests/test_telemetry.py` pins the
round-trip.

Write contract:
- One event per line, compact JSON, written as ONE ``write()`` call on an
  ``O_APPEND`` file descriptor (looped only if the kernel writes short —
  e.g. ENOSPC mid-line, after which the next emit seals the fragment with
  a newline). Within one process the lock makes every line atomic. Across
  processes sharing a log, Linux local filesystems perform each O_APPEND
  write as one atomic append so lines do not interleave — but that is a
  Linux-local-fs behavior, not a POSIX guarantee (NFS, notably, can
  interleave); a reader tolerates a torn FINAL line either way, and a
  reopening writer truncates one (below).
- Every event carries ``schema`` (version), ``run_id``, ``seq`` (per-writer
  monotonic), ``t`` (epoch seconds) and ``type``. Extra fields are always
  legal — readers must ignore what they don't know (the same forward-compat
  posture as ResultSink's header widening). Event TYPES are closed per
  schema version: non-strict readers still skip nothing, but
  ``validate_event`` flags an unknown type at/below its own version (a
  typo) and names the offending type when the version is newer (a future
  schema's addition).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

# v2: serving request lifecycle (request_enqueue / request_prefill /
# request_token / request_done — serving/scheduler.py). v3: fleet-scale FL
# (fl/fleet.py) — ``fl_cohort`` (one device dispatch of a streamed cohort)
# and ``fl_tier`` (one aggregation tier's per-round summary with exact
# payload-byte accounting). v4: distributed tracing + live SLOs —
# ``span`` (one closed trace span: telemetry/trace.py's Tracer, exported
# to Chrome trace JSON by experiments/trace_export.py) and
# ``slo_violation`` (experiments/slo_monitor.py's rolling-window verdicts).
# v5: run-health introspection (telemetry/introspect.py) — ``numerics``
# (in-jit per-layer-group grad/param/update norms + per-leaf NaN
# attribution, sampled from the training loop at a configurable cadence)
# and ``compile`` (one XLA compilation of a watched jit entry point:
# wall seconds, cache size, retrace flag, HLO flops/bytes for roofline
# attainment). v6: serving fleet (serving/fleet.py, serving/deploy.py) —
# ``route`` (one router dispatch decision: which engine a request was
# handed to, under which policy) and ``deploy`` (one engine's live weight
# hot-swap at a token boundary: the published version, streams in flight
# across the swap); ``request_*`` events additionally carry ``engine``
# (the serving engine id) and ``tenant`` (the traffic class) when emitted
# by a fleet scheduler — extras, so single-engine v2 streams stay valid.
# v7: speculative decoding (serving/speculate.py) — ``speculate`` (one
# draft-propose + verify round: proposed/accepted/rejected draft-token
# counts and tokens emitted by the ONE verify dispatch — the
# acceptance-rate and tokens-per-dispatch accounting obs_report renders
# and slo_monitor's acceptance floor watches).
# v8: autoscaling (resilience/autoscale.py) — ``scale`` (one capacity
# move between the training mesh and the serving fleet: direction plus
# the post-transition allocation, rendered by obs_report's "scale"
# section and marked as a Perfetto instant by trace_export).
# v9: memory observability (telemetry/memory.py) — ``memory`` (one
# MemoryMeter sample at a chunk edge / scheduler tick / smoke phase:
# host RSS, training-state and elastic-mirror bytes, KV pool occupancy
# and fragmentation, per-engine when fleet-scale); ``compile`` events
# additionally carry the program's static device footprint
# (``argument_bytes``/``output_bytes``/``temp_bytes``/
# ``generated_code_bytes`` from compiled.memory_analysis()) and
# ``manifest`` carries the preflight fit estimate — extras, so v5–v8
# streams stay valid.
# Version bumps are additive: a v9 reader accepts v1–v8 streams
# unchanged, and older readers reject v9 (the "future schema" rule in
# validate_event) rather than misread it.
SCHEMA_VERSION = 9

# Event types this schema version defines. The type set is CLOSED per
# schema version: ``validate_event`` checks base fields for all types, the
# per-type required fields for the known ones, and (since v4) flags an
# unknown type carrying a schema at/below the reader's version — an
# unknown type is either a typo (same version) or a future schema's
# addition (whose version bump already flags it, by name).
EVENT_TYPES = ("manifest", "step", "fault", "fl_round", "run_end", "remesh",
               "request_enqueue", "request_prefill", "request_token",
               "request_done", "fl_cohort", "fl_tier", "span",
               "slo_violation", "numerics", "compile", "route", "deploy",
               "speculate", "scale", "memory")

_BASE_FIELDS = ("schema", "run_id", "seq", "t", "type")
_REQUIRED: Dict[str, tuple] = {
    "manifest": ("jax_version", "platform"),
    "step": ("it",),
    "fault": ("counters",),
    "fl_round": ("round",),
    "run_end": ("steps",),
    # Elastic re-mesh recovery (resilience/elastic.py): replica loss →
    # survivor submesh + cross-topology state reshard. Carries old/new
    # world size plus path taken ("mirror"/"checkpoint"), seconds lost,
    # and steps replayed; multi-axis meshes additionally ride ``axis``
    # ("data"/"stage") and ``old_shape``/``new_shape`` ([D, S] lists) as
    # extras — no schema bump, extras are always legal — so a stage
    # re-partition is attributable; rendered by experiments/obs_report.py.
    "remesh": ("old_world", "new_world"),
    # Serving request lifecycle (serving/scheduler.py, schema v2). ``req``
    # is the request id threading all four together. Enqueue carries the
    # request shape (prompt_len/max_new); prefill marks admission into a
    # slot (queue_wait_s, blocks reserved + pool blocks_in_use); token is
    # per-token progress (index ``i``); done closes the request with the
    # latency summary (queue_wait_s, ttft_s, tokens_per_sec) obs_report
    # aggregates into p50/p95/p99.
    "request_enqueue": ("req",),
    "request_prefill": ("req", "slot"),
    "request_token": ("req", "i"),
    "request_done": ("req", "tokens"),
    # Fleet-scale FL (fl/fleet.py, schema v3). ``fl_cohort`` is one
    # compiled cohort dispatch: which tier/edge ran it, how many REAL
    # (non-padded) clients it carried, and their exact upload payload
    # bytes. ``fl_tier`` closes one tier's round: inputs reduced (clients
    # for the edge tier, edge aggregates for the server tier) and the
    # exact wire bytes that crossed into the tier, summed from leaf
    # shapes/dtypes (telemetry.comm.tree_bytes) — the accounting the
    # hierarchical-topology comparisons in PAPERS.md need.
    "fl_cohort": ("round", "tier", "cohort"),
    "fl_tier": ("round", "tier"),
    # Distributed tracing (telemetry/trace.py, schema v4). One event per
    # CLOSED span: ``trace_id`` groups a causal tree (one serving request,
    # one FL round, one training run), ``span_id``/``parent_span_id``
    # carry the tree structure explicitly (no thread-locals — contexts are
    # passed by hand, so nothing leaks into jit), ``start_ns``/``dur_ns``
    # are the tracer clock's monotonic nanoseconds. Extra fields are span
    # attributes. Rendered by obs_report's "traces" section; exported to
    # Perfetto/chrome://tracing by experiments/trace_export.py.
    "span": ("name", "trace_id", "span_id", "start_ns", "dur_ns"),
    # Live SLO monitoring (experiments/slo_monitor.py, schema v4): one
    # event per rolling-window violation — ``slo`` names the objective
    # (e.g. "ttft_p99_s"), ``value``/``threshold`` the measurement vs the
    # target, ``window_s`` the window it was measured over.
    "slo_violation": ("slo",),
    # Run-health numerics (telemetry/introspect.py, schema v5): one
    # in-jit sample per cadence boundary — ``it`` is the stream position,
    # extras carry ``grad_norm`` (global), ``groups`` (per-layer-group
    # grad/param norms + update/param ratio, worst-first), ``worst_group``
    # / ``worst_update_ratio``, and ``nonfinite_grads`` (leaf paths) when
    # a gradient went non-finite. Computed INSIDE the compiled step —
    # bitwise-free instrumentation, no extra dispatch.
    "numerics": ("it",),
    # Serving fleet (serving/fleet.py + serving/deploy.py, schema v6).
    # ``route`` is one dispatch decision: request ``req`` handed to engine
    # ``engine`` under ``policy`` ("least_loaded" / "predicted_ttft");
    # extras carry the decision inputs (per-engine outstanding counts,
    # predicted TTFT). ``deploy`` is one engine's weight hot-swap at a
    # token boundary: ``version`` names the publication (the trainer's
    # checkpoint step for train→deploy publishes), ``engine`` which engine
    # swapped; extras carry ``in_flight``/``queued`` (the streams that
    # crossed the swap without dropping) — obs_report renders both, and
    # the scheduler's ``deploy`` span puts the swap on the Perfetto
    # timeline.
    "route": ("req", "engine"),
    "deploy": ("version",),
    # Speculative decoding (serving/speculate.py + scheduler.py, schema
    # v7): one event per verify dispatch — ``proposed`` draft tokens this
    # round (k × active slots), ``accepted`` of them re-derived by the
    # target; extras carry ``rejected``, ``emitted`` (tokens the dispatch
    # DELIVERED: accepted + one correction/bonus per slot, minus any
    # window tail dropped after a mid-window EOS), ``k``, ``slots``
    # and ``engine``. acceptance = accepted/proposed; tokens-per-dispatch
    # = emitted per event (one verify dispatch each).
    "speculate": ("proposed", "accepted"),
    # Autoscaling (resilience/autoscale.py, schema v8): one event per
    # capacity move between training and serving — ``direction``
    # ("train_to_serve" / "serve_to_train"), ``train_world`` /
    # ``serve_engines`` the POST-transition allocation (the
    # replicas-over-time series obs_report plots); extras carry the
    # triggering ``signal`` (e.g. "ttft_pressure", "traffic_ebb"), the
    # measured value behind it, ``it`` (the training chunk edge the move
    # landed on) and ``seconds`` (the re-mesh cost, when training moved).
    "scale": ("direction", "train_world", "serve_engines"),
    # Memory observability (telemetry/memory.py MemoryMeter, schema v9):
    # one event per sample cadence — ``source`` names the sampling site
    # ("train" for a trainer chunk edge / step cadence, "serve" for a
    # scheduler tick, "fleet" for a fleet census, "host" for a bare RSS
    # trajectory point). Extras carry whatever the site can account:
    # ``rss_bytes`` (host), ``params_bytes``/``opt_state_bytes``/
    # ``mirror_bytes`` (training state via tree_bytes — host-side shape
    # math, never a device sync), ``pool_used_bytes``/
    # ``pool_capacity_bytes``/``blocks_in_use``/``holes``/``largest_run``
    # (KV pool occupancy + fragmentation from BlockAllocator), ``engine``
    # (fleet-scale), ``device_bytes`` (the per-device total the headroom
    # SLO judges against slo_monitor's ``--device-bytes`` budget), and
    # ``it``/``tick`` (stream position). Rendered by obs_report's
    # "memory" section; the flight recorder pins the last sample as the
    # postmortem memory census.
    "memory": ("source",),
    # Compile/retrace accounting (introspect.CompileWatch, schema v5):
    # one event per XLA compilation of a watched jit entry point —
    # ``name`` the factory label, ``seconds`` the compiling call's wall
    # time; extras carry ``cache_size``, ``retrace`` (True = the
    # factory's documented compile budget was exceeded), and
    # ``flops``/``bytes_accessed`` from costs.hlo_cost for attainment.
    "compile": ("name", "seconds"),
}


def default_run_id() -> str:
    return f"{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}"


class EventLog:
    """Append-only JSONL event writer (thread-safe; crash-tolerant reads).

    >>> log = EventLog("/tmp/run/events.jsonl")
    >>> log.manifest(jax_version=jax.__version__, platform="cpu")
    >>> log.step(it=10, loss=2.31, dt_s=0.4)
    """

    def __init__(self, path: str, run_id: Optional[str] = None, *,
                 heal: bool = True):
        self.path = path
        self.run_id = run_id or default_run_id()
        self._seq = 0
        self._lock = threading.Lock()
        # In-process taps on the emitted stream (the flight recorder's
        # feed — introspect.FlightRecorder.observe). Called AFTER the
        # write, outside the lock (an observer must be able to do IO of
        # its own without serializing emitters), each guarded: a broken
        # observer loses its tap, never the event or the run.
        self.observers: List[Any] = []
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # O_APPEND at the fd level: every write() lands at the current end
        # of file even if another process appended in between.
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                           0o644)
        self.write_errors = 0
        self._torn_tail = False  # our own partial write left file mid-line
        if not heal:
            # A SIDECAR writer (slo_monitor appending verdicts into a LIVE
            # stream) must be append-only: the heal below interprets a
            # missing final newline as a dead writer's fragment, but on a
            # live stream it is another process's in-flight line, and
            # truncating it would corrupt that writer's event mid-write.
            # If the file DOES end mid-line right now (a crashed
            # predecessor's fragment), seal it with a leading newline on
            # our first emit instead — worst case (the line completes in
            # between) readers skip one blank line.
            try:
                with open(path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    if f.tell() > 0:
                        f.seek(-1, os.SEEK_END)
                        self._torn_tail = f.read(1) != b"\n"
            except OSError:
                pass
            return
        # Heal a torn final line left by a crashed predecessor (a relaunch
        # reusing the same telemetry dir): without healing, this writer's
        # first event would merge into the fragment, turning an expected
        # crash artifact (readers drop a torn FINAL line) into mid-file
        # corruption (strict readers raise). Truncating to the last
        # newline discards exactly the bytes every reader would drop; the
        # write contract (whole lines in one write()) means a file not
        # ending in '\n' is a dead writer's fragment, not an in-flight
        # append. Writers taking OVER a dir heal; sidecars sharing a LIVE
        # stream pass heal=False (above).
        try:
            size = os.fstat(self._fd).st_size
            if size > 0:
                with open(path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) != b"\n":
                        # Scan BACKWARDS in chunks for the last newline:
                        # the fragment is one partial line, but the log a
                        # long-lived dir accumulates can be huge — reading
                        # it all just to rfind would cost O(file) memory.
                        pos, keep, chunk = size, 0, 1 << 16
                        while pos > 0:
                            start = max(0, pos - chunk)
                            f.seek(start)
                            nl = f.read(pos - start).rfind(b"\n")
                            if nl != -1:
                                keep = start + nl + 1
                                break
                            pos = start
                        os.ftruncate(self._fd, keep)
        except OSError:
            pass

    def emit(self, type: str, **fields: Any) -> Dict[str, Any]:
        """Append one event; returns the record (as written, or as dropped).

        Never raises on IO failure: telemetry must not sink a trainer (same
        policy as ``Heartbeat.beat`` — a full disk kills the event, counted
        in ``write_errors``, not the run). Emitting after ``close()`` also
        just counts."""
        with self._lock:
            self._seq += 1
            record = {"schema": SCHEMA_VERSION, "run_id": self.run_id,
                      "seq": self._seq, "t": time.time(), "type": type}
            record.update(fields)
            data = b""
            wrote = 0
            try:
                # Sanitize + dumps inside the try: either can still raise
                # (non-string dict keys, circular structures) and that too
                # must count, not sink the trainer. allow_nan=False is the
                # backstop: json.dumps would otherwise emit NaN/Infinity
                # tokens — which Python's loads tolerates but strict JSON
                # consumers (jq, the CI artifact viewers) reject — for any
                # non-finite float _sanitize missed.
                record = _sanitize(record)
                line = json.dumps(record, separators=(",", ":"),
                                  allow_nan=False) + "\n"
                if self._fd is None:
                    raise OSError("EventLog is closed")
                data = line.encode()
                if self._torn_tail:
                    # A prior partial write left the file mid-line; a
                    # leading newline seals that fragment into ONE
                    # malformed line (skipped by non-strict readers)
                    # instead of letting this event merge into it and
                    # corrupt both.
                    data = b"\n" + data
                # os.write may write short (ENOSPC hit mid-line, or any
                # byte count on POSIX) — loop, tracking progress so a
                # failure mid-line is repairable (above).
                view = memoryview(data)
                while view:
                    n = os.write(self._fd, view)
                    wrote += n
                    view = view[n:]
                self._torn_tail = False
            except (OSError, TypeError, ValueError, RecursionError):
                self.write_errors += 1
                if wrote:   # 0 bytes = file unchanged, keep prior state
                    self._torn_tail = wrote < len(data)
        for obs in self.observers:
            try:
                obs(record)
            except Exception:
                pass       # an observer must never sink the emitter
        return record

    # Typed conveniences — thin, so the schema has one authoritative shape.
    def manifest(self, **fields) -> Dict[str, Any]:
        return self.emit("manifest", **fields)

    def step(self, *, it: int, **fields) -> Dict[str, Any]:
        return self.emit("step", it=it, **fields)

    def fault(self, *, counters: Dict[str, int], **fields) -> Dict[str, Any]:
        return self.emit("fault", counters=counters, **fields)

    def fl_round(self, *, round: int, **fields) -> Dict[str, Any]:
        return self.emit("fl_round", round=round, **fields)

    def run_end(self, *, steps: int, **fields) -> Dict[str, Any]:
        return self.emit("run_end", steps=steps, **fields)

    def remesh(self, *, old_world: int, new_world: int,
               **fields) -> Dict[str, Any]:
        return self.emit("remesh", old_world=old_world, new_world=new_world,
                         **fields)

    # Serving request lifecycle (schema v2; serving/scheduler.py emits).
    def request_enqueue(self, *, req: str, **fields) -> Dict[str, Any]:
        return self.emit("request_enqueue", req=req, **fields)

    def request_prefill(self, *, req: str, slot: int,
                        **fields) -> Dict[str, Any]:
        return self.emit("request_prefill", req=req, slot=slot, **fields)

    def request_token(self, *, req: str, i: int, **fields) -> Dict[str, Any]:
        return self.emit("request_token", req=req, i=i, **fields)

    def request_done(self, *, req: str, tokens: int,
                     **fields) -> Dict[str, Any]:
        return self.emit("request_done", req=req, tokens=tokens, **fields)

    # Fleet-scale FL (schema v3; fl/fleet.py emits).
    def fl_cohort(self, *, round: int, tier: str, cohort: int,
                  **fields) -> Dict[str, Any]:
        return self.emit("fl_cohort", round=round, tier=tier, cohort=cohort,
                         **fields)

    def fl_tier(self, *, round: int, tier: str, **fields) -> Dict[str, Any]:
        return self.emit("fl_tier", round=round, tier=tier, **fields)

    # Distributed tracing (schema v4; telemetry/trace.py's Tracer emits).
    def span(self, *, name: str, trace_id: str, span_id: str,
             start_ns: int, dur_ns: int, parent_span_id: Optional[str] = None,
             **fields) -> Dict[str, Any]:
        if parent_span_id is not None:
            fields["parent_span_id"] = parent_span_id
        return self.emit("span", name=name, trace_id=trace_id,
                         span_id=span_id, start_ns=start_ns, dur_ns=dur_ns,
                         **fields)

    # Live SLO monitoring (schema v4; experiments/slo_monitor.py emits).
    def slo_violation(self, *, slo: str, **fields) -> Dict[str, Any]:
        return self.emit("slo_violation", slo=slo, **fields)

    # Run-health introspection (schema v5; telemetry/introspect.py).
    def numerics(self, *, it: int, **fields) -> Dict[str, Any]:
        return self.emit("numerics", it=it, **fields)

    def compile(self, *, name: str, seconds: float,
                **fields) -> Dict[str, Any]:
        return self.emit("compile", name=name, seconds=seconds, **fields)

    # Memory observability (schema v9; telemetry/memory.py MemoryMeter).
    def memory(self, *, source: str, **fields) -> Dict[str, Any]:
        return self.emit("memory", source=source, **fields)

    # Serving fleet (schema v6; serving/fleet.py routes, serving/
    # scheduler.py swaps).
    def route(self, *, req: str, engine: int, **fields) -> Dict[str, Any]:
        return self.emit("route", req=req, engine=engine, **fields)

    # Autoscaling (schema v8; resilience/autoscale.py emits).
    def scale(self, *, direction: str, train_world: int, serve_engines: int,
              **fields) -> Dict[str, Any]:
        return self.emit("scale", direction=direction,
                         train_world=train_world,
                         serve_engines=serve_engines, **fields)

    def deploy(self, *, version, **fields) -> Dict[str, Any]:
        return self.emit("deploy", version=version, **fields)

    # Speculative decoding (schema v7; serving/scheduler.py emits one per
    # verify dispatch).
    def speculate(self, *, proposed: int, accepted: int,
                  **fields) -> Dict[str, Any]:
        return self.emit("speculate", proposed=proposed, accepted=accepted,
                         **fields)

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                os.close(self._fd)
                self._fd = None

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _json_fallback(obj):
    """Last-resort serializer: numpy/jax scalars → Python, else str."""
    for attr in ("item",):
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:
                pass
    return str(obj)


def _sanitize(obj):
    """Make ``obj`` strictly-JSON-serializable: numpy/jax scalars → Python
    (via ``_json_fallback``) and non-finite floats → their ``str()``
    ("nan"/"inf"/"-inf" stay visible in the stream instead of becoming
    invalid NaN/Infinity tokens). Dict keys are left alone — a non-string
    key is a caller bug that json.dumps reports (and ``emit`` counts)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else str(obj)
    if isinstance(obj, (str, int, bool)) or obj is None:
        return obj
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    return _sanitize(_json_fallback(obj))


def validate_event(event: Dict[str, Any]) -> List[str]:
    """Schema check; returns a list of problems (empty = valid).

    Base fields are required for every event; per-type required fields for
    the types this schema version knows. A FUTURE schema version is a
    problem (the reader can't promise to understand it), and the message
    NAMES the event type that carried it — "schema 5 is newer" alone left
    a v5-writer-vs-v4-reader failure opaque about which emitter was ahead.
    An unknown type is rejected only when its declared schema is at/below
    the reader's version (there the type set is closed, so it can only be
    a typo); a newer stream's genuinely-new types are covered — by name —
    by the future-schema problem instead.
    """
    problems = [f"missing field {f!r}" for f in _BASE_FIELDS
                if f not in event]
    schema = event.get("schema")
    etype = event.get("type")
    if isinstance(schema, int) and schema > SCHEMA_VERSION:
        problems.append(
            f"schema {schema} is newer than reader ({SCHEMA_VERSION}): "
            f"cannot validate event type {etype!r} — upgrade the reader "
            "or re-record at the reader's schema")
    elif etype is not None and etype not in EVENT_TYPES:
        problems.append(
            f"unknown event type {etype!r} for schema "
            f"{schema if isinstance(schema, int) else SCHEMA_VERSION} "
            f"(known: {', '.join(EVENT_TYPES)})")
    for f in _REQUIRED.get(etype, ()):
        if f not in event:
            problems.append(f"{etype}: missing field {f!r}")
    return problems


def read_events(path: str, *, strict: bool = False,
                types: Optional[tuple] = None) -> List[Dict[str, Any]]:
    """Parse a JSONL event stream, tolerating a torn final line.

    A crash mid-append can leave a partial LAST line; that one is dropped
    silently. A malformed line anywhere else is corruption and raises under
    ``strict``; otherwise it is skipped. ``types`` filters by event type.
    """
    events: List[Dict[str, Any]] = []
    with open(path, "rb") as f:
        raw = f.read()
    lines = raw.split(b"\n")
    complete = raw.endswith(b"\n")
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            event = json.loads(line)
            if not isinstance(event, dict):
                # Valid JSON but not an event object (`null`, a number, a
                # list) — same corruption class as a parse failure; letting
                # it through would crash every consumer's `.get`.
                raise ValueError(f"non-object event: {line[:40]!r}")
        except ValueError:
            if i == len(lines) - 1 and not complete:
                continue                       # torn final line: expected
            if strict:
                raise
            continue
        if strict:
            problems = validate_event(event)
            if problems:
                raise ValueError(f"{path}:{i + 1}: {problems}")
        if types is None or event.get("type") in types:
            events.append(event)
    return events


def iter_runs(events: List[Dict[str, Any]]) -> Iterator[List[Dict[str, Any]]]:
    """Group a (possibly multi-run) event list into per-run_id sublists,
    preserving first-seen order."""
    by_run: Dict[str, List[Dict[str, Any]]] = {}
    for e in events:
        by_run.setdefault(e.get("run_id", "?"), []).append(e)
    yield from by_run.values()
