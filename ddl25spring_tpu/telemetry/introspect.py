"""Run-health introspection: in-jit numerics, compile/retrace accounting,
roofline attainment, and the anomaly flight recorder (ISSUE 9 tentpole).

PRs 3 and 8 say *that* a run is slow or sick (span timelines, SLO
breaches, StepGuard skips); this module says *why*:

- **In-jit numerics summaries** (``make_summarizer``): per-layer-group
  grad norm, param norm and update/param ratio computed INSIDE the
  existing compiled step — the summary rides the loss output of the same
  dispatch, so instrumentation adds zero extra dispatches and (because
  extra outputs never perturb XLA's computation of the existing ones)
  losses and params are bitwise identical with summaries on vs off
  (pinned in tests/test_introspect.py at K∈{1,4}). A per-leaf finite
  mask rides along, so a non-finite gradient is attributed to a NAMED
  tree path, not "somewhere".
- **Compile/retrace observability** (``CompileWatch``): a transparent
  wrapper over any jitted entry point that notices ``_cache_size()``
  growth, times the compiling call, costs the program via
  ``costs.hlo_cost`` and emits a ``compile`` event (schema v5) — with a
  retrace detector for factories whose documented invariant is ONE
  compiled program (serving's two engine steps, fleet's cohort steps).
- **Attainment accounting** (``platform_peaks``): the roofline
  denominators — ROOFLINE.md's measured chip peaks, or a calibrated CPU
  baseline on fallback — land in the run manifest so obs_report /
  slo_monitor can turn (compile event flops, span/step durations) into
  achieved FLOP/s, HBM GB/s and MFU without jax.
- **Anomaly flight recorder** (``FlightRecorder``): a bounded ring of
  recent events plus the pinned manifest / last numerics / compile
  records, dumped as a self-contained postmortem JSON bundle the moment
  a ``fault``, ``remesh`` or ``slo_violation`` event crosses the stream.
  Render with ``python -m experiments.postmortem <telemetry-dir>``.

Import contract: module import is jax-free (the read-side tools —
obs_report, postmortem, slo_monitor — import helpers from here); jax is
imported lazily inside the functions that build in-jit code.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

# --------------------------------------------------------------- tree paths

def path_str(path) -> str:
    """jax key path -> "blocks/attn/wq"-style string (stable, readable)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def leaf_paths(tree) -> List[str]:
    """Path strings of every leaf, in ``tree_flatten_with_path`` order —
    the SAME order ``make_summarizer``'s finite mask and
    ``FaultPlan``'s targeted ``nan_grad`` use, so an index in one names
    the same leaf in the others."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [path_str(p) for p, _ in flat]


def nonfinite_leaves(tree, *, limit: int = 8) -> List[str]:
    """Host-side attribution: paths of leaves carrying any NaN/Inf
    (syncs each leaf — fault-path only). At most ``limit`` paths are
    returned, with a ``"... +N more"`` tail when truncated."""
    import jax
    import numpy as np

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    bad = []
    for p, leaf in flat:
        try:
            arr = np.asarray(leaf)
        except Exception:
            continue
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            bad.append(path_str(p))
    if len(bad) > limit:
        bad = bad[:limit] + [f"... +{len(bad) - limit} more"]
    return bad


# ------------------------------------------------------- in-jit numerics

class NumericsSummary(NamedTuple):
    """The in-jit half of a numerics sample: per-GROUP sums of squares
    (sqrt happens at emission — host side) and the per-LEAF gradient
    finite mask. All leaves are tiny ([G]/[L] fp32/bool) so the summary
    rides the step's outputs for free."""
    grad_sq: Any      # [G] f32 — per-group Σ grad²
    param_sq: Any     # [G] f32 — per-group Σ new_param²
    update_sq: Any    # [G] f32 — per-group Σ (new_param − old_param)²
    grad_finite: Any  # [L] bool — per-leaf all-finite(grad)


class NumericsHandle:
    """One model's numerics instrumentation: the static leaf→group
    geometry plus ``summarize`` (call INSIDE the compiled step) and
    ``event_fields`` (host-side rendering into a ``numerics`` event).

    Groups: every top-level key of the params tree is a group, except
    ``layered_keys`` entries (default: ``"blocks"``, llama's stacked
    [L, ...] transformer stack), which expand to one group per leading
    index — per-layer-group norms from stacked leaves without unstacking
    anything.
    """

    def __init__(self, groups: List[str], paths: List[str],
                 summarize: Callable):
        self.groups = groups          # [G] group names
        self.paths = paths            # [L] leaf paths (flatten order)
        self.summarize = summarize    # (params, grads, new_params) -> NumericsSummary

    def event_fields(self, summary, *, index: Optional[int] = None,
                     top: int = 4) -> Dict[str, Any]:
        """Host-side: sync the (tiny) summary arrays and shape the
        ``numerics`` event payload. ``index`` slices a stacked [K, ...]
        summary from a fused multi-step dispatch (use -1 for the chunk's
        last step)."""
        import numpy as np

        def host(x):
            a = np.asarray(x)
            return a[index] if index is not None else a

        grad = np.sqrt(host(summary.grad_sq))
        param = np.sqrt(host(summary.param_sq))
        upd = np.sqrt(host(summary.update_sq))
        finite = host(summary.grad_finite)
        with np.errstate(invalid="ignore", divide="ignore"):
            ratio = np.where(param > 0, upd / param, 0.0)
        # NaN ratios (non-finite params) sort to the top via nan_to_max.
        ratio_rank = np.where(np.isfinite(ratio), ratio, np.inf)
        worst = int(np.argmax(ratio_rank))
        order = np.argsort(-ratio_rank)[:max(1, top)]
        fields: Dict[str, Any] = {
            "grad_norm": float(np.sqrt(np.sum(grad ** 2))),
            "worst_group": self.groups[worst],
            "worst_update_ratio": float(ratio[worst]),
            "groups": {
                self.groups[i]: {
                    "grad_norm": float(grad[i]),
                    "param_norm": float(param[i]),
                    "update_ratio": float(ratio[i]),
                } for i in order
            },
        }
        if not bool(finite.all()):
            bad = [self.paths[i] for i in np.flatnonzero(~finite)]
            if len(bad) > 8:
                bad = bad[:8] + [f"... +{len(bad) - 8} more"]
            fields["nonfinite_grads"] = bad
        return fields


def make_summarizer(params_template, *,
                    layered_keys: Tuple[str, ...] = ("blocks",),
                    psum_axis=None) -> NumericsHandle:
    """Build the in-jit numerics summarizer for one params tree.

    ``summarize(params, grads, new_params)`` must be called inside the
    step's jit: it computes per-group sums of squares over grads /
    new-params / (new − old) and the per-leaf gradient finite mask, all
    with ops on values the step already holds — no extra dispatch, no
    effect on the existing outputs (bitwise; tests pin it).

    ``psum_axis``: ZeRO-1's local gradients differ per shard, so grad
    stats (and the finite mask) are psum-agreed over the named axis —
    one tiny extra collective ([G]+[L] scalars) INSIDE the same
    dispatch; the replicated-gradient path passes None and pays nothing.
    Accepts a tuple of axis names too — the overlap/ring drivers agree
    over every data axis of a hierarchical (dcn × data) mesh.
    The psum'd grad norm is then the RMS-style Σ-over-shards of local
    grads (a drift/NaN signal, not bitwise the pmean'd gradient's norm —
    documented, since only zero1 takes this branch).
    """
    import jax
    import jax.numpy as jnp

    flat, _ = jax.tree_util.tree_flatten_with_path(params_template)
    paths = [path_str(p) for p, _ in flat]

    # Static leaf -> group geometry. A layered leaf ("blocks/...") maps
    # to L groups via its leading axis; others to their top-level key.
    groups: List[str] = []
    group_idx: Dict[str, int] = {}

    def gid(name: str) -> int:
        if name not in group_idx:
            group_idx[name] = len(groups)
            groups.append(name)
        return group_idx[name]

    layered: List[Optional[int]] = []   # first group id of the leaf's layers
    plain: List[Optional[int]] = []     # group id for non-layered leaves
    for p, leaf in flat:
        top = path_str(p[:1])
        shape = getattr(leaf, "shape", ())
        if top in layered_keys and len(shape) >= 1 and shape[0] >= 1:
            base = gid(f"{top}/0")
            for i in range(1, shape[0]):
                gid(f"{top}/{i}")
            layered.append(base)
            plain.append(None)
        else:
            layered.append(None)
            plain.append(gid(top))
    n_groups = len(groups)

    def _group_sq(tree):
        leaves = jax.tree.leaves(tree)
        acc = jnp.zeros((n_groups,), jnp.float32)
        for leaf, lay, pl in zip(leaves, layered, plain):
            x = leaf.astype(jnp.float32)
            if lay is not None:
                per_layer = jnp.sum(
                    x.reshape(x.shape[0], -1) ** 2, axis=1)
                acc = acc.at[lay:lay + x.shape[0]].add(per_layer)
            else:
                acc = acc.at[pl].add(jnp.sum(x ** 2))
        return acc

    def summarize(params, grads, new_params) -> NumericsSummary:
        grad_sq = _group_sq(grads)
        finite = jnp.stack([jnp.all(jnp.isfinite(g))
                            for g in jax.tree.leaves(grads)])
        if psum_axis is not None:
            # Raw lax collectives on purpose: the comm wrappers' static
            # wire profile is pinned by tests at instrumentation-off
            # parity, and these few hundred bytes are observability tax,
            # not payload — accounted here, in this comment, not there.
            grad_sq = jax.lax.psum(grad_sq, psum_axis)
            finite = jax.lax.psum(jnp.logical_not(finite)
                                  .astype(jnp.int32), psum_axis) == 0
        upd = jax.tree.map(lambda n, o: n.astype(jnp.float32)
                           - o.astype(jnp.float32), new_params, params)
        return NumericsSummary(grad_sq=grad_sq,
                               param_sq=_group_sq(new_params),
                               update_sq=_group_sq(upd),
                               grad_finite=finite)

    return NumericsHandle(groups, paths, summarize)


def split_step_output(out):
    """(loss, numerics-or-None) from a step's second output — the shape
    contract instrumented steps share with plain ones: a bare loss array,
    or ``(loss, NumericsSummary)`` when instrumentation is on."""
    if isinstance(out, tuple) and len(out) == 2 \
            and isinstance(out[1], NumericsSummary):
        return out[0], out[1]
    return out, None


# ------------------------------------------------ compile/retrace watching

class CompileRecord(NamedTuple):
    name: str
    seconds: float        # wall time of the compiling call (trace+compile
    #                       +run — the user-visible stall)
    cache_size: int       # entries after this call
    retrace: bool         # broke the factory's max_caches invariant
    flops: Optional[float]
    bytes_accessed: Optional[float]
    memory: Optional[dict] = None   # static device footprint (schema v9:
    #                       memory.compiled_memory — argument/output/temp/
    #                       generated-code bytes), None when unaccountable


class CompileWatch:
    """Transparent wrapper over a jitted callable that turns compilations
    into ``compile`` events.

    Detection is ``_cache_size()`` growth across a call (eval_shape /
    ``lower().compile()`` do not grow it on this jaxlib — probed), so the
    steady-state overhead is one int comparison per dispatch. On growth:
    the call's wall time is recorded, the program is costed via
    ``costs.compiled_cost`` AND byte-accounted via
    ``memory.compiled_memory`` (ONE extra compile shared by both, paid
    only on an event that already paid one, and only when someone is
    listening), and a ``compile`` event is emitted to ``self.events``
    when bound — carrying flops/bytes_accessed for attainment plus the
    schema-v9 static footprint (argument/output/temp/generated-code
    bytes), so every watched program's device byte budget is in the
    stream.

    ``max_caches``: the factory's documented compile budget — serving's
    engine steps and fleet's cohort steps promise ONE program; any growth
    past the budget is flagged ``retrace=True`` and counted in
    ``self.retraces`` (the invariant the cohort-padding / data-not-shape
    designs exist to protect). ``None`` disables the invariant (chunked
    training legitimately compiles a tail-chunk shape).

    Attribute access delegates to the wrapped callable, so
    ``_cache_size()`` / ``lower`` / ``eval_shape`` users see the original
    jit object.
    """

    def __init__(self, fn: Callable, *, name: str,
                 max_caches: Optional[int] = 1, cost: bool = True,
                 events=None, meta: Optional[Dict[str, Any]] = None,
                 meta_fn: Optional[Callable] = None):
        self._fn = fn
        self.name = name
        self.max_caches = max_caches
        self._cost = cost
        self.events = events          # late-bindable EventLog
        self.meta = dict(meta or {})
        # Per-CALL meta derived from the compiling call's arguments
        # (guarded; merged over ``meta``) — how the chunked trainer stamps
        # each compile event with the ACTUAL window size, so a tail
        # chunk's smaller program is not mistaken for a full-K one by
        # per-step normalizers (slo_monitor's MFU floor).
        self.meta_fn = meta_fn
        self.compiles: List[CompileRecord] = []
        self.retraces = 0

    def _size(self) -> Optional[int]:
        size = getattr(self._fn, "_cache_size", None)
        if size is None:
            return None
        try:
            return int(size())
        except Exception:
            return None

    def __call__(self, *args, **kwargs):
        before = self._size()
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        after = self._size()
        if before is not None and after is not None and after > before:
            seconds = time.perf_counter() - t0
            retrace = (self.max_caches is not None
                       and after > self.max_caches)
            flops = bytes_accessed = None
            mem = None
            if self._cost and self.events is not None:
                # One guarded lower→compile serves BOTH accountants —
                # the flop/byte cost model (costs.compiled_cost) and the
                # static memory footprint (memory.compiled_memory) — so
                # observing memory costs no compile beyond what costing
                # already paid.
                from .costs import compiled_cost
                from .memory import compiled_memory
                lower = getattr(self._fn, "lower", None)
                compiled = None
                if lower is not None:
                    try:
                        compiled = lower(*args, **kwargs).compile()
                    except Exception:
                        compiled = None
                if compiled is not None:
                    hlo = compiled_cost(compiled)
                    if hlo is not None:
                        flops = hlo["flops"]
                        bytes_accessed = hlo["bytes_accessed"]
                    mem = compiled_memory(compiled)
            rec = CompileRecord(self.name, seconds, after, retrace,
                                flops, bytes_accessed, mem)
            self.compiles.append(rec)
            if retrace:
                self.retraces += 1
            if self.events is not None:
                meta = dict(self.meta)
                if self.meta_fn is not None:
                    try:
                        meta.update(self.meta_fn(*args, **kwargs))
                    except Exception:
                        pass
                self.events.compile(
                    name=self.name, seconds=seconds, cache_size=after,
                    retrace=retrace, flops=flops,
                    bytes_accessed=bytes_accessed,
                    **(mem or {}), **meta)
        return out

    def __getattr__(self, attr):
        return getattr(self._fn, attr)


def watch(fn: Callable, *, name: str, max_caches: Optional[int] = 1,
          cost: bool = True, events=None,
          meta: Optional[Dict[str, Any]] = None,
          meta_fn: Optional[Callable] = None) -> CompileWatch:
    """Wrap ``fn`` in a ``CompileWatch`` (idempotent: re-watching a watch
    re-binds its name/budget instead of stacking wrappers)."""
    if isinstance(fn, CompileWatch):
        fn.name = name
        fn.max_caches = max_caches
        if events is not None:
            fn.events = events
        if meta:
            fn.meta.update(meta)
        if meta_fn is not None:
            fn.meta_fn = meta_fn
        return fn
    return CompileWatch(fn, name=name, max_caches=max_caches, cost=cost,
                        events=events, meta=meta, meta_fn=meta_fn)


def bind_events(fn, events) -> None:
    """Late-bind an EventLog to a ``CompileWatch`` (no-op for anything
    else) — how the serving scheduler attaches its stream to the
    engine's already-built watches."""
    if isinstance(fn, CompileWatch):
        fn.events = events


# ------------------------------------------------------ roofline peaks

# ROOFLINE.md's measured TPU v5e (lite) peaks — the denominators every
# attainment number in this repo is quoted against.
PLATFORM_PEAKS: Dict[str, Dict[str, Any]] = {
    "tpu": {"flops_per_sec": 197e12, "hbm_bytes_per_sec": 819e9,
            "source": "ROOFLINE.md (TPU v5e, bf16 peak / HBM)"},
}

_cpu_peak_cache: Dict[str, Any] = {}


def calibrate_cpu_peak(*, n: int = 384, repeats: int = 3) -> Dict[str, Any]:
    """Measured-not-guessed CPU roofline: time a small f32 matmul chain
    and report achieved FLOP/s — the calibrated baseline CPU-fallback
    attainment is quoted against (an absolute-peak claim for an
    oversubscribed CI host would be fiction; a measured one is a fair
    yardstick). Cached per process; ~10 ms."""
    if _cpu_peak_cache:
        return dict(_cpu_peak_cache)
    import numpy as np

    a = np.random.default_rng(0).standard_normal((n, n)).astype(np.float32)
    b = a.copy()
    a @ b                                    # warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        a @ b
        best = min(best, time.perf_counter() - t0)
    flops = 2.0 * n ** 3 / max(best, 1e-9)
    _cpu_peak_cache.update({
        "flops_per_sec": flops,
        # Effective memory bandwidth proxy: the same matmul's operand +
        # output traffic — a loose floor, flagged as calibrated.
        "hbm_bytes_per_sec": 3.0 * 4 * n * n / max(best, 1e-9),
        "source": f"calibrated ({n}^3 f32 matmul on this host)",
    })
    return dict(_cpu_peak_cache)


def platform_peaks(platform: str) -> Dict[str, Any]:
    """Roofline denominators for ``platform`` ("tpu"/"cpu"/...). Known
    accelerators come from ``PLATFORM_PEAKS`` (ROOFLINE.md); anything
    else gets the calibrated CPU baseline. Lands in the run manifest so
    jax-free readers (obs_report, slo_monitor) never re-derive it."""
    peaks = PLATFORM_PEAKS.get(platform)
    if peaks is not None:
        return dict(peaks)
    return calibrate_cpu_peak()


def attainment(flops: Optional[float], bytes_accessed: Optional[float],
               seconds: float, peaks: Dict[str, Any]) -> Dict[str, Any]:
    """One dispatch's achieved rates vs the peaks: ``{"flops_per_sec",
    "mfu", "bytes_per_sec", "hbm_frac"}`` (fields None when the matching
    numerator/denominator is missing). Pure arithmetic — shared by
    obs_report and slo_monitor, jax-free."""
    out: Dict[str, Any] = {"flops_per_sec": None, "mfu": None,
                           "bytes_per_sec": None, "hbm_frac": None}
    if seconds <= 0:
        return out
    if isinstance(flops, (int, float)) and flops > 0:
        out["flops_per_sec"] = flops / seconds
        peak = peaks.get("flops_per_sec")
        if isinstance(peak, (int, float)) and peak > 0:
            out["mfu"] = out["flops_per_sec"] / peak
    if isinstance(bytes_accessed, (int, float)) and bytes_accessed > 0:
        out["bytes_per_sec"] = bytes_accessed / seconds
        peak = peaks.get("hbm_bytes_per_sec")
        if isinstance(peak, (int, float)) and peak > 0:
            out["hbm_frac"] = out["bytes_per_sec"] / peak
    return out


# ------------------------------------------------------ flight recorder

# Event types whose arrival dumps a bundle: a StepGuard/fault-injection
# trip, an elastic re-mesh, a live SLO breach.
TRIGGER_TYPES = ("fault", "remesh", "slo_violation")

BUNDLE_KIND = "ddl25_postmortem"


class FlightRecorder:
    """Bounded ring over the live event stream + pinned context, dumped
    as a self-contained postmortem bundle when an anomaly event crosses.

    Attach as an ``EventLog`` observer (``Telemetry`` does this by
    default); every emitted event enters the ring, and the manifest /
    latest ``numerics`` / latest ``memory`` (the memory census) /
    ``compile`` events are additionally PINNED so
    they survive ring eviction — a bundle must carry its own context, not
    a pointer into a stream that may be unreadable where the bundle is
    read.

    Bounds: the ring holds ``capacity`` events; a dump serializes at most
    ``max_bytes`` (oldest ring events dropped first, count recorded in
    the bundle); at most ``max_bundles`` bundles are written per recorder
    (a crash-looping run must not fill the disk with identical
    postmortems — the cap and the drop count are themselves diagnostics).
    """

    def __init__(self, out_dir: str, *, capacity: int = 256,
                 max_bytes: int = 256 * 1024, max_bundles: int = 16,
                 triggers: Tuple[str, ...] = TRIGGER_TYPES):
        self.out_dir = out_dir
        self.capacity = max(1, int(capacity))
        self.max_bytes = max(4096, int(max_bytes))
        self.max_bundles = max(1, int(max_bundles))
        # Which event types dump. The trainer's recorder uses the full
        # set; the slo_monitor sidecar narrows to ("slo_violation",) so a
        # fault the TRAINER'S recorder already bundled is not bundled
        # twice from the tailed stream.
        self.triggers = tuple(triggers)
        self.ring: List[Dict[str, Any]] = []
        self.manifest: Optional[Dict[str, Any]] = None
        self.last_numerics: Optional[Dict[str, Any]] = None
        self.last_memory: Optional[Dict[str, Any]] = None
        self.compiles: List[Dict[str, Any]] = []
        self.bundles: List[str] = []
        self.suppressed = 0          # triggers past max_bundles
        self.write_errors = 0

    def observe(self, event: Dict[str, Any]) -> None:
        """EventLog observer: ring + pin + trigger. Never raises (same
        contract as ``EventLog.emit`` — observability must not sink the
        observed)."""
        try:
            self.ingest(event)
            if event.get("type") in self.triggers:
                self.dump(reason=event.get("type"), trigger=event)
        except Exception:
            self.write_errors += 1

    def ingest(self, event: Dict[str, Any]) -> None:
        """Ring + pin WITHOUT triggering — how a sidecar (slo_monitor)
        feeds the events it merely TAILED for bundle context, so a
        violation already in the stream cannot re-dump on replay."""
        etype = event.get("type")
        self.ring.append(event)
        if len(self.ring) > self.capacity:
            del self.ring[:len(self.ring) - self.capacity]
        if etype == "manifest":
            self.manifest = event
        elif etype == "numerics":
            self.last_numerics = event
        elif etype == "memory":
            # The memory census (schema v9): the last MemoryMeter sample
            # before the trip — RSS, state/mirror bytes, pool occupancy
            # and fragmentation — pinned so every postmortem can say what
            # memory looked like when things went wrong.
            self.last_memory = event
        elif etype == "compile":
            self.compiles.append(event)
            if len(self.compiles) > 32:
                del self.compiles[:len(self.compiles) - 32]

    def dump(self, *, reason: str,
             trigger: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Write one bundle; returns its path (None when capped/failed)."""
        if len(self.bundles) >= self.max_bundles:
            self.suppressed += 1
            return None
        bundle = {
            "bundle": BUNDLE_KIND,
            "schema": _schema_version(),
            "reason": reason,
            "t": time.time(),
            "run_id": (trigger or self.manifest or {}).get("run_id"),
            "trigger": trigger,
            "attribution": (trigger or {}).get("attribution"),
            "manifest": self.manifest,
            "last_numerics": self.last_numerics,
            "memory": self.last_memory,
            "compiles": self.compiles,
            "recent_events": list(self.ring),
            "dropped_events": 0,
        }
        try:
            data = _fit_bundle(bundle, self.max_bytes)
            os.makedirs(self.out_dir, exist_ok=True)
            # First free index at/after this recorder's count: a relaunch
            # reusing the telemetry dir (or a sidecar recorder sharing it)
            # must not overwrite a dead run's postmortem — the bundle that
            # explains the death is the one worth keeping.
            n = len(self.bundles)
            while True:
                path = os.path.join(self.out_dir,
                                    f"postmortem-{n:03d}-{reason}.json")
                if not os.path.exists(path):
                    break
                n += 1
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(data)
            os.replace(tmp, path)
            self.bundles.append(path)
            return path
        except Exception:
            self.write_errors += 1
            return None


def _schema_version() -> int:
    from .events import SCHEMA_VERSION
    return SCHEMA_VERSION


def _fit_bundle(bundle: Dict[str, Any], max_bytes: int) -> str:
    """Serialize under the byte cap: evict oldest ring events (recording
    how many) until it fits; as a last resort drop the ring entirely —
    the pinned context alone is still a useful postmortem."""
    data = json.dumps(bundle, default=str)
    while len(data.encode()) > max_bytes and bundle["recent_events"]:
        drop = max(1, len(bundle["recent_events"]) // 4)
        del bundle["recent_events"][:drop]
        bundle["dropped_events"] += drop
        data = json.dumps(bundle, default=str)
    return data


def load_bundle(path: str) -> Dict[str, Any]:
    """Read one postmortem bundle back (jax-free; raises on a file that
    is not a bundle — the renderer's input validation)."""
    with open(path) as f:
        bundle = json.load(f)
    if not isinstance(bundle, dict) or bundle.get("bundle") != BUNDLE_KIND:
        raise ValueError(f"{path}: not a {BUNDLE_KIND} bundle")
    return bundle


def find_bundles(root: str) -> List[str]:
    """Bundle paths under ``root`` (a telemetry dir or its ``postmortem/``
    subdir), sorted."""
    hits: List[str] = []
    for base, _, files in os.walk(root):
        for f in files:
            if f.startswith("postmortem-") and f.endswith(".json"):
                hits.append(os.path.join(base, f))
    return sorted(hits)
