"""Experiment records and metrics.

`RunResult` mirrors the reference's result record (reference:
lab/tutorial_1a/hfl_complete.py:113-138): algorithm name, N/C/B/E/η/seed, and
per-round wall time, cumulative message count, and test accuracy, with a
pandas rendering that displays η and B=-1 as ∞. The message-count model is the
reference's ``2·(round+1)·clients_per_round`` (hfl_complete.py:383) — one
down + one up message per sampled client per round, cumulative.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np


@dataclass
class RunResult:
    algorithm: str
    nr_clients: int                # N
    client_fraction: float         # C
    batch_size: int                # B (-1 ⇒ ∞)
    epochs: int                    # E
    lr: float                      # η
    seed: int
    wall_time: List[float] = field(default_factory=list)
    message_count: List[int] = field(default_factory=list)
    test_accuracy: List[float] = field(default_factory=list)

    def record_round(self, wall_time: float, message_count: int, test_accuracy: float) -> None:
        self.wall_time.append(float(wall_time))
        self.message_count.append(int(message_count))
        self.test_accuracy.append(float(test_accuracy))

    @property
    def rounds(self) -> int:
        return len(self.test_accuracy)

    def as_df(self):
        """Pandas rendering with the reference's display conventions
        (hfl_complete.py:124-138: unicode η column, B=-1 shown as ∞)."""
        import pandas as pd

        b = "∞" if self.batch_size == -1 else self.batch_size
        return pd.DataFrame(
            {
                "algorithm": self.algorithm,
                "N": self.nr_clients,
                "C": self.client_fraction,
                "B": b,
                "E": self.epochs,
                "η": self.lr,
                "seed": self.seed,
                "round": np.arange(1, self.rounds + 1),
                "wall_time": np.asarray(self.wall_time),
                "message_count": np.asarray(self.message_count),
                "test_accuracy": np.asarray(self.test_accuracy),
            }
        )


@dataclass
class ResilienceStats:
    """Fault-handling counters shared by the resilience layer (resilience/):
    StepGuard skip/rollback accounting, retry_call retries, Checkpointer
    restore fallbacks, FL survivor re-weighting, and preemption force-saves.
    One instance threads through a run; ``as_dict`` lands in bench JSON and
    experiment CSVs so a fault-free run's zeros are visible evidence."""

    skipped_steps: int = 0       # StepGuard: non-finite loss/params → no-op
    anomalies: int = 0           # StepGuard: EMA update-norm outliers
    rollbacks: int = 0           # StepGuard: K consecutive bad → restore
    retries: int = 0             # retry_call invocations that re-tried IO
    ckpt_fallbacks: int = 0      # Checkpointer.restore skipped corrupt steps
    dropped_clients: int = 0     # FL: vanished clients excluded from rounds
    straggler_clients: int = 0   # FL: over-deadline clients excluded
    skipped_rounds: int = 0      # FL: rounds with zero surviving clients
    preemptions: int = 0         # SIGTERM force-save exits
    remeshes: int = 0            # elastic: replica-loss re-mesh recoveries
    ckpt_reshards: int = 0       # cross-topology checkpoint restores

    def as_dict(self) -> dict:
        return {k: int(v) for k, v in self.__dict__.items()}

    def merge(self, other: "ResilienceStats") -> "ResilienceStats":
        for k, v in other.__dict__.items():
            setattr(self, k, getattr(self, k) + v)
        return self

    def delta(self, prev: dict) -> dict:
        """Counters that moved since the ``prev`` snapshot (an ``as_dict``
        result) — the shape telemetry fault events carry. Empty when
        nothing changed."""
        return {k: v - prev.get(k, 0) for k, v in self.as_dict().items()
                if v != prev.get(k, 0)}

    @property
    def total_faults_handled(self) -> int:
        return sum(self.__dict__.values())


def message_count(round_idx: int, clients_per_round: int) -> int:
    """Cumulative messages after round ``round_idx`` (0-based):
    ``2·(round+1)·m`` (reference: hfl_complete.py:383)."""
    return 2 * (round_idx + 1) * clients_per_round


def accuracy(logits: np.ndarray, labels: np.ndarray) -> float:
    """Top-1 accuracy."""
    return float((np.asarray(logits).argmax(-1) == np.asarray(labels)).mean())


def confusion_matrix(predictions: np.ndarray, labels: np.ndarray, num_classes: int) -> np.ndarray:
    """Class-wise confusion matrix, rows = true label, cols = prediction
    (reference: attacks_and_defenses.ipynb cell 17 `get_conf_maf`)."""
    predictions = np.asarray(predictions).reshape(-1)
    labels = np.asarray(labels).reshape(-1)
    cm = np.zeros((num_classes, num_classes), dtype=np.int64)
    np.add.at(cm, (labels, predictions), 1)
    return cm


def backdoor_metrics(
    clean_predictions: np.ndarray,
    clean_labels: np.ndarray,
    triggered_predictions: np.ndarray,
    backdoor_label: int,
) -> tuple:
    """(clean accuracy, attack success rate).

    ASR = fraction of the fully-triggered test set classified as the backdoor
    label (reference: attacks_and_defenses.ipynb cell 30
    `confusion_matrix_backdoor`). Samples whose true label already equals the
    backdoor label are excluded from the ASR denominator.
    """
    clean_predictions = np.asarray(clean_predictions)
    clean_labels = np.asarray(clean_labels)
    triggered_predictions = np.asarray(triggered_predictions)
    clean_acc = float((clean_predictions == clean_labels).mean())
    mask = clean_labels != backdoor_label
    if not mask.any():  # degenerate test set: every true label is the backdoor label
        return clean_acc, 0.0
    asr = float((triggered_predictions[mask] == backdoor_label).mean())
    return clean_acc, asr
