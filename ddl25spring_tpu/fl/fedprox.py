"""FedProx: FedAvg with a proximal local objective (Li et al., MLSys 2020).

Parity-plus (absent in the reference): the standard fix for client drift
under statistical heterogeneity — each client minimizes
``F_k(w) + (μ/2)·‖w − w_t‖²`` locally, so divergent non-IID updates are
tethered to the global model. Same weight-upload round and sample-count-
weighted averaging as fl.servers.FedAvgServer; only the local solver
changes (fl.local.local_prox_sgd). ``mu=0`` reproduces FedAvg exactly
(asserted in tests/test_fedprox.py). To compose FedProx with the
attack/defense machinery, plug ``local_prox_sgd`` into the Δ-upload
substrate (fl.servers.FedAvgGradServer) instead — that server, not this
one, is what attacks and defenses hook into.
"""

from __future__ import annotations

import jax

from ..utils import pytree as pt
from .local import local_prox_sgd
from .servers import _ServerBase, _weights_for


class FedProxServer(_ServerBase):
    """FedAvg round shape with the proximal local solver; ``mu`` is the
    proximal coefficient (0 ⇒ exactly FedAvg)."""

    def __init__(self, *args, mu: float = 0.01, **kw):
        super().__init__(*args, algorithm="fedprox", **kw)
        self.mu = float(mu)
        data, cfg, apply_fn = self.data, self.cfg, self.apply_fn
        mu_ = self.mu

        @jax.jit
        def round_step(params, idx, keys):
            xs, ys, ms = data.x[idx], data.y[idx], data.mask[idx]
            new_weights = jax.vmap(
                lambda x, y, m, k: local_prox_sgd(
                    apply_fn, params, x, y, m, epochs=cfg.epochs,
                    batch_size=cfg.batch_size, lr=cfg.lr, mu=mu_, key=k)
            )(xs, ys, ms, keys)
            w = _weights_for(data.sample_counts[idx])
            return pt.tree_weighted_sum(new_weights, w)

        self._round_step = round_step
