"""FedProx: FedAvg with a proximal local objective (Li et al., MLSys 2020).

Parity-plus (absent in the reference): the standard fix for client drift
under statistical heterogeneity — each client minimizes
``F_k(w) + (μ/2)·‖w − w_t‖²`` locally, so divergent non-IID updates are
tethered to the global model. Same weight-upload round and sample-count-
weighted averaging as fl.servers.FedAvgServer; only the local solver
changes (fl.local.local_prox_sgd). ``mu=0`` reproduces FedAvg exactly
(asserted in tests/test_fedprox.py). To compose FedProx with the
attack/defense machinery, plug ``local_prox_sgd`` into the Δ-upload
substrate (fl.servers.FedAvgGradServer) instead — that server, not this
one, is what attacks and defenses hook into.
"""

from __future__ import annotations

from .local import local_prox_sgd
from .servers import FedAvgServer


class FedProxServer(FedAvgServer):
    """FedAvgServer's round shape (sample → vmapped local solve → weighted
    average) with the proximal local solver swapped in; ``mu`` is the
    proximal coefficient (0 ⇒ exactly FedAvg)."""

    def __init__(self, *args, mu: float = 0.01, **kw):
        self.mu = float(mu)  # before super(): _local_solver reads it
        super().__init__(*args, algorithm="fedprox", **kw)

    def _local_solver(self):
        cfg, apply_fn, mu = self.cfg, self.apply_fn, self.mu
        return lambda p, x, y, m, k: local_prox_sgd(
            apply_fn, p, x, y, m, epochs=cfg.epochs,
            batch_size=cfg.batch_size, lr=cfg.lr, mu=mu, key=k)
