from .federated_data import FederatedDataset, federate  # noqa: F401
from .fedprox import FedProxServer  # noqa: F401
from .fleet import (FederatedArraySource, FleetConfig,  # noqa: F401
                    FleetFedAvgServer, SyntheticFleetSource, TierPolicy,
                    vmapped_round_reference)
from .privacy import (DPFedAvgServer, dp_epsilon,  # noqa: F401
                      dp_epsilon_tight, privacy_spend)
from .secure_agg import SecureAggFedAvgServer  # noqa: F401
from .servers import (  # noqa: F401
    CentralizedServer,
    FedAvgGradServer,
    FedAvgServer,
    FedSgdGradientServer,
    FedSgdWeightServer,
)
