"""Horizontal-FL servers: FedSGD (gradient & weight), FedAvg, and baselines.

Capability targets (lab/tutorial_1a/hfl_complete.py):
- `FedSgdGradientServer` :256-308 — sampled clients return one full-subset
  gradient; server applies the sample-count-weighted average with lr.
- FedSGD weight variant (hw1 A1) — clients take the SGD step locally and
  upload weights; must match the gradient variant to ~0.02% test accuracy
  (lab/hw01/homework-1.ipynb cell 9).
- `FedAvgServer` :332-386 — E local epochs, C·N sampled clients, B batch,
  sample-count weighting, per-round RunResult metrics.
- `FedAvgGradServer` (lab/tutorial_3/attacks_and_defenses.ipynb cell 4) — the
  delta-upload reframing (client returns Δ = w_init − w_final; server does
  w ← w − avg(Δ)) that all attacks and Byzantine defenses plug into.
- `CentralizedServer` :184-223 — the non-federated baseline.

TPU-native design: clients are not processes or objects — a round is ONE
jitted program that gathers the sampled clients' padded subsets from the
stacked client axis, vmaps the local-training kernel over them, and reduces
with a weighted sum. Client sampling and the per-(client, round) seed formula
stay on the host, observable and bit-reproducible (rng.py).

The aggregation point is an explicit hook (``defense=``): selection defenses
(Krum family) return surviving client indices; aggregation defenses
(median family) replace the weighted mean entirely — mirroring the
FedAvgServerDefense / FedAvgServerDefenseCoordinate split (cells 34, 43).

Aggregation discipline: the weighted average is a SEQUENTIAL fold
(utils.pytree.tree_weighted_fold) with the weights computed by ONE shared
compiled helper (``_round_weights``) and passed into the round step. Both
choices are load-bearing: the fold's fixed association makes zero-weight
rows exact no-ops (so faulted rounds can pad instead of retracing, below)
and makes the cohort-streaming fleet engine (fl/fleet.py) bitwise-equal
to these vmapped servers at equal cohort content.

Benign faults (resilience layer): every server accepts ``fault_plan=`` — a
resilience.FaultPlan scheduling client dropout/straggling per round. The
round then aggregates over the survivors with renormalized sample-count
weights (an all-clients-lost round is skipped, params unchanged), and the
drop/straggle/skip counters land in ``server.resilience``. Survivor sets
are padded back to the full sampled width with zero-weight duplicates, so
every survivor count reuses the one compiled round step. This is the
paper's Byzantine story (§6) extended to the *infrastructure* fault class:
a vanished client is handled by the same aggregation point as a malicious
one, but by re-weighting instead of by defense.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import rng as rngmod
from ..config import FLConfig
from ..metrics import ResilienceStats, RunResult, message_count
from ..utils import pytree as pt
from .federated_data import FederatedDataset
from .local import full_batch_grad, local_sgd, masked_mean_loss

PyTree = Any


def _weights_for(counts: jnp.ndarray,
                 wmask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Sample-count FedAvg weights over the sampled clients
    (hfl_complete.py:366-368). ``wmask`` (0/1 per client) zeroes padded or
    dropped entries while keeping the array shape — the compiled round step
    then serves every survivor count at one trace."""
    c = counts.astype(jnp.float32)
    if wmask is not None:
        c = c * wmask
    return c / jnp.maximum(c.sum(), 1.0)


# ONE standalone compiled weight computation, shared by every server's
# ``_round`` and by the fleet engine (fl/fleet.py): weights computed here
# and passed INTO the round step are bitwise identical across the vmapped
# and cohort-streamed paths — computing them inside each round step would
# leave the reduction over ``counts`` at the mercy of how XLA fuses that
# particular program.
_round_weights = jax.jit(_weights_for)


class _ServerBase:
    """Shared plumbing: jitted test(), client sampling, metrics."""

    def __init__(self, init_params: PyTree, apply_fn, data: FederatedDataset,
                 test_x: jnp.ndarray, test_y: jnp.ndarray, cfg: FLConfig,
                 algorithm: str, fault_plan=None, telemetry=None):
        self.apply_fn = apply_fn
        self.params = init_params
        self.data = data
        self.test_x = jnp.asarray(test_x)
        self.test_y = jnp.asarray(test_y)
        self.cfg = cfg
        # Benign-fault injection (resilience.FaultPlan): scheduled client
        # dropout/straggling per round. Counters in ``self.resilience``.
        self.fault_plan = fault_plan
        # Unified observability (telemetry.Telemetry): ``run`` emits a
        # manifest, one fl_round summary per round, a per-round heartbeat,
        # and a run_end metrics snapshot into the shared event stream.
        self.telemetry = telemetry
        self.resilience = ResilienceStats()
        self.result = RunResult(algorithm, cfg.nr_clients, cfg.client_fraction,
                                cfg.batch_size, cfg.epochs, cfg.lr, cfg.seed)

        @jax.jit
        def _test(params):
            logits = apply_fn(params, self.test_x)
            return (logits.argmax(-1) == self.test_y).mean()

        self._test = _test

    def test(self) -> float:
        """Full-test-set accuracy in one batch (hfl_complete.py:170-181)."""
        return float(self._test(self.params))

    def _sample(self, round_idx: int) -> np.ndarray:
        return np.asarray(rngmod.sample_clients(
            self.cfg.seed, round_idx, self.cfg.nr_clients, self.cfg.clients_per_round))

    def client_seeds(self, round_idx: int, client_idx: np.ndarray) -> np.ndarray:
        """The reference's observable per-(client, round) seed vector:
        seed + ind + 1 + round·m with ind the sampled client's GLOBAL index
        (hfl_complete.py:364) — so a client's local randomness is identical
        regardless of its position in the sampling order."""
        m = self.cfg.clients_per_round
        return np.asarray([rngmod.per_client_seed(self.cfg.seed, round_idx, int(i), m)
                           for i in client_idx])

    def _record(self, round_idx: int, wall: float) -> None:
        self.result.record_round(
            wall, message_count(round_idx, self.cfg.clients_per_round), self.test())

    # Faulted rounds pad the survivor set back to the full sampled width
    # (duplicating a survivor at weight 0), so every survivor count reuses
    # the ONE compiled round step. Selection defenses inspect per-client
    # geometry (a duplicated client would have pairwise distance 0 and skew
    # Krum's scores), so FedAvgGradServer opts out when a defense is set
    # and falls back to filtering (one retrace per distinct count).
    _pad_dropout = True

    def _round(self, params, r):
        idx = self._sample(r)
        wmask = None
        if self.fault_plan is not None:
            # Benign faults: scheduled clients vanish (dropped) or miss the
            # round deadline (stragglers). The round re-weights aggregation
            # over the survivors — the sample-count weights renormalize over
            # whoever is left, and every defense hook sees only updates that
            # actually arrived. Deterministic under the plan's seed; and
            # because client seeds use the GLOBAL client index
            # (hfl_complete.py:364), a survivor's local randomness is
            # identical whether or not its peers dropped — the surviving
            # contributions are bit-identical to the fault-free round's.
            # With ``_pad_dropout`` the dropped entries stay in the array as
            # zero-weight duplicates of a survivor: tree_weighted_fold
            # selects around weight-0 rows exactly, so the padded round is
            # BITWISE the filtered one (pinned in tests/test_resilience.py)
            # while holding one compiled shape across survivor counts.
            mask, dropped, stragglers = \
                self.fault_plan.surviving_clients(r, idx)
            self.resilience.dropped_clients += dropped
            self.resilience.straggler_clients += stragglers
            if not mask.any():
                # Every sampled client vanished: skip the round (params
                # unchanged) rather than dividing by zero arrivals.
                self.resilience.skipped_rounds += 1
                return params
            if not mask.all():
                if self._pad_dropout:
                    idx = np.where(mask, idx, idx[mask][0])
                    wmask = jnp.asarray(mask, jnp.float32)
                else:
                    idx = idx[mask]
        # Per-(client, round) PRNG keys from the reference seed formula:
        # dropout inside local training (the reference trains in train mode,
        # hfl_complete.py:72,271,351) and any data poisoning fold from these.
        keys = jax.vmap(jax.random.key)(jnp.asarray(self.client_seeds(r, idx)))
        idx = jnp.asarray(idx)
        w = _round_weights(self.data.sample_counts[idx], wmask)
        return self._round_step(params, idx, keys, w)

    def run(self, nr_rounds: Optional[int] = None) -> RunResult:
        nr_rounds = self.cfg.rounds if nr_rounds is None else nr_rounds
        tel = self.telemetry
        if tel is not None:
            import dataclasses
            tel.events.manifest(
                trainer=f"fl/{self.result.algorithm}",
                jax_version=jax.__version__,
                platform=jax.devices()[0].platform,
                fl_cfg=dataclasses.asdict(self.cfg), rounds=nr_rounds,
                **getattr(self, "_manifest_extra", {}))
            prev_counters = self.resilience.as_dict()
        for r in range(nr_rounds):
            t0 = time.perf_counter()
            self.params = self._round(self.params, r)
            jax.block_until_ready(self.params)
            self._record(r, time.perf_counter() - t0)
            if tel is not None:
                tel.heartbeat.beat(step=r, phase="fl_round")
                wall = self.result.wall_time[-1]
                tel.registry.observe("fl_round_s", wall)
                delta = self.resilience.delta(prev_counters)
                prev_counters = self.resilience.as_dict()
                tel.events.fl_round(
                    round=r, wall_s=wall,
                    test_accuracy=self.result.test_accuracy[-1],
                    messages=self.result.message_count[-1],
                    **({"faults": delta} if delta else {}))
        if tel is not None:
            tel.registry.absorb_resilience(self.resilience)
            tel.events.run_end(steps=nr_rounds,
                               final_accuracy=(self.result.test_accuracy[-1]
                                               if self.result.rounds else None),
                               metrics=tel.registry.snapshot())
        return self.result


class FedSgdGradientServer(_ServerBase):
    """One full-subset gradient per sampled client, weighted-averaged, one
    server SGD step per round (hfl_complete.py:256-308)."""

    def __init__(self, *args, **kw):
        super().__init__(*args, algorithm="fedsgd", **kw)
        data, cfg, apply_fn = self.data, self.cfg, self.apply_fn

        @jax.jit
        def round_step(params, idx, keys, w):
            xs, ys, ms = data.x[idx], data.y[idx], data.mask[idx]
            _, grads = jax.vmap(lambda x, y, m, k: full_batch_grad(
                apply_fn, params, x, y, m, k))(xs, ys, ms, keys)
            agg = pt.tree_weighted_fold(grads, w)
            return jax.tree.map(lambda p, g: p - cfg.lr * g, params, agg)

        self._round_step = round_step


class FedSgdWeightServer(_ServerBase):
    """Equivalent reformulation: clients take the lr·grad step locally and
    upload weights; the server weighted-averages them (hw1 A1)."""

    def __init__(self, *args, **kw):
        super().__init__(*args, algorithm="fedsgd-w", **kw)
        data, cfg, apply_fn = self.data, self.cfg, self.apply_fn

        @jax.jit
        def round_step(params, idx, keys, w):
            xs, ys, ms = data.x[idx], data.y[idx], data.mask[idx]

            def client(x, y, m, k):
                _, g = full_batch_grad(apply_fn, params, x, y, m, k)
                return jax.tree.map(lambda p, gi: p - cfg.lr * gi, params, g)

            new_weights = jax.vmap(client)(xs, ys, ms, keys)
            return pt.tree_weighted_fold(new_weights, w)

        self._round_step = round_step


class FedAvgServer(_ServerBase):
    """E local SGD epochs per sampled client, weight upload, sample-count
    weighted average (hfl_complete.py:332-386).

    The round shape (sample → vmapped local solve → weighted average) is
    shared by subclasses that swap only the local solver (fl.fedprox):
    override ``_local_solver`` to return
    ``solver(params, x, y, mask, key) -> new_params``.
    """

    def __init__(self, *args, algorithm: str = "fedavg", **kw):
        super().__init__(*args, algorithm=algorithm, **kw)
        data = self.data
        solver = self._local_solver()

        @jax.jit
        def round_step(params, idx, keys, w):
            xs, ys, ms = data.x[idx], data.y[idx], data.mask[idx]
            new_weights = jax.vmap(
                lambda x, y, m, k: solver(params, x, y, m, k))(xs, ys, ms, keys)
            return pt.tree_weighted_fold(new_weights, w)

        self._round_step = round_step

    def _local_solver(self):
        cfg, apply_fn = self.cfg, self.apply_fn
        return lambda p, x, y, m, k: local_sgd(
            apply_fn, p, x, y, m, epochs=cfg.epochs,
            batch_size=cfg.batch_size, lr=cfg.lr, key=k)


class FedAvgGradServer(_ServerBase):
    """Delta-upload FedAvg: clients return Δ = w_server − w_local_final and
    the server applies w ← w − aggregate(Δ) — the substrate every attack and
    defense plugs into (attacks_and_defenses.ipynb cell 4).

    ``adversary``: optional (mask, attack) — mask [N] bool marks Byzantine
    clients; attack transforms their honest deltas (and/or local batches).
    ``defense``: optional aggregation hook (see fl.defenses).
    """

    def __init__(self, *args, adversary=None, defense=None, **kw):
        super().__init__(*args, algorithm="fedavg-grad", **kw)
        self.adversary = adversary
        self.defense = defense
        # Selection defenses score per-client geometry; a zero-weight
        # padded duplicate would sit at distance 0 from its twin and skew
        # Krum-family scores, so defended servers keep the filtering path.
        self._pad_dropout = defense is None
        data, cfg, apply_fn = self.data, self.cfg, self.apply_fn
        attack = adversary[1] if adversary is not None else None
        malicious_mask = jnp.asarray(adversary[0]) if adversary is not None else None

        @jax.jit
        def round_step(params, idx, keys, w):
            xs, ys, ms = data.x[idx], data.y[idx], data.mask[idx]

            def client(x, y, m, key, is_mal):
                if attack is not None and attack.poisons_data:
                    # Data poisoning: malicious clients train on transformed
                    # batches (label flips, backdoor stamps). The poison fold
                    # constant is outside local_sgd's small step-index fold
                    # domain so the streams stay independent, while local_sgd
                    # still receives the raw client key — keeping honest
                    # trajectories bit-identical to FedAvgServer's (the
                    # delta-framing equivalence).
                    px, py = attack.poison(x, y, jax.random.fold_in(key, 0x7EA))
                    x = jnp.where(is_mal, px, x)
                    y = jnp.where(is_mal, py, y)
                new = local_sgd(apply_fn, params, x, y, m, epochs=cfg.epochs,
                                batch_size=cfg.batch_size, lr=cfg.lr, key=key)
                delta = pt.tree_sub(params, new)           # Δ = w0 − w_final
                if attack is not None:
                    mal_delta = attack.transform(delta, params)
                    delta = jax.tree.map(
                        lambda h, a: jnp.where(is_mal, a, h), delta, mal_delta)
                return delta

            is_mal = (malicious_mask[idx] if malicious_mask is not None
                      else jnp.zeros(idx.shape, bool))
            deltas = jax.vmap(client)(xs, ys, ms, keys, is_mal)
            if defense is None:
                agg = pt.tree_weighted_fold(deltas, w)
            else:
                agg = defense(deltas, w)
            return pt.tree_sub(params, agg)

        self._round_step = round_step


class CentralizedServer(_ServerBase):
    """Non-federated baseline: plain minibatch SGD over the whole training
    set, one epoch per round (hfl_complete.py:184-223)."""

    def __init__(self, init_params, apply_fn, x, y, test_x, test_y, cfg: FLConfig,
                 telemetry=None):
        x, y = jnp.asarray(x), jnp.asarray(y)
        data = FederatedDataset(x[None], y[None], jnp.ones(y.shape, jnp.float32)[None],
                                jnp.asarray([y.shape[0]]))
        super().__init__(init_params, apply_fn, data, test_x, test_y, cfg,
                         algorithm="centralized", telemetry=telemetry)
        # The baseline is one node: N=1, C=1, E=1, and zero messages per
        # round (reference: hfl_complete.py:205 appends message_count 0).
        self.result = RunResult("centralized", 1, 1.0, cfg.batch_size, 1,
                                cfg.lr, cfg.seed)

        @jax.jit
        def round_step(params, r):
            # The reference's centralized DataLoader reshuffles every round
            # (hfl_complete.py:194-195, shuffle=True) and runs exactly ONE
            # epoch per round (:202-205) — cfg.epochs is a federated knob
            # and does not apply to the baseline.
            perm = jax.random.permutation(
                jax.random.fold_in(jax.random.key(cfg.seed), r), data.y.shape[1])
            return local_sgd(apply_fn, params, data.x[0][perm], data.y[0][perm],
                             data.mask[0][perm], epochs=1,
                             batch_size=cfg.batch_size, lr=cfg.lr,
                             key=jax.random.fold_in(jax.random.key(cfg.seed + 1), r))

        self._round_step = round_step

    def _round(self, params, r):
        return self._round_step(params, r)

    def _record(self, round_idx: int, wall: float) -> None:
        self.result.record_round(wall, 0, self.test())
