"""Byzantine-robust aggregation rules — pure functions over the client axis.

Capability targets:
- Krum / Multi-Krum selection (attacks_and_defenses.ipynb cells 34, 37):
  score_i = Σ of the n−f−2 smallest squared L2 distances to other updates;
  Krum picks the argmin, Multi-Krum iterates k times removing each winner.
- coordinate-median / trimmed mean (cell 43, 46): per-coordinate stack over
  clients; median, or sort-trim-β then mean.
- majority-sign filtering (cell 49), norm clipping (cell 55).
- Bulyan (hw03 cell 15): Multi-Krum preselection → per-coordinate trimmed
  mean over survivors.
- SparseFed (hw03 cell 26): per-client norm clip → average → global top-k by
  magnitude, rest zeroed.

API note: the reference pre-scales client updates by sample weights and its
coordinate defenses multiply by ·20 (= clients/round) to undo that scaling
(cell 43). Here defenses receive the RAW per-client deltas ``[m, ...]`` plus
the normalized sample weights, so no magic rescale exists: selection rules
return indices (the server re-weights survivors), aggregation rules return
the aggregated delta directly. With equal sample counts the two formulations
are identical.

Everything is jnp over a stacked flat view [m, P] — jit/vmap friendly and
unit-testable against hand-computed cases.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import pytree as pt

PyTree = Any


# ------------------------------------------------------------ flat stacking

def stack_flat(deltas: PyTree) -> Tuple[jnp.ndarray, Callable[[jnp.ndarray], PyTree]]:
    """Stacked pytree (leading client axis m) -> (flat [m, P], unflatten for
    a single [P] vector)."""
    leaves = jax.tree.leaves(deltas)
    treedef = jax.tree.structure(deltas)
    m = leaves[0].shape[0]
    shapes = [l.shape[1:] for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    flat = jnp.concatenate([l.reshape(m, -1) for l in leaves], axis=1)

    def unflatten(vec: jnp.ndarray) -> PyTree:
        parts = []
        off = 0
        for shape, size in zip(shapes, sizes):
            parts.append(vec[off:off + size].reshape(shape))
            off += size
        return jax.tree.unflatten(treedef, parts)

    return flat, unflatten


def unstack_flat(flat: jnp.ndarray, template: PyTree) -> PyTree:
    """Inverse of ``stack_flat`` for a whole [m, P] stack: rebuild the
    stacked pytree (leading client axis m) whose per-leaf trailing shapes
    come from ``template`` (a single un-stacked pytree, e.g. the params).

    The fleet engine (fl/fleet.py) streams per-client deltas off-device as
    flat rows and hands defenses the SAME stacked-tree shape the vmapped
    servers produce; round-tripping through stack_flat is pure
    reshape/concatenate, so the rebuilt stack is bitwise the original."""
    leaves = jax.tree.leaves(template)
    treedef = jax.tree.structure(template)
    m = flat.shape[0]
    parts = []
    off = 0
    for leaf in leaves:
        size = int(np.prod(leaf.shape)) if leaf.shape else 1
        parts.append(flat[:, off:off + size].reshape((m,) + leaf.shape))
        off += size
    return jax.tree.unflatten(treedef, parts)


# ------------------------------------------------------------ selection rules

def krum_scores(flat: jnp.ndarray, n_malicious: int) -> jnp.ndarray:
    """Per-client Krum score: sum of its n−f−2 smallest squared distances."""
    m = flat.shape[0]
    d2 = jnp.sum((flat[:, None, :] - flat[None, :, :]) ** 2, axis=-1)  # [m, m]
    d2 = d2 + jnp.diag(jnp.full((m,), jnp.inf))                       # exclude self
    k = max(m - n_malicious - 2, 1)
    nearest = jnp.sort(d2, axis=1)[:, :k]
    return nearest.sum(axis=1)


def krum(flat: jnp.ndarray, n_malicious: int) -> jnp.ndarray:
    """Index of the Krum winner (cell 34)."""
    return jnp.argmin(krum_scores(flat, n_malicious))


def multi_krum(flat: jnp.ndarray, n_malicious: int, k: int) -> jnp.ndarray:
    """k Krum winners, selected iteratively with removal (cell 37).

    Removal is emulated by masking: after each pick, the winner's distances
    are excluded from every later score. Returns [k] indices.
    """
    m = flat.shape[0]
    d2 = jnp.sum((flat[:, None, :] - flat[None, :, :]) ** 2, axis=-1)
    d2 = d2 + jnp.diag(jnp.full((m,), jnp.inf))

    def pick(carry, _):
        removed, d2m = carry
        n_remaining = m - removed.sum()
        kk = jnp.maximum(n_remaining - n_malicious - 2, 1)
        srt = jnp.sort(d2m, axis=1)
        ranks = jnp.arange(m)[None, :]
        scores = jnp.where(ranks < kk, srt, 0.0).sum(axis=1)
        scores = jnp.where(removed, jnp.inf, scores)
        winner = jnp.argmin(scores)
        removed = removed.at[winner].set(True)
        d2m = d2m.at[:, winner].set(jnp.inf)
        return (removed, d2m), winner

    (_, _), winners = jax.lax.scan(pick, (jnp.zeros(m, bool), d2), None, length=k)
    return winners


# ------------------------------------------------------------ coordinate rules

def coordinate_median(flat: jnp.ndarray) -> jnp.ndarray:
    """Per-coordinate median over clients (cell 43)."""
    return jnp.median(flat, axis=0)


def trimmed_mean(flat: jnp.ndarray, beta: float) -> jnp.ndarray:
    """Drop the β-fraction largest and smallest per coordinate, mean the rest
    (cell 46)."""
    m = flat.shape[0]
    t = int(beta * m)
    assert m - 2 * t > 0, f"beta={beta} trims all {m} clients"
    srt = jnp.sort(flat, axis=0)
    return srt[t:m - t].mean(axis=0)


def majority_sign(flat: jnp.ndarray) -> jnp.ndarray:
    """Keep only entries agreeing with the per-coordinate majority sign,
    average them (cell 49)."""
    signs = jnp.sign(flat)
    maj = jnp.sign(signs.sum(axis=0))
    agree = (signs == maj) & (maj != 0)
    # Mean over ALL clients with disagreeing entries zeroed — the reference's
    # formulation (cell 49: zeroed entries stay in the denominator).
    return jnp.where(agree, flat, 0.0).mean(axis=0)


def norm_clipping(flat: jnp.ndarray, ratio: float = 1.0) -> jnp.ndarray:
    """Scale each client update to ≤ mean-norm·ratio, then average (cell 55)."""
    norms = jnp.linalg.norm(flat, axis=1)
    bound = norms.mean() * ratio
    scale = jnp.minimum(1.0, bound / jnp.maximum(norms, 1e-12))
    return (flat * scale[:, None]).mean(axis=0)


def bulyan(flat: jnp.ndarray, n_malicious: int, k: int, beta: float) -> jnp.ndarray:
    """Multi-Krum preselect k survivors, then coordinate trimmed-mean over
    them (hw03 cell 15). When the trim would consume all survivors
    (k ≤ 2·int(β·k), e.g. every β=0.6 grid cell), the reference silently
    skips trimming and means the multi-krum winners as-is (cell 15's
    ``else: trimmed_updates = sorted_updates`` branch) — reproduced here,
    since the hw3 grid sweeps exactly those infeasible cells."""
    winners = multi_krum(flat, n_malicious, k)
    if k - 2 * int(beta * k) > 0:
        return trimmed_mean(flat[winners], beta)
    return flat[winners].mean(axis=0)


def sparse_fed(flat: jnp.ndarray, topk_fraction: float, *, clip_ratio: float = 1.0
               ) -> jnp.ndarray:
    """Per-client norm clip → average → keep the global top-k coordinates by
    magnitude, zero the rest (hw03 cell 26)."""
    avg = norm_clipping(flat, clip_ratio)
    p = avg.shape[0]
    k = max(1, int(topk_fraction * p))
    thresh = jnp.sort(jnp.abs(avg))[p - k]
    return jnp.where(jnp.abs(avg) >= thresh, avg, 0.0)


# ------------------------------------------------------------ server adapters
# FedAvgGradServer's hook signature: defense(deltas_tree [m,...], weights [m])
# -> aggregated delta tree. These adapters lift the rules above into it.

def selection_defense(rule: Callable[..., jnp.ndarray], **kw) -> Callable:
    """Wrap a selection rule (returns indices) — survivors are re-weighted by
    their sample counts, like FedAvgServerDefense (cell 34).

    The returned hook carries its flat [m, P] → [P] core as
    ``hook.flat_hook``: consumers that already hold the flat stack (the
    fleet engine streams per-client deltas off-device as flat rows) apply
    it directly instead of round-tripping through the stacked pytree —
    same ops, so both entry points agree bitwise."""

    def flat_hook(flat: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
        idx = jnp.atleast_1d(rule(flat, **kw))
        w = weights[idx]
        w = w / jnp.maximum(w.sum(), 1e-12)
        return (flat[idx] * w[:, None]).sum(axis=0)

    def hook(deltas: PyTree, weights: jnp.ndarray) -> PyTree:
        flat, unflatten = stack_flat(deltas)
        return unflatten(flat_hook(flat, weights))

    hook.flat_hook = flat_hook
    return hook


def coordinate_defense(rule: Callable[..., jnp.ndarray], **kw) -> Callable:
    """Wrap an aggregation rule operating on the flat [m, P] stack — the
    FedAvgServerDefenseCoordinate pattern (cell 43). Carries
    ``hook.flat_hook`` like ``selection_defense`` (weights unused — the
    coordinate rules replace the weighted mean entirely)."""

    def flat_hook(flat: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
        return rule(flat, **kw)

    def hook(deltas: PyTree, weights: jnp.ndarray) -> PyTree:
        flat, unflatten = stack_flat(deltas)
        return unflatten(flat_hook(flat, weights))

    hook.flat_hook = flat_hook
    return hook
