"""Client-axis data layout for federated learning.

The reference hands each simulated client a torch Subset of MNIST
(lab/tutorial_1a/hfl_complete.py:141-150, split() at :91-104). The TPU-native
layout instead *stacks* every client's subset along a leading ``client`` axis
— ``x: [N, S, ...]``, ``y: [N, S]``, ``mask: [N, S]`` — so local training
vmaps over clients and aggregation rules are reductions over axis 0. Unequal
subset sizes are padded to the max and masked; ``sample_counts`` carries the
true sizes for FedAvg's weighting (hfl_complete.py:366-368).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

import jax.numpy as jnp
import numpy as np


@dataclass
class FederatedDataset:
    x: jnp.ndarray            # [N, S, ...] padded client inputs
    y: jnp.ndarray            # [N, S] padded labels
    mask: jnp.ndarray         # [N, S] 1.0 for real samples, 0.0 for padding
    sample_counts: jnp.ndarray  # [N] true subset sizes

    @property
    def nr_clients(self) -> int:
        return self.x.shape[0]


def federate(x: np.ndarray, y: np.ndarray, subsets: Sequence[np.ndarray]) -> FederatedDataset:
    """Stack per-client index subsets into the padded client-axis layout."""
    n = len(subsets)
    s_max = max(len(s) for s in subsets)
    xs = np.zeros((n, s_max) + x.shape[1:], dtype=x.dtype)
    ys = np.zeros((n, s_max), dtype=y.dtype)
    mask = np.zeros((n, s_max), dtype=np.float32)
    counts = np.zeros((n,), dtype=np.int32)
    for i, idx in enumerate(subsets):
        k = len(idx)
        xs[i, :k] = x[idx]
        ys[i, :k] = y[idx]
        mask[i, :k] = 1.0
        counts[i] = k
    return FederatedDataset(jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(mask),
                            jnp.asarray(counts))
