"""Differentially-private federated averaging (DP-FedAvg).

Parity-plus: the reference course covers Byzantine robustness (hw3) but not
privacy; DP-FedAvg is the standard companion capability for the same
Δ-upload substrate (fl/servers.FedAvgGradServer). Central-DP model, the
McMahan et al. "Learning Differentially Private Recurrent Language Models"
recipe:

1. every sampled client's delta is L2-clipped to ``clip_norm`` (bounding
   each client's contribution — the sensitivity of the sum);
2. clipped deltas are averaged UNIFORMLY over the m sampled clients
   (sample-count weighting would make sensitivity data-dependent);
3. the server adds Gaussian noise with per-coordinate
   σ = noise_multiplier · clip_norm / m to the average.

TPU-first shape: clipping is a vmapped pure function over the stacked
client axis; the noise is one fused normal-sample + add over the param
tree; everything stays inside the server's single jitted round_step.

Privacy accounting — two bounds, both self-contained:
- ``dp_epsilon``: the CONSERVATIVE advanced-composition bound for T
  Gaussian mechanisms with noise multiplier z,
      ε(δ) = sqrt(2·T·ln(1/δ))/z + T/(2z²),
  ignoring privacy amplification by client subsampling (overestimate,
  safe direction).
- ``dp_epsilon_tight``: the subsampled-Gaussian RDP (moments) accountant
  — Mironov et al., "Rényi Differential Privacy of the Sampled Gaussian
  Mechanism" (2019), integer orders — with amplification by the per-round
  client sampling rate q = C (Poisson-style sampling assumption). For the
  reference protocol (C=0.1) this is typically an order of magnitude
  below the conservative bound; pinned against Abadi et al. (2016)'s
  published moments-accountant value in tests/test_privacy_accounting.py.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..utils import pytree as pt
from .local import local_sgd
from .servers import _ServerBase


def clip_by_global_norm(tree, clip_norm: float):
    """Scale ``tree`` so its global L2 norm is at most ``clip_norm``
    (identity when already within). Use under vmap for per-client clips."""
    norm = pt.global_norm(tree)
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(norm, 1e-12))
    return pt.tree_scale(tree, scale)


def gaussian_noise_like(key, tree, sigma: float):
    """One Gaussian sample per coordinate of ``tree``, std ``sigma``."""
    leaves, treedef = jax.tree.flatten(tree)
    keys = jax.random.split(key, len(leaves))
    noisy = [jax.random.normal(k, l.shape, jnp.float32).astype(l.dtype)
             * sigma for k, l in zip(keys, leaves)]
    return jax.tree.unflatten(treedef, noisy)


def dp_epsilon(noise_multiplier: float, rounds: int,
               delta: float = 1e-5) -> float:
    """Conservative (no-subsampling-amplification) ε for ``rounds``
    compositions of the Gaussian mechanism with noise multiplier z —
    advanced composition: sqrt(2T·ln(1/δ))/z + T/(2z²). An overestimate of
    the true privacy cost; see module docstring."""
    z, t = float(noise_multiplier), int(rounds)
    if z <= 0:
        return float("inf")
    return math.sqrt(2.0 * t * math.log(1.0 / delta)) / z + t / (2.0 * z * z)


# ---------------------------------------------------------------------------
# Subsampled-Gaussian RDP (moments) accountant — self-contained, no deps.

# Integer Rényi orders: dense where the minimum usually lands, sparse tail
# for very-high-privacy regimes.
_RDP_ORDERS = tuple(range(2, 65)) + (80, 96, 128, 192, 256, 384, 512)


def _log_binom(n: int, k: int) -> float:
    return (math.lgamma(n + 1) - math.lgamma(k + 1)
            - math.lgamma(n - k + 1))


def _rdp_sgm(q: float, z: float, alpha: int) -> float:
    """One-step RDP of order ``alpha`` (integer ≥ 2) of the Gaussian
    mechanism with noise multiplier ``z``, amplified by Poisson subsampling
    at rate ``q`` — Mironov et al. 2019, Eq. for integer orders:

        RDP(α) = 1/(α−1) · log Σ_{k=0}^{α} C(α,k)·(1−q)^{α−k}·q^k
                                   · exp(k(k−1)/(2z²))
    """
    if q == 0.0:
        return 0.0
    if q >= 1.0:                      # no subsampling: plain Gaussian RDP
        return alpha / (2.0 * z * z)
    # log-domain sum over k (log-sum-exp) — the k=α term alone can overflow
    # a float for large α/small z.
    log_terms = [
        _log_binom(alpha, k) + (alpha - k) * math.log1p(-q)
        + (k * math.log(q) if k else 0.0)
        + k * (k - 1) / (2.0 * z * z)
        for k in range(alpha + 1)
    ]
    hi = max(log_terms)
    lse = hi + math.log(sum(math.exp(t - hi) for t in log_terms))
    return lse / (alpha - 1)


def dp_epsilon_tight(noise_multiplier: float, rounds: int,
                     sampling_rate: float, delta: float = 1e-5) -> float:
    """Tight ε via the subsampled-Gaussian RDP accountant.

    ``sampling_rate`` is the per-round probability that a given client is
    sampled — the FL protocol's client fraction C (the accountant assumes
    Poisson sampling; the protocol's fixed-size sampling is the standard
    approximation). RDP composes additively over ``rounds``; the conversion
    to (ε, δ) uses the improved bound of Canonne-Kamath-Steinke 2020:

        ε = RDP_T(α) + log((α−1)/α) − (log δ + log α)/(α−1)

    minimized over the integer order grid. Returns +inf for z ≤ 0.

    Regime note: the subsampled bound is the tight one at protocol-scale
    noise (z ≳ 0.5 — e.g. an 8×+ improvement at C=0.1, T=100, z=1); at
    very small z the exp(k(k−1)/2z²) moment term blows past advanced
    composition instead. Both are valid upper bounds — a privacy
    certificate may always quote min(this, dp_epsilon(...)).
    """
    z, t, q = float(noise_multiplier), int(rounds), float(sampling_rate)
    if z <= 0:
        return float("inf")
    if q <= 0.0 or t == 0:
        return 0.0
    best = float("inf")
    for alpha in _RDP_ORDERS:
        rdp = t * _rdp_sgm(q, z, alpha)
        eps = (rdp + math.log((alpha - 1) / alpha)
               - (math.log(delta) + math.log(alpha)) / (alpha - 1))
        best = min(best, eps)
    return max(0.0, best)


def privacy_spend(noise_multiplier: float, rounds: int, sampling_rate: float,
                  delta: float = 1e-6) -> dict:
    """Both ε bounds for one (z, T, q, δ) protocol point, as a JSON-able
    record — the fleet smoke (experiments/fleet_smoke.py) reports this at
    realistic fleet sampling rates (q ~ 1e-4, where a cohort of thousands
    samples from millions of installs) so the privacy cost of a deployment
    shape is a number in CI artifacts, not a claim. ``eps_rdp_tight`` is
    the subsampled-Gaussian RDP accountant (the certifiable figure);
    ``eps_advanced_composition`` the conservative no-amplification bound —
    at fleet q the gap is orders of magnitude, which is exactly why the
    tight accountant matters at scale."""
    return {
        "sampling_rate_q": float(sampling_rate),
        "noise_multiplier": float(noise_multiplier),
        "rounds": int(rounds),
        "delta": float(delta),
        "eps_rdp_tight": dp_epsilon_tight(noise_multiplier, rounds,
                                          sampling_rate, delta),
        "eps_advanced_composition": dp_epsilon(noise_multiplier, rounds,
                                               delta),
    }


class DPFedAvgServer(_ServerBase):
    """FedAvg with per-client delta clipping + server-side Gaussian noise.

    Same Δ-upload round shape as fl.servers.FedAvgGradServer (E local
    epochs, delta = w0 − w_final, w ← w − aggregate), with the three DP
    modifications above. ``noise_multiplier=0`` disables the noise (pure
    clipping); ``clip_norm=None`` with zero noise degenerates to uniform
    (NOT sample-count-weighted) FedAvg.
    """

    def __init__(self, *args, clip_norm: Optional[float] = 1.0,
                 noise_multiplier: float = 0.0, **kw):
        super().__init__(*args, algorithm="dp-fedavg", **kw)
        self.clip_norm = clip_norm
        self.noise_multiplier = float(noise_multiplier)
        data, cfg, apply_fn = self.data, self.cfg, self.apply_fn
        clip, z = clip_norm, self.noise_multiplier

        if z > 0.0 and clip is None:
            raise ValueError("noise_multiplier > 0 needs a finite clip_norm")

        @jax.jit
        def round_step(params, idx, keys, noise_key):
            xs, ys, ms = data.x[idx], data.y[idx], data.mask[idx]

            def client(x, y, m, key):
                new = local_sgd(apply_fn, params, x, y, m, epochs=cfg.epochs,
                                batch_size=cfg.batch_size, lr=cfg.lr, key=key)
                delta = pt.tree_sub(params, new)
                if clip is not None:
                    delta = clip_by_global_norm(delta, clip)
                return delta

            deltas = jax.vmap(client)(xs, ys, ms, keys)
            m_clients = idx.shape[0]
            # Uniform average — sensitivity of the mean is clip/m.
            agg = pt.tree_scale(
                jax.tree.map(lambda d: d.sum(0), deltas), 1.0 / m_clients)
            if z > 0.0:
                sigma = z * clip / m_clients
                agg = pt.tree_add(agg,
                                  gaussian_noise_like(noise_key, agg, sigma))
            return pt.tree_sub(params, agg)

        self._round_step = round_step

    def _round(self, params, r):
        # Noise key from a DEDICATED server stream, folded per round. The
        # per-client keys use the reference's linear seed formula
        # (seed + ind + 1 + round·m), which collides across rounds — a
        # noise key derived from keys[0] could repeat the exact noise
        # tree in two rounds (voiding the Gaussian composition) and also
        # correlate with a client's local dropout stream.
        idx = self._sample(r)
        keys = jax.vmap(jax.random.key)(
            jnp.asarray(self.client_seeds(r, idx)))
        noise_key = jax.random.fold_in(
            jax.random.key(self.cfg.seed ^ 0x5E17C0DE), r)
        return self._round_step(params, jnp.asarray(idx), keys, noise_key)
