"""Byzantine client attacks.

Capability targets (lab/tutorial_3/attacks_and_defenses.ipynb):
- gradient reversion: return −5·Δ (cells 9, 35)
- partial gradient reversion: flip only the first ~1e-5 of parameters by
  ×(−1000), evading distance-based defenses (cell 41)
- untargeted label flipping: train on (y+1) mod 10, return 5·Δ (cell 11)
- targeted label flipping: flip only source→target labels, return 5·Δ (cell 14)
- pixel-pattern backdoor: stamp a 5×3 pattern at (3, 23) with an extreme
  pixel value, poison a proportion of each batch toward the backdoor label,
  return scaled Δ (cells 23-31, 50)

Design: attacks are stateless objects with a uniform, jit-compatible
protocol; the server applies them only where the Byzantine mask is set, so a
single vmapped program trains honest and malicious clients together:

- ``poisons_data`` — whether local training data is transformed
- ``poison(x, y, key) -> (x, y)`` — data-poisoning hook (whole padded subset)
- ``transform(delta, params) -> delta`` — model-poisoning hook on Δ
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from ..utils import pytree as pt

PyTree = Any


class Attack:
    poisons_data: bool = False

    def poison(self, x, y, key):
        return x, y

    def transform(self, delta: PyTree, params: PyTree) -> PyTree:
        return delta


@dataclass
class GradientReversion(Attack):
    """Return −scale·Δ (reference: cell 35, scale 5)."""
    scale: float = 5.0
    poisons_data = False

    def transform(self, delta, params):
        return pt.tree_scale(delta, -self.scale)


@dataclass
class PartialGradientReversion(Attack):
    """Flip a tiny leading slice of the flattened update by ×(−factor):
    large damage, small L2 displacement — evades Krum-style distance
    filtering (reference: cell 41, first layers ≈1e-5 of params, ×−1000)."""
    factor: float = 1000.0
    fraction: float = 1e-5
    poisons_data = False

    def transform(self, delta, params):
        flat, unflatten = pt.flatten(delta)
        k = max(1, int(flat.shape[0] * self.fraction))
        flipped = flat.at[:k].multiply(-self.factor)
        return unflatten(flipped)


@dataclass
class UntargetedLabelFlip(Attack):
    """Local training labels become (y+1) mod num_classes; update scaled
    (reference: cell 11, 5·Δ)."""
    num_classes: int = 10
    scale: float = 5.0
    poisons_data = True

    def poison(self, x, y, key):
        return x, (y + 1) % self.num_classes

    def transform(self, delta, params):
        return pt.tree_scale(delta, self.scale)


@dataclass
class TargetedLabelFlip(Attack):
    """Only source-class labels flip to the target class (reference: cell 14,
    0→6, 5·Δ)."""
    source: int = 0
    target: int = 6
    scale: float = 5.0
    poisons_data = True

    def poison(self, x, y, key):
        return x, jnp.where(y == self.source, self.target, y)

    def transform(self, delta, params):
        return pt.tree_scale(delta, self.scale)


@dataclass
class PatternBackdoor(Attack):
    """Pixel-pattern backdoor (reference: cells 23-31): stamp a pattern of
    extreme pixel values into a proportion of each client's samples and
    relabel them to the backdoor label; scale the resulting update.

    ``pattern_value`` is in *normalized* space — the reference uses −10, far
    outside MNIST's normalized range, making the trigger unmistakable.
    """
    proportion: float = 0.3
    backdoor_label: int = 0
    scale: float = 2.0
    row: int = 3
    col: int = 23
    height: int = 5
    width: int = 3
    pattern_value: float = -10.0
    poisons_data = True

    def _stamp(self, x) -> jnp.ndarray:
        """x: [S, 1, 28, 28] (NCHW, normalized); accepts numpy or jax arrays."""
        return jnp.asarray(x).at[..., self.row:self.row + self.height,
                                 self.col:self.col + self.width].set(self.pattern_value)

    def poison(self, x, y, key):
        poisoned = jax.random.bernoulli(key, self.proportion, y.shape)
        x = jnp.where(poisoned[:, None, None, None], self._stamp(x), x)
        y = jnp.where(poisoned, self.backdoor_label, y)
        return x, y

    def transform(self, delta, params):
        return pt.tree_scale(delta, self.scale)

    def trigger_test_set(self, x: jnp.ndarray) -> jnp.ndarray:
        """Fully-triggered copy of a test set, for attack-success-rate
        evaluation (reference: cell 30)."""
        return self._stamp(x)


def injection_mask(nr_clients: int, fraction: float, seed: int) -> jnp.ndarray:
    """Byzantine fault injection: mark a random ``fraction`` of clients
    malicious (reference: cell 9 — num_malicious = int(0.20·len(clients)),
    np.random.choice over indices)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    n_mal = int(fraction * nr_clients)
    mask = np.zeros(nr_clients, dtype=bool)
    mask[rng.choice(nr_clients, n_mal, replace=False)] = True
    return jnp.asarray(mask)
