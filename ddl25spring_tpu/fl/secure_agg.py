"""Secure aggregation: pairwise additive masking for the Δ-upload round.

Parity-plus (absent in the reference): the Bonawitz et al. SecAgg shape —
every pair of sampled clients (i, j), i<j, derives a shared mask from a
common PRG seed; client i uploads ``q_i + Σ_{j>i} m_ij − Σ_{j<i} m_ji``
and the server only ever sees masked vectors, yet the pairwise masks
cancel EXACTLY in the sum. Exact cancellation needs modular integer
arithmetic (in floating point ``(a+m)+(b−m) ≠ a+b`` once masks dominate
the mantissa), so updates ride a fixed-point grid:

1. clip each client delta to ``clip_norm`` (bounds the grid);
2. quantize to int32 with the data-independent scale
   ``clip_norm / 2^(bits−1)`` (shared by construction — no communication);
3. add the pairwise int32 masks; all arithmetic wraps mod 2^32 (two's
   complement), so the server's wrapped sum of masked uploads equals the
   wrapped sum of the quantized deltas exactly;
4. dequantize the sum and average.

The quantization error is the price of exactness-under-masking: with the
default 20-bit grid it is ~clip_norm·2^-19 per coordinate per client —
far below the updates it protects. This is the cryptographic *dataflow*
(what the server observes) in one SPMD program; actual key agreement,
dropout recovery, and double-masking of the real protocol are out of
scope and said so here.

TPU-first shape: masks are PRG draws inside the vmapped client function
(O(m²) int32 PRG work per round — trivial next to local SGD); the
"server" reduction is the same tree sum every other server uses.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..utils import pytree as pt
from .local import local_sgd
from .privacy import clip_by_global_norm
from .servers import _ServerBase

_MASK_SALT = 0x5EC46600


def _pair_key(root, gi, gj, r):
    """Shared PRG key for the (unordered) client pair {gi, gj} at round r:
    both parties fold (min, max, r) into the same root, so they derive the
    same mask without communicating."""
    lo = jnp.minimum(gi, gj)
    hi = jnp.maximum(gi, gj)
    k = jax.random.fold_in(root, lo)
    k = jax.random.fold_in(k, hi)
    return jax.random.fold_in(k, r)


def quantize_tree(tree, scale: float):
    """Fixed-point int32 encoding: round(x/scale). The grid is shared by
    construction (scale is a config constant, not data-dependent)."""
    return jax.tree.map(
        lambda x: jnp.round(x / scale).astype(jnp.int32), tree)


def dequantize_tree(tree, scale: float):
    return jax.tree.map(lambda q: q.astype(jnp.float32) * scale, tree)


def mask_tree(key, tree):
    """Uniform int32 mask with the same structure as ``tree`` (full-range
    draws; addition wraps mod 2^32)."""
    leaves, treedef = jax.tree.flatten(tree)
    keys = jax.random.split(key, len(leaves))
    # 32 raw PRG bits per element, bitcast to int32: exactly uniform over
    # the mod-2^32 ring (randint's exclusive maxval would never emit
    # 2^31-1, leaving one ring element with probability 0).
    masks = [jax.lax.bitcast_convert_type(
                 jax.random.bits(k, l.shape, dtype=jnp.uint32), jnp.int32)
             for k, l in zip(keys, leaves)]
    return jax.tree.unflatten(treedef, masks)


def secagg_scale(clip_norm: float, bits: int) -> float:
    """The shared fixed-point grid step: ``clip_norm / 2^(bits-1)`` — a
    config constant, never data-dependent (module docstring step 2)."""
    return float(clip_norm) / float(2 ** (bits - 1))


def check_secagg_capacity(bits: int, m_clients: int) -> None:
    """Raise unless m clipped uploads fit int32 without wrapping the TRUE
    (post-cancellation) sum: a clipped delta can put a whole coordinate at
    clip_norm = 2^(bits-1) grid steps, so m clients can sum to
    m·2^(bits-1); past 2^31 that wraps and dequantizes with flipped sign,
    silently corrupting the round."""
    if not 2 <= bits <= 30:
        raise ValueError(f"bits={bits} outside [2, 30]")
    if m_clients >= 2 ** (31 - (bits - 1)):
        raise ValueError(
            f"bits={bits} overflows int32 at m={m_clients} sampled "
            f"clients: need m < 2^{31 - (bits - 1)}; lower bits or the "
            "cohort size")


def masked_upload(apply_fn, cfg, params, x, y, m, key, my_gid, pair_ids,
                  pair_valid, mask_root, r, clip: float, scale: float):
    """One client's view of the protocol: local_sgd → clip → quantize →
    add the pairwise masks vs every valid id in ``pair_ids``. Returns the
    masked int32 tree the server observes.

    ``pair_ids``/``pair_valid`` let a FIXED-width pair array serve any
    actual pair set (invalid entries contribute sign 0 — exactly nothing
    in int arithmetic), so the fleet engine's cohort step compiles once
    while streaming edges of any size. ONE implementation on purpose: the
    vmapped server round and the cohort-streamed fleet round
    (fl/fleet.py) are bitwise comparable only because both clients run
    exactly these ops."""
    new = local_sgd(apply_fn, params, x, y, m, epochs=cfg.epochs,
                    batch_size=cfg.batch_size, lr=cfg.lr, key=key)
    delta = clip_by_global_norm(pt.tree_sub(params, new), clip)
    q = quantize_tree(delta, scale)

    # Pairwise masks vs every OTHER client in the pair set: +mask when my
    # global id is the smaller of the pair, − otherwise — the two roles
    # derive the same key, so the sum cancels.
    def add_pair(q_acc, pair):
        other_gid, valid = pair
        k = _pair_key(mask_root, my_gid, other_gid, r)
        mask = mask_tree(k, q_acc)
        sign = jnp.where(valid,
                         jnp.where(other_gid == my_gid, 0,
                                   jnp.where(my_gid < other_gid, 1, -1)),
                         0).astype(jnp.int32)
        return jax.tree.map(lambda a, mm: a + sign * mm,
                            q_acc, mask), None

    q_masked, _ = jax.lax.scan(add_pair, q, (pair_ids, pair_valid))
    return q_masked


def finish_secagg_round(params, q_sum, scale: float, m_clients: int):
    """The server's unmasking tail, OUTSIDE jit on purpose: dequantize the
    cancelled ring sum with the single host constant ``scale/m`` (one
    multiply — two would leave the rounding to constant-folding luck) and
    apply the averaged delta. Shared by the vmapped server and the fleet
    engine so the tail's float roundings are literally the same ops — an
    in-jit tail is at the mercy of XLA fusing ``p − q·c`` into an FMA,
    which is a 1-ulp difference the bitwise parity bar would see."""
    return pt.tree_sub(params, dequantize_tree(q_sum, scale / m_clients))


class SecureAggFedAvgServer(_ServerBase):
    """FedAvg where the server only observes pairwise-masked fixed-point
    uploads (see module docstring). ``bits`` sets the quantization grid
    (clip_norm / 2^(bits-1) per step); the masked upload of any single
    client is information-theoretically uniform given the others' masks.

    The per-round aggregate equals plain uniform FedAvg up to quantization
    (≤ clip_norm·2^-(bits-1)/2 per coordinate per client) — asserted
    exactly, masked-vs-unmasked, in tests/test_secure_agg.py.
    """

    def __init__(self, *args, clip_norm: float = 5.0, bits: int = 20,
                 **kw):
        super().__init__(*args, algorithm="secagg-fedavg", **kw)
        check_secagg_capacity(bits, self.cfg.clients_per_round)
        self.clip_norm = float(clip_norm)
        self.bits = bits
        data, cfg, apply_fn = self.data, self.cfg, self.apply_fn
        scale = self._scale = secagg_scale(self.clip_norm, bits)
        clip = self.clip_norm

        @jax.jit
        def round_step(params, idx, keys, mask_root, r):
            xs, ys, ms = data.x[idx], data.y[idx], data.mask[idx]
            pair_valid = jnp.ones(idx.shape[0], bool)

            def client(x, y, m, key, my_gid):
                return masked_upload(apply_fn, cfg, params, x, y, m, key,
                                     my_gid, idx, pair_valid, mask_root, r,
                                     clip, scale)

            uploads = jax.vmap(client, in_axes=(0, 0, 0, 0, 0))(
                xs, ys, ms, keys, idx)
            # The server's view: only masked uploads. Wrapping int32 sum —
            # the pairwise masks cancel exactly mod 2^32.
            return jax.tree.map(lambda u: u.sum(0), uploads)

        self._round_step = round_step

    def _round(self, params, r):
        idx = self._sample(r)
        keys = jax.vmap(jax.random.key)(
            jnp.asarray(self.client_seeds(r, idx)))
        mask_root = jax.random.key(self.cfg.seed ^ _MASK_SALT)
        q_sum = self._round_step(params, jnp.asarray(idx), keys, mask_root,
                                 jnp.int32(r))
        return finish_secagg_round(params, q_sum, self._scale,
                                   len(idx))
