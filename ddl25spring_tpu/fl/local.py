"""Client-local training kernels — pure, vmappable over the client axis.

Capability target: the reference's client `update()` bodies —
`train_epoch` SGD over the client's DataLoader (lab/tutorial_1a/
hfl_complete.py:71-80, WeightClient.update :318-326) and the full-subset
gradient of `GradientClient` (:226-253). The reference's client loaders use
``shuffle=False`` (:148-149), so batch order is the subset order — preserved
here by reshaping the padded subset into fixed batches, which keeps every
shape static under jit/vmap.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any
# apply_fn(params, x, *, key=None) -> logits; dropout active iff key given
# (the functional analog of the reference's model.train()/model.eval(),
# hfl_complete.py:72,172).
ApplyFn = Callable[..., jnp.ndarray]


def masked_mean_loss(apply_fn: ApplyFn, params: PyTree, x: jnp.ndarray,
                     y: jnp.ndarray, mask: jnp.ndarray,
                     key=None) -> jnp.ndarray:
    """Cross-entropy averaged over real (unmasked) samples — identical to
    torch's mean CE over a batch when mask is all-ones."""
    logits = apply_fn(params, x) if key is None else apply_fn(params, x, key=key)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def full_batch_grad(apply_fn: ApplyFn, params: PyTree, x: jnp.ndarray,
                    y: jnp.ndarray, mask: jnp.ndarray,
                    key=None) -> Tuple[jnp.ndarray, PyTree]:
    """One gradient over the client's whole subset — FedSGD's client step
    (GradientClient.update, hfl_complete.py:241-253; trains in train mode,
    :271, so dropout is live when a key is threaded). Returns (loss, grads)."""
    return jax.value_and_grad(partial(masked_mean_loss, apply_fn))(
        params, x, y, mask, key)


def _batched(x: jnp.ndarray, y: jnp.ndarray, mask: jnp.ndarray, batch_size: int):
    """Reshape a padded subset [S, ...] into [n_batches, B, ...] (pad tail)."""
    s = x.shape[0]
    if batch_size <= 0 or batch_size > s:   # B=-1 ⇒ ∞ (one full batch)
        batch_size = s
    n_batches = -(-s // batch_size)
    pad = n_batches * batch_size - s
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
        y = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)])
        mask = jnp.concatenate([mask, jnp.zeros((pad,), mask.dtype)])
    return (x.reshape((n_batches, batch_size) + x.shape[1:]),
            y.reshape(n_batches, batch_size),
            mask.reshape(n_batches, batch_size))


def local_prox_sgd(apply_fn: ApplyFn, params: PyTree, x: jnp.ndarray,
                   y: jnp.ndarray, mask: jnp.ndarray, *, epochs: int,
                   batch_size: int, lr: float, mu: float,
                   key=None) -> PyTree:
    """FedProx local solver: E epochs of fixed-order minibatch SGD with the
    proximal term (μ/2)·‖w − w_global‖² added to every minibatch objective
    (Li et al., "Federated Optimization in Heterogeneous Networks"). The
    proximal gradient μ·(w − w_global) tethers heterogeneous clients to
    the global model, bounding client drift under non-IID data / variable
    local work. ``mu=0`` drops the term EXACTLY (μ·(w−w₀) multiplies out;
    pinned in tests/test_fedprox.py) — which is why ``local_sgd`` is this
    function at μ=0 rather than a second copy of the scan machinery."""
    w_global = params
    xb, yb, mb = _batched(x, y, mask, batch_size)

    def batch_step(carry, batch):
        p, step_idx = carry
        bx, by, bm = batch
        bkey = None if key is None else jax.random.fold_in(key, step_idx)
        grads = jax.grad(partial(masked_mean_loss, apply_fn))(p, bx, by, bm,
                                                              bkey)
        # Empty (all-padding) batches contribute zero gradient.
        nonempty = (bm.sum() > 0).astype(jnp.float32)
        # loss + (mu/2)||p - w_global||^2 ⇒ grad += mu*(p - w_global); the
        # term is added explicitly (cheaper than differentiating it). mu is
        # a static Python float, so the mu=0 branch is resolved at trace
        # time: the plain-SGD path (every non-prox server) carries no
        # proximal arithmetic and no live w_global operand — and the
        # "drops the term EXACTLY" guarantee is structural, not a
        # floating-point identity (0.0*(w-w0) could still flip signed
        # zeros).
        if mu == 0.0:
            p = jax.tree.map(lambda w, g: w - lr * nonempty * g, p, grads)
        else:
            p = jax.tree.map(
                lambda w, g, w0: w - lr * nonempty * (g + mu * (w - w0)),
                p, grads, w_global)
        return (p, step_idx + 1), None

    def epoch_step(carry, _):
        carry, _ = lax.scan(batch_step, carry, (xb, yb, mb))
        return carry, None

    (params, _), _ = lax.scan(epoch_step, (params, jnp.zeros((), jnp.int32)),
                              None, length=epochs)
    return params


def local_sgd(apply_fn: ApplyFn, params: PyTree, x: jnp.ndarray, y: jnp.ndarray,
              mask: jnp.ndarray, *, epochs: int, batch_size: int, lr: float,
              key=None) -> PyTree:
    """E epochs of plain SGD over fixed-order minibatches — WeightClient's
    local loop (train_epoch, hfl_complete.py:71-80; model.train() ⇒ dropout
    live per batch when a key is threaded). Pure: returns the new params;
    scan over (epochs × batches) keeps one compiled body. Each (epoch, batch)
    step folds its own dropout key from the client key. Implemented as the
    μ=0 case of ``local_prox_sgd`` (exact — the proximal gradient vanishes)."""
    return local_prox_sgd(apply_fn, params, x, y, mask, epochs=epochs,
                          batch_size=batch_size, lr=lr, mu=0.0, key=key)
