"""Fleet-scale federated learning: cohort-streaming rounds + two-tier
hierarchical aggregation (ISSUE 7 tentpole; ROADMAP item 4).

Every other FL server in this package vmaps ALL sampled clients
device-resident per round — fine for the course's 100 clients, impossible
for the north star's millions: a round's device memory is
O(clients · (subset + params)). This module replaces that with a
**cohort-streaming round engine**:

- a round samples its clients on the host, then streams them through a
  FIXED-width device cohort axis: one compiled ``cohort step`` per cohort
  width, vmapping W clients at a time;
- the running aggregate is carried across cohorts as a device pytree and
  folded SEQUENTIALLY (pt.tree_weighted_fold), so a round's device memory
  is O(cohort), not O(clients) — and, because a chunked left fold from a
  carried init is bitwise the one-shot fold, the streamed round is
  BITWISE-equal to the vmapped path at equal cohort content, at ANY
  cohort width (``vmapped_round_reference`` is that path; pinned in
  tests/test_fleet.py and checked end-to-end by
  experiments/fleet_smoke.py on a 100k-client round);
- the last cohort pads to width W with zero-weight duplicates — the fold
  selects around weight-0 rows exactly, so padding is invisible and the
  engine never retraces.

On top of the streaming engine sits a **two-tier hierarchical mode**
(``FleetConfig.edges = E > 1``): the sampled clients are partitioned over
E edge aggregators, each edge streams its own cohorts to an edge
aggregate, and a server tier reduces the E edge results. Defenses
(fl/defenses.py hooks), secure aggregation (fl/secure_agg.py pairwise
masking) and DP (fl/privacy.py clipping + noise) each apply *per tier*
via ``TierPolicy`` — an edge defends/masks/noises its own clients, the
server tier defends/noises the edge aggregates. ``edges=1`` with empty
policies IS the flat path (no server-tier reduction runs), so flat vs
hierarchical is a config axis, not a code fork. Weighting semantics:
every client carries its GLOBAL FedAvg weight only in the flat case; in
the hierarchical case edges normalize internally (c_i/S_e) and the
server weighs edges by their sample mass (S_e/S) — mathematically equal
to flat FedAvg, exact where the reduction order permits (E=1), a
documented ~1e-7 float-association tolerance otherwise.

Client data never lives device-resident in bulk: a ``source`` object
materializes cohorts on demand (``FederatedArraySource`` gathers from
host arrays; ``SyntheticFleetSource`` *generates* each client's subset
deterministically from its id, so 100k+ simulated clients cost O(cohort)
bytes ever). Client sampling and the per-(client, round) seed formula are
the same host-observable machinery as the vmapped servers (rng.py) — a
client's local randomness does not depend on which path, cohort, or tier
processed it.

Telemetry (schema v3): one ``fl_cohort`` event per cohort dispatch and
one ``fl_tier`` event per tier per round, with exact payload-byte
accounting (telemetry.comm.tree_bytes) of what crossed into each tier;
since schema v4 the same structure is also a SPAN TREE (telemetry/
trace.py) — an ``fl_round`` root with per-tier children and per-dispatch
``cohort`` grandchildren on the "fleet" trace, contexts passed explicitly
down the tier methods (pinned complete in tests/test_fleet.py) —
m·|Δ| client-uplink bytes into the edges, E·|Δ| edge-uplink bytes into
the server. Defense memory honesty: selection/aggregation defenses need
the tier's full input stack (Krum's O(n²) distance matrix is over all n
inputs), so a defended edge collects per-client FLAT deltas host-side —
O(m_e · P) host floats, still never O(clients · subset) device bytes; the
streamed stack is bitwise the vmapped one, so the selection matches the
vmapped reference exactly (the Krum-at-cohort-scale bar).
"""

from __future__ import annotations

import contextlib
import dataclasses
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import rng as rngmod
from ..config import FLConfig
from ..telemetry import introspect
from ..telemetry.comm import tree_bytes
from ..telemetry.trace import Tracer
from ..utils import pytree as pt
from .defenses import stack_flat, unstack_flat
from .federated_data import FederatedDataset
from .local import local_sgd
from .privacy import clip_by_global_norm, gaussian_noise_like
from .secure_agg import (_MASK_SALT, check_secagg_capacity, dequantize_tree,
                         masked_upload, secagg_scale)
from .servers import _ServerBase, _round_weights

PyTree = Any

# Dedicated RNG stream for per-tier DP noise: never derived from client
# keys (whose linear seed formula collides across rounds) and salted
# differently from DPFedAvgServer's stream so flat-vs-fleet comparisons
# at z=0 stay meaningful without aliasing at z>0.
_FLEET_NOISE_SALT = 0xF1EE7D0E


# ------------------------------------------------------------- data sources

class FederatedArraySource:
    """Streaming adapter over in-memory client arrays: cohorts are host
    gathers from the stacked [N, S, ...] layout (federated_data.py). The
    arrays live in HOST numpy — only the gathered cohort is shipped to the
    device — so this scales to whatever the host holds, and small parity
    tests can wrap the exact FederatedDataset a vmapped server uses."""

    def __init__(self, data: FederatedDataset):
        self._x = np.asarray(data.x)
        self._y = np.asarray(data.y)
        self._mask = np.asarray(data.mask)
        self._counts = np.asarray(data.sample_counts)

    @property
    def nr_clients(self) -> int:
        return self._x.shape[0]

    def counts(self, idx: np.ndarray) -> np.ndarray:
        return self._counts[idx]

    def cohort(self, idx: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        return self._x[idx], self._y[idx], self._mask[idx]


class SyntheticFleetSource:
    """Procedurally generated clients: each client's subset is a pure
    function of (seed, client id), materialized only when its cohort is
    gathered — the 'millions of simulated users' stand-in the fleet smoke
    streams 100k of at O(cohort) memory.

    The task is learnable on purpose (the smoke's accuracy is a liveness
    signal, not a benchmark): class prototypes are fixed by the seed,
    client i draws labels from a 2-class slice of the label space keyed by
    its id (a mild non-IID skew) and features = prototype + noise."""

    def __init__(self, nr_clients: int, *, samples_per_client: int = 8,
                 features: int = 16, classes: int = 10, seed: int = 0,
                 noise: float = 0.3):
        self.nr_clients = int(nr_clients)
        self.samples_per_client = int(samples_per_client)
        self.features = int(features)
        self.classes = int(classes)
        self.seed = int(seed)
        self.noise = float(noise)
        proto_rng = np.random.default_rng(np.random.SeedSequence([seed]))
        self.prototypes = proto_rng.normal(
            size=(classes, features)).astype(np.float32)

    def _client(self, cid: int) -> Tuple[np.ndarray, np.ndarray]:
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, int(cid)]))
        ys = (int(cid) + rng.integers(0, 2, self.samples_per_client)
              ) % self.classes
        xs = (self.prototypes[ys]
              + self.noise * rng.normal(
                  size=(self.samples_per_client, self.features))
              ).astype(np.float32)
        return xs, ys.astype(np.int32)

    def counts(self, idx: np.ndarray) -> np.ndarray:
        return np.full(len(idx), self.samples_per_client, np.int32)

    def cohort(self, idx: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        xs = np.empty((len(idx), self.samples_per_client, self.features),
                      np.float32)
        ys = np.empty((len(idx), self.samples_per_client), np.int32)
        for row, cid in enumerate(idx):
            xs[row], ys[row] = self._client(cid)
        mask = np.ones(ys.shape, np.float32)
        return xs, ys, mask

    def test_set(self, n: int, seed: int = 1
                 ) -> Tuple[np.ndarray, np.ndarray]:
        """A held-out sample of the same task for the accuracy probe."""
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, self.nr_clients + 1, seed]))
        ys = rng.integers(0, self.classes, n)
        xs = (self.prototypes[ys]
              + self.noise * rng.normal(size=(n, self.features))
              ).astype(np.float32)
        return xs, ys.astype(np.int32)


# ---------------------------------------------------------------- tier policy

@dataclass(frozen=True)
class TierPolicy:
    """What one aggregation tier does to its inputs before reducing them.

    - ``defense``: an fl.defenses hook ``(stacked_inputs, weights) -> agg``
      (selection_defense / coordinate_defense). Edge tier: over the edge's
      client deltas; server tier: over the edge aggregates. Requires the
      tier to materialize its input stack (see module docstring).
    - ``dp_clip`` / ``dp_noise_multiplier``: clip each tier input to the
      L2 ball, then add Gaussian noise σ = z·clip/n to the tier aggregate
      (DP-FedAvg per tier; uniform weighting required when z > 0, since
      sample-count weights make the sensitivity data-dependent; does NOT
      compose with a defense in the same tier — the σ calibration assumes
      the plain uniform mean's clip/n sensitivity).
    - ``secure_agg``: pairwise-masked fixed-point uploads into this tier
      (edge tier only — the masking is built into the client cohort step);
      a (clip_norm, bits) tuple. Implies uniform weighting and per-client
      clipping at clip_norm, matching SecureAggFedAvgServer bitwise at
      edges=1 (the int32 ring sum is order-free, so streaming is exact).
    """
    defense: Optional[Callable] = None
    dp_clip: Optional[float] = None
    dp_noise_multiplier: float = 0.0
    secure_agg: Optional[Tuple[float, int]] = None


@dataclass(frozen=True)
class FleetConfig:
    """Fleet engine knobs, on top of the protocol's FLConfig."""
    cohort_width: int = 64
    edges: int = 1
    weighting: str = "samples"          # "samples" | "uniform"
    edge: TierPolicy = field(default_factory=TierPolicy)
    server: TierPolicy = field(default_factory=TierPolicy)


# ------------------------------------------------------------ the fleet server

class FleetFedAvgServer(_ServerBase):
    """Δ-upload FedAvg over a cohort-streaming round engine with an
    optional edge→server hierarchy (module docstring). Same protocol
    surface as the vmapped servers: FLConfig hyperparameters, host
    sampling, per-(client, round) seeds, ``run()``/RunResult/telemetry —
    only the execution shape differs.

    >>> src = SyntheticFleetSource(100_000)
    >>> s = FleetFedAvgServer(params, apply_fn, src, xt, yt,
    ...                       FLConfig(nr_clients=100_000,
    ...                                client_fraction=1.0),
    ...                       FleetConfig(cohort_width=64, edges=4))
    >>> s.run(1)
    """

    def __init__(self, init_params, apply_fn, source, test_x, test_y,
                 cfg: FLConfig, fleet: FleetConfig = FleetConfig(), *,
                 telemetry=None):
        if fleet.cohort_width < 1:
            raise ValueError(f"cohort_width={fleet.cohort_width}")
        if not 1 <= fleet.edges <= cfg.clients_per_round:
            raise ValueError(
                f"edges={fleet.edges}: need 1..clients_per_round "
                f"({cfg.clients_per_round}) — an empty edge aggregates "
                "nothing")
        if fleet.weighting not in ("samples", "uniform"):
            raise ValueError(f"weighting={fleet.weighting!r}")
        if fleet.server.secure_agg is not None:
            raise ValueError("secure_agg is an edge-tier (client-upload) "
                             "mechanism; the server tier sees E edge "
                             "aggregates, not masked client vectors")
        for tier, name in ((fleet.edge, "edge"), (fleet.server, "server")):
            if tier.dp_noise_multiplier > 0 and tier.dp_clip is None:
                raise ValueError(f"{name}: dp_noise_multiplier > 0 needs "
                                 "a finite dp_clip")
            if tier.dp_noise_multiplier > 0 and tier.defense is not None:
                # σ = z·clip/n calibrates the noise to the UNIFORM mean's
                # sensitivity (clip/n). A selection defense averages only
                # k ≤ n survivors — sensitivity clip/k — so the same σ
                # would silently under-noise by n/k and the reported ε
                # would overstate the guarantee. Refuse rather than
                # miscalibrate; defense-aware calibration is future work.
                raise ValueError(f"{name}: dp_noise_multiplier > 0 does "
                                 "not compose with a defense — the σ = "
                                 "z·clip/n calibration assumes the plain "
                                 "uniform mean's sensitivity")
        needs_uniform = (fleet.edge.secure_agg is not None
                         or fleet.edge.dp_noise_multiplier > 0
                         or fleet.server.dp_noise_multiplier > 0)
        if needs_uniform and fleet.weighting != "uniform":
            raise ValueError("secure_agg / DP noise require "
                             "weighting='uniform' (sample-count weights "
                             "make the sensitivity data-dependent)")
        if fleet.edge.secure_agg is not None and (
                fleet.edge.defense is not None
                or fleet.edge.dp_clip is not None):
            raise ValueError("edge secure_agg already clips and hides "
                             "per-client vectors; it composes with "
                             "server-tier policies, not with edge "
                             "defense/dp_clip")
        # _ServerBase stores ``data`` opaquely (only the vmapped
        # subclasses' round steps gather from it), so the streaming source
        # rides in the same slot.
        super().__init__(init_params, apply_fn, source, test_x, test_y,
                         cfg, algorithm="fleet-fedavg", telemetry=telemetry)
        self.source = source
        self.fleet = fleet
        # Span tree per round (telemetry/trace.py): round → tier → cohort,
        # mirroring the fl_cohort/fl_tier flat events — the tree is the
        # causal structure, the flat events keep the exact byte accounting.
        # Contexts are passed down the tier methods explicitly; nothing
        # enters the compiled cohort steps.
        self._tracer = Tracer(telemetry.events) if telemetry else None
        self._manifest_extra = {"fleet": dataclasses.asdict(fleet)}
        # Per-client upload payload, exact from leaf shapes/dtypes: f32
        # deltas, or the same-width int32 fixed-point tree under secagg.
        self._client_payload_bytes = tree_bytes(init_params)
        if fleet.edge.secure_agg is not None:
            clip_norm, bits = fleet.edge.secure_agg
            # Capacity at the pair-set size = the largest edge.
            check_secagg_capacity(bits, self._edge_width(0))
            self._secagg_scale = secagg_scale(clip_norm, bits)
        self._collect = (fleet.edge.defense is not None)
        # [P] → params-shaped tree, for defense hooks' flat results.
        self._unflatten_vec = stack_flat(
            jax.tree.map(lambda p: p[None], init_params))[1]

        def delta_client(params, x, y, m, k, clip):
            """One client's Δ-upload: local_sgd → delta (→ clip) — the
            same ops as FedAvgGradServer's clients, so streamed deltas are
            bitwise the vmapped ones (vmap per-row numerics are width-
            independent; pinned in tests/test_fleet.py)."""
            new = local_sgd(apply_fn, params, x, y, m, epochs=cfg.epochs,
                            batch_size=cfg.batch_size, lr=cfg.lr, key=k)
            delta = pt.tree_sub(params, new)
            if clip is not None:
                delta = clip_by_global_norm(delta, clip)
            return delta

        # The three cohort-step flavors. Each takes params as an argument
        # (nothing dynamic in the closure), so one trace serves every
        # round of every tier.
        @jax.jit
        def stream_step(params, acc, xs, ys, ms, keys, w):
            """Plain streaming: vmap W local solves, fold the weighted
            deltas into the carried aggregate (weight-0 rows are exact
            no-ops — the padding contract)."""
            deltas = jax.vmap(
                lambda x, y, m, k: delta_client(params, x, y, m, k,
                                                fleet.edge.dp_clip)
            )(xs, ys, ms, keys)
            return pt.tree_weighted_fold(deltas, w, init=acc)

        @jax.jit
        def collect_step(params, xs, ys, ms, keys):
            """Defense mode: return the cohort's per-client FLAT deltas
            [W, P] for host-side stacking (the tier defense needs the full
            stack; memory note in the module docstring)."""
            deltas = jax.vmap(
                lambda x, y, m, k: delta_client(params, x, y, m, k,
                                                fleet.edge.dp_clip)
            )(xs, ys, ms, keys)
            flat, _ = stack_flat(deltas)
            return flat

        @jax.jit
        def secagg_step(params, xs, ys, ms, keys, gids, pair_ids,
                        pair_valid, mask_root, r, active):
            """Secure-agg mode: each ACTIVE client's pairwise-masked int32
            upload (fl/secure_agg.masked_upload — the same ops as the
            vmapped server's clients), summed over the cohort. Padded rows
            contribute exact zeros; the int32 ring sum is order-free, so
            the host's wrapped accumulation across cohorts equals the
            vmapped single sum bitwise."""
            clip_norm, bits = fleet.edge.secure_agg
            scale = secagg_scale(clip_norm, bits)

            def client(x, y, m, k, gid, act):
                q = masked_upload(apply_fn, cfg, params, x, y, m, k, gid,
                                  pair_ids, pair_valid, mask_root, r,
                                  clip_norm, scale)
                return jax.tree.map(lambda l: jnp.where(act, l, 0), q)

            ups = jax.vmap(client)(xs, ys, ms, keys, gids, active)
            return jax.tree.map(lambda u: u.sum(0), ups)

        # Compile/retrace observability (telemetry/introspect.py): each
        # cohort step's documented invariant is ONE compiled program —
        # ragged cohorts pad, raggedness is data, dropout pads survivors.
        # The watch emits ``compile`` events into the fleet's stream and
        # flags any growth past one cache entry as a retrace
        # (``_cache_size()==1`` stays pinned in tests through the watch's
        # attribute delegation).
        _events = telemetry.events if telemetry is not None else None
        self._stream_step = introspect.watch(
            stream_step, name="fleet/stream_step", max_caches=1,
            events=_events)
        self._collect_step = introspect.watch(
            collect_step, name="fleet/collect_step", max_caches=1,
            events=_events)
        self._secagg_step = introspect.watch(
            secagg_step, name="fleet/secagg_step", max_caches=1,
            events=_events)

    # ------------------------------------------------------------- plumbing
    def _edge_width(self, e: int) -> int:
        """Size of edge ``e``'s client partition (np.array_split shape)."""
        m = self.cfg.clients_per_round
        return len(np.array_split(np.arange(m), self.fleet.edges)[e])

    def _weighting_counts(self, counts: np.ndarray) -> np.ndarray:
        if self.fleet.weighting == "uniform":
            return np.ones(len(counts), np.int32)
        return counts

    def _noise_key(self, r: int, tier: int, e: int):
        k = jax.random.key(self.cfg.seed ^ _FLEET_NOISE_SALT)
        k = jax.random.fold_in(k, r)
        k = jax.random.fold_in(k, tier)
        return jax.random.fold_in(k, e)

    def _emit_cohort(self, r: int, tier: str, e: int, c: int,
                     n_real: int) -> None:
        if self.telemetry is not None:
            self.telemetry.events.fl_cohort(
                round=r, tier=tier, cohort=c, edge=e, clients=n_real,
                payload_bytes=n_real * self._client_payload_bytes)

    def _span(self, name: str, parent=None, **attrs):
        """A tracer span (or a no-op without telemetry). ``parent`` is the
        enclosing Span; the context yields this tier's Span to pass one
        level further down. Durations are HOST-side: a cohort span covers
        gather + dispatch (the device may still be folding under async
        dispatch), a tier span closes on the synced aggregate."""
        if self._tracer is None:
            return contextlib.nullcontext()
        return self._tracer.span(
            name, parent=parent.ctx if parent is not None else None,
            trace="fleet" if parent is None else None, **attrs)

    # ----------------------------------------------------------- edge tier
    def _stream_edge(self, params, r: int, e: int, eidx: np.ndarray,
                     weights: np.ndarray, parent=None) -> PyTree:
        """One edge's round in plain streaming mode: O(W) device clients
        at a time, sequential fold into the carried aggregate."""
        W = self.fleet.cohort_width
        acc = pt.tree_zeros_like(params)
        for c in range(-(-len(eidx) // W)):
            cidx = eidx[c * W:(c + 1) * W]
            cw = weights[c * W:(c + 1) * W]
            n_real = len(cidx)
            if n_real < W:     # pad: duplicate a real client at weight 0
                cidx = np.concatenate(
                    [cidx, np.full(W - n_real, cidx[0], cidx.dtype)])
                cw = np.concatenate(
                    [cw, np.zeros(W - n_real, np.float32)])
            with self._span("cohort", parent, cohort=c, clients=n_real):
                xs, ys, ms = self.source.cohort(cidx)
                keys = jax.vmap(jax.random.key)(
                    jnp.asarray(self.client_seeds(r, cidx)))
                acc = self._stream_step(params, acc, jnp.asarray(xs),
                                        jnp.asarray(ys), jnp.asarray(ms),
                                        keys, jnp.asarray(cw))
            self._emit_cohort(r, "edge", e, c, n_real)
        return acc

    def _collect_edge(self, params, r: int, e: int, eidx: np.ndarray,
                      parent=None) -> np.ndarray:
        """One edge's round in defense mode: stream cohorts, collect the
        per-client flat deltas [m_e, P] on the host."""
        W = self.fleet.cohort_width
        rows: List[np.ndarray] = []
        for c in range(-(-len(eidx) // W)):
            cidx = eidx[c * W:(c + 1) * W]
            n_real = len(cidx)
            if n_real < W:
                cidx = np.concatenate(
                    [cidx, np.full(W - n_real, cidx[0], cidx.dtype)])
            with self._span("cohort", parent, cohort=c, clients=n_real):
                xs, ys, ms = self.source.cohort(cidx)
                keys = jax.vmap(jax.random.key)(
                    jnp.asarray(self.client_seeds(r, cidx)))
                flat = self._collect_step(params, jnp.asarray(xs),
                                          jnp.asarray(ys), jnp.asarray(ms),
                                          keys)
                rows.append(np.asarray(flat)[:n_real])
            self._emit_cohort(r, "edge", e, c, n_real)
        return np.concatenate(rows, axis=0)

    def _secagg_edge(self, params, r: int, e: int, eidx: np.ndarray,
                     parent=None) -> PyTree:
        """One edge's round under pairwise masking: the host only ever
        observes masked int32 sums; wrapping np.int32 accumulation across
        cohorts is exact on the mod-2^32 ring."""
        W = self.fleet.cohort_width
        m_e = len(eidx)
        # Fixed-width pair set: every edge pads its id list to the widest
        # edge's length so the compiled step's scan length is static.
        pair_w = self._edge_width(0)
        pair_ids = np.concatenate(
            [eidx, np.zeros(pair_w - m_e, eidx.dtype)])
        pair_valid = np.arange(pair_w) < m_e
        mask_root = jax.random.key(self.cfg.seed ^ _MASK_SALT)
        total = None
        for c in range(-(-m_e // W)):
            cidx = eidx[c * W:(c + 1) * W]
            n_real = len(cidx)
            active = np.arange(W) < n_real
            if n_real < W:
                cidx = np.concatenate(
                    [cidx, np.full(W - n_real, cidx[0], cidx.dtype)])
            with self._span("cohort", parent, cohort=c, clients=n_real):
                xs, ys, ms = self.source.cohort(cidx)
                keys = jax.vmap(jax.random.key)(
                    jnp.asarray(self.client_seeds(r, cidx)))
                part = self._secagg_step(
                    params, jnp.asarray(xs), jnp.asarray(ys),
                    jnp.asarray(ms), keys, jnp.asarray(cidx),
                    jnp.asarray(pair_ids), jnp.asarray(pair_valid),
                    mask_root, jnp.int32(r), jnp.asarray(active))
                part = jax.tree.map(np.asarray, part)
                total = part if total is None else jax.tree.map(
                    np.add, total, part)          # int32: wraps mod 2^32
            self._emit_cohort(r, "edge", e, c, n_real)
        # Dequantize the cancelled sum and average uniformly — the same
        # single multiply by the host constant scale/m as
        # SecureAggFedAvgServer's server side, so edges=1 matches it
        # bitwise (the int32 ring sum already does, order-free).
        return dequantize_tree(jax.tree.map(jnp.asarray, total),
                               self._secagg_scale / m_e)

    def _edge_round(self, params, r: int, e: int, eidx: np.ndarray,
                    counts: np.ndarray, parent=None) -> PyTree:
        """One edge aggregate: stream, then apply the edge TierPolicy."""
        pol = self.fleet.edge
        with self._span("tier", parent, tier="edge", edge=e,
                        clients=len(eidx)) as tspan:
            if pol.secure_agg is not None:
                return self._secagg_edge(params, r, e, eidx, tspan)
            w = np.asarray(_round_weights(
                jnp.asarray(self._weighting_counts(counts))))
            if self._collect:
                flat = self._collect_edge(params, r, e, eidx, tspan)
                flat_hook = getattr(pol.defense, "flat_hook", None)
                if flat_hook is not None:
                    # The adapter's flat core consumes the collected
                    # [m_e, P] stack directly — no stacked-pytree round
                    # trip. Same ops as the pytree entry point, so the
                    # bitwise parity with FedAvgGradServer(defense=...)
                    # is unchanged.
                    agg = self._unflatten_vec(
                        flat_hook(jnp.asarray(flat), jnp.asarray(w)))
                else:
                    stacked = unstack_flat(jnp.asarray(flat), params)
                    agg = pol.defense(stacked, jnp.asarray(w))
            else:
                agg = self._stream_edge(params, r, e, eidx, w, tspan)
            if pol.dp_noise_multiplier > 0:
                sigma = pol.dp_noise_multiplier * pol.dp_clip / len(eidx)
                agg = pt.tree_add(agg, gaussian_noise_like(
                    self._noise_key(r, 0, e), agg, sigma))
            return agg

    # ---------------------------------------------------------- server tier
    def _server_round(self, r: int, edge_aggs: List[PyTree],
                      edge_counts: np.ndarray, parent=None) -> PyTree:
        """Reduce the E edge aggregates per the server TierPolicy. Skipped
        entirely in the flat case (E=1, empty policy) so the flat path is
        bitwise the single edge's fold — and emits no server-tier span,
        because no server tier ran."""
        pol = self.fleet.server
        if (len(edge_aggs) == 1 and pol.defense is None
                and pol.dp_clip is None and pol.dp_noise_multiplier == 0):
            return edge_aggs[0]
        with self._span("tier", parent, tier="server",
                        inputs=len(edge_aggs)):
            stacked = pt.tree_stack(edge_aggs)
            if pol.dp_clip is not None:
                stacked = jax.vmap(
                    lambda t: clip_by_global_norm(t, pol.dp_clip))(stacked)
            ew = _round_weights(jnp.asarray(
                self._weighting_counts(edge_counts)))
            if pol.defense is not None:
                agg = pol.defense(stacked, ew)
            else:
                agg = pt.tree_weighted_fold(stacked, ew)
            if pol.dp_noise_multiplier > 0:
                sigma = (pol.dp_noise_multiplier * pol.dp_clip
                         / len(edge_aggs))
                agg = pt.tree_add(agg, gaussian_noise_like(
                    self._noise_key(r, 1, 0), agg, sigma))
            return agg

    # ------------------------------------------------------------ the round
    def _round(self, params, r):
        idx = self._sample(r)
        m = len(idx)
        counts = np.asarray(self.source.counts(idx))
        parts = np.array_split(np.arange(m), self.fleet.edges)
        edge_aggs: List[PyTree] = []
        edge_counts = np.empty(len(parts), np.int64)
        with self._span("fl_round", round=r, clients=m,
                        edges=len(parts)) as rspan:
            for e, pos in enumerate(parts):
                edge_aggs.append(
                    self._edge_round(params, r, e, idx[pos], counts[pos],
                                     rspan))
                edge_counts[e] = (int(counts[pos].sum())
                                  if self.fleet.weighting == "samples"
                                  else len(pos))
            tel = self.telemetry
            if tel is not None:
                tel.events.fl_tier(
                    round=r, tier="edge", edges=len(parts), clients=m,
                    payload_bytes=m * self._client_payload_bytes,
                    wire=("int32-masked"
                          if self.fleet.edge.secure_agg is not None
                          else "float32"))
                tel.events.fl_tier(
                    round=r, tier="server", inputs=len(edge_aggs),
                    payload_bytes=(len(edge_aggs)
                                   * self._client_payload_bytes))
            agg = self._server_round(r, edge_aggs, edge_counts, rspan)
            return pt.tree_sub(params, agg)


# ------------------------------------------------------------ the reference

def vmapped_round_reference(params, apply_fn, source, idx, cfg: FLConfig,
                            r: int, *, weighting: str = "samples",
                            clip: Optional[float] = None) -> PyTree:
    """The O(clients)-device-memory path the streamed engine must match
    bitwise at equal cohort content: every sampled client vmapped resident
    at once, aggregated with the same sequential fold. Used by
    tests/test_fleet.py and the fleet smoke's control slice — it is the
    executable statement of 'what the round means', with the fleet engine
    as the scalable implementation of it."""
    idx = np.asarray(idx)
    xs, ys, ms = source.cohort(idx)
    m = cfg.clients_per_round
    seeds = [rngmod.per_client_seed(cfg.seed, r, int(i), m) for i in idx]
    keys = jax.vmap(jax.random.key)(jnp.asarray(seeds))

    def client(x, y, mk, k):
        new = local_sgd(apply_fn, params, x, y, mk, epochs=cfg.epochs,
                        batch_size=cfg.batch_size, lr=cfg.lr, key=k)
        delta = pt.tree_sub(params, new)
        if clip is not None:
            delta = clip_by_global_norm(delta, clip)
        return delta

    deltas = jax.vmap(client)(jnp.asarray(xs), jnp.asarray(ys),
                              jnp.asarray(ms), keys)
    counts = (np.ones(len(idx), np.int32) if weighting == "uniform"
              else np.asarray(source.counts(idx)))
    w = _round_weights(jnp.asarray(counts))
    return pt.tree_sub(params, pt.tree_weighted_fold(deltas, w))
