// Native host-side token data pipeline for ddl25spring_tpu.
//
// Role: the reference's data path leans on native code inside its
// dependencies (sentencepiece C++ behind simplellm's SPTokenizer, libtorch
// dataloader machinery — SURVEY.md §2.12). This is the framework's own
// native equivalent: SentencePiece-compatible encoding (BPE greedy-merge and
// unigram Viterbi, mirroring ddl25spring_tpu/tokenizers/spm.py semantics
// including tie-breaking), document sourcing (corpus file or synthetic
// TinyStories-style grammar), fixed-shape sequence packing with the
// reference's skip-offset semantics (intro_DP_GA.py:29), and a threaded
// prefetch ring so tokenization overlaps TPU compute.
//
// Exposed via a C ABI consumed by ctypes (ddl25spring_tpu/data/native.py).
// Build: make -C native   (g++ -O2 -shared -fPIC, pthreads only).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fstream>
#include <mutex>
#include <queue>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kTypeNormal = 1, kTypeUnknown = 2, kTypeControl = 3,
              kTypeByte = 6;

// ----------------------------------------------------------------- vocab

struct Vocab {
  std::unordered_map<std::string, int32_t> piece_to_id;
  std::unordered_map<uint8_t, int32_t> byte_to_id;
  std::vector<float> scores;
  int32_t unk_id = 0, bos_id = -1, eos_id = -1;
  bool is_bpe = false;
  int max_piece_cp = 1;  // longest NORMAL piece, in code points
  float unk_penalty = -20.0f;
};

int codepoint_len(const std::string& s) {
  int n = 0;
  for (unsigned char c : s)
    if ((c & 0xC0) != 0x80) n++;
  return n;
}

Vocab* build_vocab(const uint8_t* pieces, const int64_t* offsets,
                   const float* scores, const int32_t* types,
                   int32_t n_pieces, int32_t is_bpe) {
  auto* v = new Vocab();
  v->is_bpe = is_bpe != 0;
  v->scores.assign(scores, scores + n_pieces);
  float min_score = 0.0f;
  for (int32_t i = 0; i < n_pieces; i++) {
    std::string piece(reinterpret_cast<const char*>(pieces + offsets[i]),
                      offsets[i + 1] - offsets[i]);
    int32_t t = types[i];
    if (t == kTypeByte) {
      // pieces look like "<0x0A>"
      v->byte_to_id[(uint8_t)std::stoi(piece.substr(3, 2), nullptr, 16)] = i;
    } else if (t == kTypeUnknown) {
      v->unk_id = i;
    } else if (t == kTypeControl) {
      if (piece == "<s>") v->bos_id = i;
      else if (piece == "</s>") v->eos_id = i;
    } else {
      v->piece_to_id.emplace(std::move(piece), i);
    }
    if (t == kTypeNormal) {
      std::string p(reinterpret_cast<const char*>(pieces + offsets[i]),
                    offsets[i + 1] - offsets[i]);
      v->max_piece_cp = std::max(v->max_piece_cp, codepoint_len(p));
    }
    min_score = std::min(min_score, scores[i]);
  }
  v->unk_penalty = n_pieces ? min_score - 10.0f : -20.0f;
  return v;
}

// ----------------------------------------------------------- encoding

// Split a UTF-8 string into byte offsets of each code point (plus end).
std::vector<int> cp_offsets(const std::string& s) {
  std::vector<int> off;
  for (int i = 0; i < (int)s.size(); i++)
    if (((unsigned char)s[i] & 0xC0) != 0x80) off.push_back(i);
  off.push_back((int)s.size());
  return off;
}

void byte_fallback(const Vocab& v, const std::string& piece,
                   std::vector<int32_t>* out) {
  bool all = true;
  for (unsigned char b : piece)
    if (!v.byte_to_id.count(b)) { all = false; break; }
  if (all)
    for (unsigned char b : piece) out->push_back(v.byte_to_id.at(b));
  else
    out->push_back(v.unk_id);
}

// SentencePiece-BPE greedy merge, mirroring spm.py _encode_bpe exactly:
// repeatedly merge the adjacent pair whose concatenation has the highest
// score, ties broken by smallest left index (Python's (-score, i, j) heap).
void encode_bpe(const Vocab& v, const std::string& s,
                std::vector<int32_t>* out) {
  auto off = cp_offsets(s);
  int n = (int)off.size() - 1;
  if (n == 0) return;
  // parts are contiguous byte ranges [start, end) over s.
  std::vector<int> pstart(n), pend(n), nxt(n), prv(n);
  std::vector<char> alive(n, 1);
  for (int i = 0; i < n; i++) {
    pstart[i] = off[i];
    pend[i] = off[i + 1];
    nxt[i] = i + 1 < n ? i + 1 : -1;
    prv[i] = i - 1;
  }
  struct Cand { float neg_score; int i, j; };
  auto cmp = [](const Cand& a, const Cand& b) {
    if (a.neg_score != b.neg_score) return a.neg_score > b.neg_score;
    if (a.i != b.i) return a.i > b.i;
    return a.j > b.j;  // min-heap on (neg_score, i, j), like Python's heapq
  };
  std::priority_queue<Cand, std::vector<Cand>, decltype(cmp)> heap(cmp);
  auto push = [&](int i) {
    int j = nxt[i];
    if (j == -1) return;
    auto it = v.piece_to_id.find(s.substr(pstart[i], pend[j] - pstart[i]));
    if (it != v.piece_to_id.end())
      heap.push({-v.scores[it->second], i, j});
  };
  for (int i = 0; i < n - 1; i++) push(i);
  while (!heap.empty()) {
    Cand c = heap.top();
    heap.pop();
    int i = c.i, j = c.j;
    if (!alive[i] || !alive[j] || nxt[i] != j) continue;  // stale
    pend[i] = pend[j];
    alive[j] = 0;
    nxt[i] = nxt[j];
    if (nxt[j] != -1) prv[nxt[j]] = i;
    if (prv[i] != -1) push(prv[i]);
    push(i);
  }
  for (int i = 0; i != -1; i = nxt[i]) {
    if (!alive[i]) continue;
    std::string part = s.substr(pstart[i], pend[i] - pstart[i]);
    auto it = v.piece_to_id.find(part);
    if (it != v.piece_to_id.end()) out->push_back(it->second);
    else byte_fallback(v, part, out);
  }
}

// Unigram Viterbi, mirroring spm.py _encode_unigram (incl. the reversed
// byte order quirk of its backtrack fallback).
void encode_unigram(const Vocab& v, const std::string& s,
                    std::vector<int32_t>* out) {
  auto off = cp_offsets(s);
  int n = (int)off.size() - 1;
  constexpr double NEG = -1e18;
  std::vector<double> best(n + 1, NEG);
  std::vector<int> back_start(n + 1, -2);
  std::vector<int32_t> back_id(n + 1, -1);
  best[0] = 0.0;
  for (int end = 1; end <= n; end++) {
    int lo = std::max(0, end - v.max_piece_cp);
    for (int start = lo; start < end; start++) {
      if (best[start] <= NEG / 2) continue;
      auto it = v.piece_to_id.find(
          s.substr(off[start], off[end] - off[start]));
      if (it == v.piece_to_id.end()) continue;
      double sc = best[start] + v.scores[it->second];
      if (sc > best[end]) {
        best[end] = sc;
        back_start[end] = start;
        back_id[end] = it->second;
      }
    }
    if (back_start[end] == -2 && best[end - 1] > NEG / 2) {
      best[end] = best[end - 1] + v.unk_penalty;
      back_start[end] = end - 1;
      back_id[end] = -1;
    }
  }
  std::vector<int32_t> rev;
  int pos = n;
  while (pos > 0) {
    int start = back_start[pos];
    int32_t pid = back_id[pos];
    if (pid >= 0) {
      rev.push_back(pid);
    } else {
      std::string ch = s.substr(off[start], off[pos] - off[start]);
      bool all = true;
      for (unsigned char b : ch)
        if (!v.byte_to_id.count(b)) { all = false; break; }
      if (all) {
        // spm.py extends with reversed(bytes) while building the reversed
        // list — net effect: bytes come out in forward order after the
        // final reverse; match it.
        for (auto it = ch.rbegin(); it != ch.rend(); ++it)
          rev.push_back(v.byte_to_id.at((unsigned char)*it));
      } else {
        rev.push_back(v.unk_id);
      }
    }
    pos = start;
  }
  out->insert(out->end(), rev.rbegin(), rev.rend());
}

const char kWS[] = "\xE2\x96\x81";  // "▁" U+2581

void encode(const Vocab& v, const std::string& text, bool add_bos,
            std::vector<int32_t>* out) {
  std::string s = kWS;
  for (char c : text) {
    if (c == ' ') s += kWS;
    else s += c;
  }
  if (add_bos && v.bos_id >= 0) out->push_back(v.bos_id);
  if (v.is_bpe) encode_bpe(v, s, out);
  else encode_unigram(v, s, out);
}

// ------------------------------------------------------ document sources

const char* kNames[] = {"Lily", "Tom", "Mia", "Ben", "Sara", "Max", "Anna",
                        "Leo", "Ella", "Sam", "Lucy", "Tim", "Amy", "Jack",
                        "Rosa", "Finn"};
const char* kAnimals[] = {"cat", "dog", "bird", "bunny", "frog", "duck",
                          "fox", "bear", "mouse", "owl"};
const char* kObjects[] = {"ball", "kite", "book", "toy", "hat", "cake",
                          "flower", "boat", "drum", "star"};
const char* kPlaces[] = {"park", "garden", "forest", "house", "beach",
                         "hill", "farm", "pond", "yard", "school"};
const char* kAdjs[] = {"happy", "little", "big", "red", "shiny", "soft",
                       "brave", "silly", "kind", "tiny"};
const char* kVerbs[] = {"played", "jumped", "ran", "laughed", "sang",
                        "danced", "walked", "smiled", "looked", "hopped"};

struct DocSource {
  std::vector<std::string> corpus;  // empty -> synthetic
  size_t next_line = 0;
  std::mt19937_64 rng;

  explicit DocSource(const char* path, uint64_t seed) : rng(seed) {
    if (path && *path) {
      std::ifstream f(path);
      std::string line;
      while (std::getline(f, line)) {
        while (!line.empty() && (line.back() == '\r' || line.back() == '\n' ||
                                 line.back() == ' '))
          line.pop_back();
        if (!line.empty()) corpus.push_back(line);
      }
    }
  }

  template <size_t N>
  const char* pick(const char* (&arr)[N]) {
    return arr[rng() % N];
  }

  std::string synthetic() {
    // Same grammar as data/tokens.py synthetic_story (its numpy RNG stream
    // differs — native runs are self-consistent, not cross-runtime
    // reproducible with the Python generator).
    std::string name = pick(kNames), name2 = pick(kNames);
    std::string animal = pick(kAnimals), animal2 = pick(kAnimals);
    std::string obj = pick(kObjects), place = pick(kPlaces);
    std::string adj = pick(kAdjs), adj2 = pick(kAdjs);
    std::string verb = pick(kVerbs), verb2 = pick(kVerbs);
    switch (rng() % 4) {
      case 0:
        return "Once upon a time there was a " + adj + " " + animal +
               " named " + name + ". " + name + " loved to play with a " +
               obj + " in the " + place + ". One day " + name + " " + verb +
               " all day long. The " + animal + " was very " + adj2 +
               ". At the end of the day " + name + " went home and slept.";
      case 1:
        return name + " and " + name2 + " went to the " + place +
               ". They found a " + adj + " " + obj + ". " + name +
               " said, I want to share this " + obj + " with you. " + name2 +
               " " + verb + " with joy. They were " + adj2 +
               " friends forever.";
      case 2:
        return "One day a " + adj + " " + animal + " found a " + obj +
               " near the " + place + ". The " + animal + " " + verb +
               " and " + verb2 + ". A " + adj2 + " " + animal2 +
               " came to help. Together they played until the sun went down.";
      default:
        return "Little " + name + " had a " + adj + " " + obj +
               ". Every morning " + name + " took the " + obj + " to the " +
               place + ". One day the " + obj + " was lost. " + name + " " +
               verb + " everywhere. A " + adj2 + " " + animal +
               " found it and " + name + " was happy again.";
    }
  }

  std::string next() {
    if (corpus.empty()) return synthetic();
    std::string d = corpus[next_line];
    next_line = (next_line + 1) % corpus.size();
    return d;
  }
};

// ------------------------------------------------------ prefetch pipeline

struct TokenStream {
  Vocab* vocab;
  DocSource docs;
  int32_t batch, seq_len, prefetch;
  int64_t skip;
  std::vector<int32_t> buf;       // token accumulator
  std::deque<std::vector<int32_t>> ready;  // each [batch*seq_len]
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  std::thread producer;
  std::atomic<bool> stop{false};
  std::atomic<int64_t> produced{0};
  bool started = false;

  TokenStream(Vocab* v, const char* path, uint64_t seed, int32_t batch_,
              int32_t seq_len_, int64_t skip_, int32_t prefetch_)
      : vocab(v), docs(path, seed), batch(batch_), seq_len(seq_len_),
        prefetch(std::max(1, prefetch_)), skip(skip_) {}

  ~TokenStream() {
    stop.store(true);
    cv_space.notify_all();
    if (producer.joinable()) producer.join();
    delete vocab;
  }

  void fill_seq(int32_t* out) {
    while ((int64_t)buf.size() < seq_len) {
      std::vector<int32_t> ids;
      encode(*vocab, docs.next(), /*add_bos=*/true, &ids);
      if (vocab->eos_id >= 0) ids.push_back(vocab->eos_id);
      buf.insert(buf.end(), ids.begin(), ids.end());
    }
    std::copy(buf.begin(), buf.begin() + seq_len, out);
    buf.erase(buf.begin(), buf.begin() + seq_len);
  }

  void run() {
    std::vector<int32_t> tmp(seq_len);
    for (int64_t i = 0; i < skip && !stop.load(); i++) fill_seq(tmp.data());
    while (!stop.load()) {
      std::vector<int32_t> out((size_t)batch * seq_len);
      for (int32_t b = 0; b < batch; b++) fill_seq(out.data() + (size_t)b * seq_len);
      std::unique_lock<std::mutex> lk(mu);
      cv_space.wait(lk, [&] {
        return stop.load() || (int32_t)ready.size() < prefetch;
      });
      if (stop.load()) return;
      ready.push_back(std::move(out));
      produced.fetch_add(1);
      cv_ready.notify_one();
    }
  }

  void next(int32_t* out) {
    {
      std::lock_guard<std::mutex> lk(mu);
      if (!started) {
        started = true;
        producer = std::thread([this] { run(); });
      }
    }
    std::unique_lock<std::mutex> lk(mu);
    cv_ready.wait(lk, [&] { return !ready.empty(); });
    std::vector<int32_t> b = std::move(ready.front());
    ready.pop_front();
    cv_space.notify_one();
    lk.unlock();
    std::memcpy(out, b.data(), b.size() * sizeof(int32_t));
  }
};

}  // namespace

// ----------------------------------------------------------------- C ABI

extern "C" {

void* ts_create(const uint8_t* pieces, const int64_t* offsets,
                const float* scores, const int32_t* types, int32_t n_pieces,
                int32_t is_bpe, const char* corpus_path, uint64_t seed,
                int32_t batch, int32_t seq_len, int64_t skip,
                int32_t prefetch) {
  Vocab* v = build_vocab(pieces, offsets, scores, types, n_pieces, is_bpe);
  return new TokenStream(v, corpus_path, seed, batch, seq_len, skip, prefetch);
}

void ts_next(void* h, int32_t* out) {
  static_cast<TokenStream*>(h)->next(out);
}

// Encode `text` (UTF-8) directly; returns the id count (caller provides
// capacity; overflow returns the required size without writing past cap).
int64_t ts_encode(void* h, const char* text, int64_t text_len,
                  int32_t add_bos, int32_t* out, int64_t cap) {
  auto* ts = static_cast<TokenStream*>(h);
  std::vector<int32_t> ids;
  encode(*ts->vocab, std::string(text, (size_t)text_len), add_bos != 0, &ids);
  int64_t n = (int64_t)ids.size();
  if (n <= cap) std::memcpy(out, ids.data(), n * sizeof(int32_t));
  return n;
}

int64_t ts_batches_produced(void* h) {
  return static_cast<TokenStream*>(h)->produced.load();
}

void ts_destroy(void* h) { delete static_cast<TokenStream*>(h); }

}  // extern "C"
