"""Shared example plumbing: device/mesh selection for one-command runs."""

from __future__ import annotations

import argparse
import os
import sys


def base_parser(**defaults) -> argparse.ArgumentParser:
    """Common flags. --iters/--batch are only added for the examples that
    consume them (those passing defaults), so FL-style examples don't accept
    flags they would silently ignore."""
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="force N virtual CPU devices (the reference's "
                         "multi-node-without-a-cluster mode, homework_1_b1.sh)")
    if "iters" in defaults:
        ap.add_argument("--iters", type=int, default=defaults["iters"])
    if "batch" in defaults:
        ap.add_argument("--batch", type=int, default=defaults["batch"])
    return ap


def setup_devices(args) -> None:
    """Must run before any jax device use."""
    if args.cpu_devices:
        import re
        flags = os.environ.get("XLA_FLAGS", "")
        # The explicit flag overrides any stale count already in XLA_FLAGS.
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       flags)
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.cpu_devices}").strip()
        import jax
        jax.config.update("jax_platforms", "cpu")


def repo_on_path() -> None:
    here = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, os.path.dirname(here))
