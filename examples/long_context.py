"""Sequence-parallel (ring attention) training — long context over a mesh.

No reference counterpart: the course stack fixes seq_len=256 on one device
(SURVEY.md §5.7). This is the framework's first-class long-context mode:
the sequence is a mesh axis, K/V shards rotate around the ICI ring via
lax.ppermute with online-softmax accumulation (parallel/sp.py), so context
scales linearly with ring size.

    python examples/long_context.py --cpu-devices 4 --seq 1024 --ring 4
"""

from _common import base_parser, repo_on_path, setup_devices

repo_on_path()


def main():
    ap = base_parser(iters=50, batch=2)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--ring", type=int, default=0,
                    help="sequence-axis size (default: all devices)")
    args = ap.parse_args()
    setup_devices(args)
    import jax
    import optax

    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.data.tokens import TokenStream
    from ddl25spring_tpu.parallel import make_mesh, sp
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.tokenizers import load_tokenizer

    n_dev = len(jax.devices())
    ring = args.ring or n_dev
    assert 0 < ring <= n_dev and n_dev % ring == 0, \
        f"--ring {ring} must divide device count {n_dev}"
    assert args.seq % ring == 0, \
        f"--seq {args.seq} must divide over the ring of {ring}"
    data = n_dev // ring
    tok = load_tokenizer()
    cfg = LlamaConfig(dtype="bfloat16", vocab_size=tok.vocab_size,
                      ctx_size=args.seq)
    mesh = make_mesh({"data": data, "seq": ring})
    opt = optax.adam(8e-4)
    state = sp.init_state(mesh, llama.init_llama(jax.random.key(0), cfg), opt)
    step = sp.make_sp_train_step(cfg, opt, mesh)
    stream = TokenStream(tok, data * args.batch, args.seq)
    it = iter(stream)
    for i in range(args.iters):
        state, loss = step(state, sp.shard_batch(mesh, next(it)))
        if i % max(1, args.iters // 10) == 0:
            print(f"iter {i}: loss {float(loss):.4f}")
    print(f"final loss {float(loss):.4f} "
          f"(seq {args.seq} over ring of {ring}, data={data})")


if __name__ == "__main__":
    main()
