"""Horizontal FL on MNIST — FedSGD / FedAvg / centralized, one command.

Reference: lab/tutorial_1a/hfl_complete.py `__main__` (and the homework-1
defaults N=100, C=0.1, E=1, B=100, lr=0.01, 10 rounds, IID, seed 10 —
lab/homework-1.ipynb cell 5). Clients are a vmapped axis of one jitted
round program, not sequential objects; prints the RunResult dataframe.

    python examples/hfl.py --algo fedavg --rounds 10
"""

from _common import base_parser, repo_on_path, setup_devices

repo_on_path()


def main():
    ap = base_parser()
    ap.add_argument("--algo", choices=("fedsgd", "fedsgd-w", "fedavg",
                                       "centralized"), default="fedavg")
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--clients", type=int, default=100)
    ap.add_argument("--fraction", type=float, default=0.1)
    ap.add_argument("--noniid", action="store_true")
    ap.add_argument("--n-train", type=int, default=60000)
    ap.add_argument("--n-test", type=int, default=10000)
    args = ap.parse_args()
    setup_devices(args)
    import jax

    from ddl25spring_tpu.config import FLConfig
    from ddl25spring_tpu.fl import (CentralizedServer, FedAvgServer,
                                    FedSgdGradientServer, FedSgdWeightServer)
    from ddl25spring_tpu.models import mnist_cnn
    from experiments import common

    cfg = FLConfig(nr_clients=args.clients, client_fraction=args.fraction,
                   rounds=args.rounds, iid=not args.noniid)
    if args.algo == "centralized":
        x, y, xt, yt = common.mnist_arrays(args.n_train, args.n_test)
        server = CentralizedServer(mnist_cnn.init(jax.random.key(0)),
                                   mnist_cnn.apply, x, y, xt, yt, cfg)
    else:
        cls = {"fedsgd": FedSgdGradientServer, "fedsgd-w": FedSgdWeightServer,
               "fedavg": FedAvgServer}[args.algo]
        params, data, xt, yt = common.mnist_fl_setup(
            cfg, n_train=args.n_train, n_test=args.n_test)
        server = cls(params, mnist_cnn.apply, data, xt, yt, cfg)
    result = server.run(cfg.rounds)
    print(result.as_df().to_string(index=False))


if __name__ == "__main__":
    main()
