"""DP gradient-aggregation training — the reference's intro_DP_GA collapsed
into one SPMD program.

Reference: lab/tutorial_1b/DP/gradient_aggr/intro_DP_GA.py (+ run.sh spawning
3 gloo ranks): per-iter flatten → all_reduce(SUM) → unflatten → ÷world_size.
Here: ``lax.pmean(grads, "data")`` inside a jitted shard_map step over every
available device; the stream offset per shard reproduces skip=rank*5000.

    python examples/dp_gradient.py --cpu-devices 3 --iters 200
"""

from _common import base_parser, repo_on_path, setup_devices

repo_on_path()


def main():
    args = base_parser(iters=200, batch=3).parse_args()
    setup_devices(args)
    import jax

    from ddl25spring_tpu.config import LlamaConfig, TrainConfig
    from ddl25spring_tpu.parallel import make_mesh
    from ddl25spring_tpu.train.llm import train_llm_dp

    n = len(jax.devices())
    report = train_llm_dp(
        LlamaConfig(dtype="bfloat16"),
        TrainConfig(iters=args.iters, batch_size=args.batch, data=n),
        mesh=make_mesh({"data": n}),
        aggregation="gradient",
        log_every=max(1, args.iters // 20))
    print(f"final loss {report.losses[-1]:.4f}  "
          f"{report.tokens_per_sec:.0f} tok/s over {n} device(s)")


if __name__ == "__main__":
    main()
