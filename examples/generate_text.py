"""Train-then-sample: tiny-Llama on the token stream, then KV-cache decoding.

No reference counterpart: the course stack only trains (SURVEY.md §2.9
lists no generation surface in the simplellm API it uses). This is the
framework's inference mode — one jitted program per phase: the DP train
step (fused projections + fused Adam), then models.generate's prefill +
single-token decode scan with in-place cache writes.

    python examples/generate_text.py --iters 200 --new-tokens 64
    python examples/generate_text.py --temperature 0.8 --top-k 40
"""

from _common import base_parser, repo_on_path, setup_devices

repo_on_path()


def main():
    ap = base_parser(iters=200, batch=8)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="0 = greedy; >0 samples")
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--prompt", type=str, default="Once upon a time")
    args = ap.parse_args()
    setup_devices(args)
    import jax
    import jax.numpy as jnp

    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.data.tokens import TokenStream
    from ddl25spring_tpu.models import generate, llama
    from ddl25spring_tpu.ops import fused_adam
    from ddl25spring_tpu.parallel import dp, make_mesh
    from ddl25spring_tpu.tokenizers import load_tokenizer

    tok = load_tokenizer()
    cfg = LlamaConfig(vocab_size=tok.vocab_size, ctx_size=128)
    mesh = make_mesh({"data": 1})
    opt = fused_adam(8e-4)
    state = dp.replicate(
        mesh, dp.init_state(llama.init_llama(jax.random.key(0), cfg), opt))
    step = dp.make_grad_aggregation_step(
        lambda p, b: llama.forward_loss(p, b, cfg), opt, mesh)

    stream = iter(TokenStream(tok, args.batch, cfg.ctx_size))
    for i in range(args.iters):
        state, loss = step(state, dp.shard_batch(mesh, next(stream)))
        if i % max(1, args.iters // 10) == 0:
            print(f"iter {i:4d}: loss {float(loss):.4f}")

    ids = tok.encode(args.prompt)[: cfg.ctx_size // 2] or [1]
    prompt = jnp.asarray([ids], jnp.int32)
    out = generate.generate(
        state.params, prompt, cfg, args.new_tokens,
        key=jax.random.key(7), temperature=args.temperature,
        top_k=args.top_k or None)
    print("prompt    :", args.prompt)
    print("completion:", tok.decode(out[0].tolist()))


if __name__ == "__main__":
    main()
