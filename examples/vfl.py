"""Vertical FL / split learning on heart.csv — one command.

Reference: lab/tutorial_2b/vfl.py `__main__` — 4 parties' bottom MLPs feed a
server top model through the activation-concat cut layer; 300 epochs, B=64.

    python examples/vfl.py --clients 4 --epochs 300
"""

from _common import base_parser, repo_on_path, setup_devices

repo_on_path()


def main():
    ap = base_parser()
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=300)
    ap.add_argument("--partitioner", choices=("base", "even", "min2"),
                    default="base",
                    help="'base' = the tutorial's fixed feature deal "
                         "(vfl.py:105-157); 'even'/'min2' = hw2's seeded "
                         "policies")
    ap.add_argument("--dedup", action="store_true",
                    help="duplicate-aware train/test split (honest "
                         "generalization; see data/tabular.py)")
    args = ap.parse_args()
    setup_devices(args)
    from ddl25spring_tpu.config import VFLConfig
    from ddl25spring_tpu.train.vfl import train_vfl
    from experiments import common

    xs_tr, y_tr, xs_te, y_te, _ = common.heart_vfl_setup(
        args.clients, args.partitioner, seed=0, dedup=args.dedup)
    cfg = VFLConfig(nr_clients=args.clients, epochs=args.epochs)
    _, rep = train_vfl(xs_tr, y_tr, xs_te, y_te, cfg,
                       log_every=max(1, args.epochs // 10))
    print(f"test accuracy {rep.test_accuracy:.4f} "
          f"({args.clients} parties, {args.partitioner}"
          f"{', dedup split' if args.dedup else ''})")


if __name__ == "__main__":
    main()
