"""DP weight-aggregation training — intro_DP_WA's *intended* semantics.

Reference: lab/tutorial_1b/DP/weight_aggr/intro_DP_WA.py — step first, then
allreduce the weights (the script's missing write-back is a recorded bug we
do not reproduce; see parallel/dp.py).

    python examples/dp_weight.py --cpu-devices 3 --iters 200
"""

from _common import base_parser, repo_on_path, setup_devices

repo_on_path()


def main():
    args = base_parser(iters=200, batch=3).parse_args()
    setup_devices(args)
    import jax

    from ddl25spring_tpu.config import LlamaConfig, TrainConfig
    from ddl25spring_tpu.parallel import make_mesh
    from ddl25spring_tpu.train.llm import train_llm_dp

    n = len(jax.devices())
    report = train_llm_dp(
        LlamaConfig(dtype="bfloat16"),
        TrainConfig(iters=args.iters, batch_size=args.batch, data=n),
        mesh=make_mesh({"data": n}),
        aggregation="weight",
        log_every=max(1, args.iters // 20))
    print(f"final loss {report.losses[-1]:.4f}  "
          f"{report.tokens_per_sec:.0f} tok/s over {n} device(s)")


if __name__ == "__main__":
    main()
