"""FedAvg under Byzantine attack with a pluggable defense — one command.

Reference: lab/tutorial_3/attacks_and_defenses.ipynb — 20% of clients
replaced by attacker subclasses (cell 9), defenses plugged into the
aggregation point (cells 34/43); hw3 setting lr=0.02, B=200, C=0.2, E=2,
seed 42.

    python examples/attacks_defenses.py --attack gradient_reversion --defense krum
"""

from _common import base_parser, repo_on_path, setup_devices

repo_on_path()

ATTACKS = ("gradient_reversion", "partial_reversion", "untargeted_flip",
           "targeted_flip", "backdoor", "none")
DEFENSES = ("none", "krum", "multi_krum", "median", "trimmed_mean",
            "majority_sign", "clipping", "bulyan", "sparse_fed")


def main():
    ap = base_parser()
    ap.add_argument("--attack", choices=ATTACKS, default="gradient_reversion")
    ap.add_argument("--defense", choices=DEFENSES, default="krum")
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--noniid", action="store_true")
    ap.add_argument("--n-train", type=int, default=60000)
    ap.add_argument("--n-test", type=int, default=10000)
    args = ap.parse_args()
    setup_devices(args)
    import numpy as np

    from ddl25spring_tpu.config import FLConfig
    from ddl25spring_tpu.fl import FedAvgGradServer
    from ddl25spring_tpu.fl import attacks as atk
    from ddl25spring_tpu.metrics import backdoor_metrics
    from ddl25spring_tpu.models import mnist_cnn
    from experiments import common
    from experiments.hw3_defenses import (HW3, MALICIOUS_FRACTION,
                                          _defense_hook)

    cfg = FLConfig(rounds=args.rounds, iid=not args.noniid, **HW3)
    params, data, xt, yt = common.mnist_fl_setup(
        cfg, n_train=args.n_train, n_test=args.n_test)

    attack = {"gradient_reversion": atk.GradientReversion(),
              "partial_reversion": atk.PartialGradientReversion(),
              "untargeted_flip": atk.UntargetedLabelFlip(),
              "targeted_flip": atk.TargetedLabelFlip(),
              "backdoor": atk.PatternBackdoor(),
              "none": None}[args.attack]
    adversary = None
    if attack is not None:
        adversary = (atk.injection_mask(cfg.nr_clients, MALICIOUS_FRACTION,
                                        cfg.seed), attack)

    n_mal = int(MALICIOUS_FRACTION * cfg.clients_per_round)
    defense = _defense_hook(args.defense, n_mal, k=10, beta=0.2,
                            topk_fraction=0.4)

    server = FedAvgGradServer(params, mnist_cnn.apply, data, xt, yt, cfg,
                              adversary=adversary, defense=defense)
    result = server.run(cfg.rounds)
    print(result.as_df().to_string(index=False))
    if isinstance(attack, atk.PatternBackdoor):
        logits_c = mnist_cnn.apply(server.params, xt)
        logits_t = mnist_cnn.apply(server.params, attack.trigger_test_set(xt))
        acc, asr = backdoor_metrics(np.asarray(logits_c.argmax(-1)), np.asarray(yt),
                                    np.asarray(logits_t.argmax(-1)),
                                    attack.backdoor_label)
        print(f"clean acc {acc:.4f}  backdoor ASR {asr:.4f}")


if __name__ == "__main__":
    main()
