"""DP training with the efficiency levers: fused/Pallas/mixed-precision
optimizers, compressed gradient allreduce, gradient accumulation.

(No reference counterpart — the reference trains fp32 torch modules with a
full-precision gloo allreduce.) One flag each for the levers the framework
adds on top of the reference's DP recipe:

- ``--optimizer {adam,fused,pallas,master}`` — optax baseline, single-pass
  fused Adam (ops/adam.py), the fully-fused Pallas apply (ops/pallas_adam),
  or fp32-master-weight Adam for bf16 params (ops/mixed_precision.py;
  implies ``param_dtype=bfloat16``)
- ``--wire {fp32,bf16,int8_ef}`` — gradient-allreduce wire format
  (parallel/compress.py)
- ``--accum N`` — gradient accumulation (N microbatches per step);
  mutually exclusive with wire compression (the compressed steps own
  their collective schedule)

    python examples/efficient_dp.py --cpu-devices 4 --iters 100 \
        --optimizer master --wire bf16
"""

from _common import base_parser, repo_on_path, setup_devices

repo_on_path()


def main():
    ap = base_parser(iters=200, batch=4)
    ap.add_argument("--optimizer", default="fused",
                    choices=["adam", "fused", "pallas", "master"])
    ap.add_argument("--wire", default="fp32",
                    choices=["fp32", "bf16", "int8_ef"])
    ap.add_argument("--accum", type=int, default=1)
    args = ap.parse_args()
    if args.wire != "fp32" and args.accum != 1:
        ap.error("--wire compression and --accum are mutually exclusive "
                 "(the compressed steps own their collective schedule)")
    setup_devices(args)
    import jax

    from ddl25spring_tpu.config import LlamaConfig, TrainConfig
    from ddl25spring_tpu.parallel import make_mesh
    from ddl25spring_tpu.train.llm import train_llm_dp

    n = len(jax.devices())
    model_cfg = LlamaConfig(
        dtype="bfloat16",
        param_dtype="bfloat16" if args.optimizer == "master" else "float32")
    report = train_llm_dp(
        model_cfg,
        TrainConfig(iters=args.iters, batch_size=args.batch, data=n,
                    optimizer=args.optimizer, wire=args.wire,
                    accum_steps=args.accum),
        mesh=make_mesh({"data": n}),
        log_every=max(1, args.iters // 20))
    print(f"final loss {report.losses[-1]:.4f}  "
          f"{report.tokens_per_sec:.0f} tok/s over {n} device(s)  "
          f"[opt={args.optimizer} wire={args.wire} accum={args.accum}]")


if __name__ == "__main__":
    main()
