"""Pipeline-parallel training — naive staged, GPipe, or true 1F1B.

Reference: lab/tutorial_1b/PP/1F1B/intro_PP_1F1B.py (naive 3-stage; the file
is named 1F1B but is not one) and lab/tutorial_1a/homework_1_b1.py
(microbatched GPipe over isend/irecv). Here: the schedule is a lax.scan, the
stage hop is one lax.ppermute over the ICI ring, and ``--schedule 1f1b``
runs an actual interleaved 1F1B (parallel/pp.py).

    python examples/pp_pipeline.py --cpu-devices 3 --microbatches 3 --schedule gpipe
"""

from _common import base_parser, repo_on_path, setup_devices

repo_on_path()


def main():
    ap = base_parser(iters=100, batch=3)
    ap.add_argument("--microbatches", type=int, default=3)
    ap.add_argument("--schedule", choices=("gpipe", "1f1b"), default="gpipe")
    args = ap.parse_args()
    setup_devices(args)
    import jax
    import optax

    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.data.tokens import TokenStream
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel import make_mesh, pp
    from ddl25spring_tpu.tokenizers import load_tokenizer

    tok = load_tokenizer()
    cfg = LlamaConfig(dtype="bfloat16", vocab_size=tok.vocab_size)
    n_stages = len(jax.devices())
    assert cfg.n_layers % n_stages == 0, (cfg.n_layers, n_stages)
    mesh = make_mesh({"stage": n_stages})
    opt = optax.adam(8e-4)
    state = pp.init_state(mesh, llama.init_llama(jax.random.key(0), cfg), opt)
    step = pp.make_pipeline_step(cfg, opt, mesh, args.microbatches,
                                 schedule=args.schedule)
    batch_rows = args.batch * args.microbatches
    stream = TokenStream(tok, batch_rows, cfg.ctx_size)
    it = iter(stream)
    for i in range(args.iters):
        state, loss = step(state, pp.shard_batch(mesh, next(it)))
        if i % max(1, args.iters // 20) == 0:
            print(f"iter {i}: loss {float(loss):.4f}")
    print(f"final loss {float(loss):.4f} "
          f"({args.schedule}, {n_stages} stages x {args.microbatches} mbs)")


if __name__ == "__main__":
    main()
