"""Mixture-of-Experts training with expert parallelism.

No reference counterpart (SURVEY.md §2.10 marks EP absent). Every block's
SwiGLU MLP becomes a top-k routed expert bank sharded over an ``expert``
mesh axis; the router stays replicated (models/moe.py, parallel/ep.py).

    python examples/moe_ep.py --cpu-devices 4 --experts 4
"""

from _common import base_parser, repo_on_path, setup_devices

repo_on_path()


def main():
    ap = base_parser(iters=50, batch=2)
    ap.add_argument("--experts", type=int, default=4)
    ap.add_argument("--top-k", type=int, default=2)
    ap.add_argument("--ep", type=int, default=0,
                    help="expert-axis size (default: all devices)")
    args = ap.parse_args()
    setup_devices(args)
    import jax
    import optax

    from ddl25spring_tpu.config import LlamaConfig, MoEConfig
    from ddl25spring_tpu.data.tokens import TokenStream
    from ddl25spring_tpu.models import moe
    from ddl25spring_tpu.parallel import ep, make_mesh
    from ddl25spring_tpu.tokenizers import load_tokenizer

    n_dev = len(jax.devices())
    if args.ep:
        n_ep = args.ep
    else:
        # Largest expert-axis size that both divides the device count and
        # divides the expert count evenly (min(n_dev, experts) alone can
        # violate either, e.g. 4 devices × 3 experts).
        n_ep = max(e for e in range(1, min(n_dev, args.experts) + 1)
                   if n_dev % e == 0 and args.experts % e == 0)
    assert n_dev % n_ep == 0, f"--ep {n_ep} must divide device count {n_dev}"
    assert args.experts % n_ep == 0, \
        f"--experts {args.experts} must divide over --ep {n_ep} shards"
    data = n_dev // n_ep
    tok = load_tokenizer()
    cfg = MoEConfig(base=LlamaConfig(dtype="bfloat16",
                                     vocab_size=tok.vocab_size),
                    n_experts=args.experts, top_k=args.top_k)
    mesh = make_mesh({"data": data, "expert": n_ep})
    opt = optax.adam(8e-4)
    state = ep.init_state(mesh, moe.init_moe_llama(jax.random.key(0), cfg), opt)
    step = ep.make_ep_train_step(cfg, opt, mesh)
    stream = TokenStream(tok, data * args.batch, cfg.base.ctx_size)
    it = iter(stream)
    for i in range(args.iters):
        state, loss = step(state, ep.shard_batch(mesh, next(it)))
        if i % max(1, args.iters // 10) == 0:
            print(f"iter {i}: loss {float(loss):.4f}")
    print(f"final loss {float(loss):.4f} "
          f"({args.experts} experts top-{args.top_k} over {n_ep} shards)")


if __name__ == "__main__":
    main()
