"""Joint DP×PP — the homework_1_b2 topology, bug-fixed.

Reference: lab/hw01/homework 1 b/homework_1_b2.py — 2 pipelines × 3 stages
over 6 gloo ranks, with the DP allreduce only in the first-stage group
[0, 3] (a recorded bug: other stages' replicas silently diverge). Here the
mesh is ``{"data": 2, "stage": 3}`` and ALL stages pmean over ``data``.

    python examples/dp_pp_joint.py --cpu-devices 6 --microbatches 3
"""

from _common import base_parser, repo_on_path, setup_devices

repo_on_path()


def main():
    ap = base_parser(iters=100, batch=3)
    ap.add_argument("--microbatches", type=int, default=3)
    ap.add_argument("--pipelines", type=int, default=2)
    ap.add_argument("--schedule", choices=("gpipe", "1f1b"), default="gpipe")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree (adds a 'model' mesh axis; "
                         "Megatron-sharded block weights, parallel/tp.py)")
    args = ap.parse_args()
    setup_devices(args)
    import jax
    import numpy as np
    import optax

    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.data.tokens import sharded_batches
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel import make_mesh, pp
    from ddl25spring_tpu.tokenizers import load_tokenizer

    n_dev = len(jax.devices())
    data = args.pipelines
    assert n_dev % (data * args.tp) == 0, (n_dev, data, args.tp)
    n_stages = n_dev // (data * args.tp)
    tok = load_tokenizer()
    cfg = LlamaConfig(dtype="bfloat16", vocab_size=tok.vocab_size)
    assert cfg.n_layers % n_stages == 0, (cfg.n_layers, n_stages)
    mesh = make_mesh({"data": data, "stage": n_stages, "model": args.tp})
    opt = optax.adam(8e-4)
    state = pp.init_state(mesh, llama.init_llama(jax.random.key(0), cfg), opt)
    step = pp.make_pipeline_step(cfg, opt, mesh, args.microbatches,
                                 schedule=args.schedule)
    rows_per_pipe = args.batch * args.microbatches
    # Disjoint stream windows per pipeline — the reference's skip offsets.
    batches = sharded_batches(tok, rows_per_pipe, cfg.ctx_size, data,
                              shard_skip=5000)
    for i in range(args.iters):
        host = next(batches).reshape(data * rows_per_pipe, cfg.ctx_size)
        state, loss = step(state, pp.shard_batch(mesh, host))
        if i % max(1, args.iters // 20) == 0:
            print(f"iter {i}: loss {float(loss):.4f}")
    print(f"final loss {float(loss):.4f} "
          f"({data} pipelines x {n_stages} stages)")


if __name__ == "__main__":
    main()
