"""Resilience layer: fault injection vs self-healing, end to end.

The acceptance matrix from the resilience design: for each injected fault —
NaN gradient at step k, SIGTERM at step k, corrupted latest checkpoint, FL
client dropout mid-round — the guarded run completes, the fault shows up in
the emitted counters, and the final result matches a fault-free run within
tolerance (exactly for the pure resume cases).
"""

import csv
import os
import signal
import subprocess
import sys
import textwrap
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.checkpoint import Checkpointer, save_best
from ddl25spring_tpu.config import FLConfig, LlamaConfig, ResilienceConfig, TrainConfig
from ddl25spring_tpu.metrics import ResilienceStats
from ddl25spring_tpu.parallel import dp, make_mesh
from ddl25spring_tpu.resilience import (FaultPlan, PreemptionHandler,
                                        StepGuard, backoff_schedule,
                                        corrupt_latest_checkpoint, parse_spec,
                                        retry_call)
from ddl25spring_tpu.tokenizers import ByteTokenizer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = LlamaConfig(vocab_size=259, dmodel=16, num_heads=2, n_layers=2,
                   ctx_size=16)


# --------------------------------------------------------------- fault plans

def test_fault_plan_parse_and_queries():
    plan = FaultPlan.from_spec(
        " nan_grad@3, spike_grad@5:50 ,preempt@7,drop_client@2:2", seed=9)
    assert plan.grad_fault_at(3).kind == "nan_grad"
    assert plan.grad_fault_at(5).arg == 50.0
    assert plan.grad_fault_at(4) is None
    assert plan.preempt_at(7) and not plan.preempt_at(6)
    assert bool(plan) and not bool(FaultPlan.from_spec(""))
    with pytest.raises(ValueError):
        parse_spec("nan_grad")          # missing @step
    with pytest.raises(ValueError):
        parse_spec("warp_core@3")       # unknown kind


def test_fault_plan_client_choice_deterministic():
    plan = FaultPlan.from_spec("drop_client@1:2,delay_client@1:1", seed=4)
    idx = np.arange(10)
    m1, d1, s1 = plan.surviving_clients(1, idx)
    m2, d2, s2 = plan.surviving_clients(1, idx)
    assert (m1 == m2).all() and (d1, s1) == (2, 1) == (d2, s2)
    assert m1.sum() == 7
    # Unfaulted rounds lose nobody.
    m3, d3, s3 = plan.surviving_clients(0, idx)
    assert m3.all() and d3 == 0 and s3 == 0
    # A different seed picks a different victim set (10 choose 3 makes a
    # collision across all three picks vanishingly unlikely for these seeds).
    m4, _, _ = FaultPlan.from_spec("drop_client@1:2,delay_client@1:1",
                                   seed=5).surviving_clients(1, idx)
    assert not (m1 == m4).all()


# -------------------------------------------------------------------- retry

def test_backoff_schedule_deterministic_and_shaped():
    s1 = backoff_schedule(5, base=0.1, max_delay=0.5, jitter=0.25, seed=3)
    s2 = backoff_schedule(5, base=0.1, max_delay=0.5, jitter=0.25, seed=3)
    assert s1 == s2
    # Exponential up to the cap, within the jitter band.
    for i, d in enumerate(s1):
        nominal = min(0.1 * 2 ** i, 0.5)
        assert 0.75 * nominal <= d <= 1.25 * nominal


def test_retry_call_retries_then_succeeds_and_raises():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return 42

    slept = []
    retried = []
    assert retry_call(flaky, attempts=5, sleep=slept.append,
                      on_retry=lambda i, e: retried.append(i)) == 42
    assert calls["n"] == 3 and len(slept) == 2 and retried == [0, 1]

    def always():
        calls["n"] += 1
        raise ValueError("permanent")

    calls["n"] = 0
    with pytest.raises(ValueError):
        retry_call(always, attempts=3, sleep=lambda s: None)
    assert calls["n"] == 3  # the budget was spent before surfacing


# ---------------------------------------------------------------- StepGuard

def _tiny_dp(devices, guard_nonfinite=False, lr=1e-2):
    mesh = make_mesh({"data": 2}, devices=devices[:2])
    params = {"w": jnp.arange(4, dtype=jnp.float32) / 4, "b": jnp.zeros((2,))}
    opt = optax.adam(lr)

    def loss_fn(p, batch):
        return jnp.mean((batch @ p["w"].reshape(2, 2) + p["b"]) ** 2)

    step = dp.make_grad_aggregation_step(loss_fn, opt, mesh,
                                         guard_nonfinite=guard_nonfinite)
    state = dp.replicate(mesh, dp.init_state(params, opt))
    rng = np.random.default_rng(0)
    batch = dp.shard_batch(
        mesh, rng.normal(size=(4, 2)).astype(np.float32))
    return mesh, state, step, batch


def test_guarded_fault_free_run_bit_identical(devices):
    """A StepGuard around a fault-free step must change NOTHING: the final
    params are bit-identical to the unguarded run's and every counter is 0."""
    _, state_a, step, batch = _tiny_dp(devices)
    _, state_b, _, _ = _tiny_dp(devices)
    stats = ResilienceStats()
    guard = StepGuard(step, stats=stats)
    for _ in range(6):
        state_a, loss_a = step(state_a, batch)
        state_b, loss_b = guard(state_b, batch)
    for a, b in zip(jax.tree.leaves(state_a), jax.tree.leaves(state_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.asarray(loss_a) == np.asarray(loss_b)
    assert stats.total_faults_handled == 0


def test_stepguard_skips_nan_step(devices):
    """A NaN-injected step is skipped: params unchanged across it, the skip
    counter increments, and training continues finitely afterwards."""
    _, state, step, batch = _tiny_dp(devices)
    stats = ResilienceStats()
    plan = FaultPlan.from_spec("nan_grad@2")
    guard = StepGuard(plan.wrap_step(step), stats=stats)
    params_before_fault = None
    for it in range(5):
        if it == 2:
            params_before_fault = jax.tree.map(np.asarray, state.params)
        state, loss = guard(state, batch)
        if it == 2:
            for a, b in zip(jax.tree.leaves(params_before_fault),
                            jax.tree.leaves(state.params)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert stats.skipped_steps == 1 and stats.rollbacks == 0
    assert all(bool(jnp.all(jnp.isfinite(x)))
               for x in jax.tree.leaves(state.params))
    assert bool(jnp.isfinite(loss))


def test_stepguard_ema_catches_spike(devices):
    """A finite-but-exploded update (spike_grad) trips the EMA update-norm
    detector and is skipped as an anomaly."""
    _, state, step, batch = _tiny_dp(devices)
    stats = ResilienceStats()
    plan = FaultPlan.from_spec("spike_grad@6:1000")
    guard = StepGuard(plan.wrap_step(step), stats=stats,
                      ema_warmup=3, anomaly_factor=8.0)
    before = None
    for it in range(8):
        if it == 6:
            before = jax.tree.map(np.asarray, state.params)
        state, loss = guard(state, batch)
    assert stats.anomalies == 1 and stats.skipped_steps == 0
    # The spiked update was rejected wholesale.
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(state.params)):
        assert np.max(np.abs(np.asarray(a) - np.asarray(b))) < 1.0


def test_stepguard_rollback_after_k_bad(devices, tmp_path):
    """K consecutive bad steps roll the state back to the last good
    checkpoint (restored through Checkpointer's fallback machinery)."""
    _, state, step, batch = _tiny_dp(devices)
    stats = ResilienceStats()
    with Checkpointer(str(tmp_path / "ck"), stats=stats) as ckpt:
        # Two good steps, checkpoint, then a permanent NaN fault.
        for _ in range(2):
            state, _ = step(state, batch)
        ckpt.save(2, state)
        ckpt.wait()
        good = jax.tree.map(np.asarray, state)

        plan = FaultPlan.from_spec("nan_grad@0,nan_grad@1,nan_grad@2")
        guard = StepGuard(plan.wrap_step(step), ckpt=ckpt, stats=stats,
                          max_consecutive_bad=3)
        for _ in range(3):
            state, _ = guard(state, batch)
    assert stats.skipped_steps == 3 and stats.rollbacks == 1
    for a, b in zip(jax.tree.leaves(good), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_dp_in_jit_guard_skips_nonfinite(devices):
    """The fused guard_nonfinite path: a poisoned batch yields a non-finite
    loss but the params/opt state/step are a select-back no-op."""
    mesh, state, step, batch = _tiny_dp(devices, guard_nonfinite=True)
    state, loss = step(state, batch)
    assert int(state.step) == 1 and bool(jnp.isfinite(loss))
    before = jax.tree.map(np.asarray, state.params)
    poisoned = dp.shard_batch(mesh, np.full((4, 2), np.nan, np.float32))
    state, loss = step(state, poisoned)
    assert not bool(jnp.isfinite(loss))
    assert int(state.step) == 1  # did not advance
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -------------------------------------------------------------- checkpoints

def test_restore_falls_back_past_corrupt_latest(tmp_path, devices):
    """Corrupt the newest orbax step on disk; restore must fall back to the
    previous valid step and say so in the counters."""
    tree = {"w": jnp.arange(8, dtype=jnp.float32)}
    stats = ResilienceStats()
    with Checkpointer(str(tmp_path / "ck"), stats=stats) as ckpt:
        for s in (1, 2, 3):
            ckpt.save(s, {"w": tree["w"] * s})
        ckpt.wait()
        corrupt_latest_checkpoint(str(tmp_path / "ck"))
        restored = ckpt.restore(tree)
        assert ckpt.restored_step == 2
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.arange(8, dtype=np.float32) * 2)
    assert stats.ckpt_fallbacks >= 1


def test_save_overwrite_replaces_stale_step_after_fallback(tmp_path, devices):
    """After a corrupt-latest fallback, a run re-treading the corrupt step's
    index must be able to re-save it: ``overwrite=True`` replaces the stale
    entry (a blind save would be an orbax StepAlreadyExistsError), and the
    replacement restores cleanly as the new latest."""
    tree = {"w": jnp.arange(8, dtype=jnp.float32)}
    with Checkpointer(str(tmp_path / "ck")) as ckpt:
        for s in (1, 2, 3):
            ckpt.save(s, {"w": tree["w"] * s})
        ckpt.wait()
        corrupt_latest_checkpoint(str(tmp_path / "ck"))
        ckpt.restore(tree)
        assert ckpt.restored_step == 2
        ckpt.save(3, {"w": tree["w"] * 30}, force=True, overwrite=True)
        ckpt.wait()
        restored = ckpt.restore(tree)
        assert ckpt.restored_step == 3
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.arange(8, dtype=np.float32) * 30)


def test_checkpoint_digest_catches_silent_bitflip(tmp_path):
    """A single flipped bit in a saved shard — invisible to orbax, which
    would hand the poisoned bytes back bit-exactly — fails the save-time
    digest manifest, so restore counts a ``ckpt_fallbacks`` and falls back
    to the previous step BEFORE any poisoned weights reach the run."""
    import pathlib

    tree = {"w": jnp.arange(64, dtype=jnp.float32)}
    stats = ResilienceStats()
    with Checkpointer(str(tmp_path / "ck"), stats=stats) as ckpt:
        ckpt.save(1, {"w": tree["w"]})
        ckpt.save(2, {"w": tree["w"] * 2})
        ckpt.wait()                       # digest manifests land here
        step_dir = pathlib.Path(tmp_path / "ck" / "2")
        # Flip one bit mid-file in the largest file (the array bytes);
        # size and structure are untouched — the silent-corruption case
        # truncation-style faults (corrupt_latest_checkpoint) don't model.
        victim = max((p for p in step_dir.rglob("*") if p.is_file()),
                     key=lambda p: p.stat().st_size)
        raw = bytearray(victim.read_bytes())
        raw[len(raw) // 2] ^= 0x01
        victim.write_bytes(raw)
        restored = ckpt.restore(tree)
        assert ckpt.restored_step == 1
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.arange(64, dtype=np.float32))
    assert stats.ckpt_fallbacks >= 1


def test_restore_all_corrupt_raises(tmp_path):
    tree = {"w": jnp.ones((4,))}
    with Checkpointer(str(tmp_path / "ck"), max_to_keep=2) as ckpt:
        ckpt.save(1, tree)
        ckpt.wait()
        corrupt_latest_checkpoint(str(tmp_path / "ck"))
        with pytest.raises(FileNotFoundError):
            ckpt.restore(tree)


def test_save_best_atomic_preserves_previous_on_failure(tmp_path, monkeypatch):
    """A failing write never clobbers the existing best file, and no temp
    litter survives."""
    path = str(tmp_path / "best.npz")
    save_best(path, {"w": jnp.ones((3,))})
    good = open(path, "rb").read()

    def boom(*a, **kw):
        raise OSError("disk full")

    monkeypatch.setattr(np, "savez", boom)
    with pytest.raises(OSError):
        save_best(path, {"w": jnp.zeros((3,))})
    assert open(path, "rb").read() == good
    assert [f for f in os.listdir(tmp_path) if f != "best.npz"] == []


# --------------------------------------------------------------- preemption

def test_preemption_handler_catches_and_restores():
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionHandler() as pre:
        assert not pre.requested
        signal.raise_signal(signal.SIGTERM)
        assert pre.requested
    assert signal.getsignal(signal.SIGTERM) is prev


def _train(tmp_path, name, *, iters, fault_plan=None, sink_rows=None,
           resilience=None):
    from ddl25spring_tpu.train.llm import train_llm_dp

    sink = None
    if sink_rows is not None:
        sink = lambda it, loss: sink_rows.append((it, loss))
    return train_llm_dp(
        model_cfg=TINY,
        train_cfg=TrainConfig(batch_size=2, seq_len=16, iters=iters, lr=3e-3),
        mesh=make_mesh({"data": 1}, devices=jax.devices()[:1]),
        tokenizer=ByteTokenizer(),
        log_every=0,
        checkpoint_dir=str(tmp_path / name),
        checkpoint_every=4,
        loss_sink=sink, sink_every=1,
        fault_plan=fault_plan,
        resilience=resilience,
    )


def test_simulated_preemption_resumes_exactly(tmp_path, devices):
    """The resume half of the acceptance matrix, in-process: a simulated
    SIGTERM preemption force-saves, the rerun resumes with exact stream
    replay, and the stitched loss record equals an uninterrupted run's
    EXACTLY, with a contiguous iteration record."""
    rows_ref = []
    ref = _train(tmp_path, "ref", iters=10, sink_rows=rows_ref)
    assert not ref.preempted

    rows1 = []
    r1 = _train(tmp_path, "pre", iters=10, sink_rows=rows1,
                fault_plan=FaultPlan.from_spec("preempt@5"))
    assert r1.preempted and r1.resilience.preemptions == 1
    assert len(r1.losses) < 10

    rows2 = []
    r2 = _train(tmp_path, "pre", iters=10, sink_rows=rows2)
    assert not r2.preempted

    stitched = dict(rows1)
    stitched.update(dict(rows2))
    assert sorted(stitched) == list(range(10))       # contiguous record
    for it, loss in dict(rows_ref).items():
        assert stitched[it] == loss, f"iter {it} diverged after resume"
    assert r2.losses[-1] == ref.losses[-1]


def test_nan_fault_guarded_trainer_completes(tmp_path, devices):
    """NaN-grad at step k through the full DP trainer with the guard on: the
    run completes, the skip is counted, and the final loss lands within
    tolerance of the fault-free run's (one missing update on a smooth
    curve)."""
    ref = _train(tmp_path, "ref2", iters=10)
    got = _train(tmp_path, "nan", iters=10,
                 fault_plan=FaultPlan.from_spec("nan_grad@4"),
                 resilience=ResilienceConfig(guard=True, ema_warmup=100))
    assert got.resilience.skipped_steps == 1
    assert not np.isfinite(got.losses[4])  # the fault is visible...
    finite = [l for l in got.losses if np.isfinite(l)]
    assert len(finite) == 9                # ...and contained
    assert abs(got.losses[-1] - ref.losses[-1]) < 0.25 * abs(ref.losses[-1])


def test_unguarded_nan_fault_poisons_run(tmp_path, devices):
    """Negative control: without the guard the same NaN fault destroys the
    rest of the run — the counters prove the guard is what saved it above."""
    got = _train(tmp_path, "nanfree", iters=8,
                 fault_plan=FaultPlan.from_spec("nan_grad@3"))
    assert not np.isfinite(got.losses[-1])


# -------------------------------------------------- SIGTERM subprocess test

_TRAIN_SCRIPT = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)
    from ddl25spring_tpu.config import LlamaConfig, TrainConfig
    from ddl25spring_tpu.parallel import make_mesh
    from ddl25spring_tpu.tokenizers import ByteTokenizer
    from ddl25spring_tpu.train.llm import train_llm_dp

    out_dir = sys.argv[1]
    csv_path = os.path.join(out_dir, "loss.csv")

    def sink(it, loss):
        with open(csv_path, "a") as f:
            f.write(f"{it},{loss}\\n")
            f.flush()

    report = train_llm_dp(
        model_cfg=LlamaConfig(vocab_size=259, dmodel=16, num_heads=2,
                              n_layers=2, ctx_size=16),
        train_cfg=TrainConfig(batch_size=2, seq_len=16, iters=16, lr=3e-3),
        mesh=make_mesh({"data": 1}),
        tokenizer=ByteTokenizer(),
        log_every=0,
        checkpoint_dir=os.path.join(out_dir, "ck"),
        checkpoint_every=4,
        loss_sink=sink, sink_every=1,
    )
    print("PREEMPTED" if report.preempted else "COMPLETED", flush=True)
""")


def test_sigterm_subprocess_resumes_to_completion(tmp_path):
    """Real SIGTERM against a real training subprocess mid-loop: the child
    force-saves and exits cleanly; rerunning the identical command resumes
    and completes with a contiguous loss record.

    Race-tolerant by design: the 16-iter tiny child can legitimately
    OUTRUN the parent's 0.5 s progress poll and finish before the signal
    lands, in which case it honestly reports COMPLETED (this was a known
    flake when the assertion demanded PREEMPTED). Either outcome is a
    correct run; what this test actually pins is resume correctness, and
    the evidence for that is the stitched loss record — contiguous,
    finite, later rows winning the resume overlap — not the exit state."""
    script = tmp_path / "train_script.py"
    script.write_text(_TRAIN_SCRIPT)
    csv_path = tmp_path / "loss.csv"
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", "")}

    proc = subprocess.Popen([sys.executable, str(script), str(tmp_path)],
                            cwd=REPO, env=env, stdout=subprocess.PIPE,
                            text=True)
    deadline = time.time() + 240
    while time.time() < deadline:
        if csv_path.exists() and len(csv_path.read_text().splitlines()) >= 3:
            break
        if proc.poll() is not None and proc.poll() != 0:
            pytest.fail(f"trainer exited early rc={proc.returncode}")
        if proc.poll() == 0:
            break                # won the race: completed before the poll
        time.sleep(0.5)
    else:
        proc.kill()
        pytest.fail("trainer never made progress")
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == 0, out
    assert ("PREEMPTED" in out) or ("COMPLETED" in out), out
    preempted = "PREEMPTED" in out

    proc2 = subprocess.run([sys.executable, str(script), str(tmp_path)],
                           cwd=REPO, env=env, capture_output=True, text=True,
                           timeout=300)
    assert proc2.returncode == 0, proc2.stderr[-2000:]
    # The rerun either resumes-and-completes or finds the finished
    # checkpoint ("nothing to train") — both print COMPLETED.
    assert "COMPLETED" in proc2.stdout

    rows = [r for r in csv.reader(csv_path.read_text().splitlines()) if r]
    recorded = {}
    first_seen = {}
    for it, loss in rows:     # later rows win: the resume's overlap re-write
        it = int(it)
        recorded[it] = float(loss)
        first_seen.setdefault(it, float(loss))
    assert sorted(recorded) == list(range(16))   # contiguous 0..15
    assert all(np.isfinite(v) for v in recorded.values())
    if preempted:
        # Resume correctness, not just coverage: wherever the rerun
        # re-trod an iteration the first run already recorded, the
        # deterministic replay must reproduce the identical loss.
        assert all(first_seen[i] == recorded[i] for i in recorded)


# ----------------------------------------------------------- FL dropout

@pytest.fixture(scope="module")
def fl_setup():
    from ddl25spring_tpu.data import mnist
    from ddl25spring_tpu.fl import federate
    from ddl25spring_tpu.models import mnist_cnn

    x_raw, y, xt_raw, yt = mnist.load_mnist(n_train=400, n_test=100, seed=0)
    x = mnist.normalize(x_raw)
    xt = mnist.normalize(xt_raw)
    cfg = FLConfig(nr_clients=8, client_fraction=0.5, batch_size=50,
                   epochs=1, lr=0.05, rounds=2, seed=10)
    subsets = mnist.split(y, cfg.nr_clients, iid=True, seed=cfg.seed)
    data = federate(x, y.astype(np.int32), subsets)
    params = mnist_cnn.init(jax.random.key(0))
    apply_fn = mnist_cnn.apply
    return params, apply_fn, data, xt, yt.astype(np.int32), cfg


def test_fl_round_tolerates_client_dropout(fl_setup):
    """Clients vanishing mid-round: the round completes by re-weighting over
    survivors, deterministically under the plan seed, with the loss of
    coverage visible in the counters."""
    from ddl25spring_tpu.fl import FedAvgServer

    params, apply_fn, data, xt, yt, cfg = fl_setup
    plan = FaultPlan.from_spec("drop_client@0:2,delay_client@1:1", seed=3)

    a = FedAvgServer(params, apply_fn, data, xt, yt, cfg, fault_plan=plan)
    b = FedAvgServer(params, apply_fn, data, xt, yt, cfg, fault_plan=plan)
    ra = a.run(2)
    rb = b.run(2)
    assert a.resilience.dropped_clients == 2
    assert a.resilience.straggler_clients == 1
    assert a.resilience.skipped_rounds == 0
    # Deterministic under seed: identical servers walk identical paths.
    for pa, pb in zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)):
        np.testing.assert_array_equal(np.asarray(pa), np.asarray(pb))
    assert ra.test_accuracy == rb.test_accuracy
    # And the run still learned: accuracy is sane, not collapsed.
    fault_free = FedAvgServer(params, apply_fn, data, xt, yt, cfg)
    rf = fault_free.run(2)
    assert abs(ra.test_accuracy[-1] - rf.test_accuracy[-1]) < 0.25


def test_fl_all_clients_lost_round_is_skipped(fl_setup):
    """A round in which EVERY sampled client drops is skipped outright:
    counted in skipped_rounds, and the next round proceeds normally."""
    from ddl25spring_tpu.fl import FedAvgServer

    params, apply_fn, data, xt, yt, cfg = fl_setup
    plan = FaultPlan.from_spec("drop_client@0:99", seed=1)
    s = FedAvgServer(params, apply_fn, data, xt, yt, cfg, fault_plan=plan)
    before = jax.tree.map(np.asarray, s.params)
    # One run of 2 rounds: round 0 loses everyone, round 1 is fault-free.
    # (run() always iterates from round index 0, so two run(1) calls would
    # both hit the faulted round and never exercise the recovery.)
    s.run(2)
    assert s.resilience.skipped_rounds == 1
    assert s.result.rounds == 2
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(s.params)))
    assert changed, "round 1 (fault-free) must train past the skipped round"


def test_fl_survivor_reweighting_matches_direct_subset(fl_setup):
    """Re-weighted aggregation over survivors is EXACTLY the round the
    server would have run had it sampled only the survivors: the dropout
    path adds no numerics of its own. Since the padded-round refactor the
    dropout path keeps the dropped entries as zero-weight duplicates —
    tree_weighted_fold selects around weight-0 rows, so the padded round
    still equals the filtered one bitwise."""
    from ddl25spring_tpu.fl import FedAvgServer
    from ddl25spring_tpu.fl.servers import _round_weights

    params, apply_fn, data, xt, yt, cfg = fl_setup
    plan = FaultPlan.from_spec("drop_client@0:2", seed=3)
    s = FedAvgServer(params, apply_fn, data, xt, yt, cfg, fault_plan=plan)
    idx = s._sample(0)
    mask, _, _ = plan.surviving_clients(0, idx)
    survivors = idx[mask]
    dropped_params = s._round(s.params, 0)

    t = FedAvgServer(params, apply_fn, data, xt, yt, cfg)
    keys = jax.vmap(jax.random.key)(
        jnp.asarray(t.client_seeds(0, survivors)))
    survivors = jnp.asarray(survivors)
    w = _round_weights(data.sample_counts[survivors], None)
    direct_params = t._round_step(t.params, survivors, keys, w)
    for a, b in zip(jax.tree.leaves(dropped_params),
                    jax.tree.leaves(direct_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fl_dropout_rounds_reuse_one_compiled_round_step(fl_setup):
    """The satellite fix for the per-round retrace: rounds with DIFFERENT
    survivor counts pad back to the full sampled width with zero-weight
    masks, so the compiled round step serves every dropout pattern at ONE
    trace (the old filtering path recompiled once per distinct count)."""
    from ddl25spring_tpu.fl import FedAvgServer

    params, apply_fn, data, xt, yt, cfg = fl_setup
    # Distinct survivor counts in rounds 0/1/2: 2 dropped, 1, none.
    plan = FaultPlan.from_spec("drop_client@0:2,drop_client@1:1", seed=5)
    s = FedAvgServer(params, apply_fn, data, xt, yt, cfg, fault_plan=plan)
    s.run(3)
    assert s.resilience.dropped_clients == 3
    assert s._round_step._cache_size() == 1
