"""Tensor parallelism: Megatron-sharded blocks vs the single-device model."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ddl25spring_tpu.config import LlamaConfig
from ddl25spring_tpu.models import llama
from ddl25spring_tpu.ops import causal_lm_loss
from ddl25spring_tpu.parallel import make_mesh, tp


def _cfg():
    return LlamaConfig(vocab_size=128, dmodel=32, num_heads=4, n_layers=2,
                       ctx_size=32)


def test_tp_forward_matches_single_device():
    cfg = _cfg()
    mesh = make_mesh({"model": 4}, devices=jax.devices()[:4])
    params = llama.init_llama(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, cfg.ctx_size), 0,
                                cfg.vocab_size)
    out = tp.tp_forward(tp.shard_params(mesh, params), tokens, cfg, mesh)
    ref = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-3)


def test_tp_params_actually_sharded():
    cfg = _cfg()
    mesh = make_mesh({"model": 4}, devices=jax.devices()[:4])
    params = tp.shard_params(mesh, llama.init_llama(jax.random.key(0), cfg))
    wq_spec = params["blocks"]["wq"].sharding.spec
    wo_spec = params["blocks"]["wo"].sharding.spec
    assert wq_spec == P(None, None, "model"), wq_spec
    assert wo_spec == P(None, "model", None), wo_spec
    assert params["embed"].sharding.spec == P()


def test_tp_train_step_matches_single_device():
    cfg = _cfg()
    mesh = make_mesh({"model": 4}, devices=jax.devices()[:4])
    params = llama.init_llama(jax.random.key(0), cfg)
    opt = optax.sgd(0.1)  # linear in grads; see test_sp for why not Adam
    tokens = jax.random.randint(jax.random.key(1), (2, cfg.ctx_size), 0,
                                cfg.vocab_size)

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: causal_lm_loss(llama.forward(p, tokens, cfg), tokens))(params)
    updates, _ = opt.update(ref_grads, opt.init(params), params)
    ref_params = optax.apply_updates(params, updates)

    state = tp.init_state(mesh, params, opt)
    step = tp.make_tp_train_step(cfg, opt, mesh)
    state, loss = step(state, tp.shard_batch(mesh, tokens))

    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5, rtol=1e-5)
    for (path, a), b in zip(
            jax.tree_util.tree_flatten_with_path(state.params)[0],
            jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-4,
            err_msg=jax.tree_util.keystr(path))


def test_tp_composes_with_dp():
    cfg = _cfg()
    mesh = make_mesh({"data": 2, "model": 4})
    params = llama.init_llama(jax.random.key(0), cfg)
    opt = optax.sgd(0.1)
    tokens = jax.random.randint(jax.random.key(1), (4, cfg.ctx_size), 0,
                                cfg.vocab_size)

    ref_loss = causal_lm_loss(llama.forward(params, tokens, cfg), tokens)

    state = tp.init_state(mesh, params, opt)
    step = tp.make_tp_train_step(cfg, opt, mesh)
    state, loss = step(state, tp.shard_batch(mesh, tokens))

    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5, rtol=1e-5)
