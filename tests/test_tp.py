"""Tensor parallelism: Megatron-sharded blocks vs the single-device model."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from ddl25spring_tpu.config import LlamaConfig
from ddl25spring_tpu.models import llama
from ddl25spring_tpu.ops import causal_lm_loss
from ddl25spring_tpu.parallel import make_mesh, tp


def _cfg():
    return LlamaConfig(vocab_size=128, dmodel=32, num_heads=4, n_layers=2,
                       ctx_size=32)


def test_tp_forward_matches_single_device():
    cfg = _cfg()
    mesh = make_mesh({"model": 4}, devices=jax.devices()[:4])
    params = llama.init_llama(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, cfg.ctx_size), 0,
                                cfg.vocab_size)
    out = tp.tp_forward(tp.shard_params(mesh, params), tokens, cfg, mesh)
    ref = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-3)


def test_tp_params_actually_sharded():
    cfg = _cfg()
    mesh = make_mesh({"model": 4}, devices=jax.devices()[:4])
    params = tp.shard_params(mesh, llama.init_llama(jax.random.key(0), cfg))
    wq_spec = params["blocks"]["wq"].sharding.spec
    wo_spec = params["blocks"]["wo"].sharding.spec
    assert wq_spec == P(None, None, "model"), wq_spec
    # Trailing-None-free on purpose: XLA normalizes output shardings, and
    # an unnormalized input spec would be a different jit cache signature
    # (one spurious re-lowering per driver — see tp.param_specs).
    assert wo_spec == P(None, "model"), wo_spec
    assert params["embed"].sharding.spec == P()


def test_tp_train_step_matches_single_device():
    cfg = _cfg()
    mesh = make_mesh({"model": 4}, devices=jax.devices()[:4])
    params = llama.init_llama(jax.random.key(0), cfg)
    opt = optax.sgd(0.1)  # linear in grads; see test_sp for why not Adam
    tokens = jax.random.randint(jax.random.key(1), (2, cfg.ctx_size), 0,
                                cfg.vocab_size)

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: causal_lm_loss(llama.forward(p, tokens, cfg), tokens))(params)
    updates, _ = opt.update(ref_grads, opt.init(params), params)
    ref_params = optax.apply_updates(params, updates)

    state = tp.init_state(mesh, params, opt)
    step = tp.make_tp_train_step(cfg, opt, mesh)
    state, loss = step(state, tp.shard_batch(mesh, tokens))

    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5, rtol=1e-5)
    for (path, a), b in zip(
            jax.tree_util.tree_flatten_with_path(state.params)[0],
            jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-4,
            err_msg=jax.tree_util.keystr(path))


def test_tp_composes_with_dp():
    cfg = _cfg()
    mesh = make_mesh({"data": 2, "model": 4})
    params = llama.init_llama(jax.random.key(0), cfg)
    opt = optax.sgd(0.1)
    tokens = jax.random.randint(jax.random.key(1), (4, cfg.ctx_size), 0,
                                cfg.vocab_size)

    ref_loss = causal_lm_loss(llama.forward(params, tokens, cfg), tokens)

    state = tp.init_state(mesh, params, opt)
    step = tp.make_tp_train_step(cfg, opt, mesh)
    state, loss = step(state, tp.shard_batch(mesh, tokens))

    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5, rtol=1e-5)


# --------------------------------------------------------------- PSA column
#
# The ISSUE-18 composition column: partially-synchronized activations
# (TrainConfig.psa), the fused K-scan TP dispatch, and the DP×TP ring.
# The golden checks: psa off/"full" are BITWISE the legacy path, the
# relaxed modes hold a pinned convergence bar against the exact path on
# the tiny-llama fixture, and every driver variant is bitwise-reproducible
# under the K-scan / preempt-resume / numerics levers.


def _host_params(cfg, seed=0):
    """numpy leaves: jax.device_put may ALIAS a same-device jax.Array into
    the donated state, and the donated step would then delete the caller's
    buffers (the dp.replicate donation hazard) — numpy forces a copy."""
    return jax.tree.map(np.asarray, llama.init_llama(jax.random.key(seed), cfg))


def _tokens(cfg, batch=4, seed=1):
    return np.asarray(jax.random.randint(jax.random.key(seed),
                                         (batch, cfg.ctx_size), 0,
                                         cfg.vocab_size))


def _run_steps(step, state, batch, n):
    losses = []
    for _ in range(n):
        state, l = step(state, batch)
        losses.append(float(l))
    return state, losses


def test_tp_psa_off_and_full_bitwise_vs_legacy(devices):
    """psa="" (raw in-model psums) and psa="full" (the same sync positions
    through the telemetry comm wrappers) are BITWISE the legacy
    make_tp_train_step path — losses and params — over 3 adam steps.
    (One shared legacy reference: the factory compiles dominate this
    file's tier-1 cost.)"""
    cfg = _cfg()
    mesh = make_mesh({"model": 4}, devices=devices[:4])
    params = _host_params(cfg)
    tokens = _tokens(cfg)
    opt = optax.adam(1e-3)

    ref_state = tp.init_state(mesh, params, opt)
    legacy = tp.make_tp_train_step(cfg, opt, mesh)
    ref_state, ref_losses = _run_steps(legacy, ref_state,
                                       tp.shard_batch(mesh, tokens), 3)
    ref_leaves = jax.tree.leaves(jax.device_get(ref_state.params))

    for psa in ("", "full"):
        state, step = tp.make_tp_step(cfg, opt, mesh, params, psa=psa)
        state, losses = _run_steps(step, state,
                                   tp.shard_batch(mesh, tokens), 3)
        assert losses == ref_losses, psa
        for a, b in zip(ref_leaves,
                        jax.tree.leaves(jax.device_get(state.params))):
            np.testing.assert_array_equal(a, b)


def test_tp_psa_relaxed_convergence_bar(devices):
    """The relaxed sync modes on the tiny-llama fixture: losses finite and
    descending, and the 5-step trajectory tracks the exact path within the
    pinned bar — deferred sync's boundary correction and int8 EF's
    residual compensation keep the relaxation principled, not drifting.
    (One shared exact reference across the modes; defer:1 is subsumed by
    defer:2 — more deferral, same machinery.)"""
    cfg = _cfg()
    mesh = make_mesh({"model": 4}, devices=devices[:4])
    params = _host_params(cfg)
    tokens = _tokens(cfg)
    opt = optax.adam(1e-3)

    exact_state, exact_step = tp.make_tp_step(cfg, opt, mesh, params)
    _, exact_losses = _run_steps(exact_step, exact_state,
                                 tp.shard_batch(mesh, tokens), 5)

    for psa in ("defer:2", "int8_ef"):
        state, step = tp.make_tp_step(cfg, opt, mesh, params, psa=psa,
                                      batch_shape=(tokens.shape[0],
                                                   cfg.ctx_size))
        _, losses = _run_steps(step, state, tp.shard_batch(mesh, tokens), 5)

        assert all(np.isfinite(losses)), (psa, losses)
        assert losses[-1] < losses[0], (psa, losses)
        np.testing.assert_allclose(losses, exact_losses, atol=2e-2, rtol=0,
                                   err_msg=psa)


def test_tp_psa_int8_error_feedback_property(devices):
    """The EF residual contract of _psa_int8_sync on a quadratic-sized
    fixture: the residual carries exactly the quantization error
    (c − s·q per shard), so consecutive syncs TELESCOPE — out1 + out2 =
    2·exact − psum(res2), i.e. the CUMULATIVE error after two syncs is
    bounded by ONE quantization step, not two. (The per-step error is
    allowed to wobble — EF compensates cumulatively, it is not a
    per-step contraction.)"""
    from ddl25spring_tpu.parallel._compat import shard_map

    mesh = make_mesh({"model": 4}, devices=devices[:4])
    y = np.linspace(-1.0, 1.0, 4 * 8 * 16, dtype=np.float32).reshape(4, 8, 16)

    def body(y_shard):
        y0 = y_shard[0]
        out1, res1 = tp._psa_int8_sync(y0, jnp.zeros_like(y0), 1)
        out2, res2 = tp._psa_int8_sync(y0, res1, 1)
        exact = jax.lax.psum(y0, "model")
        return out1[None], out2[None], res1[None], res2[None], exact[None]

    out1, out2, res1, res2, exact = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("model"),),
        out_specs=(P("model"),) * 5, check_vma=False))(y)
    e1 = np.abs(np.asarray(out1) - np.asarray(exact)).max()
    # int8 quantization error bound: each shard contributes ≤ s/2 ≈
    # max|c|/254; 4 shards of values in [-1, 1] (+ residual headroom).
    assert e1 <= 4 * 2.0 / 254 + 1e-6, e1
    # telescoping: out1 + out2 = 2·exact − psum(res2), so the two-sync
    # cumulative error is bounded by ONE sync's quantization error.
    cum = np.abs((np.asarray(out1) + np.asarray(out2))
                 - 2 * np.asarray(exact)).max()
    assert cum <= 4 * 2.0 / 254 + 1e-6, cum
    # the residual really is the per-shard quantization error: applying
    # it once must not leave a residual larger than one quantization step.
    assert np.abs(np.asarray(res2)).max() <= 2.0 / 254 + 1e-6


@pytest.mark.parametrize("psa", ["", "int8_ef"])
def test_tp_multi_step_bitwise_matches_per_step(devices, psa):
    """tp.make_tp_multi_step reproduces K per-step calls BITWISE at
    K∈{1,4} — the shared-body factory promise; int8_ef additionally
    proves the activation EF residual tree threads the scan carry.
    One 4-step per-step reference trajectory serves both K values
    (snapshotted after step 1 and step 4) to keep tier-1 cost down."""
    cfg = _cfg()
    mesh = make_mesh({"model": 4}, devices=devices[:4])
    tokens = _tokens(cfg)
    opt = optax.adam(1e-3)
    bshape = (tokens.shape[0], cfg.ctx_size)

    state1, step1 = tp.make_tp_step(cfg, opt, mesh, _host_params(cfg),
                                    psa=psa, batch_shape=bshape)
    batch = tp.shard_batch(mesh, tokens)
    ref = {}
    state1, l1 = _run_steps(step1, state1, batch, 1)
    ref[1] = (l1, jax.tree.leaves(jax.device_get(state1.params)))
    state1, l4 = _run_steps(step1, state1, batch, 3)
    ref[4] = (l1 + l4, jax.tree.leaves(jax.device_get(state1.params)))

    for k in (1, 4):
        state2, step2 = tp.make_tp_multi_step(
            cfg, opt, mesh, _host_params(cfg), psa=psa, batch_shape=bshape)
        window = tp.shard_batch_window(
            mesh, np.broadcast_to(tokens, (k,) + tokens.shape))
        state2, losses = step2(state2, window)

        ref_losses, ref_leaves = ref[k]
        assert [float(x) for x in losses] == ref_losses, k
        for a, b in zip(ref_leaves,
                        jax.tree.leaves(jax.device_get(state2.params))):
            np.testing.assert_array_equal(a, b)


def test_tp_numerics_on_off_bitwise(devices):
    """Arming make_tp_numerics adds OUTPUTS only: losses and params are
    bitwise identical on vs off, and the summary is model-axis
    psum-agreed (replicated — every shard returns the same stats)."""
    cfg = _cfg()
    mesh = make_mesh({"model": 4}, devices=devices[:4])
    tokens = _tokens(cfg)
    opt = optax.adam(1e-3)

    state1, step1 = tp.make_tp_step(cfg, opt, mesh, _host_params(cfg))
    state1, l1 = _run_steps(step1, state1, tp.shard_batch(mesh, tokens), 2)

    numerics = tp.make_tp_numerics(_host_params(cfg), mesh)
    state2, step2 = tp.make_tp_step(cfg, opt, mesh, _host_params(cfg),
                                    numerics=numerics)
    l2 = []
    summary = None
    for _ in range(2):
        state2, (loss, summary) = step2(state2, tp.shard_batch(mesh, tokens))
        l2.append(float(loss))

    assert l1 == l2
    for a, b in zip(jax.tree.leaves(jax.device_get(state1.params)),
                    jax.tree.leaves(jax.device_get(state2.params))):
        np.testing.assert_array_equal(a, b)
    assert all(np.isfinite(x).all() for x in jax.tree.leaves(
        jax.device_get(summary)))


def test_tp_dp_overlap_replicas_bitwise_in_sync(devices):
    """DP×TP int8 ring + zero1: after 3 steps every replica of every
    param holds bitwise-identical values — data replicas because the int8
    delta gather applies the same quantized deltas everywhere (the
    compress.py zero1 rule), and MODEL replicas of the replicated leaves
    (norm scales) because the int8 scales are model-agreed
    (compress._int8_encode scale_sync_axis; without it each model cell's
    scale couples to its own col/row shard values and the replicated
    entries decode differently per cell)."""
    cfg = _cfg()
    mesh = make_mesh({"data": 2, "model": 4}, devices=devices[:8])
    tokens = _tokens(cfg, batch=8, seed=2)
    opt = optax.adam(1e-3)

    state, step = tp.make_tp_overlap_step(
        cfg, opt, mesh, _host_params(cfg), aggregation="zero1",
        wire="int8_ef", overlap_microbatches=2)
    state, losses = _run_steps(step, state, tp.shard_batch(mesh, tokens), 3)
    assert all(np.isfinite(losses))

    # embed is replicated over BOTH axes: all 8 addressable shards must
    # agree bitwise. Sharded leaves replicate over data only — the
    # per-device comparison below covers them via the full-array gather.
    embed = state.params["embed"]
    shards = [np.asarray(s.data) for s in embed.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)
    for leaf in jax.tree.leaves(state.params):
        by_index = {}
        for s in leaf.addressable_shards:
            # s.index is a tuple of slice objects (unhashable) — key on
            # the (start, stop) pairs instead.
            key = tuple((sl.start, sl.stop) for sl in s.index)
            by_index.setdefault(key, []).append(np.asarray(s.data))
        for group in by_index.values():
            for g in group[1:]:
                np.testing.assert_array_equal(group[0], g)


@pytest.mark.parametrize("driver", ["psa_step", "overlap"])
def test_tp_preempt_resume_bitwise_through_ef_residuals(devices, driver):
    """A host snapshot/restore mid-run (the preempt/resume cycle) is
    BITWISE invisible: the activation EF residuals (TPActState) and the
    ring/gather EF residuals (OverlapEFState) live in the state tree, so
    4 straight steps == 2 steps + snapshot + restore + 2 steps."""
    cfg = _cfg()
    opt = optax.adam(1e-3)
    if driver == "psa_step":
        mesh = make_mesh({"model": 4}, devices=devices[:4])
        tokens = _tokens(cfg)
        make = lambda: tp.make_tp_step(  # noqa: E731
            cfg, opt, mesh, _host_params(cfg), psa="int8_ef",
            batch_shape=(tokens.shape[0], cfg.ctx_size))
        batch = tp.shard_batch(mesh, tokens)
    else:
        mesh = make_mesh({"data": 2, "model": 4}, devices=devices[:8])
        tokens = _tokens(cfg, batch=8, seed=2)
        make = lambda: tp.make_tp_overlap_step(  # noqa: E731
            cfg, opt, mesh, _host_params(cfg), aggregation="zero1",
            wire="int8_ef", overlap_microbatches=1)
        batch = tp.shard_batch(mesh, tokens)

    state, step = make()
    state, straight = _run_steps(step, state, batch, 4)
    straight_params = jax.device_get(state.params)

    state2, step2 = make()
    state2, first = _run_steps(step2, state2, batch, 2)
    snapshot = jax.device_get(state2)          # host round-trip (orbax shape)
    template, step3 = make()                   # fresh program, fresh buffers
    restored = jax.tree.map(
        lambda h, t: jax.device_put(np.asarray(h), t.sharding),
        snapshot, template)
    restored, rest = _run_steps(step3, restored, batch, 2)

    assert first + rest == straight
    for a, b in zip(jax.tree.leaves(straight_params),
                    jax.tree.leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(a, b)


def test_tp_psa_named_errors(devices):
    """Unsupported PSA spellings and combinations die with NAMED errors,
    not shape mismatches deep in a trace."""
    cfg = _cfg()
    mesh = make_mesh({"model": 4}, devices=devices[:4])
    opt = optax.adam(1e-3)
    with pytest.raises(ValueError, match="divisible"):
        tp.make_tp_step(cfg, opt, mesh, _host_params(cfg), psa="defer:3")
    with pytest.raises(ValueError, match="psa"):
        tp.make_tp_step(cfg, opt, mesh, _host_params(cfg), psa="bogus")
    with pytest.raises(ValueError, match="batch_shape"):
        tp.make_tp_step(cfg, opt, mesh, _host_params(cfg), psa="int8_ef")
    mesh2 = make_mesh({"data": 2, "model": 4})
    with pytest.raises(ValueError, match="int8_ef"):
        tp.make_tp_overlap_step(cfg, opt, mesh2, _host_params(cfg),
                                aggregation="zero1", wire="int8_ef",
                                overlap_microbatches=1, psa="int8_ef")
    mesh3 = make_mesh({"data": 4}, devices=devices[:4])
    with pytest.raises(ValueError, match="model"):
        tp.make_tp_overlap_step(cfg, opt, mesh3, _host_params(cfg),
                                aggregation="zero1", wire="fp32",
                                overlap_microbatches=1)


def test_train_llm_tp_rejects_unsupported_levers(devices):
    """The TP trainer's validation wall (the test_train_llm_pp_rejects_
    dp_only_levers precedent): every combination the docs list as
    unsupported must hard-error at config time with a NAMED reason.
    PSA × elastic is no longer on the list (the remesh path resizes the
    activation EF residual trees now — tests/test_elastic.py); what
    remains named-unsupported is elastic × the DP×TP ring driver and
    elastic × numerics."""
    from ddl25spring_tpu.config import ResilienceConfig, TrainConfig
    from ddl25spring_tpu.tokenizers import ByteTokenizer
    from ddl25spring_tpu.train.llm import train_llm_tp

    cfg = _cfg()
    base = dict(batch_size=4, seq_len=16, iters=2, lr=3e-3, model=4)
    kw = dict(mesh=make_mesh({"model": 4}, devices=devices[:4]),
              tokenizer=ByteTokenizer(), log_every=0)
    with pytest.raises(ValueError, match="accum_steps"):
        train_llm_tp(cfg, TrainConfig(**base, accum_steps=4), **kw)
    with pytest.raises(ValueError, match="DP-trainer-only"):
        train_llm_tp(cfg, TrainConfig(**base, dcn=2, wire_dcn="int8_ef"),
                     **kw)
    with pytest.raises(ValueError, match="overlap_microbatches"):
        train_llm_tp(cfg, TrainConfig(**base, wire="int8_ef"), **kw)
    with pytest.raises(ValueError, match="ring driver"):
        train_llm_tp(cfg, TrainConfig(**base), aggregation="zero1", **kw)
    with pytest.raises(ValueError, match="ring driver"):
        train_llm_tp(cfg, TrainConfig(**base, overlap_microbatches=1),
                     aggregation="zero1",
                     resilience=ResilienceConfig(elastic=True), **kw)
    with pytest.raises(ValueError, match="numerics_every"):
        train_llm_tp(cfg, TrainConfig(**base, psa="int8_ef",
                                      numerics_every=1),
                     resilience=ResilienceConfig(elastic=True), **kw)
    with pytest.raises(ValueError, match="scale_hook"):
        train_llm_tp(cfg, TrainConfig(**base),
                     scale_hook=lambda *a: None, **kw)
    with pytest.raises(ValueError, match="injit_guard"):
        train_llm_tp(cfg, TrainConfig(**base),
                     resilience=ResilienceConfig(injit_guard=True), **kw)
