"""Sequence parallelism: ring attention and the SP train step.

Checks that sharding the sequence over the 8-device virtual mesh is
numerically equivalent to the single-device reference — same logits, same
loss, same training trajectory.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from ddl25spring_tpu.config import LlamaConfig
from ddl25spring_tpu.models import llama
from ddl25spring_tpu.ops import causal_lm_loss
from ddl25spring_tpu.parallel._compat import shard_map
from ddl25spring_tpu.parallel import make_mesh, sp


def _cfg(ctx=64):
    return LlamaConfig(vocab_size=128, dmodel=32, num_heads=4, n_layers=2,
                       ctx_size=ctx)


def test_ring_attention_matches_full():
    """ring_attention over 4 shards == full causal attention."""
    mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
    b, t, h, dh = 2, 64, 4, 16
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (b, t, h, dh), jnp.float32)
    k = jax.random.normal(kk, (b, t, h, dh), jnp.float32)
    v = jax.random.normal(kv, (b, t, h, dh), jnp.float32)

    ring = jax.jit(shard_map(
        lambda q, k, v: sp.ring_attention(q, k, v, "seq", causal=True),
        mesh=mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"),
        check_vma=False))
    out = ring(q, k, v)
    ref = llama._xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_sp_forward_matches_single_device():
    cfg = _cfg()
    mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
    params = llama.init_llama(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, cfg.ctx_size), 0,
                                cfg.vocab_size)
    out = sp.sp_forward(params, tokens, cfg, mesh)
    ref = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-3)


def test_sp_train_step_matches_single_device():
    """One SP train step == one single-device step: same loss, same params."""
    cfg = _cfg()
    mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
    params = llama.init_llama(jax.random.key(0), cfg)
    # SGD, not Adam: the param check must be linear in the gradients, or
    # m/sqrt(v) normalization amplifies float accumulation-order noise on
    # near-zero coordinates into percent-level param differences.
    opt = optax.sgd(0.1)
    tokens = jax.random.randint(jax.random.key(1), (2, cfg.ctx_size), 0,
                                cfg.vocab_size)

    # Reference first: the SP step donates its input state, which would
    # invalidate `params` buffers aliased into it.
    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: causal_lm_loss(llama.forward(p, tokens, cfg), tokens))(params)
    updates, _ = opt.update(ref_grads, opt.init(params), params)
    ref_params = optax.apply_updates(params, updates)

    state = sp.init_state(mesh, params, opt)
    step = sp.make_sp_train_step(cfg, opt, mesh)
    state, loss = step(state, sp.shard_batch(mesh, tokens))

    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4)


def test_sp_composes_with_dp():
    """(data=2, seq=4) mesh: DP×SP step matches single-device on the same
    global batch."""
    cfg = _cfg()
    mesh = make_mesh({"data": 2, "seq": 4})
    params = llama.init_llama(jax.random.key(0), cfg)
    opt = optax.adam(1e-3)
    tokens = jax.random.randint(jax.random.key(1), (4, cfg.ctx_size), 0,
                                cfg.vocab_size)

    ref_loss = causal_lm_loss(llama.forward(params, tokens, cfg), tokens)

    state = sp.init_state(mesh, params, opt)
    step = sp.make_sp_train_step(cfg, opt, mesh)
    state, loss = step(state, sp.shard_batch(mesh, tokens))

    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5, rtol=1e-5)
