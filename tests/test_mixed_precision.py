"""Mixed-precision (bf16 params + fp32 master) training — ops/mixed_precision.

Pins: master/moment dtypes, params staying on the downcast master, trajectory
agreement with full-fp32 Adam within bf16 resolution, the vanishing-update
failure mode the master weights exist to fix, and end-to-end bf16-param LLM
training through the dp step factory.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ddl25spring_tpu.ops.mixed_precision import master_weight_adam


def test_state_dtypes_and_param_tracking():
    params = {"w": jnp.linspace(-1, 1, 256).astype(jnp.bfloat16)}
    opt = master_weight_adam(1e-3)
    state = opt.init(params)
    assert state.master["w"].dtype == jnp.float32
    assert state.mu["w"].dtype == jnp.float32
    key = jax.random.key(0)
    for _ in range(5):
        key, sub = jax.random.split(key)
        grads = {"w": jax.random.normal(sub, (256,), jnp.bfloat16)}
        updates, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        # Params track the downcast master to <= 1 ulp (exact under
        # Sterbenz when consecutive values are within 2x, i.e. always for
        # Adam-sized steps; the subtract-then-add can round otherwise).
        np.testing.assert_allclose(
            np.asarray(params["w"], np.float32),
            np.asarray(state.master["w"].astype(jnp.bfloat16), np.float32),
            rtol=1e-2, atol=1e-6)


def test_matches_fp32_adam_within_bf16_resolution():
    w0 = jnp.linspace(-0.5, 0.5, 128)
    ref_opt = optax.adam(1e-2)
    mp_opt = master_weight_adam(1e-2)
    ref_p = {"w": w0}
    mp_p = {"w": w0.astype(jnp.bfloat16)}
    ref_s, mp_s = ref_opt.init(ref_p), mp_opt.init(mp_p)
    key = jax.random.key(1)
    for _ in range(10):
        key, sub = jax.random.split(key)
        g32 = jax.random.normal(sub, (128,))
        u, ref_s = ref_opt.update({"w": g32}, ref_s, ref_p)
        ref_p = optax.apply_updates(ref_p, u)
        u, mp_s = mp_opt.update({"w": g32.astype(jnp.bfloat16)}, mp_s, mp_p)
        mp_p = optax.apply_updates(mp_p, u)
    # The fp32 MASTER tracks the fp32 trajectory closely (bf16 only enters
    # through the gradients here); the bf16 params are its rounding.
    np.testing.assert_allclose(np.asarray(mp_s.master["w"]),
                               np.asarray(ref_p["w"]), atol=5e-3)


def test_master_prevents_vanishing_updates():
    """A relative step of ~2^-12 vanishes in pure-bf16 accumulation but
    must accumulate in the fp32 master: the reason the recipe exists."""
    p_bf16 = {"w": jnp.full((8,), 1.0, jnp.bfloat16)}
    tiny = 2.0 ** -12

    # Pure bf16: adding tiny to 1.0 rounds back to 1.0 (8-bit mantissa).
    assert float(jnp.bfloat16(1.0) + jnp.bfloat16(tiny)) == 1.0

    opt = master_weight_adam(learning_rate=tiny, b1=0.0, b2=0.0, eps=0.0)
    state = opt.init(p_bf16)
    params = p_bf16
    # With b1=b2=0 and unit gradients, each step moves the master by
    # exactly -tiny (Adam's m/sqrt(v) = 1). 600 steps accumulate ~0.146 —
    # far above bf16 resolution, so the params must eventually move.
    for _ in range(600):
        grads = {"w": jnp.ones((8,), jnp.bfloat16)}
        updates, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    assert float(state.master["w"][0]) < 1.0 - 0.1
    assert float(params["w"][0]) < 1.0  # the accumulated drift surfaced


def test_llm_end_to_end_bf16_params():
    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel import dp, make_mesh

    mesh = make_mesh({"data": 2})
    cfg = LlamaConfig(vocab_size=64, dmodel=16, num_heads=2, n_layers=2,
                      ctx_size=8, dtype="bfloat16", param_dtype="bfloat16")
    params = llama.init_llama(jax.random.key(0), cfg)
    assert jax.tree.leaves(params)[0].dtype == jnp.bfloat16
    opt = master_weight_adam(1e-3)
    state = dp.replicate(mesh, dp.init_state(params, opt))
    step = dp.make_grad_aggregation_step(
        lambda p, b: llama.forward_loss(p, b, cfg), opt, mesh)
    toks = jax.random.randint(jax.random.key(1), (4, 8), 0, 64)
    sb = dp.shard_batch(mesh, toks)
    losses = []
    for _ in range(10):
        state, loss = step(state, sb)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert jax.tree.leaves(state.params)[0].dtype == jnp.bfloat16
    assert jax.tree.leaves(state.opt_state.master)[0].dtype == jnp.float32