"""Serving fleet: SLO-aware router + live weight hot-swap (ISSUE 11).

The fleet's acceptance bars: every request's stream is bitwise
``generate()``'s at ANY router engine count (N ∈ {1, 3}) and across a
same-weights hot-swap; a new-weights swap changes ONLY tokens sampled
after the boundary; each engine keeps the two-programs/zero-retraces
contract across publishes; the train→deploy conveyor (CheckpointPublisher
→ publish dir → WeightPublisher) round-trips params through the
digest-verified checkpoint machinery; admission stays byte-for-byte FCFS
by default with size-aware "sjf" and priorities behind the knob; and the
schema-v6 route/deploy telemetry strict-validates.
"""

import itertools

import jax
import numpy as np
import pytest

from ddl25spring_tpu.config import LlamaConfig
from ddl25spring_tpu.models import llama
from ddl25spring_tpu.serving import (CheckpointPublisher, Engine,
                                     PagedKVConfig, Request, Scheduler,
                                     ServingFleet, TrafficClass,
                                     WeightPublisher, aggregate_latency,
                                     class_slos, multi_tenant_workload,
                                     reference_stream, run_serving_fleet,
                                     synthetic_workload)
from ddl25spring_tpu.telemetry.events import EventLog, read_events

CFG = LlamaConfig(vocab_size=97, dmodel=32, num_heads=4, n_layers=2,
                  ctx_size=32)
PAGED = PagedKVConfig(num_blocks=24, block_len=4, max_blocks_per_seq=8)


@pytest.fixture(scope="module")
def params():
    return llama.init_llama(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def params2():
    """Genuinely different weights (another init seed) for the
    new-weights hot-swap tests — same tree, same shapes."""
    return llama.init_llama(jax.random.PRNGKey(42), CFG)


class FakeClock:
    """Deterministic scheduler clock: advances only when told, so two
    driver runs see identical timestamps tick for tick."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _drive_fleet(params, requests, *, num_engines, swap_at_tick=None,
                 swap_params=None, num_slots=2, events=None,
                 admission="fcfs", policy="least_loaded"):
    """Deterministic driver: submit everything at t=0, tick to drain,
    optionally publishing at a fixed tick. Returns (records, prefix)
    where prefix[rid] = tokens emitted STRICTLY BEFORE the publish call
    — the "sampled before the boundary" set every swap test compares."""
    clock = FakeClock()
    fleet = ServingFleet(params, CFG, PAGED, num_engines=num_engines,
                         num_slots=num_slots, prefill_chunk=4,
                         events=events, clock=clock, admission=admission,
                         policy=policy)
    for r in requests:
        fleet.submit(r, now=0.0)
    prefix = {}
    tick = 0
    while fleet.outstanding or fleet.swap_pending:
        if swap_at_tick is not None and tick == swap_at_tick:
            prefix = {rid: list(rec.tokens)
                      for rid, rec in fleet.records.items()}
            fleet.publish(swap_params, version="test-swap")
        clock.t += 0.01
        fleet.tick()
        tick += 1
        assert tick < 500, "fleet failed to drain"
    return fleet, prefix


def _workload(seed, n=8):
    return synthetic_workload(seed=seed, n_requests=n, rate_rps=500.0,
                              vocab_size=CFG.vocab_size,
                              prompt_lens=(2, 5, 9), max_news=(3, 5, 8),
                              temperatures=(0.0, 0.7))


# ------------------------------------------------------------------ routing

def test_fleet_streams_bitwise_vs_generate_any_engine_count(params):
    """The headline bar: every request's stream equals generate()'s at
    equal seed regardless of the router's engine count — routing (like
    slot placement and batch company) is a latency decision only."""
    wl = _workload(3, n=10)
    reps = {n: run_serving_fleet(params, CFG, PAGED, wl, num_engines=n,
                                 num_slots=2, prefill_chunk=4,
                                 policy="predicted_ttft")
            for n in (1, 3)}
    for req in wl:
        want = reference_stream(params, CFG, PAGED, req)
        for n, rep in reps.items():
            assert rep.records[req.rid].tokens == want, (req.rid, n)
    # And per-engine budgets: two programs each, zero retraces.
    assert reps[3].compiles == [2, 2, 2]
    assert reps[3].retraces == [0, 0, 0]


def test_router_least_loaded_spreads_deterministically(params):
    """Idle engines tie-break by id, load counts break ties after — the
    first N submissions land round-robin on engines 0..N-1."""
    reqs = [Request(rid=f"r{i}", prompt=(1, 2, 3), max_new=2)
            for i in range(6)]
    clock = FakeClock()
    fleet = ServingFleet(params, CFG, PAGED, num_engines=3, num_slots=4,
                         prefill_chunk=4, clock=clock)
    picks = [fleet.submit(r, now=0.0) for r in reqs]
    assert picks == [0, 1, 2, 0, 1, 2]
    while fleet.outstanding:
        fleet.tick()


def test_router_predicted_ttft_prefers_unloaded_engine(params):
    """With equal TTFT windows, the queue-depth scaling must route away
    from a loaded engine."""
    from ddl25spring_tpu.serving.fleet import Router
    clock = FakeClock()
    fleet = ServingFleet(params, CFG, PAGED, num_engines=2, num_slots=2,
                         prefill_chunk=4, clock=clock,
                         policy="predicted_ttft")
    router: Router = fleet.router
    # Seed identical rolling windows, then load engine 0.
    router._ttft[0].append((0.0, 0.1))
    router._ttft[1].append((0.0, 0.1))
    fleet.scheds[0].submit(Request(rid="busy", prompt=(1, 2), max_new=4),
                           now=0.0)
    assert router.predicted_ttft(0) > router.predicted_ttft(1)
    eid = fleet.submit(Request(rid="new", prompt=(1, 2), max_new=2),
                       now=0.0)
    assert eid == 1
    while fleet.outstanding:
        fleet.tick()


# ----------------------------------------------------------- weight hot-swap

def test_same_weights_hot_swap_is_bitwise_invisible(params):
    """Satellite bar: a same-weights publish mid-stream leaves EVERY
    request's token stream bitwise identical to the no-swap run — across
    a 2-engine fleet with the staggered rollout landing mid-decode."""
    wl = _workload(7, n=8)
    base, _ = _drive_fleet(params, wl, num_engines=2)
    swapped, _ = _drive_fleet(params, wl, num_engines=2, swap_at_tick=3,
                              swap_params=params)
    for r in wl:
        assert (swapped.records[r.rid].tokens
                == base.records[r.rid].tokens), r.rid
    assert [d["engine"] for d in swapped.deploys] == [0, 1]
    # The swap is data, never a shape: still two programs, zero retraces.
    assert swapped.compiles() == [2, 2] and swapped.retraces() == [0, 0]


def test_new_weights_hot_swap_changes_only_post_boundary_tokens(params,
                                                                params2):
    """Satellite bar: a new-weights swap changes ONLY tokens sampled
    after the boundary — everything emitted before the publish is bitwise
    the no-swap run's, counts stay exact, and the engine never retraces."""
    wl = _workload(11, n=6)
    base, _ = _drive_fleet(params, wl, num_engines=1, num_slots=3)
    swapped, prefix = _drive_fleet(params, wl, num_engines=1, num_slots=3,
                                   swap_at_tick=4, swap_params=params2)
    assert prefix, "swap fired before anything was emitted is a weak test"
    changed = 0
    for r in wl:
        got = swapped.records[r.rid].tokens
        want = base.records[r.rid].tokens
        pre = prefix.get(r.rid, [])
        assert len(got) == len(want) == r.max_new
        # Nothing sampled before the boundary moved...
        assert got[:len(pre)] == want[:len(pre)], r.rid
        assert pre == want[:len(pre)], r.rid
        changed += got != want
    # ...and the new weights demonstrably took effect downstream (6
    # requests × several post-boundary tokens over a 97-token vocab:
    # an all-equal outcome means the swap silently didn't happen).
    assert changed > 0
    assert swapped.compiles() == [2] and swapped.retraces() == [0]


def test_swap_params_rejects_mismatched_tree(params):
    eng = Engine(params, CFG, PAGED, 1)
    bad = jax.tree.map(lambda x: x[..., None], params)
    with pytest.raises(ValueError, match="leaf mismatch|tree structure"):
        eng.swap_params(bad)


def test_bad_publish_fails_atomically_fleet_stays_serviceable(params):
    """A structure-equal but wrong-shaped publish must fail AT publish(),
    with no engine swapped, no rollout pending, and the fleet still able
    to serve and accept a good publish afterwards."""
    wl = _workload(17, n=4)
    clock = FakeClock()
    fleet = ServingFleet(params, CFG, PAGED, num_engines=2, num_slots=2,
                         prefill_chunk=4, clock=clock)
    for r in wl:
        fleet.submit(r, now=0.0)
    fleet.tick()
    bad = jax.tree.map(lambda x: x[..., :1], params)   # same tree, wrong
    with pytest.raises(ValueError, match="leaf mismatch"):
        fleet.publish(bad, version="bad")
    assert not fleet.swap_pending and fleet.deploys == []
    fleet.publish(params, version="good")              # fleet untouched
    while fleet.outstanding or fleet.swap_pending:
        clock.t += 0.01
        fleet.tick()
    assert [d["version"] for d in fleet.deploys] == ["good", "good"]
    for r in wl:
        assert fleet.records[r.rid].tokens == reference_stream(
            params, CFG, PAGED, r), r.rid


def test_publish_while_rollout_pending_raises(params):
    fleet = ServingFleet(params, CFG, PAGED, num_engines=2, num_slots=1,
                         prefill_chunk=4, clock=FakeClock())
    fleet.publish(params, version=1)
    with pytest.raises(RuntimeError, match="still rolling out"):
        fleet.publish(params, version=2)
    fleet.tick(), fleet.tick()          # drain the rollout
    fleet.publish(params, version=2)    # now legal again


# ------------------------------------------------------------ train→deploy

def test_weight_publisher_roundtrip_and_staleness(params, params2,
                                                  tmp_path):
    """CheckpointPublisher → publish dir → WeightPublisher: the restored
    tree is bitwise the published one (digest-verified,
    restore-at-saved-shapes machinery), a re-poll with nothing new
    returns None, and a newer publication supersedes."""
    pub_dir = str(tmp_path / "publish")
    with CheckpointPublisher(pub_dir, log_fn=lambda *_: None) as pub:
        pub(100, params2)
        assert pub.published == [100]
    wp = WeightPublisher(pub_dir, params)
    step, got = wp.poll()
    assert step == 100
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(params2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert wp.poll() is None            # nothing new
    with CheckpointPublisher(pub_dir, log_fn=lambda *_: None) as pub:
        pub(200, params)
    step2, _ = wp.poll()
    assert step2 == 200


def test_weight_publisher_publish_to_fleet_swaps_all_engines(params,
                                                             params2,
                                                             tmp_path):
    pub_dir = str(tmp_path / "publish")
    with CheckpointPublisher(pub_dir, log_fn=lambda *_: None) as pub:
        pub(7, params2)
    fleet = ServingFleet(params, CFG, PAGED, num_engines=2, num_slots=1,
                         prefill_chunk=4, clock=FakeClock())
    wp = WeightPublisher(pub_dir, params)
    assert wp.publish_to(fleet) == 7
    while fleet.swap_pending:
        fleet.tick()
    for eng in fleet.engines:
        for a, b in zip(jax.tree.leaves(eng.params),
                        jax.tree.leaves(params2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert wp.publish_to(fleet) is None   # stale: no second rollout


def test_trainer_on_checkpoint_hook_publishes(tmp_path):
    """The train/llm.py publication hook: periodic + final saves each
    publish a params-only step the serving side can poll — the
    train→deploy loop closed end to end."""
    from ddl25spring_tpu.config import TrainConfig
    from ddl25spring_tpu.train.llm import train_llm_dp

    model_cfg = LlamaConfig(vocab_size=128, dmodel=16, num_heads=2,
                            n_layers=2, ctx_size=16)
    pub_dir = str(tmp_path / "publish")
    pub = CheckpointPublisher(pub_dir, log_fn=lambda *_: None)
    train_llm_dp(model_cfg, TrainConfig(iters=4, batch_size=2, seq_len=16,
                                        seed=3),
                 log_every=0, warmup_steps_excluded=1,
                 checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
                 on_checkpoint=pub)
    pub.close()
    assert pub.published == [2, 4]
    # The trainer swaps in the tokenizer's vocab size; the serving
    # template must be built at the TRAINED shapes.
    from ddl25spring_tpu.tokenizers import load_tokenizer
    template = llama.init_llama(
        jax.random.PRNGKey(9),
        model_cfg.replace(vocab_size=load_tokenizer().vocab_size))
    step, got = WeightPublisher(pub_dir, template).poll()
    assert step == 4
    # The published tree is the TRAINED params (moved off the template's
    # fresh init), finite everywhere, template-shaped.
    leaves = jax.tree.leaves(got)
    assert all(np.isfinite(np.asarray(x)).all() for x in leaves)
    assert any(not np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(leaves, jax.tree.leaves(template)))


def test_broken_publication_hook_never_sinks_training(tmp_path):
    from ddl25spring_tpu.config import TrainConfig
    from ddl25spring_tpu.train.llm import train_llm_dp

    model_cfg = LlamaConfig(vocab_size=128, dmodel=16, num_heads=2,
                            n_layers=2, ctx_size=16)
    calls = []

    def hook(step, state):
        calls.append(step)
        raise RuntimeError("publisher down")

    report = train_llm_dp(model_cfg,
                          TrainConfig(iters=4, batch_size=2, seq_len=16,
                                      seed=3),
                          log_every=0, warmup_steps_excluded=1,
                          checkpoint_dir=str(tmp_path / "ck"),
                          checkpoint_every=2, on_checkpoint=hook,
                          log_fn=lambda *_: None)
    assert calls == [2, 4] and len(report.losses) == 4


# ----------------------------------------------------- admission policy seam

def _lockstep(params, requests, admission, *, num_slots=3, paged=PAGED):
    clock = FakeClock()
    eng = Engine(params, CFG, paged, num_slots, prefill_chunk=4)
    sched = Scheduler(eng, clock=clock, admission=admission)
    for r in requests:
        sched.submit(r, now=0.0)
    trace = []
    while sched.outstanding:
        clock.t += 0.01
        sched.tick()
        trace.append(sorted(r.rid for r in sched._by_slot.values()))
        assert len(trace) < 300
    return sched, trace


def test_fcfs_mode_byte_for_byte_unchanged(params):
    """Satellite pin: admission='fcfs' (the default) admits, batches and
    emits EXACTLY as the pre-knob scheduler — same in-flight sets at
    every boundary, same tokens, same admit timestamps."""
    wl = _workload(13, n=8)
    default, trace_d = _lockstep(params, wl, "fcfs")
    explicit = Scheduler(Engine(params, CFG, PAGED, 3, prefill_chunk=4),
                         clock=FakeClock())
    assert explicit.policy == "fcfs"     # the default IS fcfs
    again, trace_a = _lockstep(params, wl, "fcfs")
    assert trace_d == trace_a
    for r in wl:
        assert (default.records[r.rid].tokens
                == again.records[r.rid].tokens
                == reference_stream(params, CFG, PAGED, r)), r.rid
        assert (default.records[r.rid].admit_t
                == again.records[r.rid].admit_t)


def test_sjf_admits_shortest_when_head_blocks(params):
    """Size-aware admission (ROADMAP 2c): when the head's reservation
    doesn't fit but a smaller same-priority request's does, sjf admits
    the small one; fcfs keeps it waiting. Streams stay bitwise either
    way — admission order is a latency decision."""
    tiny = PagedKVConfig(num_blocks=9, block_len=4, max_blocks_per_seq=8)
    holder = Request(rid="hold", prompt=tuple(range(2, 10)), max_new=9)
    big = Request(rid="big", prompt=tuple(range(3, 11)), max_new=10)
    small = Request(rid="small", prompt=(5, 6), max_new=2)
    # holder: 16 positions = 4 blocks of the 8 allocatable; big: 17
    # positions = 5 blocks (blocked while holder runs); small: 1 block.
    for admission, small_jumps in (("fcfs", False), ("sjf", True)):
        clock = FakeClock()
        eng = Engine(params, CFG, tiny, 3, prefill_chunk=4)
        sched = Scheduler(eng, clock=clock, admission=admission)
        sched.submit(holder, now=0.0)
        clock.t = 0.1
        sched.tick()                       # holder admitted + prefilling
        assert sched.records["hold"].admit_t is not None
        sched.submit(big, now=0.2)
        sched.submit(small, now=0.2)
        clock.t = 0.3
        sched.tick()
        admitted_small = sched.records["small"].admit_t is not None
        assert admitted_small == small_jumps, admission
        assert sched.records["big"].admit_t is None     # blocked either way
        while sched.outstanding:
            sched.tick()
        for r in (holder, big, small):
            assert sched.records[r.rid].tokens == reference_stream(
                params, CFG, tiny, r), (admission, r.rid)


def test_priority_admits_before_earlier_lower_priority(params):
    """A higher-priority request enqueued LATER admits first once a slot
    frees — and with all priorities equal the order is pure FCFS."""
    clock = FakeClock()
    eng = Engine(params, CFG, PAGED, 1, prefill_chunk=8)
    sched = Scheduler(eng, clock=clock)
    sched.submit(Request(rid="hold", prompt=(1, 2, 3), max_new=3), now=0.0)
    clock.t = 0.1
    sched.tick()
    sched.submit(Request(rid="lo", prompt=(2, 3), max_new=2, priority=0),
                 now=0.1)
    sched.submit(Request(rid="hi", prompt=(3, 4), max_new=2, priority=1),
                 now=0.2)
    while sched.outstanding:
        clock.t += 0.1
        sched.tick()
    assert (sched.records["hi"].admit_t
            < sched.records["lo"].admit_t)


# ------------------------------------------------- frontend + telemetry v6

def test_aggregate_latency_empty_and_single_are_well_formed():
    """Satellite pin: empty and single-request windows return the FULL
    record shape (counts + None percentiles), no caller special-casing."""
    empty = aggregate_latency({})
    assert empty["completed"] == 0 and empty["total_tokens"] == 0
    assert empty["sustained_tokens_per_sec"] is None
    for key in ("queue_wait_s", "ttft_s", "request_tokens_per_sec"):
        assert empty[key] == {"p50": None, "p95": None, "p99": None}
    from ddl25spring_tpu.serving import RequestRecord
    rec = RequestRecord(rid="r", prompt_len=3, max_new=2, enqueue_t=0.0,
                        admit_t=0.5, first_token_t=1.0, done_t=2.0,
                        tokens=[4, 5])
    one = aggregate_latency({"r": rec})
    assert one["completed"] == 1
    assert one["ttft_s"]["p50"] == one["ttft_s"]["p99"] == 1.0
    assert one["sustained_tokens_per_sec"] == pytest.approx(2 / 1.5)


def test_multi_tenant_workload_deterministic_and_tagged():
    classes = (TrafficClass("chat", 50.0, priority=1, ttft_p99_s=1.0),
               TrafficClass("batch", 10.0, queue_p99_s=5.0))
    a = multi_tenant_workload(seed=4, classes=classes, n_per_class=5,
                              vocab_size=64)
    b = multi_tenant_workload(seed=4, classes=classes, n_per_class=5,
                              vocab_size=64)
    assert a == b and len(a) == 10
    assert all(x.arrival <= y.arrival for x, y in zip(a, a[1:]))
    by_cls = {k: list(v) for k, v in itertools.groupby(
        sorted(a, key=lambda r: r.tenant), key=lambda r: r.tenant)}
    assert set(by_cls) == {"chat", "batch"}
    assert all(r.priority == 1 and r.rid.startswith("chat-")
               for r in by_cls["chat"])
    assert class_slos(classes) == {"chat": {"ttft_p99_s": 1.0},
                                   "batch": {"queue_p99_s": 5.0}}
    # Per-class counts as a mapping, and child streams are seed-stable
    # under class-list extension (each class draws its own child seed).
    c = multi_tenant_workload(seed=4, classes=classes,
                              n_per_class={"chat": 2, "batch": 1},
                              vocab_size=64)
    assert sum(r.tenant == "chat" for r in c) == 2


def test_fleet_stream_schema_v6_strict_and_engine_tagged(params, tmp_path):
    """The fleet's telemetry strict-validates (route/deploy required
    fields, engine/tenant tags), carries one route per request and one
    deploy per engine, and obs_report renders the per-engine grouping."""
    wl = _workload(5, n=6)
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="fleet") as log:
        fleet, _ = _drive_fleet(params, wl, num_engines=2, swap_at_tick=2,
                                swap_params=params, events=log)
    events = read_events(path, strict=True)      # validates schema v6
    routes = [e for e in events if e["type"] == "route"]
    deploys = [e for e in events if e["type"] == "deploy"]
    assert {e["req"] for e in routes} == {r.rid for r in wl}
    assert sorted(e["engine"] for e in deploys) == [0, 1]
    assert all(e["version"] == "test-swap" for e in deploys)
    done = [e for e in events if e["type"] == "request_done"]
    assert all(e.get("engine") in (0, 1) and isinstance(e.get("tenant"),
                                                        str)
               for e in done)
    # Every request's engine tag agrees with the router's decision.
    route_of = {e["req"]: e["engine"] for e in routes}
    assert all(route_of[e["req"]] == e["engine"] for e in done)
    # deploy spans exist for the Perfetto export path.
    assert any(e["type"] == "span" and e.get("name") == "deploy"
               for e in events)


def test_obs_report_groups_serving_by_engine(params, tmp_path, capsys):
    from experiments.obs_report import report_run
    wl = _workload(9, n=6)
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="fleet") as log:
        _drive_fleet(params, wl, num_engines=2, swap_at_tick=2,
                     swap_params=params, events=log)
    report_run(read_events(path))
    out = capsys.readouterr().out
    assert "engine 0:" in out and "engine 1:" in out
    assert "deploy version test-swap" in out
    assert "routed: 6 requests" in out


def test_slo_monitor_per_class_verdicts():
    """Per-class rolling windows: a class over ITS threshold breaches as
    '<class>:ttft_p99_s' while the other class (and the un-SLO'd global
    view) stays clean; the breakdown groups by class and engine."""
    from experiments.slo_monitor import SLOConfig, SLOMonitor
    cfg = SLOConfig(window_s=30.0,
                    per_class={"chat": {"ttft_p99_s": 0.2},
                               "batch": {"ttft_p99_s": 10.0}})
    mon = SLOMonitor(cfg)
    for i in range(6):
        mon.feed([{"type": "request_done", "t": float(i), "req": f"c{i}",
                   "tokens": 4, "ttft_s": 0.5, "queue_wait_s": 0.1,
                   "tenant": "chat", "engine": i % 2}])
        mon.feed([{"type": "request_done", "t": float(i), "req": f"b{i}",
                   "tokens": 4, "ttft_s": 1.0, "queue_wait_s": 0.1,
                   "tenant": "batch", "engine": i % 2}])
    fresh = mon.evaluate(6.0)
    assert [v["slo"] for v in fresh] == ["chat:ttft_p99_s"]
    bd = mon.breakdown()
    assert bd["per_class"]["chat"]["done"] == 6
    assert bd["per_class"]["batch"]["ttft_p99_s"] == 1.0
    assert set(bd["per_engine"]) == {"0", "1"}
    assert bd["per_engine"]["0"]["done"] == 6


def test_slo_monitor_class_slo_cli_parsing():
    from experiments.slo_monitor import parse_class_slo
    assert parse_class_slo(["chat:ttft_p99=0.5,queue_p99=2"]) == {
        "chat": {"ttft_p99_s": 0.5, "queue_p99_s": 2.0}}
    assert parse_class_slo(None) is None
    with pytest.raises(ValueError, match="unknown objective"):
        parse_class_slo(["chat:nope=1"])
