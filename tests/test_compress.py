"""Compressed gradient all-reduce (parallel/compress.py).

Pins: (1) the bf16-wire step tracks the uncompressed step closely; (2) the
collective really runs in the compressed dtype (jaxpr evidence — the test
that would catch a silent decay to an fp32 wire); (3) int8+error-feedback
converges where naive int8 stalls, and its residual is exactly the
quantization remainder; (4) both steps train a real model end to end on the
virtual mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.parallel import compress, dp, make_mesh


def _mesh2():
    return make_mesh({"data": 2})


def _quadratic_setup(key, dim=64):
    # Convex problem with a known optimum at w*: loss = mean((x@w - y)^2).
    k1, k2, k3 = jax.random.split(key, 3)
    w_star = jax.random.normal(k1, (dim,))
    x = jax.random.normal(k2, (256, dim))
    y = x @ w_star
    params = {"w": jnp.zeros((dim,))}

    def loss_fn(p, batch):
        xb, yb = batch[..., :-1], batch[..., -1]
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    batch = jnp.concatenate([x, y[:, None]], axis=-1)
    return params, loss_fn, batch, w_star


def test_bf16_step_tracks_uncompressed():
    mesh = _mesh2()
    params, loss_fn, batch, _ = _quadratic_setup(jax.random.key(0))
    opt = optax.sgd(0.05)

    s_ref = dp.replicate(mesh, dp.init_state(
        jax.tree.map(jnp.copy, params), opt))
    s_bf = dp.replicate(mesh, dp.init_state(
        jax.tree.map(jnp.copy, params), opt))
    step_ref = dp.make_grad_aggregation_step(loss_fn, opt, mesh)
    step_bf = compress.make_bf16_grad_step(loss_fn, opt, mesh)
    sb = dp.shard_batch(mesh, batch)
    for _ in range(20):
        s_ref, l_ref = step_ref(s_ref, sb)
        s_bf, l_bf = step_bf(s_bf, sb)
    # bf16 has ~3 decimal digits; over 20 steps the trajectories stay close.
    np.testing.assert_allclose(float(l_bf), float(l_ref), rtol=0.05)
    np.testing.assert_allclose(np.asarray(s_bf.params["w"]),
                               np.asarray(s_ref.params["w"]), atol=0.02)


def test_wire_dtypes_in_compiled_program():
    """The compressed collectives must actually move compressed elements:
    the bf16 step's gradient pmean operand is bf16, and the int8 step's one
    gradient collective is an all_gather whose operand is int8 (the int32
    sum is local arithmetic, not a collective) — not fp32 gradients."""
    mesh = _mesh2()
    params, loss_fn, batch, _ = _quadratic_setup(jax.random.key(1))
    opt = optax.sgd(0.05)

    jaxpr = str(jax.make_jaxpr(
        lambda s, b: compress.make_bf16_grad_step(loss_fn, opt, mesh)(s, b))(
            dp.replicate(mesh, dp.init_state(params, opt)),
            dp.shard_batch(mesh, batch)))
    assert "bf16[65]" in jaxpr.replace("bfloat16", "bf16") or \
        "bf16[64]" in jaxpr.replace("bfloat16", "bf16"), \
        "no bf16 gradient collective found in the bf16-wire step"

    # Two leaves: the payload must ride ONE concatenated all_gather, not
    # one collective per leaf.
    params = {**params, "extra": jnp.zeros((32,))}
    state = compress.init_ef_state(mesh, params, opt)
    jaxpr8 = str(jax.make_jaxpr(
        lambda s, b: compress.make_int8_ef_grad_step(loss_fn, opt, mesh)(s, b))(
            state, dp.shard_batch(mesh, batch)))
    import re
    n_gathers = len(re.findall(r"= all_gather\[", jaxpr8))
    assert n_gathers == 1, \
        f"expected one concatenated all_gather eqn, found {n_gathers}"
    # The gradient's collective is an all_gather of an i8 operand...
    assert re.search(r"all_gather\S*\s[a-z]+:i8\[", jaxpr8) or \
        re.search(r":i8\[64\][^\n]*\n[^\n]*all_gather", jaxpr8) or \
        ("all_gather" in jaxpr8 and "i8[64]" in jaxpr8), \
        "no int8 all_gather found in the int8-EF step"
    # ...and no gradient-sized int32 (or fp32-gradient) psum exists: the
    # only psum operands are the scalar loss / scale reductions.
    for m in re.finditer(r"(psum|pmax|pmin)[^\n]*", jaxpr8):
        assert "i32[64]" not in m.group(0) and "f32[64]" not in m.group(0), \
            f"gradient-sized reduction on the wire: {m.group(0)}"


def test_int8_ef_residual_is_quantization_remainder():
    mesh = _mesh2()
    params, loss_fn, batch, _ = _quadratic_setup(jax.random.key(2))
    opt = optax.sgd(0.0)  # lr 0: params frozen, residual pure quantization
    state = compress.init_ef_state(mesh, params, opt)
    step = compress.make_int8_ef_grad_step(loss_fn, opt, mesh)
    state, _ = step(state, dp.shard_batch(mesh, batch))
    # |residual| <= s/2 elementwise, s = pmax|c|/127: remainder of rounding.
    res = np.asarray(jax.device_get(state.residual["w"]))
    assert res.shape[0] == 2
    # Reconstruct the SHARED scale (pmax over both shards' c = g + 0).
    grads = []
    for shard in range(2):
        sb = np.asarray(batch).reshape(2, -1, batch.shape[-1])[shard]
        xb, yb = sb[:, :-1], sb[:, -1]
        grads.append(2 * xb.T @ (xb @ np.zeros(64) - yb) / len(sb))
    s = max(np.abs(g).max() for g in grads) / 127.0
    assert np.abs(res).max() <= s * 0.51 + 1e-12


def test_int8_ef_converges_on_quadratic():
    mesh = _mesh2()
    params, loss_fn, batch, w_star = _quadratic_setup(jax.random.key(3))
    opt = optax.sgd(0.05)
    state = compress.init_ef_state(mesh, params, opt)
    step = compress.make_int8_ef_grad_step(loss_fn, opt, mesh)
    sb = dp.shard_batch(mesh, batch)
    losses = []
    for _ in range(60):
        state, loss = step(state, sb)
        losses.append(float(loss))
    assert losses[-1] < 1e-2 * losses[0], (losses[0], losses[-1])


@pytest.mark.parametrize("maker", ["bf16", "int8"])
def test_llm_end_to_end(maker):
    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama

    mesh = _mesh2()
    cfg = LlamaConfig(vocab_size=64, dmodel=16, num_heads=2, n_layers=2,
                      ctx_size=8)
    params = llama.init_llama(jax.random.key(0), cfg)
    opt = optax.adam(1e-3)

    def loss_fn(p, b):
        return llama.forward_loss(p, b, cfg)

    if maker == "bf16":
        state = dp.replicate(mesh, dp.init_state(params, opt))
        step = compress.make_bf16_grad_step(loss_fn, opt, mesh)
    else:
        state = compress.init_ef_state(mesh, params, opt)
        step = compress.make_int8_ef_grad_step(loss_fn, opt, mesh)
    toks = jax.random.randint(jax.random.key(1), (4, 8), 0, 64)
    sb = dp.shard_batch(mesh, toks)
    first = None
    for _ in range(10):
        state, loss = step(state, sb)
        first = first if first is not None else float(loss)
    assert float(loss) < first
