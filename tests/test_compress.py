"""Compressed gradient all-reduce (parallel/compress.py).

Pins: (1) the bf16-wire step tracks the uncompressed step closely; (2) the
collective really runs in the compressed dtype (jaxpr evidence — the test
that would catch a silent decay to an fp32 wire); (3) int8+error-feedback
converges where naive int8 stalls, and its residual is exactly the
quantization remainder; (4) both steps train a real model end to end on the
virtual mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.parallel import compress, dp, make_mesh


def _mesh2():
    return make_mesh({"data": 2})


def _quadratic_setup(key, dim=64):
    # Convex problem with a known optimum at w*: loss = mean((x@w - y)^2).
    k1, k2, k3 = jax.random.split(key, 3)
    w_star = jax.random.normal(k1, (dim,))
    x = jax.random.normal(k2, (256, dim))
    y = x @ w_star
    params = {"w": jnp.zeros((dim,))}

    def loss_fn(p, batch):
        xb, yb = batch[..., :-1], batch[..., -1]
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    batch = jnp.concatenate([x, y[:, None]], axis=-1)
    return params, loss_fn, batch, w_star


def test_bf16_step_tracks_uncompressed():
    mesh = _mesh2()
    params, loss_fn, batch, _ = _quadratic_setup(jax.random.key(0))
    opt = optax.sgd(0.05)

    s_ref = dp.replicate(mesh, dp.init_state(
        jax.tree.map(jnp.copy, params), opt))
    s_bf = dp.replicate(mesh, dp.init_state(
        jax.tree.map(jnp.copy, params), opt))
    step_ref = dp.make_grad_aggregation_step(loss_fn, opt, mesh)
    step_bf = compress.make_bf16_grad_step(loss_fn, opt, mesh)
    sb = dp.shard_batch(mesh, batch)
    for _ in range(20):
        s_ref, l_ref = step_ref(s_ref, sb)
        s_bf, l_bf = step_bf(s_bf, sb)
    # bf16 has ~3 decimal digits; over 20 steps the trajectories stay close.
    np.testing.assert_allclose(float(l_bf), float(l_ref), rtol=0.05)
    np.testing.assert_allclose(np.asarray(s_bf.params["w"]),
                               np.asarray(s_ref.params["w"]), atol=0.02)


def test_wire_dtypes_in_compiled_program():
    """The compressed collectives must actually move compressed elements:
    the bf16 step's gradient pmean operand is bf16, and the int8 step's one
    gradient collective is an all_gather whose operand is int8 (the int32
    sum is local arithmetic, not a collective) — not fp32 gradients."""
    mesh = _mesh2()
    params, loss_fn, batch, _ = _quadratic_setup(jax.random.key(1))
    opt = optax.sgd(0.05)

    jaxpr = str(jax.make_jaxpr(
        lambda s, b: compress.make_bf16_grad_step(loss_fn, opt, mesh)(s, b))(
            dp.replicate(mesh, dp.init_state(params, opt)),
            dp.shard_batch(mesh, batch)))
    assert "bf16[65]" in jaxpr.replace("bfloat16", "bf16") or \
        "bf16[64]" in jaxpr.replace("bfloat16", "bf16"), \
        "no bf16 gradient collective found in the bf16-wire step"

    # Two leaves: the payload must ride ONE concatenated all_gather, not
    # one collective per leaf.
    params = {**params, "extra": jnp.zeros((32,))}
    state = compress.init_ef_state(mesh, params, opt)
    jaxpr8 = str(jax.make_jaxpr(
        lambda s, b: compress.make_int8_ef_grad_step(loss_fn, opt, mesh)(s, b))(
            state, dp.shard_batch(mesh, batch)))
    import re
    n_gathers = len(re.findall(r"= all_gather\[", jaxpr8))
    assert n_gathers == 1, \
        f"expected one concatenated all_gather eqn, found {n_gathers}"
    # The gradient's collective is an all_gather of an i8 operand...
    assert re.search(r"all_gather\S*\s[a-z]+:i8\[", jaxpr8) or \
        re.search(r":i8\[64\][^\n]*\n[^\n]*all_gather", jaxpr8) or \
        ("all_gather" in jaxpr8 and "i8[64]" in jaxpr8), \
        "no int8 all_gather found in the int8-EF step"
    # ...and no gradient-sized int32 (or fp32-gradient) psum exists: the
    # only psum operands are the scalar loss / scale reductions.
    for m in re.finditer(r"(psum|pmax|pmin)[^\n]*", jaxpr8):
        assert "i32[64]" not in m.group(0) and "f32[64]" not in m.group(0), \
            f"gradient-sized reduction on the wire: {m.group(0)}"


def test_int8_ef_residual_is_quantization_remainder():
    mesh = _mesh2()
    params, loss_fn, batch, _ = _quadratic_setup(jax.random.key(2))
    opt = optax.sgd(0.0)  # lr 0: params frozen, residual pure quantization
    state = compress.init_ef_state(mesh, params, opt)
    step = compress.make_int8_ef_grad_step(loss_fn, opt, mesh)
    state, _ = step(state, dp.shard_batch(mesh, batch))
    # |residual| <= s/2 elementwise, s = pmax|c|/127: remainder of rounding.
    res = np.asarray(jax.device_get(state.residual["w"]))
    assert res.shape[0] == 2
    # Reconstruct the SHARED scale (pmax over both shards' c = g + 0).
    grads = []
    for shard in range(2):
        sb = np.asarray(batch).reshape(2, -1, batch.shape[-1])[shard]
        xb, yb = sb[:, :-1], sb[:, -1]
        grads.append(2 * xb.T @ (xb @ np.zeros(64) - yb) / len(sb))
    s = max(np.abs(g).max() for g in grads) / 127.0
    assert np.abs(res).max() <= s * 0.51 + 1e-12


def test_int8_ef_converges_on_quadratic():
    mesh = _mesh2()
    params, loss_fn, batch, w_star = _quadratic_setup(jax.random.key(3))
    opt = optax.sgd(0.05)
    state = compress.init_ef_state(mesh, params, opt)
    step = compress.make_int8_ef_grad_step(loss_fn, opt, mesh)
    sb = dp.shard_batch(mesh, batch)
    losses = []
    for _ in range(60):
        state, loss = step(state, sb)
        losses.append(float(loss))
    assert losses[-1] < 1e-2 * losses[0], (losses[0], losses[-1])


@pytest.mark.parametrize("maker", ["bf16", "int8"])
def test_llm_end_to_end(maker):
    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama

    mesh = _mesh2()
    cfg = LlamaConfig(vocab_size=64, dmodel=16, num_heads=2, n_layers=2,
                      ctx_size=8)
    params = llama.init_llama(jax.random.key(0), cfg)
    opt = optax.adam(1e-3)

    def loss_fn(p, b):
        return llama.forward_loss(p, b, cfg)

    if maker == "bf16":
        state = dp.replicate(mesh, dp.init_state(params, opt))
        step = compress.make_bf16_grad_step(loss_fn, opt, mesh)
    else:
        state = compress.init_ef_state(mesh, params, opt)
        step = compress.make_int8_ef_grad_step(loss_fn, opt, mesh)
    toks = jax.random.randint(jax.random.key(1), (4, 8), 0, 64)
    sb = dp.shard_batch(mesh, toks)
    first = None
    for _ in range(10):
        state, loss = step(state, sb)
        first = first if first is not None else float(loss)
    assert float(loss) < first


# ---------------------------------------------------------------------------
# Overlapped, compressed gradient sync (the ACCO-style microbatch ring).
#
# Pins: (1) the ppermute ring reduce-scatter is bitwise-equal to its
# documented ring-order spec and to lax.psum_scatter wherever the addition
# is exact (the two associate differently, so general floats match to
# re-association tolerance); (2) wire dtypes really ride the ppermute hops
# (jaxpr evidence); (3) the K-step scanned driver is bitwise the per-step
# driver at any K and M, for every wire format; (4) M=1 f32 matches the
# existing fused paths to fp32 tolerance; (5) int8+EF converges where the
# ring quantization alone would stall, and the EF residuals survive a
# preempt/resume cycle EXACTLY (bitwise trajectory across the restart) —
# on the new driver and on the legacy per-step int8 path.

from jax.sharding import NamedSharding, PartitionSpec as P

from ddl25spring_tpu.parallel._compat import shard_map


def _mesh4(devices):
    return make_mesh({"data": 4}, devices=devices[:4])


def _ring_spec_reference(cols, owner, n):
    """Host-side spec of the ring order: chunk ``owner``'s partial starts
    at rank owner+1 and accumulates one rank per hop, the owner last."""
    c = cols[0].shape[0] // n
    sl = slice(owner * c, (owner + 1) * c)
    order = [(owner + 1 + i) % n for i in range(n)]
    s = cols[order[0]][sl].copy()
    for i in order[1:]:
        s = s + cols[i][sl]
    return s


def test_ring_reduce_scatter_matches_spec_order_bitwise(devices):
    """The f32 ring is bitwise its documented summation order — chunk c
    associates as (((g_{c+1} + g_{c+2}) + ...) + g_c) — on every shard."""
    n = 4
    mesh = _mesh4(devices)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, n * 6)).astype(np.float32)

    def f(v):
        out, _ = compress.ring_reduce_scatter(v, "data", wire="fp32")
        return out

    g = jax.jit(shard_map(f, mesh=mesh, in_specs=P("data"),
                          out_specs=P("data"), check_vma=False))
    out = np.asarray(g(jax.device_put(
        x.reshape(-1), NamedSharding(mesh, P("data"))))).reshape(n, 6)
    for r in range(n):
        np.testing.assert_array_equal(
            out[r], _ring_spec_reference(list(x), r, n))


def test_ring_reduce_scatter_vs_psum_scatter(devices):
    """Satellite pin: vs ``lax.psum_scatter``. XLA CPU's scatter associates
    rank-linearly while the ring associates ring-order (a ring cannot
    produce the linear order for every chunk without serializing through
    rank 0), so the contract is: BITWISE equality wherever the addition is
    exact — integer-valued gradients, where association cannot matter —
    and re-association tolerance on general floats."""
    from jax import lax
    n = 4
    mesh = _mesh4(devices)
    rng = np.random.default_rng(1)

    def f_ring(v):
        out, _ = compress.ring_reduce_scatter(v, "data", wire="fp32")
        return out

    def f_ref(v):
        return lax.psum_scatter(v, "data", scatter_dimension=0, tiled=True)

    ring = jax.jit(shard_map(f_ring, mesh=mesh, in_specs=P("data"),
                             out_specs=P("data"), check_vma=False))
    ref = jax.jit(shard_map(f_ref, mesh=mesh, in_specs=P("data"),
                            out_specs=P("data"), check_vma=False))

    exact = jax.device_put(
        rng.integers(-1000, 1000, size=n * n * 8).astype(np.float32),
        NamedSharding(mesh, P("data")))
    np.testing.assert_array_equal(np.asarray(ring(exact)),
                                  np.asarray(ref(exact)))
    floats = jax.device_put(
        rng.standard_normal(n * n * 8).astype(np.float32),
        NamedSharding(mesh, P("data")))
    np.testing.assert_allclose(np.asarray(ring(floats)),
                               np.asarray(ref(floats)),
                               rtol=1e-6, atol=1e-6)


def test_overlap_wire_dtypes_ride_the_ppermute_hops():
    """jaxpr evidence that the ring's in-flight chunks are COMPRESSED: the
    int8 driver's ppermutes carry i8 chunk payloads (plus f32 scalar
    scales) and no gradient-sized f32 ppermute exists; the bf16 driver's
    carry bf16."""
    mesh = _mesh2()
    params, loss_fn, batch, _ = _quadratic_setup(jax.random.key(1))
    opt = optax.sgd(0.05)

    state8, step8 = compress.make_overlap_step(
        loss_fn, opt, mesh, params, microbatches=2, wire="int8_ef",
        aggregation="zero1")
    jx8 = str(jax.make_jaxpr(lambda s, b: step8(s, b))(
        state8, dp.shard_batch(mesh, batch)))
    hops = [ln for ln in jx8.splitlines() if "ppermute" in ln]
    assert any(":i8[32]" in ln or "i8[32]" in ln for ln in hops), \
        f"no int8 chunk hop in: {hops}"
    for ln in hops:
        # f32 ppermutes may carry only the scalar scale sidecars (f32[]).
        assert "f32[32]" not in ln, \
            f"gradient-sized f32 hop on the wire: {ln}"

    stateb, stepb = compress.make_overlap_step(
        loss_fn, opt, mesh, params, microbatches=1, wire="bf16",
        aggregation="gradient")
    jxb = str(jax.make_jaxpr(lambda s, b: stepb(s, b))(
        stateb, dp.shard_batch(mesh, batch))).replace("bfloat16", "bf16")
    hops = [ln for ln in jxb.splitlines() if "ppermute" in ln]
    assert any("bf16[32]" in ln for ln in hops), \
        f"no bf16 chunk hop in: {hops}"


@pytest.mark.parametrize("wire", ["fp32", "bf16", "int8_ef"])
def test_overlap_multi_step_bitwise_matches_per_step(devices, wire):
    """The fused K-step overlap driver reproduces the per-step driver's
    loss sequence AND final state bitwise at K=4, M=2 — the scanned body
    is the shared local step, so drift is a bug (the make_multi_step
    contract carried to the ring driver; for int8 this additionally
    proves the EF residuals thread the scan carry exactly)."""
    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama

    mesh = _mesh4(devices)
    cfg = LlamaConfig(vocab_size=64, dmodel=16, num_heads=2, n_layers=2,
                      ctx_size=8)

    def loss_fn(p, b):
        return llama.forward_loss(p, b, cfg)

    ks = jax.random.split(jax.random.key(2), 4)
    batches = [jax.random.randint(k, (8, 8), 0, 64) for k in ks]

    s1, step1 = compress.make_overlap_step(
        loss_fn, optax.adam(1e-3), mesh,
        llama.init_llama(jax.random.key(0), cfg),
        microbatches=2, wire=wire, aggregation="zero1")
    ref = []
    for b in batches:
        s1, l = step1(s1, dp.shard_batch(mesh, b))
        ref.append(float(l))

    sK, stepK = compress.make_overlap_multi_step(
        loss_fn, optax.adam(1e-3), mesh,
        llama.init_llama(jax.random.key(0), cfg),
        microbatches=2, wire=wire, aggregation="zero1")
    sK, losses = stepK(sK, dp.shard_batch_window(mesh, np.stack(batches)))
    assert [float(x) for x in np.asarray(losses)] == ref
    for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(sK)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_overlap_f32_matches_existing_paths(devices):
    """M=1 f32 ring vs the existing fused paths: same math, ring-vs-linear
    reduction order only — fp32-tolerance equality for both aggregations
    (the overlap restructuring itself must not touch the numerics)."""
    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama

    mesh = _mesh4(devices)
    cfg = LlamaConfig(vocab_size=64, dmodel=16, num_heads=2, n_layers=2,
                      ctx_size=8)

    def loss_fn(p, b):
        return llama.forward_loss(p, b, cfg)

    batches = [jax.random.randint(k, (8, 8), 0, 64)
               for k in jax.random.split(jax.random.key(3), 3)]

    z_state, z_step = dp.make_zero1_step(
        loss_fn, optax.adam(1e-3), mesh,
        llama.init_llama(jax.random.key(0), cfg))
    o_state, o_step = compress.make_overlap_step(
        loss_fn, optax.adam(1e-3), mesh,
        llama.init_llama(jax.random.key(0), cfg),
        microbatches=1, wire="fp32", aggregation="zero1")
    for b in batches:
        z_state, zl = z_step(z_state, dp.shard_batch(mesh, b))
        o_state, ol = o_step(o_state, dp.shard_batch(mesh, b))
        np.testing.assert_allclose(float(ol), float(zl), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(z_state.params),
                    jax.tree.leaves(o_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-6, rtol=1e-5)

    g_state = dp.replicate(mesh, dp.init_state(
        llama.init_llama(jax.random.key(0), cfg), optax.adam(1e-3)))
    g_step = dp.make_grad_aggregation_step(loss_fn, optax.adam(1e-3), mesh)
    og_state, og_step = compress.make_overlap_step(
        loss_fn, optax.adam(1e-3), mesh,
        llama.init_llama(jax.random.key(0), cfg),
        microbatches=1, wire="fp32", aggregation="gradient")
    for b in batches:
        g_state, gl = g_step(g_state, dp.shard_batch(mesh, b))
        og_state, ogl = og_step(og_state, dp.shard_batch(mesh, b))
        np.testing.assert_allclose(float(ogl), float(gl), rtol=1e-6)


def test_overlap_int8_converges_on_quadratic():
    """int8 in-flight ring chunks + int8 second leg with EF converge on
    the convex problem (the existing int8 path's bar), at M=2 where the
    microbatch pipeline and the per-hop quantization are both live."""
    mesh = _mesh2()
    params, loss_fn, batch, _ = _quadratic_setup(jax.random.key(3))
    for agg in ("gradient", "zero1"):
        state, step = compress.make_overlap_step(
            loss_fn, optax.sgd(0.05), mesh,
            jax.tree.map(jnp.copy, params), microbatches=2,
            wire="int8_ef", aggregation=agg)
        sb = dp.shard_batch(mesh, batch)
        losses = []
        for _ in range(60):
            state, loss = step(state, sb)
            losses.append(float(loss))
        assert losses[-1] < 1e-2 * losses[0], (agg, losses[0], losses[-1])


def test_overlap_replicas_stay_bitwise_identical(devices):
    """Every wire format broadcasts ONE payload all shards apply
    identically, so the replicated params must stay bitwise in sync —
    the invariant that makes the quantized second leg sound."""
    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama

    mesh = _mesh4(devices)
    cfg = LlamaConfig(vocab_size=64, dmodel=16, num_heads=2, n_layers=2,
                      ctx_size=8)

    def loss_fn(p, b):
        return llama.forward_loss(p, b, cfg)

    batch = jax.random.randint(jax.random.key(1), (8, 8), 0, 64)
    for wire in ("fp32", "bf16", "int8_ef"):
        for agg in ("gradient", "zero1"):
            state, step = compress.make_overlap_step(
                loss_fn, optax.adam(1e-3), mesh,
                llama.init_llama(jax.random.key(0), cfg),
                microbatches=2, wire=wire, aggregation=agg)
            for _ in range(2):
                state, _ = step(state, dp.shard_batch(mesh, batch))
            for leaf in jax.tree.leaves(state.params):
                shards = [np.asarray(s.data)
                          for s in leaf.addressable_shards]
                for s in shards[1:]:
                    np.testing.assert_array_equal(shards[0], s)


def test_overlap_ef_residual_exact_through_preempt_resume(devices):
    """The acceptance bar: an int8+EF overlap run (zero1, K=2) interrupted
    at a chunk edge and resumed from its checkpoint walks BITWISE the
    uninterrupted trajectory — possible only if both EF residual trees
    restore exactly (a zeroed residual would shift every loss after the
    resume point)."""
    from ddl25spring_tpu.config import LlamaConfig, TrainConfig
    from ddl25spring_tpu.tokenizers import ByteTokenizer
    from ddl25spring_tpu.train import train_llm_dp

    cfg = LlamaConfig(vocab_size=259, dmodel=16, num_heads=2, n_layers=2,
                      ctx_size=16)
    base = dict(batch_size=2, seq_len=16, lr=3e-3, data=2, wire="int8_ef",
                overlap_microbatches=2, steps_per_dispatch=2)
    mesh = lambda: make_mesh({"data": 2}, devices=devices[:2])  # noqa: E731

    ref = train_llm_dp(cfg, TrainConfig(**base, iters=6),
                       tokenizer=ByteTokenizer(), aggregation="zero1",
                       mesh=mesh(), log_every=0)
    import tempfile
    d = tempfile.mkdtemp()
    a = train_llm_dp(cfg, TrainConfig(**base, iters=4),
                     tokenizer=ByteTokenizer(), aggregation="zero1",
                     mesh=mesh(), log_every=0, checkpoint_dir=d,
                     checkpoint_every=100)
    b = train_llm_dp(cfg, TrainConfig(**base, iters=6),
                     tokenizer=ByteTokenizer(), aggregation="zero1",
                     mesh=mesh(), log_every=0, checkpoint_dir=d,
                     checkpoint_every=100)
    assert a.losses + b.losses == ref.losses


def test_int8_ef_legacy_resume_preserves_residual(devices):
    """Satellite pin: the legacy per-step int8+EF path's residual IS part
    of checkpointed state (EFTrainState rides the checkpointer whole) —
    a mid-run preemption must not silently drop accumulated quantization
    error, proven by bitwise trajectory equality across a resume."""
    from ddl25spring_tpu.config import LlamaConfig, TrainConfig
    from ddl25spring_tpu.tokenizers import ByteTokenizer
    from ddl25spring_tpu.train import train_llm_dp

    cfg = LlamaConfig(vocab_size=259, dmodel=16, num_heads=2, n_layers=2,
                      ctx_size=16)
    base = dict(batch_size=2, seq_len=16, lr=3e-3, data=2, wire="int8_ef")
    mesh = lambda: make_mesh({"data": 2}, devices=devices[:2])  # noqa: E731

    ref = train_llm_dp(cfg, TrainConfig(**base, iters=6),
                       tokenizer=ByteTokenizer(), mesh=mesh(), log_every=0)
    import tempfile
    d = tempfile.mkdtemp()
    a = train_llm_dp(cfg, TrainConfig(**base, iters=3),
                     tokenizer=ByteTokenizer(), mesh=mesh(), log_every=0,
                     checkpoint_dir=d, checkpoint_every=100)
    b = train_llm_dp(cfg, TrainConfig(**base, iters=6),
                     tokenizer=ByteTokenizer(), mesh=mesh(), log_every=0,
                     checkpoint_dir=d, checkpoint_every=100)
    assert a.losses + b.losses == ref.losses


def test_overlap_trainer_composition_and_guards(devices):
    """Trainer-level composition: overlap_microbatches=2 + bf16 wire +
    zero1 + steps_per_dispatch=2 trains finite and matches its own
    per-step-dispatch run bitwise; invalid compositions fail loudly."""
    from ddl25spring_tpu.config import LlamaConfig, TrainConfig
    from ddl25spring_tpu.tokenizers import ByteTokenizer
    from ddl25spring_tpu.train import train_llm_dp

    cfg = LlamaConfig(vocab_size=259, dmodel=16, num_heads=2, n_layers=2,
                      ctx_size=16)
    base = dict(batch_size=2, seq_len=16, iters=4, lr=3e-3, data=2,
                wire="bf16", overlap_microbatches=2)
    mesh = lambda: make_mesh({"data": 2}, devices=devices[:2])  # noqa: E731
    ref = train_llm_dp(cfg, TrainConfig(**base), tokenizer=ByteTokenizer(),
                       aggregation="zero1", mesh=mesh(), log_every=0)
    got = train_llm_dp(cfg, TrainConfig(**base, steps_per_dispatch=2),
                       tokenizer=ByteTokenizer(), aggregation="zero1",
                       mesh=mesh(), log_every=0)
    assert got.losses == ref.losses
    assert all(np.isfinite(ref.losses))

    with pytest.raises(ValueError, match="zero1 aggregation only"):
        train_llm_dp(cfg, TrainConfig(**base), tokenizer=ByteTokenizer(),
                     aggregation="weight", mesh=mesh(), log_every=0)
    with pytest.raises(ValueError, match="accum_steps"):
        train_llm_dp(cfg, TrainConfig(**base, accum_steps=2),
                     tokenizer=ByteTokenizer(), mesh=mesh(), log_every=0)
    # numerics_every now COMPOSES with the ring driver (PR 12 satellite —
    # was a hard error): same trajectory bitwise, instrumentation on.
    instr = train_llm_dp(cfg, TrainConfig(**base, numerics_every=2),
                         tokenizer=ByteTokenizer(), aggregation="zero1",
                         mesh=mesh(), log_every=0)
    assert instr.losses == ref.losses


# ---------------------------------------------------------------------------
# Bucketed backward (comm_buckets > 1): sub-1/n ring chunking that starts
# the first hop before the full gradient materializes (ISSUE 19).
# ---------------------------------------------------------------------------


def _llama_setup(key=0):
    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama

    cfg = LlamaConfig(vocab_size=64, dmodel=16, num_heads=2, n_layers=2,
                      ctx_size=8)

    def loss_fn(p, b):
        return llama.forward_loss(p, b, cfg)

    return cfg, loss_fn, llama.init_llama(jax.random.key(key), cfg)


def test_bucket_map_covers_in_vjp_emission_order():
    """The BucketMap partitions the padded flat space exactly once, with
    lm_head first and the embedding last (top-of-network buckets first —
    the VJP emission order that makes early rings independent of late
    grads), blocks layers walked top-down, and the global pad riding the
    LAST bucket's tail."""
    _, _, params = _llama_setup()
    n = 4
    for B in (1, 2, 3, 8):
        bm = compress.make_bucket_map(params, n, B)
        assert bm.nbuckets == B
        assert sum(bm.sizes) == bm.local
        assert bm.n * bm.local == bm.total + bm.pad
        # pieces tile [0, n·local) exactly once, in order
        pos = 0
        for _, start, size in [pc for b in bm.pieces for pc in b]:
            del start
            pos += size
        assert pos + bm.pad == bm.n * bm.local
    bm = compress.make_bucket_map(params, n, 8)
    leaf_order = [pc[0] for b in bm.pieces for pc in b]
    paths = [jax.tree_util.keystr(p) for p, _ in
             jax.tree_util.tree_flatten_with_path(params)[0]]
    first_key = paths[leaf_order[0]]
    last_key = paths[leaf_order[-1]]
    assert "lm_head" in first_key, first_key
    assert "embed" in last_key, last_key
    with pytest.raises(ValueError, match="exceeds the per-shard slice"):
        compress.make_bucket_map(params, n, 10 ** 9)


def test_bucketed_fp32_ring_bitwise_at_every_bucket_count(devices):
    """THE house bar, at the ring level: on exact-arithmetic inputs
    (small integers — every fp32 sum is exact regardless of association)
    the per-bucket rings and the unbucketed ``ring_reduce_scatter``
    BITWISE agree with the exact cross-shard sum — hence with each other
    — at every bucket count. Bucketing re-chunks the ring and reorders
    coordinates, which can only reassociate sums; exact sums don't
    care."""
    mesh = _mesh4(devices)
    n, local = 4, 16
    params = {"w": jnp.zeros((n * local,))}   # single leaf: no pad
    xs = np.asarray(jax.random.randint(jax.random.key(9),
                                       (n, n * local), -50, 50),
                    dtype=np.float32)
    exact = xs.sum(axis=0)                    # integer sums: exact in fp32

    for B in (1, 2, 3, 8):
        bm = compress.make_bucket_map(params, n, B)

        def body(x):
            v = x.reshape(-1)
            outs = []
            for b in range(bm.nbuckets):
                o = bm.n * bm.offsets[b]
                red, _ = compress.ring_reduce_scatter(
                    v[o:o + bm.n * bm.sizes[b]], "data", wire="fp32",
                    residual=None, label=f"ring_grad_b{b}")
                outs.append(red)
            return jnp.concatenate(outs)[None]

        got = jax.jit(shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
            check_vma=False))(jnp.asarray(xs))
        got = np.asarray(got)                 # [n, local] owned concats
        for r in range(n):
            want = np.concatenate([
                exact[bm.n * bm.offsets[b] + r * bm.sizes[b]:
                      bm.n * bm.offsets[b] + (r + 1) * bm.sizes[b]]
                for b in range(bm.nbuckets)])
            np.testing.assert_array_equal(got[r], want)


def test_bucketed_driver_fp32_matches_unbucketed(devices):
    """Driver level: the first step from w=0 on integer data is exact
    arithmetic end-to-end (integer gradients, dyadic lr) — losses AND
    params bitwise across bucket counts; further steps accumulate only
    reassociation-level float noise (losses stay equal, params to fp32
    tolerance), for both aggregations."""
    mesh = _mesh4(devices)
    dim = 64
    k1, k2 = jax.random.split(jax.random.key(7))
    w_star = jnp.round(jax.random.normal(k1, (dim,)) * 3)
    x = jnp.round(jax.random.normal(k2, (64, dim)) * 2)
    y = x @ w_star
    batch = jnp.concatenate([x, y[:, None]], axis=-1)

    def loss_fn(p, b):
        xb, yb = b[..., :-1], b[..., -1]
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    def run(B, agg, steps):
        state, step = compress.make_overlap_step(
            loss_fn, optax.sgd(2. ** -4), mesh, {"w": jnp.zeros((dim,))},
            microbatches=2, wire="fp32", aggregation=agg, comm_buckets=B)
        losses = []
        for _ in range(steps):
            state, l = step(state, dp.shard_batch(mesh, batch))
            losses.append(float(l))
        return losses, np.asarray(state.params["w"])

    for agg in ("gradient", "zero1"):
        ref1_l, ref1_w = run(1, agg, 1)
        ref4_l, ref4_w = run(1, agg, 4)
        for B in (2, 3, 8):
            got_l, got_w = run(B, agg, 1)
            assert got_l == ref1_l, (agg, B, ref1_l, got_l)
            np.testing.assert_array_equal(got_w, ref1_w)
            got_l, got_w = run(B, agg, 4)
            assert got_l == ref4_l, (agg, B, ref4_l, got_l)
            np.testing.assert_allclose(got_w, ref4_w, atol=1e-6, rtol=0)


def test_bucketed_int8_converges_on_quadratic():
    """int8 wire × comm_buckets=4: per-bucket quantization + per-bucket EF
    residual tuples hold the PR 10 convergence bound on the convex
    problem, for both aggregations."""
    mesh = _mesh2()
    params, loss_fn, batch, _ = _quadratic_setup(jax.random.key(3))
    for agg in ("gradient", "zero1"):
        state, step = compress.make_overlap_step(
            loss_fn, optax.sgd(0.05), mesh,
            jax.tree.map(jnp.copy, params), microbatches=2,
            wire="int8_ef", aggregation=agg, comm_buckets=4)
        sb = dp.shard_batch(mesh, batch)
        losses = []
        for _ in range(60):
            state, loss = step(state, sb)
            losses.append(float(loss))
        assert losses[-1] < 1e-2 * losses[0], (agg, losses[0], losses[-1])


@pytest.mark.parametrize("wire", ["fp32", "int8_ef"])
def test_bucketed_multi_step_bitwise_matches_per_step(devices, wire):
    """K-scan at a FIXED bucket count is bitwise vs per-step dispatch —
    the per-bucket EF residual tuples and per-bucket ZeRO-1 moments
    thread the scan carry exactly (the make_multi_step contract carried
    to the bucketed ring)."""
    from ddl25spring_tpu.models import llama

    mesh = _mesh4(devices)
    cfg, loss_fn, _ = _llama_setup()
    ks = jax.random.split(jax.random.key(2), 4)
    batches = [jax.random.randint(k, (8, 8), 0, 64) for k in ks]

    s1, step1 = compress.make_overlap_step(
        loss_fn, optax.adam(1e-3), mesh,
        llama.init_llama(jax.random.key(0), cfg),
        microbatches=2, wire=wire, aggregation="zero1", comm_buckets=2)
    ref = []
    for b in batches:
        s1, l = step1(s1, dp.shard_batch(mesh, b))
        ref.append(float(l))

    sK, stepK = compress.make_overlap_multi_step(
        loss_fn, optax.adam(1e-3), mesh,
        llama.init_llama(jax.random.key(0), cfg),
        microbatches=2, wire=wire, aggregation="zero1", comm_buckets=2)
    sK, losses = stepK(sK, dp.shard_batch_window(mesh, np.stack(batches)))
    assert [float(x) for x in np.asarray(losses)] == ref
    for a, b in zip(jax.tree.leaves(s1), jax.tree.leaves(sK)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bucketed_preempt_resume_bitwise(devices):
    """The acceptance bar at comm_buckets=8: an int8+EF bucketed run
    (zero1, K=2) interrupted at a chunk edge and resumed from checkpoint
    walks BITWISE the uninterrupted trajectory — the per-bucket EF
    residual tuples ride the checkpointed state tree whole."""
    from ddl25spring_tpu.config import LlamaConfig, TrainConfig
    from ddl25spring_tpu.tokenizers import ByteTokenizer
    from ddl25spring_tpu.train import train_llm_dp

    cfg = LlamaConfig(vocab_size=259, dmodel=16, num_heads=2, n_layers=2,
                      ctx_size=16)
    base = dict(batch_size=2, seq_len=16, lr=3e-3, data=2, wire="int8_ef",
                overlap_microbatches=2, steps_per_dispatch=2,
                comm_buckets=8)
    mesh = lambda: make_mesh({"data": 2}, devices=devices[:2])  # noqa: E731

    ref = train_llm_dp(cfg, TrainConfig(**base, iters=6),
                       tokenizer=ByteTokenizer(), aggregation="zero1",
                       mesh=mesh(), log_every=0)
    import tempfile
    d = tempfile.mkdtemp()
    a = train_llm_dp(cfg, TrainConfig(**base, iters=4),
                     tokenizer=ByteTokenizer(), aggregation="zero1",
                     mesh=mesh(), log_every=0, checkpoint_dir=d,
                     checkpoint_every=100)
    b = train_llm_dp(cfg, TrainConfig(**base, iters=6),
                     tokenizer=ByteTokenizer(), aggregation="zero1",
                     mesh=mesh(), log_every=0, checkpoint_dir=d,
                     checkpoint_every=100)
    assert a.losses + b.losses == ref.losses
    assert all(np.isfinite(ref.losses))


def test_ring_overlap_evidence_positive_and_negative(devices):
    """The PR 10 evidence standard, applied within the backward: at B=1
    the first ring hop depends on the WHOLE backward scan (overlap
    fraction 0, first hop waits); at B=8 M=1 the lm_head bucket's hops
    are dataflow-independent of the blocks' VJP scan — first hop starts
    before the full gradient materializes. Asserted on the jaxpr, not on
    timings."""
    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama

    mesh = _mesh4(devices)
    cfg = LlamaConfig(vocab_size=259, dmodel=32, num_heads=2, n_layers=2,
                      ctx_size=16)

    def loss_fn(p, b):
        return llama.forward_loss(p, b, cfg)

    def evidence(B, M):
        state, step = compress.make_overlap_step(
            loss_fn, optax.adam(1e-3), mesh,
            llama.init_llama(jax.random.key(0), cfg),
            microbatches=M, wire="int8_ef", aggregation="zero1",
            comm_buckets=B)
        batch = dp.shard_batch(
            mesh, jax.random.randint(jax.random.key(1),
                                     (4 * M, 16), 0, 259))
        return compress.ring_overlap_evidence(step, state, batch)

    ev1 = evidence(1, 1)
    assert ev1["overlap_fraction"] == 0.0
    assert not ev1["first_hop_independent"]

    ev8 = evidence(8, 1)
    assert ev8["first_hop_independent"], ev8
    assert ev8["overlap_fraction"] > 0.0, ev8
    assert ev8["n_ring_hops"] == 8 * ev1["n_ring_hops"]


def test_bucketed_zero_retraces_across_grid(devices):
    """Zero retraces across the comm_buckets × wire × K grid: every
    config compiles exactly ONE program across repeated dispatches
    (max_caches=1 — a second trace is a hard failure)."""
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.telemetry import introspect

    mesh = _mesh4(devices)
    cfg, loss_fn, _ = _llama_setup()
    batches = [np.asarray(jax.random.randint(k, (8, 8), 0, 64))
               for k in jax.random.split(jax.random.key(5), 3)]
    for B in (2, 8):
        for wire in ("fp32", "int8_ef"):
            for K in (1, 2):
                if K == 1:
                    state, step = compress.make_overlap_step(
                        loss_fn, optax.adam(1e-3), mesh,
                        llama.init_llama(jax.random.key(0), cfg),
                        microbatches=2, wire=wire, aggregation="zero1",
                        comm_buckets=B)
                    step = introspect.watch(
                        step, name=f"grid-b{B}-{wire}-k1", max_caches=1)
                    for b in batches:
                        state, _ = step(state, dp.shard_batch(mesh, b))
                else:
                    state, step = compress.make_overlap_multi_step(
                        loss_fn, optax.adam(1e-3), mesh,
                        llama.init_llama(jax.random.key(0), cfg),
                        microbatches=2, wire=wire, aggregation="zero1",
                        comm_buckets=B)
                    step = introspect.watch(
                        step, name=f"grid-b{B}-{wire}-k2", max_caches=1)
                    w = dp.shard_batch_window(mesh, np.stack(batches[:2]))
                    for _ in range(2):
                        state, _ = step(state, w)
