"""Telemetry layer (ddl25spring_tpu/telemetry) + observability satellites.

Pins the ISSUE-2 contracts: event-schema round-trip (incl. torn-final-line
crash tolerance and concurrent writers), EXACT static comm-volume bytes for
known DP configs (fp32 vs the compressed wire formats), heartbeat-based
stall detection in the watchdog's LivenessMonitor, cost_analysis guard
behavior on this jaxlib, thread-safe ResultSink header widening,
ResilienceStats.merge field completeness, and StepTimer misuse raising.
"""

import dataclasses
import json
import math
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.config import LlamaConfig, TrainConfig
from ddl25spring_tpu.metrics import ResilienceStats
from ddl25spring_tpu.models import llama
from ddl25spring_tpu.parallel import compress, dp, make_mesh
from ddl25spring_tpu.telemetry import (EventLog, Heartbeat, MetricsRegistry,
                                       SCHEMA_VERSION, Telemetry,
                                       flops_crosscheck, hlo_cost,
                                       measure_comm, read_events,
                                       read_heartbeat, validate_event)
from ddl25spring_tpu.tokenizers import ByteTokenizer
from ddl25spring_tpu.utils.tracing import ResultSink, StepTimer

TINY = LlamaConfig(vocab_size=259, dmodel=16, num_heads=2, n_layers=2,
                   ctx_size=16)


# ----------------------------------------------------------- event stream

def test_eventlog_schema_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="r1") as log:
        log.manifest(jax_version=jax.__version__, platform="cpu")
        log.step(it=0, loss=2.5, dt_s=0.1)
        log.fault(counters={"skipped_steps": 1}, it=3)
        log.fl_round(round=0, wall_s=0.2, test_accuracy=0.5)
        log.run_end(steps=10, metrics={"counters": {}})
    events = read_events(path, strict=True)  # strict: validates every event
    assert [e["type"] for e in events] == [
        "manifest", "step", "fault", "fl_round", "run_end"]
    assert [e["seq"] for e in events] == [1, 2, 3, 4, 5]
    assert all(e["run_id"] == "r1" for e in events)
    assert all(e["schema"] == SCHEMA_VERSION for e in events)
    assert events[1]["loss"] == 2.5 and events[1]["it"] == 0
    # type filter
    assert [e["it"] for e in read_events(path, types=("step",))] == [0]


def test_eventlog_torn_final_line_and_corruption(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="r1") as log:
        log.step(it=0, loss=1.0)
        log.step(it=1, loss=2.0)
    with open(path, "ab") as f:
        f.write(b'{"schema": 1, "run_id": "r1", "seq": 3, "t": 0, "ty')
    # A torn FINAL line is a crash artifact, dropped even under strict.
    assert [e["it"] for e in read_events(path, strict=True)] == [0, 1]
    # Mid-file garbage is corruption: skipped lax, raised strict.
    with open(path, "ab") as f:
        f.write(b'rbage\n')
        f.write(json.dumps({"schema": 1, "run_id": "r1", "seq": 4, "t": 0,
                            "type": "step", "it": 2}).encode() + b"\n")
    assert [e["it"] for e in read_events(path)] == [0, 1, 2]
    with pytest.raises(ValueError):
        read_events(path, strict=True)
    # Valid JSON that is not an object (`null`, a number) is the same
    # corruption class: skipped lax (with a types filter too), raised
    # strict — never leaked to crash a consumer's `.get`.
    path2 = str(tmp_path / "nondict.jsonl")
    with open(path2, "w") as f:
        f.write('null\n')
        f.write(json.dumps({"schema": 1, "run_id": "r", "seq": 1, "t": 0,
                            "type": "step", "it": 0}) + "\n")
    assert [e["it"] for e in read_events(path2, types=("step",))] == [0]
    with pytest.raises(ValueError):
        read_events(path2, strict=True)


def test_eventlog_reopen_heals_torn_fragment(tmp_path):
    """A relaunch reusing the telemetry dir truncates a crashed
    predecessor's torn final line instead of appending onto it — the
    fragment must not become mid-file corruption that strict readers
    raise on."""
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="r1") as log:
        log.step(it=0, loss=1.0)
    with open(path, "ab") as f:
        f.write(b'{"schema": 1, "run_id": "r1", "seq": 2, "t": 0, "ty')
    with EventLog(path, run_id="r2") as log:
        log.manifest(jax_version="test", platform="cpu")
    events = read_events(path, strict=True)
    assert [e["run_id"] for e in events] == ["r1", "r2"]


def test_eventlog_emit_never_raises(tmp_path):
    """IO failure drops the event and counts (same never-sink-a-trainer
    policy as Heartbeat.beat) — including emits after close()."""
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, run_id="r1")
    log.step(it=0, loss=1.0)
    log.close()
    record = log.emit("step", it=1, loss=2.0)   # must not raise
    assert record["it"] == 1 and log.write_errors == 1
    assert [e["it"] for e in read_events(path)] == [0]
    # Serialization failures count too: _json_fallback can't save
    # non-string dict keys, and json.dumps' TypeError must not escape.
    log2 = EventLog(path, run_id="r2")
    log2.emit("custom", data={(0, 1): "tuple-keyed"})
    assert log2.write_errors == 1
    log2.step(it=2, loss=3.0)                   # stream still usable
    log2.close()
    assert [e["it"] for e in read_events(path, strict=True)] == [0, 2]


def test_eventlog_heal_scans_backwards_across_chunks(tmp_path):
    """The reopen-heal finds the last newline by scanning backwards in
    64 KiB chunks — a fragment longer than one chunk (a crash mid-way
    through a huge manifest) must still truncate to the right offset."""
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="r1") as log:
        log.step(it=0, loss=1.0)
    with open(path, "ab") as f:
        f.write(b'{"pad": "' + b"x" * (200 * 1024))  # 200 KiB torn line
    with EventLog(path, run_id="r2") as log:
        log.step(it=1, loss=2.0)
    assert [e["it"] for e in read_events(path, strict=True)] == [0, 1]


def test_eventlog_partial_write_seals_torn_tail(tmp_path, monkeypatch):
    """ENOSPC mid-line: os.write lands SOME bytes then fails. The failed
    event counts as a write error, and the next successful emit seals the
    fragment with a newline so it stays ONE skippable malformed line
    instead of merging into (and corrupting) the next event."""
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, run_id="r1")
    log.step(it=0, loss=1.0)

    real_write = os.write
    calls = []

    # POSIX write(2) semantics for a disk filling mid-line: the first call
    # writes what fits and returns SHORT; the retry gets ENOSPC.
    def short_then_fail(fd, data):
        if fd == log._fd:
            calls.append(len(data))
            if len(calls) == 1:
                return real_write(fd, bytes(data)[:10])
            raise OSError(28, "No space left on device")
        return real_write(fd, data)

    monkeypatch.setattr(os, "write", short_then_fail)
    log.step(it=1, loss=2.0)                  # partially lands, counted
    monkeypatch.setattr(os, "write", real_write)
    log.step(it=2, loss=3.0)                  # must seal, then append
    log.close()
    assert log.write_errors == 1
    assert [e["it"] for e in read_events(path)] == [0, 2]
    with pytest.raises(ValueError):           # the fragment IS corruption
        read_events(path, strict=True)


def test_eventlog_nonfinite_floats_stay_strict_json(tmp_path):
    """An unguarded chaos run can hand emit() loss=nan — the stream must
    stay STRICT JSON (the CI artifact is consumed by jq/non-Python
    readers), so non-finite floats land as their str(), never as the
    NaN/Infinity tokens json.dumps writes by default."""
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="r1") as log:
        log.step(it=0, loss=float("nan"),
                 extra=[float("inf"), np.float32("nan")])
    raw = open(path).read()
    assert "NaN" not in raw and "Infinity" not in raw
    assert log.write_errors == 0
    (event,) = read_events(path, strict=True)
    assert event["loss"] == "nan" and event["extra"][0] == "inf"


def test_telemetry_step_every_floor(tmp_path):
    """step_every=0 ('disable step events') must not arm a
    ZeroDivisionError inside the training loop's `it % step_every`."""
    tel = Telemetry(str(tmp_path / "run"), step_every=0)
    assert tel.step_every == 1
    tel.close()


def test_validate_event_contract():
    base = {"schema": SCHEMA_VERSION, "run_id": "r", "seq": 1, "t": 0.0}
    assert validate_event({**base, "type": "step", "it": 3}) == []
    # Per-type required fields.
    assert validate_event({**base, "type": "step"}) != []
    # Unknown types are forward-compatible, not errors.
    assert validate_event({**base, "type": "novel_event"}) == []
    # A FUTURE schema version is a problem; missing base fields are too.
    assert validate_event({**base, "schema": SCHEMA_VERSION + 1,
                           "type": "step", "it": 0}) != []
    assert validate_event({"type": "step", "it": 0}) != []


def test_request_event_emitters_roundtrip(tmp_path):
    """Schema v2: the serving lifecycle's four typed emitters produce
    valid, strictly-readable events carrying their required fields."""
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="srv") as log:
        log.request_enqueue(req="r-1", prompt_len=8, max_new=4,
                            temperature=0.8, queued=1)
        log.request_prefill(req="r-1", slot=2, blocks=3, queue_wait_s=0.01,
                            blocks_in_use=3)
        log.request_token(req="r-1", i=0, tok=17, slot=2)
        log.request_done(req="r-1", tokens=4, queue_wait_s=0.01,
                         ttft_s=0.05, tokens_per_sec=80.0, blocks_freed=3,
                         blocks_in_use=0)
    events = read_events(path, strict=True)    # strict = validate_event
    assert [e["type"] for e in events] == [
        "request_enqueue", "request_prefill", "request_token",
        "request_done"]
    assert all(e["schema"] == SCHEMA_VERSION for e in events)
    assert events[1]["slot"] == 2 and events[3]["tokens"] == 4


def test_validate_event_request_required_fields():
    """request_* events missing their per-type required fields must be
    flagged — the schema bump added real rows, not just names."""
    base = {"schema": SCHEMA_VERSION, "run_id": "r", "seq": 1, "t": 0.0}
    assert validate_event({**base, "type": "request_enqueue",
                           "req": "a"}) == []
    assert validate_event({**base, "type": "request_enqueue"}) != []
    assert validate_event({**base, "type": "request_prefill",
                           "req": "a"}) != []        # missing slot
    assert validate_event({**base, "type": "request_token",
                           "req": "a"}) != []        # missing i
    assert validate_event({**base, "type": "request_done",
                           "req": "a"}) != []        # missing tokens
    assert validate_event({**base, "type": "request_done", "req": "a",
                           "tokens": 3}) == []
    # v1 streams (all pre-serving types) remain valid under the v2 reader.
    assert validate_event({**base, "schema": 1, "type": "step",
                           "it": 0}) == []


def test_fleet_event_emitters_roundtrip(tmp_path):
    """Schema v3: the fleet FL emitters (fl_cohort / fl_tier) produce
    valid, strictly-readable events carrying their required fields."""
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="fleet") as log:
        log.fl_cohort(round=0, tier="edge", cohort=3, edge=1, clients=64,
                      payload_bytes=64 * 1320)
        log.fl_tier(round=0, tier="edge", edges=4, clients=256,
                    payload_bytes=256 * 1320, wire="float32")
        log.fl_tier(round=0, tier="server", inputs=4,
                    payload_bytes=4 * 1320)
    events = read_events(path, strict=True)
    assert [e["type"] for e in events] == ["fl_cohort", "fl_tier",
                                           "fl_tier"]
    assert all(e["schema"] == SCHEMA_VERSION for e in events)
    assert events[0]["clients"] == 64
    assert events[2]["tier"] == "server"


def test_validate_event_fleet_required_fields():
    """fl_cohort / fl_tier events missing their per-type required fields
    must be flagged, and pre-v3 streams stay valid under the v3 reader."""
    base = {"schema": SCHEMA_VERSION, "run_id": "r", "seq": 1, "t": 0.0}
    assert validate_event({**base, "type": "fl_cohort", "round": 0,
                           "tier": "edge", "cohort": 0}) == []
    assert validate_event({**base, "type": "fl_cohort", "round": 0,
                           "tier": "edge"}) != []      # missing cohort
    assert validate_event({**base, "type": "fl_tier", "round": 0,
                           "tier": "server"}) == []
    assert validate_event({**base, "type": "fl_tier", "round": 0}) != []
    # v2 streams (serving lifecycle) remain valid under the v3 reader.
    assert validate_event({**base, "schema": 2, "type": "request_done",
                           "req": "a", "tokens": 3}) == []


def test_eventlog_concurrent_writers(tmp_path):
    """10 threads x 50 events through one log: every event lands intact
    (one write() under the lock), seq is a permutation of 1..500."""
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, run_id="r1")

    def emit(tid):
        for i in range(50):
            log.emit("step", it=i, thread=tid)

    threads = [threading.Thread(target=emit, args=(t,)) for t in range(10)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()
    events = read_events(path, strict=True)
    assert len(events) == 500
    assert sorted(e["seq"] for e in events) == list(range(1, 501))


# ------------------------------------------------- comm-volume accounting

def _param_bytes(params, itemsize):
    return sum(math.prod(l.shape) for l in jax.tree.leaves(params)) * itemsize


def test_comm_exact_bytes_dp_fp32(devices):
    """The known-config contract: a data=2 DP gradient-aggregation step
    moves EXACTLY n_params fp32 elements through grad_allreduce plus one
    scalar loss, with ring wire factor 2*(n-1)/n = 1.0 at n=2."""
    n = 2
    mesh = make_mesh({"data": n}, devices=devices[:n])
    params = llama.init_llama(jax.random.key(0), TINY)
    opt = optax.adam(1e-3)
    state = dp.replicate(mesh, dp.init_state(params, opt))
    step = dp.make_grad_aggregation_step(
        lambda p, b: llama.forward_loss(p, b, TINY), opt, mesh)
    batch = jax.ShapeDtypeStruct((n * 2, TINY.ctx_size), jnp.int32)
    profile = measure_comm(step, state, batch)
    assert profile is not None and profile.records
    by = profile.by_label()
    expected = _param_bytes(params, 4)                 # fp32 wire
    assert by["grad_allreduce"]["payload_bytes"] == expected
    assert by["grad_allreduce"]["axis_size"] == n
    assert by["loss_allreduce"]["payload_bytes"] == 4  # one fp32 scalar
    # Ring allreduce at n=2: 2*(n-1)/n = 1.0 -> wire == payload.
    assert by["grad_allreduce"]["wire_bytes_per_device"] == expected
    assert profile.payload_bytes_per_step == expected + 4


def test_comm_bf16_wire_halves_payload(devices):
    """The compression lever the accounting exists to measure: the bf16
    wire format's grad collective carries exactly HALF the fp32 bytes."""
    n = 2
    mesh = make_mesh({"data": n}, devices=devices[:n])
    params = llama.init_llama(jax.random.key(0), TINY)
    opt = optax.adam(1e-3)
    state = dp.replicate(mesh, dp.init_state(params, opt))
    step = compress.make_bf16_grad_step(
        lambda p, b: llama.forward_loss(p, b, TINY), opt, mesh)
    batch = jax.ShapeDtypeStruct((n * 2, TINY.ctx_size), jnp.int32)
    profile = measure_comm(step, state, batch)
    by = profile.by_label()
    assert by["grad_allreduce_bf16"]["payload_bytes"] == _param_bytes(params, 2)


def test_comm_scale_multiplies_scan_trips():
    """A record's ``scale`` (scan trip count) multiplies the per-step
    aggregate — the mechanism the PP/SP ring call sites rely on."""
    from ddl25spring_tpu.telemetry.comm import CommProfile, CommRecord
    r = CommRecord(op="ppermute", label="hop", axis="stage", axis_size=4,
                   payload_bytes=100, scale=6)
    p = CommProfile([r])
    assert p.payload_bytes_per_step == 600
    assert p.by_label()["hop"]["calls"] == 6
    assert r.wire_bytes_per_device == 100.0      # one neighbor send per exec


def test_measure_comm_handles_cached_trace():
    """A step whose trace is already cached must still produce records
    (the one-retry-after-clear_caches path in measure_comm)."""
    @jax.jit
    def f(x):
        from ddl25spring_tpu.telemetry import comm
        return comm.psum(x, "i", label="row_sum")

    vx = jax.ShapeDtypeStruct((8, 4), jnp.float32)

    def mapped(x):
        return jax.vmap(f, axis_name="i")(x)

    first = measure_comm(mapped, vx)
    second = measure_comm(mapped, vx)      # cache-warm path
    # Accounting is per-participant: the operand INSIDE the mapped axis is
    # the [4] f32 local row, and the axis resolves to its 8 participants.
    assert first.by_label()["row_sum"]["payload_bytes"] == 4 * 4
    assert first.by_label()["row_sum"]["axis_size"] == 8
    assert second.by_label()["row_sum"]["payload_bytes"] == 4 * 4


# ------------------------------------------------------- HLO cost guard

def test_hlo_cost_on_this_jaxlib():
    """cost_analysis availability guard: on this jax/jaxlib the chain works
    and a single matmul's count matches 2*M*N*K, so flops_crosscheck
    reports source='hlo'. If a future jaxlib breaks the API, hlo_cost must
    degrade to None (and the crosscheck to 'analytic') — both arms are the
    pinned contract."""
    m, k, n = 32, 64, 16
    f = jax.jit(lambda a, b: a @ b)
    a = jax.ShapeDtypeStruct((m, k), jnp.float32)
    b = jax.ShapeDtypeStruct((k, n), jnp.float32)
    hlo = hlo_cost(f, a, b)
    analytic = 2.0 * m * k * n
    if hlo is None:  # legal degradation on a drifted jaxlib
        assert flops_crosscheck(analytic, hlo)["flops_source"] == "analytic"
        return
    assert hlo["flops"] > 0
    check = flops_crosscheck(analytic, hlo)
    assert check["flops_source"] == "hlo"
    assert check["rel_err"] < 0.10


def test_hlo_cost_unavailable_paths():
    assert hlo_cost(lambda x: x, 1) is None          # not jitted: no .lower
    assert flops_crosscheck(100.0, None) == {
        "flops_source": "analytic", "hlo_flops": None, "rel_err": None}
    # >10% divergence: the analytic formula stays authoritative.
    far = flops_crosscheck(100.0, {"flops": 150.0, "bytes_accessed": None})
    assert far["flops_source"] == "analytic"
    assert far["rel_err"] == pytest.approx(0.5)
    near = flops_crosscheck(100.0, {"flops": 105.0, "bytes_accessed": None})
    assert near["flops_source"] == "hlo"


def test_hlo_cost_normalize_variants():
    from ddl25spring_tpu.telemetry.costs import _normalize
    assert _normalize([{"flops": 10.0}]) == {"flops": 10.0,
                                             "bytes_accessed": None}
    assert _normalize({"flops": 10.0, "bytes accessed": 5.0}) == {
        "flops": 10.0, "bytes_accessed": 5.0}
    assert _normalize({"flops": -1}) is None          # some backends' "n/a"
    assert _normalize(None) is None
    assert _normalize([]) is None


# -------------------------------------------- heartbeat + watchdog stall

def test_heartbeat_roundtrip_and_seq(tmp_path):
    path = str(tmp_path / "heartbeat.json")
    hb = Heartbeat(path)
    assert hb.beat(step=3)
    assert hb.beat(step=4, phase="train")
    got = read_heartbeat(path)
    assert got["step"] == 4 and got["seq"] == 2 and got["phase"] == "train"
    assert got["pid"] == os.getpid()
    # Unreadable/missing/torn files degrade to None, never raise.
    assert read_heartbeat(str(tmp_path / "missing.json")) is None
    with open(path, "w") as f:
        f.write('{"torn')
    assert read_heartbeat(path) is None


def test_liveness_monitor_heartbeat_stall_detection(tmp_path):
    """The watchdog's first-class heartbeat signal: seq advancing proves
    life with zero progress-file growth; neither signal moving is a stall;
    a NEW WRITER (pid change, seq restart) is life, not a stall."""
    from experiments.watchdog import LivenessMonitor
    progress = tmp_path / "progress.csv"
    progress.write_text("iter,loss\n")
    hb_path = str(tmp_path / "heartbeat.json")
    hb = Heartbeat(hb_path)
    hb.beat(step=0)

    mon = LivenessMonitor(str(progress), hb_path)
    assert mon.poll() is False                  # nothing moved since init
    hb.beat(step=1)                             # heartbeat only, no CSV row
    assert mon.poll() is True
    assert mon.poll() is False                  # stalled again
    progress.write_text("iter,loss\n0,2.5\n")   # CSV only, no beat
    assert mon.poll() is True
    # Relaunch: a fresh writer's seq restarts at 1 with a different pid —
    # that must register as movement even though 1 < the old seq.
    with open(hb_path, "w") as f:
        json.dump({"schema": 1, "pid": os.getpid() + 1, "step": 0, "seq": 1,
                   "time": 0.0, "monotonic": 0.0}, f)
    assert mon.poll() is True
    # Heartbeat file vanishing is "no signal", not movement.
    os.unlink(hb_path)
    assert mon.poll() is False


def test_liveness_monitor_without_heartbeat(tmp_path):
    """No --heartbeat: exactly the legacy growth-only behavior."""
    from experiments.watchdog import LivenessMonitor
    progress = tmp_path / "progress.csv"
    mon = LivenessMonitor(str(progress))        # file doesn't exist yet
    assert mon.poll() is False
    progress.write_text("a\n")
    assert mon.poll() is True
    assert mon.poll() is False


# ----------------------------------------------------- metrics registry

def test_registry_percentiles_and_snapshot():
    reg = MetricsRegistry()
    for v in range(1, 101):                     # 1..100
        reg.observe("t", float(v))
    pcts = reg.percentiles("t")
    assert pcts["p50"] == pytest.approx(50.5)
    assert pcts["p95"] == pytest.approx(95.05)
    assert pcts["p99"] == pytest.approx(99.01)
    reg.counter_inc("n", 2)
    reg.gauge_set("g", 7.0)
    with pytest.raises(ValueError):
        reg.counter_inc("n", -1)
    snap = reg.snapshot()
    assert snap["counters"]["n"] == 2.0 and snap["gauges"]["g"] == 7.0
    h = snap["histograms"]["t"]
    assert h["count"] == 100 and h["max"] == 100.0
    assert reg.percentiles("missing") == {}


def test_registry_absorbs_resilience_completely():
    """The adapter iterates the stats object's own fields: EVERY counter —
    including any future one — lands in the registry."""
    reg = MetricsRegistry()
    stats = ResilienceStats(skipped_steps=2, preemptions=1)
    reg.absorb_resilience(stats)
    for name in stats.as_dict():
        assert reg.counter(f"faults/{name}") == getattr(stats, name)


def test_resilience_stats_merge_field_completeness():
    """A newly added counter must not be silently dropped by merge/as_dict:
    both walk the dataclass's own fields, pinned here field-by-field."""
    fields = [f.name for f in dataclasses.fields(ResilienceStats)]
    a = ResilienceStats(**{f: i + 1 for i, f in enumerate(fields)})
    b = ResilienceStats(**{f: 100 * (i + 1) for i, f in enumerate(fields)})
    a.merge(b)
    for i, f in enumerate(fields):
        assert getattr(a, f) == 101 * (i + 1), f"merge dropped {f!r}"
    assert set(a.as_dict()) == set(fields)
    assert a.total_faults_handled == sum(101 * (i + 1)
                                         for i in range(len(fields)))
    # delta walks the same fields: every moved counter appears, none else.
    assert a.delta(b.as_dict()) == {f: i + 1
                                    for i, f in enumerate(fields)}
    assert a.delta(a.as_dict()) == {}


# ------------------------------------------------- tracing satellites

def test_step_timer_tick_before_start_raises():
    t = StepTimer()
    with pytest.raises(RuntimeError):
        t.tick()
    t.start()
    assert t.tick() >= 0.0 and len(t.times) == 1


def test_resultsink_concurrent_header_widening(tmp_path):
    """8 threads append records with PROGRESSIVELY WIDER field sets into one
    sink: no row may be lost to a widening rewrite racing an append, and
    the final header must be the union of all fields."""
    path = str(tmp_path / "out.csv")
    sink = ResultSink(path)

    def writer(tid):
        for i in range(25):
            row = {"iter": i, "thread": tid}
            if i >= 10:
                row[f"extra_{tid}"] = i       # per-thread widening field
            sink.write(row)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    import csv as _csv
    with open(path, newline="") as f:
        rows = list(_csv.DictReader(f))
    assert len(rows) == 8 * 25                     # zero rows dropped
    header = rows[0].keys()
    assert {"iter", "thread", *{f"extra_{t}" for t in range(8)}} <= set(header)
    for t in range(8):                             # every thread's tail rows
        tail = [r for r in rows
                if r["thread"] == str(t) and r[f"extra_{t}"] != ""]
        assert len(tail) == 15


# ------------------------------------------------- end-to-end integration

def test_trainer_telemetry_end_to_end(tmp_path, devices):
    """train_llm_dp with a Telemetry attached: valid JSONL stream (manifest
    with EXACT static comm bytes, step cadence, run_end snapshot) plus a
    live heartbeat — the acceptance flow obs_report renders."""
    n = 2
    with Telemetry(str(tmp_path / "run"), step_every=2) as tel:
        from ddl25spring_tpu.train.llm import train_llm_dp
        report = train_llm_dp(
            model_cfg=TINY,
            train_cfg=TrainConfig(batch_size=2, seq_len=16, iters=5,
                                  lr=3e-3, data=n),
            mesh=make_mesh({"data": n}, devices=devices[:n]),
            tokenizer=ByteTokenizer(), log_every=0, telemetry=tel)
        events = read_events(tel.events_path, strict=True)
    by_type = {}
    for e in events:
        by_type.setdefault(e["type"], []).append(e)
    manifest = by_type["manifest"][0]
    assert manifest["trainer"] == "dp" and manifest["mesh"] == {"data": n}
    params = llama.init_llama(jax.random.key(0), TINY)
    comm = manifest["comm"]["collectives"]
    assert comm["grad_allreduce"]["payload_bytes"] == _param_bytes(params, 4)
    assert [e["it"] for e in by_type["step"]] == [0, 2, 4]
    run_end = by_type["run_end"][0]
    assert run_end["steps"] == report.steps == 5
    snap = run_end["metrics"]
    assert snap["histograms"]["host_iter_s"]["count"] == 5
    assert snap["gauges"]["phase/dispatch_s"] > 0
    hb = read_heartbeat(tel.heartbeat_path)
    assert hb["step"] == 5 and hb["phase"] == "done"
    # The renderer consumes what the trainers emit (acceptance criterion).
    from experiments.obs_report import main as report_main
    assert report_main([str(tmp_path / "run")]) == 0


def test_trainer_telemetry_chunked_dispatch(tmp_path, devices):
    """Chunked mode (steps_per_dispatch=2): the manifest's comm profile
    covers one DISPATCH with the per-train-step normalization alongside
    (CommProfile.as_dict), step events land on chunk edges carrying the
    window size, and obs_report still renders the run."""
    n = 2
    with Telemetry(str(tmp_path / "run"), step_every=2) as tel:
        from ddl25spring_tpu.train.llm import train_llm_dp
        report = train_llm_dp(
            model_cfg=TINY,
            train_cfg=TrainConfig(batch_size=2, seq_len=16, iters=6,
                                  lr=3e-3, data=n, steps_per_dispatch=2),
            mesh=make_mesh({"data": n}, devices=devices[:n]),
            tokenizer=ByteTokenizer(), log_every=0, telemetry=tel)
        events = read_events(tel.events_path, strict=True)
    by_type = {}
    for e in events:
        by_type.setdefault(e["type"], []).append(e)
    comm = by_type["manifest"][0]["comm"]
    assert comm["steps_per_dispatch"] == 2
    # One dispatch = 2 recorded steps of traffic; the normalization halves.
    assert comm["payload_bytes_per_train_step"] == pytest.approx(
        comm["payload_bytes_per_step"] / 2)
    params = llama.init_llama(jax.random.key(0), TINY)
    assert comm["collectives"]["grad_allreduce"]["payload_bytes"] == \
        2 * _param_bytes(params, 4)
    steps = by_type["step"]
    assert [e["it"] for e in steps] == [1, 3, 5]   # chunk edges
    assert all(e["steps_per_dispatch"] == 2 for e in steps)
    assert steps[0].get("warmup") is True          # compile chunk flagged
    assert by_type["run_end"][0]["steps"] == report.steps == 6
    assert len(report.losses) == 6
    from experiments.obs_report import main as report_main
    assert report_main([str(tmp_path / "run")]) == 0


def test_fl_server_emits_round_events(tmp_path):
    """FL servers report through the same stream: one fl_round per round
    with accuracy/wall/messages, plus manifest and run_end."""
    from ddl25spring_tpu.config import FLConfig
    from ddl25spring_tpu.data import mnist
    from ddl25spring_tpu.fl import FedAvgServer, federate
    from ddl25spring_tpu.models import mnist_cnn
    x_raw, y, xt_raw, yt = mnist.load_mnist(n_train=300, n_test=100, seed=0)
    x, xt = mnist.normalize(x_raw), mnist.normalize(xt_raw)
    cfg = FLConfig(nr_clients=6, client_fraction=0.5, batch_size=50,
                   epochs=1, lr=0.05, rounds=2, seed=3)
    data = federate(x, y.astype(np.int32),
                    mnist.split(y, cfg.nr_clients, iid=True, seed=cfg.seed))
    with Telemetry(str(tmp_path / "fl")) as tel:
        server = FedAvgServer(mnist_cnn.init(jax.random.key(0)),
                              mnist_cnn.apply, data, xt,
                              yt.astype(np.int32), cfg, telemetry=tel)
        result = server.run(2)
        events = read_events(tel.events_path, strict=True)
    rounds = [e for e in events if e["type"] == "fl_round"]
    assert [r["round"] for r in rounds] == [0, 1]
    assert rounds[-1]["test_accuracy"] == result.test_accuracy[-1]
    assert rounds[-1]["messages"] == result.message_count[-1]
    end = [e for e in events if e["type"] == "run_end"][-1]
    assert end["final_accuracy"] == result.test_accuracy[-1]
    assert read_heartbeat(tel.heartbeat_path)["seq"] == 2
