"""Telemetry layer (ddl25spring_tpu/telemetry) + observability satellites.

Pins the ISSUE-2 contracts: event-schema round-trip (incl. torn-final-line
crash tolerance and concurrent writers), EXACT static comm-volume bytes for
known DP configs (fp32 vs the compressed wire formats), heartbeat-based
stall detection in the watchdog's LivenessMonitor, cost_analysis guard
behavior on this jaxlib, thread-safe ResultSink header widening,
ResilienceStats.merge field completeness, and StepTimer misuse raising.
"""

import dataclasses
import json
import math
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl25spring_tpu.config import LlamaConfig, TrainConfig
from ddl25spring_tpu.metrics import ResilienceStats
from ddl25spring_tpu.models import llama
from ddl25spring_tpu.parallel import compress, dp, make_mesh
from ddl25spring_tpu.telemetry import (EventLog, Heartbeat, MetricsRegistry,
                                       SCHEMA_VERSION, Telemetry,
                                       flops_crosscheck, hlo_cost,
                                       measure_comm, read_events,
                                       read_heartbeat, validate_event)
from ddl25spring_tpu.tokenizers import ByteTokenizer
from ddl25spring_tpu.utils.tracing import ResultSink, StepTimer

TINY = LlamaConfig(vocab_size=259, dmodel=16, num_heads=2, n_layers=2,
                   ctx_size=16)


# ----------------------------------------------------------- event stream

def test_eventlog_schema_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="r1") as log:
        log.manifest(jax_version=jax.__version__, platform="cpu")
        log.step(it=0, loss=2.5, dt_s=0.1)
        log.fault(counters={"skipped_steps": 1}, it=3)
        log.fl_round(round=0, wall_s=0.2, test_accuracy=0.5)
        log.run_end(steps=10, metrics={"counters": {}})
    events = read_events(path, strict=True)  # strict: validates every event
    assert [e["type"] for e in events] == [
        "manifest", "step", "fault", "fl_round", "run_end"]
    assert [e["seq"] for e in events] == [1, 2, 3, 4, 5]
    assert all(e["run_id"] == "r1" for e in events)
    assert all(e["schema"] == SCHEMA_VERSION for e in events)
    assert events[1]["loss"] == 2.5 and events[1]["it"] == 0
    # type filter
    assert [e["it"] for e in read_events(path, types=("step",))] == [0]


def test_eventlog_torn_final_line_and_corruption(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="r1") as log:
        log.step(it=0, loss=1.0)
        log.step(it=1, loss=2.0)
    with open(path, "ab") as f:
        f.write(b'{"schema": 1, "run_id": "r1", "seq": 3, "t": 0, "ty')
    # A torn FINAL line is a crash artifact, dropped even under strict.
    assert [e["it"] for e in read_events(path, strict=True)] == [0, 1]
    # Mid-file garbage is corruption: skipped lax, raised strict.
    with open(path, "ab") as f:
        f.write(b'rbage\n')
        f.write(json.dumps({"schema": 1, "run_id": "r1", "seq": 4, "t": 0,
                            "type": "step", "it": 2}).encode() + b"\n")
    assert [e["it"] for e in read_events(path)] == [0, 1, 2]
    with pytest.raises(ValueError):
        read_events(path, strict=True)
    # Valid JSON that is not an object (`null`, a number) is the same
    # corruption class: skipped lax (with a types filter too), raised
    # strict — never leaked to crash a consumer's `.get`.
    path2 = str(tmp_path / "nondict.jsonl")
    with open(path2, "w") as f:
        f.write('null\n')
        f.write(json.dumps({"schema": 1, "run_id": "r", "seq": 1, "t": 0,
                            "type": "step", "it": 0}) + "\n")
    assert [e["it"] for e in read_events(path2, types=("step",))] == [0]
    with pytest.raises(ValueError):
        read_events(path2, strict=True)


def test_eventlog_reopen_heals_torn_fragment(tmp_path):
    """A relaunch reusing the telemetry dir truncates a crashed
    predecessor's torn final line instead of appending onto it — the
    fragment must not become mid-file corruption that strict readers
    raise on."""
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="r1") as log:
        log.step(it=0, loss=1.0)
    with open(path, "ab") as f:
        f.write(b'{"schema": 1, "run_id": "r1", "seq": 2, "t": 0, "ty')
    with EventLog(path, run_id="r2") as log:
        log.manifest(jax_version="test", platform="cpu")
    events = read_events(path, strict=True)
    assert [e["run_id"] for e in events] == ["r1", "r2"]


def test_eventlog_emit_never_raises(tmp_path):
    """IO failure drops the event and counts (same never-sink-a-trainer
    policy as Heartbeat.beat) — including emits after close()."""
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, run_id="r1")
    log.step(it=0, loss=1.0)
    log.close()
    record = log.emit("step", it=1, loss=2.0)   # must not raise
    assert record["it"] == 1 and log.write_errors == 1
    assert [e["it"] for e in read_events(path)] == [0]
    # Serialization failures count too: _json_fallback can't save
    # non-string dict keys, and json.dumps' TypeError must not escape.
    log2 = EventLog(path, run_id="r2")
    log2.emit("custom", data={(0, 1): "tuple-keyed"})
    assert log2.write_errors == 1
    log2.step(it=2, loss=3.0)                   # stream still usable
    log2.close()
    assert [e["it"] for e in read_events(path, strict=True)] == [0, 2]


def test_eventlog_heal_scans_backwards_across_chunks(tmp_path):
    """The reopen-heal finds the last newline by scanning backwards in
    64 KiB chunks — a fragment longer than one chunk (a crash mid-way
    through a huge manifest) must still truncate to the right offset."""
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="r1") as log:
        log.step(it=0, loss=1.0)
    with open(path, "ab") as f:
        f.write(b'{"pad": "' + b"x" * (200 * 1024))  # 200 KiB torn line
    with EventLog(path, run_id="r2") as log:
        log.step(it=1, loss=2.0)
    assert [e["it"] for e in read_events(path, strict=True)] == [0, 1]


def test_eventlog_partial_write_seals_torn_tail(tmp_path, monkeypatch):
    """ENOSPC mid-line: os.write lands SOME bytes then fails. The failed
    event counts as a write error, and the next successful emit seals the
    fragment with a newline so it stays ONE skippable malformed line
    instead of merging into (and corrupting) the next event."""
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, run_id="r1")
    log.step(it=0, loss=1.0)

    real_write = os.write
    calls = []

    # POSIX write(2) semantics for a disk filling mid-line: the first call
    # writes what fits and returns SHORT; the retry gets ENOSPC.
    def short_then_fail(fd, data):
        if fd == log._fd:
            calls.append(len(data))
            if len(calls) == 1:
                return real_write(fd, bytes(data)[:10])
            raise OSError(28, "No space left on device")
        return real_write(fd, data)

    monkeypatch.setattr(os, "write", short_then_fail)
    log.step(it=1, loss=2.0)                  # partially lands, counted
    monkeypatch.setattr(os, "write", real_write)
    log.step(it=2, loss=3.0)                  # must seal, then append
    log.close()
    assert log.write_errors == 1
    assert [e["it"] for e in read_events(path)] == [0, 2]
    with pytest.raises(ValueError):           # the fragment IS corruption
        read_events(path, strict=True)


def test_eventlog_nonfinite_floats_stay_strict_json(tmp_path):
    """An unguarded chaos run can hand emit() loss=nan — the stream must
    stay STRICT JSON (the CI artifact is consumed by jq/non-Python
    readers), so non-finite floats land as their str(), never as the
    NaN/Infinity tokens json.dumps writes by default."""
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="r1") as log:
        log.step(it=0, loss=float("nan"),
                 extra=[float("inf"), np.float32("nan")])
    raw = open(path).read()
    assert "NaN" not in raw and "Infinity" not in raw
    assert log.write_errors == 0
    (event,) = read_events(path, strict=True)
    assert event["loss"] == "nan" and event["extra"][0] == "inf"


def test_telemetry_step_every_floor(tmp_path):
    """step_every=0 ('disable step events') must not arm a
    ZeroDivisionError inside the training loop's `it % step_every`."""
    tel = Telemetry(str(tmp_path / "run"), step_every=0)
    assert tel.step_every == 1
    tel.close()


def test_validate_event_contract():
    base = {"schema": SCHEMA_VERSION, "run_id": "r", "seq": 1, "t": 0.0}
    assert validate_event({**base, "type": "step", "it": 3}) == []
    # Per-type required fields.
    assert validate_event({**base, "type": "step"}) != []
    # The type set is CLOSED per schema version: an unknown type at/below
    # the reader's version is a typo, not forward compat — and the problem
    # names it.
    problems = validate_event({**base, "type": "novel_event"})
    assert problems and "novel_event" in problems[0]
    # A FUTURE schema version is a problem; missing base fields are too.
    assert validate_event({**base, "schema": SCHEMA_VERSION + 1,
                           "type": "step", "it": 0}) != []
    assert validate_event({"type": "step", "it": 0}) != []


def test_validate_event_forward_version_names_offender():
    """A vN+1 writer against this reader used to fail with only 'schema N+1
    is newer' — the message must now NAME the event type that carried the
    future version, and an unknown type riding a future schema must be
    reported as the version skew it is, not double-flagged as a typo."""
    base = {"run_id": "r", "seq": 1, "t": 0.0}
    problems = validate_event({**base, "schema": SCHEMA_VERSION + 1,
                               "type": "hologram"})
    assert len(problems) == 1
    assert "hologram" in problems[0]
    assert str(SCHEMA_VERSION + 1) in problems[0]
    # Same unknown type AT the reader's version: flagged as unknown, with
    # the version it claimed.
    problems = validate_event({**base, "schema": SCHEMA_VERSION,
                               "type": "hologram"})
    assert len(problems) == 1 and "unknown event type" in problems[0]


def test_request_event_emitters_roundtrip(tmp_path):
    """Schema v2: the serving lifecycle's four typed emitters produce
    valid, strictly-readable events carrying their required fields."""
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="srv") as log:
        log.request_enqueue(req="r-1", prompt_len=8, max_new=4,
                            temperature=0.8, queued=1)
        log.request_prefill(req="r-1", slot=2, blocks=3, queue_wait_s=0.01,
                            blocks_in_use=3)
        log.request_token(req="r-1", i=0, tok=17, slot=2)
        log.request_done(req="r-1", tokens=4, queue_wait_s=0.01,
                         ttft_s=0.05, tokens_per_sec=80.0, blocks_freed=3,
                         blocks_in_use=0)
    events = read_events(path, strict=True)    # strict = validate_event
    assert [e["type"] for e in events] == [
        "request_enqueue", "request_prefill", "request_token",
        "request_done"]
    assert all(e["schema"] == SCHEMA_VERSION for e in events)
    assert events[1]["slot"] == 2 and events[3]["tokens"] == 4


def test_validate_event_request_required_fields():
    """request_* events missing their per-type required fields must be
    flagged — the schema bump added real rows, not just names."""
    base = {"schema": SCHEMA_VERSION, "run_id": "r", "seq": 1, "t": 0.0}
    assert validate_event({**base, "type": "request_enqueue",
                           "req": "a"}) == []
    assert validate_event({**base, "type": "request_enqueue"}) != []
    assert validate_event({**base, "type": "request_prefill",
                           "req": "a"}) != []        # missing slot
    assert validate_event({**base, "type": "request_token",
                           "req": "a"}) != []        # missing i
    assert validate_event({**base, "type": "request_done",
                           "req": "a"}) != []        # missing tokens
    assert validate_event({**base, "type": "request_done", "req": "a",
                           "tokens": 3}) == []
    # v1 streams (all pre-serving types) remain valid under the v2 reader.
    assert validate_event({**base, "schema": 1, "type": "step",
                           "it": 0}) == []


def test_fleet_event_emitters_roundtrip(tmp_path):
    """Schema v3: the fleet FL emitters (fl_cohort / fl_tier) produce
    valid, strictly-readable events carrying their required fields."""
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="fleet") as log:
        log.fl_cohort(round=0, tier="edge", cohort=3, edge=1, clients=64,
                      payload_bytes=64 * 1320)
        log.fl_tier(round=0, tier="edge", edges=4, clients=256,
                    payload_bytes=256 * 1320, wire="float32")
        log.fl_tier(round=0, tier="server", inputs=4,
                    payload_bytes=4 * 1320)
    events = read_events(path, strict=True)
    assert [e["type"] for e in events] == ["fl_cohort", "fl_tier",
                                           "fl_tier"]
    assert all(e["schema"] == SCHEMA_VERSION for e in events)
    assert events[0]["clients"] == 64
    assert events[2]["tier"] == "server"


def test_validate_event_fleet_required_fields():
    """fl_cohort / fl_tier events missing their per-type required fields
    must be flagged, and pre-v3 streams stay valid under the v3 reader."""
    base = {"schema": SCHEMA_VERSION, "run_id": "r", "seq": 1, "t": 0.0}
    assert validate_event({**base, "type": "fl_cohort", "round": 0,
                           "tier": "edge", "cohort": 0}) == []
    assert validate_event({**base, "type": "fl_cohort", "round": 0,
                           "tier": "edge"}) != []      # missing cohort
    assert validate_event({**base, "type": "fl_tier", "round": 0,
                           "tier": "server"}) == []
    assert validate_event({**base, "type": "fl_tier", "round": 0}) != []
    # v2 streams (serving lifecycle) remain valid under the v3 reader.
    assert validate_event({**base, "schema": 2, "type": "request_done",
                           "req": "a", "tokens": 3}) == []


def test_eventlog_concurrent_writers(tmp_path):
    """10 threads x 50 events through one log: every event lands intact
    (one write() under the lock), seq is a permutation of 1..500."""
    path = str(tmp_path / "events.jsonl")
    log = EventLog(path, run_id="r1")

    def emit(tid):
        for i in range(50):
            log.emit("step", it=i, thread=tid)

    threads = [threading.Thread(target=emit, args=(t,)) for t in range(10)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()
    events = read_events(path, strict=True)
    assert len(events) == 500
    assert sorted(e["seq"] for e in events) == list(range(1, 501))


# ------------------------------------------------- comm-volume accounting

def _param_bytes(params, itemsize):
    return sum(math.prod(l.shape) for l in jax.tree.leaves(params)) * itemsize


def test_comm_exact_bytes_dp_fp32(devices):
    """The known-config contract: a data=2 DP gradient-aggregation step
    moves EXACTLY n_params fp32 elements through grad_allreduce plus one
    scalar loss, with ring wire factor 2*(n-1)/n = 1.0 at n=2."""
    n = 2
    mesh = make_mesh({"data": n}, devices=devices[:n])
    params = llama.init_llama(jax.random.key(0), TINY)
    opt = optax.adam(1e-3)
    state = dp.replicate(mesh, dp.init_state(params, opt))
    step = dp.make_grad_aggregation_step(
        lambda p, b: llama.forward_loss(p, b, TINY), opt, mesh)
    batch = jax.ShapeDtypeStruct((n * 2, TINY.ctx_size), jnp.int32)
    profile = measure_comm(step, state, batch)
    assert profile is not None and profile.records
    by = profile.by_label()
    expected = _param_bytes(params, 4)                 # fp32 wire
    assert by["grad_allreduce"]["payload_bytes"] == expected
    assert by["grad_allreduce"]["axis_size"] == n
    assert by["loss_allreduce"]["payload_bytes"] == 4  # one fp32 scalar
    # Ring allreduce at n=2: 2*(n-1)/n = 1.0 -> wire == payload.
    assert by["grad_allreduce"]["wire_bytes_per_device"] == expected
    assert profile.payload_bytes_per_step == expected + 4


def test_comm_bf16_wire_halves_payload(devices):
    """The compression lever the accounting exists to measure: the bf16
    wire format's grad collective carries exactly HALF the fp32 bytes."""
    n = 2
    mesh = make_mesh({"data": n}, devices=devices[:n])
    params = llama.init_llama(jax.random.key(0), TINY)
    opt = optax.adam(1e-3)
    state = dp.replicate(mesh, dp.init_state(params, opt))
    step = compress.make_bf16_grad_step(
        lambda p, b: llama.forward_loss(p, b, TINY), opt, mesh)
    batch = jax.ShapeDtypeStruct((n * 2, TINY.ctx_size), jnp.int32)
    profile = measure_comm(step, state, batch)
    by = profile.by_label()
    assert by["grad_allreduce_bf16"]["payload_bytes"] == _param_bytes(params, 2)


def test_comm_scale_multiplies_scan_trips():
    """A record's ``scale`` (scan trip count) multiplies the per-step
    aggregate — the mechanism the PP/SP ring call sites rely on."""
    from ddl25spring_tpu.telemetry.comm import CommProfile, CommRecord
    r = CommRecord(op="ppermute", label="hop", axis="stage", axis_size=4,
                   payload_bytes=100, scale=6)
    p = CommProfile([r])
    assert p.payload_bytes_per_step == 600
    assert p.by_label()["hop"]["calls"] == 6
    assert r.wire_bytes_per_device == 100.0      # one neighbor send per exec


def test_measure_comm_handles_cached_trace():
    """A step whose trace is already cached must still produce records
    (the one-retry-after-clear_caches path in measure_comm)."""
    @jax.jit
    def f(x):
        from ddl25spring_tpu.telemetry import comm
        return comm.psum(x, "i", label="row_sum")

    vx = jax.ShapeDtypeStruct((8, 4), jnp.float32)

    def mapped(x):
        return jax.vmap(f, axis_name="i")(x)

    first = measure_comm(mapped, vx)
    second = measure_comm(mapped, vx)      # cache-warm path
    # Accounting is per-participant: the operand INSIDE the mapped axis is
    # the [4] f32 local row, and the axis resolves to its 8 participants.
    assert first.by_label()["row_sum"]["payload_bytes"] == 4 * 4
    assert first.by_label()["row_sum"]["axis_size"] == 8
    assert second.by_label()["row_sum"]["payload_bytes"] == 4 * 4


# ------------------------------------------------------- HLO cost guard

def test_hlo_cost_on_this_jaxlib():
    """cost_analysis availability guard: on this jax/jaxlib the chain works
    and a single matmul's count matches 2*M*N*K, so flops_crosscheck
    reports source='hlo'. If a future jaxlib breaks the API, hlo_cost must
    degrade to None (and the crosscheck to 'analytic') — both arms are the
    pinned contract."""
    m, k, n = 32, 64, 16
    f = jax.jit(lambda a, b: a @ b)
    a = jax.ShapeDtypeStruct((m, k), jnp.float32)
    b = jax.ShapeDtypeStruct((k, n), jnp.float32)
    hlo = hlo_cost(f, a, b)
    analytic = 2.0 * m * k * n
    if hlo is None:  # legal degradation on a drifted jaxlib
        assert flops_crosscheck(analytic, hlo)["flops_source"] == "analytic"
        return
    assert hlo["flops"] > 0
    check = flops_crosscheck(analytic, hlo)
    assert check["flops_source"] == "hlo"
    assert check["rel_err"] < 0.10


def test_hlo_cost_unavailable_paths():
    assert hlo_cost(lambda x: x, 1) is None          # not jitted: no .lower
    assert flops_crosscheck(100.0, None) == {
        "flops_source": "analytic", "hlo_flops": None, "rel_err": None}
    # >10% divergence: the analytic formula stays authoritative.
    far = flops_crosscheck(100.0, {"flops": 150.0, "bytes_accessed": None})
    assert far["flops_source"] == "analytic"
    assert far["rel_err"] == pytest.approx(0.5)
    near = flops_crosscheck(100.0, {"flops": 105.0, "bytes_accessed": None})
    assert near["flops_source"] == "hlo"


def test_hlo_cost_normalize_variants():
    from ddl25spring_tpu.telemetry.costs import _normalize
    assert _normalize([{"flops": 10.0}]) == {"flops": 10.0,
                                             "bytes_accessed": None}
    assert _normalize({"flops": 10.0, "bytes accessed": 5.0}) == {
        "flops": 10.0, "bytes_accessed": 5.0}
    assert _normalize({"flops": -1}) is None          # some backends' "n/a"
    assert _normalize(None) is None
    assert _normalize([]) is None


# -------------------------------------------- heartbeat + watchdog stall

def test_heartbeat_roundtrip_and_seq(tmp_path):
    path = str(tmp_path / "heartbeat.json")
    hb = Heartbeat(path)
    assert hb.beat(step=3)
    assert hb.beat(step=4, phase="train")
    got = read_heartbeat(path)
    assert got["step"] == 4 and got["seq"] == 2 and got["phase"] == "train"
    assert got["pid"] == os.getpid()
    # Unreadable/missing/torn files degrade to None, never raise.
    assert read_heartbeat(str(tmp_path / "missing.json")) is None
    with open(path, "w") as f:
        f.write('{"torn')
    assert read_heartbeat(path) is None


def test_liveness_monitor_heartbeat_stall_detection(tmp_path):
    """The watchdog's first-class heartbeat signal: seq advancing proves
    life with zero progress-file growth; neither signal moving is a stall;
    a NEW WRITER (pid change, seq restart) is life, not a stall."""
    from experiments.watchdog import LivenessMonitor
    progress = tmp_path / "progress.csv"
    progress.write_text("iter,loss\n")
    hb_path = str(tmp_path / "heartbeat.json")
    hb = Heartbeat(hb_path)
    hb.beat(step=0)

    mon = LivenessMonitor(str(progress), hb_path)
    assert mon.poll() is False                  # nothing moved since init
    hb.beat(step=1)                             # heartbeat only, no CSV row
    assert mon.poll() is True
    assert mon.poll() is False                  # stalled again
    progress.write_text("iter,loss\n0,2.5\n")   # CSV only, no beat
    assert mon.poll() is True
    # Relaunch: a fresh writer's seq restarts at 1 with a different pid —
    # that must register as movement even though 1 < the old seq.
    with open(hb_path, "w") as f:
        json.dump({"schema": 1, "pid": os.getpid() + 1, "step": 0, "seq": 1,
                   "time": 0.0, "monotonic": 0.0}, f)
    assert mon.poll() is True
    # Heartbeat file vanishing is "no signal", not movement.
    os.unlink(hb_path)
    assert mon.poll() is False


def test_liveness_monitor_without_heartbeat(tmp_path):
    """No --heartbeat: exactly the legacy growth-only behavior."""
    from experiments.watchdog import LivenessMonitor
    progress = tmp_path / "progress.csv"
    mon = LivenessMonitor(str(progress))        # file doesn't exist yet
    assert mon.poll() is False
    progress.write_text("a\n")
    assert mon.poll() is True
    assert mon.poll() is False


# ----------------------------------------------------- metrics registry

def test_registry_percentiles_and_snapshot():
    reg = MetricsRegistry()
    for v in range(1, 101):                     # 1..100
        reg.observe("t", float(v))
    pcts = reg.percentiles("t")
    assert pcts["p50"] == pytest.approx(50.5)
    assert pcts["p95"] == pytest.approx(95.05)
    assert pcts["p99"] == pytest.approx(99.01)
    reg.counter_inc("n", 2)
    reg.gauge_set("g", 7.0)
    with pytest.raises(ValueError):
        reg.counter_inc("n", -1)
    snap = reg.snapshot()
    assert snap["counters"]["n"] == 2.0 and snap["gauges"]["g"] == 7.0
    h = snap["histograms"]["t"]
    assert h["count"] == 100 and h["max"] == 100.0
    assert reg.percentiles("missing") == {}


def test_registry_absorbs_resilience_completely():
    """The adapter iterates the stats object's own fields: EVERY counter —
    including any future one — lands in the registry."""
    reg = MetricsRegistry()
    stats = ResilienceStats(skipped_steps=2, preemptions=1)
    reg.absorb_resilience(stats)
    for name in stats.as_dict():
        assert reg.counter(f"faults/{name}") == getattr(stats, name)


def test_resilience_stats_merge_field_completeness():
    """A newly added counter must not be silently dropped by merge/as_dict:
    both walk the dataclass's own fields, pinned here field-by-field."""
    fields = [f.name for f in dataclasses.fields(ResilienceStats)]
    a = ResilienceStats(**{f: i + 1 for i, f in enumerate(fields)})
    b = ResilienceStats(**{f: 100 * (i + 1) for i, f in enumerate(fields)})
    a.merge(b)
    for i, f in enumerate(fields):
        assert getattr(a, f) == 101 * (i + 1), f"merge dropped {f!r}"
    assert set(a.as_dict()) == set(fields)
    assert a.total_faults_handled == sum(101 * (i + 1)
                                         for i in range(len(fields)))
    # delta walks the same fields: every moved counter appears, none else.
    assert a.delta(b.as_dict()) == {f: i + 1
                                    for i, f in enumerate(fields)}
    assert a.delta(a.as_dict()) == {}


# ------------------------------------------------- tracing satellites

def test_step_timer_tick_before_start_raises():
    t = StepTimer()
    with pytest.raises(RuntimeError):
        t.tick()
    t.start()
    assert t.tick() >= 0.0 and len(t.times) == 1


def test_resultsink_concurrent_header_widening(tmp_path):
    """8 threads append records with PROGRESSIVELY WIDER field sets into one
    sink: no row may be lost to a widening rewrite racing an append, and
    the final header must be the union of all fields."""
    path = str(tmp_path / "out.csv")
    sink = ResultSink(path)

    def writer(tid):
        for i in range(25):
            row = {"iter": i, "thread": tid}
            if i >= 10:
                row[f"extra_{tid}"] = i       # per-thread widening field
            sink.write(row)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    import csv as _csv
    with open(path, newline="") as f:
        rows = list(_csv.DictReader(f))
    assert len(rows) == 8 * 25                     # zero rows dropped
    header = rows[0].keys()
    assert {"iter", "thread", *{f"extra_{t}" for t in range(8)}} <= set(header)
    for t in range(8):                             # every thread's tail rows
        tail = [r for r in rows
                if r["thread"] == str(t) and r[f"extra_{t}"] != ""]
        assert len(tail) == 15


# ------------------------------------------------- end-to-end integration

def test_trainer_telemetry_end_to_end(tmp_path, devices):
    """train_llm_dp with a Telemetry attached: valid JSONL stream (manifest
    with EXACT static comm bytes, step cadence, run_end snapshot) plus a
    live heartbeat — the acceptance flow obs_report renders."""
    n = 2
    with Telemetry(str(tmp_path / "run"), step_every=2) as tel:
        from ddl25spring_tpu.train.llm import train_llm_dp
        report = train_llm_dp(
            model_cfg=TINY,
            train_cfg=TrainConfig(batch_size=2, seq_len=16, iters=5,
                                  lr=3e-3, data=n),
            mesh=make_mesh({"data": n}, devices=devices[:n]),
            tokenizer=ByteTokenizer(), log_every=0, telemetry=tel)
        events = read_events(tel.events_path, strict=True)
    by_type = {}
    for e in events:
        by_type.setdefault(e["type"], []).append(e)
    manifest = by_type["manifest"][0]
    assert manifest["trainer"] == "dp" and manifest["mesh"] == {"data": n}
    params = llama.init_llama(jax.random.key(0), TINY)
    comm = manifest["comm"]["collectives"]
    assert comm["grad_allreduce"]["payload_bytes"] == _param_bytes(params, 4)
    assert [e["it"] for e in by_type["step"]] == [0, 2, 4]
    run_end = by_type["run_end"][0]
    assert run_end["steps"] == report.steps == 5
    snap = run_end["metrics"]
    assert snap["histograms"]["host_iter_s"]["count"] == 5
    assert snap["gauges"]["phase/dispatch_s"] > 0
    hb = read_heartbeat(tel.heartbeat_path)
    assert hb["step"] == 5 and hb["phase"] == "done"
    # The renderer consumes what the trainers emit (acceptance criterion).
    from experiments.obs_report import main as report_main
    assert report_main([str(tmp_path / "run")]) == 0


def test_trainer_telemetry_chunked_dispatch(tmp_path, devices):
    """Chunked mode (steps_per_dispatch=2): the manifest's comm profile
    covers one DISPATCH with the per-train-step normalization alongside
    (CommProfile.as_dict), step events land on chunk edges carrying the
    window size, and obs_report still renders the run."""
    n = 2
    with Telemetry(str(tmp_path / "run"), step_every=2) as tel:
        from ddl25spring_tpu.train.llm import train_llm_dp
        report = train_llm_dp(
            model_cfg=TINY,
            train_cfg=TrainConfig(batch_size=2, seq_len=16, iters=6,
                                  lr=3e-3, data=n, steps_per_dispatch=2),
            mesh=make_mesh({"data": n}, devices=devices[:n]),
            tokenizer=ByteTokenizer(), log_every=0, telemetry=tel)
        events = read_events(tel.events_path, strict=True)
    by_type = {}
    for e in events:
        by_type.setdefault(e["type"], []).append(e)
    comm = by_type["manifest"][0]["comm"]
    assert comm["steps_per_dispatch"] == 2
    # One dispatch = 2 recorded steps of traffic; the normalization halves.
    assert comm["payload_bytes_per_train_step"] == pytest.approx(
        comm["payload_bytes_per_step"] / 2)
    params = llama.init_llama(jax.random.key(0), TINY)
    assert comm["collectives"]["grad_allreduce"]["payload_bytes"] == \
        2 * _param_bytes(params, 4)
    steps = by_type["step"]
    assert [e["it"] for e in steps] == [1, 3, 5]   # chunk edges
    assert all(e["steps_per_dispatch"] == 2 for e in steps)
    assert steps[0].get("warmup") is True          # compile chunk flagged
    assert by_type["run_end"][0]["steps"] == report.steps == 6
    assert len(report.losses) == 6
    from experiments.obs_report import main as report_main
    assert report_main([str(tmp_path / "run")]) == 0


def test_fl_server_emits_round_events(tmp_path):
    """FL servers report through the same stream: one fl_round per round
    with accuracy/wall/messages, plus manifest and run_end."""
    from ddl25spring_tpu.config import FLConfig
    from ddl25spring_tpu.data import mnist
    from ddl25spring_tpu.fl import FedAvgServer, federate
    from ddl25spring_tpu.models import mnist_cnn
    x_raw, y, xt_raw, yt = mnist.load_mnist(n_train=300, n_test=100, seed=0)
    x, xt = mnist.normalize(x_raw), mnist.normalize(xt_raw)
    cfg = FLConfig(nr_clients=6, client_fraction=0.5, batch_size=50,
                   epochs=1, lr=0.05, rounds=2, seed=3)
    data = federate(x, y.astype(np.int32),
                    mnist.split(y, cfg.nr_clients, iid=True, seed=cfg.seed))
    with Telemetry(str(tmp_path / "fl")) as tel:
        server = FedAvgServer(mnist_cnn.init(jax.random.key(0)),
                              mnist_cnn.apply, data, xt,
                              yt.astype(np.int32), cfg, telemetry=tel)
        result = server.run(2)
        events = read_events(tel.events_path, strict=True)
    rounds = [e for e in events if e["type"] == "fl_round"]
    assert [r["round"] for r in rounds] == [0, 1]
    assert rounds[-1]["test_accuracy"] == result.test_accuracy[-1]
    assert rounds[-1]["messages"] == result.message_count[-1]
    end = [e for e in events if e["type"] == "run_end"][-1]
    assert end["final_accuracy"] == result.test_accuracy[-1]
    assert read_heartbeat(tel.heartbeat_path)["seq"] == 2


# ------------------------------------------------- span layer (schema v4)

def test_span_context_propagation_roundtrip(tmp_path):
    """The tentpole contract: explicit parent propagation reconstructs the
    exact tree — trace/span/parent ids round-trip through the stream
    (strict-valid under schema v4), SpanContext survives as_dict/from_dict
    across a process boundary, and the reassembled tree has one root,
    zero orphans, children in start order."""
    from ddl25spring_tpu.telemetry.trace import (SpanContext, Tracer,
                                                 trace_trees, tree_check)
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="t") as log:
        tr = Tracer(log)
        with tr.span("round", trace="round-0", round=0) as root:
            # Simulate crossing a process/function boundary: the context
            # travels as a dict, not an object.
            wire = root.ctx.as_dict()
            handed = SpanContext.from_dict(wire)
            assert handed == root.ctx
            with tr.span("tier", parent=handed, tier="edge") as tier:
                with tr.span("cohort", parent=tier.ctx, cohort=0):
                    pass
                with tr.span("cohort", parent=tier.ctx, cohort=1):
                    pass
    events = read_events(path, strict=True)       # v4-valid
    assert all(e["type"] == "span" for e in events)
    trees = trace_trees(events)
    assert set(trees) == {"round-0"}
    t = trees["round-0"]
    assert tree_check(t) == {"roots": 1, "orphans": 0, "imbalanced": 0}
    root_ev = t["roots"][0]
    assert root_ev["name"] == "round" and root_ev["round"] == 0
    (tier_ev,) = t["children"][root_ev["span_id"]]
    cohorts = t["children"][tier_ev["span_id"]]
    assert [c["cohort"] for c in cohorts] == [0, 1]   # start-ns order
    # Parenting is by id, not nesting order of emission (children emit
    # BEFORE their parent closes).
    assert [e["name"] for e in events] == ["cohort", "cohort", "tier",
                                           "round"]


def test_span_orphan_detection(tmp_path):
    """A span naming a never-closed parent must surface as an orphan, not
    silently reattach — that is the self-check obs_report renders."""
    from ddl25spring_tpu.telemetry.trace import trace_trees, tree_check
    base = {"schema": SCHEMA_VERSION, "run_id": "r", "seq": 1, "t": 0.0,
            "type": "span", "trace_id": "x", "start_ns": 0, "dur_ns": 1}
    events = [{**base, "name": "root", "span_id": "s1"},
              {**base, "name": "lost", "span_id": "s9",
               "parent_span_id": "s404"}]
    t = trace_trees(events)["x"]
    assert tree_check(t)["orphans"] == 1
    assert t["orphans"][0]["name"] == "lost"


def test_tracer_phases_adapter_and_opt_out():
    """Tracer(phases=Spans()) is the absorption path: every completed span
    feeds the accumulator (under its phase alias when given), umbrella
    spans opt out with phase=False, and events=None still accumulates —
    un-telemetered runs keep phase accounting through the one path."""
    from ddl25spring_tpu.telemetry.trace import Spans, Tracer
    acc = Spans()
    tr = Tracer(None, phases=acc)
    with tr.span("dispatch", trace="train", phase=False) as root:
        with tr.span("compute", parent=root.ctx, phase="dispatch"):
            pass
        with tr.span("stage", parent=root.ctx, phase="data"):
            pass
    assert acc.count("dispatch") == 1 and acc.count("data") == 1
    assert acc.count("compute") == 0          # filed under the alias
    assert acc.total("dispatch") >= 0.0
    # The umbrella span itself must NOT have double-counted anything.
    assert set(acc.as_dict()) == {"dispatch", "data"}


def test_span_schema_v4_validation_and_v3_backcompat():
    """span/slo_violation are v4 types with real required fields; a v3
    stream (old types at schema 3) stays strictly valid under this
    reader — the bump is additive."""
    base = {"run_id": "r", "seq": 1, "t": 0.0}
    ok = {**base, "schema": SCHEMA_VERSION, "type": "span", "name": "a",
          "trace_id": "t", "span_id": "s1", "start_ns": 0, "dur_ns": 1}
    assert validate_event(ok) == []
    for missing in ("name", "trace_id", "span_id", "start_ns", "dur_ns"):
        bad = {k: v for k, v in ok.items() if k != missing}
        assert validate_event(bad) != [], missing
    assert validate_event({**base, "schema": SCHEMA_VERSION,
                           "type": "slo_violation", "slo": "ttft"}) == []
    assert validate_event({**base, "schema": SCHEMA_VERSION,
                           "type": "slo_violation"}) != []
    # v3 (and v1) streams: every pre-v4 type validates unchanged.
    for schema, ev in ((3, {"type": "fl_cohort", "round": 0, "tier": "edge",
                            "cohort": 1}),
                       (3, {"type": "fl_tier", "round": 0, "tier": "edge"}),
                       (1, {"type": "step", "it": 0}),
                       (2, {"type": "request_done", "req": "a",
                            "tokens": 2})):
        assert validate_event({**base, "schema": schema, **ev}) == []


def test_trace_export_golden():
    """Tiny stream -> EXACT Chrome trace JSON: metadata rows for the
    process (run) and thread (trace), one complete event per span at
    tracer-clock microseconds, and the flat fault event anchored as an
    instant marker via the first span's epoch-vs-ns offset."""
    from experiments.trace_export import chrome_trace
    events = [
        {"schema": 4, "run_id": "r", "seq": 1, "t": 100.0, "type": "span",
         "name": "queue", "trace_id": "req-0", "span_id": "s2",
         "parent_span_id": "s1", "start_ns": 1000, "dur_ns": 2000},
        {"schema": 4, "run_id": "r", "seq": 2, "t": 100.5, "type": "span",
         "name": "request", "trace_id": "req-0", "span_id": "s1",
         "start_ns": 1000, "dur_ns": 6000, "tokens": 3},
        {"schema": 4, "run_id": "r", "seq": 3, "t": 101.0, "type": "fault",
         "counters": {"skipped_steps": 2}, "it": 7},
    ]
    # Instants anchor via the NEAREST span in epoch time — here the
    # "request" span at t=100.5, whose end (start+dur ns) calibrates the
    # epoch->span-clock offset.
    anchor = 100.5 - (1000 + 6000) / 1e9
    assert chrome_trace(events) == {
        "traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
             "args": {"name": "run r"}},
            {"ph": "M", "name": "thread_name", "pid": 1, "tid": 1,
             "args": {"name": "req-0"}},
            {"ph": "i", "name": "fault", "cat": "event", "s": "p",
             "ts": (101.0 - anchor) * 1e6, "pid": 1, "tid": 0,
             "args": {"counters": {"skipped_steps": 2}, "it": 7}},
            {"ph": "X", "name": "queue", "cat": "span", "ts": 1.0,
             "dur": 2.0, "pid": 1, "tid": 1,
             "args": {"span_id": "s2", "parent_span_id": "s1"}},
            {"ph": "X", "name": "request", "cat": "span", "ts": 1.0,
             "dur": 6.0, "pid": 1, "tid": 1,
             "args": {"tokens": 3, "span_id": "s1"}},
        ],
        "displayTimeUnit": "ms",
    }
    # --no-instants drops the marker but not the spans.
    spans_only = chrome_trace(events, instants=False)
    assert [e["ph"] for e in spans_only["traceEvents"]] == ["M", "M",
                                                            "X", "X"]


# ------------------------------------------------- slo monitor

def _mk(seq, t, type, **fields):
    return {"schema": SCHEMA_VERSION, "run_id": "r", "seq": seq, "t": t,
            "type": type, **fields}


def test_slo_monitor_flags_stalled_stream():
    """The acceptance bar: a stream that goes silent with work
    outstanding is flagged within ONE rolling window — the final
    evaluation runs at the heartbeat's last beat, a window past the last
    token, where the sustained-rate floor breaks."""
    from experiments.slo_monitor import SLOConfig, check_stream
    events = [_mk(1, 0.0, "request_enqueue", req="a"),
              _mk(2, 0.2, "request_enqueue", req="b"),
              _mk(3, 0.5, "request_token", req="a", i=0),
              _mk(4, 1.0, "request_token", req="a", i=1),
              _mk(5, 1.5, "request_token", req="a", i=2)]
    cfg = SLOConfig(window_s=10.0, min_tokens_per_sec=0.1)
    # Healthy read: the stream's own horizon still has tokens in window.
    assert check_stream(events, cfg) == []
    # Stall: the writer's heartbeat kept beating for one more window with
    # zero tokens and both requests still outstanding.
    violations = check_stream(events, cfg, heartbeat={"time": 12.0})
    assert [v["slo"] for v in violations] == ["tokens_per_sec"]
    assert violations[0]["value"] == 0.0
    # Same silence with NOTHING outstanding is idleness, not a stall.
    done = events + [_mk(6, 1.6, "request_done", req="a", tokens=3),
                     _mk(7, 1.7, "request_done", req="b", tokens=0)]
    assert check_stream(done, cfg, heartbeat={"time": 12.0}) == []


def test_slo_monitor_ttft_and_transitions():
    """p99 TTFT over the window; one incident per ok->breached transition
    (a sustained breach must not spam one event per poll)."""
    from experiments.slo_monitor import SLOConfig, SLOMonitor
    cfg = SLOConfig(window_s=10.0, ttft_p99_s=1.0)
    m = SLOMonitor(cfg)
    m.feed([_mk(1, 0.0, "request_enqueue", req="a"),
            _mk(2, 5.0, "request_done", req="a", tokens=2, ttft_s=4.0)])
    assert [v["slo"] for v in m.evaluate(5.0)] == ["ttft_p99_s"]
    assert m.evaluate(6.0) == []            # still breached: no re-fire
    assert m.evaluate(20.0) == []           # window drained: recovered
    assert not m.active
    m.feed([_mk(3, 21.0, "request_done", req="b", tokens=1, ttft_s=9.0)])
    assert [v["slo"] for v in m.evaluate(21.0)] == ["ttft_p99_s"]
    assert len(m.violations) == 2


def test_slo_monitor_guard_skip_rate():
    from experiments.slo_monitor import SLOConfig, SLOMonitor
    cfg = SLOConfig(window_s=100.0, max_skip_rate=0.2)
    m = SLOMonitor(cfg)
    m.feed([_mk(1, 1.0, "step", it=9, steps=10),
            _mk(2, 2.0, "fault", counters={"skipped_steps": 5})])
    viols = m.evaluate(3.0)
    assert [v["slo"] for v in viols] == ["guard_skip_rate"]
    # Skipped steps still consume their batches, so they are IN the step
    # events' counts: rate = skips / steps.
    assert viols[0]["value"] == pytest.approx(5 / 10)


def test_slo_monitor_emits_events(tmp_path):
    """Violations land in the stream as schema-v4 slo_violation events a
    strict reader accepts — and obs_report renders them."""
    from experiments.slo_monitor import SLOConfig, SLOMonitor
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="slo") as log:
        m = SLOMonitor(SLOConfig(window_s=5.0, queue_p99_s=0.1), emit=log)
        m.feed([_mk(1, 0.0, "request_done", req="a", tokens=1,
                    queue_wait_s=3.0)])
        m.evaluate(0.5)
    events = read_events(path, strict=True)
    assert [e["type"] for e in events] == ["slo_violation"]
    assert events[0]["slo"] == "queue_p99_s"
    from experiments.obs_report import main as report_main
    assert report_main([path]) == 0


def test_stream_tailer_incremental_and_torn_lines(tmp_path):
    """The live tailer: picks up appends incrementally, buffers a torn
    final line until its newline lands (never misparses a mid-write
    line), and survives a shrink (healed fragment) by re-reading."""
    from experiments.slo_monitor import StreamTailer
    path = str(tmp_path / "events.jsonl")
    t = StreamTailer(path)
    assert t.poll() == []                       # no file yet: no signal
    with open(path, "wb") as f:
        f.write(b'{"type": "step", "it": 0}\n{"type": "st')
        f.flush()
        assert [e["it"] for e in t.poll()] == [0]   # torn tail buffered
        f.write(b'ep", "it": 1}\n')
        f.flush()
        assert [e["it"] for e in t.poll()] == [1]   # seam healed exactly
    os.truncate(path, 0)                        # recycled stream
    with open(path, "ab") as f:
        f.write(b'{"type": "step", "it": 7}\n')
    assert [e["it"] for e in t.poll()] == [7]       # reset + re-read


def test_two_tracers_one_trace_no_span_id_collision(tmp_path):
    """The elastic wiring: the training loop's tracer and the controller's
    tracer BOTH emit on trace 'train'. Independent per-tracer counters
    must not collide (trace_trees keys spans by id — a collision silently
    overwrites spans and corrupts the reassembled tree)."""
    from ddl25spring_tpu.telemetry.trace import Tracer, trace_trees
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="r") as log:
        loop_tr, ctrl_tr = Tracer(log), Tracer(log)
        with loop_tr.span("dispatch", trace="train", it=0):
            pass
        with ctrl_tr.span("remesh", trace="train", it=0) as rroot:
            with ctrl_tr.span("restore", parent=rroot.ctx):
                pass
        with loop_tr.span("dispatch", trace="train", it=2):
            pass
    events = read_events(path, strict=True)
    t = trace_trees(events)["train"]
    assert len(t["spans"]) == len(events) == 4     # nothing overwritten
    assert len(t["roots"]) == 3 and not t["orphans"]
    ids = [e["span_id"] for e in events]
    assert len(set(ids)) == 4


def test_stream_tailer_from_end_skips_existing(tmp_path):
    """from_end=True (the watchdog's relaunch attach): pre-existing events
    — a dead run's outstanding request_enqueues — are never re-fed."""
    from experiments.slo_monitor import StreamTailer
    path = str(tmp_path / "events.jsonl")
    with open(path, "wb") as f:
        f.write(b'{"type": "request_enqueue", "req": "dead"}\n')
    t = StreamTailer(path, from_end=True)
    assert t.poll() == []
    with open(path, "ab") as f:
        f.write(b'{"type": "request_enqueue", "req": "alive"}\n')
    assert [e["req"] for e in t.poll()] == ["alive"]


def test_slo_monitor_partial_first_window_rate():
    """A healthy just-started stream must not read as a stall: during the
    first partial window the rate divisor is the observed span, not the
    full window (compile pushing the first token late would otherwise
    deflate a true 12 tok/s below a 10 tok/s floor)."""
    from experiments.slo_monitor import SLOConfig, SLOMonitor
    m = SLOMonitor(SLOConfig(window_s=30.0, min_tokens_per_sec=10.0))
    m.feed([_mk(1, 20.0, "request_enqueue", req="a")]
           + [_mk(2 + i, 20.0 + i * 0.08, "request_token", req="a", i=i)
              for i in range(120)])          # 12 tok/s from the start
    # Evaluated at t=30 the stream has existed for 10s: dividing its 120
    # tokens by the full 30s window would read 4 < 10 and cry stall at a
    # healthy server — the observed span is what the floor judges.
    assert m.evaluate(30.0) == []


def test_sidecar_eventlog_never_truncates_live_stream(tmp_path):
    """heal=False (the slo_monitor sidecar): attaching to a stream whose
    final line is mid-write must NOT truncate it — the live writer's
    O_APPEND continuation still lands after the fragment, and the
    sidecar's first emit seals it with a leading newline instead."""
    path = str(tmp_path / "events.jsonl")
    with EventLog(path, run_id="live") as live:
        live.step(it=0, loss=1.0)
    size_before = os.path.getsize(path)
    with open(path, "ab") as f:
        f.write(b'{"schema": 4, "run_id": "live", "seq": 2, "t": 0, "ty')
    frag_size = os.path.getsize(path)
    sidecar = EventLog(path, run_id="slo", heal=False)
    assert os.path.getsize(path) == frag_size     # nothing truncated
    sidecar.slo_violation(slo="ttft_p99_s", value=2.0, threshold=1.0)
    sidecar.close()
    # The fragment stays one skippable malformed line; both real events
    # survive; the default (heal=True) path in the same state would have
    # truncated back to size_before.
    events = read_events(path)
    assert [(e["run_id"], e["type"]) for e in events] == [
        ("live", "step"), ("slo", "slo_violation")]
    assert size_before < frag_size


def test_trace_trees_partitions_by_run_id():
    """Relaunches share a file, a trace name AND a span-id sequence (each
    process's first tracer is instance 1): trace_trees must keep the runs'
    trees apart instead of silently overwriting spans."""
    from ddl25spring_tpu.telemetry.trace import trace_trees, tree_check
    def span(run, sid, name, parent=None, start=0):
        e = {"schema": SCHEMA_VERSION, "run_id": run, "seq": 1, "t": 0.0,
             "type": "span", "trace_id": "train", "name": name,
             "span_id": sid, "start_ns": start, "dur_ns": 1}
        if parent:
            e["parent_span_id"] = parent
        return e
    events = [span("run1", "s1.2", "compute", "s1.1"),
              span("run1", "s1.1", "dispatch"),
              span("run2", "s1.2", "compute", "s1.1", start=5),
              span("run2", "s1.1", "dispatch", start=5)]
    trees = trace_trees(events)
    assert set(trees) == {"train", "run2/train"}
    for t in trees.values():
        assert tree_check(t) == {"roots": 1, "orphans": 0, "imbalanced": 0}
        assert len(t["spans"]) == 2


def test_slo_monitor_counts_done_tokens_without_token_events():
    """Scheduler(token_events=False) streams carry throughput only at
    completion granularity; the tok/s floor must read it there instead of
    declaring every such server stalled."""
    from experiments.slo_monitor import SLOConfig, SLOMonitor
    cfg = SLOConfig(window_s=10.0, min_tokens_per_sec=1.0)
    m = SLOMonitor(cfg)
    m.feed([_mk(1, 0.0, "request_enqueue", req="a"),
            _mk(2, 1.0, "request_enqueue", req="b"),
            _mk(3, 5.0, "request_done", req="a", tokens=40)])
    assert m.evaluate(8.0) == []            # 40 tokens/8s, healthy
    # A stream WITH token events never double-counts the done totals.
    m2 = SLOMonitor(cfg)
    m2.feed([_mk(1, 0.0, "request_enqueue", req="a"),
             _mk(2, 0.5, "request_enqueue", req="b")]
            + [_mk(3 + i, 1.0 + i, "request_token", req="a", i=i)
               for i in range(4)]
            + [_mk(9, 5.0, "request_done", req="a", tokens=4)])
    assert sum(n for _, n in m2._tokens) == 4


def test_slo_monitor_cold_start_grace_then_stall():
    """No token has EVER arrived: that is startup (XLA compile), not a
    throughput deficit — the floor stays quiet for one full window from
    the stream's birth, then a still-token-less stream IS a stall."""
    from experiments.slo_monitor import SLOConfig, SLOMonitor
    m = SLOMonitor(SLOConfig(window_s=30.0, min_tokens_per_sec=0.5))
    m.feed([_mk(1, 0.0, "request_enqueue", req="a")])
    assert m.evaluate(10.0) == []           # compiling, within grace
    assert m.evaluate(29.0) == []
    viols = m.evaluate(31.0)                # a window with zero tokens
    assert [v["slo"] for v in viols] == ["tokens_per_sec"]
    assert viols[0]["value"] == 0.0


def test_stream_tailer_from_end_survives_heal_shrink(tmp_path):
    """A relaunched writer's EventLog heals a torn fragment by TRUNCATING
    a few bytes; a from_end tailer must re-attach at the new end, not
    reset to 0 and replay the dead run's history (whose never-completed
    enqueues would poison the fresh monitor's outstanding counters)."""
    from experiments.slo_monitor import StreamTailer
    path = str(tmp_path / "events.jsonl")
    with open(path, "wb") as f:
        f.write(b'{"type": "request_enqueue", "req": "dead"}\n')
        f.write(b'{"type": "st')                    # torn fragment
    t = StreamTailer(path, from_end=True)
    assert t.poll() == []
    with open(path, "r+b") as f:                    # the relaunch heals...
        f.truncate(len(b'{"type": "request_enqueue", "req": "dead"}\n'))
    with open(path, "ab") as f:                     # ...and writes anew
        f.write(b'{"type": "request_enqueue", "req": "alive"}\n')
    assert [e["req"] for e in t.poll()] == ["alive"]


# ------------------------------------- overlap ring accounting (ISSUE 10)

def test_comm_ring_accounting_matches_analytic(devices):
    """The ring driver's comm profile is EXACT: ppermute trip counts ×
    chunk payloads reproduce the analytic K·M·(n−1)·chunk_bytes wire
    formula to the byte per wire format (ppermute ring factor 1 — one
    neighbor send per trip), and the int8 scale sidecars account
    K·M·(n−1)·4 bytes."""
    import optax

    from ddl25spring_tpu.parallel.dp import _flat_geometry

    n, K, M = 4, 2, 2
    mesh = make_mesh({"data": n}, devices=devices[:n])
    params = llama.init_llama(jax.random.key(0), TINY)
    _, _, local, _ = _flat_geometry(mesh, params)
    window = jax.ShapeDtypeStruct((K, n * 2, TINY.ctx_size), jnp.int32)

    def loss_fn(p, b):
        return llama.forward_loss(p, b, TINY)

    for wire, itemsize in (("fp32", 4), ("bf16", 2), ("int8_ef", 1)):
        state, step = compress.make_overlap_multi_step(
            loss_fn, optax.adam(1e-3), mesh,
            llama.init_llama(jax.random.key(0), TINY),
            microbatches=M, wire=wire, aggregation="zero1")
        profile = measure_comm(step, state, window)
        assert profile is not None and profile.records
        by = profile.by_label()
        suffix = {"fp32": "f32", "bf16": "bf16", "int8_ef": "int8"}[wire]
        ring = by[f"ring_grad_{suffix}"]
        want = K * M * (n - 1) * local * itemsize
        assert ring["payload_bytes"] == want, (wire, ring)
        assert ring["calls"] == K * M * (n - 1)
        # ppermute ring factor is exactly 1: wire bytes == payload bytes.
        assert ring["wire_bytes_per_device"] == want
        if wire == "int8_ef":
            scales = by["ring_grad_scale"]
            assert scales["payload_bytes"] == K * M * (n - 1) * 4
            # The compressed second leg (delta gather) is int8 too.
            assert by["overlap_delta_gather_int8"]["payload_bytes"] == \
                K * local * 1


def test_as_dict_overlap_normalization_rule():
    """The normalization rule, pinned once so future drivers can't
    double-count: per-TRAIN-STEP figures divide the per-dispatch totals
    by steps_per_dispatch ONLY — an overlap step's M microbatch rings are
    that step's traffic, so dividing by M too would under-count M×. The
    per-microbatch-ring view is an ADDITIONAL field (÷M on top)."""
    from ddl25spring_tpu.telemetry.comm import CommProfile, CommRecord
    K, M = 4, 2
    # One ring hop traced per microbatch (unrolled), each executing K
    # times per dispatch: 2 records at scale=K.
    records = [CommRecord(op="ppermute", label="ring_grad_f32",
                          axis="data", axis_size=2, payload_bytes=100,
                          scale=K)
               for _ in range(M)]
    p = CommProfile(records)
    d = p.as_dict(steps_per_dispatch=K, overlap_microbatches=M)
    assert d["wire_bytes_per_device_per_step"] == K * M * 100
    assert d["wire_bytes_per_device_per_train_step"] == M * 100   # ÷K only
    assert d["wire_bytes_per_device_per_microbatch"] == 100       # ÷K÷M
    assert d["overlap_microbatches"] == M
    # M = 1 adds nothing: the legacy dict shape is unchanged.
    d1 = p.as_dict(steps_per_dispatch=K)
    assert "overlap_microbatches" not in d1
    assert "wire_bytes_per_device_per_microbatch" not in d1
