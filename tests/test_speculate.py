"""Speculative decoding + CoW prefix sharing on the paged serving engine.

The ISSUE 13 acceptance bars: greedy speculative streams are BITWISE
``generate()``'s at k ∈ {1, 3} for any draft, any admission order, with
and without CoW prefix sharing; the engine's compile set is exactly the
documented programs with zero retraces across the speculate on/off × k
grid; rejection sampling preserves the target distribution (empirical
acceptance matches the analytic ``Σ min(p, q)`` for a known p/q pair);
EOS emitted mid-window retires at the right token; a shared-prefix
workload's allocator peak drops. Engine-level greedy parity batteries
live in tests/test_generate.py next to the path they mirror.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.config import LlamaConfig
from ddl25spring_tpu.models import llama
from ddl25spring_tpu.serving import (Engine, PagedKVConfig, Request,
                                     Scheduler, SpecConfig,
                                     reference_stream, run_serving,
                                     synthetic_workload)
from ddl25spring_tpu.serving.speculate import rejection_accept
from ddl25spring_tpu.telemetry.events import EventLog, read_events

CFG = LlamaConfig(vocab_size=97, dmodel=32, num_heads=4, n_layers=2,
                  ctx_size=32)
PAGED = PagedKVConfig(num_blocks=24, block_len=4, max_blocks_per_seq=8)


@pytest.fixture(scope="module")
def params():
    return llama.init_llama(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def draft_params():
    """A separately-weighted same-arch draft: disagrees with the target
    often (an adversarial acceptance rate), which is exactly what the
    bitwise bar must survive."""
    return llama.init_llama(jax.random.PRNGKey(7), CFG)


# ------------------------------------------------------- rejection sampling

def test_rejection_acceptance_matches_analytic():
    """The speculative-sampling identity, unit-tested without a model:
    with draft tokens ~ q and accept prob min(1, p/q), the per-position
    acceptance rate is analytically Σ_x min(p(x), q(x)) — empirical rate
    over many seeds must match, and the EMITTED first token (accepted
    draft or residual resample) must be distributed as p."""
    p0 = jnp.array([0.5, 0.3, 0.15, 0.05])
    q0 = jnp.array([0.2, 0.5, 0.2, 0.1])
    analytic = float(jnp.minimum(p0, q0).sum())        # 0.2+0.3+0.15+0.05
    k = 1
    p = jnp.stack([p0, p0])                            # [k+1, V]
    q = q0[None, :]                                    # [k, V]
    n = 4000
    rng = np.random.default_rng(0)
    # One vmapped dispatch over all n trials (keys PRNGKey(0..n-1), same
    # per-trial math as a host loop — rejection_accept is deterministic
    # per key): n host round trips would dominate the suite's wall time
    # for zero extra statistical power.
    drafts = rng.choice(4, size=n, p=np.asarray(q0)).astype(np.int32)
    keys = jax.jit(jax.vmap(jax.random.PRNGKey))(jnp.arange(n))
    a, corr = jax.vmap(
        lambda key, d: rejection_accept(key, p, q, d[None]))(
            keys, jnp.asarray(drafts))
    a = np.asarray(a)
    corr = np.asarray(corr)
    rate = a.sum() / n
    emitted = np.bincount(np.where(a > 0, drafts, corr), minlength=4)
    assert abs(rate - analytic) < 0.03, (rate, analytic)
    emp = emitted / n
    assert np.abs(emp - np.asarray(p0)).max() < 0.03, emp


def test_rejection_identical_distributions_always_accept():
    """p == q ⇒ min(1, p/q) == 1: acceptance is deterministic — the
    same-weights-draft trick that makes the CPU bench's
    tokens-per-dispatch bar exact."""
    p0 = jnp.array([0.4, 0.4, 0.2])
    p = jnp.stack([p0, p0, p0])
    q = jnp.stack([p0, p0])
    for seed in range(20):
        a, _ = rejection_accept(jax.random.PRNGKey(seed), p, q,
                                jnp.array([0, 2]))
        assert int(a) == 2, seed


def test_same_weights_stochastic_draft_accepts_everything(params):
    """Engine-level twin: a same-weights draft at temperature > 0 has
    p == q bitwise, so every round accepts all k proposals — acceptance
    rate exactly 1 in the report."""
    wl = [Request(rid="s0", prompt=(3, 5, 7), max_new=8, temperature=0.8,
                  seed=11),
          Request(rid="s1", prompt=(2, 9, 4, 1, 6), max_new=6,
                  temperature=0.6, seed=5)]
    rep = run_serving(params, CFG, PAGED, wl, num_slots=2, prefill_chunk=4,
                      speculate=SpecConfig(k=3, draft_params=params))
    assert rep.acceptance_rate == 1.0
    assert all(len(rep.records[r.rid].tokens) == r.max_new for r in wl)


# ------------------------------------------------------ compile contract

def test_spec_engine_compile_set_and_zero_retraces(params, draft_params):
    """Across the speculate on/off × k grid the compile count is exactly
    the documented program set — 2 plain (prefill + decode), 4 with
    speculation (prefill + verify + the draft's two; decode_step idles)
    — and NOTHING ever retraces: admission, raggedness, acceptance and
    horizon tails are data."""
    wl = synthetic_workload(seed=3, n_requests=8, rate_rps=500.0,
                            vocab_size=CFG.vocab_size,
                            prompt_lens=(2, 5, 9), max_news=(3, 5, 8),
                            temperatures=(0.0, 0.7))
    for spec, want_compiles in ((None, 2),
                                (SpecConfig(k=1, draft_params=draft_params),
                                 4),
                                (SpecConfig(k=3, draft_params=draft_params),
                                 4)):
        rep = run_serving(params, CFG, PAGED, wl, num_slots=3,
                          prefill_chunk=4, speculate=spec)
        assert rep.retraces == 0, spec
        assert rep.compiles == want_compiles, spec
        assert rep.aggregates["completed"] == len(wl)


def test_spec_tokens_per_dispatch_beats_plain(params):
    """The throughput bar at test scale, made deterministic: a single
    stream (no batching credit on either side) with a same-weights draft
    (greedy acceptance exactly 1) at k=3 — the plain engine pays one
    dispatch per token, the speculative one lands k+1 per verify
    dispatch. Multi-request workloads keep the same STREAMS (pinned in
    the parity battery); their concurrency mix differs because
    speculation drains slots faster, so the clean per-dispatch ratio is
    the single-stream one (the serving bench measures the loaded one)."""
    wl = [Request(rid="one", prompt=(2, 9, 4, 1), max_new=9)]
    plain = run_serving(params, CFG, PAGED, wl, num_slots=1,
                        prefill_chunk=8)
    spec = run_serving(params, CFG, PAGED, wl, num_slots=1,
                       prefill_chunk=8,
                       speculate=SpecConfig(k=3, draft_params=params))
    assert plain.records["one"].tokens == spec.records["one"].tokens
    assert spec.acceptance_rate == 1.0
    assert plain.tokens_per_dispatch == 1.0      # one token per dispatch
    assert spec.tokens_per_dispatch == 4.0       # k+1 per verify dispatch
    assert spec.decode_dispatches < plain.decode_dispatches


# -------------------------------------------------------- EOS mid-window

def test_eos_mid_window_retires_at_the_right_token(params):
    """An EOS landing INSIDE an accepted window (not at its edge) must
    retire the request at exactly that token: the stream is generate()'s
    truncated at the first EOS inclusive, post-EOS window tokens never
    existed, and the whole reservation frees at that boundary."""
    prompt = tuple(range(2, 8))
    full = reference_stream(params, CFG, PAGED,
                            Request(rid="p", prompt=prompt, max_new=12))
    eos = full[2]      # third token: inside the first k=3 verify window
    cut = full[:full.index(eos) + 1]
    assert len(cut) < 12
    eng = Engine(params, CFG, PAGED, 1, prefill_chunk=8,
                 speculate=SpecConfig(k=3, draft_params=params))
    sched = Scheduler(eng)
    sched.submit(Request(rid="r", prompt=prompt, max_new=12, eos_id=eos),
                 now=0.0)
    while sched.outstanding:
        sched.tick()
    assert sched.records["r"].tokens == cut
    assert eng.allocator.in_use == 0
    # Delivered-basis accounting: the dropped post-EOS window tail must
    # not inflate tokens-per-dispatch — Σ emitted over the v7 rounds is
    # exactly the delivered stream minus the prefill-sampled TTFT token,
    # and the engine's decode_tokens (the report's tokens_per_dispatch
    # numerator) matches.
    assert sum(r["emitted"] for r in sched.spec_rounds) == len(cut) - 1
    assert eng.decode_tokens == len(cut) - 1


def test_eos_mid_window_overlapping_max_new_retires_once(params):
    """Regression: one verify window can BOTH emit the EOS mid-window AND
    reach max_new at its last row (same-weights draft ⇒ acceptance 1, so
    k=3 + max_new=4 makes the whole horizon one window). The engine
    self-retires the slot while emitting the window tail; the scheduler's
    EOS path must see the already-freed slot and not retire it a second
    time (this crashed with ValueError before the liveness check)."""
    prompt = tuple(range(2, 8))
    full = reference_stream(params, CFG, PAGED,
                            Request(rid="p", prompt=prompt, max_new=4))
    eos = full[2]
    assert full.index(eos) == 2      # mid-window, non-final row — the
    cut = full[:3]                   # overlap this test exists to pin
    eng = Engine(params, CFG, PAGED, 1, prefill_chunk=8,
                 speculate=SpecConfig(k=3, draft_params=params))
    sched = Scheduler(eng)
    sched.submit(Request(rid="r", prompt=prompt, max_new=4, eos_id=eos),
                 now=0.0)
    while sched.outstanding:
        sched.tick()
    assert sched.records["r"].tokens == cut
    assert eng.allocator.in_use == 0


def test_hot_swap_lands_at_verify_boundary_bitwise(params, draft_params):
    """A weight swap mid-rollout under speculation lands between ticks —
    i.e. at a VERIFY boundary, so a round's draft proposals and its
    verification never mix target generations. Same-weights swap:
    bitwise invisible, zero retraces across it (the draft keeps its own
    weights)."""
    import jax as _jax

    prompt = tuple(range(2, 8))
    want = reference_stream(params, CFG, PAGED,
                            Request(rid="w", prompt=prompt, max_new=10))
    eng = Engine(params, CFG, PAGED, 1, prefill_chunk=8,
                 speculate=SpecConfig(k=3, draft_params=draft_params))
    sched = Scheduler(eng)
    sched.submit(Request(rid="r", prompt=prompt, max_new=10), now=0.0)
    ticks = 0
    swapped = False
    while sched.outstanding:
        sched.tick()
        ticks += 1
        if ticks == 2 and not swapped:
            # Mid-decode, between rounds: a fresh equal tree (host copy).
            clone = _jax.tree.map(lambda x: x + 0, params)
            sched.swap_weights(clone, version=1)
            swapped = True
    assert swapped and sched.records["r"].tokens == want
    assert sum(w.retraces for w in eng.watches()) == 0


# --------------------------------------------------- CoW prefix sharing

def _drive_pair(params, prompt, max_new, *, prefix_share, speculate=None,
                stagger=2, prompt_b=None):
    """Two requests (identical prompts unless ``prompt_b``), the second
    admitted mid-flight of the first; returns (streams, physical peak)."""
    eng = Engine(params, CFG, PAGED, 2, prefill_chunk=16,
                 prefix_share=prefix_share, speculate=speculate)
    s_a = eng.admit(np.asarray(prompt, np.int32), max_new)
    out = {s_a: []}
    s_b, steps = None, 0
    while eng.busy or s_b is None:
        if steps == stagger and s_b is None:
            s_b = eng.admit(np.asarray(prompt_b or prompt, np.int32),
                            max_new)
            out[s_b] = []
        for ev in eng.step():
            out[ev.slot].append(ev.token)
        steps += 1
    return (out[s_a], out[s_b]), eng.allocator.peak_in_use


def test_cow_prefix_sharing_drops_peak_and_stays_bitwise(params):
    """Two overlapping requests with an identical 3-block prompt: with
    prefix sharing the second maps the donor's prompt blocks read-only,
    so the physical allocator peak DROPS by the shared count while both
    streams stay bitwise generate()'s."""
    prompt = tuple(range(2, 14))                 # 12 tokens = 3 full blocks
    want = reference_stream(params, CFG, PAGED,
                            Request(rid="w", prompt=prompt, max_new=6))
    (a1, b1), peak_cow = _drive_pair(params, prompt, 6, prefix_share=True)
    (a0, b0), peak_plain = _drive_pair(params, prompt, 6,
                                       prefix_share=False)
    assert a1 == b1 == a0 == b0 == want
    assert peak_cow == peak_plain - 3            # 3 shared prompt blocks


def test_cow_divergent_tails_share_only_the_common_prefix(params):
    """Same 2-block prefix, different tails: the divergent tail lands in
    private blocks (the first divergent write copies — here, computes —
    into the sharer's own allocation), each stream bitwise its own
    generate()."""
    common = tuple(range(3, 11))                 # 8 tokens = 2 full blocks
    pa, pb = common + (20, 21), common + (30,)
    want_a = reference_stream(params, CFG, PAGED,
                              Request(rid="a", prompt=pa, max_new=5))
    want_b = reference_stream(params, CFG, PAGED,
                              Request(rid="b", prompt=pb, max_new=5))
    (a, b), peak = _drive_pair(params, pa, 5, prefix_share=True,
                               prompt_b=pb)
    assert a == want_a and b == want_b
    (_, _), peak_plain = _drive_pair(params, pa, 5, prefix_share=False,
                                     prompt_b=pb)
    assert peak == peak_plain - 2                # 2 shared prefix blocks


def test_cow_whole_prompt_shared_still_samples_first_token(params):
    """An identical prompt that is ENTIRELY full blocks: the sharer maps
    every prompt block and recomputes only the final chunk (writes to
    trash) to recover the first-token hidden state — stream bitwise."""
    prompt = tuple(range(4, 12))                 # 8 = 2 exact blocks
    want = reference_stream(params, CFG, PAGED,
                            Request(rid="w", prompt=prompt, max_new=4))
    (a, b), _ = _drive_pair(params, prompt, 4, prefix_share=True)
    assert a == b == want


def test_cow_with_speculation_bitwise(params, draft_params):
    """CoW and speculation compose: shared prompt blocks exist in BOTH
    pools (the donor's draft prefill wrote the draft copies), greedy
    streams stay bitwise through k=3 verify windows."""
    prompt = tuple(range(5, 17))                 # 3 full blocks
    want = reference_stream(params, CFG, PAGED,
                            Request(rid="w", prompt=prompt, max_new=6))
    spec = SpecConfig(k=3, draft_params=draft_params)
    (a, b), peak = _drive_pair(params, prompt, 6, prefix_share=True,
                               speculate=spec)
    assert a == b == want
    (_, _), peak_plain = _drive_pair(params, prompt, 6, prefix_share=False,
                                     speculate=spec)
    assert peak == peak_plain - 3


def test_cow_under_poisson_load_bitwise_and_saves_blocks(params):
    """A shared-prefix Poisson workload through the scheduler: every
    stream bitwise, physical peak strictly below the no-sharing run."""
    base = tuple(range(2, 10))                   # 2 full blocks shared
    wl = [Request(rid=f"r{i:02d}", prompt=base + (40 + i,), max_new=4,
                  arrival=0.002 * i) for i in range(8)]
    rep_cow = run_serving(params, CFG, PAGED, wl, num_slots=4,
                          prefill_chunk=8, prefix_share=True)
    rep_pln = run_serving(params, CFG, PAGED, wl, num_slots=4,
                          prefill_chunk=8)
    for r in wl:
        want = reference_stream(params, CFG, PAGED, r)
        assert rep_cow.records[r.rid].tokens == want, r.rid
        assert rep_pln.records[r.rid].tokens == want, r.rid
    assert rep_cow.peak_blocks_in_use < rep_pln.peak_blocks_in_use


# ------------------------------------------------------ gather narrowing

def test_gather_narrowing_bitwise_with_bounded_compiles(params):
    """Opt-in decode-gather narrowing: streams stay bitwise generate()'s
    (the dropped table columns contribute exact zeros through the
    masked softmax), compile count stays within one per bucket width,
    zero retraces, and the avoided gather bytes are accounted."""
    wl = synthetic_workload(seed=11, n_requests=8, rate_rps=300.0,
                            vocab_size=CFG.vocab_size,
                            prompt_lens=(2, 5, 9), max_news=(3, 6),
                            temperatures=(0.0, 0.7))
    rep = run_serving(params, CFG, PAGED, wl, num_slots=3, prefill_chunk=4,
                      gather_buckets=True)
    for r in wl:
        assert rep.records[r.rid].tokens == reference_stream(
            params, CFG, PAGED, r), r.rid
    assert rep.retraces == 0
    buckets = len({1, 2, 4, 8})                  # mb=8 → 1/2/4/8
    assert 2 <= rep.compiles <= 1 + buckets      # prefill + used widths
    assert rep.gather_bytes_saved > 0
    assert rep.gather_bytes > 0


def test_gather_narrowing_with_speculation_at_the_horizon(params,
                                                          draft_params):
    """Regression: buckets × speculation on a full-width reservation. A
    late verify window's host-side block need ceil((pos + k + 1) / bl)
    spills one past the table width, and no bucket covers it — the need
    must cap at max_blocks_per_seq (the overflow rows are trash-masked
    in-program) instead of StopIteration off the bucket list. Stream
    stays bitwise; nothing retraces."""
    # One run covers both regressions: the edge request's 31-position
    # full-width reservation drives a late window (pos ≥ 29) to ask for
    # a 9th block, and the short prompt narrows the gather so the run
    # spans two bucket widths — the DRAFT decode runs over the same
    # narrowed slice as the verify, so its compile budget must cover one
    # program per bucket width too (a spurious retrace when the draft's
    # budget stayed at 1).
    wl = [Request(rid="short", prompt=(3, 5), max_new=4),
          Request(rid="edge", prompt=(4,) * 24, max_new=8)]
    rep = run_serving(params, CFG, PAGED, wl, num_slots=2,
                      prefill_chunk=8, gather_buckets=True,
                      speculate=SpecConfig(k=3, draft_params=draft_params))
    for q in wl:
        assert rep.records[q.rid].tokens == reference_stream(
            params, CFG, PAGED, q), q.rid
    assert rep.retraces == 0


# ----------------------------------------------------- telemetry (v7)

def test_speculate_events_emitted_and_schema_valid(params, tmp_path):
    """Every verify dispatch emits one strict-valid ``speculate`` event
    (schema v7) whose accounting reconciles with the report: Σ emitted
    == decode tokens, acceptance == accepted/proposed."""
    path = str(tmp_path / "events.jsonl")
    wl = synthetic_workload(seed=9, n_requests=5, rate_rps=300.0,
                            vocab_size=CFG.vocab_size, prompt_lens=(3, 6),
                            max_news=(4, 6), temperatures=(0.0,))
    with EventLog(path) as log:
        rep = run_serving(params, CFG, PAGED, wl, num_slots=2,
                          prefill_chunk=4, events=log,
                          speculate=SpecConfig(k=2, draft_params=params))
    events = read_events(path, strict=True)      # strict: v7 validates
    specs = [e for e in events if e["type"] == "speculate"]
    assert len(specs) == rep.decode_dispatches > 0
    assert sum(e["emitted"] for e in specs) == rep.decode_tokens
    assert sum(e["proposed"] for e in specs) == rep.spec_proposed
    assert sum(e["accepted"] for e in specs) == rep.spec_accepted
    assert all(e["k"] == 2 and e["rejected"] >= 0 for e in specs)


def test_bench_compare_tokens_per_dispatch_higher_is_better(tmp_path):
    """The speculative-decode trajectory row gates like a throughput row:
    a tokens-per-dispatch DROP is a regression, a rise is not."""
    import json

    from experiments.bench_compare import compare, lower_is_better

    assert not lower_is_better("tokens_per_dispatch")

    def write(name, value):
        p = tmp_path / name
        p.write_text(json.dumps({
            "metric": "serving_smoke",
            "rows": [{"metric": "tokens_per_dispatch", "value": value,
                      "platform": "cpu", "variant": "spec-k4"}]}) + "\n")
        return str(p)

    good = write("BENCH_r01.json", 4.5)
    bad = write("cand.json", 2.0)
    _, regressions = compare([good], bad, max_regression_pct=10.0)
    assert regressions and "tokens_per_dispatch" in regressions[0]
    _, regressions = compare([good], write("cand2.json", 4.6),
                             max_regression_pct=10.0)
    assert not regressions


def test_slo_monitor_acceptance_floor():
    """A degenerate draft (acceptance → 0) breaches the acceptance-rate
    floor; a healthy one does not — and recovery re-arms the
    transition."""
    from experiments.slo_monitor import SLOConfig, replay_monitor

    def stream(rate):
        acc = int(round(10 * rate))
        return [{"schema": 7, "run_id": "r", "seq": i + 1, "t": float(i),
                 "type": "speculate", "proposed": 10, "accepted": acc,
                 "rejected": 10 - acc, "emitted": acc + 1, "k": 5,
                 "slots": 2} for i in range(40)]

    cfg = SLOConfig(window_s=10.0, min_acceptance_rate=0.5)
    bad = replay_monitor(stream(0.1), cfg)
    assert any(v["slo"] == "spec_acceptance_rate" for v in bad.violations)
    good = replay_monitor(stream(0.9), cfg)
    assert not good.violations
