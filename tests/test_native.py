"""Native (C++) token pipeline vs the pure-Python reference implementation.

Parity is the test: same piece table + same corpus file must yield identical
encodings and identical packed batches from native/tokenstream.cpp and from
tokenizers/spm.py + data/tokens.py.
"""

import numpy as np
import pytest

from ddl25spring_tpu.data.native import NativeTokenStream, native_available
from ddl25spring_tpu.data.tokens import TokenStream
from ddl25spring_tpu.tokenizers.spm import (_BYTE, _CONTROL, _NORMAL,
                                            _UNKNOWN, SentencePieceTokenizer)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native library unavailable")


def _toy_pieces():
    """A tiny vocab exercising merges, byte fallback, and specials."""
    pieces = [
        ("<unk>", 0.0, _UNKNOWN),
        ("<s>", 0.0, _CONTROL),
        ("</s>", 0.0, _CONTROL),
    ]
    words = ["▁the", "▁cat", "▁dog", "▁sat", "▁on", "▁mat", "▁a", "the",
             "cat", "▁", "c", "a", "t", "s", "o", "n", "h", "e", "d", "g",
             "m", "▁ca", "at", "▁th", "▁sa", "▁o", "▁m", "▁d"]
    for i, w in enumerate(words):
        pieces.append((w, -float(i + 1) / 4.0, _NORMAL))
    for b in range(256):
        pieces.append((f"<0x{b:02X}>", 0.0, _BYTE))
    return pieces


@pytest.fixture(scope="module", params=[False, True], ids=["unigram", "bpe"])
def pair(request):
    pieces = _toy_pieces()
    py = SentencePieceTokenizer.from_pieces(pieces, is_bpe=request.param)
    nat = NativeTokenStream(py, batch_size=2, seq_len=16, seed=3)
    return py, nat


TEXTS = [
    "the cat sat on the mat",
    "a dog",
    "cats and dogs",           # 'nd' etc. forces fallback paths
    "héllo wörld",             # multi-byte UTF-8 → byte fallback
    "",
    "   spaces   galore ",
]


def test_encode_parity(pair):
    py, nat = pair
    for text in TEXTS:
        assert nat.encode(text, add_bos=True) == py.encode(text, add_bos=True), text
        assert nat.encode(text) == py.encode(text), text


def test_encode_parity_reference_model():
    """If the reference's vendored Llama SP model is present, check parity on
    it too (32k-piece BPE — the real workload vocab)."""
    from ddl25spring_tpu.tokenizers.spm import load_tokenizer
    py = load_tokenizer()
    if not hasattr(py, "pieces"):
        pytest.skip("no SentencePiece model available")
    nat = NativeTokenStream(py, batch_size=1, seq_len=8)
    for text in TEXTS + ["Once upon a time there was a happy cat named Tom."]:
        assert nat.encode(text, add_bos=True) == py.encode(text, add_bos=True), text


def test_batch_parity_on_corpus(tmp_path, pair):
    """Same corpus file → bitwise-identical packed batches, including skip."""
    py, _ = pair
    corpus = tmp_path / "corpus.txt"
    corpus.write_text("the cat sat on the mat\na dog sat\nthe mat\n" * 5)

    py_stream = iter(TokenStream(py, batch_size=2, seq_len=16, skip=3,
                                 path=str(corpus)))
    nat_stream = NativeTokenStream(py, batch_size=2, seq_len=16, skip=3,
                                   path=str(corpus))
    for _ in range(5):
        np.testing.assert_array_equal(next(py_stream), nat_stream.next_batch())
    nat_stream.close()


def test_prefetch_runs_ahead(pair):
    """The producer thread fills the ring beyond what the consumer took."""
    import time
    py, _ = pair
    nat = NativeTokenStream(py, batch_size=2, seq_len=32, prefetch=4)
    nat.next_batch()
    deadline = time.time() + 5.0
    while nat.batches_produced() < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert nat.batches_produced() >= 3   # ran ahead of the single consume
    nat.close()


def test_prefetch_counter_and_double_close(pair):
    """batches_produced() keeps advancing ahead of consumption, and close()
    is idempotent: the second close (and the __del__ after an explicit
    close) must not double-free the native handle."""
    import time
    py, _ = pair
    nat = NativeTokenStream(py, batch_size=2, seq_len=16, prefetch=2)
    consumed = nat.next_batch()
    assert consumed.shape == (2, 16)
    deadline = time.time() + 5.0
    while nat.batches_produced() < 2 and time.time() < deadline:
        time.sleep(0.01)
    assert nat.batches_produced() >= 2  # producer ran ahead of 1 consume
    nat.close()
    assert nat._handle is None          # close() cleared the handle...
    nat.close()                         # ...so a second close is a no-op
    nat.__del__()                       # and so is finalization after close


def test_synthetic_batches_shape_and_determinism(pair):
    py, _ = pair
    a = NativeTokenStream(py, batch_size=3, seq_len=24, seed=7)
    b = NativeTokenStream(py, batch_size=3, seq_len=24, seed=7)
    ba, bb = a.next_batch(), b.next_batch()
    assert ba.shape == (3, 24) and ba.dtype == np.int32
    np.testing.assert_array_equal(ba, bb)   # same seed → same stream
    a.close(); b.close()
