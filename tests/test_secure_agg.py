"""Secure aggregation (fl/secure_agg.py): exact cancellation, masking, E2E.

Pins: pairwise masks cancel EXACTLY in the wrapped int32 sum (the property
floating-point masking cannot give); a single masked upload is
full-range-uniform (the server learns nothing from one upload beyond the
modular sum); the secure round equals the plain clipped round up to the
fixed-point grid; training works end to end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddl25spring_tpu.config import FLConfig
from ddl25spring_tpu.data import mnist
from ddl25spring_tpu.fl import federate
from ddl25spring_tpu.fl.privacy import DPFedAvgServer
from ddl25spring_tpu.fl.secure_agg import (SecureAggFedAvgServer, _pair_key,
                                           dequantize_tree, mask_tree,
                                           quantize_tree)


@pytest.fixture(scope="module")
def fl_setup():
    x_raw, y, xt_raw, yt = mnist.load_mnist(n_train=1000, n_test=300, seed=0)
    x = mnist.normalize(x_raw)
    xt = mnist.normalize(xt_raw)
    cfg = FLConfig(nr_clients=10, client_fraction=0.3, batch_size=50,
                   epochs=1, lr=0.05, rounds=2, seed=10)
    subsets = mnist.split(y, cfg.nr_clients, iid=True, seed=cfg.seed)
    data = federate(x, y.astype(np.int32), subsets)
    params = mnist_cnn_init()
    return params, data, xt, yt.astype(np.int32), cfg


def mnist_cnn_init():
    from ddl25spring_tpu.models import mnist_cnn
    return mnist_cnn.init(jax.random.key(0))


def test_pairwise_masks_cancel_exactly():
    """Three clients' manually-masked int32 trees sum (wrapped) to exactly
    the unmasked sum — the core SecAgg identity."""
    root = jax.random.key(7)
    gids = jnp.asarray([2, 5, 9])
    trees = [{"w": jax.random.randint(jax.random.key(i), (64,), -1000, 1000,
                                      dtype=jnp.int32)} for i in range(3)]

    def masked(i):
        t = trees[i]
        for j in range(3):
            if j == i:
                continue
            m = mask_tree(_pair_key(root, gids[i], gids[j], 0), t)
            sign = 1 if int(gids[i]) < int(gids[j]) else -1
            t = jax.tree.map(lambda a, mm: a + sign * mm, t, m)
        return t

    total_masked = jax.tree.map(lambda *xs: sum(xs), *[masked(i)
                                                       for i in range(3)])
    total_plain = jax.tree.map(lambda *xs: sum(xs), *trees)
    np.testing.assert_array_equal(np.asarray(total_masked["w"]),
                                  np.asarray(total_plain["w"]))


def test_single_masked_upload_is_full_range():
    """One masked upload alone spans the int32 range (≈ uniform), hiding
    the ~±1000 quantized values underneath."""
    root = jax.random.key(7)
    t = {"w": jnp.zeros((4096,), jnp.int32)}
    m = mask_tree(_pair_key(root, jnp.int32(1), jnp.int32(3), 0), t)
    masked = jax.tree.map(jnp.add, t, m)["w"]
    # Uniform int32 std = 2^32 / sqrt(12) ≈ 1.24e9.
    assert float(jnp.abs(masked.astype(jnp.float32)).max()) > 1e9
    assert abs(float(masked.astype(jnp.float64).std()) - 2**32 / 12**0.5) \
        / (2**32 / 12**0.5) < 0.05


def test_quantize_roundtrip_error_bound():
    x = {"w": jnp.linspace(-5.0, 5.0, 1001)}
    scale = 5.0 / 2**19
    err = np.abs(np.asarray(dequantize_tree(quantize_tree(x, scale),
                                            scale)["w"] - x["w"]))
    assert err.max() <= scale / 2 + 1e-9


def test_secure_round_matches_clipped_round(fl_setup):
    """One secure round == one plain clipped (zero-noise DP) round up to
    the per-coordinate fixed-point bound clip·2^-(bits-1)/2 · (per-client
    average)."""
    params, data, xt, yt, cfg = fl_setup
    sec = SecureAggFedAvgServer(params, _apply(), data, xt, yt, cfg,
                                clip_norm=5.0, bits=20)
    plain = DPFedAvgServer(params, _apply(), data, xt, yt, cfg,
                           clip_norm=5.0, noise_multiplier=0.0)
    p_sec = sec._round(sec.params, 0)
    p_plain = plain._round(plain.params, 0)
    grid = 5.0 / 2**19
    for a, b in zip(jax.tree.leaves(p_sec), jax.tree.leaves(p_plain)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=grid)  # quantization only


def test_secure_agg_learns(fl_setup):
    params, data, xt, yt, cfg = fl_setup
    server = SecureAggFedAvgServer(params, _apply(), data, xt, yt, cfg,
                                   clip_norm=5.0, bits=20)
    res = server.run(nr_rounds=5)
    assert res.test_accuracy[-1] > 0.25


def _apply():
    from ddl25spring_tpu.models import mnist_cnn
    return mnist_cnn.apply
