"""Elastic data parallelism: replica loss → re-mesh + cross-topology
state resharding (resilience/elastic.py, ISSUE 5 tentpole).

The acceptance matrix: with zero faults the elastic loop is bitwise the
non-elastic path; a ``device_loss`` fault in a 4-replica ZeRO-1 run
shrinks to 3 replicas and the post-remesh trajectory is bitwise a fresh
3-replica run restored from the same state (mirror fast path AND
checkpoint slow path); the resharding primitives preserve every surviving
coordinate exactly and refuse to drop non-zero data.

The tiny model uses dmodel=20 ON PURPOSE: its 23260 params give DIFFERENT
4-way and 3-way ZeRO-1 padded lengths (23260 vs 23262), so every
cross-topology test genuinely swaps the pad instead of passing shapes
through unchanged.
"""

import os
import shutil

import jax
import numpy as np
import optax
import pytest

from ddl25spring_tpu.checkpoint import Checkpointer
from ddl25spring_tpu.config import LlamaConfig, ResilienceConfig, TrainConfig
from ddl25spring_tpu.metrics import ResilienceStats
from ddl25spring_tpu.models import llama
from ddl25spring_tpu.ops import causal_lm_loss
from ddl25spring_tpu.ops.adam import resize_zero_padded
from ddl25spring_tpu.parallel import dp, make_mesh
from ddl25spring_tpu.parallel.mesh import rejoin_mesh, survivor_submesh
from ddl25spring_tpu.resilience import (FaultPlan, ReplicaLossError,
                                        ReplicaReturnSignal)
from ddl25spring_tpu.tokenizers import ByteTokenizer
from ddl25spring_tpu.train.llm import train_llm_dp

# dmodel=20 -> 23260 params: 4-way and 3-way padded lengths differ (see
# module docstring) — the property the cross-topology assertions need.
TINY = LlamaConfig(vocab_size=259, dmodel=20, num_heads=2, n_layers=2,
                   ctx_size=16)
BASE = dict(batch_size=2, seq_len=16, lr=3e-3)


def _mesh(devices, n):
    return make_mesh({"data": n}, devices=devices[:n])


def _train(devices, n, *, iters=8, tmp=None, name=None, agg="zero1",
           spd=2, resilience=None, checkpoint_every=1000, wire="fp32",
           ovl=0, cb=1):
    return train_llm_dp(
        TINY,
        TrainConfig(**BASE, iters=iters, data=n, steps_per_dispatch=spd,
                    wire=wire, overlap_microbatches=ovl, comm_buckets=cb),
        mesh=_mesh(devices, n), tokenizer=ByteTokenizer(), aggregation=agg,
        log_every=0, resilience=resilience,
        checkpoint_dir=None if tmp is None else str(tmp / name),
        checkpoint_every=checkpoint_every)


def _prune_to(tmp, src, dst, step):
    """Copy a checkpoint dir keeping only ``step``'s save, so a fresh run
    resumes from exactly that recovery point."""
    shutil.copytree(tmp / src, tmp / dst)
    for name in os.listdir(tmp / dst):
        if name.isdigit() and int(name) != step:
            shutil.rmtree(tmp / dst / name)
    for name in os.listdir(tmp / dst / "digests"):
        if int(name.partition(".")[0]) != step:
            os.unlink(tmp / dst / "digests" / name)


# ------------------------------------------------------------- primitives

def test_resize_zero_padded_grow_truncate_and_refuse():
    v = np.array([1.0, 2.0, 3.0, 0.0], np.float32)
    np.testing.assert_array_equal(resize_zero_padded(v, 6),
                                  [1, 2, 3, 0, 0, 0])
    np.testing.assert_array_equal(resize_zero_padded(v, 3), [1, 2, 3])
    assert resize_zero_padded(v, 4) is v or (resize_zero_padded(v, 4) == v).all()
    with pytest.raises(ValueError):        # non-zero tail: refuse to drop
        resize_zero_padded(v, 2)
    with pytest.raises(ValueError):        # not a flat vector
        resize_zero_padded(np.ones((2, 2), np.float32), 2)


def test_survivor_submesh_drops_lost_replicas(devices):
    mesh = _mesh(devices, 4)
    sub = survivor_submesh(mesh, [1])
    assert sub.shape["data"] == 3
    kept = list(sub.devices.flatten())
    assert kept == [devices[0], devices[2], devices[3]]  # order preserved
    with pytest.raises(ValueError):
        survivor_submesh(mesh, [0, 1, 2, 3])     # nobody left
    with pytest.raises(ValueError):
        survivor_submesh(mesh, [7])              # out of range
    # Multi-axis scope (ISSUE 20): on a 2×2 DP×PP mesh a victim whose
    # stage column has a surviving replica drops its whole DATA row —
    # same stage count, the survivors keep flat (data-major) order.
    pp_mesh = make_mesh({"data": 2, "stage": 2}, devices=devices[:4])
    sub_pp = survivor_submesh(pp_mesh, [0])
    assert dict(sub_pp.shape) == {"data": 1, "stage": 2}
    assert list(sub_pp.devices.flatten()) == [devices[2], devices[3]]
    # 1×4: no data row survives the loss, so the stage axis must
    # RE-PARTITION — named error without layer_divisor, largest divisor
    # that fits (4 -> 2 over 3 survivors) with it.
    pp14 = make_mesh({"data": 1, "stage": 4}, devices=devices[:4])
    with pytest.raises(ValueError, match="layer_divisor"):
        survivor_submesh(pp14, [1])
    sub14 = survivor_submesh(pp14, [1], layer_divisor=4)
    assert dict(sub14.shape) == {"data": 1, "stage": 2}
    assert list(sub14.devices.flatten()) == [devices[0], devices[2]]
    # A model-axis mesh has no re-partition fallback: losing a whole
    # data row's worth of TP shards is unrecoverable, by name.
    tp_mesh = make_mesh({"data": 2, "model": 2}, devices=devices[:4])
    sub_tp = survivor_submesh(tp_mesh, [3])
    assert dict(sub_tp.shape) == {"data": 1, "model": 2}
    with pytest.raises(ValueError, match="unrecoverable"):
        survivor_submesh(make_mesh({"data": 1, "model": 4},
                                   devices=devices[:4]), [1])
    # 3-axis meshes stay out of elastic scope, by name.
    with pytest.raises(ValueError, match="3-axis"):
        survivor_submesh(make_mesh({"data": 2, "stage": 2, "model": 2},
                                   devices=devices[:8]), [0])


def test_device_loss_fault_parse_victims_deterministic():
    plan = FaultPlan.from_spec("device_loss@4:2", seed=3)
    e = plan.device_loss_at(4)
    assert e is not None and e.arg == 2.0
    assert plan.device_loss_at(3) is None

    def boom(state, batch):
        raise AssertionError("the dispatch must die before running")

    wrapped = plan.wrap_step(boom, start=4)
    with pytest.raises(ReplicaLossError) as ei:
        wrapped(None, None)
    err = ei.value
    assert err.step == 4 and err.count == 2
    assert err.victims(4) == ReplicaLossError(4, 2, seed=3).victims(4)
    assert len(err.victims(4)) == 2
    assert len(err.victims(2)) == 1              # always >= 1 survivor
    # A start offset past the schedule never fires.
    plan.wrap_step(lambda s, b: (s, b), start=5)(1, 2)


def test_reshard_state_zero1_4_to_3_is_value_exact(devices):
    """The all-gather-then-rescatter primitive: every surviving coordinate
    of params/mu/nu lands bit-identical in the 3-way layout, and the
    moments really are resharded (different padded length, still sharded
    over ``data``)."""
    params = llama.init_llama(jax.random.key(0), TINY)

    def loss_fn(p, batch):
        return causal_lm_loss(llama.forward(p, batch, TINY), batch)

    mesh4 = _mesh(devices, 4)
    state4, step4 = dp.make_zero1_step(loss_fn, optax.adam(1e-3), mesh4,
                                       params)
    batch = jax.random.randint(jax.random.key(1), (8, 16), 0, 259)
    for _ in range(2):                     # non-trivial moments
        state4, _ = step4(state4, dp.shard_batch(mesh4, batch))
    host = dp.host_snapshot(state4)

    mesh3 = survivor_submesh(mesh4, [2])
    template, _ = dp.make_zero1_step(loss_fn, optax.adam(1e-3), mesh3,
                                     params)
    state3 = dp.reshard_state(host, template)

    h_leaves = jax.tree.leaves(host)
    t_leaves = jax.tree.leaves(state3)
    changed = 0
    for h, t in zip(h_leaves, t_leaves):
        h, tv = np.asarray(h), np.asarray(t)
        if h.shape != tv.shape:
            changed += 1
            n = min(h.shape[0], tv.shape[0])
            np.testing.assert_array_equal(h[:n], tv[:n])
            assert not tv[n:].any() and not h[n:].any()
        else:
            np.testing.assert_array_equal(h, tv)
    assert changed >= 2                    # mu and nu at least moved pads
    vec = [x for x in jax.tree.leaves(state3.opt_state)
           if getattr(x, "ndim", 0) == 1]
    assert vec and all(not x.sharding.is_fully_replicated for x in vec)
    assert all(x.shape[0] % 3 == 0 for x in vec)


def test_checkpoint_restores_across_mesh_size(tmp_path, devices):
    """Cross-topology reshard-on-load: a ZeRO-1 state saved at world size
    4 restores into a 3-way template (saved-shape restore + pad swap),
    counted in ``ckpt_reshards``."""
    params = llama.init_llama(jax.random.key(0), TINY)

    def loss_fn(p, batch):
        return causal_lm_loss(llama.forward(p, batch, TINY), batch)

    mesh4 = _mesh(devices, 4)
    state4, step4 = dp.make_zero1_step(loss_fn, optax.adam(1e-3), mesh4,
                                       params)
    batch = jax.random.randint(jax.random.key(1), (8, 16), 0, 259)
    state4, _ = step4(state4, dp.shard_batch(mesh4, batch))
    host = dp.host_snapshot(state4)

    stats = ResilienceStats()
    with Checkpointer(str(tmp_path / "ck"), stats=stats) as ckpt:
        ckpt.save(1, state4)
        ckpt.wait()
        mesh3 = _mesh(devices, 3)
        template, _ = dp.make_zero1_step(loss_fn, optax.adam(1e-3), mesh3,
                                         params)
        state3 = ckpt.restore(template)
    assert stats.ckpt_reshards == 1 and stats.ckpt_fallbacks == 0
    for h, t in zip(jax.tree.leaves(host), jax.tree.leaves(state3)):
        h, tv = np.asarray(h), np.asarray(t)
        n = min(h.size, tv.size)
        np.testing.assert_array_equal(h.reshape(-1)[:n],
                                      tv.reshape(-1)[:n])


# ---------------------------------------------------------- trainer loops

@pytest.mark.parametrize("agg,spd", [("zero1", 2), ("gradient", 1)])
def test_elastic_no_fault_bitwise_matches_non_elastic(devices, agg, spd):
    """Zero faults: the elastic loop (window driver + mirror syncs +
    recovery machinery armed but idle) walks bitwise the same loss
    trajectory as today's non-elastic path, with zero recovery events."""
    ref = _train(devices, 4, iters=6, agg=agg, spd=spd)
    got = _train(devices, 4, iters=6, agg=agg, spd=spd,
                 resilience=ResilienceConfig(elastic=True))
    assert got.losses == ref.losses
    assert got.remeshes == [] and got.resilience.remeshes == 0


@pytest.mark.parametrize("mirror_every,ckpt_every,expect_path,expect_replay",
                         [(1, 1000, "mirror", 0),
                          (0, 4, "checkpoint", 2)])
def test_elastic_shrink_post_remesh_bitwise(tmp_path, devices, mirror_every,
                                            ckpt_every, expect_path,
                                            expect_replay):
    """The acceptance chaos test: device_loss at dispatch 3 (step 6 at
    K=2) in a 4-replica ZeRO-1 run shrinks to 3 replicas and continues;
    the post-remesh loss sequence is bitwise identical to a fresh
    3-replica run restored from the same (recovery-point) state. Both
    recovery paths: host-RAM mirror (resume at the failure edge, nothing
    replayed) and checkpoint (resume at the last save, 2 steps re-trained
    at the new width)."""
    el = _train(devices, 4, iters=8, tmp=tmp_path, name="el",
                checkpoint_every=ckpt_every,
                resilience=ResilienceConfig(elastic=True,
                                            mirror_every=mirror_every,
                                            faults="device_loss@3"))
    assert len(el.remeshes) == 1 and el.resilience.remeshes == 1
    rec = el.remeshes[0]
    assert rec["old_world"] == 4 and rec["new_world"] == 3
    assert rec["detected_at"] == 6 and rec["path"] == expect_path
    assert rec["steps_replayed"] == expect_replay
    assert rec["resume_step"] == 6 - expect_replay
    assert rec["seconds"] > 0
    assert len(el.losses) == 8 and np.isfinite(el.losses).all()

    # Recovery persisted the 3-way layout at the resume step; a fresh
    # 3-replica run restored from exactly that state must continue on
    # exactly el's post-remesh floats. (Drop the later steps first so the
    # comparison resumes from the recovery point, not the final save.)
    m = rec["resume_step"]
    src, dst = tmp_path / "el", tmp_path / "cmp"
    shutil.copytree(src, dst)
    for name in os.listdir(dst):
        if name.isdigit() and int(name) != m:
            shutil.rmtree(dst / name)
    for name in os.listdir(dst / "digests"):
        if int(name.partition(".")[0]) != m:
            os.unlink(dst / "digests" / name)
    ref3 = _train(devices, 3, iters=8, tmp=tmp_path, name="cmp",
                  checkpoint_every=1000)
    assert ref3.start_step == m
    assert el.losses[m:] == ref3.losses     # bitwise: same floats


def test_elastic_gradient_aggregation_shrink(devices):
    """Elastic also covers plain gradient-aggregation DP (everything
    replicated — the reshard degenerates to re-placement on the survivor
    submesh): the 4→3 shrink completes finite with recovery recorded."""
    got = _train(devices, 4, iters=8, agg="gradient",
                 resilience=ResilienceConfig(elastic=True,
                                             faults="device_loss@2"))
    assert len(got.remeshes) == 1
    assert got.remeshes[0]["old_world"] == 4
    assert got.remeshes[0]["new_world"] == 3
    assert len(got.losses) == 8 and np.isfinite(got.losses).all()


def test_elastic_two_losses_4_to_3_to_2(devices):
    """Two replica losses in one run: 4 → 3 → 2, the second recovery
    resharding the FIRST recovery's 3-way layout (mirror path), with the
    fault schedule never re-firing across rebuilds."""
    got = _train(devices, 4, iters=10,
                 resilience=ResilienceConfig(
                     elastic=True, faults="device_loss@1,device_loss@3"))
    assert [r["old_world"] for r in got.remeshes] == [4, 3]
    assert [r["new_world"] for r in got.remeshes] == [3, 2]
    assert len(got.losses) == 10 and np.isfinite(got.losses).all()
    assert got.resilience.remeshes == 2


def test_elastic_single_replica_loss_is_fatal(devices):
    """Losing the only replica leaves no survivors: elastic mode must
    re-raise, not stage a vacuous 1→1 'recovery' onto the dead device."""
    with pytest.raises(ReplicaLossError):
        _train(devices, 1, iters=4,
               resilience=ResilienceConfig(elastic=True,
                                           faults="device_loss@0"))


def test_device_loss_without_elastic_is_fatal(devices):
    """Negative control: the same device_loss fault without elastic mode
    kills the run — the error propagates out of the loop, which is what
    the elasticity layer exists to prevent."""
    with pytest.raises(ReplicaLossError):
        _train(devices, 4, iters=6,
               resilience=ResilienceConfig(elastic=False,
                                           faults="device_loss@1"))


def test_elastic_telemetry_remesh_event_and_recovery_json(tmp_path, devices):
    """The observability side: a remesh emits a schema-valid ``remesh``
    event (old/new world, path, seconds, steps replayed), run_end carries
    the remesh count, and the report records post-remesh throughput."""
    from ddl25spring_tpu.telemetry import Telemetry, read_events, validate_event

    tel = Telemetry(str(tmp_path / "obs"))
    with tel:
        got = train_llm_dp(
            TINY, TrainConfig(**BASE, iters=8, data=4, steps_per_dispatch=2),
            mesh=_mesh(devices, 4), tokenizer=ByteTokenizer(),
            aggregation="zero1", log_every=0, telemetry=tel,
            resilience=ResilienceConfig(elastic=True,
                                        faults="device_loss@2"))
    events = read_events(tel.events_path)
    remesh = [e for e in events if e.get("type") == "remesh"]
    assert len(remesh) == 1
    assert validate_event(remesh[0]) == []
    assert remesh[0]["old_world"] == 4 and remesh[0]["new_world"] == 3
    assert remesh[0]["path"] == "mirror"
    assert remesh[0]["seconds"] > 0 and remesh[0]["steps_replayed"] == 0
    run_end = [e for e in events if e.get("type") == "run_end"][-1]
    assert run_end["remeshes"] == 1
    assert got.post_remesh_tokens_per_sec > 0
    # obs_report renders the remesh section without crashing (jax-free).
    import io
    from contextlib import redirect_stdout
    from experiments.obs_report import main as report_main
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert report_main([str(tmp_path / "obs")]) == 0
    out = buf.getvalue()
    assert "remesh" in out and "4 -> 3" in out


def test_elastic_compressed_wire_needs_ring_driver(devices):
    """Repinned composition rule (ISSUE 16, was ISSUE 14's blanket
    refusal): elastic + compressed wire is now SUPPORTED — but only
    through the overlap/ring driver, whose ``OverlapEFState`` residual
    trees the remesh path reshards N→M alongside the ZeRO-1 moments
    (parallel/dp.py:_resize_ring_residual). A compressed wire WITHOUT the
    ring driver still hard-errors at config time (the legacy per-step
    compressed paths own collective schedules nobody re-meshes), and the
    message must name the knob value plus the fix so it is actionable
    from the traceback alone."""
    kw = dict(mesh=_mesh(devices, 2), tokenizer=ByteTokenizer(),
              log_every=0,
              resilience=ResilienceConfig(elastic=True))
    with pytest.raises(ValueError, match="wire='int8_ef'"):
        train_llm_dp(TINY, TrainConfig(**BASE, iters=2, data=2,
                                       wire="int8_ef"), **kw)
    with pytest.raises(ValueError, match="overlap_microbatches >= 1"):
        train_llm_dp(TINY, TrainConfig(**BASE, iters=2, data=2,
                                       wire="int8_ef"), **kw)
    # The supported composition runs: elastic + int8 EF wire + ring
    # driver, no faults — two clean steps, finite losses.
    got = train_llm_dp(TINY, TrainConfig(**BASE, iters=2, data=2,
                                         wire="int8_ef",
                                         overlap_microbatches=1), **kw)
    assert len(got.losses) == 2
    assert all(np.isfinite(l) for l in got.losses)

# ------------------------------------------------------ scale-up (ISSUE 16)

def test_rejoin_mesh_restores_pool_order(devices):
    """The scale-UP inverse of survivor_submesh: rejoining the lost
    device with the original pool reconstructs the ORIGINAL device order
    (what makes 4→3→4 comparable to a fresh 4-replica run), duplicates
    and out-of-pool devices are hard errors, and the DP-only scope
    matches the shrink primitive."""
    pool = devices[:4]
    mesh4 = _mesh(devices, 4)
    sub = survivor_submesh(mesh4, [1])
    back = rejoin_mesh(sub, [devices[1]], pool=pool)
    assert list(back.devices.flatten()) == list(pool)   # original order
    # Without the pool, returned devices append at the end.
    tail = rejoin_mesh(sub, [devices[1]])
    assert list(tail.devices.flatten()) == [devices[0], devices[2],
                                            devices[3], devices[1]]
    with pytest.raises(ValueError):                     # already present
        rejoin_mesh(sub, [devices[0]], pool=pool)
    with pytest.raises(ValueError):                     # duplicate arrivals
        rejoin_mesh(sub, [devices[1], devices[1]], pool=pool)
    with pytest.raises(ValueError):                     # outside the pool
        rejoin_mesh(sub, [devices[7]], pool=pool)
    with pytest.raises(ValueError):                     # nothing returned
        rejoin_mesh(sub, [], pool=pool)
    # Multi-axis rejoin (ISSUE 20): a full-pool rejoin reshapes straight
    # back into the ORIGINAL (data, stage) grid device-for-device; a
    # partial rejoin re-runs the factorization choice (capped at the
    # original stage count, needing layer_divisor).
    pp_pool, pp_shape = devices[:4], (2, 2)
    pp_mesh = make_mesh({"data": 2, "stage": 2}, devices=pp_pool)
    pp_sub = survivor_submesh(pp_mesh, [2])             # 2×2 -> 1×2
    back_pp = rejoin_mesh(pp_sub, [devices[2], devices[3]], pool=pp_pool,
                          pool_shape=pp_shape, layer_divisor=4)
    assert dict(back_pp.shape) == {"data": 2, "stage": 2}
    assert list(back_pp.devices.flatten()) == list(pp_pool)
    with pytest.raises(ValueError, match="layer_divisor"):
        rejoin_mesh(pp_sub, [devices[2]], pool=pp_pool,
                    pool_shape=pp_shape)                # partial, no divisor


def test_device_return_parse_arrivals_deterministic():
    """``device_return`` faults parse like ``device_loss``, raise BEFORE
    the dispatch runs, replay-safely skip with ``start=``, and pick
    seeded-deterministic arrivals from the absent pool — while the
    device_loss victim choice is pinned against vocabulary growth (adding
    the new kind must not re-roll committed victims)."""
    plan = FaultPlan.from_spec("device_loss@2,device_return@5:2", seed=3)
    e = plan.device_return_at(5)
    assert e is not None and e.arg == 2.0
    assert plan.device_return_at(4) is None

    def boom(state, batch):
        raise AssertionError("the dispatch must die before running")

    wrapped = plan.wrap_step(boom, start=5)
    with pytest.raises(ReplicaReturnSignal) as ei:
        wrapped(None, None)
    sig = ei.value
    assert sig.step == 5 and sig.count == 2
    # Deterministic given (seed, step): same arrivals every call, drawn
    # from the absent pool, capped at what is actually absent.
    assert sig.arrivals([0, 2, 3]) == sig.arrivals([0, 2, 3])
    assert sig.arrivals([0, 2, 3]) == ReplicaReturnSignal(
        5, 2, seed=3).arrivals([0, 2, 3])
    assert len(sig.arrivals([0, 2, 3])) == 2
    assert sig.arrivals([1]) == [1]                     # capped at absent
    assert sig.arrivals([]) == []
    # A start offset past the schedule never fires (replay safety).
    plan.wrap_step(lambda s, b: (s, b), start=6)(1, 2)
    # Vocabulary-growth pin: victims() must keep its pre-device_return
    # seeding (frozen salt), not a len(KINDS)-derived one.
    assert ReplicaLossError(4, 2, seed=3).victims(4) == \
        ReplicaLossError(4, 2, seed=3).victims(4)


def test_resize_ring_residual_shrink_grow_value_exact():
    """The EF-residual reshard primitive: surviving (row, coordinate)
    pairs move bit-exactly, pad swaps like the ZeRO-1 slices (zero tail
    enforced), new rows start at zero, and every row's OWN chunk is
    re-zeroed in the NEW geometry (the slot the owner never reads)."""
    from ddl25spring_tpu.parallel.dp import _resize_ring_residual

    # 4-way: 8 real coords, local=2, no pad. 3-way target: local=3,
    # ring_len=9, one pad coordinate per row.
    h = np.arange(1, 33, dtype=np.float32).reshape(4, 8)
    for r in range(4):
        h[r, r * 2:(r + 1) * 2] = 0.0                  # own chunk zero
    out = _resize_ring_residual(h, (3, 9))
    assert out.shape == (3, 9)
    for r in range(3):
        np.testing.assert_array_equal(out[r, 8:], 0.0)  # grown pad zero
        np.testing.assert_array_equal(out[r, r * 3:(r + 1) * 3], 0.0)
        keep = [c for c in range(8) if not (r * 3 <= c < (r + 1) * 3)
                and not (r * 2 <= c < (r + 1) * 2)]
        np.testing.assert_array_equal(out[r, keep], h[r, keep])
    # Round trip back to 4-way: pad truncates (it is zero), row 3 returns
    # as zeros (its pending corrections left with the topology).
    back = _resize_ring_residual(out, (4, 8))
    assert back.shape == (4, 8)
    np.testing.assert_array_equal(back[3], 0.0)
    for r in range(3):
        keep = [c for c in range(8) if not (r * 3 <= c < (r + 1) * 3)
                and not (r * 2 <= c < (r + 1) * 2)]
        np.testing.assert_array_equal(back[r, keep], h[r, keep])
        np.testing.assert_array_equal(back[r, r * 2:(r + 1) * 2], 0.0)
    # Refusals: non-zero data in the truncated tail, bad geometry.
    bad = np.ones((2, 8), np.float32)
    with pytest.raises(ValueError):
        _resize_ring_residual(bad, (2, 6))
    with pytest.raises(ValueError):
        _resize_ring_residual(h, (3, 8))               # 8 % 3 != 0


@pytest.mark.parametrize(
    "agg,spd,mirror_every,ckpt_every,expect_path,return_at,expect_replay",
    [("zero1", 2, 1, 1000, "mirror", 5, 0),
     ("zero1", 1, 0, 2, "checkpoint", 6, 1),
     ("gradient", 1, 1, 1000, "mirror", 5, 0),
     ("gradient", 2, 0, 2, "checkpoint", 5, 0)])
def test_elastic_round_trip_4_3_4_bitwise(tmp_path, devices, agg, spd,
                                          mirror_every, ckpt_every,
                                          expect_path, return_at,
                                          expect_replay):
    """The ISSUE 16 tentpole bar: a 4→3→4 trajectory (device_loss then
    device_return) holds the SAME bitwise standard as shrink-only — the
    post-grow losses equal a fresh 4-replica run restored from the grow
    recovery point, on both recovery paths, both aggregation modes, and
    K ∈ {1, 2}. The grow rejoins the exact device the shrink lost
    (pool-order restore), so the comparison mesh is literally the
    original. The zero1/K=1 checkpoint variant places the return one
    dispatch past the save cadence so the grow genuinely REPLAYS a step
    at the restored width (the stream re-split path)."""
    iters = 12 if spd == 2 else 8
    el = _train(devices, 4, iters=iters, tmp=tmp_path, name="el", agg=agg,
                spd=spd, checkpoint_every=ckpt_every,
                resilience=ResilienceConfig(
                    elastic=True, mirror_every=mirror_every,
                    faults=f"device_loss@2,device_return@{return_at}"))
    assert [(r["old_world"], r["new_world"]) for r in el.remeshes] == \
        [(4, 3), (3, 4)]
    assert [r["direction"] for r in el.remeshes] == ["shrink", "grow"]
    shrink, grow = el.remeshes
    assert grow["returned"] == shrink["lost"]          # same device back
    assert grow["path"] == expect_path
    assert grow["steps_replayed"] == expect_replay
    assert grow["resume_step"] == grow["detected_at"] - expect_replay
    assert grow["seconds"] > 0
    assert len(el.losses) == iters and np.isfinite(el.losses).all()

    m = grow["resume_step"]
    _prune_to(tmp_path, "el", "cmp", m)
    ref4 = _train(devices, 4, iters=iters, tmp=tmp_path, name="cmp",
                  agg=agg, spd=spd, checkpoint_every=1000)
    assert ref4.start_step == m
    assert el.losses[m:] == ref4.losses                # bitwise: same floats


def test_elastic_ring_int8_round_trip_bitwise(tmp_path, devices):
    """Elastic × compressed wire (the composition ISSUE 14 refused):
    4→3→4 under the int8-EF ring driver, with the ``OverlapEFState``
    residual trees resharded N→M→N alongside the ZeRO-1 moments — the
    post-grow trajectory is bitwise a fresh 4-replica int8-ring run
    restored from the grow point."""
    el = _train(devices, 4, iters=8, spd=1, tmp=tmp_path, name="el",
                wire="int8_ef", ovl=2,
                resilience=ResilienceConfig(
                    elastic=True, mirror_every=1,
                    faults="device_loss@2,device_return@5"))
    assert [r["direction"] for r in el.remeshes] == ["shrink", "grow"]
    assert [(r["old_world"], r["new_world"]) for r in el.remeshes] == \
        [(4, 3), (3, 4)]
    assert len(el.losses) == 8 and np.isfinite(el.losses).all()

    m = el.remeshes[1]["resume_step"]
    _prune_to(tmp_path, "el", "cmp", m)
    ref4 = _train(devices, 4, iters=8, spd=1, tmp=tmp_path, name="cmp",
                  wire="int8_ef", ovl=2, checkpoint_every=1000)
    assert ref4.start_step == m
    assert el.losses[m:] == ref4.losses


def test_reshard_state_bucketed_residual_tuples(devices):
    """comm_buckets > 1 reshard (ISSUE 19): the per-bucket EF residual
    tuples resize bucket-by-bucket when every interior bucket's
    coordinate span survives the world change (TINY at B=5: 23260
    params split into five 4652-coordinate buckets at BOTH 4-way and
    2-way), the 1-D gather-residual buckets ride through bitwise, and
    the two refusals fire by name: a snapshot/template bucket-count
    mismatch, and an indivisible bucket×shard factorization (B=2:
    the 4-way leading bucket spans 4·2908 = 11632 coordinates, the
    2-way one 2·5815 = 11630)."""
    from ddl25spring_tpu.parallel import compress

    params = llama.init_llama(jax.random.key(0), TINY)

    def loss_fn(p, batch):
        return causal_lm_loss(llama.forward(p, batch, TINY), batch)

    def build(n, buckets):
        mesh = _mesh(devices, n)
        state, step = compress.make_overlap_step(
            loss_fn, optax.adam(1e-3), mesh, params, microbatches=2,
            wire="int8_ef", aggregation="zero1", comm_buckets=buckets)
        return mesh, state, step

    mesh4, state4, step4 = build(4, 5)
    batch = jax.random.randint(jax.random.key(1), (8, 16), 0, 259)
    for _ in range(2):                         # non-zero EF residuals
        state4, _ = step4(state4, dp.shard_batch(mesh4, batch))
    host = dp.host_snapshot(state4)
    assert isinstance(host.ring_residual, tuple)
    assert len(host.ring_residual) == 5
    assert any(np.asarray(r).any() for r in host.ring_residual)

    # 4 -> 2: each bucket's ring rows re-chunk 4×1163 -> 2×2326 with the
    # same 4652-coordinate span, so surviving rows keep every coordinate
    # outside the old/new own chunks and the new own chunk is re-zeroed.
    _, t2, _ = build(2, 5)
    s2 = dp.reshard_state(host, t2)
    assert len(s2.ring_residual) == 5
    for h, t in zip(host.ring_residual, s2.ring_residual):
        h, tv = np.asarray(h), np.asarray(t)
        assert h.shape == (4, 4652) and tv.shape == (2, 4652)
        for r in range(2):
            np.testing.assert_array_equal(
                tv[r, r * 2326:(r + 1) * 2326], 0.0)
            keep = [c for c in range(4652)
                    if not (r * 2326 <= c < (r + 1) * 2326)
                    and not (r * 1163 <= c < (r + 1) * 1163)]
            np.testing.assert_array_equal(tv[r, keep], h[r, keep])
    # Gather residuals are 1-D [span] globals per bucket: span-invariant
    # worlds carry them through bitwise.
    for h, t in zip(host.gather_residual, s2.gather_residual):
        np.testing.assert_array_equal(np.asarray(h), np.asarray(t))

    # Round trip back to 4-way: rows 0/1 keep the surviving coordinates,
    # rows 2/3 return as zeros (their corrections left with the mesh).
    _, t4, _ = build(4, 5)
    s4 = dp.reshard_state(dp.host_snapshot(s2), t4)
    for h, t in zip(host.ring_residual, s4.ring_residual):
        h, tv = np.asarray(h), np.asarray(t)
        assert tv.shape == (4, 4652)
        np.testing.assert_array_equal(tv[2:], 0.0)
        for r in range(2):
            keep = [c for c in range(4652)
                    if not (r * 2326 <= c < (r + 1) * 2326)
                    and not (r * 1163 <= c < (r + 1) * 1163)]
            np.testing.assert_array_equal(tv[r, keep], h[r, keep])
    for h, t in zip(host.gather_residual, s4.gather_residual):
        np.testing.assert_array_equal(np.asarray(h), np.asarray(t))
    for h, t in zip(jax.tree.leaves(host.params),
                    jax.tree.leaves(s4.params)):
        np.testing.assert_array_equal(np.asarray(h), np.asarray(t))

    # Refusal 1: snapshot carries 5 residual buckets, template a single
    # legacy array — rebucketing a live EF state is not defined.
    _, t1, _ = build(2, 1)
    with pytest.raises(ValueError, match="comm_buckets mismatch"):
        dp.reshard_state(host, t1)

    # Refusal 2: B=2 interior spans differ across 4-way/2-way — named.
    _, s4b2, _ = build(4, 2)
    _, t2b2, _ = build(2, 2)
    with pytest.raises(ValueError,
                       match="indivisible bucket×shard factorization"):
        dp.reshard_state(dp.host_snapshot(s4b2), t2b2)


def test_elastic_bucketed_ring_int8_round_trip_bitwise(tmp_path, devices):
    """Elastic × bucketed backward (ISSUE 19 satellite): 4→2→4 under the
    int8-EF ring with comm_buckets=5 — TINY's five 4652-coordinate
    buckets have world-invariant spans at 4-way and 2-way, so the
    per-bucket residual tuples reshard in both directions and the
    post-grow trajectory is bitwise a fresh bucketed 4-replica run
    restored from the grow point. (A 4→3 shrink changes the interior
    spans and is refused by name — pinned in
    test_reshard_state_bucketed_residual_tuples.)"""
    el = _train(devices, 4, iters=8, spd=1, tmp=tmp_path, name="el",
                wire="int8_ef", ovl=2, cb=5,
                resilience=ResilienceConfig(
                    elastic=True, mirror_every=1,
                    faults="device_loss@2:2,device_return@5:2"))
    assert [r["direction"] for r in el.remeshes] == ["shrink", "grow"]
    assert [(r["old_world"], r["new_world"]) for r in el.remeshes] == \
        [(4, 2), (2, 4)]
    assert len(el.losses) == 8 and np.isfinite(el.losses).all()

    m = el.remeshes[1]["resume_step"]
    _prune_to(tmp_path, "el", "cmp", m)
    ref4 = _train(devices, 4, iters=8, spd=1, tmp=tmp_path, name="cmp",
                  wire="int8_ef", ovl=2, cb=5, checkpoint_every=1000)
    assert ref4.start_step == m
    assert el.losses[m:] == ref4.losses


def test_elastic_ring_int8_preempt_remesh_resume_bitwise(tmp_path, devices):
    """Preempt → remesh → resume under elastic + int8 ring: a run that
    shrinks at step 2 and is preempted at step 5 force-saves the 3-way
    layout WITH its EF residuals; the rerun resumes and the stitched loss
    record equals the same run without the preemption EXACTLY — residual
    state survives both the reshard and the save/restore cycle."""
    ref = _train(devices, 4, iters=8, spd=1, wire="int8_ef", ovl=2,
                 resilience=ResilienceConfig(
                     elastic=True, mirror_every=1, faults="device_loss@2"))
    assert len(ref.losses) == 8

    r1 = _train(devices, 4, iters=8, spd=1, tmp=tmp_path, name="pre",
                wire="int8_ef", ovl=2, checkpoint_every=2,
                resilience=ResilienceConfig(
                    elastic=True, mirror_every=1,
                    faults="device_loss@2,preempt@5"))
    assert r1.preempted and len(r1.losses) < 8
    assert len(r1.remeshes) == 1

    # Rerun at the post-shrink world size: the saved layout is 3-way.
    r2 = _train(devices, 3, iters=8, spd=1, tmp=tmp_path, name="pre",
                wire="int8_ef", ovl=2, checkpoint_every=2)
    assert not r2.preempted
    assert ref.losses[r2.start_step:] == r2.losses     # bitwise resume
    assert ref.losses[:r2.start_step] == r1.losses[:r2.start_step]


# ------------------------------------- multi-axis elasticity (ISSUE 20)

# n_layers=4 so a stage re-partition has somewhere to land (4 -> 2 -> 1
# all divide); dmodel=20 keeps the differing-pad property of TINY.
TINY4 = TINY.replace(n_layers=4)
PP_BASE = dict(batch_size=2, seq_len=16, lr=3e-3, microbatches=2)


def _pp_mesh(devices, d, s):
    return make_mesh({"data": d, "stage": s}, devices=devices[:d * s])


def _train_pp(devices, d, s, *, iters=8, tmp=None, name=None, spd=2,
              agg="gradient", wire="fp32", ovl=0, cb=1, resilience=None,
              checkpoint_every=1000, telemetry=None):
    from ddl25spring_tpu.train.llm import train_llm_pp
    return train_llm_pp(
        TINY4,
        TrainConfig(**PP_BASE, iters=iters, data=d, stage=s,
                    steps_per_dispatch=spd, wire=wire,
                    overlap_microbatches=ovl, comm_buckets=cb),
        mesh=_pp_mesh(devices, d, s), tokenizer=ByteTokenizer(),
        aggregation=agg, log_every=0, resilience=resilience,
        checkpoint_dir=None if tmp is None else str(tmp / name),
        checkpoint_every=checkpoint_every, telemetry=telemetry)


@pytest.mark.parametrize("d,s,agg,ovl", [(2, 2, "gradient", 0),
                                         (1, 4, "zero1", 1)])
def test_elastic_pp_no_fault_bitwise_matches_non_elastic(devices, d, s,
                                                         agg, ovl):
    """Zero faults on a DP×PP mesh: the elastic window loop (recovery
    machinery armed but idle) walks bitwise the same losses as the
    non-elastic pipeline trainer, on both the plain and the ring/zero1
    drivers."""
    ref = _train_pp(devices, d, s, iters=6, agg=agg, ovl=ovl)
    got = _train_pp(devices, d, s, iters=6, agg=agg, ovl=ovl,
                    resilience=ResilienceConfig(elastic=True))
    assert got.losses == ref.losses
    assert got.remeshes == [] and got.resilience.remeshes == 0


@pytest.mark.parametrize("mirror_every,ckpt_every,expect_path,expect_replay",
                         [(1, 1000, "mirror", 0),
                          (0, 4, "checkpoint", 2)])
def test_elastic_pp_stage_repartition_bitwise(tmp_path, devices,
                                              mirror_every, ckpt_every,
                                              expect_path, expect_replay):
    """The ISSUE 20 tentpole bar, re-partition direction: a device loss
    on a 1×4 pipeline leaves no complete data row, so layers re-slice
    onto 2 stages (blocks [1, ...] per stage -> [2, ...], moved by global
    coordinate id) and training continues — with the post-re-partition
    losses bitwise a fresh 1×2 run restored from the recovery state, on
    both recovery paths."""
    el = _train_pp(devices, 1, 4, iters=8, tmp=tmp_path, name="el",
                   checkpoint_every=ckpt_every,
                   resilience=ResilienceConfig(elastic=True,
                                               mirror_every=mirror_every,
                                               faults="device_loss@3"))
    assert len(el.remeshes) == 1 and el.resilience.remeshes == 1
    rec = el.remeshes[0]
    assert rec["axis"] == "stage"
    assert rec["old_shape"] == [1, 4] and rec["new_shape"] == [1, 2]
    assert rec["old_world"] == 4 and rec["new_world"] == 2
    assert rec["detected_at"] == 6 and rec["path"] == expect_path
    assert rec["steps_replayed"] == expect_replay
    assert rec["resume_step"] == 6 - expect_replay
    assert len(el.losses) == 8 and np.isfinite(el.losses).all()

    m = rec["resume_step"]
    _prune_to(tmp_path, "el", "cmp", m)
    ref2 = _train_pp(devices, 1, 2, iters=8, tmp=tmp_path, name="cmp")
    assert ref2.start_step == m
    assert el.losses[m:] == ref2.losses                # bitwise: same floats


def test_elastic_pp_data_shrink_preferred_bitwise(tmp_path, devices):
    """The reshard direction: a device loss on a 2×2 mesh whose stage
    column still has a surviving replica drops the victim's DATA row —
    stage count unchanged, the recovery is a pure reshard — and the
    post-remesh losses are bitwise a fresh 1×2 run restored from the
    recovery state."""
    el = _train_pp(devices, 2, 2, iters=8, tmp=tmp_path, name="el",
                   resilience=ResilienceConfig(elastic=True,
                                               faults="device_loss@3"))
    assert len(el.remeshes) == 1
    rec = el.remeshes[0]
    assert rec["axis"] == "data"
    assert rec["old_shape"] == [2, 2] and rec["new_shape"] == [1, 2]
    assert len(el.losses) == 8 and np.isfinite(el.losses).all()

    m = rec["resume_step"]
    _prune_to(tmp_path, "el", "cmp", m)
    ref2 = _train_pp(devices, 1, 2, iters=8, tmp=tmp_path, name="cmp")
    assert ref2.start_step == m
    assert el.losses[m:] == ref2.losses


@pytest.mark.parametrize("d,s,grow_axis", [(2, 2, "data"), (1, 4, "stage")])
def test_elastic_pp_round_trip_restores_original_topology(tmp_path, devices,
                                                          d, s, grow_axis):
    """The multi-axis pool-order bar: device_loss then a full
    device_return walks (D, S) -> (D', S') -> (D, S) — the grow rejoins
    every absent pool slot and the full-pool reshape rebuilds the
    ORIGINAL factorization (rejoin_mesh pool_shape), in both directions:
    a data-row drop grows its row back, a stage re-partition grows back
    to the original stage count. Post-grow losses are bitwise a fresh
    (D, S) run restored from the grow recovery point."""
    el = _train_pp(devices, d, s, iters=12, tmp=tmp_path, name="el",
                   resilience=ResilienceConfig(
                       elastic=True, mirror_every=1,
                       faults="device_loss@2,device_return@5:3"))
    assert [r["direction"] for r in el.remeshes] == ["shrink", "grow"]
    shrink, grow = el.remeshes
    assert shrink["old_shape"] == [d, s] and shrink["new_shape"] == [1, 2]
    assert grow["axis"] == grow_axis
    assert grow["old_shape"] == [1, 2] and grow["new_shape"] == [d, s]
    assert grow["old_world"] == 2 and grow["new_world"] == 4
    assert len(el.losses) == 12 and np.isfinite(el.losses).all()

    m = grow["resume_step"]
    _prune_to(tmp_path, "el", "cmp", m)
    ref = _train_pp(devices, d, s, iters=12, tmp=tmp_path, name="cmp")
    assert ref.start_step == m
    assert el.losses[m:] == ref.losses                 # bitwise: same floats


def test_elastic_pp_zero_retraces_per_topology(tmp_path, devices):
    """Compile accounting across a re-partition: each topology's window
    driver carries its own (D, S)-tagged CompileWatch, both tags appear
    in the event stream, and NO compile event is a retrace — a topology
    compiles its programs once and serves every subsequent dispatch from
    cache."""
    from ddl25spring_tpu.telemetry import Telemetry, read_events

    tel = Telemetry(str(tmp_path / "obs"))
    with tel:
        got = _train_pp(devices, 1, 4, iters=8, telemetry=tel,
                        resilience=ResilienceConfig(elastic=True,
                                                    faults="device_loss@3"))
    assert len(got.remeshes) == 1
    compiles = {}
    for e in read_events(tel.events_path):
        if e.get("type") == "compile":
            row = compiles.setdefault(e["name"],
                                      {"compiles": 0, "retraces": 0})
            row["compiles"] += 1
            row["retraces"] += int(bool(e.get("retrace")))
    assert "train/pp-gpipe-elastic-d1s4" in compiles
    assert "train/pp-gpipe-elastic-d1s2" in compiles
    assert all(v["retraces"] == 0 for v in compiles.values())
    remesh = [e for e in read_events(tel.events_path)
              if e.get("type") == "remesh"]
    assert len(remesh) == 1
    assert remesh[0]["axis"] == "stage"
    assert remesh[0]["old_shape"] == [1, 4]
    assert remesh[0]["new_shape"] == [1, 2]


def test_elastic_pp_chaos_nan_grad_skip_and_stage_loss(devices):
    """Chaos composition: one elastic 1×4 pipeline run takes BOTH a
    nan_grad fault (StepGuard skips the poisoned dispatch — consumed,
    not learned) and a later device loss (stage re-partition 4 -> 2);
    the run finishes every iteration finite with both recoveries
    recorded on their own counters."""
    got = _train_pp(devices, 1, 4, iters=10,
                    resilience=ResilienceConfig(
                        elastic=True, guard=True,
                        faults="nan_grad@1,device_loss@3"))
    assert got.resilience.skipped_steps >= 1           # the guard fired
    assert got.resilience.remeshes == 1                # and the re-mesh
    assert got.remeshes[0]["axis"] == "stage"
    # The poisoned dispatch's losses stay visible as NaN (the
    # test_resilience.py contract: the fault is visible AND contained) —
    # everything from the re-mesh step onward is finite.
    assert len(got.losses) == 10
    assert np.isfinite(got.losses[4:]).all()
    assert sum(np.isfinite(l) for l in got.losses) >= 8


def test_elastic_pp_rejects_interleaved_by_name(devices):
    """The named non-composition: the interleaved schedule's chunk-major
    layer order breaks the contiguous blocked stage slices a
    re-partition re-slices — config-time error naming the fix."""
    from ddl25spring_tpu.train.llm import train_llm_pp
    with pytest.raises(ValueError, match="interleaved"):
        train_llm_pp(
            TINY4,
            TrainConfig(**PP_BASE, iters=2, data=1, stage=2,
                        steps_per_dispatch=2),
            mesh=_pp_mesh(devices, 1, 2), tokenizer=ByteTokenizer(),
            schedule="interleaved", log_every=0,
            resilience=ResilienceConfig(elastic=True))


# --------------------------------------- TP PSA elasticity (ROADMAP 7a)

def _train_tp(devices, d, *, iters=8, tmp=None, name=None, spd=1,
              psa="int8_ef", resilience=None, checkpoint_every=1000):
    from ddl25spring_tpu.train.llm import train_llm_tp
    return train_llm_tp(
        TINY4,
        TrainConfig(batch_size=2, seq_len=16, lr=3e-3, iters=iters,
                    data=d, model=2, steps_per_dispatch=spd, psa=psa),
        mesh=make_mesh({"data": d, "model": 2}, devices=devices[:d * 2]),
        tokenizer=ByteTokenizer(), log_every=0, resilience=resilience,
        checkpoint_dir=None if tmp is None else str(tmp / name),
        checkpoint_every=checkpoint_every)


def test_elastic_tp_psa_no_fault_bitwise(devices):
    """The lifted PSA × elastic combination (ROADMAP 7a): with zero
    faults the elastic TP loop under psa='int8_ef' is bitwise the
    non-elastic trainer."""
    ref = _train_tp(devices, 2, iters=4)
    got = _train_tp(devices, 2, iters=4,
                    resilience=ResilienceConfig(elastic=True))
    assert got.losses == ref.losses and got.remeshes == []


def test_elastic_tp_psa_int8_preempt_remesh_resume_bitwise(tmp_path,
                                                           devices):
    """ROADMAP 7a acceptance: preempt → remesh → resume under
    psa='int8_ef' on a DP×TP mesh. A 2×2 run loses a device (data row
    drop to 1×2 — the TPActState activation EF residual tree resized
    per data row by dp._resize_act_residual), is preempted later, and
    the rerun's stitched losses equal the same run without the
    preemption EXACTLY — the PSA residuals survive both the reshard and
    the save/restore cycle."""
    ref = _train_tp(devices, 2, iters=8,
                    resilience=ResilienceConfig(elastic=True, mirror_every=1,
                                                faults="device_loss@2"))
    assert len(ref.losses) == 8 and len(ref.remeshes) == 1
    assert ref.remeshes[0]["axis"] == "data"
    assert ref.remeshes[0]["old_shape"] == [2, 2]
    assert ref.remeshes[0]["new_shape"] == [1, 2]

    r1 = _train_tp(devices, 2, iters=8, tmp=tmp_path, name="pre",
                   checkpoint_every=2,
                   resilience=ResilienceConfig(
                       elastic=True, mirror_every=1,
                       faults="device_loss@2,preempt@5"))
    assert r1.preempted and len(r1.losses) < 8
    assert len(r1.remeshes) == 1

    # Rerun at the post-shrink factorization: the saved layout is 1×2.
    r2 = _train_tp(devices, 1, iters=8, tmp=tmp_path, name="pre",
                   checkpoint_every=2)
    assert not r2.preempted
    assert ref.losses[r2.start_step:] == r2.losses     # bitwise resume
    assert ref.losses[:r2.start_step] == r1.losses[:r2.start_step]


def test_elastic_tp_model_axis_loss_is_fatal(devices):
    """A 1×2 TP mesh losing a device has no surviving data row and no
    re-partition fallback (the Megatron layout is not layer-sliced):
    elastic mode must re-raise, not fabricate a topology."""
    with pytest.raises(ReplicaLossError):
        _train_tp(devices, 1, iters=4,
                  resilience=ResilienceConfig(elastic=True,
                                              faults="device_loss@1"))
