"""KV-cache decoding vs the full forward pass.

The cache path must be a pure re-arrangement of the same math: prefill+decode
logits are compared against `llama.forward` at every position, and greedy
generation must equal the O(T²) re-forward argmax loop.
"""

import jax
import jax.numpy as jnp
import pytest

from ddl25spring_tpu.config import LlamaConfig
from ddl25spring_tpu.models import generate, llama

CFG = LlamaConfig(vocab_size=97, dmodel=32, num_heads=4, n_layers=3,
                  ctx_size=32)


@pytest.fixture(scope="module")
def params():
    return llama.init_llama(jax.random.PRNGKey(0), CFG)


def test_prefill_matches_forward(params):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, CFG.vocab_size)
    full = llama.forward(params, tokens, CFG)            # [B, T, V]
    cache = generate.init_cache(CFG, 2, 16)
    logits, _ = generate.forward_cached(params, tokens, cache, 0, CFG)
    assert jnp.allclose(logits, full[:, -1, :], atol=1e-4)


def test_decode_steps_match_forward(params):
    """Feed tokens one at a time through the cache; every step's logits must
    equal the full forward's logits at that position."""
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, CFG.vocab_size)
    full = llama.forward(params, tokens, CFG)
    cache = generate.init_cache(CFG, 2, 8)
    for t in range(tokens.shape[1]):
        logits, cache = generate.forward_cached(
            params, tokens[:, t:t + 1], cache, t, CFG)
        assert jnp.allclose(logits, full[:, t, :], atol=1e-4), t


def test_prefill_then_decode_matches_forward(params):
    """Mixed mode: prefill 5 tokens, decode 3 more — each decode step must
    agree with the all-at-once forward over the concatenation."""
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 8), 0, CFG.vocab_size)
    full = llama.forward(params, tokens, CFG)
    cache = generate.init_cache(CFG, 1, 8)
    logits, cache = generate.forward_cached(params, tokens[:, :5], cache, 0, CFG)
    assert jnp.allclose(logits, full[:, 4, :], atol=1e-4)
    for t in range(5, 8):
        logits, cache = generate.forward_cached(
            params, tokens[:, t:t + 1], cache, t, CFG)
        assert jnp.allclose(logits, full[:, t, :], atol=1e-4), t


def test_greedy_generate_matches_reforward_loop(params):
    prompt = jax.random.randint(jax.random.PRNGKey(4), (2, 4), 0, CFG.vocab_size)
    out = generate.generate(params, prompt, CFG, 6)
    assert out.shape == (2, 6)
    # Reference: naive O(T²) loop re-running the full forward each step.
    seq = prompt
    want = []
    for _ in range(6):
        logits = llama.forward(params, seq, CFG)[:, -1, :]
        nxt = jnp.argmax(logits, axis=-1)
        want.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    assert jnp.array_equal(out, jnp.stack(want, axis=1))


def test_sampled_generate_respects_top_k(params):
    prompt = jnp.zeros((1, 2), jnp.int32)
    out = generate.generate(params, prompt, CFG, 5, key=jax.random.PRNGKey(7),
                            temperature=0.8, top_k=3)
    assert out.shape == (1, 5)
    # Replay with the cache to check every sampled id was inside the top-3
    # of its step's distribution.
    cache = generate.init_cache(CFG, 1, 7)
    logits, cache = generate.forward_cached(params, prompt, cache, 0, CFG)
    for i in range(5):
        top3 = set(jax.lax.top_k(logits[0], 3)[1].tolist())
        assert int(out[0, i]) in top3, i
        if i < 4:
            logits, cache = generate.forward_cached(
                params, out[:, i:i + 1], cache, 2 + i, CFG)


def test_nucleus_filter_keeps_smallest_covering_prefix():
    """_sample with top_p on a hand-built distribution: probs
    (0.5, 0.3, 0.15, 0.05) → p=0.6 keeps {0, 1} (token 1 crosses the
    boundary and is included), p=0.4 keeps only {0}, p=1.0 keeps all."""
    probs = jnp.array([[0.5, 0.3, 0.15, 0.05]])
    logits = jnp.log(probs)
    keys = jax.random.split(jax.random.PRNGKey(3), 200)

    def support(top_p):
        ids = [int(generate._sample(k, logits, 1.0, None, top_p)[0])
               for k in keys]
        return set(ids)

    assert support(0.4) == {0}
    assert support(0.6) <= {0, 1} and 1 in support(0.6)
    assert support(1.0) <= {0, 1, 2, 3} and len(support(1.0)) >= 3


def test_sampled_generate_respects_top_p(params):
    prompt = jnp.zeros((1, 2), jnp.int32)
    out = generate.generate(params, prompt, CFG, 4, key=jax.random.PRNGKey(9),
                            temperature=0.8, top_p=0.9)
    assert out.shape == (1, 4)
    # Replay: every sampled id must lie in the nucleus (smallest prefix of
    # the temperature-scaled distribution reaching 0.9) of its step.
    cache = generate.init_cache(CFG, 1, 6)
    logits, cache = generate.forward_cached(params, prompt, cache, 0, CFG)
    for i in range(4):
        p = jax.nn.softmax(logits[0] / 0.8)
        order = jnp.argsort(-p)
        mass_before = jnp.cumsum(p[order]) - p[order]
        nucleus = set(order[mass_before < 0.9].tolist())
        assert int(out[0, i]) in nucleus, i
        if i < 3:
            logits, cache = generate.forward_cached(
                params, out[:, i:i + 1], cache, 2 + i, CFG)


def test_padding_idx_zero_embedding_in_decode():
    cfg = LlamaConfig(vocab_size=97, dmodel=32, num_heads=4, n_layers=2,
                      ctx_size=16, padding_idx=0)
    params = llama.init_llama(jax.random.PRNGKey(0), cfg)
    tokens = jnp.array([[0, 5, 0, 7]], jnp.int32)
    full = llama.forward(params, tokens, cfg)
    cache = generate.init_cache(cfg, 1, 4)
    for t in range(4):
        logits, cache = generate.forward_cached(
            params, tokens[:, t:t + 1], cache, t, cfg)
        assert jnp.allclose(logits, full[:, t, :], atol=1e-4), t


def test_generate_with_sharded_params_and_batch(params, devices):
    """Distributed inference: params replicated / batch sharded over a
    ``data`` mesh axis must decode exactly what one device decodes —
    jit partitions the whole prefill+decode program via GSPMD."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ddl25spring_tpu.parallel import make_mesh

    prompt = jax.random.randint(jax.random.PRNGKey(9), (4, 5), 0,
                                CFG.vocab_size)
    want = generate.generate(params, prompt, CFG, 6)

    mesh = make_mesh({"data": 2}, devices=devices[:2])
    p_sh = jax.device_put(params, NamedSharding(mesh, P()))
    prompt_sh = jax.device_put(prompt, NamedSharding(mesh, P("data")))
    got = generate.generate(p_sh, prompt_sh, CFG, 6)
    assert jnp.array_equal(want, got)


def test_generate_oversized_request_raises(params):
    """prompt_len + max_new_tokens > max_len must be a clear ValueError,
    not a silent out-of-range cache write (dynamic_update_slice would clamp
    the start index and OVERWRITE earlier positions, producing garbage tail
    tokens)."""
    prompt = jnp.zeros((1, 6), jnp.int32)
    with pytest.raises(ValueError, match="exceeds max_len"):
        generate.generate(params, prompt, CFG, 4, max_len=8)   # needs 10
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate.generate(params, prompt, CFG, 0)
    # The boundary case fits exactly and must NOT raise.
    out = generate.generate(params, prompt, CFG, 4, max_len=10)
    assert out.shape == (1, 4)


# ----------------------------------------------------- serving-engine parity
# The slot-based prefill()/decode_step() engine (ddl25spring_tpu/serving)
# re-arranges this module's math over a paged block pool; these tests pin
# that it reproduces generate() TOKEN-FOR-TOKEN at equal seeds — the
# serving subsystem's correctness bar (ISSUE 6).

def _paged():
    from ddl25spring_tpu.serving import PagedKVConfig
    return PagedKVConfig(num_blocks=32, block_len=4, max_blocks_per_seq=8)


def _engine_streams(params, requests, *, num_slots, prefill_chunk,
                    top_k=None, top_p=None, speculate=None):
    """Run ragged ``(prompt, max_new, temperature, seed)`` requests in ONE
    slot batch; returns each slot's emitted tokens."""
    import numpy as np

    from ddl25spring_tpu.serving import Engine
    eng = Engine(params, CFG, _paged(), num_slots,
                 prefill_chunk=prefill_chunk, top_k=top_k, top_p=top_p,
                 speculate=speculate)
    slots = {}
    for i, (prompt, mx, temp, seed) in enumerate(requests):
        key = jax.random.PRNGKey(seed) if temp > 0 else None
        s = eng.admit(np.asarray(prompt, np.int32), mx, temperature=temp,
                      key=key)
        slots[i] = s
    toks = {s: [] for s in slots.values()}
    while eng.busy:
        for ev in eng.step():
            toks[ev.slot].append(ev.token)
    return [toks[slots[i]] for i in range(len(requests))]


def _generate_stream(params, prompt, mx, temp, seed, *, top_k=None,
                     top_p=None):
    # The ONE reference-construction helper (serving/frontend.py) — the
    # rules that make the parity bar valid (max_len/kv_dtype pinned to the
    # pool, key only when sampling) must not be re-derived here.
    from ddl25spring_tpu.serving import Request, reference_stream
    req = Request(rid="ref", prompt=tuple(int(t) for t in prompt),
                  max_new=mx, temperature=temp, seed=seed)
    return reference_stream(params, CFG, _paged(), req, top_k=top_k,
                            top_p=top_p)


def test_slot_engine_matches_generate_greedy_bitwise(params):
    """Ragged greedy prompts sharing one slot batch: each stream must be
    BITWISE the stream generate() emits for that request alone."""
    rng = jax.random.PRNGKey(21)
    reqs = []
    for i, (tp, mx) in enumerate([(3, 6), (9, 4), (5, 8)]):
        rng, sub = jax.random.split(rng)
        prompt = jax.random.randint(sub, (tp,), 0, CFG.vocab_size).tolist()
        reqs.append((prompt, mx, 0.0, 0))
    got = _engine_streams(params, reqs, num_slots=3, prefill_chunk=4)
    for (prompt, mx, temp, seed), stream in zip(reqs, got):
        assert stream == _generate_stream(params, prompt, mx, temp, seed)


def test_slot_engine_matches_generate_sampled_bitwise(params):
    """Temperature sampling at equal seeds, mixed with a greedy neighbor in
    the same batch: per-slot RNG keys must reproduce generate()'s exact
    split sequence regardless of batch company."""
    reqs = [([5, 17, 3], 6, 0.8, 13),
            ([2, 9, 41, 7, 30, 11, 4], 5, 0.6, 99),
            ([8, 8], 7, 0.0, 0)]
    got = _engine_streams(params, reqs, num_slots=3, prefill_chunk=4)
    for (prompt, mx, temp, seed), stream in zip(reqs, got):
        assert stream == _generate_stream(params, prompt, mx, temp, seed)


def test_slot_engine_chunked_prefill_matches_whole_prompt(params):
    """A prompt split over several prefill chunks (chunk < prompt_len) must
    emit the same stream as one-shot prefill — chunking is a latency
    decision, not a math change. Also pins the RNG discipline: the key
    splits ONCE per prefill no matter how many chunks carry it."""
    prompt = [int(x) for x in
              jax.random.randint(jax.random.PRNGKey(5), (11,), 0,
                                 CFG.vocab_size)]
    want_greedy = _generate_stream(params, prompt, 6, 0.0, 0)
    want_sampled = _generate_stream(params, prompt, 6, 0.9, 42)
    for chunk in (2, 3, 16):       # straddling, uneven, single-chunk
        got = _engine_streams(params, [(prompt, 6, 0.0, 0),
                                       (prompt, 6, 0.9, 42)],
                              num_slots=2, prefill_chunk=chunk)
        assert got[0] == want_greedy, chunk
        assert got[1] == want_sampled, chunk


def test_slot_engine_matches_generate_with_top_k_top_p(params):
    """The static top_k/top_p filters compose identically on both paths."""
    reqs = [([1, 2, 3], 5, 0.8, 3), ([4, 5], 4, 0.7, 8)]
    got = _engine_streams(params, reqs, num_slots=2, prefill_chunk=4,
                          top_k=7, top_p=0.9)
    for (prompt, mx, temp, seed), stream in zip(reqs, got):
        assert stream == _generate_stream(params, prompt, mx, temp, seed,
                                          top_k=7, top_p=0.9)


# -------------------------------------------------- speculative decoding
# Greedy speculative decoding must emit BITWISE the greedy stream: every
# accepted draft token is re-derived as the target's own argmax, and so
# is the correction/bonus token beyond the accepted prefix — for ANY
# draft, at any k (serving/speculate.py; the engine battery's scheduler-
# level and CoW twins live in tests/test_speculate.py).

def _spec(params_or_draft, k):
    from ddl25spring_tpu.serving import SpecConfig
    return SpecConfig(k=k, draft_params=params_or_draft)


def test_reference_speculative_stream_matches_generate(params):
    """The hand-checkable reference (models/generate.py): greedy
    draft-propose/verify over full re-forwards equals generate() token
    for token at k ∈ {1, 3} — for a same-weights draft (acceptance 1,
    every proposal used) AND a disagreeing one (acceptance < 1, every
    correction used)."""
    draft = llama.init_llama(jax.random.PRNGKey(9), CFG)
    prompt = [3, 5, 7, 2]
    want = generate.generate(params, jnp.asarray([prompt]), CFG,
                             7)[0].tolist()
    for k in (1, 3):
        for dp in (params, draft):
            got, stats = generate.speculative_stream(params, dp, prompt,
                                                     CFG, 7, k=k)
            assert got == want, (k, stats)
            assert stats["proposed"] > 0
            assert 0 <= stats["accepted"] <= stats["proposed"]
    # Same weights accept every usable proposal; the acceptance counter
    # is exact, not an estimate — INCLUDING at a max_new that is not a
    # multiple of the round size, where the final round's proposals are
    # horizon-truncated: only min(k, remaining) count as proposed (the
    # engine's schema-v7 rule), so truncation never reads as rejection.
    for mx in (7, 6):
        _, s_same = generate.speculative_stream(params, params, prompt,
                                                CFG, mx, k=3)
        assert s_same["accepted"] == s_same["proposed"] > 0, mx


def test_slot_engine_speculative_greedy_bitwise(params):
    """Ragged greedy prompts in one slot batch under speculation: each
    stream bitwise generate()'s for k ∈ {1, 3}, with a same-weights and
    a separately-weighted draft — acceptance rate is a throughput knob,
    never a token knob."""
    draft = llama.init_llama(jax.random.PRNGKey(9), CFG)
    reqs = []
    rng = jax.random.PRNGKey(23)
    for tp, mx in [(3, 6), (9, 4), (5, 8)]:
        rng, sub = jax.random.split(rng)
        prompt = jax.random.randint(sub, (tp,), 0, CFG.vocab_size).tolist()
        reqs.append((prompt, mx, 0.0, 0))
    want = [_generate_stream(params, p, mx, t, s) for p, mx, t, s in reqs]
    for k in (1, 3):
        for dp in (params, draft):
            got = _engine_streams(params, reqs, num_slots=3,
                                  prefill_chunk=4, speculate=_spec(dp, k))
            assert got == want, k


def test_speculative_acceptance_straddles_block_edge(params):
    """Verify windows whose accepted prefix crosses a block boundary
    (block_len=4; prompt lengths chosen so windows start mid-block and
    end in the next) write the straddling K/V correctly: streams stay
    bitwise through every crossing, including a max_seq_len request
    whose final window is horizon-clamped (the live mask — an unmasked
    tail write would wrap onto the slot's own last block)."""
    reqs = [([1, 2, 3], 10, 0.0, 0),       # windows at pos 3,7,11,...
            ([5, 6, 7, 8, 9, 10], 8, 0.0, 0),
            # 24+8-1 = 31 positions: the full 8-block reservation, so the
            # final window's tail rows clamp onto the slot's OWN last
            # block — only the live mask keeps them in the trash.
            ([4] * 24, 8, 0.0, 0)]
    want = [_generate_stream(params, p, mx, t, s) for p, mx, t, s in reqs]
    got = _engine_streams(params, reqs, num_slots=3, prefill_chunk=16,
                          speculate=_spec(params, 3))
    assert got == want


def test_speculative_greedy_neighbors_unperturbed_by_sampling(params):
    """A greedy stream sharing a speculative batch with sampling
    neighbors must stay bitwise — rejection sampling consumes the
    NEIGHBOR's key, never the greedy slot's tokens."""
    reqs = [([5, 17, 3], 6, 0.8, 13), ([8, 8], 7, 0.0, 0)]
    got = _engine_streams(params, reqs, num_slots=2, prefill_chunk=4,
                          speculate=_spec(params, 2))
    assert got[1] == _generate_stream(params, [8, 8], 7, 0.0, 0)
    assert len(got[0]) == 6


def test_bf16_kv_cache_close_to_fp32(params):
    """kv_dtype="bfloat16" halves cache storage (the serving lever measured
    in bench.py's decode sidebar); the decode must stay the same computation
    up to bf16 rounding of cached K/V: logits within bf16 tolerance, and
    greedy tokens identical for a short horizon at this scale."""
    prompt = jax.random.randint(jax.random.PRNGKey(7), (2, 6), 0,
                                CFG.vocab_size)
    out32 = generate.generate(params, prompt, CFG, 8)
    out16 = generate.generate(params, prompt, CFG, 8, kv_dtype="bfloat16")
    assert out16.dtype == out32.dtype
    assert (out16 == out32).mean() > 0.9  # rounding may flip a near-tie

    cache = generate.init_cache(CFG, 2, 8, "bfloat16")
    assert cache["k"].dtype == jnp.bfloat16
    logits16, _ = generate.forward_cached(params, prompt, cache, 0, CFG)
    full = llama.forward(params, prompt, CFG)[:, -1, :]
    assert jnp.allclose(logits16, full, atol=0.05)
